"""Benchmark: QT-Opt grasping-critic training throughput per chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric (TPU): grasps (examples) per second per chip through the full
jitted train step (forward + backward + momentum update + weight decay +
EMA) on the REFERENCE-SCALE network: Grasping44 (16 convs + BN, named
grasp-param blocks, /root/reference/research/qtopt/networks.py:299-615)
at 472x472x3 bfloat16 images. The per-chip config is auto-tuned: the
bench measures batch 64, keeps doubling the batch while throughput
improves (cap 512), then probes rematerialization at the winning batch
— the step is HBM-bound, so larger batches amortize per-step
optimizer/EMA traffic and remat trades idle-MXU FLOPs for activation
bytes. The config actually used lands in the JSON ("batch_size",
"remat"); "value_batch64" keeps the fixed-batch non-remat number for
round-over-round comparison.

Baseline anchor: the reference publishes no absolute throughput
(BASELINE.md). The anchor is the BASELINE.json north star's 8xV100-class
setup estimated at ~400 grasps/sec/GPU for this exact network class, so
vs_baseline = measured_per_chip / 400 and the >=4x north-star target
reads as vs_baseline >= 4.

CPU fallback (wedged/absent TPU tunnel): the small-CNN smoke config with
its own metric name and the round-1 recorded anchor — not comparable to
the TPU number, only to itself across rounds.
"""

from __future__ import annotations

import json
import sys

import numpy as np

from tensor2robot_tpu.utils import backend as backend_lib

BASELINE_PER_CHIP = 400.0  # est. V100-class grasps/sec/device (see docstring)
BATCH_SIZE = 64
# Network/image-size config lives in research/qtopt/flagship.py (shared
# with the tuning/latency scripts so all measurements time one network).
WARMUP_STEPS = 3
MEASURE_STEPS = 50
# Peak dense bf16 FLOP/s per chip for the MFU denominator. v5e public
# spec: 197 TFLOP/s bf16. Unknown kinds fall back to the v5e figure
# (this project's only real device) — device_kind lands in the JSON so
# a mismatch is visible.
PEAK_BF16_FLOPS = {
    "TPU v5 lite": backend_lib.V5E_PEAK_BF16_FLOPS,
    "TPU v5e": backend_lib.V5E_PEAK_BF16_FLOPS,
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,
    "default": backend_lib.V5E_PEAK_BF16_FLOPS,
}


def main() -> None:
  if not backend_lib.accelerator_healthy():
    # Device backend unreachable: fall back to CPU rather than hang.
    backend_lib.pin_cpu()
    backend_lib.assert_cpu_backend()
  import jax

  from tensor2robot_tpu import modes, specs as specs_lib
  from tensor2robot_tpu.parallel import train_step as ts
  from tensor2robot_tpu.research.qtopt import flagship

  device = jax.devices()[0]
  on_tpu = device.platform != "cpu"
  measure_steps = MEASURE_STEPS if on_tpu else 5

  def make_model(remat: bool = False, s2d: bool = False):
    # The one shared flagship config (research/qtopt/flagship.py) so the
    # bench, tuning and latency scripts all time the SAME network.
    return flagship.make_flagship_model(device.platform, remat=remat,
                                        space_to_depth=s2d)

  def measure(batch_size: int, remat: bool = False, s2d: bool = False):
    """Returns (examples/sec, flops/step, bytes/step) for the train step."""
    model = make_model(remat, s2d)
    features = specs_lib.make_random_numpy(
        model.preprocessor.get_out_feature_specification(modes.TRAIN),
        batch_size=batch_size, seed=0)
    labels = specs_lib.make_random_numpy(
        model.preprocessor.get_out_label_specification(modes.TRAIN),
        batch_size=batch_size, seed=1)
    features = jax.device_put(features, device)
    labels = jax.device_put(labels, device)
    state, _ = ts.create_train_state(model, jax.random.PRNGKey(0), features)
    # AOT-compile once: the executable is both the timed step and the
    # source of the XLA cost analysis (flops + bytes per step) — no
    # second trace/compile over the tunnel. The bench must emit its
    # number even when the backend lacks AOT/cost support, so both are
    # best-effort with the plain jitted step as fallback.
    flops = bytes_accessed = float("nan")
    step = ts.make_train_step(model)
    try:
      step = step.lower(state, features, labels).compile()
      cost = step.cost_analysis()
      cost = cost[0] if isinstance(cost, (list, tuple)) else (cost or {})
      flops = float(cost.get("flops", float("nan")))
      bytes_accessed = float(cost.get("bytes accessed", float("nan")))
    except Exception as e:  # noqa: BLE001 - efficiency fields are optional
      # If .lower()/.compile() itself failed, `step` is still the plain
      # jitted fn; if only cost_analysis failed, it is the (callable)
      # AOT executable. Either way the timing loop below works.
      print(f"bench: AOT cost analysis unavailable "
            f"({type(e).__name__}: {e}); efficiency fields will be null",
            file=sys.stderr)
    # backend_lib.time_train_steps is the one shared tunnel-safe timing
    # recipe: warmup -> host-fetch barrier on the smallest param leaf
    # (block_until_ready returns early over the axon tunnel; the loss
    # does not depend on the final step's optimizer/EMA update) ->
    # timed loop -> barrier. The ~0.1 s fetch round-trip is amortized
    # over measure_steps and biases throughput slightly LOW.
    sec, _ = backend_lib.time_train_steps(
        step, state, features, labels, iters=measure_steps,
        warmup=WARMUP_STEPS)
    # Per-probe trace on stderr (the JSON contract line stays single):
    # the window/driver logs then record the whole tuning curve, not
    # just the winner.
    print(f"bench: probe batch={batch_size} remat={remat} s2d={s2d} -> "
          f"{batch_size / sec:.1f} ex/s ({sec * 1e3:.1f} ms/step)",
          file=sys.stderr)
    return batch_size / sec, flops, bytes_accessed

  # The bench must emit a number even if the reference-scale config does
  # not fit a particular chip's HBM: halve the batch on RESOURCE_EXHAUSTED
  # (throughput is reported per example, so it stays comparable-ish; the
  # batch actually used is recorded in the JSON).
  def measure_with_oom_fallback(batch_size):
    while True:
      try:
        return measure(batch_size) + (batch_size,)
      except Exception as e:  # noqa: BLE001 - retry only on OOM
        if "RESOURCE_EXHAUSTED" not in str(e) or batch_size <= 4:
          raise
        print(f"bench: batch {batch_size} OOM; retrying at "
              f"{batch_size // 2}", file=sys.stderr)
        batch_size //= 2

  examples_per_sec, flops, bytes_accessed, batch_size = (
      measure_with_oom_fallback(BATCH_SIZE if on_tpu else 16))
  if not on_tpu:
    # Host-load noise swings this VM +-20% (PERFORMANCE.md round-2 A/B):
    # take the median of three short runs so a single low sample does
    # not read as a round-over-round regression. TPU runs stay single
    # (50 steps amortize noise; re-running costs tunnel compiles).
    reruns = sorted([examples_per_sec] +
                    [measure(batch_size)[0] for _ in range(2)])
    examples_per_sec = reruns[1]
  value_batch64 = examples_per_sec if batch_size == BATCH_SIZE else None
  use_remat = False
  if on_tpu and batch_size == BATCH_SIZE:
    # The step is HBM-bandwidth-bound (PERFORMANCE.md roofline) and the
    # optimizer/EMA traffic is per-STEP: larger batches amortize it per
    # example. Keep doubling while throughput improves (cap 512 bounds
    # the window time); any failure keeps the last good number. The
    # batch actually used lands in the JSON.
    probe = 2 * BATCH_SIZE
    while probe <= 512:
      try:
        bigger, flops2, bytes2 = measure(probe)
      except Exception as e:  # noqa: BLE001 - the last number stands
        print(f"bench: batch-{probe} probe failed "
              f"({type(e).__name__}: {e}); keeping batch {batch_size}",
              file=sys.stderr)
        break
      if bigger <= examples_per_sec:
        break
      examples_per_sec, batch_size = bigger, probe
      flops, bytes_accessed = flops2, bytes2
      probe *= 2
  use_s2d = False
  if on_tpu:
    # Rematerialization probe at the winning batch. The local v5e AOT
    # lever matrix (PERFORMANCE.md round 4) predicts remat HURTS here
    # (more bytes AND more flops; the step is not activation-bound) —
    # the probe stays as the on-chip check. Keep whichever wins.
    try:
      r_eps, r_flops, r_bytes = measure(batch_size, remat=True)
      if r_eps > examples_per_sec:
        examples_per_sec, use_remat = r_eps, True
        flops, bytes_accessed = r_flops, r_bytes
    except Exception as e:  # noqa: BLE001 - the non-remat number stands
      print(f"bench: remat probe failed ({type(e).__name__}: {e}); "
            f"keeping remat=False", file=sys.stderr)
    # Space-to-depth stem probe (exact math, tests pin equivalence):
    # the 3-channel stem conv drives 3/128 MXU lanes; folding 2x2
    # pixels into 12 channels quadruples lane utilization on a conv the
    # cost model prices at 3% of flops but that can take a far larger
    # wall-clock share at 2% MXU efficiency. Only the chip can price
    # it; "space_to_depth" lands in the JSON.
    try:
      s_eps, s_flops, s_bytes = measure(batch_size, remat=use_remat,
                                        s2d=True)
      if s_eps > examples_per_sec:
        examples_per_sec, use_s2d = s_eps, True
        flops, bytes_accessed = s_flops, s_bytes
    except Exception as e:  # noqa: BLE001 - the non-s2d number stands
      print(f"bench: space-to-depth probe failed "
            f"({type(e).__name__}: {e}); keeping s2d=False",
            file=sys.stderr)
  # Efficiency accounting: achieved model FLOP/s over the device peak
  # (MFU a.k.a. MXU utilization) and HBM bytes per step, both from the
  # compiled executable's own XLA cost analysis — so the driver record
  # tracks efficiency, not just throughput.
  step_sec = batch_size / examples_per_sec
  peak = PEAK_BF16_FLOPS.get(device.device_kind, PEAK_BF16_FLOPS["default"])
  mfu = (flops / step_sec / peak) if np.isfinite(flops) else None
  if on_tpu:
    print(json.dumps({
        "metric": "qtopt_grasps_per_sec_per_chip",
        "value": round(examples_per_sec, 2),
        "unit": "examples/sec",
        "vs_baseline": round(examples_per_sec / BASELINE_PER_CHIP, 3),
        # < BATCH_SIZE: OOM degradation (the reference-scale batch did
        # not fit); > BATCH_SIZE: a doubling probe (cap 512) won. The
        # remat probe may also flip "remat" on. value_batch64 keeps the
        # fixed-batch non-remat number for round-over-round comparison.
        "batch_size": batch_size,
        "remat": use_remat,
        "space_to_depth": use_s2d,
        "value_batch64": (round(value_batch64, 2)
                          if value_batch64 is not None else None),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "flops_per_step": flops if np.isfinite(flops) else None,
        "bytes_per_step": (bytes_accessed
                           if np.isfinite(bytes_accessed) else None),
        "device_kind": device.device_kind,
    }))
  else:
    # Honest labeling: the CPU smoke config (smaller image/batch) is not
    # comparable to the V100-class anchor. The anchor is the throughput
    # measured for this exact config on this host during round 1
    # (3643 examples/sec), so vs_baseline ~= 1.0 means "no regression vs
    # the recorded CPU baseline", nothing more.
    cpu_anchor = 3643.0  # recorded for this exact config at batch 16
    print(json.dumps({
        "metric": "qtopt_grasps_per_sec_cpu_smoke",
        "value": round(examples_per_sec, 2),
        "unit": "examples/sec",
        "vs_baseline": round(examples_per_sec / cpu_anchor, 3),
        "batch_size": batch_size,
    }))


if __name__ == "__main__":
  main()
