"""Benchmark: QT-Opt grasping-critic training throughput per chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric (TPU): grasps (examples) per second per chip through the full
jitted train step (forward + backward + momentum update + weight decay +
EMA) on the REFERENCE-SCALE network: Grasping44 (16 convs + BN, named
grasp-param blocks, /root/reference/research/qtopt/networks.py:299-615)
at 472x472x3 bfloat16 images. The per-chip config is auto-tuned over
the batch ladder {256 first (the measured winner — headline secured
even if the tunnel stalls mid-run), 64 (round-over-round comparison),
128, 512} keeping the best (round 5 showed a slow compiler VALLEY at
b80-b128 with the fast regime returning at b256 — throughput is not
unimodal, so every rung is probed), then probes rematerialization and
the space-to-depth stem at the winning batch. The config actually used
lands in the JSON ("batch_size", "remat", "space_to_depth");
"value_batch64" keeps the fixed-batch non-remat number for
round-over-round comparison.

Probe isolation (round 5): every measurement runs in its OWN short
subprocess — the pattern scripts/tpu_window.sh established for safe
tunnel use. A probe that hangs (a wedged axon tunnel hangs client init
and can stall any device call forever; see PERFORMANCE.md incident
history) is abandoned after a deadline WITHOUT being signalled
(SIGTERM/SIGKILL of a process holding a TPU client is the documented
tunnel-wedging trigger), further probes are skipped, and the bench
emits the best number it already has. Before round 5 a single hung
probe forfeited the whole headline JSON (observed live: the s2d probe
stalled >18 min on an otherwise-captured 1478 ex/s run).

Baseline anchor: the reference publishes no absolute throughput
(BASELINE.md). The anchor is the BASELINE.json north star's 8xV100-class
setup estimated at ~400 grasps/sec/GPU for this exact network class, so
vs_baseline = measured_per_chip / 400 and the >=4x north-star target
reads as vs_baseline >= 4.

CPU fallback (wedged/absent TPU tunnel): the small-CNN smoke config with
its own metric name — not comparable to the TPU number, only to itself
across rounds. Since PR 7 the smoke probe feeds the train step from the
REAL record pipeline (TFRecords -> parse -> preprocess -> place,
native staged plane when the toolchain is present) as back-to-back A/B
pairs against the synthetic device-resident feed: the headline value is
the record-fed number, `data_vs_synthetic` is the load-invariant
pair-median ratio (diff-gated), and `synthetic_value` keeps the
pre-PR-7 comparison. Since PR 8 the record path runs OVERLAPPED
(`data/overlap.py` stages + a DevicePrefetcher placing batches — the
train loop's exact shape), `bench.py --smoke` runs this A/B directly
(scripts/data_bench.sh gates it), the headline's `overlap` block
carries per-stage timing attribution, and EVERY bench headline embeds
a `host_load` block (loadavg/cpu_count/concurrent-bench flock guard)
so load-masked readings are attributable at diff time.

Pipeline schedules (PR 9): `bench.py --pp` prices the interleaved-1F1B
schedule against GPipe on the virtual 8-device mesh (paired A/B,
`onefonb_vs_gpipe` + static `pp_bubble_fraction` diff-gated via
`scripts/pp_bench.sh`; PERFORMANCE.md "Reading a pipeline bench").

Fleet serving (PR 11 / ISSUE 12): `bench.py --fleet` prices the
multi-replica `ServingFleet` — paired 1-vs-2-replica arms on disjoint
device groups of the virtual 8-device mesh under identical open-loop
load, plus a zero-downtime rollout window (`fleet_vs_single_replica`
+ `fleet_rollout_shed` diff-gated via `scripts/fleet_bench.sh`;
PERFORMANCE.md "Reading a fleet bench").

graftguard chaos (ISSUE 13): `bench.py --chaos` runs a SEEDED fault
storm (`obs.faultlab`) across the data, train, and serving planes over
a live fleet + trainer — corrupt records skipped under quota, NaN
divergence rewound from the newest VERIFIED checkpoint (numerical
parity with a clean resume pinned), bit-flipped checkpoints
quarantined, injected dispatch failures evicted + probation-readmitted
with zero client-visible failures — headlining `chaos_goodput_ratio`
(paired faulted/clean serving goodput) and `chaos_recovery_ms` (worst
per-fault-class MTTR), diff-gated via `scripts/chaos_bench.sh`
(PERFORMANCE.md "Reading a chaos bench"); an unrecovered fault class
exits 3.

graftloop (ISSUE 14): `bench.py --loop` runs the seeded chaos storm
over the WHOLE always-on actor/learner loop (`tensor2robot_tpu.loop`)
— paired clean/chaos arms of collect-train-publish-rollout on the
pose toy task, the chaos arm injecting an actor kill, a learner NaN
divergence (rewound mid-collection), a torn published checkpoint
(REFUSED publication by the manifest walk), and a replica-eviction
dispatch burst (probation-readmitted) — headlining
`loop_goodput_ratio` (chaos/clean collection episodes/s; acceptance
floor 0.8) and `publish_to_serve_ms`, with the no-unverified-serve
audit and the staleness bound pinned; diff-gated via
`scripts/loop_bench.sh` (PERFORMANCE.md "Reading a loop bench"); an
unrecovered fault class exits 3.

graftcache (PR 7): every probe routes trace->compile through the
persistent executable cache at GRAFTCACHE_DIR (default `.graftcache`),
so re-benching an unchanged config deserializes instead of recompiling;
`bench.py --cache cold|warm` measures the cold/warm start pair itself
(`scripts/cache_bench.sh` gates it).

graftforge (PR 15 / ISSUE 15): `bench.py --forge` prices the
ahead-of-time compile FARM — a cold 2-replica-fleet + trainer start in
a fresh subprocess, the `obs.forge.run_forge` worker pool populating
the `forge_smoke/` cache namespace, then the forge-warmed start in
another fresh subprocess, which must deserialize EVERYTHING
(`engine_compiles == [0, 0]`, `train_cache_hit`, compile share 0 with
per-rung provenance); `forged_vs_cold` >= 2.0 is the acceptance floor
(`scripts/forge_bench.sh` gates it). Both headline modes embed
a `tunnel_health` block (`utils.backend.HeartbeatMonitor`: every health
probe and bench probe child stamps healthy/degraded/dead with a
timestamped transition timeline), so a fallback record carries the
CAUSE and TIME of the tunnel turning — the round-5 gap where
BENCH_r05.json silently switched metric names at the 14:10 UTC death.
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys
import tempfile
import time

from tensor2robot_tpu.obs import graftrace
from tensor2robot_tpu.obs import metrics as obs_metrics
from tensor2robot_tpu.obs import trace as obs_trace
from tensor2robot_tpu.utils import backend as backend_lib

BASELINE_PER_CHIP = 400.0  # est. V100-class grasps/sec/device (see docstring)
BATCH_SIZE = 64
# Network/image-size config lives in research/qtopt/flagship.py (shared
# with the tuning/latency scripts so all measurements time one network).
WARMUP_STEPS = 3
MEASURE_STEPS = 50
# Per-probe wall-clock budget. A healthy probe is compile (20-40 s over
# the tunnel) + ~53 steps (<1 min); the slowest healthy probe observed
# is ~4 min. Past this deadline the child is abandoned un-signalled.
PROBE_DEADLINE_SEC = 600.0
# graftcache: persistent executable/AOT cache shared by every probe
# subprocess and bench run on this checkout (override with
# GRAFTCACHE_DIR; the dir is gitignored). Only the FIRST run at a
# given (config, topology, backend-version) pays compile — rounds
# re-benching an unchanged step deserialize in ms.
DEFAULT_CACHE_DIR = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".graftcache")


def _cache_dir() -> str:
  return os.environ.get("GRAFTCACHE_DIR") or DEFAULT_CACHE_DIR


def _runs_path() -> str:
  """THE bench-side runs.jsonl location (GRAFTSCOPE_RUNS overridable) —
  one rule shared by the runlog append and the warm-phase baseline
  lookup, so they can never read different histories."""
  return os.environ.get("GRAFTSCOPE_RUNS") or os.path.join(
      os.path.dirname(os.path.abspath(__file__)), "runs.jsonl")


BENCH_LOCK_FILENAME = ".graftbench.lock"
_bench_lock_handle = None
# Latches True the first time acquisition fails: the guard must report
# "another bench overlapped this run AT ANY POINT", not just whether
# the lock happened to be free at headline-emission time.
_bench_lock_contended = False


def _acquire_bench_lock() -> bool:
  """Best-effort single-bench guard: a non-blocking flock on a
  repo-local lockfile, held for the process lifetime. Called at the
  START of every bench mode (measurements run under the lock) and
  again when the headline is built; False = ANOTHER bench (or gate
  script) overlapped this run on this host — the readings competed
  for the same cores and must be flagged, not argued about at diff
  time."""
  global _bench_lock_handle, _bench_lock_contended
  if _bench_lock_handle is not None:
    return not _bench_lock_contended
  try:
    import fcntl

    handle = open(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), BENCH_LOCK_FILENAME),
        "a")
    try:
      fcntl.flock(handle.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError:
      handle.close()
      _bench_lock_contended = True
      print("bench: another bench holds the repo lockfile — this "
            "reading will be stamped concurrent_bench=true",
            file=sys.stderr)
      return False
    _bench_lock_handle = handle  # held (and auto-released) for the process
    return not _bench_lock_contended
  except Exception:  # noqa: BLE001 - a guard, never a blocker
    return True


def _median(vals):
  """Upper median (sorted[n // 2]) — the one median every paired A/B
  family reports. For even counts this is the LARGER middle value,
  which flatters a down-bad ratio gate — prefer odd pair counts where
  that matters."""
  vals = sorted(vals)
  return vals[len(vals) // 2]


def _host_load_block() -> dict:
  """Host-load context stamped into EVERY bench headline (and therefore
  every runs.jsonl bench record): 1/5/15-min load averages, the cpu
  budget, and the concurrent-bench guard. Measurement hygiene for a VM
  whose identical-code readings swing 4x with load (PERFORMANCE.md
  "Reading a data bench"): a surprising diff first checks whether the
  host was busy, instead of relitigating the code change."""
  try:
    load_1m, load_5m, load_15m = (round(v, 2) for v in os.getloadavg())
  except OSError:  # platform without getloadavg
    load_1m = load_5m = load_15m = None
  return {
      "loadavg_1m": load_1m,
      "loadavg_5m": load_5m,
      "loadavg_15m": load_15m,
      "cpu_count": os.cpu_count(),
      # True = another bench/gate held the repo lockfile while this one
      # ran: the two competed for cores and BOTH readings are suspect.
      "concurrent_bench": not _acquire_bench_lock(),
  }


# Peak dense bf16 FLOP/s per chip for the MFU denominator. v5e public
# spec: 197 TFLOP/s bf16. Unknown kinds fall back to the v5e figure
# (this project's only real device) — device_kind lands in the JSON so
# a mismatch is visible.
PEAK_BF16_FLOPS = {
    "TPU v5 lite": backend_lib.V5E_PEAK_BF16_FLOPS,
    "TPU v5e": backend_lib.V5E_PEAK_BF16_FLOPS,
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,
    "default": backend_lib.V5E_PEAK_BF16_FLOPS,
}


SMOKE_DATA_RECORDS = 1024
SMOKE_DATA_FILES = 4


def _make_smoke_input_generator(root: str, model, batch_size: int,
                                seed: int):
  """The REAL training data path for the smoke probe: a TFRecord twin of
  the smoke model's wire spec on disk (written once per probe), read
  back through `DefaultRecordInputGenerator` -> `RecordBatchPipeline`
  (native staged plane when the toolchain is present) with the model's
  own preprocess_fn — exactly how train_eval feeds batches. The image
  plane is written pre-extracted (the pod-scale no-decode feed, same
  choice as the data bench).
  """
  import numpy as np

  from tensor2robot_tpu import modes, specs as specs_lib
  from tensor2robot_tpu.data import codec, input_generators, tfrecord

  feature_spec = specs_lib.flatten_spec_structure(
      model.preprocessor.get_in_feature_specification(modes.TRAIN))
  label_spec = specs_lib.flatten_spec_structure(
      model.preprocessor.get_in_label_specification(modes.TRAIN))
  wire_features = specs_lib.SpecStruct()
  for key, spec in feature_spec.items():
    if spec.is_image and not spec.is_extracted:
      spec = spec.replace(is_extracted=True)
    wire_features[key] = spec
  write_spec = specs_lib.SpecStruct(
      {**dict(wire_features.items()), **dict(label_spec.items())})

  pattern = os.path.join(root, "smoke-*.tfr")
  if not [p for p in os.listdir(root) if p.endswith(".tfr")]:
    rng = np.random.RandomState(0)
    per_file = SMOKE_DATA_RECORDS // SMOKE_DATA_FILES
    for shard in range(SMOKE_DATA_FILES):
      path = os.path.join(root, f"smoke-{shard:05d}.tfr")
      with tfrecord.RecordWriter(path) as writer:
        for _ in range(per_file):
          values = {}
          for key, spec in write_spec.items():
            shape = tuple(int(d) for d in spec.shape)
            if spec.is_extracted:
              values[key] = rng.randint(
                  0, 255, shape, np.uint8).tobytes()
            elif np.dtype(spec.dtype).kind in "iu":
              values[key] = rng.randint(0, 2, shape, spec.dtype)
            else:
              values[key] = rng.randn(*shape).astype(spec.dtype)
          writer.write(codec.encode_example(values, write_spec))

  generator = input_generators.DefaultRecordInputGenerator(
      pattern, batch_size=batch_size, seed=seed)
  generator.set_specification(wire_features, label_spec)
  generator.set_preprocess_fn(model.preprocessor.preprocess)
  return generator


def _time_data_fed_steps(step, state, generator, batch_size: int,
                         steps: int, device, warmup: int = 2,
                         prefetch_depth: int = 2):
  """One records->train-step pass: pulls batches from the REAL record
  pipeline and dispatches the already-compiled step on each. Since the
  overlapped host data plane landed, the pipeline runs as stages
  (stager arena -> parse pool -> preprocess worker, `data/overlap.py`)
  and a `DevicePrefetcher` worker performs the host->device placement
  — exactly train_eval's loop shape — so the timed loop only dequeues
  device-resident batches and dispatches (`prefetch_depth=0` restores
  the serial place-on-loop-thread path for A/Bs). Ends in a host-fetch
  barrier on a param leaf (block_until_ready is not a barrier over the
  tunnel; CLAUDE.md). Returns (examples_per_sec, state, overlap
  telemetry snapshot)."""
  import jax

  from tensor2robot_tpu.parallel import mesh as mesh_lib

  def _place(batch):
    # The batch's SpecStructs go to the step AS-IS — the compiled
    # executable's input pytree was traced on SpecStructs too.
    return (jax.device_put(batch["features"], device),
            jax.device_put(batch["labels"], device))

  with obs_metrics.isolated():
    stream = iter(generator.create_dataset("train"))
    if prefetch_depth:
      batches = mesh_lib.DevicePrefetcher(
          stream, place_fn=_place, depth=prefetch_depth,
          max_batches=warmup + steps, close_source=True)
    else:
      batches = (_place(b) for b in stream)
    try:
      def one(state):
        features, labels = next(batches)
        state, _ = step(state, features, labels)
        return state

      for _ in range(warmup):  # file opens / stager spin-up / parse pool
        state = one(state)
      backend_lib.sync(min(jax.tree_util.tree_leaves(state.params),
                           key=lambda l: l.size))
      t0 = time.perf_counter()
      for _ in range(steps):
        state = one(state)
      backend_lib.sync(min(jax.tree_util.tree_leaves(state.params),
                           key=lambda l: l.size))
      elapsed = time.perf_counter() - t0
    finally:
      if prefetch_depth:
        batches.close()  # joins worker + loader stages (close_source)
      elif hasattr(stream, "close"):
        stream.close()
    # One canonical key shape with the train run record's step_stats
    # summary (runlog.overlap_summary) — one runs.jsonl history, one
    # spelling per stage metric.
    from tensor2robot_tpu.obs import runlog as runlog_lib

    overlap_snap = {
        k: round(v, 4) for k, v in runlog_lib.overlap_summary(
            obs_metrics.snapshot(prefix="data/overlap_")).items()}
  return steps * batch_size / elapsed, state, overlap_snap


def probe_main(cfg: dict) -> dict:
  """Runs ONE measurement (the probe child body); returns the record.

  Called in a fresh subprocess for TPU probes (tunnel-hazard isolation)
  and in-process for the CPU smoke fallback (no tunnel involved).
  """
  if cfg["platform"] == "cpu":
    backend_lib.pin_cpu()
    backend_lib.assert_cpu_backend()
  import jax

  from tensor2robot_tpu import modes, specs as specs_lib
  from tensor2robot_tpu.obs import excache as excache_lib
  from tensor2robot_tpu.parallel import train_step as ts
  from tensor2robot_tpu.research.qtopt import flagship

  # graftcache: the probe's trace->compile routes through the
  # persistent executable cache, so only the FIRST bench run at a given
  # config pays the compile — every later probe subprocess deserializes
  # (the round-5 valley probes paid 20-40 s compile each, every run).
  # The XLA compilation cache rides along for plain-jit fallbacks.
  cache = None
  cache_dir = cfg.get("cache_dir")
  if cache_dir:
    cache = excache_lib.ExecutableCache(cache_dir)
    excache_lib.enable_xla_cache(cache_dir)

  device = jax.devices()[0]
  on_tpu = device.platform != "cpu"
  batch_size = cfg["batch_size"]
  remat = cfg.get("remat", False)
  s2d = cfg.get("s2d", False)
  # loop_steps > 1 measures the on-device K-step scan loop
  # (train_step.make_train_loop — the TPUEstimator iterations_per_loop
  # equivalent): K REAL train steps on K distinct pre-staged batches per
  # host dispatch, dividing the per-dispatch transport overhead by K.
  loop_steps = int(cfg.get("loop_steps", 1) or 1)
  measure_steps = MEASURE_STEPS if on_tpu else 5

  model = flagship.make_flagship_model(device.platform, remat=remat,
                                       space_to_depth=s2d)
  import numpy as np

  def _batches(spec, seed0, n):
    outs = [specs_lib.make_random_numpy(spec, batch_size=batch_size,
                                        seed=seed0 + i) for i in range(n)]
    if n == 1:
      return outs[0]
    return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *outs)

  feature_spec = model.preprocessor.get_out_feature_specification(
      modes.TRAIN)
  label_spec = model.preprocessor.get_out_label_specification(modes.TRAIN)
  host_features = _batches(feature_spec, 0, loop_steps)
  # Init consumes ONE batch; slice it on the host — indexing the
  # device-resident stack would pay an eager per-leaf tunnel round trip
  # (~1.5 s each, CLAUDE.md) for data numpy already holds.
  init_features = (host_features if loop_steps == 1 else
                   jax.tree_util.tree_map(lambda x: x[0], host_features))
  features = jax.device_put(host_features, device)
  labels = jax.device_put(_batches(label_spec, 100, loop_steps), device)
  state, _ = ts.create_train_state(model, jax.random.PRNGKey(0),
                                   init_features)
  # AOT-compile once through graftscope-xray: the executable is both
  # the timed step and the source of the XLA cost analysis (flops +
  # bytes per step) — no second trace/compile over the tunnel — and the
  # xray record additionally carries compile time, jaxpr size, donated
  # bytes and temp memory for the run-history record. The bench must
  # emit its number even when the backend lacks AOT/cost support, so
  # the analysis is best-effort with the plain jitted step as fallback.
  from tensor2robot_tpu.obs import xray as xray_lib

  flops = bytes_accessed = float("nan")
  xray_rec = None
  if loop_steps > 1:
    step = ts.make_train_loop(model, loop_steps)
  else:
    step = ts.make_train_step(model)
  try:
    step, xray_rec = xray_lib.analyze_jit(
        "bench/train_loop" if loop_steps > 1 else "bench/train_step",
        step, state, features, labels, cache=cache)
    flops = float(xray_rec["flops"]
                  if xray_rec["flops"] is not None else float("nan"))
    bytes_accessed = float(
        xray_rec["bytes_accessed"]
        if xray_rec["bytes_accessed"] is not None else float("nan"))
  except Exception as e:  # noqa: BLE001 - efficiency fields are optional
    # `step` is still the plain jitted fn here; the timing loop below
    # works either way.
    print(f"bench: AOT cost analysis unavailable "
          f"({type(e).__name__}: {e}); efficiency fields will be null",
          file=sys.stderr)
  memory = None
  try:
    memory = xray_lib.memory_accounting(state, batch=(features, labels))
    memory["hbm_watermark_bytes"] = xray_lib.hbm_watermark_estimate(
        memory, [xray_rec] if xray_rec else [])
  except Exception:  # noqa: BLE001 - memory accounting is optional
    pass
  # backend_lib.time_train_steps_halves is the one shared tunnel-safe
  # timing recipe: warmup -> host-fetch barrier on the smallest param
  # leaf (block_until_ready returns early over the axon tunnel; the
  # loss does not depend on the final step's optimizer/EMA update) ->
  # two timed half-windows with barrier costs estimated and subtracted
  # (pure step time; pre-round-5 captures read ~2 ms/step heavy by
  # including one barrier — PERFORMANCE.md comparability notes).
  # CPU smoke: host-load noise swings this VM +-20% (PERFORMANCE.md
  # round-2 A/B), so time the loop `reruns` times on the one compiled
  # step and keep the median. TPU runs stay single (50 steps amortize
  # noise; re-running costs tunnel time).
  # Steady-state discipline (round 5): the timed loop runs as two
  # barrier-separated halves and the SECOND half is the reported
  # number — one-time remote effects inside the window (first-touch
  # allocation/defrag; the b128 cliff probe read 449 ms/step plain-
  # mean) land in the first half, and a large half-to-half gap is
  # recorded as its own diagnostic ("first_half_sec").
  # In loop mode each dispatch runs K steps; shrink the dispatch count
  # to keep probe wall-time comparable and divide per-dispatch results
  # back to per-step for apples-to-apples records.
  iters = (measure_steps if loop_steps == 1
           else max(4, measure_steps // loop_steps))
  # Real-data-path measurement (ROADMAP item 5 remainder): records ->
  # parse -> preprocess -> place -> train step through the SAME pipeline
  # train_eval uses (native staged plane when the toolchain is there).
  # The host swings 4x run-to-run on identical code (PERFORMANCE.md
  # "Reading a data bench"), so the synthetic and data-fed passes run as
  # BACK-TO-BACK pairs with alternating order and the load-invariant
  # number is the median per-pair ratio — the same design as
  # scripts/data_bench.sh.
  data_path = bool(cfg.get("data_path")) and loop_steps == 1
  data_root = None
  if data_path:
    from tensor2robot_tpu import native

    data_root = tempfile.mkdtemp(prefix="bench_smoke_data_")
  runs = []
  data_runs = []
  data_ratios = []
  overlap_snap = None
  for rerun in range(cfg.get("reruns", 1)):
    data_first = data_path and bool(rerun % 2)
    if data_first:
      generator = _make_smoke_input_generator(data_root, model,
                                              batch_size, seed=7 + rerun)
      data_eps, state, overlap_snap = _time_data_fed_steps(
          step, state, generator, batch_size, measure_steps, device)
    run_flags: dict = {}
    h1, h2, state = backend_lib.time_train_steps_halves(
        step, state, features, labels, iters=iters,
        warmup=WARMUP_STEPS, out_flags=run_flags)
    runs.append((h2, h1, bool(run_flags.get("barrier_dominated"))))
    if data_path and not data_first:
      generator = _make_smoke_input_generator(data_root, model,
                                              batch_size, seed=7 + rerun)
      data_eps, state, overlap_snap = _time_data_fed_steps(
          step, state, generator, batch_size, measure_steps, device)
    if data_path:
      synth_eps = batch_size * loop_steps / h2
      data_runs.append(data_eps)
      data_ratios.append(data_eps / synth_eps)
      print(f"bench: data-path pair {rerun}: synthetic {synth_eps:.0f} "
            f"ex/s, record-fed {data_eps:.0f} ex/s "
            f"({data_ratios[-1]:.2f}x)", file=sys.stderr)
  sec, first_half, barrier_dominated = sorted(runs)[len(runs) // 2]
  sec /= loop_steps
  first_half /= loop_steps
  print(f"bench: probe batch={batch_size} remat={remat} s2d={s2d} "
        f"loop={loop_steps} -> "
        f"{batch_size / sec:.1f} ex/s ({sec * 1e3:.1f} ms/step steady; "
        f"first half {first_half * 1e3:.1f} ms/step)",
        file=sys.stderr)
  data_block = None
  if data_path:
    import shutil

    shutil.rmtree(data_root, ignore_errors=True)
    data_block = {
        # Median record-fed throughput (absolute: flaps with host load)
        # + the load-invariant pair-median ratio vs the synthetic
        # device-resident feed (<= ~1.0; the residual gap is whatever
        # host data work the overlapped loader could NOT hide behind
        # device compute — per-stage attribution in `overlap` below).
        "examples_per_sec": sorted(data_runs)[len(data_runs) // 2],
        "vs_synthetic": sorted(data_ratios)[len(data_ratios) // 2],
        "native_stager": native.available(),
        "pairs": len(data_runs),
        # Per-stage `data/overlap_*` timings + queue depths from the
        # LAST record-fed pass (hist means/p90s + gauges): which stage
        # binds when the ratio drops (PERFORMANCE.md "Reading an
        # overlap bench").
        "overlap": overlap_snap,
    }
  return {
      "ok": True,
      # With data_path on, the headline number IS the real data path
      # (records -> parse -> preprocess -> place -> step); the
      # device-resident synthetic number stays alongside for
      # round-over-round comparison with pre-PR-7 records.
      "examples_per_sec": (data_block["examples_per_sec"] if data_path
                           else batch_size / sec),
      "synthetic_examples_per_sec": batch_size / sec,
      "data_path": data_block,
      "step_sec": sec,
      "first_half_sec": first_half,
      # The kept (median) run's timing was barrier-dominated: step_sec
      # is a CLAMPED estimate (backend.time_train_steps_halves) that
      # can sit on either side of the truth — in particular
      # examples_per_sec may be inflated — so autotune's ranking never
      # lets a flagged record outrank a clean one, and the sentinel
      # spike detector skips equivalently-flagged stepstats records.
      "barrier_dominated": barrier_dominated,
      # XLA cost analysis prices a lax.scan BODY once (trip count is not
      # multiplied in) — measured: the K=8 loop executable reports the
      # same flops as the single-step one — so loop-mode cost fields are
      # already per-step.
      "flops": None if math.isnan(flops) else flops,
      "bytes_accessed": (None if math.isnan(bytes_accessed)
                         else bytes_accessed),
      "device_kind": device.device_kind,
      "platform": device.platform,
      "batch_size": batch_size,
      "loop_steps": loop_steps,
      # graftscope-xray blocks (JSON-safe dicts; None when unavailable):
      # compile telemetry + per-shard/HBM-watermark accounting for the
      # run-history record the parent appends to runs.jsonl.
      "xray": xray_rec,
      "memory": memory,
      # graftcache accounting for this probe (hits/misses/load_ms): a
      # warm probe shows hits>0 with compile_s ~0 in the xray block.
      "cache": excache_lib.cache_stats() if cache is not None else None,
  }


def _probe_child_entry(cfg_json: str, out_path: str) -> None:
  try:
    rec = probe_main(json.loads(cfg_json))
  except Exception as e:  # noqa: BLE001 - parent decides how to react
    rec = {"ok": False, "error": f"{type(e).__name__}: {e}"}
  if out_path == "-":
    # Standalone probe mode (scripts/tpu_window.sh uses it to A/B
    # local-vs-remote compile): record to stdout, not a file.
    print(json.dumps(rec))
    return
  tmp = out_path + ".tmp"
  with open(tmp, "w") as f:
    json.dump(rec, f)
  os.replace(tmp, out_path)


def _subprocess_probe(batch_size: int, remat: bool = False,
                      s2d: bool = False,
                      loop_steps: int = 1,
                      deadline: float = PROBE_DEADLINE_SEC,
                      extra_env: dict | None = None) -> dict:
  """Runs one TPU probe in a fresh subprocess; never signals it.

  Returns the child's record, {"ok": False, ...} on child error, or
  {"timeout": True} when the deadline passes (child left to finish or
  hang on its own — signalling a process that holds a TPU client is the
  documented tunnel-wedging trigger, PERFORMANCE.md rules #4/#5).
  `extra_env` lands in the child's environment BEFORE interpreter start
  — the axon sitecustomize reads its config (e.g.
  PALLAS_AXON_REMOTE_COMPILE) at import time, so this is the only way
  to vary it per probe.
  """
  cfg = {"platform": "tpu", "batch_size": batch_size, "remat": remat,
         "s2d": s2d, "loop_steps": loop_steps, "cache_dir": _cache_dir()}
  fd, out_path = tempfile.mkstemp(prefix="bench_probe_", suffix=".json")
  os.close(fd)
  os.unlink(out_path)  # child creates it atomically
  proc = subprocess.Popen(
      [sys.executable, os.path.abspath(__file__), "--probe",
       json.dumps(cfg), out_path],
      stdout=sys.stderr, stderr=sys.stderr,
      env=(dict(os.environ, **extra_env) if extra_env else None))
  start = time.monotonic()
  while time.monotonic() - start < deadline:
    if proc.poll() is not None:
      break
    time.sleep(2.0)
  try:
    if proc.poll() is None:
      print(f"bench: probe batch={batch_size} remat={remat} s2d={s2d} "
            f"exceeded {deadline:.0f}s deadline; abandoning it un-signalled "
            "and skipping remaining probes", file=sys.stderr)
      return {"timeout": True}
    with open(out_path) as f:
      rec = json.load(f)
    if isinstance(rec, dict):
      # Child wall clock for the heartbeat monitor: a probe that took
      # most of its deadline is a degraded tunnel even when it succeeds.
      rec.setdefault("probe_wall_sec", time.monotonic() - start)
    return rec
  except OSError:
    return {"ok": False,
            "error": f"probe child exited rc={proc.returncode} "
                     "without writing a result"}
  finally:
    # Best-effort: on the timeout path the abandoned child may still
    # os.replace() its record here later; the unlink then just loses a
    # stale temp file instead of leaking one per hung-tunnel run.
    try:
      os.unlink(out_path)
    except OSError:
      pass


def autotune(probe, initial_batch: int = BATCH_SIZE,
             batch_cap: int = 512,
             priority_batch: int = 256) -> dict | None:
  """Batch/remat/s2d auto-tune over a probe callable; pure logic.

  `probe(batch_size, remat, s2d)` returns probe_main-style records (or
  {"timeout": True}). Returns the winning record extended with
  {"batch_size", "remat", "s2d", "value_batch64", "aborted"}; None when
  no probe yields a usable number (caller falls back).
  Policy (round 5: the chip showed throughput is NOT unimodal in batch
  -- a flat ~10-27x-slow compiler valley at b80-b128 with the fast
  regime returning at b256, the AOT knee -- so every batch in the
  ladder is probed and the best kept):
    - `priority_batch` (the measured winner, 256) is probed FIRST: if
      the tunnel stalls mid-run, the best-so-far is the headline batch
      rather than the b64 comparison probe (ascending order used to
      cost exactly that);
    - then the initial batch (keeps the round-over-round
      `value_batch64` comparison) and the rest of the doubling ladder
      up to `batch_cap`; an OOM skips every batch >= the OOMed one
      (they only OOM harder);
    - if the whole ladder OOMs, the initial batch halves down (floor 4;
      degraded runs probe no ladder);
    - remat, then space-to-depth, probed at the winning batch;
    - ANY timeout abandons all remaining probes (the tunnel is suspect
      and each further probe would hang the full deadline) but keeps
      the best already-measured number;
    - a `barrier_dominated` record (clamped timing — an inflated
      examples/sec is possible) never outranks a clean measurement.
  """
  best = None
  last_error = None

  def wins(challenger, incumbent):
    """True when `challenger` should replace `incumbent` as best.

    A `barrier_dominated` record's step time is a CLAMPED value
    (backend.time_train_steps_halves: a noisy-high barrier estimate can
    understate the true step time, inflating examples/sec by up to the
    clamp factor), so a clean measurement ALWAYS outranks a flagged
    one regardless of magnitude; equal trust compares throughput.
    """
    if incumbent is None:
      return True
    c_flag = bool(challenger.get("barrier_dominated"))
    i_flag = bool(incumbent.get("barrier_dominated"))
    if c_flag != i_flag:
      return i_flag
    return challenger["examples_per_sec"] > incumbent["examples_per_sec"]

  def try_probe(b, remat, s2d, what):
    nonlocal best, last_error
    if best is not None and best["aborted"]:
      return None
    r = probe(b, remat, s2d)
    if r.get("timeout"):
      last_error = "timeout"
      if best is not None:
        best["aborted"] = True
      return None
    if not r.get("ok"):
      last_error = r.get("error", "")
      print(f"bench: {what} probe failed ({last_error}); "
            f"keeping the current best", file=sys.stderr)
      return None
    last_error = None
    return r

  # Ladder in priority order: known winner, comparison batch, the rest
  # of the doubling ladder ascending.
  ladder = [priority_batch, initial_batch]
  b = 2 * initial_batch
  while b <= batch_cap:
    ladder.append(b)
    b *= 2
  ladder = list(dict.fromkeys(b for b in ladder if 0 < b <= batch_cap))
  oom_floor = None
  max_ok_batch = None
  value_batch64 = None
  for b in ladder:
    if best is not None and best["aborted"]:
      break
    # Skip rungs at/above an OOMed batch ONLY while no LARGER rung has
    # already succeeded: the ladder runs priority-first (256 before 64),
    # so a transient OOM at b64 after a successful b256 says nothing
    # about b128/b512 — before this guard it silently masked them
    # (ADVICE.md round 5). A genuine capacity ceiling still short-
    # circuits: nothing above it has ever fit.
    if (oom_floor is not None and b >= oom_floor
        and (max_ok_batch is None or max_ok_batch < oom_floor)):
      continue
    r = try_probe(b, False, False, f"batch-{b}")
    if r is None:
      if last_error == "timeout" and best is None:
        return None
      if "RESOURCE_EXHAUSTED" in (last_error or ""):
        oom_floor = b if oom_floor is None else min(oom_floor, b)
      continue
    max_ok_batch = b if max_ok_batch is None else max(max_ok_batch, b)
    if b == BATCH_SIZE:
      value_batch64 = r["examples_per_sec"]
    if wins(r, best):
      # aborted cannot be True here: a timeout returns None from
      # try_probe and breaks the ladder before another update.
      best = dict(r, batch_size=b, remat=False, s2d=False,
                  aborted=False)
  if best is None and oom_floor is not None:
    # The reference-scale batches do not fit: degrade by halving the
    # initial batch (rounds 2-4 OOM policy; no ladder on degraded
    # runs). Gated on an actual OOM — a ladder failing on generic
    # errors fails fast to the caller's fallback instead of burning
    # four more full-deadline probes that cannot succeed either.
    b = initial_batch // 2
    while b >= 4:
      r = try_probe(b, False, False, f"degraded-batch-{b}")
      if r is not None:
        best = dict(r, batch_size=b, remat=False, s2d=False,
                    aborted=False)
        break
      if last_error == "timeout":
        return None
      b //= 2
  if best is None:
    print(f"bench: no probe produced a number ({last_error})",
          file=sys.stderr)
    return None
  best["value_batch64"] = value_batch64
  # Rematerialization probe at the winning batch. The local v5e AOT
  # lever matrix (PERFORMANCE.md round 4) predicts remat HURTS here
  # (more bytes AND more flops; the step is not activation-bound) —
  # the probe stays as the on-chip check. Keep whichever wins.
  r = try_probe(best["batch_size"], True, False, "remat")
  if r is not None and wins(r, best):
    best.update(r, remat=True)
  # Space-to-depth stem probe (exact math, tests pin equivalence):
  # the 3-channel stem conv drives 3/128 MXU lanes; folding 2x2
  # pixels into 12 channels quadruples lane utilization on a conv the
  # cost model prices at 3% of flops but that can take a far larger
  # wall-clock share at 2% MXU efficiency. Only the chip can price it.
  r = try_probe(best["batch_size"], best["remat"], True, "space-to-depth")
  if r is not None and wins(r, best):
    best.update(r, s2d=True)
  return best


def _ab_local_compile(batch_size: int) -> None:
  """A/B item for scripts/tpu_window.sh: one probe at the headline
  config with the axon client compiling IN-PROCESS via the image's
  libtpu (PALLAS_AXON_REMOTE_COMPILE=0) instead of the terminal's
  /remote_compile endpoint (whose hour-long stall ate the round-5 s2d
  probe). Follows the window-plan contract: health-gates itself,
  bounds the probe with the standard deadline, and exits 2 when the
  tunnel is down or the probe yields no number — so the plan's resume
  logic re-runs it next window instead of marking it captured.
  """
  if not backend_lib.accelerator_healthy():
    print("tunnel down; local-compile A/B not run", file=sys.stderr)
    sys.exit(2)
  rec = _subprocess_probe(
      batch_size, extra_env={"PALLAS_AXON_REMOTE_COMPILE": "0"})
  if "libtpu version mismatch" in rec.get("error", ""):
    # Round-5 measured fact: the terminal runs an OLDER libtpu build
    # than the image (Nov 2025 vs Jan 2026), so locally-AOT-compiled
    # executables are refused. That is a permanent property of this
    # environment, not a transient failure — record it as the A/B's
    # answer (exit 0) so the window plan does not retry forever.
    print(json.dumps({"compile_mode": "local", "supported": False,
                      "reason": "libtpu version mismatch between image "
                                "and terminal", "error": rec["error"]}))
    return
  if rec.get("timeout") or not rec.get("ok"):
    print(f"local-compile A/B probe failed: {rec}", file=sys.stderr)
    sys.exit(1)  # item failed (retry next window) — NOT tunnel-down
  print(json.dumps(dict(rec, compile_mode="local")))


def _record_probe(rec: dict) -> dict:
  """Feeds one probe outcome through the graftscope metrics registry
  AND the tunnel heartbeat monitor (`backend.tunnel_health()`).

  Every BENCH_*.json record since this landed carries the same
  `graftscope` block (see `_graftscope_block`), so driver-side tooling
  can consume probe accounting without parsing stderr; the heartbeat
  stamps are what let a later CPU fallback report the cause and TIME
  of the tunnel turning (the round-5 gap: BENCH_r05.json silently
  switched metric names at the 14:10 UTC tunnel death).
  """
  wall = float(rec.get("probe_wall_sec") or 0.0)
  if rec.get("timeout"):
    obs_metrics.counter("bench/probes_timeout").inc()
    backend_lib.record_heartbeat(False, elapsed_s=PROBE_DEADLINE_SEC,
                                 source="bench_probe",
                                 cause="probe_timeout")
  elif rec.get("ok"):
    obs_metrics.counter("bench/probes_ok").inc()
    obs_metrics.histogram("bench/probe_examples_per_sec").record(
        rec["examples_per_sec"])
    obs_metrics.histogram("bench/probe_step_ms").record(
        rec["step_sec"] * 1e3)
    if rec.get("platform") != "cpu":
      # Slow threshold scaled to the probe deadline, not the monitor's
      # 60 s default: a healthy child pays fresh jax init + a first
      # compile (minutes over the tunnel) — only a child burning most
      # of its deadline is degradation evidence.
      backend_lib.record_heartbeat(True, elapsed_s=wall,
                                   source="bench_probe",
                                   degraded_after_s=0.5
                                   * PROBE_DEADLINE_SEC)
  else:
    obs_metrics.counter("bench/probes_failed").inc()
    if rec.get("platform") != "cpu":
      error = str(rec.get("error", ""))[:120]
      if "RESOURCE_EXHAUSTED" in error:
        # An OOM is the batch ladder working as designed: the tunnel
        # ran the workload and answered — a HEALTHY probe outcome, not
        # degradation (the oom_floor policy handles the batch side).
        backend_lib.record_heartbeat(True, elapsed_s=wall,
                                     source="bench_probe",
                                     degraded_after_s=0.5
                                     * PROBE_DEADLINE_SEC)
      else:
        # Any other child failure is inconclusive: the tunnel answered
        # SOMETHING (not dead), but e.g. a libtpu mismatch or transport
        # error is not a clean bill of health either.
        backend_lib.record_heartbeat(None, elapsed_s=wall,
                                     source="bench_probe",
                                     cause=f"probe_error:{error}")
  return rec


def _xray_headline_block(probe_rec: dict) -> dict:
  """The headline JSON's `xray` block from one probe record — ONE
  shape for the TPU and CPU-smoke paths, so the two bench modes cannot
  drift into divergent schemas inside the same runs.jsonl."""
  xray_rec = probe_rec.get("xray") or {}
  memory = probe_rec.get("memory") or {}
  return {
      "compile_sec": xray_rec.get("compile_s"),
      "jaxpr_eqns": xray_rec.get("jaxpr_eqns"),
      "arithmetic_intensity": xray_rec.get("arithmetic_intensity"),
      "roofline_ms": xray_rec.get("roofline_ms"),
      "hbm_watermark_bytes": memory.get("hbm_watermark_bytes"),
  }


def _write_runlog(headline: dict, platform, device_kind,
                  compile_records=None, memory=None,
                  step_sec=None) -> None:
  """THE bench-side runlog append (train-smoke AND serve headlines):
  scrubs the headline into a strict-JSON bench block (allow_nan=False —
  one NaN/inf scalar must cost that field, not the record), builds one
  `graftscope-run-v1` record, and appends it to the repo-root
  `runs.jsonl` (override with GRAFTSCOPE_RUNS) so the BENCH_* trajectory
  is machine-comparable: `python -m tensor2robot_tpu.bin.graftscope
  diff runs.jsonl#-2 runs.jsonl#-1` prices a round against the previous
  one. Best-effort — the headline JSON never depends on the append."""
  try:
    from tensor2robot_tpu.obs import runlog

    bench_block = dict(headline)
    bench_block.pop("graftscope", None)  # registry snapshot, not diffable
    if step_sec is not None:
      bench_block["step_sec"] = step_sec

    def scrub(value):
      # The serve headline nests floats (latency_ms, sweep[].qps, batcher
      # stats): scrub recursively, or one nested inf costs the whole
      # record at the strict allow_nan=False append.
      if isinstance(value, float) and not math.isfinite(value):
        return None
      if isinstance(value, dict):
        return {k: scrub(v) for k, v in value.items()}
      if isinstance(value, (list, tuple)):
        return [scrub(v) for v in value]
      return value

    bench_block = scrub(bench_block)
    record = runlog.make_record(
        "bench", platform=platform, device_kind=device_kind,
        compile_records=compile_records or None, memory=memory,
        bench=bench_block)
    runlog.append_record(_runs_path(), record)
  except Exception as e:  # noqa: BLE001 - history is telemetry, not output
    print(f"bench: runs.jsonl append failed ({type(e).__name__}: {e})",
          file=sys.stderr)


def _append_runlog(headline: dict, probe_rec: dict) -> None:
  """Train-smoke headline → runlog record (see `_write_runlog`)."""
  xray_rec = probe_rec.get("xray")
  _write_runlog(headline,
                platform=probe_rec.get("platform"),
                device_kind=probe_rec.get("device_kind"),
                compile_records=[xray_rec] if xray_rec else None,
                memory=probe_rec.get("memory"),
                step_sec=probe_rec.get("step_sec"))


def _graftscope_block() -> dict:
  """Stable telemetry schema for the headline JSON: probe counters are
  pre-created so the keys exist even on a zero-probe (CPU-fallback)
  run."""
  for name in ("bench/probes_ok", "bench/probes_failed",
               "bench/probes_timeout"):
    obs_metrics.counter(name)
  return {"schema": "graftscope-bench-v1",
          "metrics": obs_metrics.snapshot(prefix="bench/")}


DATA_NUM_RECORDS = 6144
DATA_NUM_FILES = 8
DATA_BATCH = 64
DATA_MEASURE_BATCHES = 90  # warmup 2 + 90 < one 96-batch epoch
DATA_RERUNS = 5
# Recorded for this exact config on this host (round 6): examples/sec
# through the NATIVE staging plane (stager arena -> parse_arena),
# records->parsed-batch end to end, serial (no prefetch/parallel-parse
# threads — the ratio isolates the staging plane, not thread luck).
# Like cpu_anchor, vs_baseline ~= 1.0 reads as "no data-plane
# regression vs the recorded baseline", nothing more.
DATA_CPU_ANCHOR = 95000.0


def _make_data_bench_dataset(root: str):
  """Synthetic QT-Opt-shaped staging dataset: a pre-extracted uint8
  image plane (the pod-scale no-decode feed, 32x32x3 = 3 KiB/record) +
  a float pose + an int64 success label, sharded over DATA_NUM_FILES
  TFRecord files. Returns (file_patterns, parse_fn)."""
  import numpy as np

  from tensor2robot_tpu import specs as specs_lib
  from tensor2robot_tpu.data import codec, parsing, tfrecord
  spec = specs_lib.SpecStruct({
      "image": specs_lib.TensorSpec(shape=(32, 32, 3), dtype=np.uint8,
                                    name="state/image", data_format="jpeg",
                                    is_extracted=True),
      "pose": specs_lib.TensorSpec(shape=(7,), dtype=np.float32,
                                   name="pose"),
      "grasp_success": specs_lib.TensorSpec(shape=(1,), dtype=np.int64,
                                            name="grasp_success"),
  })
  rng = np.random.RandomState(0)
  per_file = DATA_NUM_RECORDS // DATA_NUM_FILES
  for shard in range(DATA_NUM_FILES):
    path = os.path.join(root, f"grasps-{shard:05d}.tfr")
    with tfrecord.RecordWriter(path) as writer:
      for _ in range(per_file):
        writer.write(codec.encode_example(
            {"image": rng.randint(0, 255, (32, 32, 3),
                                  np.uint8).tobytes(),
             "pose": rng.randn(7).astype(np.float32),
             "grasp_success": rng.randint(0, 2, (1,), np.int64)}, spec))
  return os.path.join(root, "grasps-*.tfr"), parsing.create_parse_fn(spec)


def _time_data_pass(patterns: str, parse_fn, use_native_stager: bool,
                    seed: int) -> dict:
  """One records->parsed-batch pass of one pipeline flavor; serial
  stages (prefetch 0, one parse worker) so the number prices the
  staging plane itself, not thread luck."""
  from tensor2robot_tpu.data import pipeline as pipeline_lib

  pipe = pipeline_lib.RecordBatchPipeline(
      patterns, parse_fn, batch_size=DATA_BATCH, mode="train",
      shuffle_buffer_size=512, seed=seed, prefetch_size=0,
      num_parallel_parses=1, use_native_stager=use_native_stager)
  with obs_metrics.isolated():
    stream = iter(pipe)
    for _ in range(2):  # warmup: stager spin-up / first-file opens
      next(stream)
    t0 = time.perf_counter()
    for _ in range(DATA_MEASURE_BATCHES):
      next(stream)
    elapsed = time.perf_counter() - t0
    snap = obs_metrics.snapshot(prefix="data/")
  return {
      "examples_per_sec": DATA_MEASURE_BATCHES * DATA_BATCH / elapsed,
      "telemetry": {
          "stage_ms_mean": snap.get("hist/data/stage_ms/mean"),
          "stage_ms_p90": snap.get("hist/data/stage_ms/p90"),
          "arena_bytes_mean": snap.get("hist/data/arena_bytes/mean"),
          "queue_depth": snap.get("gauge/data/stager_queue_depth"),
          "staged_batches": snap.get("counter/data/staged_batches"),
      },
  }


def data_main() -> None:
  """Data-plane bench: ONE JSON headline line, backend-free.

  Measures records->parsed-batch throughput end to end over a synthetic
  QT-Opt-shaped dataset, twice through the SAME RecordBatchPipeline:
  once on the pure-Python generator chain (interleave_records ->
  shuffled -> _batched -> per-record parse feed, today's fallback) and
  once on the native staging plane (C++ BatchStager arena ->
  BatchExampleParser.parse_arena). The headline is the stager number
  under the stable `qtopt_parse_ex_per_sec_cpu_smoke` name with the
  chain ratio alongside (ISSUE 6 acceptance: >= 1.3x), plus the
  `data/*` stager telemetry, and a `graftscope-run-v1` record appended
  to runs.jsonl so `graftscope diff` gates data-plane regressions like
  training ones. Never touches jax — the data plane is host-only.
  """
  from tensor2robot_tpu import native

  with tempfile.TemporaryDirectory(prefix="bench_data_") as root:
    patterns, parse_fn = _make_data_bench_dataset(root)
    # Host-load noise on this VM swings single passes +-50%
    # (PERFORMANCE.md round 2/6 A/Bs), so the chain and the stager run
    # as BACK-TO-BACK pairs sharing load conditions and the acceptance
    # ratio is the median of the per-pair ratios — slow host drift
    # cancels instead of landing on whichever side ran later.
    chain_runs, stager_runs, ratios = [], [], []
    for rerun in range(DATA_RERUNS):
      # Alternate A/B order within the pair so linear drift inside a
      # pair biases half the ratios up and half down instead of all one
      # way.
      stager_first = bool(rerun % 2) and native.available()
      if stager_first:
        stager_rec = _time_data_pass(patterns, parse_fn, True,
                                     seed=7 + rerun)
      chain = _time_data_pass(patterns, parse_fn, False, seed=7 + rerun)
      chain_runs.append(chain)
      if native.available():
        if not stager_first:
          stager_rec = _time_data_pass(patterns, parse_fn, True,
                                       seed=7 + rerun)
        stager_runs.append(stager_rec)
        ratios.append(stager_rec["examples_per_sec"]
                      / chain["examples_per_sec"])
        print(f"bench-data: pair {rerun}: chain "
              f"{chain['examples_per_sec']:.0f} ex/s, stager "
              f"{stager_rec['examples_per_sec']:.0f} ex/s "
              f"({ratios[-1]:.2f}x)", file=sys.stderr)
      else:
        print(f"bench-data: pair {rerun}: chain "
              f"{chain['examples_per_sec']:.0f} ex/s "
              "(no native toolchain)", file=sys.stderr)

  def median_by_eps(runs):
    return sorted(runs, key=lambda r: r["examples_per_sec"])[len(runs) // 2]

  python_chain = median_by_eps(chain_runs)
  stager = median_by_eps(stager_runs) if stager_runs else None
  best = stager or python_chain
  ratio = sorted(ratios)[len(ratios) // 2] if ratios else None
  headline = {
      "metric": "qtopt_parse_ex_per_sec_cpu_smoke",
      "value": round(best["examples_per_sec"], 2),
      "unit": "examples/sec",
      "vs_baseline": round(best["examples_per_sec"] / DATA_CPU_ANCHOR, 3),
      # The acceptance ratio (ISSUE 6 / PERFORMANCE.md "Reading a data
      # bench"): native staging plane vs the pure-Python record chain,
      # same records, same serial parse stage. None = toolchain absent
      # (the headline then prices the fallback chain itself).
      "stager_vs_python_chain": round(ratio, 3) if ratio else None,
      "python_chain_value": round(python_chain["examples_per_sec"], 2),
      "native_toolchain": native.available(),
      "batch_size": DATA_BATCH,
      "num_records": DATA_NUM_RECORDS,
      "record_bytes": 32 * 32 * 3 + 7 * 4 + 8,  # approx payload/record
      "stager": best["telemetry"],
      "host_load": _host_load_block(),
      "graftscope": _graftscope_block(),
  }
  print(json.dumps(headline))
  _write_runlog(headline, platform="cpu", device_kind="host-data-plane")


CACHE_MAX_BATCH = 4
# Recorded for this exact config on this host (round 7): total cold
# start (serve bucket-ladder warmup + train-step first compile) 5238 ms
# vs 1822 ms in a warm process (all 4 executables deserialized from
# graftcache — 2.9x). vs_baseline = anchor/value (time metric: bigger
# is better) and ~= 1.0 reads as "no cold/warm-start regression vs the
# recorded baseline", nothing more.
CACHE_COLD_ANCHOR_MS = 5200.0
CACHE_WARM_ANCHOR_MS = 1800.0


def cache_main(phase: str) -> None:
  """Cold/warm-start bench: ONE JSON headline line (CPU smoke path).

  Measures the end-to-end executable cold start the graftcache tier
  exists to kill: `BucketedEngine.warmup()` over the whole bucket
  ladder PLUS the train step's first-dispatch compile, in THIS process,
  against the persistent cache at GRAFTCACHE_DIR (default
  `.graftcache`). `--cache cold` evicts the smoke entries first so
  every executable pays trace+lower+compile; `--cache warm` must run in
  a fresh process after a cold run and reports `engine_compiles == 0` /
  `train_cache_hit == true` with every executable deserialized from
  disk (the ISSUE 7 acceptance pin; tests/test_excache.py pins the same
  cross-process contract). The warm headline carries
  `cold_vs_warm_warmup` (cold warmup_ms / warm warmup_ms, looked up
  from the latest cold record in runs.jsonl) — the load-invariant
  speedup ratio `graftscope diff` gates down-bad, like
  `stager_vs_python_chain`. Run both through `scripts/cache_bench.sh`.
  """
  if phase not in ("cold", "warm"):
    raise SystemExit(f"bench --cache: unknown phase {phase!r} "
                     "(want cold|warm)")
  backend_lib.pin_cpu()
  backend_lib.assert_cpu_backend()
  import jax

  from tensor2robot_tpu import modes, serving, specs as specs_lib
  from tensor2robot_tpu.obs import excache as excache_lib
  from tensor2robot_tpu.obs import xray as xray_lib
  from tensor2robot_tpu.parallel import train_step as ts
  from tensor2robot_tpu.predictors import predictors as predictors_lib
  from tensor2robot_tpu.research.qtopt import flagship

  cache_dir = _cache_dir()
  cache = excache_lib.ExecutableCache(cache_dir)
  if phase == "cold":
    # Scoped to THIS bench's namespace: the cache dir is shared with
    # every TPU/CPU probe, and a blanket evict would re-tax the next
    # real bench run 20-40 s of tunnel compile per probe executable.
    evicted = cache.evict(name_prefix="cache_smoke/")
    print(f"bench-cache: cold start — evicted {evicted} cache_smoke/ "
          f"entr(y/ies) from {cache_dir}", file=sys.stderr)
  # No XLA compilation-cache tier here on purpose: every executable this
  # bench measures routes through the serialized-AOT tier, and a process
  # that LOADS anything from a warm XLA cache serializes poisoned
  # payloads afterwards (measured; excache.store validation) — which
  # would make the cold phase's stores flaky. Tier 2 is for plain-jit
  # consumers (train_eval), not for this measurement.

  device = jax.devices()[0]
  model = flagship.make_flagship_model(device.platform)

  # Serving cold start: the whole bucket ladder through warmup().
  predictor = predictors_lib.CheckpointPredictor(model=model,
                                                 model_dir="/nonexistent")
  predictor.init_randomly()
  engine = serving.BucketedEngine(predictor=predictor,
                                  max_batch_size=CACHE_MAX_BATCH,
                                  name="cache_smoke/serve",
                                  cache=cache)
  engine.warmup()
  serve_warmup_ms = float(engine.warmup_ms or 0.0)

  # Trainer cold start: the train step's first dispatch (analyze_jit,
  # the same path train_eval's XrayedFunction pays on restart).
  feature_spec = model.preprocessor.get_out_feature_specification(
      modes.TRAIN)
  label_spec = model.preprocessor.get_out_label_specification(modes.TRAIN)
  features = jax.device_put(specs_lib.make_random_numpy(
      feature_spec, batch_size=16, seed=0), device)
  labels = jax.device_put(specs_lib.make_random_numpy(
      label_spec, batch_size=16, seed=100), device)
  state, _ = ts.create_train_state(model, jax.random.PRNGKey(0), features)
  t0 = time.perf_counter()
  step, train_rec = xray_lib.analyze_jit("cache_smoke/train_step",
                                         ts.make_train_step(model),
                                         state, features, labels,
                                         cache=cache)
  state, _ = step(state, features, labels)
  train_start_ms = (time.perf_counter() - t0) * 1e3
  train_cache = train_rec.get("cache") or {}

  warmup_ms = serve_warmup_ms + train_start_ms
  cold_vs_warm = None
  if phase == "warm":
    # The latest cold record in this runs.jsonl prices the ratio; fail
    # loud in the gate script, soft here (first warm run ever).
    from tensor2robot_tpu.obs import runlog

    for record in reversed(runlog.load_records(_runs_path())):
      bench_block = record.get("bench") or {}
      if bench_block.get("metric") == "qtopt_cold_start_ms_cpu_smoke":
        cold_ms = float(bench_block.get("warmup_ms") or 0.0)
        if cold_ms > 0 and warmup_ms > 0:
          cold_vs_warm = cold_ms / warmup_ms
        break
  headline = {
      "metric": f"qtopt_{phase}_start_ms_cpu_smoke",
      "value": round(warmup_ms, 2),
      "unit": "ms",
      "vs_baseline": round(
          (CACHE_COLD_ANCHOR_MS if phase == "cold"
           else CACHE_WARM_ANCHOR_MS) / max(warmup_ms, 1e-9), 3),
      "warmup_ms": round(warmup_ms, 2),
      "serve_warmup_ms": round(serve_warmup_ms, 2),
      "train_start_ms": round(train_start_ms, 2),
      "engine_compiles": engine.compile_count,
      "engine_cache_loads": engine.cache_loads,
      "train_cache_hit": bool(train_cache.get("hit")),
      "buckets": engine.buckets,
      # cold warmup_ms / warm warmup_ms (>= 1; warm-only): the
      # load-invariant cold-start speedup, diff-gated down-bad.
      "cold_vs_warm_warmup": (round(cold_vs_warm, 3)
                              if cold_vs_warm else None),
      "cache_dir": cache_dir,
      "cache": excache_lib.cache_stats(),
      "device_kind": device.device_kind,
      "platform": device.platform,
      "host_load": _host_load_block(),
      "graftscope": _graftscope_block(),
  }
  print(json.dumps(headline))
  _write_runlog(headline, platform=device.platform,
                device_kind=device.device_kind,
                compile_records=engine.compile_records + [train_rec])


# graftforge bench config (bench.py --forge, ISSUE 15): a 2-replica
# fleet + the trainer's first dispatch, cold vs FORGE-WARMED, in fresh
# subprocesses. Small ladder on purpose: the farm and both arms run
# serially on this 1-core host, and the ratio (not the absolute wall)
# is the gated number.
FORGE_REPLICAS = 2
FORGE_MAX_BATCH = 4      # rungs [1, 2, 4] per replica
FORGE_TRAIN_BATCH = 16
FORGE_NAMESPACE = "forge_smoke"
# Recorded on this host (round 15): cold fleet+trainer start 6027 ms vs
# 1807 ms forge-warmed (forged_vs_cold 3.34; all 6 rungs + the train
# step deserialized, compile share 0). vs_baseline = anchor/value (time
# metric: bigger is better; ~1.0 = no cold-start regression). The cold
# side has no anchor: it is reported raw and only the paired ratio is
# gated (the cold arm swings 4.3-6.0 s with host state).
FORGE_FORGED_ANCHOR_MS = 1800.0


def _forge_bench_plan() -> dict:
  """The hand-built forge plan matching `_forge_child_entry`'s
  deployment EXACTLY (2 placed flagship replicas x the [1,2,4] ladder +
  the single-device train step) — the bench's own enumeration, namespaced
  `forge_smoke/` so evicting it never re-taxes other probes' entries."""
  from tensor2robot_tpu.obs import forge as forge_lib

  targets = [{
      "family": "serve",
      "name": f"{FORGE_NAMESPACE}/serve",
      "buckets": serving_lib_bucket_ladder(FORGE_MAX_BATCH),
      "replica_index": index,
      "num_replicas": FORGE_REPLICAS,
      "placed": True,
      "executables": len(serving_lib_bucket_ladder(FORGE_MAX_BATCH)),
      "forgeable": True,
  } for index in range(FORGE_REPLICAS)]
  targets.append({
      "family": "train",
      "name": f"{FORGE_NAMESPACE}/train_step",
      "mesh_shape": None,  # the one-chip deployment shape: SingleDevice-
      "batch_size": FORGE_TRAIN_BATCH,  # sharding donation, cacheable
      "executables": 1,
      "forgeable": True,
  })
  return {
      "schema": forge_lib.FORGE_SCHEMA,
      "schema_version": forge_lib.FORGE_SCHEMA_VERSION,
      "config_files": [],
      "bindings": [],
      "model": {"kind": "flagship"},
      "model_dir": None,
      "targets": targets,
  }


def serving_lib_bucket_ladder(max_batch: int) -> list:
  from tensor2robot_tpu.serving import engine as engine_lib

  return engine_lib.bucket_ladder(max_batch)


def _forge_child_entry(phase: str, cache_dir: str, out_path: str) -> None:
  """Fresh-process cold-start measurement arm (`--forge-child`): builds
  the 2-replica flagship fleet (replica state placed per device group,
  exactly what the forge farm's workers key against) + the trainer's
  first dispatch, against `cache_dir` ('' = no cache: the cold arm).
  Fresh processes are the measurement contract — an in-process pair
  would hand the second arm the first's jit caches."""
  backend_lib.pin_cpu()
  backend_lib.assert_cpu_backend()
  import jax

  from tensor2robot_tpu import modes, serving, specs as specs_lib
  from tensor2robot_tpu.obs import excache as excache_lib
  from tensor2robot_tpu.obs import xray as xray_lib
  from tensor2robot_tpu.parallel import mesh as mesh_lib
  from tensor2robot_tpu.parallel import train_step as ts
  from tensor2robot_tpu.predictors import predictors as predictors_lib
  from tensor2robot_tpu.research.qtopt import flagship

  cache = cache_dir or None
  device = jax.devices()[0]
  groups = mesh_lib.replica_device_groups(FORGE_REPLICAS, jax.devices())

  def make_replica(index, _group):
    model = flagship.make_flagship_model(device.platform)
    predictor = predictors_lib.CheckpointPredictor(model=model,
                                                   model_dir="/nonexistent")
    predictor.init_randomly()
    if groups[index]:
      predictor.place_on_device(groups[index][0])
    return serving.BucketedEngine(
        predictor=predictor, max_batch_size=FORGE_MAX_BATCH,
        name=f"serve/forge/replica{index}", cache=cache,
        cache_namespace=f"{FORGE_NAMESPACE}/serve")

  build_start = time.perf_counter()
  fleet = serving.ServingFleet(replica_factory=make_replica,
                               num_replicas=FORGE_REPLICAS,
                               max_batch_size=FORGE_MAX_BATCH)
  build_ms = (time.perf_counter() - build_start) * 1e3
  try:
    warm_start = time.perf_counter()
    fleet.warmup()
    serve_warmup_ms = (time.perf_counter() - warm_start) * 1e3

    model = flagship.make_flagship_model(device.platform)
    feature_spec = model.preprocessor.get_out_feature_specification(
        modes.TRAIN)
    label_spec = model.preprocessor.get_out_label_specification(
        modes.TRAIN)
    features = jax.device_put(specs_lib.make_random_numpy(
        feature_spec, batch_size=FORGE_TRAIN_BATCH, seed=0), device)
    labels = jax.device_put(specs_lib.make_random_numpy(
        label_spec, batch_size=FORGE_TRAIN_BATCH, seed=100), device)
    state, _ = ts.create_train_state(model, jax.random.PRNGKey(0),
                                     features)
    t0 = time.perf_counter()
    step, train_rec = xray_lib.analyze_jit(
        f"{FORGE_NAMESPACE}/train_step", ts.make_train_step(model),
        state, features, labels,
        cache=excache_lib.ExecutableCache(cache) if cache else None)
    state, _ = step(state, features, labels)
    train_start_ms = (time.perf_counter() - t0) * 1e3

    engines = [fleet.replica(i) for i in range(FORGE_REPLICAS)]
    result = {
        "phase": phase,
        "build_ms": round(build_ms, 2),
        "serve_warmup_ms": round(serve_warmup_ms, 2),
        "train_start_ms": round(train_start_ms, 2),
        "start_ms": round(serve_warmup_ms + train_start_ms, 2),
        "engine_compiles": [e.compile_count for e in engines],
        "engine_cache_loads": [e.cache_loads for e in engines],
        "warmup_load_ms": round(sum(e.warmup_load_ms for e in engines),
                                2),
        "warmup_compile_ms": round(sum(e.warmup_compile_ms
                                       for e in engines), 2),
        "warmup_provenance": fleet.warmup_provenance(),
        "train_cache_hit": bool((train_rec.get("cache") or {}).get("hit")),
        "compile_records": ([r for e in engines
                             for r in e.compile_records] + [train_rec]),
        "cache": excache_lib.cache_stats(),
        "device_kind": device.device_kind,
        "platform": device.platform,
    }
  finally:
    fleet.close()
  with open(out_path, "w") as f:
    json.dump(result, f)


def _run_forge_child(phase: str, cache_dir: str) -> dict:
  out_path = os.path.join(tempfile.mkdtemp(prefix="forge-bench-"),
                          f"{phase}.json")
  proc = subprocess.run(
      [sys.executable, os.path.abspath(__file__), "--forge-child", phase,
       cache_dir, out_path],
      timeout=900, env={**os.environ, "JAX_PLATFORMS": "cpu"})
  if proc.returncode != 0 or not os.path.isfile(out_path):
    raise SystemExit(f"bench --forge: {phase} child failed "
                     f"(rc={proc.returncode})")
  with open(out_path) as f:
    return json.load(f)


def forge_main() -> None:
  """graftforge cold-vs-forged start bench: ONE JSON headline line.

  THE ISSUE 15 acceptance numbers. Three phases, all on the virtual
  8-device CPU mesh: (1) a COLD arm in a fresh subprocess — 2-replica
  flagship `ServingFleet` warmup + trainer first dispatch with no cache
  (every executable pays trace+lower+compile); (2) the FORGE FARM
  (`obs.forge.run_forge` over the bench's own plan — the same worker
  subprocess pool `graftscope forge` drives) populating the
  `forge_smoke/` namespace of GRAFTCACHE_DIR; (3) a FORGED arm in
  another fresh subprocess — the identical fleet+trainer start, which
  must deserialize EVERYTHING (`engine_compiles == [0, 0]`,
  `train_cache_hit == true`, pinned by scripts/forge_bench.sh).
  `forged_vs_cold` (cold/forged start ratio, back-to-back fresh
  processes => load-invariant) is diff-gated down-bad; acceptance floor
  2.0. The forged arm's `warmup_load_ms`/`warmup_compile_ms` split plus
  per-rung provenance make any regression attributable to specific
  rungs. See PERFORMANCE.md "Reading a forge bench"."""
  flags = os.environ.get("XLA_FLAGS", "")
  if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
  backend_lib.pin_cpu()
  backend_lib.assert_cpu_backend()
  import jax

  from tensor2robot_tpu.obs import excache as excache_lib
  from tensor2robot_tpu.obs import forge as forge_lib

  cache_dir = _cache_dir()
  cache = excache_lib.ExecutableCache(cache_dir)
  evicted = cache.evict(name_prefix=f"{FORGE_NAMESPACE}/")
  print(f"bench-forge: evicted {evicted} {FORGE_NAMESPACE}/ entr"
        f"(y/ies) from {cache_dir}", file=sys.stderr)

  print("bench-forge: cold arm (fresh subprocess, no cache)",
        file=sys.stderr)
  cold = _run_forge_child("cold", "")

  print("bench-forge: running the forge farm", file=sys.stderr)
  plan = _forge_bench_plan()
  manifest = forge_lib.run_forge(plan, cache_dir, jobs=2)
  if manifest["errors"]:
    raise SystemExit(f"bench --forge: farm errors: {manifest['errors']}")

  print("bench-forge: forged arm (fresh subprocess, warmed cache)",
        file=sys.stderr)
  forged = _run_forge_child("forged", cache_dir)

  forged_vs_cold = (cold["start_ms"] / forged["start_ms"]
                    if forged["start_ms"] > 0 else None)
  warm_total = forged["warmup_load_ms"] + forged["warmup_compile_ms"]
  headline = {
      "metric": "qtopt_forged_start_ms_cpu_smoke",
      "value": forged["start_ms"],
      "unit": "ms",
      "vs_baseline": round(
          FORGE_FORGED_ANCHOR_MS / max(forged["start_ms"], 1e-9), 3),
      "forged_start_ms": forged["start_ms"],
      "cold_start_ms": cold["start_ms"],
      # cold/forged start ratio (>= 1; fresh back-to-back subprocesses
      # => load-invariant): the diff-gated ISSUE 15 headline, floor 2.0.
      "forged_vs_cold": (round(forged_vs_cold, 3)
                         if forged_vs_cold else None),
      # The all-zero pin: a forge-warmed fleet + trainer start performs
      # ZERO fresh compiles (forge_bench.sh fails loud otherwise).
      "engine_compiles": forged["engine_compiles"],
      "engine_cache_loads": forged["engine_cache_loads"],
      "train_cache_hit": forged["train_cache_hit"],
      "buckets": serving_lib_bucket_ladder(FORGE_MAX_BATCH),
      "replicas": FORGE_REPLICAS,
      # Satellite: the warmup split + per-rung provenance — WHERE a
      # regression lives, not just that one exists.
      "warmup_load_ms": forged["warmup_load_ms"],
      "warmup_compile_ms": forged["warmup_compile_ms"],
      "forge_compile_share": round(
          forged["warmup_compile_ms"] / warm_total, 4) if warm_total
      else 0.0,
      "warmup_provenance": forged["warmup_provenance"],
      "serve_warmup_ms": forged["serve_warmup_ms"],
      "train_start_ms": forged["train_start_ms"],
      "cold_arm": {k: cold[k] for k in
                   ("serve_warmup_ms", "train_start_ms",
                    "warmup_compile_ms", "engine_compiles")},
      "forge": {k: manifest[k] for k in
                ("jobs", "wall_s", "counts", "total_compile_s")},
      "cache_dir": cache_dir,
      "cache": forged["cache"],
      "device_kind": forged["device_kind"],
      "platform": forged["platform"],
      "num_devices": len(jax.devices()),
      "host_load": _host_load_block(),
      "graftscope": _graftscope_block(),
  }
  print(json.dumps(headline))
  _write_runlog(headline, platform=forged["platform"],
                device_kind=forged["device_kind"],
                compile_records=forged["compile_records"])


PP_STAGES = 4            # pp ranks on the virtual 8-device mesh (2x4x1)
PP_VIRTUAL = 2           # 1F1B chunks per rank (8 layers total)
PP_MICRO = 8             # microbatches per step
PP_MICRO_BATCH = 32      # rows per microbatch (sharded over 'data')
PP_DIM = 512             # stage width: compute must dominate per-tick
                         # scan/ppermute overhead or the tick-count win
                         # is invisible on the time-shared CPU mesh
                         # (PERFORMANCE.md "Reading a pipeline bench"
                         # prices the asymptote)
PP_MEASURE_STEPS = 8
PP_RERUNS = 5


def pp_main() -> None:
  """Pipeline-schedule bench: ONE JSON headline line (CPU smoke path).

  Prices the interleaved-1F1B schedule win over GPipe on the virtual
  8-device CPU mesh (the tests' 2x4x1 topology — pp=4 ranks, batch rows
  sharded over 'data'): the SAME 8-layer residual-MLP trunk trains once
  as GPipe (4 coarse stages of 2 depth-contiguous layers, v=1) and once
  as interleaved 1F1B (8 single-layer virtual chunks, v=2), through
  `make_pipelined_train_step(audit_name=...)` so both executables carry
  the analyze_jit donation audit and the `pp/*` schedule gauges.

  Every rank computes on every tick of the lockstep scan (idle slots
  compute masked zeros), so even on a time-shared CPU mesh wall time
  tracks TOTAL layer-tick slots — GPipe's 2*(M+S-1)=22 per rank vs
  1F1B's v*ceil(M/S)*S+S-1=19 — and the paired step-time ratio
  `onefonb_vs_gpipe` (~22/19 analytic) is load-invariant the same way
  `data_vs_synthetic` is: arms run back-to-back with alternating order
  and the median per-pair ratio is the gated number. The headline value
  is the 1F1B schedule's STATIC bubble fraction (idle-tick accounting,
  deterministic from (S, M, v)); measured per-tick wall time rides
  alongside (`tick_ms`). Diff-gated by `scripts/pp_bench.sh` via
  `graftscope diff` (PERFORMANCE.md "Reading a pipeline bench").
  """
  backend_lib.pin_cpu(n_devices=8)
  backend_lib.assert_cpu_backend()
  import jax
  import jax.numpy as jnp
  import numpy as np
  import optax

  from tensor2robot_tpu.parallel import mesh as mesh_lib
  from tensor2robot_tpu.parallel import pipeline_parallel as pp_lib

  mesh = mesh_lib.create_mesh(mesh_shape=(2, PP_STAGES, 1),
                              axis_names=("data", "pp", "model"))
  s, v, m_count, mb, dim = (PP_STAGES, PP_VIRTUAL, PP_MICRO,
                            PP_MICRO_BATCH, PP_DIM)
  rng = np.random.RandomState(0)
  layers = [{"w": jnp.asarray(rng.randn(dim, dim).astype(np.float32)
                              / np.sqrt(dim)),
             "b": jnp.zeros((dim,), jnp.float32)} for _ in range(s * v)]
  micro = jnp.asarray(rng.randn(m_count, mb, dim).astype(np.float32))
  targets = jnp.asarray(rng.randn(m_count, mb, dim).astype(np.float32))

  def layer_fn(p, x):
    return x + jnp.tanh(x @ p["w"] + p["b"])

  def coarse_stage_fn(p, x):
    # One GPipe stage = v depth-contiguous layers ([v, ...] leaves).
    def body(h, lp):
      return layer_fn(lp, h), None

    h, _ = jax.lax.scan(body, x, p)
    return h

  def loss_fn(outputs, tgt):
    return jnp.mean((outputs - tgt) ** 2)

  optimizer = optax.adam(1e-3)

  def build(arm):
    if arm == "gpipe":
      stacked = pp_lib.stack_stage_params(
          [jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                  *layers[i * v:(i + 1) * v])
           for i in range(s)])
      step = pp_lib.make_pipelined_train_step(
          coarse_stage_fn, loss_fn, optimizer, mesh, axis_name="pp",
          batch_axis="data", num_virtual_stages=1,
          audit_name="bench/pp_gpipe_train_step")
      accounting = pp_lib.schedule_accounting(s, m_count, 1)
      layer_ticks = accounting["total_ticks"] * v
    else:
      # Pre-permuted interleaved layout (the production path): the
      # per-step depth->interleaved gather and its backward scatter stay
      # out of the hot loop.
      stacked = pp_lib.interleave_stage_stack(
          pp_lib.stack_stage_params(layers), s, v)
      step = pp_lib.make_pipelined_train_step(
          layer_fn, loss_fn, optimizer, mesh, axis_name="pp",
          batch_axis="data", num_virtual_stages=v,
          params_layout="interleaved",
          audit_name="bench/pp_onefonb_train_step")
      accounting = pp_lib.schedule_accounting(s, m_count, v)
      layer_ticks = accounting["total_ticks"]
    n_virtual = 1 if arm == "gpipe" else v
    params = pp_lib.shard_pipeline_tree(stacked, mesh, "pp", n_virtual)
    opt_state = pp_lib.shard_pipeline_tree(optimizer.init(stacked), mesh,
                                           "pp", n_virtual)
    return step, params, opt_state, accounting, layer_ticks

  def time_arm(step, params, opt_state):
    first_loss = None
    for _ in range(2):  # warmup: first call compiles through analyze_jit
      params, opt_state, loss = step(params, opt_state, micro, targets)
      first_loss = first_loss if first_loss is not None else float(loss)
    backend_lib.sync(jax.tree_util.tree_leaves(params)[0])
    t0 = time.perf_counter()
    for _ in range(PP_MEASURE_STEPS):
      params, opt_state, _ = step(params, opt_state, micro, targets)
    backend_lib.sync(jax.tree_util.tree_leaves(params)[0])
    step_ms = (time.perf_counter() - t0) * 1e3 / PP_MEASURE_STEPS
    return step_ms, params, opt_state, first_loss

  arms = {}
  for arm in ("gpipe", "onefonb"):
    step, params, opt_state, accounting, layer_ticks = build(arm)
    arms[arm] = {"step": step, "params": params, "opt_state": opt_state,
                 "accounting": accounting, "layer_ticks": layer_ticks,
                 "runs": [], "first_loss": None}
  ratios = []
  for rerun in range(PP_RERUNS):
    order = (("onefonb", "gpipe") if rerun % 2 else ("gpipe", "onefonb"))
    pair = {}
    for arm in order:
      a = arms[arm]
      step_ms, a["params"], a["opt_state"], first = time_arm(
          a["step"], a["params"], a["opt_state"])
      a["runs"].append(step_ms)
      if a["first_loss"] is None:
        a["first_loss"] = first
      pair[arm] = step_ms
    ratios.append(pair["gpipe"] / pair["onefonb"])
    print(f"bench-pp: pair {rerun}: gpipe {pair['gpipe']:.1f} ms/step, "
          f"1f1b {pair['onefonb']:.1f} ms/step "
          f"({ratios[-1]:.2f}x)", file=sys.stderr)

  def median(values):
    return sorted(values)[len(values) // 2]

  gpipe, onefonb = arms["gpipe"], arms["onefonb"]
  # Same init, same data: the two schedules are the same function, so
  # their first-step losses must agree to fp32 tolerance — the bench
  # re-checks the equivalence contract the tests pin, every run.
  loss_parity_err = abs(gpipe["first_loss"] - onefonb["first_loss"])
  if loss_parity_err > 1e-4 * max(1.0, abs(gpipe["first_loss"])):
    raise SystemExit(
        f"bench --pp: schedule equivalence violated: gpipe first-step "
        f"loss {gpipe['first_loss']} vs 1f1b {onefonb['first_loss']}")

  def arm_block(a):
    step_ms = median(a["runs"])
    return {
        "step_ms": round(step_ms, 3),
        # Measured per layer-tick slot (every rank runs one LAYER of
        # compute per slot; GPipe's coarse stage = v layer slots/tick).
        "tick_ms": round(step_ms / a["layer_ticks"], 4),
        "layer_ticks": a["layer_ticks"],
        "bubble_fraction": round(a["accounting"]["bubble_fraction"], 4),
        "accounting": a["accounting"],
        "first_step_loss": round(a["first_loss"], 6),
    }

  bubble = onefonb["accounting"]["bubble_fraction"]
  gpipe_bubble = gpipe["accounting"]["bubble_fraction"]
  gpipe_rec = getattr(gpipe["step"], "record", None)
  onefonb_rec = getattr(onefonb["step"], "record", None)
  headline = {
      "metric": "qtopt_pp_bubble_frac_cpu_smoke",
      # The headline value is STATIC schedule accounting — deterministic
      # from (S, M, v), so the gate band can be tight; the measured side
      # lives in onefonb_vs_gpipe / tick_ms.
      "value": round(bubble, 4),
      "unit": "bubble_fraction",
      "vs_baseline": round(gpipe_bubble / bubble, 3),
      "pp_bubble_fraction": round(bubble, 4),
      "gpipe_bubble_fraction": round(gpipe_bubble, 4),
      # The load-invariant paired step-time ratio (>= ~22/19 analytic
      # when compute dominates tick overhead), diff-gated down-bad.
      "onefonb_vs_gpipe": round(median(ratios), 3),
      "gpipe": arm_block(gpipe),
      "onefonb": arm_block(onefonb),
      "loss_parity_abs_err": loss_parity_err,
      "num_stages": s,
      "num_virtual_stages": v,
      "num_micro": m_count,
      "micro_batch": mb,
      "stage_dim": dim,
      "pairs": len(ratios),
      "measure_steps": PP_MEASURE_STEPS,
      # pp/* gauges the schedules registered at trace time + the xray
      # donation audit (donated_bytes > 0 proves the donated in-place
      # optimizer flow survived the schedule change).
      "schedule_gauges": obs_metrics.snapshot(prefix="pp/"),
      "donated_bytes": {
          "gpipe": (gpipe_rec or {}).get("donated_bytes"),
          "onefonb": (onefonb_rec or {}).get("donated_bytes"),
      },
      "device_kind": jax.devices()[0].device_kind,
      "platform": jax.devices()[0].platform,
      "host_load": _host_load_block(),
      "graftscope": _graftscope_block(),
  }
  print(json.dumps(headline))
  _write_runlog(headline, platform="cpu", device_kind="host-pp-smoke",
                compile_records=[r for r in (gpipe_rec, onefonb_rec) if r])


SESSION_PREFIX_LENGTHS = (8, 32)
SESSION_PAIRS = 5
SESSION_MAX_SESSIONS = 8
SESSION_BUCKETS = (1, 2, 4)
# Recorded for the T=32 decode tick at first landing on this host
# (ISSUE 11, quiet load: 0.26 ms/tick — overhead-bound, see
# PERFORMANCE.md "Reading a session bench"): like every absolute
# wall-clock on the 1-core VM it swings with load — the load-invariant
# number is session_vs_stateless (paired back-to-back episodes).
# vs_baseline ~= 1.0 reads as "no decode-tick regression vs the
# recorded baseline", nothing more.
SESSION_CPU_ANCHOR_MS = 0.26


def session_main() -> None:
  """Stateful-session serve bench: ONE JSON headline line (CPU smoke).

  THE ISSUE 11 acceptance numbers, measured as paired back-to-back A/B
  episodes over the causal-attention `SequenceRegressionModel` at
  prefix lengths T in {8, 32}:

  * stateless arm — the pre-session serving shape: every control tick
    re-runs the full O(T) padded-prefix predict through the in-process
    predictor (the robot pays T full forwards per episode);
  * cached arm — one `SessionEngine` session per episode: open, T
    decode ticks against the device-resident KV arena, close.

  `session_vs_stateless` is the pair-median per-tick cost ratio
  stateless/cached at T=32 (>= 2.0x acceptance floor; back-to-back
  pairs make it load-invariant on this +-4x host).
  `decode_tick_flat_32_vs_8` is the O(1) claim: the cached tick cost
  must be flat (+-20%) as the prefix grows 8 -> 32 while the stateless
  tick scales with T. A churn sweep (open/step/close under slot
  pressure, evictions included) pins zero recompiles after warmup
  (`engine_compiles` stays at the warmed ladder count, exec_fallbacks
  0).

  ISSUE 20 adds a graftkern A/B at the headline T: the same predictor
  behind two fresh engines, `use_decode_kernel=True` (forced — on CPU
  this runs the fused Pallas kernels under the interpreter, so the
  real kernel body is exercised every bench run) vs `=False` (the
  jitted gather/decode/scatter reference). `decode_kernel_vs_xla` is
  the pair-median xla/kernel per-tick ratio (>1 = kernel faster; on
  CPU it reads BELOW 1 — interpreter tax — and the gate tracks drift,
  not absolute speed; the hardware win only shows on TPU, see
  PERFORMANCE.md "Reading a decode-kernel bench"). The kernel arm must
  be compile-quiet after its warm episode (`kernel_compiles_stable`).
  The default (auto) engine stays on the jitted path off-TPU, so the
  pre-existing gates measure what they always measured.

  Appended to runs.jsonl; `scripts/session_bench.sh` diff-gates
  `session_vs_stateless` + `decode_kernel_vs_xla` (down-bad) and
  `decode_tick_ms` (up-bad).
  """
  backend_lib.pin_cpu()
  backend_lib.assert_cpu_backend()
  import jax
  import numpy as np

  from tensor2robot_tpu import serving
  from tensor2robot_tpu.models import sequence_model
  from tensor2robot_tpu.predictors import predictors as predictors_lib

  device = jax.devices()[0]
  rng = np.random.RandomState(0)
  per_t: dict = {}
  engine = None
  churn_block = None
  stage_block = None
  kernel_block = None
  for seq_len in SESSION_PREFIX_LENGTHS:
    # hidden 128: big enough that model compute (not per-call dispatch
    # overhead, ~0.1 ms on this host) dominates the stateless tick, so
    # the ratio reads the O(T)-vs-O(1) structure rather than Python.
    model = sequence_model.SequenceRegressionModel(
        obs_size=16, action_size=7, sequence_length=seq_len,
        hidden_size=128, num_blocks=2, num_heads=4)
    predictor = predictors_lib.CheckpointPredictor(model=model,
                                                   model_dir="/nonexistent")
    predictor.init_randomly()
    engine = serving.SessionEngine(predictor=predictor,
                                   max_sessions=SESSION_MAX_SESSIONS,
                                   buckets=SESSION_BUCKETS)
    engine.warmup()
    obs_seq = rng.randn(1, seq_len, 16).astype(np.float32)
    request = {"observation": obs_seq}

    def stateless_episode_ms() -> float:
      t0 = time.perf_counter()
      for _ in range(seq_len):
        predictor.predict(request)
      return (time.perf_counter() - t0) * 1e3 / seq_len

    def cached_episode_ms() -> float:
      t0 = time.perf_counter()
      sid = engine.open()
      for t in range(seq_len):
        engine.step(sid, {"observation": obs_seq[0, t]})
      engine.close_session(sid)
      return (time.perf_counter() - t0) * 1e3 / seq_len

    # Warm both arms out of the timed window (xray compile on the
    # predictor side; the engine ladder compiled at warmup()).
    predictor.predict(request)
    warm_sid = engine.open()
    engine.step(warm_sid, {"observation": obs_seq[0, 0]})
    engine.close_session(warm_sid)

    stateless_ms: list = []
    cached_ms: list = []
    ratios: list = []
    for pair in range(SESSION_PAIRS):
      # Alternate order inside each back-to-back pair so slow host
      # phases hit both arms evenly (the data-bench pairing design).
      if pair % 2 == 0:
        s_ms, c_ms = stateless_episode_ms(), cached_episode_ms()
      else:
        c_ms, s_ms = cached_episode_ms(), stateless_episode_ms()
      stateless_ms.append(s_ms)
      cached_ms.append(c_ms)
      ratios.append(s_ms / c_ms if c_ms else float("inf"))
      print(f"bench-session: T={seq_len} pair {pair}: stateless "
            f"{s_ms:.2f} ms/tick, cached {c_ms:.2f} ms/tick "
            f"({ratios[-1]:.2f}x)", file=sys.stderr)
    per_t[seq_len] = {
        "stateless_tick_ms": round(_median(stateless_ms), 3),
        "decode_tick_ms": round(_median(cached_ms), 3),
        "session_vs_stateless": round(_median(ratios), 3),
        "pairs": SESSION_PAIRS,
    }

    if seq_len == SESSION_PREFIX_LENGTHS[-1]:
      # graftkern A/B at the headline T (ISSUE 20): same predictor, two
      # fresh engines with the kernel tier forced to opposite sides.
      # Distinct names => distinct graftcache namespaces, so kernel-arm
      # rungs never collide with xla-arm rungs. Paired alternating-order
      # episodes, exactly like the session_vs_stateless pairing above.
      kern_engine = serving.SessionEngine(
          predictor=predictor, max_sessions=SESSION_MAX_SESSIONS,
          buckets=SESSION_BUCKETS, name="serve/session/kern",
          use_decode_kernel=True)
      xla_engine = serving.SessionEngine(
          predictor=predictor, max_sessions=SESSION_MAX_SESSIONS,
          buckets=SESSION_BUCKETS, name="serve/session/xla",
          use_decode_kernel=False)

      def arm_episode_ms(arm) -> float:
        t0 = time.perf_counter()
        sid = arm.open()
        for t in range(seq_len):
          arm.step(sid, {"observation": obs_seq[0, t]})
        arm.close_session(sid)
        return (time.perf_counter() - t0) * 1e3 / seq_len

      for arm in (kern_engine, xla_engine):
        arm.warmup()
        arm_episode_ms(arm)  # warm episode, out of the timed window
      kern_compiles_warm = kern_engine.compile_count
      kern_ms_samples: list = []
      xla_ms_samples: list = []
      ab_ratios: list = []
      for pair in range(SESSION_PAIRS):
        if pair % 2 == 0:
          k_ms, x_ms = (arm_episode_ms(kern_engine),
                        arm_episode_ms(xla_engine))
        else:
          x_ms, k_ms = (arm_episode_ms(xla_engine),
                        arm_episode_ms(kern_engine))
        kern_ms_samples.append(k_ms)
        xla_ms_samples.append(x_ms)
        ab_ratios.append(x_ms / k_ms if k_ms else float("inf"))
        print(f"bench-session: T={seq_len} kernel-A/B pair {pair}: "
              f"kernel {k_ms:.2f} ms/tick, xla {x_ms:.2f} ms/tick "
              f"({ab_ratios[-1]:.2f}x)", file=sys.stderr)
      kernel_block = {
          # >1 = kernel arm faster. On CPU the kernel arm runs the
          # Pallas INTERPRETER (interpret_mode below), so this reads
          # below 1 and the diff gate tracks drift, not absolute wins.
          "decode_kernel_vs_xla": round(_median(ab_ratios), 3),
          "kernel_tick_ms": round(_median(kern_ms_samples), 3),
          "xla_tick_ms": round(_median(xla_ms_samples), 3),
          "kernel_active": kern_engine.decode_kernel_active,
          "kernel_reason": kern_engine.decode_kernel_reason,
          "xla_reason": xla_engine.decode_kernel_reason,
          # The acceptance pin: zero fresh compiles in the kernel arm
          # across the measured episodes (warm ladder + warm episode
          # already paid every trace).
          "kernel_compiles_stable":
              kern_engine.compile_count == kern_compiles_warm,
          "kernel_compiles": kern_engine.compile_count,
          "interpret_mode": device.platform != "tpu",
          "pairs": SESSION_PAIRS,
      }

      # Churn sweep at the headline T: opens/steps under slot pressure
      # (forced evictions) + multi-session step_many across every
      # bucket — compile_count must not move and nothing may fall back.
      compiles_before = engine.compile_count
      with obs_metrics.isolated():
        sids = [engine.open() for _ in range(SESSION_MAX_SESSIONS)]
        for group in (4, 2, 1, 3):
          engine.step_many([(s, {"observation": obs_seq[0, 0]})
                            for s in sids[:group]])
        for _ in range(SESSION_MAX_SESSIONS // 2):
          sids.append(engine.open())  # evicts an idle LRU session
        for sid in sids:
          try:
            engine.step(sid, {"observation": obs_seq[0, 1]})
          except serving.SessionError:
            pass  # evicted mid-sweep: the expected slot-pressure path
        for sid in sids:
          try:
            engine.close_session(sid)
          except serving.SessionError:
            pass
        churn_snap = obs_metrics.snapshot(prefix="serve/session/")
      churn_block = {
          "compile_count_stable":
              engine.compile_count == compiles_before,
          "opens": churn_snap.get("counter/serve/session/opens"),
          "evictions": churn_snap.get("counter/serve/session/evictions"),
          "ticks": churn_snap.get("counter/serve/session/ticks"),
          "exec_fallbacks": churn_snap.get(
              "counter/serve/session/exec_fallbacks", 0.0),
      }

      # graftrace stage decomposition at the headline T, measured
      # through the continuous-batching front (the paired arms above
      # drive the raw engine, so nothing queues there): concurrent
      # episodes stepping through one SessionBatcher, queue_wait +
      # dispatch recorded per tick.
      import threading

      with obs_metrics.isolated():
        with serving.SessionBatcher(engine=engine,
                                    max_delay_ms=1.0) as front:
          def episode() -> None:
            sid = front.open()
            for t in range(8):
              front.step(sid, {"observation": obs_seq[0, t]})
            front.close_session(sid)

          clients = [threading.Thread(target=episode)
                     for _ in range(4)]
          for c in clients:
            c.start()
          for c in clients:
            c.join()
        stage_block = graftrace.stage_breakdown()

  t_lo, t_hi = SESSION_PREFIX_LENGTHS[0], SESSION_PREFIX_LENGTHS[-1]
  decode_hi = per_t[t_hi]["decode_tick_ms"]
  decode_lo = per_t[t_lo]["decode_tick_ms"]
  headline = {
      "metric": "seq_session_tick_ms_cpu_smoke",
      "value": decode_hi,
      "unit": "ms/tick",
      "vs_baseline": round(decode_hi / SESSION_CPU_ANCHOR_MS, 3),
      # The two diff-gated scalars (runlog.DEFAULT_THRESHOLDS): the
      # load-invariant paired ratio (down-bad) and the absolute decode
      # tick (up-bad, loose band), both at the headline T.
      "session_vs_stateless": per_t[t_hi]["session_vs_stateless"],
      "decode_tick_ms": decode_hi,
      # The O(1) claim: cached tick cost flat (+-20% acceptance) while
      # the prefix quadruples.
      "decode_tick_flat_32_vs_8": round(decode_hi / decode_lo, 3)
      if decode_lo else None,
      # graftkern A/B (ISSUE 20): pair-median xla/kernel tick ratio at
      # the headline T, diff-gated down-bad (drift detector — on CPU
      # the kernel arm is interpreter-mode, so the absolute value is
      # not a win claim; the `decode_kernel` block carries the detail).
      "decode_kernel_vs_xla":
          kernel_block["decode_kernel_vs_xla"] if kernel_block else None,
      "decode_kernel": kernel_block,
      "by_prefix": {str(t): per_t[t] for t in SESSION_PREFIX_LENGTHS},
      "buckets": engine.buckets,
      "max_sessions": SESSION_MAX_SESSIONS,
      "engine_compiles": engine.compile_count,
      "cache_loads": engine.cache_loads,
      "warmup_ms": (round(engine.warmup_ms, 2)
                    if engine.warmup_ms is not None else None),
      "session_cache_bytes": engine.cache_bytes,
      "stage_breakdown": stage_block,
      "churn": churn_block,
      "device_kind": device.device_kind,
      "platform": device.platform,
      "host_load": _host_load_block(),
      "graftscope": _graftscope_block(),
  }
  print(json.dumps(headline))
  _write_runlog(headline, platform=device.platform,
                device_kind=device.device_kind,
                compile_records=engine.compile_records)


SERVE_CONCURRENCY = 8
SERVE_MAX_BATCH = 8
SERVE_SWEEP = (1, 2, 4, 8)
# Recorded for this exact config on this host (round 6; host-load noise
# swings this VM +-20%, PERFORMANCE.md round 2): batched QPS at
# concurrency 8 through MicroBatcher + BucketedEngine over the CPU smoke
# critic. Like cpu_anchor below, vs_baseline ~= 1.0 reads as "no serving
# regression vs the recorded baseline", nothing more.
SERVE_CPU_ANCHOR = 1700.0


def serve_main(requests_per_thread: int = 150) -> None:
  """Closed-loop serve bench: ONE JSON headline line (CPU smoke path).

  Measures the graftserve stack end to end over the QT-Opt flagship
  predictor (the CPU smoke critic — `flagship.make_flagship_model`
  degrades honestly off-TPU): a sequential unbatched-predict baseline,
  then a concurrency sweep through MicroBatcher + BucketedEngine. The
  headline is batched QPS at concurrency 8 under the stable
  `qtopt_serve_qps_cpu_smoke` metric name, with p50/p95/p99 from the
  `serve/request_ms` histogram and a `graftscope-run-v1` record appended
  to runs.jsonl so `graftscope diff` gates serving regressions exactly
  like training ones. In-process on the pinned CPU backend — the serve
  smoke never touches the tunnel (a TPU serve probe is a future window
  item; it must ride the subprocess-probe isolation pattern above).
  """
  backend_lib.pin_cpu()
  backend_lib.assert_cpu_backend()
  import jax

  from tensor2robot_tpu import serving, specs as specs_lib
  from tensor2robot_tpu.predictors import predictors as predictors_lib
  from tensor2robot_tpu.research.qtopt import flagship
  from tensor2robot_tpu.serving import loadgen

  device = jax.devices()[0]
  model = flagship.make_flagship_model(device.platform)
  predictor = predictors_lib.CheckpointPredictor(model=model,
                                                 model_dir="/nonexistent")
  predictor.init_randomly()
  request = dict(specs_lib.make_random_numpy(
      predictor.get_feature_specification(), batch_size=1,
      seed=0).items())
  make_request = lambda i: request  # noqa: E731 - read-only shared dict

  # Unbatched baseline: ONE sequential client against the raw predictor
  # (per-request dispatch — the pre-graftserve serving shape). A warmup
  # call first so its one-time xray compile stays out of the window.
  predictor.predict(request)
  with obs_metrics.isolated():
    unbatched = loadgen.run_load(
        predictor.predict, make_request, concurrency=1,
        requests_per_thread=2 * requests_per_thread)
  print(f"bench-serve: unbatched sequential {unbatched['qps']:.1f} req/s",
        file=sys.stderr)

  engine = serving.BucketedEngine(predictor=predictor,
                                  max_batch_size=SERVE_MAX_BATCH)
  engine.warmup()
  sweep = []
  latency = {}
  batch_stats: dict = {}
  stage_block = None
  with serving.MicroBatcher(backend=engine,
                            max_batch_size=SERVE_MAX_BATCH,
                            max_delay_ms=2.0) as batcher:
    batcher.predict(request)  # settle the worker before timing
    for concurrency in SERVE_SWEEP:
      with obs_metrics.isolated():
        result = loadgen.run_load(batcher.predict, make_request,
                                  concurrency=concurrency,
                                  requests_per_thread=requests_per_thread)
        if concurrency == SERVE_CONCURRENCY:
          latency = loadgen.latency_percentiles()
          # Where the request time went (graftrace stage decomposition:
          # queue_wait/batch_form/dispatch/split sum to ~request_ms;
          # pad/device are informational sub-spans of dispatch).
          stage_block = graftrace.stage_breakdown()
          snap = obs_metrics.snapshot(prefix="serve/")
          batch_stats = {
              "batches": snap.get("counter/serve/batcher/batches"),
              "mean_batch_rows": snap.get("hist/serve/batch_rows/mean"),
              "shed": (snap.get("counter/serve/batcher/shed_queue_full",
                                0.0)
                       + snap.get("counter/serve/batcher/shed_deadline",
                                  0.0)),
              "slo_breaches": snap.get("counter/serve/slo_breaches", 0.0),
              # Nonzero = the warmup cache was bypassed in steady state
              # (engine_compiles alone can't show it: it is warmup-only).
              "exec_fallbacks": snap.get(
                  "counter/serve/engine/exec_fallbacks", 0.0),
          }
      sweep.append({"concurrency": concurrency,
                    "qps": round(result["qps"], 2),
                    "errors": result["errors"]})
      print(f"bench-serve: batched c={concurrency} "
            f"{result['qps']:.1f} req/s", file=sys.stderr)
  batched_qps = sweep[-1]["qps"]
  compiles = engine.compile_count
  headline = {
      "metric": "qtopt_serve_qps_cpu_smoke",
      "value": round(batched_qps, 2),
      "unit": "requests/sec",
      "vs_baseline": round(batched_qps / SERVE_CPU_ANCHOR, 3),
      "concurrency": SERVE_CONCURRENCY,
      "unbatched_qps": round(unbatched["qps"], 2),
      # The acceptance ratio: the dynamic batcher must beat per-request
      # dispatch by >= 2x at concurrency 8 (ISSUE 5 / PERFORMANCE.md
      # "Reading a serve bench").
      "batched_vs_unbatched": round(batched_qps / unbatched["qps"], 3)
      if unbatched["qps"] else None,
      "max_batch_size": SERVE_MAX_BATCH,
      "buckets": engine.buckets,
      "engine_compiles": compiles,
      # Serving cold start (no cache armed here — the serve bench prices
      # the true compile path; the cached cold/warm pair lives in
      # `bench.py --cache`). Diff-gated up-bad like step time.
      "warmup_ms": (round(engine.warmup_ms, 2)
                    if engine.warmup_ms is not None else None),
      "latency_ms": {k: round(v, 3) for k, v in latency.items()},
      "stage_breakdown": stage_block,
      "batcher": batch_stats,
      "sweep": sweep,
      "device_kind": device.device_kind,
      "platform": device.platform,
      "host_load": _host_load_block(),
      "graftscope": _graftscope_block(),
  }
  print(json.dumps(headline))
  _append_serve_runlog(headline, engine.compile_records, device)


def _append_serve_runlog(headline: dict, compile_records, device) -> None:
  """Serve headline → runlog record with per-bucket compile telemetry
  (see `_write_runlog`), so `graftscope diff` gates a serving regression
  with the same direction-aware thresholds as training throughput."""
  _write_runlog(headline, platform=device.platform,
                device_kind=device.device_kind,
                compile_records=compile_records)


FLEET_REPLICAS = 2
FLEET_MAX_BATCH = 8
FLEET_PAIRS = 3
# The emulated per-dispatch device/tunnel wall (see fleet_main's
# docstring for why the CPU smoke must model it): fixed so both A/B arms
# share it exactly and the paired ratio stays load-invariant.
FLEET_DEVICE_WAIT_MS = 12.0
FLEET_RATE_HZ = 1200.0
FLEET_ARRIVALS = 1000
FLEET_CLIENTS = 96
FLEET_ROLLOUT_RATE_HZ = 250.0
FLEET_ROLLOUT_ARRIVALS = 500
# Traced-vs-untraced A/B pairs (ISSUE 18): the per-event ring-append
# cost of graftrace, priced as a paired goodput ratio on the fleet arm
# (stage histograms run in BOTH arms — they are always-on telemetry —
# so the ratio isolates exactly the optional trace-event recording).
FLEET_TRACE_PAIRS = 3  # odd: the median is a real middle pair, not the
                       # upper of two (single pairs swing ±8% with host
                       # load; the clipped-at-zero lower tail would
                       # otherwise bias the even-count median up)
# Recorded for this exact config on this host at first landing
# (ISSUE 12). Like every absolute wall-clock on the 1-core VM it swings
# with load — the load-invariant number is fleet_vs_single_replica
# (paired back-to-back arms). vs_baseline ~= 1.0 reads as "no fleet
# serving regression vs the recorded baseline", nothing more.
FLEET_CPU_ANCHOR = 900.0
# graftwatch (ISSUE 19): the serving-latency SLO the bench fleets carry.
# Deliberately generous (the smoke's queue tails under saturation are
# hundreds of ms on this 1-core host) — a breach of a ONE-SECOND SLO in
# the smoke is a real regression, not wall-clock noise, so the
# slo_budget_burn gate stays quiet on healthy runs and loud on real ones.
FLEET_SLO_MS = 1000.0
# Burn windows shrunk to the smoke's timescale (the production defaults
# are 60 s/300 s; a bench arm lasts ~1 s, which would never fill them).
FLEET_SLO_FAST_WINDOW_S = 1.0
FLEET_SLO_SLOW_WINDOW_S = 4.0
# The smoke's open-loop Poisson rate deliberately oversubscribes the
# duo fleet — ~30% of arrivals shed; shedding here is the backpressure
# mechanism UNDER TEST, not an outage. Budget the shed SLO to that
# intent (vs the 2% production default) so the headline reads healthy
# on a normal run and `slo_budget_burn` gates on CHANGES in shed
# pressure, not on the smoke's designed-in saturation.
FLEET_SLO_SHED_BUDGET = 0.5


class _HotSwapPredictor:
  """Bench-local checkpoint-publish stand-in: `restore()` swaps in new
  params (a deterministic bump) and advances the version, exactly the
  observable contract of a real checkpoint poll — the bench has no
  model_dir, and training one inside the bench window would swamp the
  serving measurement. Everything below the swap (bundle re-bind,
  cached-executable reuse, router steering) is the REAL rollout path;
  tests/test_fleet.py pins the same rollout against real on-disk
  checkpoints."""

  def __init__(self, predictor):
    self._predictor = predictor

  def restore(self) -> bool:
    import jax

    state = self._predictor._state
    bump = lambda t: None if t is None else jax.tree_util.tree_map(  # noqa: E731
        lambda a: a + 0.125, t)
    self._predictor._state = state.replace(
        params=bump(state.params), ema_params=bump(state.ema_params))
    self._predictor._global_step = self._predictor._global_step + 1
    return True

  def __getattr__(self, name):
    return getattr(self._predictor, name)


class _DeviceWaitEngine:
  """Emulates the device/tunnel wall component of a replica dispatch on
  the CPU smoke: real engine predict (real compiled executable, real
  padding/fetch) followed by a fixed sleep standing in for the
  non-host-CPU wall time a production dispatch spends in device
  execution / tunnel transport (~1.5 s/eager op over axon; ms-scale on
  a local chip). On this 1-core VM the pure-CPU arm measures ~1.0x for
  2 replicas by construction (two threads of host work cannot exceed
  one core — measured 0.99x, PERFORMANCE.md "Reading a fleet bench"),
  so the CPU smoke prices what the fleet layer actually adds in
  production: keeping N device pipelines full. Both A/B arms wear the
  SAME wrapper, so the wait cancels out of everything except the
  overlap the router achieves."""

  def __init__(self, engine, wait_ms: float):
    self._engine = engine
    self._wait_ms = wait_ms

  def predict(self, features):
    outputs = self._engine.predict(features)
    if self._wait_ms:
      time.sleep(self._wait_ms / 1e3)
    return outputs

  def __getattr__(self, name):
    return getattr(self._engine, name)


def _make_fleet_bench_replica(index: int, group, name_prefix: str,
                              hot_swap: bool = False) -> _DeviceWaitEngine:
  """The ONE replica factory both fleet arms (`--fleet`) and the chaos
  storm's serving plane (`--chaos`) build on — the storm must measure
  the SAME serving shape the fleet bench prices, so the setup lives in
  one place: flagship critic + randomly-initialized CheckpointPredictor
  committed to the group's lead device behind a BucketedEngine, wearing
  the emulated device wall. `hot_swap` adds the `_HotSwapPredictor`
  wrapper the fleet bench's rollout() leg swaps through."""
  import jax

  from tensor2robot_tpu import serving
  from tensor2robot_tpu.predictors import predictors as predictors_lib
  from tensor2robot_tpu.research.qtopt import flagship

  model = flagship.make_flagship_model(jax.devices()[0].platform)
  predictor = predictors_lib.CheckpointPredictor(model=model,
                                                 model_dir="/nonexistent")
  predictor.init_randomly()  # same seed per replica: identical params
  if group:
    predictor.place_on_device(group[0])
  if hot_swap:
    predictor = _HotSwapPredictor(predictor)
  engine = serving.BucketedEngine(predictor=predictor,
                                  max_batch_size=FLEET_MAX_BATCH,
                                  name=f"{name_prefix}/replica{index}")
  return _DeviceWaitEngine(engine, FLEET_DEVICE_WAIT_MS)


def fleet_main() -> None:
  """Fleet-serving bench: ONE JSON headline line (CPU smoke path).

  THE ISSUE 12 acceptance numbers, measured as paired back-to-back A/B
  arms over the QT-Opt flagship critic on the virtual 8-device mesh
  (XLA_FLAGS host-platform device count, same topology tier-1 tests
  use; `parallel.mesh.replica_device_groups` carves 4 devices per
  replica and each replica's predictor state is committed to its
  group's lead device):

  * single arm — a 1-replica `ServingFleet` (router + one
    MicroBatcher + one BucketedEngine): the pre-fleet serving shape
    plus router overhead, so the ratio prices the fleet's scaling, not
    the router's absence;
  * fleet arm — the 2-replica `ServingFleet` over disjoint device
    groups.

  Both arms serve identical open-loop Poisson traffic
  (`loadgen.run_trace_load` — arrivals admitted on schedule regardless
  of completions, the only load shape that saturates honestly) with an
  identical per-dispatch emulated device wall (`_DeviceWaitEngine`:
  this host has ONE core, so replicating pure-CPU work measures 0.99x
  flat by physics; the production win is overlapping the device/tunnel
  wall across replicas, and the smoke models exactly that component,
  with the real CPU dispatch cost measured and reported beside it).
  `fleet_vs_single_replica` is the pair-median goodput ratio —
  back-to-back pairs with alternating order make it load-invariant on
  this +-4x host (>= 1.5x acceptance floor at 2 replicas).

  Then a ZERO-DOWNTIME ROLLOUT window: continuous open-loop load at a
  rate one replica can absorb while `fleet.rollout()` canaries and
  rolls both replicas (`restore()` under cached executables). The
  pinned contract — 0 failed requests, 0 fresh compiles in the window
  — lands in the headline's `rollout` block and is diff-gated
  (`fleet_rollout_shed` up-bad at 0 tolerance). Ladder economics ride
  along: the traffic-derived bucket ladder vs the fixed one over the
  window's observed request sizes (`ladder_ab`).
  """
  # The virtual 8-device mesh, BEFORE any backend touch (env must be
  # set pre-initialization; tests/conftest.py uses the same flag).
  flags = os.environ.get("XLA_FLAGS", "")
  if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
  backend_lib.pin_cpu()
  backend_lib.assert_cpu_backend()
  import threading

  import jax

  from tensor2robot_tpu import serving, specs as specs_lib
  from tensor2robot_tpu.parallel import mesh as mesh_lib
  from tensor2robot_tpu.serving import engine as engine_lib
  from tensor2robot_tpu.serving import loadgen

  devices = jax.devices()
  device = devices[0]  # headline record's device_kind/platform
  groups = mesh_lib.replica_device_groups(FLEET_REPLICAS, devices)

  def make_replica(index: int, group) -> _DeviceWaitEngine:
    return _make_fleet_bench_replica(index, group, "serve/fleet",
                                     hot_swap=True)

  print(f"bench-fleet: warming 1-replica + {FLEET_REPLICAS}-replica "
        "fleets (shared bucket ladder)", file=sys.stderr)
  single = serving.ServingFleet(
      replica_factory=lambda i, d: make_replica(i, groups[0]),
      num_replicas=1, max_batch_size=FLEET_MAX_BATCH, max_delay_ms=2.0,
      max_queue=32, warmup=True, latency_slo_ms=FLEET_SLO_MS)
  duo = serving.ServingFleet(
      replica_factory=lambda i, d: make_replica(i, groups[i]),
      num_replicas=FLEET_REPLICAS, max_batch_size=FLEET_MAX_BATCH,
      max_delay_ms=2.0, max_queue=32, warmup=True,
      latency_slo_ms=FLEET_SLO_MS)
  try:
    request = dict(specs_lib.make_random_numpy(
        single.replica(0).get_feature_specification(), batch_size=1,
        seed=0).items())
    make_request = lambda i: request  # noqa: E731 - read-only shared dict

    # The honest decomposition: the real CPU cost of one batched
    # dispatch on this host, measured on the UNWRAPPED engine, so the
    # emulated device wall is always readable against it.
    probe_batch = dict(specs_lib.make_random_numpy(
        single.replica(0).get_feature_specification(),
        batch_size=FLEET_MAX_BATCH, seed=1).items())
    inner_engine = single.replica(0)._engine
    inner_engine.predict(probe_batch)  # settle
    t0 = time.perf_counter()
    for _ in range(10):
      inner_engine.predict(probe_batch)
    dispatch_cpu_ms = (time.perf_counter() - t0) * 1e2

    def run_arm(fleet, seed: int) -> dict:
      with obs_metrics.isolated() as registry:
        result = loadgen.run_trace_load(
            predict=fleet.predict, make_request=make_request,
            num_arrivals=FLEET_ARRIVALS, rate_hz=FLEET_RATE_HZ,
            profile="poisson", seed=seed,
            max_client_threads=FLEET_CLIENTS)
        result["request_rows"] = engine_lib.observed_request_rows()
        snap = registry.snapshot(prefix="serve/")
      result["exec_fallbacks"] = snap.get(
          "counter/serve/engine/exec_fallbacks", 0.0)
      result["shed"] = sum(count for name, count in result["errors"].items()
                           if "Shed" in name)
      return result

    compiles_after_warmup = [c for c in single.compile_counts()
                             + duo.compile_counts() if c is not None]
    pairs = []
    observed_rows: list = []
    exec_fallbacks = 0.0
    for pair in range(FLEET_PAIRS):
      # Alternate order inside each back-to-back pair so slow host
      # phases hit both arms evenly (the data-bench pairing design).
      if pair % 2 == 0:
        s_res = run_arm(single, seed=pair)
        d_res = run_arm(duo, seed=pair)
      else:
        d_res = run_arm(duo, seed=pair)
        s_res = run_arm(single, seed=pair)
      observed_rows.extend(d_res["request_rows"])
      s_qps = s_res["ok_requests"] / s_res["wall_sec"]
      d_qps = d_res["ok_requests"] / d_res["wall_sec"]
      pairs.append({
          "single_qps": round(s_qps, 1), "fleet_qps": round(d_qps, 1),
          "ratio": round(d_qps / s_qps if s_qps else float("inf"), 3),
          "single_shed": s_res["shed"], "fleet_shed": d_res["shed"],
          "start_lag_ms_p95": round(d_res["start_lag_ms_p95"], 1),
      })
      print(f"bench-fleet: pair {pair}: single {s_qps:.0f} req/s, "
            f"fleet {d_qps:.0f} req/s ({pairs[-1]['ratio']:.2f}x)",
            file=sys.stderr)
      exec_fallbacks += s_res["exec_fallbacks"] + d_res["exec_fallbacks"]
    ratio = _median([p["ratio"] for p in pairs])
    fleet_qps = _median([p["fleet_qps"] for p in pairs])
    single_qps = _median([p["single_qps"] for p in pairs])

    # Tracing-overhead A/B (acceptance: <= 3% on the CPU smoke;
    # diff-gated up-bad as trace_overhead_ratio): back-to-back duo arms
    # with the trace ring recording vs not, alternating order. The
    # traced arm also yields the headline stage decomposition and
    # serve_queue_wait_p99_ms (both from its isolated metrics window).
    tracer = obs_trace.get_tracer()
    trace_pairs = []
    stage_block = None
    queue_wait_p99 = None

    def run_overhead_arm(traced: bool, seed: int) -> float:
      nonlocal stage_block, queue_wait_p99
      tracer.clear()
      (obs_trace.enable if traced else obs_trace.disable)()
      try:
        with obs_metrics.isolated():
          res = loadgen.run_trace_load(
              predict=duo.predict, make_request=make_request,
              num_arrivals=FLEET_ARRIVALS, rate_hz=FLEET_RATE_HZ,
              profile="poisson", seed=seed,
              max_client_threads=FLEET_CLIENTS)
          if traced:
            stage_block = graftrace.stage_breakdown()
            qw = (stage_block or {}).get("stages", {}).get("queue_wait")
            if qw is not None:
              queue_wait_p99 = qw["p99_ms"]
        return res["ok_requests"] / res["wall_sec"]
      finally:
        obs_trace.disable()
        tracer.clear()

    for pair in range(FLEET_TRACE_PAIRS):
      order = (True, False) if pair % 2 == 0 else (False, True)
      qps_by_arm = {}
      for traced in order:
        qps_by_arm[traced] = run_overhead_arm(traced, seed=100 + pair)
      trace_pairs.append({
          "traced_qps": round(qps_by_arm[True], 1),
          "untraced_qps": round(qps_by_arm[False], 1),
          "overhead": round(
              max(0.0, 1.0 - (qps_by_arm[True] / qps_by_arm[False]
                              if qps_by_arm[False] else 1.0)), 4),
      })
      print(f"bench-fleet: trace pair {pair}: traced "
            f"{qps_by_arm[True]:.0f} req/s, untraced "
            f"{qps_by_arm[False]:.0f} req/s "
            f"(overhead {trace_pairs[-1]['overhead']:.3f})",
            file=sys.stderr)
    trace_overhead = _median([p["overhead"] for p in trace_pairs])

    # Zero-downtime rollout window: continuous open-loop load at a rate
    # ONE replica can absorb (the pin is no failures while capacity is
    # halved replica-by-replica), rollout mid-window.
    window_results: list = []

    def window_load() -> None:
      window_results.append(loadgen.run_trace_load(
          predict=duo.predict, make_request=make_request,
          num_arrivals=FLEET_ROLLOUT_ARRIVALS,
          rate_hz=FLEET_ROLLOUT_RATE_HZ, profile="poisson", seed=97,
          max_client_threads=32))

    loader = threading.Thread(target=window_load, name="fleet-rollout-load")
    loader.start()
    time.sleep(0.4)  # window established before the canary swap
    report = duo.rollout(probe_request=request)
    loader.join()
    window = window_results[0]
    window_failed = int(sum(window["errors"].values()))
    rollout_block = {
        "swapped": report["swapped"],
        "canary_index": report.get("canary_index"),
        "aborted": report["aborted"],
        "parity_ok": report["parity_ok"],
        "fresh_compiles": report["fresh_compiles"],
        "probe_ms": [round(e["probe_ms"], 2) for e in report["replicas"]
                     if e.get("probe_ms") is not None],
        "window_requests": window["arrivals"],
        # THE pinned contract, diff-gated via fleet_rollout_shed:
        # every error in the window (sheds included) counts — a
        # rollout must be invisible to traffic.
        "window_shed": window_failed,
        "window_qps": round(window["qps"], 1),
    }
    print(f"bench-fleet: rollout swapped {report['swapped']}/"
          f"{FLEET_REPLICAS}, window {window['arrivals']} requests, "
          f"{window_failed} failed/shed", file=sys.stderr)

    # Traffic-derived ladder economics over the observed request sizes
    # (fixed doubling ladder = fallback + A/B baseline).
    derived = engine_lib.traffic_bucket_ladder(observed_rows,
                                               FLEET_MAX_BATCH)
    fixed = engine_lib.bucket_ladder(FLEET_MAX_BATCH)
    ladder_ab = {
        "fixed": fixed,
        "derived": derived,
        "fixed_stats": engine_lib.ladder_padding_stats(observed_rows,
                                                       fixed),
        "derived_stats": engine_lib.ladder_padding_stats(observed_rows,
                                                         derived),
    }

    # graftwatch (ISSUE 19): one dedicated SLO-evaluation window over
    # the fleet arm — the stock serving objectives run through the
    # multi-window burn-rate engine while open-loop load flows (the
    # engine samples the live registry every 100 ms, exactly how the
    # serving loop consumes it), then a point-in-time judgment of the
    # window's final snapshot. `slo_budget_burn` (worst fast-window
    # burn) and `fleet_utilization` (ledger busy / wall x devices) are
    # the diff-gated scalars (up-bad / down-bad in
    # obs.runlog.DEFAULT_THRESHOLDS).
    from tensor2robot_tpu.obs import slo as slo_lib
    slo_specs = slo_lib.default_serving_slos(
        shed_budget=FLEET_SLO_SHED_BUDGET,
        fast_window_s=FLEET_SLO_FAST_WINDOW_S,
        slow_window_s=FLEET_SLO_SLOW_WINDOW_S)
    slo_engine = slo_lib.SloEngine(slo_specs)
    with obs_metrics.isolated() as slo_registry:
      slo_window: list = []

      def slo_load() -> None:
        slo_window.append(loadgen.run_trace_load(
            predict=duo.predict, make_request=make_request,
            num_arrivals=FLEET_ARRIVALS, rate_hz=FLEET_RATE_HZ,
            profile="poisson", seed=211,
            max_client_threads=FLEET_CLIENTS))

      slo_loader = threading.Thread(target=slo_load,
                                    name="fleet-slo-load")
      slo_loader.start()
      while slo_loader.is_alive():
        slo_engine.observe(slo_registry.snapshot(prefix="serve/"),
                           now=time.monotonic())
        time.sleep(0.1)
      slo_loader.join()
      slo_engine.observe(slo_registry.snapshot(prefix="serve/"),
                         now=time.monotonic())
      slo_point = slo_lib.evaluate_snapshot(
          slo_specs, slo_registry.snapshot(prefix="serve/"))
    slo_block = {
        "specs": [spec.describe() for spec in slo_specs],
        "state": slo_engine.state(),
        "point": slo_point,
        "window_requests": slo_window[0]["arrivals"],
        "latency_slo_ms": FLEET_SLO_MS,
        "healthy": slo_engine.healthy()
                   and all(s["ok"] for s in slo_point.values()),
    }
    util_block = duo.utilization_summary()
    print(f"bench-fleet: slo window {slo_window[0]['arrivals']} "
          f"requests, worst burn {slo_engine.worst_burn():.2f}x, "
          f"fleet utilization {util_block['utilization']:.3f} "
          f"(busy {util_block['device_seconds_busy']:.2f}s over "
          f"{util_block['devices']} device(s))", file=sys.stderr)

    compiles_after_all = [c for c in single.compile_counts()
                          + duo.compile_counts() if c is not None]
    headline = {
        "metric": "qtopt_fleet_qps_cpu_smoke",
        "value": fleet_qps,
        "unit": "requests/sec",
        "vs_baseline": round(fleet_qps / FLEET_CPU_ANCHOR, 3),
        # The acceptance ratio (load-invariant, diff-gated down-bad):
        # 2-replica fleet vs 1-replica goodput under identical
        # open-loop load, pair-median.
        "fleet_vs_single_replica": ratio,
        "replicas": FLEET_REPLICAS,
        "single_replica_qps": single_qps,
        "pairs": pairs,
        "emulated_device_wait_ms": FLEET_DEVICE_WAIT_MS,
        "replica_dispatch_cpu_ms": round(dispatch_cpu_ms, 2),
        # ISSUE 18 observability economics: where the request time goes
        # (graftrace stage decomposition, summed stages reconciling
        # against serve/request_ms within 5%), what the worst queueing
        # tail costs (diff-gated up-bad), and what recording it all
        # costs (paired A/B, <= 3% acceptance, diff-gated up-bad).
        "stage_breakdown": stage_block,
        "serve_queue_wait_p99_ms": queue_wait_p99,
        "trace_overhead_ratio": trace_overhead,
        "trace_overhead_pairs": trace_pairs,
        "open_loop": {"profile": "poisson", "rate_hz": FLEET_RATE_HZ,
                      "arrivals_per_arm": FLEET_ARRIVALS},
        "buckets": single.replica(0).buckets,
        "device_groups": [len(g) for g in groups],
        # Zero recompiles after warmup across both replicas AND the
        # rollout (compile counters pinned; exec_fallbacks 0 means no
        # dispatch bypassed the warmed cache either).
        "engine_compiles": compiles_after_all,
        "zero_recompiles_after_warmup":
            compiles_after_all == compiles_after_warmup,
        "exec_fallbacks": exec_fallbacks,
        "rollout": rollout_block,
        "ladder_ab": ladder_ab,
        # ISSUE 19 graftwatch: SLO + device-time economics. The two
        # scalars are the diff-gated rows; the blocks carry the full
        # burn/ledger state for `graftscope history`/`watch` readers.
        "slo": slo_block,
        "slo_budget_burn": round(slo_engine.worst_burn(), 4),
        "utilization": util_block,
        "fleet_utilization": round(util_block["utilization"], 4),
        "device_kind": device.device_kind,
        "platform": device.platform,
        "host_load": _host_load_block(),
        "graftscope": _graftscope_block(),
    }
    print(json.dumps(headline))
    compile_records = []
    for fleet in (single, duo):
      for index in range(fleet.num_replicas):
        compile_records.extend(fleet.replica(index).compile_records)
    _write_runlog(headline, platform=device.platform,
                  device_kind=device.device_kind,
                  compile_records=compile_records)
  finally:
    single.close()
    duo.close()


# Chaos bench config (bench.py --chaos): one seed drives every fault
# decision, so a chaos run is reproducible fault-for-fault.
CHAOS_SEED = 13
CHAOS_TRAIN_STEPS = 40
CHAOS_CKPT_EVERY = 10
# Log-fetch arrival index of the injected NaN (log every step): fires
# at step 25 — AFTER the step-20 save (which ckpt.bitflip corrupts), so
# the rewind must detect the corruption and fall back to step 10.
CHAOS_NONFINITE_AT = 24
CHAOS_DATA_BATCHES = 40
CHAOS_DATA_BATCH = 32
CHAOS_ARRIVALS = 400
CHAOS_RATE_HZ = 600.0
CHAOS_CLIENTS = 64
# Odd on purpose: `_median` is the upper median, and an even pair
# count would let the gated down-bad goodput ratio report the BETTER
# of two pairs (hiding a one-pair recovery regression).
CHAOS_PAIRS = 3


def chaos_main() -> None:
  """graftguard chaos bench: ONE JSON headline line (CPU smoke path).

  A SEEDED fault storm over all three planes, measuring that every
  injected fault class RECOVERS (the ISSUE 13 acceptance) and what the
  recovery costs:

  * **data plane** — a record pipeline under injected corrupt-record
    bytes, a preprocess exception and a mid-epoch source I/O error,
    with the graftguard skip quota armed: the pass must complete with
    the faults counted-and-skipped, zero raises.
  * **train plane** — a mock-model trainer with a NaN loss injected at
    step 25 and the step-20 checkpoint bit-flipped at save: sentinel
    fatal incident -> flight-recorder bundle -> divergence REWIND,
    which must detect the corrupt step-20 checkpoint (manifest
    checksum), quarantine it, and restore step 10. The run must finish
    all steps, and a CLEAN run resumed from the same verified
    checkpoint must reach NUMERICAL PARITY with the rewound run's
    final params (the rewind restores training, not just liveness —
    both consume the deterministic mock stream from the top).
  * **serving plane** — paired clean/faulted open-loop arms over a
    live 2-replica fleet (real engines, emulated device wall, the
    --fleet design): the faulted arm injects a 6-arrival dispatch
    failure burst on replica 1 (6, not unhealthy_after=3: a success
    completing between two failure recordings legitimately resets the
    streak) plus latency spikes; the
    fleet must FAIL OVER every faulted request (zero client-visible
    failures), evict, and the probation loop must AUTO-READMIT.

  Headline gates (`scripts/chaos_bench.sh`, diff-gated like every
  bench family): `chaos_goodput_ratio` — pair-median faulted/clean
  serving goodput (down-bad; load-invariant by pairing) — and
  `chaos_recovery_ms` — the worst per-fault-class recovery wall time
  (probation readmit, divergence rewind; up-bad, loose wall-clock
  band). `all_recovered` false exits 3: an unrecovered fault class is
  an acceptance failure, not a diff question.
  """
  flags = os.environ.get("XLA_FLAGS", "")
  if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
  backend_lib.pin_cpu()
  backend_lib.assert_cpu_backend()
  import shutil
  import threading

  import numpy as np

  from tensor2robot_tpu import checkpoints as checkpoints_lib
  from tensor2robot_tpu import train_eval
  from tensor2robot_tpu.data import pipeline as pipeline_lib
  from tensor2robot_tpu.obs import faultlab
  from tensor2robot_tpu.utils import mocks

  recovered: dict = {}
  mttr_ms: dict = {}

  # ---- data plane -------------------------------------------------------
  print("bench-chaos: data plane (corrupt records under quota)",
        file=sys.stderr)
  data_root = tempfile.mkdtemp(prefix="chaos-data-")
  try:
    patterns, parse_fn = _make_data_bench_dataset(data_root)
    data_plan = faultlab.FaultPlan([
        faultlab.FaultSpec(point=faultlab.DATA_CORRUPT_RECORD, every=10,
                           count=3),
        faultlab.FaultSpec(point=faultlab.DATA_PREPROCESS, at=(15,),
                           count=1),
        faultlab.FaultSpec(point=faultlab.DATA_RECORD_IO, at=(30,),
                           count=1),
    ], seed=CHAOS_SEED)
    pipe = pipeline_lib.RecordBatchPipeline(
        patterns, parse_fn, batch_size=CHAOS_DATA_BATCH, mode="train",
        shuffle_buffer_size=128, seed=CHAOS_SEED, prefetch_size=2,
        num_parallel_parses=2,
        max_corrupt_records=16 * CHAOS_DATA_BATCH)
    with data_plan.activated(), obs_metrics.isolated() as registry:
      stream = iter(pipe)
      consumed = 0
      t0 = time.perf_counter()
      for _ in range(CHAOS_DATA_BATCHES):
        next(stream)
        consumed += 1
      data_wall_s = time.perf_counter() - t0
      if hasattr(stream, "close"):
        stream.close()
      snap = registry.snapshot(prefix="data/")
    data_block = {
        "batches_consumed": consumed,
        "wall_sec": round(data_wall_s, 3),
        "records_skipped": snap.get("counter/data/corrupt_records_skipped",
                                    0.0),
        "batches_skipped": snap.get("counter/data/corrupt_batches_skipped",
                                    0.0),
        "source_io_errors": snap.get("counter/data/source_io_errors", 0.0),
        "injected": data_plan.summary(),
    }
    recovered["data"] = (consumed == CHAOS_DATA_BATCHES
                         and data_block["batches_skipped"] > 0
                         and data_block["source_io_errors"] > 0)
  finally:
    shutil.rmtree(data_root, ignore_errors=True)
  print(f"bench-chaos: data plane consumed {data_block['batches_consumed']}"
        f" batches, skipped {data_block['records_skipped']:.0f} records, "
        f"{data_block['source_io_errors']:.0f} source I/O error(s)",
        file=sys.stderr)

  # ---- train plane ------------------------------------------------------
  print("bench-chaos: train plane (NaN divergence + bit-flipped "
        "checkpoint -> rewind)", file=sys.stderr)
  train_root = tempfile.mkdtemp(prefix="chaos-train-")
  try:
    dir_chaos = os.path.join(train_root, "chaos")
    dir_clean = os.path.join(train_root, "clean")
    trainer_kwargs = dict(
        mode="train", max_train_steps=CHAOS_TRAIN_STEPS,
        checkpoint_every_n_steps=CHAOS_CKPT_EVERY,
        log_every_n_steps=1, executable_cache_dir=None)
    train_plan = faultlab.FaultPlan([
        faultlab.FaultSpec(point=faultlab.TRAIN_NONFINITE,
                           at=(CHAOS_NONFINITE_AT,), count=1),
        faultlab.FaultSpec(point=faultlab.CKPT_BITFLIP, at=(1,), count=1),
    ], seed=CHAOS_SEED)
    with train_plan.activated():
      train_eval.train_eval_model(
          model=mocks.MockT2RModel(device_type="cpu"),
          model_dir=dir_chaos,
          input_generator_train=mocks.MockInputGenerator(batch_size=8),
          **trainer_kwargs)
    from tensor2robot_tpu.obs import runlog as runlog_lib

    chaos_rec = [r for r in runlog_lib.load_records(
        os.path.join(dir_chaos, "runs.jsonl"))
        if r.get("kind") == "train"][-1]
    guard = (chaos_rec.get("extra") or {}).get("graftguard") or {}
    rewinds = int(guard.get("rewinds", 0))
    rewind_steps = guard.get("rewind_steps") or []
    train_snapshot = obs_metrics.snapshot(prefix="train/")
    rewind_ms = train_snapshot.get("hist/train/rewind_ms/max")
    quarantine_dir = os.path.join(dir_chaos, "checkpoints",
                                  checkpoints_lib.QUARANTINE_DIRNAME)
    quarantined = (sorted(os.listdir(quarantine_dir))
                   if os.path.isdir(quarantine_dir) else [])

    # Numerical-parity pin: a clean run resumed from the SAME verified
    # checkpoint the rewind restored must reach the same final params.
    parity_ok = None
    param_max_abs_diff = None
    if rewinds and rewind_steps:
      target = int(rewind_steps[0])
      os.makedirs(os.path.join(dir_clean, "checkpoints"), exist_ok=True)
      shutil.copytree(
          os.path.join(dir_chaos, "checkpoints", str(target)),
          os.path.join(dir_clean, "checkpoints", str(target)))
      train_eval.train_eval_model(
          model=mocks.MockT2RModel(device_type="cpu"),
          model_dir=dir_clean,
          input_generator_train=mocks.MockInputGenerator(batch_size=8),
          **trainer_kwargs)

      def _final_params(model_dir):
        with checkpoints_lib.CheckpointManager(
            os.path.join(model_dir, "checkpoints")) as manager:
          restored = manager.restore()
          assert manager.last_restored_step == CHAOS_TRAIN_STEPS, (
              manager.last_restored_step)
          return restored["params"] if "params" in restored else restored

      import jax

      params_chaos = _final_params(dir_chaos)
      params_clean = _final_params(dir_clean)
      diffs = jax.tree_util.tree_map(
          lambda a, b: float(np.max(np.abs(np.asarray(a, np.float64)
                                           - np.asarray(b, np.float64)))),
          params_chaos, params_clean)
      param_max_abs_diff = max(jax.tree_util.tree_leaves(diffs))
      parity_ok = param_max_abs_diff <= 1e-6
    train_block = {
        "steps": CHAOS_TRAIN_STEPS,
        "rewinds": rewinds,
        "rewind_steps": rewind_steps,
        "rewind_ms": rewind_ms,
        "quarantined_steps": quarantined,
        "parity_ok": parity_ok,
        "param_max_abs_diff": param_max_abs_diff,
        "injected": train_plan.summary(),
        "final_step": (chaos_rec.get("extra") or {}).get("final_step"),
    }
    recovered["train"] = bool(
        rewinds == 1 and quarantined and parity_ok
        and train_block["final_step"] == CHAOS_TRAIN_STEPS)
    if rewind_ms is not None:
      mttr_ms["divergence_rewind"] = round(float(rewind_ms), 1)
  finally:
    shutil.rmtree(train_root, ignore_errors=True)
  print(f"bench-chaos: train plane rewinds={train_block['rewinds']} "
        f"(targets {train_block['rewind_steps']}), quarantined "
        f"{train_block['quarantined_steps']}, parity_ok="
        f"{train_block['parity_ok']}", file=sys.stderr)

  # ---- serving plane ----------------------------------------------------
  print("bench-chaos: serving plane (dispatch-failure burst -> eviction "
        "-> probation readmit)", file=sys.stderr)
  import jax

  from tensor2robot_tpu import serving, specs as specs_lib
  from tensor2robot_tpu.parallel import mesh as mesh_lib
  from tensor2robot_tpu.serving import loadgen

  devices = jax.devices()
  device = devices[0]  # headline record's device_kind/platform
  groups = mesh_lib.replica_device_groups(FLEET_REPLICAS, devices)

  request_holder: list = []
  fleet = serving.ServingFleet(
      replica_factory=lambda i, d: _make_fleet_bench_replica(
          i, groups[i], "serve/chaos"),
      num_replicas=FLEET_REPLICAS, max_batch_size=FLEET_MAX_BATCH,
      max_delay_ms=2.0, max_queue=32, warmup=True,
      probation_probe=lambda: request_holder[0])
  try:
    request = dict(specs_lib.make_random_numpy(
        fleet.replica(0).get_feature_specification(), batch_size=1,
        seed=0).items())
    request_holder.append(request)
    make_request = lambda i: request  # noqa: E731 - read-only shared dict

    def run_arm(faulted: bool, seed: int) -> dict:
      plan = None
      if faulted:
        plan = faultlab.activate(faultlab.FaultPlan([
            # A burst of consecutive dispatch failures on replica 1
            # (>= the default unhealthy_after=3; 6 because a success
            # COMPLETING between two failure recordings under
            # concurrent load legitimately resets the streak) =>
            # eviction mid-window; failover must absorb every one.
            # Latency spikes ride along.
            faultlab.FaultSpec(point=faultlab.SERVE_DISPATCH, key=1,
                               at=tuple(range(40, 46)), count=6),
            faultlab.FaultSpec(point=faultlab.SERVE_LATENCY, every=50,
                               arg=30.0),
        ], seed=CHAOS_SEED + seed))
      try:
        result = loadgen.run_trace_load(
            predict=fleet.predict, make_request=make_request,
            num_arrivals=CHAOS_ARRIVALS, rate_hz=CHAOS_RATE_HZ,
            profile="poisson", seed=seed,
            max_client_threads=CHAOS_CLIENTS)
      finally:
        if plan is not None:
          faultlab.deactivate()
      # Sheds are ADMISSION refusals (bounded queues doing their job
      # under injected latency spikes — backpressure, not a recovery
      # failure); everything else is a client-visible failure the
      # failover machinery should have absorbed.
      result["shed"] = int(sum(count for name, count
                               in result["errors"].items()
                               if "Shed" in name))
      result["failed"] = int(sum(result["errors"].values())
                             ) - result["shed"]
      result["injected"] = plan.summary() if plan is not None else None
      # Self-heal barrier between arms: the probation loop must have
      # readmitted every evicted replica before the next arm measures.
      deadline = time.monotonic() + 10.0
      while (len(fleet.healthy_replicas()) < FLEET_REPLICAS
             and time.monotonic() < deadline):
        time.sleep(0.01)
      result["healthy_after"] = len(fleet.healthy_replicas())
      return result

    pairs = []
    serve_injected: list = []
    for pair in range(CHAOS_PAIRS):
      if pair % 2 == 0:
        clean = run_arm(False, seed=pair)
        faulted = run_arm(True, seed=pair)
      else:
        faulted = run_arm(True, seed=pair)
        clean = run_arm(False, seed=pair)
      serve_injected.append(faulted["injected"])
      clean_qps = clean["ok_requests"] / clean["wall_sec"]
      faulted_qps = faulted["ok_requests"] / faulted["wall_sec"]
      pairs.append({
          "clean_qps": round(clean_qps, 1),
          "faulted_qps": round(faulted_qps, 1),
          "ratio": round(faulted_qps / clean_qps if clean_qps
                         else float("inf"), 3),
          "faulted_failed": faulted["failed"],
          "faulted_shed": faulted["shed"],
          "clean_failed": clean["failed"],
          "healthy_after": faulted["healthy_after"],
      })
      print(f"bench-chaos: pair {pair}: clean {clean_qps:.0f} req/s, "
            f"faulted {faulted_qps:.0f} req/s "
            f"({pairs[-1]['ratio']:.2f}x), faulted_failed="
            f"{faulted['failed']}, healthy_after="
            f"{faulted['healthy_after']}", file=sys.stderr)
    goodput_ratio = _median([p["ratio"] for p in pairs])
    serve_snap = obs_metrics.snapshot(prefix="serve/fleet/")
    readmit_max = serve_snap.get("hist/serve/fleet/readmit_ms/max")
    if readmit_max is not None:
      mttr_ms["replica_unhealthy"] = round(float(readmit_max), 1)
    evictions = serve_snap.get("counter/serve/fleet/unhealthy", 0.0)
    readmits = serve_snap.get("counter/serve/fleet/probation_readmits",
                              0.0)
    serve_block = {
        "pairs": pairs,
        "evictions": evictions,
        "probation_readmits": readmits,
        "probation_probes": serve_snap.get(
            "counter/serve/fleet/probation_probes", 0.0),
        "faulted_failed_total": sum(p["faulted_failed"] for p in pairs),
        "faulted_shed_total": sum(p["faulted_shed"] for p in pairs),
        "injected": serve_injected,
        "open_loop": {"profile": "poisson", "rate_hz": CHAOS_RATE_HZ,
                      "arrivals_per_arm": CHAOS_ARRIVALS},
        "emulated_device_wait_ms": FLEET_DEVICE_WAIT_MS,
    }
    # Recovered: the burst evicted at least one replica, every eviction
    # was probation-readmitted, both replicas were healthy at the end
    # of every faulted arm, and no client saw a non-backpressure
    # failure (failover absorbed every injected dispatch fault).
    recovered["serve"] = bool(
        evictions >= 1 and readmits >= evictions
        and all(p["healthy_after"] == FLEET_REPLICAS for p in pairs)
        and serve_block["faulted_failed_total"] == 0)
  finally:
    fleet.close()

  # ---- headline ---------------------------------------------------------
  all_recovered = bool(recovered and all(recovered.values()))
  chaos_recovery_ms = max(mttr_ms.values()) if mttr_ms else None
  headline = {
      "metric": "qtopt_chaos_cpu_smoke",
      "value": goodput_ratio,
      "unit": "faulted/clean goodput ratio",
      "chaos_goodput_ratio": goodput_ratio,
      "chaos_recovery_ms": chaos_recovery_ms,
      "all_recovered": all_recovered,
      "recovered_by_plane": recovered,
      "mttr_ms": mttr_ms,
      "seed": CHAOS_SEED,
      "data": data_block,
      "train": train_block,
      "serve": serve_block,
      "device_kind": device.device_kind,
      "platform": device.platform,
      "host_load": _host_load_block(),
      "graftscope": _graftscope_block(),
  }
  print(json.dumps(headline))
  _write_runlog(headline, platform=device.platform,
                device_kind=device.device_kind)
  if not all_recovered:
    print("bench-chaos: ACCEPTANCE FAILURE — not every fault class "
          f"recovered: {recovered}", file=sys.stderr)
    sys.exit(3)


# graftloop chaos bench config (bench.py --loop): one seed drives every
# fault decision, so a loop storm is reproducible fault-for-fault.
LOOP_SEED = 17
LOOP_ACTORS = 2
LOOP_REPLICAS = 2
LOOP_STEPS_PER_ROUND = 10
LOOP_ROUNDS = 3
# Log-fetch arrival of the injected NaN (log every step, arrivals
# accumulate across the learner's rounds): 13 = step 14, round 2 —
# AFTER the round-1 step-10 save, so the divergence rewind has a
# verified target while collection keeps serving the published v10.
LOOP_NONFINITE_AT = 13
# Save arrival of the torn checkpoint: 2 = the round-3 step-30 save —
# the manifest is written from the good bytes then the step is torn, so
# the publisher's verification walk must REFUSE it (the fleet keeps
# serving step 20; nothing unverified ever reaches an actor).
LOOP_TORN_SAVE_AT = 2
# ISSUE 14 acceptance floor: chaos-arm collection goodput vs clean.
LOOP_GOODPUT_FLOOR = 0.8
LOOP_WALL_TIMEOUT_S = 420.0


def loop_main() -> None:
  """graftloop chaos bench: ONE JSON headline line (CPU smoke path).

  Paired clean/chaos arms of the WHOLE always-on loop — an actor pool
  collecting pose-task episodes through a 2-replica ServingFleet into
  the bounded replay sink, the learner training in rounds and
  publishing verified checkpoints that hot-swap into the fleet — with
  the chaos arm running a SEEDED four-fault storm (actor kill, learner
  NaN divergence, torn published checkpoint, replica-eviction dispatch
  burst) that must recover with ZERO operator intervention:

  * collection goodput (episodes/s) >= LOOP_GOODPUT_FLOOR x the clean
    arm (`loop_goodput_ratio`, the headline value);
  * NO unverified checkpoint ever served: the served-version audit is
    empty in BOTH arms and the torn step was explicitly REFUSED
    (publish_rejected >= 1 in the chaos arm, pinned by re-verifying
    the torn step's manifest verdict);
  * the staleness bound held (no action from a policy > K published
    versions behind);
  * the learner reached its training target through the rewind, every
    eviction was probation-readmitted, and no worker escalated to
    FAILED.

  Headline gates (`scripts/loop_bench.sh`): `loop_goodput_ratio`
  (down-bad) and `publish_to_serve_ms` (deploy latency, up-bad loose
  wall-clock band); `publish_to_first_action_ms` rides along in the
  headline. `all_recovered` false exits 3 — an unrecovered fault class
  is an acceptance failure, not a diff question.
  """
  flags = os.environ.get("XLA_FLAGS", "")
  if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
  backend_lib.pin_cpu()
  backend_lib.assert_cpu_backend()
  import shutil

  import jax

  from tensor2robot_tpu import checkpoints as checkpoints_lib
  from tensor2robot_tpu.envs import pose_env
  from tensor2robot_tpu.loop import loop as loop_lib
  from tensor2robot_tpu.obs import faultlab
  from tensor2robot_tpu.policies import policies as policies_lib
  from tensor2robot_tpu.research.pose_env import models as pose_models

  device = jax.devices()[0]
  total_steps = LOOP_STEPS_PER_ROUND * LOOP_ROUNDS

  def run_arm(faulted: bool, root: str) -> dict:
    plan = None
    if faulted:
      plan = faultlab.FaultPlan([
          # Actor 0 dies mid-collection: the supervisor's restart path.
          faultlab.FaultSpec(point=faultlab.LOOP_ACTOR_CRASH, key=0,
                             at=(5,), count=1),
          # NaN divergence in learner round 2: graftguard rewind to the
          # published step-10 checkpoint — collection must NOT stop.
          faultlab.FaultSpec(point=faultlab.TRAIN_NONFINITE,
                             at=(LOOP_NONFINITE_AT,), count=1),
          # Torn step-30 save: the publish path must refuse it.
          faultlab.FaultSpec(point=faultlab.CKPT_TORN,
                             at=(LOOP_TORN_SAVE_AT,), count=1),
          # Six consecutive dispatch failures on replica 1 (>= the
          # unhealthy_after=3 streak; 6 because a success completing
          # between two failure recordings legitimately resets it):
          # eviction mid-loop, probation must auto-readmit.
          faultlab.FaultSpec(point=faultlab.SERVE_DISPATCH, key=1,
                             at=tuple(range(40, 46)), count=6),
      ], seed=LOOP_SEED)
    with obs_metrics.isolated() as registry:
      # Arm the graftrace shard exporter into this arm's model_dir:
      # every loop worker (actors, learner, publisher, supervisor)
      # shares this process, so one pid's ring covers the whole loop;
      # the publisher worker flushes periodically and close() drains
      # the tail. The merged timeline is the ISSUE 18 acceptance
      # artifact (episode -> shard -> round -> publish -> first_action
      # as a walkable chain).
      # max_gens: the 5 s publisher flush cadence writes ~12 gens over
      # a bench arm; the production default (8) would prune the early
      # generations that hold round 1's causal spine (episode ->
      # shard -> round -> publish) and the merged chain check would
      # fail on ring rotation, not on a real causality break.
      graftrace.configure(os.path.join(root, "trace"),
                          role="loop-chaos" if faulted else "loop-clean",
                          max_gens=64)
      graft_loop = loop_lib.GraftLoop(
          model_factory=lambda: pose_models.PoseEnvContinuousMCModel(
              device_type="cpu"),
          model_dir=root,
          env_factory=lambda i: pose_env.PoseToyEnv(seed=i),
          policy_factory=lambda fleet: policies_lib.CEMPolicy(
              predictor=fleet, action_size=2, cem_samples=8,
              cem_iterations=2, cem_elites=3, seed=0),
          episode_to_transitions_fn=pose_env.episode_to_transitions,
          num_actors=LOOP_ACTORS, num_replicas=LOOP_REPLICAS,
          max_batch_size=8, train_batch_size=16,
          steps_per_round=LOOP_STEPS_PER_ROUND, num_rounds=LOOP_ROUNDS,
          max_staleness_versions=1, replay_max_bytes=64 << 20,
          episodes_per_shard=8, max_episode_steps=2,
          # Collection pacing (both arms, so the pair stays fair): on
          # this 1-core host an unthrottled warm actor pool starves the
          # learner of the GIL and round 1 never finishes.
          actor_pause_s=0.05, seed=LOOP_SEED)
      if plan is not None:
        faultlab.activate(plan)
      try:
        summary = graft_loop.run(wall_timeout_s=LOOP_WALL_TIMEOUT_S)
      finally:
        if plan is not None:
          faultlab.deactivate()
        graftrace.flush()
        obs_trace.disable()
      snap = registry.snapshot()
    summary["injected"] = plan.summary() if plan is not None else None
    summary["learner_rewinds"] = snap.get(
        "counter/loop/learner_rewinds", 0.0)
    summary["evictions"] = snap.get("counter/serve/fleet/unhealthy", 0.0)
    summary["probation_readmits"] = snap.get(
        "counter/serve/fleet/probation_readmits", 0.0)
    summary["worker_downtime_ms_max"] = snap.get(
        "hist/loop/worker_downtime_ms/max")
    summary["final_checkpoint_step"] = checkpoints_lib.latest_step(
        os.path.join(root, loop_lib.CHECKPOINT_DIRNAME))
    return summary

  loop_root = tempfile.mkdtemp(prefix="loop-bench-")
  try:
    print("bench-loop: clean arm (collect/train/publish, no faults)",
          file=sys.stderr)
    clean = run_arm(False, os.path.join(loop_root, "clean"))
    print(f"bench-loop: clean {clean['episodes']} episodes in "
          f"{clean['wall_sec']}s ({clean['episodes_per_sec']}/s), "
          f"{clean['publishes']} publishes", file=sys.stderr)
    print("bench-loop: chaos arm (actor kill + NaN rewind + torn "
          "publish + replica eviction)", file=sys.stderr)
    chaos = run_arm(True, os.path.join(loop_root, "chaos"))
    print(f"bench-loop: chaos {chaos['episodes']} episodes in "
          f"{chaos['wall_sec']}s ({chaos['episodes_per_sec']}/s), "
          f"{chaos['publishes']} publishes, "
          f"{chaos['publish_rejected']:.0f} rejected, "
          f"{chaos['worker_restarts']:.0f} restarts", file=sys.stderr)

    # The merged clean-arm timeline must carry ONE walkable causal
    # chain from an episode's collect span through its replay shard,
    # the learner round that consumed it, the publish of the trained
    # version, and the first served action of that version — the
    # graftrace acceptance artifact (each hop a parent/links edge, so
    # `graftscope timeline` renders it as Perfetto flow arrows).
    from tensor2robot_tpu.obs import aggregate as aggregate_lib
    merged = aggregate_lib.merge_timeline(
        os.path.join(loop_root, "clean", "trace"))
    events = merged["payload"]["traceEvents"]
    trace_block = {
        "shards": merged["stats"]["shards"],
        "events": merged["stats"]["events"],
        "flow_links": merged["stats"]["flow_links"],
        "episode_chain": aggregate_lib.has_causal_chain(
            events, ("loop/episode", "loop/replay/shard",
                     "loop/learner/round", "loop/publish",
                     "loop/first_action")),
        "publish_chain": aggregate_lib.has_causal_chain(
            events, ("loop/publish", "loop/first_action")),
    }
    print(f"bench-loop: timeline {trace_block['shards']} shards, "
          f"{trace_block['events']} events, "
          f"{trace_block['flow_links']} flow links, episode chain "
          f"{trace_block['episode_chain']}", file=sys.stderr)

    # The torn step must be provably the one the manifest walk refused:
    # its verdict re-checked from disk is False, and it never appears in
    # the served-version audit.
    torn_verdict = checkpoints_lib.verify_step_files(
        os.path.join(loop_root, "chaos", loop_lib.CHECKPOINT_DIRNAME),
        total_steps)
    # A wedged clean arm (zero episodes) must FAIL the goodput gate,
    # not vacuously pass it as ratio=inf (which strict-JSON consumers
    # also choke on): ratio 0.0 trips the down-bad floor loudly.
    goodput_ratio = (chaos["episodes_per_sec"] / clean["episodes_per_sec"]
                     if clean["episodes_per_sec"] > 0 else 0.0)
    recovered = {
        # Supervisor restarted the killed actor; nobody escalated.
        "actor_crash": bool(
            chaos["worker_restarts"] >= 1
            and chaos["worker_escalations"] == 0
            and "failed" not in chaos["worker_states"].values()),
        # The rewind happened AND the learner still reached its target.
        "learner_rewind": bool(
            chaos["learner_rewinds"] >= 1
            and (chaos["final_checkpoint_step"] or 0) >= total_steps),
        # The torn checkpoint was refused, and no unverified version was
        # ever acted on (in either arm — the clean arm pins the audit's
        # baseline).
        "torn_publish": bool(
            chaos["publish_rejected"] >= 1 and torn_verdict is False
            and not chaos["unverified_served"]
            and not clean["unverified_served"]),
        # The dispatch burst evicted, probation readmitted every one.
        "replica_eviction": bool(
            chaos["evictions"] >= 1
            and chaos["probation_readmits"] >= chaos["evictions"]),
        # The staleness bound held under the storm.
        "staleness_bound": bool(chaos["staleness_bound_held"]
                                and clean["staleness_bound_held"]),
        "goodput": bool(goodput_ratio >= LOOP_GOODPUT_FLOOR),
    }
    all_recovered = all(recovered.values())
    headline = {
        "metric": "qtopt_loop_cpu_smoke",
        "value": round(goodput_ratio, 3),
        "unit": "chaos/clean collection goodput ratio",
        "loop_goodput_ratio": round(goodput_ratio, 3),
        "publish_to_serve_ms": chaos["publish_to_serve_ms_max"],
        "publish_to_first_action_ms": chaos[
            "publish_to_first_action_ms_max"],
        "worker_downtime_ms": chaos["worker_downtime_ms_max"],
        "all_recovered": all_recovered,
        "recovered": recovered,
        "goodput_floor": LOOP_GOODPUT_FLOOR,
        # ISSUE 19 graftwatch: the chaos arm's continuous-SLO state
        # (loop staleness + publish-to-serve objectives, evaluated
        # every publisher tick) and the fleet's device-time ledger —
        # the storm must burn no loop budget and the ledger must still
        # reconcile after evictions/readmits.
        "slo": chaos.get("slo"),
        "utilization": chaos.get("utilization"),
        "seed": LOOP_SEED,
        "graftrace": trace_block,
        "clean": clean,
        "chaos": chaos,
        "device_kind": device.device_kind,
        "platform": device.platform,
        "host_load": _host_load_block(),
        "graftscope": _graftscope_block(),
    }
    print(json.dumps(headline))
    _write_runlog(headline, platform=device.platform,
                  device_kind=device.device_kind)
    if not all_recovered:
      print("bench-loop: ACCEPTANCE FAILURE — not every fault class "
            f"recovered: {recovered}", file=sys.stderr)
      sys.exit(3)
  finally:
    shutil.rmtree(loop_root, ignore_errors=True)


def main() -> None:
  if len(sys.argv) >= 2 and sys.argv[1] == "--probe":
    _probe_child_entry(sys.argv[2], sys.argv[3])
    return
  if len(sys.argv) >= 2 and sys.argv[1] == "--forge-child":
    # Measurement arm of `--forge` (exempt from the bench lock: it
    # belongs to the parent bench, like --probe children).
    _forge_child_entry(sys.argv[2], sys.argv[3], sys.argv[4])
    return
  # Single-bench guard, taken BEFORE any measurement (probe children are
  # exempt: they belong to this bench). A failed acquisition latches the
  # concurrent_bench flag the headline's host_load block reports.
  _acquire_bench_lock()
  if len(sys.argv) >= 2 and sys.argv[1] == "--ab-local-compile":
    _ab_local_compile(int(sys.argv[2]) if len(sys.argv) > 2 else BATCH_SIZE)
    return
  if len(sys.argv) >= 2 and sys.argv[1] == "--serve":
    serve_main(int(sys.argv[2]) if len(sys.argv) > 2 else 150)
    return
  if len(sys.argv) >= 2 and sys.argv[1] == "--session":
    session_main()
    return
  if len(sys.argv) >= 2 and sys.argv[1] == "--fleet":
    fleet_main()
    return
  if len(sys.argv) >= 2 and sys.argv[1] == "--chaos":
    chaos_main()
    return
  if len(sys.argv) >= 2 and sys.argv[1] == "--loop":
    loop_main()
    return
  if len(sys.argv) >= 2 and sys.argv[1] == "--data":
    data_main()
    return
  if len(sys.argv) >= 2 and sys.argv[1] == "--smoke":
    smoke_main()
    return
  if len(sys.argv) >= 2 and sys.argv[1] == "--pp":
    pp_main()
    return
  if len(sys.argv) >= 2 and sys.argv[1] == "--cache":
    cache_main(sys.argv[2] if len(sys.argv) > 2 else "cold")
    return
  if len(sys.argv) >= 2 and sys.argv[1] == "--forge":
    forge_main()
    return
  best = None
  if backend_lib.accelerator_healthy():
    best = autotune(lambda b, remat, s2d: _record_probe(
        _subprocess_probe(b, remat, s2d)))
  if best is not None:
    # Efficiency accounting: achieved model FLOP/s over the device peak
    # (MFU a.k.a. MXU utilization) and HBM bytes per step, both from the
    # compiled executable's own XLA cost analysis — so the driver record
    # tracks efficiency, not just throughput.
    eps = best["examples_per_sec"]
    step_sec = best["batch_size"] / eps
    peak = PEAK_BF16_FLOPS.get(best.get("device_kind"),
                               PEAK_BF16_FLOPS["default"])
    flops = best.get("flops")
    mfu = (flops / step_sec / peak) if flops else None
    headline = {
        "metric": "qtopt_grasps_per_sec_per_chip",
        "value": round(eps, 2),
        "unit": "examples/sec",
        "vs_baseline": round(eps / BASELINE_PER_CHIP, 3),
        # < BATCH_SIZE: OOM degradation (the reference-scale batch did
        # not fit); > BATCH_SIZE: a doubling probe won. The remat/s2d
        # probes may also flip their flags on. value_batch64 keeps the
        # fixed-batch non-remat number for round-over-round comparison.
        # probes_aborted: a probe hit the hang deadline and the rest
        # were skipped — the value is a lower bound for the tuned one.
        "batch_size": best["batch_size"],
        "remat": best["remat"],
        "space_to_depth": best["s2d"],
        "value_batch64": (round(best["value_batch64"], 2)
                          if best["value_batch64"] is not None else None),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "flops_per_step": flops,
        "bytes_per_step": best.get("bytes_accessed"),
        "device_kind": best.get("device_kind"),
        "probes_aborted": best["aborted"],
        "barrier_dominated": bool(best.get("barrier_dominated", False)),
        # Below-dispatch introspection for the winning probe (obs.xray):
        # compile economics + the per-chip HBM watermark estimate that
        # rounds 2-5 OOMed without.
        "xray": _xray_headline_block(best),
        # graftcache accounting for the winning probe: a warm re-bench
        # shows hits>0 with compile_sec ~0 in the xray block above.
        "cache": best.get("cache"),
        # Tunnel heartbeat timeline (same shape as the CPU-fallback
        # path, so the two bench modes cannot drift): every probe
        # outcome stamped with state transitions + causes.
        "tunnel_health": backend_lib.tunnel_health(),
        "host_load": _host_load_block(),
        "graftscope": _graftscope_block(),
    }
    print(json.dumps(headline))
    _append_runlog(headline, best)
    return
  smoke_main(fallback_from="tpu")


def smoke_main(fallback_from: str | None = None) -> None:
  """CPU train-smoke headline (`qtopt_grasps_per_sec_cpu_smoke`):
  record-fed vs synthetic paired A/B through the overlapped host data
  plane, in-process on the pinned CPU backend — pin_cpu never touches
  the tunnel. Run directly with `python bench.py --smoke`
  (`scripts/data_bench.sh` diff-gates its `data_vs_synthetic` ratio);
  also the automatic fallback of the headline bench when the device
  backend is unreachable or every TPU probe failed — `fallback_from`
  is set ONLY on that path, so a deliberate `--smoke` run is never
  mislabeled as a tunnel fallback in runs.jsonl. Honest labeling:
  the CPU smoke config (smaller image/batch) is not comparable to the
  V100-class anchor. The anchor is the record-fed throughput measured
  for this config on this host (PR 7), so vs_baseline ~= 1.0 means "no
  regression vs the recorded CPU baseline", nothing more."""
  rec = _record_probe(
      probe_main({"platform": "cpu", "batch_size": 16, "reruns": 3,
                  "data_path": True, "cache_dir": _cache_dir()}))
  # Recorded for the RECORD-FED config at batch 16 on this host (round
  # 7 — the smoke headline now measures the real data path: records ->
  # parse -> preprocess -> place -> step; pre-PR-7 records used the
  # synthetic device-resident anchor 3643, landed at ~1350 synthetic /
  # ~810 record-fed when this was recorded). Host noise swings this VM
  # 4x run-to-run, so `data_path.vs_synthetic` (pair-median, load-
  # invariant) is the gateable number, not vs_baseline.
  cpu_anchor = 800.0
  data_block = rec.get("data_path") or {}
  tunnel_health = backend_lib.tunnel_health()
  headline = {
      "metric": "qtopt_grasps_per_sec_cpu_smoke",
      "value": round(rec["examples_per_sec"], 2),
      "unit": "examples/sec",
      "vs_baseline": round(rec["examples_per_sec"] / cpu_anchor, 3),
      "batch_size": rec["batch_size"],
      # The synthetic device-resident number (the pre-PR-7 headline
      # semantics) + the load-invariant data-plane ratio, diff-gated
      # via DEFAULT_THRESHOLDS["data_vs_synthetic"].
      "synthetic_value": (round(rec["synthetic_examples_per_sec"], 2)
                          if rec.get("synthetic_examples_per_sec")
                          is not None else None),
      "data_vs_synthetic": (round(data_block["vs_synthetic"], 3)
                            if data_block.get("vs_synthetic") is not None
                            else None),
      "native_stager": data_block.get("native_stager"),
      # Per-stage host-pipeline attribution for the record-fed side
      # (data/overlap_* hist means/p90s + queue-depth gauges): which
      # stage binds when data_vs_synthetic drops — see PERFORMANCE.md
      # "Reading an overlap bench".
      "overlap": data_block.get("overlap"),
      "data_pairs": data_block.get("pairs"),
      "cache": rec.get("cache"),
      "xray": _xray_headline_block(rec),
      # THE round-5 gap, closed: the fallback record now carries the
      # cause and time of the tunnel turning (heartbeat transitions
      # from the health probe + every TPU probe attempted this run)
      # instead of only a silently different metric name.
      "tunnel_health": tunnel_health,
      "host_load": _host_load_block(),
      "graftscope": _graftscope_block(),
  }
  if fallback_from:
    # Present ONLY when this smoke run IS the TPU bench's fallback (the
    # round-5 gap: a record that silently switched metric names at the
    # tunnel death); a deliberate --smoke run omits the key entirely,
    # so presence-based consumers classify records correctly.
    headline["fallback"] = {"from": fallback_from,
                            "unix_time": time.time(),
                            "cause": tunnel_health.get("cause")}
  print(json.dumps(headline))
  _append_runlog(headline, rec)


if __name__ == "__main__":
  main()
