"""Replay writers: stream episode transitions to TFRecord files.

Reference: `TFRecordReplayWriter` (/root/reference/utils/writer.py:27-61)
— actors write collected episodes as tf.Example records that the learner's
input generators read back (the actor/learner decoupling of §2.5).
"""

from __future__ import annotations

from typing import Any, Mapping, Optional, Sequence

from tensor2robot_tpu.data import codec, tfrecord

__all__ = ["TFRecordReplayWriter"]


class TFRecordReplayWriter:
  """Writes transitions (flat dicts of numpy values) as Example records."""

  def __init__(self, path: str, spec_structure=None):
    self._writer = tfrecord.RecordWriter(path)
    self._spec_structure = spec_structure

  def write(self, transitions: Sequence[Any]) -> None:
    """Writes a list of transitions; each is either a flat mapping of
    values or pre-serialized bytes."""
    for transition in transitions:
      if isinstance(transition, bytes):
        self._writer.write(transition)
      else:
        self._writer.write(
            codec.encode_example(transition, self._spec_structure))

  def flush(self) -> None:
    self._writer.flush()

  def close(self) -> None:
    self._writer.close()

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()
