"""Pipelined host loader: parse -> preprocess as overlapped stages.

The record chain used to run its Python-side per-batch work serially on
whatever thread iterated the pipeline: the native stager (`data/
stager.py`) stages arenas on GIL-released C++ threads, but arena
parsing, numpy preprocessing and the downstream device placement all
shared one consumer thread, so a third of train-step throughput went to
host data work that a fast chip just waited on (the `data_vs_synthetic`
~0.65 reading, PERFORMANCE.md "Reading an overlap bench"). This module
turns that chain into explicit overlapped stages with bounded,
stop-aware hand-off queues — the JAX-native successor of TPUEstimator's
per-host infeed threads (/root/reference/models/tpu_model_wrapper.py)
and the overlapped host input pipelines of "Scalable Training of
Language Models using JAX pjit" (PAPERS.md):

  raw source (stager arena / record-tuple batches)   [feeder thread]
    -> parse pool (ordered, `parse_workers` threads) [bounded futures]
    -> preprocess (ONE worker: stateful/seeded preprocessors keep
       deterministic consumption order)              [assembler thread]
    -> byte-capped output queue                      [consumer]

With `fuse_preprocess=True` (ROADMAP item 6's last slice) preprocess
moves INTO the parse pool: each pooled task runs parse + preprocess
back to back, and the assembler only unwraps futures in submission
order. For a PURE per-batch preprocess fn — the declared
`AbstractPreprocessor` contract ("a pure function over SpecStructs of
arrays", preprocessors/base.py) — the output stream is byte-identical
to the serial-worker chain (same batches, same order; only WHICH
thread ran the numpy changes), while the single preprocess worker
stops being the pipeline's serial bottleneck. A preprocess fn that
carries cross-batch state must keep the serial worker
(`fuse_preprocess=False`); `RecordBatchPipeline` auto-gates on the
declared-purity signal (`data/pipeline.py` `fused_preprocess`).

Output order is the raw-batch order (futures are queued in submission
order and the assembler consumes them FIFO), so the overlapped loader
is BYTE-IDENTICAL to the serial chain over the same record stream —
tests/test_overlap.py pins that, eval mode included. The device-side
consumer is `parallel.mesh.DevicePrefetcher`, which keeps its
tunnel-safe close/phase discipline; every stage here is host-only and
therefore safe to stop at any point.

Thread discipline (mechanized by the graftlint `thread-stage-*` rules):
`close()` joins EVERY stage thread (feeder, pool, assembler) — the
teardown test asserts zero leaked threads — the loader is a context
manager, and a `weakref.finalize` backstop stops the stages of a
collected-but-unclosed instance (workers close over locals, never
`self`, so abandonment is actually collectable).

graftscope telemetry (pipeline batches; flows into runs.jsonl via the
standard registry snapshot and `runlog.step_stats_summary`):

  data/overlap_source_ms      feeder wait on the raw source per batch
                              (the stager/record chain is the slow side
                              when this grows)
  data/overlap_parse_ms       parse time per batch inside the pool
  data/overlap_preprocess_ms  preprocess time per batch (assembler)
  data/overlap_wait_ms        consumer dequeue wait (0 in steady state
                              = the loader outruns the consumer; this
                              is what the train loop's data_wait_ms
                              sees)
  data/overlap_parse_queue_depth   in-flight parse futures
  data/overlap_out_queue_depth     preprocessed batches ready
  data/overlap_out_bytes           bytes held in the output queue
  data/overlap_batches             batches handed to the consumer
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Callable, Iterator, List, Optional

from tensor2robot_tpu.obs import metrics as obs_metrics

__all__ = ["OverlappedLoader", "batch_nbytes", "DEFAULT_QUEUE_BYTES"]

# Default byte cap for the preprocessed-batch output queue. Generous for
# smoke batches (a 64x472x472x3 f32 image batch is ~170 MB — ONE such
# batch still flows: a byte-capped queue always admits an item when
# empty) while bounding host RSS to O(depth) typical batches.
DEFAULT_QUEUE_BYTES = 256 << 20  # 256 MiB

# Consumer-side wait observations buffered per `record_many` flush
# (hot-path discipline, PERFORMANCE.md "telemetry overhead").
_FLUSH_EVERY = 64


def batch_nbytes(batch: Any) -> int:
  """Payload bytes of one host batch (numpy leaves; 0 for unknowns)."""
  total = 0
  items = batch.items() if hasattr(batch, "items") else ()
  for _, value in items:
    if hasattr(value, "items"):
      total += batch_nbytes(value)
    else:
      total += int(getattr(value, "nbytes", 0) or 0)
  return total


class _ByteBoundedQueue:
  """Bounded FIFO hand-off queue: item count AND payload bytes.

  `put` blocks while the queue is at its item cap or would exceed the
  byte cap — but ALWAYS admits an item into an empty queue, so one
  over-cap batch flows alone instead of deadlocking (the same rule as
  the native stager's reader queues). Both `put` and `get` watch a stop
  event at 0.1 s granularity so an abandoned producer/consumer never
  blocks forever.
  """

  def __init__(self, max_items: int, max_bytes: int = 0):
    self._max_items = max(int(max_items), 1)
    self._max_bytes = max(int(max_bytes), 0)
    self._items: List[Any] = []
    self._sizes: List[int] = []
    self._bytes = 0
    self._cond = threading.Condition()

  def _full_for(self, nbytes: int) -> bool:
    if not self._items:
      return False  # empty queue always admits (over-cap items flow)
    if len(self._items) >= self._max_items:
      return True
    return bool(self._max_bytes) and self._bytes + nbytes > self._max_bytes

  def put(self, item: Any, nbytes: int, stop: threading.Event) -> bool:
    """Enqueues `item`; returns False if `stop` was set while waiting."""
    with self._cond:
      while self._full_for(nbytes):
        if stop.is_set():
          return False
        self._cond.wait(timeout=0.1)
      if stop.is_set():
        return False
      self._items.append(item)
      self._sizes.append(int(nbytes))
      self._bytes += int(nbytes)
      self._cond.notify_all()
      return True

  def get(self, stop: Optional[threading.Event] = None) -> Any:
    """Dequeues the oldest item; with `stop`, returns None once set and
    the queue is empty (producer died without a sentinel)."""
    with self._cond:
      while not self._items:
        if stop is not None and stop.is_set():
          return None
        self._cond.wait(timeout=0.1)
      item = self._items.pop(0)
      self._bytes -= self._sizes.pop(0)
      self._cond.notify_all()
      return item

  def depth(self) -> int:
    with self._cond:
      return len(self._items)

  def nbytes(self) -> int:
    with self._cond:
      return self._bytes


class OverlappedLoader:
  """Iterator of preprocessed host batches, produced by pipelined
  stages (see module docstring for the stage graph and telemetry).

  `raw` is any iterator of raw batches (stager arenas or record-tuple
  lists); `parse_fn(raw_batch)` and `preprocess_fn(parsed)` are the
  pipeline's own per-batch callables. Exceptions in any stage re-raise
  in the consumer with the stages stopped. Exhaustion closes the loader
  (all threads joined); `close()` is idempotent and MANDATORY for
  abandoning consumers — the context-manager protocol closes on exit,
  and a `weakref.finalize` backstop stops (but cannot join, illegal
  from GC) the stages of a collected instance.
  """

  _END = object()
  # A batch dropped by `skip_batch_on_error` (the graftguard corrupt-
  # record quota): flows through the futures queue so ordering is
  # untouched, filtered before the output queue.
  _SKIPPED = object()

  def __init__(self,
               raw: Iterator[Any],
               parse_fn: Callable[[Any], Any],
               preprocess_fn: Callable[[Any], Any],
               parse_workers: int = 2,
               depth: int = 2,
               max_bytes: int = DEFAULT_QUEUE_BYTES,
               telemetry: bool = True,
               fuse_preprocess: bool = False,
               skip_batch_on_error: Optional[
                   Callable[[BaseException], bool]] = None):
    from concurrent.futures import ThreadPoolExecutor

    parse_workers = max(int(parse_workers), 1)
    depth = max(int(depth), 1)
    stop = threading.Event()
    # Futures hand-off: bounded at 2x the pool so the feeder stays at
    # most one pool's worth of batches ahead of the assembler (in-flight
    # raw arenas are byte-bounded upstream by the stager's own caps).
    parse_q = _ByteBoundedQueue(max_items=max(2 * parse_workers, depth))
    out_q = _ByteBoundedQueue(max_items=depth, max_bytes=max_bytes)
    pool = ThreadPoolExecutor(parse_workers,
                              thread_name_prefix="overlap-parse")
    end = self._END

    if telemetry:
      source_hist = obs_metrics.histogram("data/overlap_source_ms")
      parse_hist = obs_metrics.histogram("data/overlap_parse_ms")
      preprocess_hist = obs_metrics.histogram("data/overlap_preprocess_ms")
      parse_depth_gauge = obs_metrics.gauge("data/overlap_parse_queue_depth")
      out_depth_gauge = obs_metrics.gauge("data/overlap_out_queue_depth")
      out_bytes_gauge = obs_metrics.gauge("data/overlap_out_bytes")
    perf_counter_ns = time.perf_counter_ns

    skipped = self._SKIPPED

    def _absorb(e: BaseException) -> bool:
      """graftguard quota hook: True = drop this batch and continue."""
      if skip_batch_on_error is None or isinstance(
          e, (KeyboardInterrupt, SystemExit)):
        return False
      try:
        return bool(skip_batch_on_error(e))
      except Exception:  # noqa: BLE001 - a broken hook must not mask `e`
        return False

    def _timed_parse(item):
      t0 = perf_counter_ns()
      try:
        out = parse_fn(item)
        if telemetry:
          parse_hist.record((perf_counter_ns() - t0) * 1e-6)
        if fuse_preprocess:
          # Fused mode (module docstring): preprocess runs HERE, on the
          # pool thread, immediately after its own batch's parse — the
          # per-stage telemetry split is preserved so attribution in
          # runs.jsonl reads the same either way.
          t0 = perf_counter_ns()
          out = preprocess_fn(out)
          if telemetry:
            preprocess_hist.record((perf_counter_ns() - t0) * 1e-6)
      except BaseException as e:  # noqa: BLE001 - quota decides
        if _absorb(e):
          return skipped
        raise
      return out

    # Stage threads close over locals ONLY — never `self` — so an
    # abandoned-without-close() loader is collectable and the finalizer
    # below can actually fire (the DevicePrefetcher discipline).
    def _feeder():
      try:
        while not stop.is_set():
          t0 = perf_counter_ns()
          try:
            item = next(raw)
          except StopIteration:
            break
          if telemetry:
            source_hist.record((perf_counter_ns() - t0) * 1e-6)
          future = pool.submit(_timed_parse, item)
          if not parse_q.put(future, 0, stop):
            future.cancel()
            return
          if telemetry:
            parse_depth_gauge.set(float(parse_q.depth()))
        if not stop.is_set():
          parse_q.put(end, 0, stop)
      except BaseException as e:  # noqa: BLE001 - surfaced to consumer
        parse_q.put(e, 0, stop)

    def _assembler():
      try:
        while not stop.is_set():
          got = parse_q.get(stop)
          if got is None or got is end:
            break
          if isinstance(got, BaseException):
            out_q.put(got, 0, stop)
            return
          batch = got.result()
          if batch is skipped:
            continue  # dropped under the corrupt-record quota
          if not fuse_preprocess:
            t0 = perf_counter_ns()
            try:
              batch = preprocess_fn(batch)
            except BaseException as e:  # noqa: BLE001 - quota decides
              if _absorb(e):
                continue
              raise
            if telemetry:
              preprocess_hist.record((perf_counter_ns() - t0) * 1e-6)
          if not out_q.put(batch, batch_nbytes(batch), stop):
            return
          if telemetry:
            out_depth_gauge.set(float(out_q.depth()))
            out_bytes_gauge.set(float(out_q.nbytes()))
        if not stop.is_set():
          out_q.put(end, 0, stop)
      except BaseException as e:  # noqa: BLE001 - surfaced to consumer
        out_q.put(e, 0, stop)

    self._stop = stop
    self._parse_q = parse_q
    self._out_q = out_q
    self._pool = pool
    self._raw = raw
    self._done = False
    self._telemetry = telemetry
    self._pending_ms: List[float] = []
    if telemetry:
      self._wait_hist = obs_metrics.histogram("data/overlap_wait_ms")
      self._batch_counter = obs_metrics.counter("data/overlap_batches")
    self._feeder = threading.Thread(target=_feeder, daemon=True,
                                    name="overlap-feeder")
    self._assembler = threading.Thread(target=_assembler, daemon=True,
                                       name="overlap-preprocess")
    self._feeder.start()
    self._assembler.start()
    # Backstop for abandoned instances: stop the stages (never join —
    # illegal from a GC callback) so they cannot spin holding batches
    # forever; the idle pool threads are released without waiting.
    self._finalizer = weakref.finalize(
        self, OverlappedLoader._finalize, stop, pool)

  @staticmethod
  def _finalize(stop: threading.Event,
                pool) -> None:
    stop.set()
    pool.shutdown(wait=False, cancel_futures=True)

  def __iter__(self) -> "OverlappedLoader":
    return self

  def __next__(self):
    if self._done:
      raise StopIteration
    t0 = time.perf_counter_ns()
    item = self._out_q.get(self._stop)
    if self._telemetry:
      self._pending_ms.append((time.perf_counter_ns() - t0) * 1e-6)
      if len(self._pending_ms) >= _FLUSH_EVERY:
        self._flush_waits()
    if item is self._END or item is None:
      self.close()
      raise StopIteration
    if isinstance(item, BaseException):
      self.close()
      raise item
    return item

  def _flush_waits(self) -> None:
    if self._pending_ms:
      self._wait_hist.record_many(self._pending_ms)
      self._batch_counter.inc(len(self._pending_ms))
      self._pending_ms.clear()

  def __enter__(self) -> "OverlappedLoader":
    return self

  def __exit__(self, exc_type, exc_value, traceback):
    self.close()
    return False

  def close(self, timeout: float = 60.0) -> None:
    """Stops and JOINS every stage thread (idempotent).

    All stages are host-only (parse/preprocess numpy work — device
    placement lives in the downstream DevicePrefetcher, which owns the
    transfer-phase discipline), so stopping mid-batch is always safe
    and the joins are normally bounded by one in-flight batch per
    stage. `timeout` applies ONLY to a feeder blocked inside
    `next(raw)` on a stalled source (which never sees the stop event):
    close() then logs loudly and abandons that one daemon thread
    instead of hanging — the DevicePrefetcher rule for the same case.
    """
    if self._done and not (self._feeder.is_alive()
                           or self._assembler.is_alive()):
      return
    self._done = True
    self._stop.set()
    # Stalled-source handling under the shared RetryPolicy: the join is
    # paced in jittered growing slices (instead of one opaque blocking
    # join), so a source that stays stalled shows up as
    # `retry/overlap_source_stall/*` pressure in telemetry while the
    # total wait stays bounded by `timeout`.
    from tensor2robot_tpu.utils import retry as retry_lib

    # jitter=0: this paces joins on our OWN thread (nothing to
    # de-synchronize), and a jittered draw could shrink the summed
    # ladder to ~0.75*timeout — abandoning a feeder that would have
    # unstalled within the documented budget. The zero-jitter ladder
    # sums to exactly `timeout` (t/64 * (1+1+2+4+8+16+16+16)).
    policy = retry_lib.RetryPolicy(
        name="overlap_source_stall", max_attempts=8,
        base_delay_s=timeout / 64.0, multiplier=2.0,
        max_delay_s=timeout / 4.0, jitter=0.0, deadline_s=timeout)
    self._feeder.join(timeout=policy.backoff_s(0))
    if self._feeder.is_alive():
      retries = obs_metrics.counter("retry/overlap_source_stall/retries")
      for delay in policy.delays():
        retries.inc()
        self._feeder.join(timeout=delay)
        if not self._feeder.is_alive():
          break
    feeder_stalled = self._feeder.is_alive()
    if feeder_stalled:
      from absl import logging

      obs_metrics.counter("retry/overlap_source_stall/giveups").inc()
      logging.error(
          "OverlappedLoader.close(): feeder still alive after %.0fs — "
          "blocked in next(raw) on a stalled data source; abandoning "
          "the daemon thread.", timeout)
    # Unblock + retire the pool: cancel queued parses, wait out the
    # in-flight ones (host numpy — bounded), then join the assembler,
    # which observes the stop event within 0.1 s.
    self._pool.shutdown(wait=True, cancel_futures=True)
    self._assembler.join()
    self._finalizer.detach()
    if not feeder_stalled and hasattr(self._raw, "close"):
      # Release the raw source promptly (the native stager's context
      # sits inside the `_raw_batches` generator frame); only safe once
      # the feeder has actually stopped executing the generator.
      try:
        self._raw.close()
      except Exception:  # noqa: BLE001 - teardown must not mask errors
        pass
    if self._telemetry:
      self._flush_waits()
