"""Encoding numpy data to tf.Example-format records, driven by specs.

Writer-side counterpart of the parser: used by replay writers, test-fixture
generation, and export receivers. Mirrors the serialization conventions the
reference relies on from tf.train.Example (float_list/int64_list/bytes_list,
JPEG/PNG-encoded image bytes, SequenceExample feature_lists).
"""

from __future__ import annotations

import io
from typing import Any, Mapping, Optional

import numpy as np

from tensor2robot_tpu.data import example_pb2
from tensor2robot_tpu import specs as specs_lib

__all__ = ["encode_image", "decode_image", "set_feature", "encode_example",
           "encode_sequence_example"]


def encode_image(array: np.ndarray, data_format: str = "jpeg") -> bytes:
  """Encodes an HWC uint8 array to compressed image bytes via PIL."""
  from PIL import Image

  array = np.asarray(array)
  if array.ndim == 3 and array.shape[-1] == 1:
    array = array[..., 0]
  img = Image.fromarray(array)
  buf = io.BytesIO()
  fmt = {"jpg": "JPEG", "jpeg": "JPEG", "png": "PNG", "bmp": "BMP",
         "gif": "GIF"}[data_format.lower()]
  img.save(buf, format=fmt)
  return buf.getvalue()


def decode_image(data: bytes, channels: Optional[int] = None) -> np.ndarray:
  """Decodes image bytes to an HWC uint8 array (reference
  /root/reference/utils/tfdata.py:426-484 uses tf.image.decode_image)."""
  from PIL import Image

  img = Image.open(io.BytesIO(data))
  if channels == 3 and img.mode != "RGB":
    img = img.convert("RGB")
  elif channels == 1 and img.mode != "L":
    img = img.convert("L")
  array = np.asarray(img)
  if array.ndim == 2:
    array = array[..., None]
  return array


def set_feature(feature: "example_pb2.Feature", value: Any,
                spec: Optional[specs_lib.TensorSpec] = None) -> None:
  """Fills one Feature message from a numpy value according to its spec."""
  if spec is not None and spec.is_extracted:
    # Pre-extracted planes ship as raw bytes (np.frombuffer on parse) —
    # never re-encoded, whatever data_format says about the origin. The
    # wire dtype must match what the parser will frombuffer with: the
    # spec dtype, except bfloat16 which rides the wire as float32
    # (parsing._plan_for's TPU infeed dtype policy).
    if isinstance(value, bytes):
      feature.bytes_list.value.append(value)
    else:
      wire_dtype = spec.dtype
      if wire_dtype == specs_lib._canonical_dtype("bfloat16"):
        wire_dtype = np.dtype(np.float32)
      if np.dtype(wire_dtype).kind in "SUO" or np.dtype(wire_dtype).itemsize == 0:
        # String/object planes: one bytes value per item, payloads
        # untouched (a numpy unicode cast would put UTF-32 on the wire;
        # null-padded 'S' arrays would corrupt ragged payloads).
        if isinstance(value, np.ndarray):
          items = value.reshape(-1).tolist()
        elif isinstance(value, (list, tuple)):
          items = value
        else:
          items = [value]
        for item in items:
          feature.bytes_list.value.append(
              item.encode("utf-8") if isinstance(item, str) else bytes(item))
        return
      feature.bytes_list.value.append(
          np.ascontiguousarray(np.asarray(value, dtype=wire_dtype))
          .tobytes())
    return
  if spec is not None and spec.is_image:
    if isinstance(value, bytes):
      feature.bytes_list.value.append(value)
    else:
      feature.bytes_list.value.append(
          encode_image(np.asarray(value), spec.data_format))
    return
  if isinstance(value, bytes):
    feature.bytes_list.value.append(value)
    return
  if isinstance(value, str):
    feature.bytes_list.value.append(value.encode("utf-8"))
    return
  array = np.asarray(value)
  if array.dtype.kind in "SU":
    for item in array.ravel():
      data = item if isinstance(item, bytes) else str(item).encode("utf-8")
      feature.bytes_list.value.append(data)
  elif array.dtype.kind in "iub":
    feature.int64_list.value.extend(int(v) for v in array.ravel())
  else:
    feature.float_list.value.extend(float(v) for v in array.ravel())


def encode_example(values: Mapping[str, Any],
                   spec_structure: Optional[specs_lib.SpecStructLike] = None
                   ) -> bytes:
  """Serializes a flat dict of values to tf.Example wire bytes.

  Feature keys use `spec.name` when set, else the flat path key — the same
  name-vs-key duality the reference parser honors
  (/root/reference/utils/tfdata.py:515-541).
  """
  flat_specs = None
  if spec_structure is not None:
    flat_specs = specs_lib.flatten_spec_structure(spec_structure)
  example = example_pb2.Example()
  flat_values = specs_lib.flatten_spec_structure(dict(values))
  for key, value in flat_values.items():
    spec = flat_specs[key] if flat_specs is not None and key in flat_specs \
        else None
    name = (spec.name if spec is not None and spec.name else key)
    set_feature(example.features.feature[name], value, spec)
  return example.SerializeToString()


def encode_sequence_example(
    context: Mapping[str, Any],
    sequences: Mapping[str, Any],
    spec_structure: Optional[specs_lib.SpecStructLike] = None) -> bytes:
  """Serializes context + per-step sequence values to SequenceExample bytes.

  `sequences` values must have a leading time dimension.
  """
  flat_specs = None
  if spec_structure is not None:
    flat_specs = specs_lib.flatten_spec_structure(spec_structure)

  def _spec_for(key):
    if flat_specs is not None and key in flat_specs:
      return flat_specs[key]
    return None

  example = example_pb2.SequenceExample()
  for key, value in specs_lib.flatten_spec_structure(dict(context)).items():
    spec = _spec_for(key)
    name = spec.name if spec is not None and spec.name else key
    set_feature(example.context.feature[name], value, spec)
  for key, value in specs_lib.flatten_spec_structure(dict(sequences)).items():
    spec = _spec_for(key)
    name = spec.name if spec is not None and spec.name else key
    feature_list = example.feature_lists.feature_list[name]
    for step_value in value:
      set_feature(feature_list.feature.add(), step_value, spec)
  return example.SerializeToString()


def maybe_recompress_jpeg(data: bytes, quality: int = 95,
                          max_side: Optional[int] = None) -> bytes:
  """Re-encodes image bytes as JPEG, optionally capping resolution
  (reference jpeg re-compress/decompress helpers,
  /root/reference/utils/tfdata.py:546-626) — shrinks replay/log storage."""
  from PIL import Image
  import io as io_lib

  img = Image.open(io_lib.BytesIO(data))
  if img.mode != "RGB":
    img = img.convert("RGB")
  if max_side is not None and max(img.size) > max_side:
    scale = max_side / max(img.size)
    img = img.resize((int(img.width * scale), int(img.height * scale)))
  buf = io_lib.BytesIO()
  img.save(buf, format="JPEG", quality=quality)
  return buf.getvalue()


def decode_image_batch(datas, channels: Optional[int] = None) -> np.ndarray:
  """Decodes a list of image byte strings to one [N, H, W, C] array."""
  return np.stack([decode_image(d, channels=channels) for d in datas])
