"""Input generators: spec-filled batch sources for the train/eval loop.

Re-designs the reference's `input_generators/` package
(/root/reference/input_generators/abstract_input_generator.py:34-204,
default_input_generator.py:47-314). An input generator holds feature/label
specs plus a preprocess function — both injected from the model via
`set_specification_from_model` — and produces an iterator of batches
(SpecStructs of numpy arrays) for a mode. The trainer shards those batches
onto the device mesh.
"""

from __future__ import annotations

import abc
import json
import os
from typing import Any, Callable, Iterator, Mapping, Optional, Sequence, Union

import numpy as np

from tensor2robot_tpu import modes as modes_lib
from tensor2robot_tpu import specs as specs_lib
from tensor2robot_tpu.data import parsing, pipeline
from tensor2robot_tpu.utils import config

__all__ = [
    "AbstractInputGenerator",
    "DefaultRecordInputGenerator",
    "FractionalRecordInputGenerator",
    "MultiEvalRecordInputGenerator",
    "GeneratorInputGenerator",
    "DefaultRandomInputGenerator",
    "DefaultConstantInputGenerator",
    "WeightedRecordInputGenerator",
]


class AbstractInputGenerator(abc.ABC):
  """Holds specs + preprocess_fn; produces batch iterators per mode.

  Reference contract (/root/reference/input_generators/
  abstract_input_generator.py:76-160): specs are *not* constructor inputs —
  they are injected from the model (via its preprocessor) so the input
  pipeline always matches what the model consumes.
  """

  def __init__(self, batch_size: int = 32):
    self._batch_size = batch_size
    self._feature_spec: Optional[specs_lib.SpecStruct] = None
    self._label_spec: Optional[specs_lib.SpecStruct] = None
    self._preprocess_fn = None
    # Host-overlap tuning injected by the trainer (train_eval_model's
    # `host_overlap_workers` / `host_overlap_queue_mb` gin knobs) via
    # `set_overlap_options` — only record-backed generators consume it.
    self._overlap_options: dict = {}

  @property
  def batch_size(self) -> int:
    return self._batch_size

  @batch_size.setter
  def batch_size(self, value: int) -> None:
    self._batch_size = value

  @property
  def feature_spec(self) -> Optional[specs_lib.SpecStruct]:
    return self._feature_spec

  @property
  def label_spec(self) -> Optional[specs_lib.SpecStruct]:
    return self._label_spec

  def set_specification(self,
                        feature_spec: specs_lib.SpecStructLike,
                        label_spec: Optional[specs_lib.SpecStructLike] = None
                        ) -> None:
    self._feature_spec = specs_lib.flatten_spec_structure(feature_spec)
    self._label_spec = (specs_lib.flatten_spec_structure(label_spec)
                        if label_spec is not None else None)

  def set_specification_from_model(self, model, mode: str) -> None:
    """Pulls the preprocessor's *in* specs and preprocess fn from a model
    (reference :76-98: spec flow model -> preprocessor -> input)."""
    preprocessor = model.preprocessor
    self.set_specification(
        preprocessor.get_in_feature_specification(mode),
        preprocessor.get_in_label_specification(mode))
    self._preprocess_fn = preprocessor.preprocess

  def set_preprocess_fn(self, preprocess_fn) -> None:
    self._preprocess_fn = preprocess_fn

  def set_overlap_options(self,
                          num_parallel_parses: Optional[int] = None,
                          prefetch_size: Optional[int] = None,
                          overlap: Optional[bool] = None,
                          overlap_queue_mb: Optional[float] = None,
                          fused_preprocess: Optional[bool] = None) -> None:
    """Injects host-overlap pipeline tuning (parse worker count,
    hand-off depth, byte caps, preprocess fusion into the parse pool)
    from the trainer — the slow-host-fast-chip knobs of the pipelined
    loader (`data/overlap.py`). None values keep the generator's own
    defaults (for `fused_preprocess` that is the declared-purity auto
    gate, `pipeline.RecordBatchPipeline._fuse_preprocess_enabled`);
    generators without a record pipeline accept and ignore the call."""
    for key, value in (("num_parallel_parses", num_parallel_parses),
                       ("prefetch_size", prefetch_size),
                       ("overlap", overlap),
                       ("overlap_queue_mb", overlap_queue_mb),
                       ("fused_preprocess", fused_preprocess)):
      if value is not None:
        self._overlap_options[key] = value

  def _assert_specs_initialized(self) -> None:
    if self._feature_spec is None:
      raise ValueError(
          "Input generator specs not set. Call set_specification_from_model "
          "or set_specification first.")

  @abc.abstractmethod
  def create_dataset(self, mode: str) -> Iterator[specs_lib.SpecStruct]:
    """Returns an iterator over `{features: ..., labels: ...}` batches."""

  def __call__(self, mode: str) -> Iterator[specs_lib.SpecStruct]:
    return self.create_dataset(modes_lib.validate(mode))


@config.configurable
class DefaultRecordInputGenerator(AbstractInputGenerator):
  """TFRecord-file-backed generator (reference :47-101)."""

  def __init__(self,
               file_patterns: Union[str, Sequence[str], Mapping[str, Any],
                                    None] = None,
               batch_size: int = 32,
               shuffle_buffer_size: int = 512,
               prefetch_size: int = 2,
               num_parallel_parses: int = 2,
               overlap: Optional[bool] = None,
               overlap_queue_mb: Optional[float] = None,
               seed: Optional[int] = None,
               process_index: Optional[int] = None,
               process_count: Optional[int] = None):
    super().__init__(batch_size=batch_size)
    if not file_patterns:
      raise ValueError("file_patterns must be provided.")
    self._file_patterns = file_patterns
    self._shuffle_buffer_size = shuffle_buffer_size
    self.set_overlap_options(num_parallel_parses=num_parallel_parses,
                             prefetch_size=prefetch_size,
                             overlap=overlap,
                             overlap_queue_mb=overlap_queue_mb)
    self._seed = seed
    # Host-sharding info is injected by the trainer (which owns the JAX
    # runtime); defaults are single-host. Querying jax.process_index() here
    # would force backend initialization from the data layer.
    self._process_index = process_index
    self._process_count = process_count

  def set_process_info(self, process_index: int, process_count: int) -> None:
    self._process_index = process_index
    self._process_count = process_count

  def create_dataset(self, mode: str) -> Iterator[specs_lib.SpecStruct]:
    self._assert_specs_initialized()
    parse_fn = parsing.create_parse_fn(self._feature_spec, self._label_spec)
    opts = self._overlap_options
    return iter(pipeline.RecordBatchPipeline(
        self._file_patterns,
        parse_fn,
        batch_size=self._batch_size,
        mode=mode,
        shuffle_buffer_size=self._shuffle_buffer_size,
        prefetch_size=opts.get("prefetch_size", 2),
        num_parallel_parses=opts.get("num_parallel_parses", 2),
        overlap=opts.get("overlap"),
        overlap_queue_mb=opts.get("overlap_queue_mb"),
        fused_preprocess=opts.get("fused_preprocess"),
        seed=self._seed,
        preprocess_fn=self._preprocess_fn,
        process_index=self._process_index or 0,
        process_count=self._process_count or 1))


@config.configurable
class FractionalRecordInputGenerator(DefaultRecordInputGenerator):
  """Uses only a fraction of the matched files — data-ablation experiments
  (reference :104-124)."""

  def __init__(self, file_fraction: float = 1.0, **kwargs):
    super().__init__(**kwargs)
    if not 0.0 < file_fraction <= 1.0:
      raise ValueError(f"file_fraction must be in (0, 1], got {file_fraction}")
    self._file_fraction = file_fraction

  def create_dataset(self, mode: str) -> Iterator[specs_lib.SpecStruct]:
    if self._file_fraction < 1.0:
      files = pipeline.resolve_file_patterns(self._file_patterns)
      n = max(1, int(self._file_fraction * len(files)))
      self._file_patterns = files[:n]
    return super().create_dataset(mode)


@config.configurable
class MultiEvalRecordInputGenerator(DefaultRecordInputGenerator):
  """Selects its dataset by eval-job name from the cluster env
  (reference :127-140 reads TF_CONFIG['multi_eval_name'])."""

  def __init__(self,
               eval_dataset_map: Optional[Mapping[str, Any]] = None,
               **kwargs):
    if not eval_dataset_map:
      raise ValueError("eval_dataset_map must be provided.")
    eval_name = multi_eval_name()
    if eval_name not in eval_dataset_map:
      raise ValueError(
          f"Eval job {eval_name!r} not in eval_dataset_map "
          f"{sorted(eval_dataset_map)}.")
    super().__init__(file_patterns=eval_dataset_map[eval_name], **kwargs)


def multi_eval_name(default: str = "eval") -> str:
  """Reads the eval-job name from T2R_CLUSTER (JSON) or TF_CONFIG-style env
  (reference /root/reference/input_generators/default_input_generator.py:
  36-44)."""
  for var in ("T2R_CLUSTER", "TF_CONFIG"):
    raw = os.environ.get(var)
    if raw:
      try:
        return json.loads(raw).get("multi_eval_name", default)
      except (ValueError, AttributeError):
        continue
  return default


@config.configurable
class GeneratorInputGenerator(AbstractInputGenerator):
  """Backed by a python generator yielding (features, labels) numpy dicts
  (reference :143-193)."""

  def __init__(self, generator_fn: Optional[Callable] = None,
               batch_size: int = 32):
    super().__init__(batch_size=batch_size)
    if generator_fn is None:
      raise ValueError("generator_fn must be provided.")
    self._generator_fn = generator_fn

  def create_dataset(self, mode: str) -> Iterator[specs_lib.SpecStruct]:
    self._assert_specs_initialized()

    def _iterate():
      gen = self._generator_fn(mode)
      while True:
        columns_f, columns_l = [], []
        for _ in range(self._batch_size):
          try:
            features, labels = next(gen)
          except StopIteration:
            return
          columns_f.append(specs_lib.flatten_spec_structure(features))
          columns_l.append(specs_lib.flatten_spec_structure(labels))
        out = specs_lib.SpecStruct()
        for key in columns_f[0]:
          out["features/" + key] = np.stack([c[key] for c in columns_f])
        for key in columns_l[0]:
          out["labels/" + key] = np.stack([c[key] for c in columns_l])
        yield self._apply_preprocess(out, mode)

    return _iterate()

  def _apply_preprocess(self, batch, mode):
    if self._preprocess_fn is None:
      return batch
    features, labels = self._preprocess_fn(
        batch["features"], batch["labels"] if "labels" in batch else
        specs_lib.SpecStruct(), mode)
    out = specs_lib.SpecStruct()
    out["features"] = specs_lib.flatten_spec_structure(features)
    if labels is not None and len(labels):
      out["labels"] = specs_lib.flatten_spec_structure(labels)
    return out


@config.configurable
class DefaultRandomInputGenerator(AbstractInputGenerator):
  """Random data matching the specs — smoke tests & benchmarks
  (reference :196-206)."""

  def __init__(self, batch_size: int = 32, sequence_length: int = 3,
               seed: int = 0):
    super().__init__(batch_size=batch_size)
    self._sequence_length = sequence_length
    self._seed = seed

  def create_dataset(self, mode: str) -> Iterator[specs_lib.SpecStruct]:
    self._assert_specs_initialized()

    def _iterate():
      step = 0
      while True:
        out = specs_lib.SpecStruct()
        out["features"] = specs_lib.make_random_numpy(
            self._feature_spec, batch_size=self._batch_size,
            sequence_length=self._sequence_length, seed=self._seed + step)
        if self._label_spec is not None and len(self._label_spec):
          labels = specs_lib.make_random_numpy(
              self._label_spec, batch_size=self._batch_size,
              sequence_length=self._sequence_length,
              seed=self._seed + step + 10_000_019)
          if len(labels):  # all-optional label specs generate nothing
            out["labels"] = labels
        step += 1
        if self._preprocess_fn is not None:
          features, labels = self._preprocess_fn(
              out["features"],
              out["labels"] if "labels" in out else specs_lib.SpecStruct(),
              mode)
          out = specs_lib.SpecStruct()
          out["features"] = features
          if labels is not None and len(labels):
            out["labels"] = labels
        yield out

    return _iterate()


@config.configurable
class DefaultConstantInputGenerator(AbstractInputGenerator):
  """Constant data matching the specs (reference :209-225)."""

  def __init__(self, constant_value: float = 1.0, batch_size: int = 32,
               sequence_length: int = 3):
    super().__init__(batch_size=batch_size)
    self._constant_value = constant_value
    self._sequence_length = sequence_length

  def create_dataset(self, mode: str) -> Iterator[specs_lib.SpecStruct]:
    self._assert_specs_initialized()

    def _iterate():
      while True:
        out = specs_lib.SpecStruct()
        out["features"] = specs_lib.make_constant_numpy(
            self._feature_spec, self._constant_value, self._batch_size,
            self._sequence_length)
        if self._label_spec is not None and len(self._label_spec):
          out["labels"] = specs_lib.make_constant_numpy(
              self._label_spec, self._constant_value, self._batch_size,
              self._sequence_length)
        yield out

    return _iterate()


@config.configurable
class WeightedRecordInputGenerator(AbstractInputGenerator):
  """Weighted mixture over file-pattern groups (reference :228-314)."""

  def __init__(self,
               file_pattern_groups: Optional[Sequence[Any]] = None,
               weights: Optional[Sequence[float]] = None,
               batch_size: int = 32,
               seed: Optional[int] = None,
               shuffle_buffer_size: int = 512):
    super().__init__(batch_size=batch_size)
    if not file_pattern_groups:
      raise ValueError("file_pattern_groups must be provided.")
    self._groups = file_pattern_groups
    self._weights = weights or [1.0 / len(file_pattern_groups)] * len(
        file_pattern_groups)
    self._seed = seed
    self._shuffle_buffer_size = shuffle_buffer_size

  def create_dataset(self, mode: str) -> Iterator[specs_lib.SpecStruct]:
    self._assert_specs_initialized()
    parse_fn = parsing.create_parse_fn(self._feature_spec, self._label_spec)
    opts = dict(self._overlap_options)
    kwargs = {k: opts[k] for k in ("prefetch_size", "num_parallel_parses",
                                   "overlap", "overlap_queue_mb")
              if k in opts}
    return iter(pipeline.WeightedRecordPipeline(
        self._groups, self._weights, parse_fn,
        batch_size=self._batch_size, mode=mode, seed=self._seed,
        shuffle_buffer_size=self._shuffle_buffer_size,
        preprocess_fn=self._preprocess_fn, **kwargs))
