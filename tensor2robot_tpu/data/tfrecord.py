"""TFRecord container IO without TensorFlow.

The reference reads/writes TFRecord files through tf.data / tf.io
(/root/reference/utils/tfdata.py:174-210, /root/reference/utils/writer.py:
27-61). This module implements the container format directly — length-
prefixed records with masked CRC32C checksums — so the host data pipeline
has no TF runtime dependency.

Record layout (the public TFRecord framing):
  uint64 length
  uint32 masked_crc32c(length)
  bytes  data[length]
  uint32 masked_crc32c(data)
"""

from __future__ import annotations

import os
import struct
from typing import Iterable, Iterator, List, Optional, Sequence

import numpy as np

__all__ = ["RecordWriter", "read_records", "iter_records", "count_records"]

# -- CRC32C (Castagnoli), table-driven, vectorized with numpy ---------------

_CRC_TABLE = None


def _crc_table() -> np.ndarray:
  global _CRC_TABLE
  if _CRC_TABLE is None:
    poly = 0x82F63B78
    table = np.zeros(256, dtype=np.uint32)
    for i in range(256):
      crc = i
      for _ in range(8):
        crc = (crc >> 1) ^ (poly if crc & 1 else 0)
      table[i] = crc
    _CRC_TABLE = table
  return _CRC_TABLE


def _crc32c(data: bytes) -> int:
  table = _crc_table()
  crc = np.uint32(0xFFFFFFFF)
  buf = np.frombuffer(data, dtype=np.uint8)
  # Scalar loop in numpy is slow for big buffers; process in python ints
  # with the table — still fast enough for host-side IO, and replaceable
  # by a C extension without changing callers.
  crc_int = int(crc)
  tbl = table.tolist()
  for byte in buf.tolist():
    crc_int = tbl[(crc_int ^ byte) & 0xFF] ^ (crc_int >> 8)
  return crc_int ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
  from tensor2robot_tpu import native

  value = native.masked_crc32c(data)
  if value is not None:
    return value
  crc = _crc32c(data)
  return ((((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF)


class RecordWriter:
  """Writes TFRecord files (reference `TFRecordReplayWriter` container)."""

  def __init__(self, path: str):
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    self._file = open(path, "wb")

  def write(self, record: bytes) -> None:
    length = struct.pack("<Q", len(record))
    self._file.write(length)
    self._file.write(struct.pack("<I", _masked_crc(length)))
    self._file.write(record)
    self._file.write(struct.pack("<I", _masked_crc(record)))

  def flush(self) -> None:
    self._file.flush()

  def close(self) -> None:
    self._file.close()

  def __enter__(self) -> "RecordWriter":
    return self

  def __exit__(self, *exc) -> None:
    self.close()


def iter_records(path: str, verify_crc: bool = False) -> Iterator[bytes]:
  """Streams records from one TFRecord file (native C++ reader when
  available, pure-Python fallback otherwise)."""
  from tensor2robot_tpu import native

  if native.available():
    yield from native.iter_records_native(path, verify_crc=verify_crc)
    return
  with open(path, "rb") as f:
    while True:
      header = f.read(12)
      if not header:
        return
      if len(header) < 12:
        raise IOError(f"Truncated record header in {path}")
      (length,) = struct.unpack("<Q", header[:8])
      if verify_crc:
        (expected,) = struct.unpack("<I", header[8:12])
        if _masked_crc(header[:8]) != expected:
          raise IOError(f"Corrupt length CRC in {path}")
      data = f.read(length)
      if len(data) < length:
        raise IOError(f"Truncated record body in {path}")
      footer = f.read(4)
      if len(footer) < 4:
        raise IOError(f"Truncated record footer in {path}")
      if verify_crc:
        (expected,) = struct.unpack("<I", footer)
        if _masked_crc(data) != expected:
          raise IOError(f"Corrupt data CRC in {path}")
      yield data


def read_records(path: str, verify_crc: bool = False) -> List[bytes]:
  return list(iter_records(path, verify_crc=verify_crc))


def count_records(path: str) -> int:
  """Counts records by seeking over bodies (no payload reads)."""
  n = 0
  with open(path, "rb") as f:
    while True:
      header = f.read(12)
      if not header:
        return n
      if len(header) < 12:
        raise IOError(f"Truncated record header in {path}")
      (length,) = struct.unpack("<Q", header[:8])
      f.seek(length + 4, os.SEEK_CUR)
      n += 1
