"""TFRecord container IO without TensorFlow.

The reference reads/writes TFRecord files through tf.data / tf.io
(/root/reference/utils/tfdata.py:174-210, /root/reference/utils/writer.py:
27-61). This module implements the container format directly — length-
prefixed records with masked CRC32C checksums — so the host data pipeline
has no TF runtime dependency.

Record layout (the public TFRecord framing):
  uint64 length
  uint32 masked_crc32c(length)
  bytes  data[length]
  uint32 masked_crc32c(data)
"""

from __future__ import annotations

import os
import struct
from typing import Iterable, Iterator, List, Optional, Sequence

import numpy as np

__all__ = ["RecordWriter", "read_records", "iter_records", "count_records"]

# Records larger than this are treated as corruption, matching the
# native reader's cap (`native/tfrecord_io.cc` kMaxRecordBytes): a
# garbage length prefix must raise IOError on BOTH paths, not
# OverflowError/MemoryError from handing f.read() a 2^60 length
# (tests/test_stager.py fuzz parity).
_MAX_RECORD_BYTES = 1 << 31

# -- CRC32C (Castagnoli), slicing-by-8 table-driven fallback ----------------
# The native library (`native/tfrecord_io.cc`) is the fast path; this
# fallback only runs in toolchain-absent environments. Slicing-by-8:
# 8 derived tables fold 8 input bytes per loop iteration (the classic
# Intel technique), with numpy reinterpreting the payload as uint64
# words — ~8x fewer Python-level iterations than the byte-at-a-time
# loop this replaced, bit-identical output (pinned against the native
# CRC on random payloads in tests/test_stager.py).

_CRC_TABLES = None


def _crc_tables() -> List[List[int]]:
  global _CRC_TABLES
  if _CRC_TABLES is None:
    poly = np.uint64(0x82F63B78)
    # Table 0 is the standard byte-at-a-time table, built vectorized:
    # 8 shift/xor rounds over all 256 entries at once.
    table = np.arange(256, dtype=np.uint64)
    for _ in range(8):
      table = (table >> np.uint64(1)) ^ (poly * (table & np.uint64(1)))
    tables = [table]
    # Table k folds a byte that sits k positions deeper in the stream:
    # tables[k][b] = tables[0][tables[k-1][b] & 0xFF] ^ (tables[k-1][b] >> 8)
    for _ in range(7):
      prev = tables[-1]
      tables.append(tables[0][(prev & np.uint64(0xFF)).astype(np.int64)]
                    ^ (prev >> np.uint64(8)))
    _CRC_TABLES = [t.tolist() for t in tables]
  return _CRC_TABLES


def _crc32c(data: bytes) -> int:
  t0, t1, t2, t3, t4, t5, t6, t7 = _crc_tables()
  crc = 0xFFFFFFFF
  n_words = len(data) // 8
  if n_words:
    # One little-endian uint64 per iteration; the running CRC folds into
    # the low 4 bytes of the word (CRC32C is reflected).
    words = np.frombuffer(data, dtype="<u8", count=n_words)
    for word in words.tolist():
      word ^= crc
      crc = (t7[word & 0xFF] ^ t6[(word >> 8) & 0xFF]
             ^ t5[(word >> 16) & 0xFF] ^ t4[(word >> 24) & 0xFF]
             ^ t3[(word >> 32) & 0xFF] ^ t2[(word >> 40) & 0xFF]
             ^ t1[(word >> 48) & 0xFF] ^ t0[word >> 56])
  for byte in data[n_words * 8:]:
    crc = t0[(crc ^ byte) & 0xFF] ^ (crc >> 8)
  return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
  from tensor2robot_tpu import native

  value = native.masked_crc32c(data)
  if value is not None:
    return value
  crc = _crc32c(data)
  return ((((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF)


class RecordWriter:
  """Writes TFRecord files (reference `TFRecordReplayWriter` container)."""

  def __init__(self, path: str):
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    self._file = open(path, "wb")

  def write(self, record: bytes) -> None:
    length = struct.pack("<Q", len(record))
    self._file.write(length)
    self._file.write(struct.pack("<I", _masked_crc(length)))
    self._file.write(record)
    self._file.write(struct.pack("<I", _masked_crc(record)))

  def flush(self) -> None:
    self._file.flush()

  def close(self) -> None:
    self._file.close()

  def __enter__(self) -> "RecordWriter":
    return self

  def __exit__(self, *exc) -> None:
    self.close()


def iter_records(path: str, verify_crc: bool = False) -> Iterator[bytes]:
  """Streams records from one TFRecord file (native C++ reader when
  available, pure-Python fallback otherwise)."""
  from tensor2robot_tpu import native

  if native.available():
    yield from native.iter_records_native(path, verify_crc=verify_crc)
    return
  with open(path, "rb") as f:
    while True:
      header = f.read(12)
      if not header:
        return
      if len(header) < 12:
        raise IOError(f"Truncated record header in {path}")
      (length,) = struct.unpack("<Q", header[:8])
      if length > _MAX_RECORD_BYTES:
        raise IOError(f"Implausible record length in {path} "
                      "(corrupt file?)")
      if verify_crc:
        (expected,) = struct.unpack("<I", header[8:12])
        if _masked_crc(header[:8]) != expected:
          raise IOError(f"Corrupt length CRC in {path}")
      data = f.read(length)
      if len(data) < length:
        raise IOError(f"Truncated record body in {path}")
      footer = f.read(4)
      if len(footer) < 4:
        raise IOError(f"Truncated record footer in {path}")
      if verify_crc:
        (expected,) = struct.unpack("<I", footer)
        if _masked_crc(data) != expected:
          raise IOError(f"Corrupt data CRC in {path}")
      yield data


def read_records(path: str, verify_crc: bool = False) -> List[bytes]:
  return list(iter_records(path, verify_crc=verify_crc))


def count_records(path: str) -> int:
  """Counts records by seeking over bodies (no payload reads)."""
  n = 0
  with open(path, "rb") as f:
    while True:
      header = f.read(12)
      if not header:
        return n
      if len(header) < 12:
        raise IOError(f"Truncated record header in {path}")
      (length,) = struct.unpack("<Q", header[:8])
      if length > _MAX_RECORD_BYTES:
        raise IOError(f"Implausible record length in {path} "
                      "(corrupt file?)")
      f.seek(length + 4, os.SEEK_CUR)
      n += 1
