"""Host-side streaming input pipeline.

JAX-native replacement for the reference's tf.data template
(/root/reference/utils/tfdata.py:629-718): file glob -> shuffle files ->
parallel interleave -> record shuffle -> repeat -> batch -> **batched
parse** -> preprocess -> prefetch. The pipeline runs on host CPU threads
(decode stays off-device, SURVEY.md §7) and hands dense numpy batches to
the device layer, which `jax.device_put`s them with a NamedSharding.

Differences from the reference, by design:
* no tf.data runtime — a small thread-pool pipeline with explicit stages;
* per-host file sharding for multi-process (pod) training replaces
  TPUEstimator's per-host input_fn invocation
  (/root/reference/utils/tfdata.py:38-61);
* deterministic mode for eval, nondeterministic interleave for training
  (reference options, :629-689).

graftguard data-plane degradation: with `max_corrupt_records` > 0 a
batch that fails to parse/preprocess (corrupt record bytes, a poisoned
preprocess) is SKIPPED and its records counted
(`data/corrupt_records_skipped`, `data/corrupt_batches_skipped`)
instead of killing the epoch, and a record-source I/O error ends the
current epoch early (counted, training continues on the next epoch)
— raising only once the counted quota is exceeded, so a rotten shard
still surfaces instead of silently starving the run. The quota is 0
by default: eval and parity paths keep the strict raise-immediately
contract. `obs.faultlab` points (`data.record_io`,
`data.corrupt_record`, `data.preprocess`) inject exactly these
failures for the chaos bench.
"""

from __future__ import annotations

import glob as glob_lib
import itertools
import logging
import queue
import random
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Mapping, Optional, Sequence, Tuple, Union

import numpy as np

from tensor2robot_tpu import specs as specs_lib
from tensor2robot_tpu.data import overlap as overlap_lib
from tensor2robot_tpu.data import parsing, tfrecord
from tensor2robot_tpu.data import stager as stager_lib
from tensor2robot_tpu.obs import faultlab as faultlab_lib
from tensor2robot_tpu.obs import metrics as obs_metrics
from tensor2robot_tpu.obs import trace as obs_trace
from tensor2robot_tpu.utils import config

__all__ = ["resolve_file_patterns", "RecordBatchPipeline", "prefetch",
           "interleave_records", "shuffled"]

PreprocessFn = Callable[[specs_lib.SpecStruct, specs_lib.SpecStruct, str],
                        Tuple[specs_lib.SpecStruct, specs_lib.SpecStruct]]

# How many per-batch wait observations the prefetch consumer buffers
# locally before one `record_many` flush into the metrics registry.
_FLUSH_EVERY = 64

# Sentinel for a batch dropped under the graftguard corrupt-record
# quota (filtered out of the serial chain before the consumer).
_SKIP = object()


def _corrupted_copy(batch):
  """faultlab `data.corrupt_record` payload: returns `batch` with the
  FIRST record's bytes overwritten with 0xFF (an invalid proto wire
  tag), so the parser fails exactly the way real corruption fails.
  Copies — the raw batch may be shared with telemetry/retries."""
  if isinstance(batch, stager_lib.StagedBatch):
    arena = batch.arena.copy()
    offset = int(batch.offsets[0])
    length = int(batch.lengths[0])
    arena[offset:offset + length] = 0xFF
    return stager_lib.StagedBatch(arena, batch.offsets, batch.lengths)
  batch = list(batch)
  first = {key: b"\xff" * max(len(value), 4)
           for key, value in batch[0].items()}
  batch[0] = first
  return batch


def resolve_file_patterns(
    file_patterns: Union[str, Sequence[str]],
    process_index: int = 0,
    process_count: int = 1) -> List[str]:
  """Expands comma-separated glob patterns; shards files across hosts.

  Reference `get_data_format_and_filenames`
  (/root/reference/utils/tfdata.py:92-138) with JAX multi-process sharding
  in place of per-host TPUEstimator input invocation.
  """
  files, _ = _resolve_file_patterns_sharded(file_patterns, process_index,
                                            process_count)
  return files


def _resolve_file_patterns_sharded(
    file_patterns: Union[str, Sequence[str]],
    process_index: int = 0,
    process_count: int = 1) -> Tuple[List[str], bool]:
  """`resolve_file_patterns` plus a shared-files flag.

  Returns (files, shared): `shared` is True on the fewer-files-than-
  hosts path, where every host reads the SAME full file list —
  `RecordBatchPipeline` then offsets its epoch shuffle seed by
  `process_index` so co-hosted processes don't train on identical
  record orders (correctness preserved, determinism traded for
  progress)."""
  if isinstance(file_patterns, str):
    file_patterns = file_patterns.split(",")
  files: List[str] = []
  for pattern in file_patterns:
    pattern = pattern.strip()
    if not pattern:
      continue
    matched = sorted(glob_lib.glob(pattern))
    if not matched:
      raise ValueError(f"File pattern {pattern!r} matched no files.")
    files.extend(matched)
  shared = False
  if process_count > 1:
    if len(files) >= process_count:
      files = files[process_index::process_count]
    else:
      shared = True
  return files, shared


def interleave_records(files: Sequence[str],
                       cycle_length: int = 4,
                       shuffle_files: bool = False,
                       seed: Optional[int] = None) -> Iterator[bytes]:
  """Round-robin interleave of records from several files (reference
  parallel interleave, /root/reference/utils/tfdata.py:174-210)."""
  files = list(files)
  if shuffle_files:
    random.Random(seed).shuffle(files)
  pending = list(files)
  active: List[Iterator[bytes]] = []
  while pending or active:
    while pending and len(active) < cycle_length:
      active.append(tfrecord.iter_records(pending.pop(0)))
    next_active = []
    for it in active:
      try:
        yield next(it)
        next_active.append(it)
      except StopIteration:
        pass
    active = next_active


def shuffled(stream: Iterator[Any], buffer_size: int,
             seed: Optional[int] = None) -> Iterator[Any]:
  """Reservoir-style shuffle buffer (tf.data.Dataset.shuffle semantics).

  `buffer_size` <= 0 is a pass-through (tf.data treats shuffle(0)/(1) as
  no-ops) — without the guard the first post-fill item would hit
  `rng.randrange(0)` and raise ValueError."""
  if buffer_size <= 0:
    yield from stream
    return
  rng = random.Random(seed)
  buffer: List[Any] = []
  for item in stream:
    if len(buffer) < buffer_size:
      buffer.append(item)
      continue
    idx = rng.randrange(buffer_size)
    yield buffer[idx]
    buffer[idx] = item
  rng.shuffle(buffer)
  yield from buffer


def parallel_map_ordered(fn: Callable[[Any], Any],
                         stream: Iterator[Any],
                         num_workers: int = 2,
                         max_inflight: Optional[int] = None
                         ) -> Iterator[Any]:
  """Order-preserving parallel map with bounded in-flight work.

  The parse stage scales across threads because the native parser and
  image decode release the GIL (tf.data's parallel map equivalent for
  this pipeline)."""
  import collections
  from concurrent.futures import ThreadPoolExecutor

  max_inflight = max_inflight or 2 * num_workers
  with ThreadPoolExecutor(num_workers) as pool:
    futures: "collections.deque" = collections.deque()
    for item in stream:
      futures.append(pool.submit(fn, item))
      while len(futures) >= max_inflight:
        yield futures.popleft().result()
    while futures:
      yield futures.popleft().result()


def _batched(stream: Iterator[Any], batch_size: int,
             drop_remainder: bool) -> Iterator[List[Any]]:
  """Groups a stream into lists of batch_size (tf.data batch semantics)."""
  batch: List[Any] = []
  for item in stream:
    batch.append(item)
    if len(batch) == batch_size:
      yield batch
      batch = []
  if batch and not drop_remainder:
    yield batch


def prefetch(stream: Iterator[Any], size: int = 2) -> Iterator[Any]:
  """Background-thread prefetch (tf.data prefetch(AUTOTUNE) equivalent).

  The worker watches a stop event so an abandoned consumer (finished
  eval round, dropped iterator) releases the thread and its upstream
  file handles instead of blocking on a full queue forever."""
  q: "queue.Queue" = queue.Queue(maxsize=size)
  _END = object()
  stop = threading.Event()
  error: List[BaseException] = []

  def _put(item) -> bool:
    while not stop.is_set():
      try:
        q.put(item, timeout=0.1)
        return True
      except queue.Full:
        continue
    return False

  def _worker():
    try:
      for item in stream:
        if not _put(item):
          return
    except BaseException as e:  # propagate into consumer
      error.append(e)
    finally:
      _put(_END)

  thread = threading.Thread(target=_worker, daemon=True)
  thread.start()
  # graftscope: how long the consumer stalls on the queue is THE input
  # pipeline health number (empty queue = host parse can't keep up).
  # Hot-path discipline (PERFORMANCE.md "telemetry overhead"): this loop
  # runs once per batch between the device dispatches, so it takes ONE
  # clock pair per item (shared by trace and histogram), gates the trace
  # write on `tracer.enabled` instead of allocating a no-op span, and
  # flushes wait times to the registry in blocks of `_FLUSH_EVERY`
  # (`Histogram.record_many`: one lock round trip per block, identical
  # statistics). Snapshots lag the live stream by at most one block;
  # the `finally` flush keeps totals exact at stream end.
  wait_hist = obs_metrics.histogram("data/prefetch_wait_ms")
  batch_counter = obs_metrics.counter("data/batches")
  tracer = obs_trace.get_tracer()
  pending_ms: List[float] = []
  perf_counter_ns = time.perf_counter_ns
  try:
    while True:
      t0 = perf_counter_ns()
      item = q.get()
      dur_ns = perf_counter_ns() - t0
      if tracer.enabled:
        tracer.add_complete("data/prefetch_wait", t0, dur_ns, cat="data")
      if item is _END:
        if error:
          raise error[0]
        return
      pending_ms.append(dur_ns * 1e-6)
      if len(pending_ms) >= _FLUSH_EVERY:
        wait_hist.record_many(pending_ms)
        batch_counter.inc(len(pending_ms))
        pending_ms.clear()
      yield item
  finally:
    stop.set()
    if pending_ms:
      wait_hist.record_many(pending_ms)
      batch_counter.inc(len(pending_ms))


@config.configurable
class RecordBatchPipeline:
  """records -> shuffled -> batched -> parsed -> preprocessed batches.

  Supports multi-dataset zip (aligned files per `dataset_key`) and weighted
  mixture sampling across dataset groups (reference
  `WeightedRecordInputGenerator`,
  /root/reference/input_generators/default_input_generator.py:228-314).

  Staging plane: with the native toolchain present, the single-dataset
  records->batch path runs on the C++ `BatchStager` (`data/stager.py`:
  GIL-free interleave + shuffle + batch assembly, whole batches handed
  over as one arena) and the pure-Python generator chain stays as the
  no-toolchain fallback — `use_native_stager` (None = auto) forces
  either side, which the parity tests use. Multi-dataset zip keeps the
  per-record Python zip but streams each dataset's records through the
  native plane in record mode.

  Overlap plane (`data/overlap.py`): with `overlap` on (None = auto:
  whenever `prefetch_size` > 0), iteration returns an
  `OverlappedLoader` — arena/record parsing runs on an ordered
  `num_parallel_parses`-thread pool and preprocessing on its own worker
  downstream of the staging plane, with bounded stop-aware hand-off
  queues (`overlap_queue_mb` byte-caps the preprocessed-batch queue),
  so the consumer only ever dequeues finished batches. Output is
  byte-identical to the serial chain over the same record stream (same
  seeds, same order; tests/test_overlap.py pins it). The returned
  iterator has `close()` joining every stage thread — callers that
  abandon iteration early (finished eval rounds) should close it; the
  train loop's DevicePrefetcher does so on its own close.
  `overlap=False` restores the serial generator chain, which the
  data-bench A/B and parity tests use.
  """

  def __init__(self,
               file_patterns: Union[str, Sequence[str], Mapping[str, Any]],
               parse_fn: parsing.ParseFn,
               batch_size: int,
               mode: str = "train",
               shuffle_buffer_size: int = 512,
               cycle_length: int = 4,
               drop_remainder: bool = True,
               repeat: bool = True,
               seed: Optional[int] = None,
               preprocess_fn: Optional[PreprocessFn] = None,
               mixture_weights: Optional[Sequence[float]] = None,
               prefetch_size: int = 2,
               num_parallel_parses: int = 2,
               process_index: int = 0,
               process_count: int = 1,
               use_native_stager: Optional[bool] = None,
               overlap: Optional[bool] = None,
               overlap_queue_mb: Optional[float] = None,
               fused_preprocess: Optional[bool] = None,
               max_corrupt_records: int = 0):
    self._parse_fn = parse_fn
    self._batch_size = batch_size
    self._mode = mode
    self._train = mode == "train"
    self._shuffle_buffer_size = shuffle_buffer_size if self._train else 0
    self._cycle_length = cycle_length
    self._drop_remainder = drop_remainder
    self._repeat = repeat and self._train
    self._seed = seed
    self._preprocess_fn = preprocess_fn
    self._mixture_weights = mixture_weights
    self._prefetch_size = prefetch_size
    self._num_parallel_parses = num_parallel_parses
    self._use_native_stager = use_native_stager
    self._overlap = overlap
    self._overlap_queue_bytes = (
        overlap_lib.DEFAULT_QUEUE_BYTES if overlap_queue_mb is None
        else max(int(overlap_queue_mb * (1 << 20)), 1))
    self._fused_preprocess = fused_preprocess
    # graftguard degradation quota (module docstring): total RECORDS
    # allowed to be dropped over this pipeline's lifetime before a
    # parse/preprocess/source failure raises. 0 = strict.
    self._max_corrupt_records = max(int(max_corrupt_records), 0)
    self._corrupt_records_seen = 0
    self._corrupt_lock = threading.Lock()
    self._warned_stager_unavailable = False
    dataset_keys = parse_fn.dataset_keys
    if isinstance(file_patterns, Mapping):
      resolved = {
          k: _resolve_file_patterns_sharded(v, process_index, process_count)
          for k, v in file_patterns.items()}
    else:
      if len(dataset_keys) > 1:
        raise ValueError(
            f"Specs use dataset keys {dataset_keys}; pass a mapping of "
            "dataset_key -> file patterns.")
      resolved = {
          dataset_keys[0]: _resolve_file_patterns_sharded(
              file_patterns, process_index, process_count)}
    self._files = {k: files for k, (files, _) in resolved.items()}
    # Fewer files than hosts: every co-hosted process reads the SAME
    # file list, so each offsets its epoch shuffle seed by its
    # process_index (one offset pipeline-wide — multi-dataset zip
    # streams must keep using one common seed or their file orders
    # de-align). Sharded hosts keep offset 0: their record orders
    # already differ by construction, and the round-1..5 seed behavior
    # is preserved.
    self._host_seed_offset = (
        process_index * 1_000_003
        if any(shared for _, shared in resolved.values()) else 0)
    unknown = set(self._files) - set(dataset_keys)
    if unknown:
      raise ValueError(
          f"File patterns given for unknown dataset keys {sorted(unknown)}; "
          f"specs define {dataset_keys}.")

  @property
  def batch_size(self) -> int:
    return self._batch_size

  def _stager_enabled(self) -> bool:
    if self._use_native_stager is not None:
      if self._use_native_stager and not stager_lib.stager_available():
        # Loud once per pipeline: an explicit force of the native plane
        # that cannot be honored is a deployment misconfiguration (no
        # toolchain / broken build), and the ~2x-slower Python chain
        # would otherwise engage with no signal beyond absent data/*
        # telemetry. Auto mode (None) stays a silent fallback by design.
        if not self._warned_stager_unavailable:
          self._warned_stager_unavailable = True
          logging.warning(
              "use_native_stager=True but the native toolchain is "
              "unavailable; falling back to the pure-Python record "
              "chain (expect ~2x lower host staging throughput).")
        return False
      return self._use_native_stager
    return stager_lib.stager_available()

  def _epoch_seed(self, epoch: int) -> Optional[int]:
    return (None if self._seed is None
            else self._seed + epoch + self._host_seed_offset)

  # -- graftguard degradation (module docstring) ----------------------------

  def _charge_quota(self, exc: BaseException, what: str) -> bool:
    """Charges one batch's worth of records against the corruption
    quota; False when the quota is off or exceeded (the caller must
    raise). Thread-safe — the overlap plane calls this from pool
    threads. The accounting unit is the batch's records (`batch_size`;
    a corrupt record costs its batch — the parse unit)."""
    if self._max_corrupt_records <= 0:
      return False
    with self._corrupt_lock:
      self._corrupt_records_seen += self._batch_size
      over = self._corrupt_records_seen > self._max_corrupt_records
    if over:
      logging.error(
          "data: corrupt-record quota exceeded (%d records skipped > "
          "max_corrupt_records=%d); surfacing %s", self._corrupt_records_seen,
          self._max_corrupt_records, type(exc).__name__)
      return False
    logging.warning("data: skipped %s under quota (%s: %s)", what,
                    type(exc).__name__, exc)
    return True

  def _absorb_batch_error(self, exc: BaseException) -> bool:
    """Decides whether a failed parse/preprocess batch is SKIPPED
    (True: counted against the record quota as corrupt records) or
    must raise (False: quota disabled or exceeded)."""
    if not self._charge_quota(exc, "a corrupt batch"):
      return False
    obs_metrics.counter("data/corrupt_records_skipped").inc(self._batch_size)
    obs_metrics.counter("data/corrupt_batches_skipped").inc()
    return True

  def _absorb_source_error(self, exc: BaseException) -> bool:
    """A record-source I/O error ends the CURRENT epoch early instead
    of killing the run (the remaining epoch records are charged as one
    batch against the same quota); False past the quota or when the
    quota is off. Counted ONLY as `data/source_io_errors` — an I/O
    flake is not data corruption, and conflating the counters would
    point a dashboard at the wrong failure."""
    if not self._charge_quota(exc, "the rest of the epoch (source I/O)"):
      return False
    obs_metrics.counter("data/source_io_errors").inc()
    return True

  def _inject_record_faults(self, stream: Iterator[Any]) -> Iterator[Any]:
    """`data.record_io` faultlab seam: the stream raises a (real-
    IOError-subclass) injected error mid-epoch. Wrapped only while a
    plan is active, so the steady-state chain pays nothing."""
    for item in stream:
      if faultlab_lib.maybe_fire(faultlab_lib.DATA_RECORD_IO) is not None:
        raise faultlab_lib.InjectedIOError(
            "faultlab: injected record-source I/O error")
      yield item

  def _guarded(self, fn):
    """Quota-absorbing wrapper for the serial parse/preprocess chain:
    a failed batch becomes the `_SKIP` sentinel (filtered before the
    consumer) while the quota holds."""
    def inner(batch):
      if batch is _SKIP:
        return _SKIP
      try:
        return fn(batch)
      except (KeyboardInterrupt, SystemExit):
        raise
      except BaseException as e:  # noqa: BLE001 - quota decides
        if self._absorb_batch_error(e):
          return _SKIP
        raise
    return inner

  def _epoch_files(self, files: Sequence[str],
                   epoch_seed: Optional[int]) -> List[str]:
    """Final per-epoch file order: train mode shuffles in Python with
    the epoch seed on BOTH staging planes, so native/Python file order
    is identical (`interleave_records` shuffle_files parity)."""
    files = list(files)
    if self._train:
      random.Random(epoch_seed).shuffle(files)
    return files

  def _interleave(self, files: Sequence[str],
                  epoch_seed: Optional[int]) -> Iterator[bytes]:
    """Per-dataset record stream: native record-mode staging when the
    toolchain is present, the Python generator chain otherwise."""
    files = self._epoch_files(files, epoch_seed)
    if self._stager_enabled() and files:
      stream: Iterator[bytes] = stager_lib.iter_staged_records(
          files, self._cycle_length)
    else:
      stream = interleave_records(files, self._cycle_length)
    if faultlab_lib.active() is not None:
      stream = self._inject_record_faults(stream)
    return stream

  def _record_tuples(self, epoch_seed: Optional[int]
                     ) -> Iterator[Dict[str, bytes]]:
    """Yields aligned {dataset_key: record} tuples for one pass."""
    if self._mixture_weights is not None:
      # Weighted sampling across dataset groups: each group is a separate
      # mixture source; all specs must share one dataset_key in this mode.
      raise NotImplementedError(
          "mixture_weights are handled by WeightedRecordPipeline.")
    streams = {k: self._interleave(files, epoch_seed)
               for k, files in self._files.items()}
    keys = list(streams)
    while True:
      item = {}
      try:
        for k in keys:
          item[k] = next(streams[k])
      except StopIteration:
        return
      yield item

  def _raw_batches(self) -> Iterator[Any]:
    """Raw record batches: `List[{dataset_key: record}]` on the Python
    chain, `stager.StagedBatch` arenas on the native plane (single
    dataset only — the zip path must align records across keys one at a
    time). `_parse_only` consumes either shape."""
    single_key = (len(self._files) == 1 and self._mixture_weights is None)
    epoch = 0
    while True:
      epoch_seed = self._epoch_seed(epoch)
      files = next(iter(self._files.values())) if single_key else None
      try:
        if files and self._stager_enabled():
          epoch_batches: Iterator[Any] = stager_lib.stage_batches(
              self._epoch_files(files, epoch_seed),
              batch_size=self._batch_size,
              cycle_length=self._cycle_length,
              shuffle_buffer=self._shuffle_buffer_size,
              seed=epoch_seed,
              drop_remainder=self._drop_remainder)
          if faultlab_lib.active() is not None:
            epoch_batches = self._inject_record_faults(epoch_batches)
          yield from epoch_batches
        else:
          stream: Iterator[Dict[str, bytes]] = self._record_tuples(epoch_seed)
          if self._shuffle_buffer_size:
            stream = shuffled(stream, self._shuffle_buffer_size, epoch_seed)
          yield from _batched(stream, self._batch_size, self._drop_remainder)
      except (IOError, OSError) as e:
        # graftguard: a mid-epoch source I/O error (rotten shard, NFS
        # hiccup, an injected data.record_io fault) ends THIS epoch
        # early under the counted quota; strict mode re-raises.
        if not self._absorb_source_error(e):
          raise
      if not self._repeat:
        return
      epoch += 1

  def _overlap_enabled(self, prefetch_size: int) -> bool:
    """The overlap-plane decision: explicit `overlap` wins; auto (None)
    pipelines whenever the caller wants background behavior at all
    (`prefetch_size` > 0). `overlap=False` keeps the serial generator
    chain — the data-bench A/B and the parity tests force it."""
    if self._overlap is not None:
      return self._overlap
    return prefetch_size > 0

  def _fuse_preprocess_enabled(self) -> bool:
    """The fused-preprocess decision (ROADMAP item 6's last slice):
    explicit `fused_preprocess` wins; auto (None) fuses preprocess into
    the parse pool ONLY when purity is declared — the preprocess fn is
    a bound method of an `AbstractPreprocessor` (whose `_preprocess_fn`
    contract is "a pure function over SpecStructs", preprocessors/
    base.py) or the fn carries a truthy `stateless` attribute; a bare
    callable may close over cross-batch state, so it keeps the serial
    preprocess worker and its deterministic consumption order."""
    if self._fused_preprocess is not None:
      return self._fused_preprocess
    fn = self._preprocess_fn
    if fn is None:
      return True  # identity preprocess: trivially pure
    if getattr(fn, "stateless", False):
      return True
    from tensor2robot_tpu.preprocessors import base as preprocessors_base

    return isinstance(getattr(fn, "__self__", None),
                      preprocessors_base.AbstractPreprocessor)

  def _assemble(self, raw: Iterator[Any],
                prefetch_size: Optional[int] = None,
                num_parallel_parses: Optional[int] = None
                ) -> Iterator[specs_lib.SpecStruct]:
    """raw record-tuple batches -> parsed+preprocessed (+prefetched)
    batches. Parsing runs in parallel; preprocessing stays serial in
    consumption order so stateful/seeded preprocessors keep
    deterministic behavior. Shared with WeightedRecordPipeline, which
    passes its OWN `num_parallel_parses` as a parameter — overwriting
    this pipeline's attribute instead (the pre-round-6 behavior) leaked
    the override into the template source's later iterations.

    With the overlap plane on this returns an `OverlappedLoader`
    (parse pool + preprocess worker + byte-capped hand-off queues,
    `data/overlap.py`) whose output is byte-identical to the serial
    chain below; otherwise the legacy chain: ordered parallel parse map
    + serial preprocess + `prefetch` thread."""
    workers = (self._num_parallel_parses if num_parallel_parses is None
               else num_parallel_parses)
    size = self._prefetch_size if prefetch_size is None else prefetch_size
    degrade = self._max_corrupt_records > 0
    if self._overlap_enabled(size):
      return overlap_lib.OverlappedLoader(
          iter(raw), self._parse_only, self._apply_preprocess,
          parse_workers=max(workers, 1), depth=max(size, 1),
          max_bytes=self._overlap_queue_bytes,
          fuse_preprocess=self._fuse_preprocess_enabled(),
          skip_batch_on_error=(self._absorb_batch_error if degrade
                               else None))
    if workers > 1:
      parse = self._guarded(self._parse_only) if degrade else self._parse_only
      parsed = parallel_map_ordered(parse, raw, num_workers=workers)
      preprocess = (self._guarded(self._apply_preprocess) if degrade
                    else self._apply_preprocess)
      stream: Iterator[specs_lib.SpecStruct] = map(preprocess, parsed)
    else:
      finalize = self._guarded(self._finalize) if degrade else self._finalize
      stream = map(finalize, raw)
    if degrade:
      stream = (batch for batch in stream if batch is not _SKIP)
    if size:
      stream = prefetch(stream, size)
    return stream

  def _parse_only(self, batch: Any) -> specs_lib.SpecStruct:
    if faultlab_lib.maybe_fire(faultlab_lib.DATA_CORRUPT_RECORD) is not None:
      batch = _corrupted_copy(batch)
    if isinstance(batch, stager_lib.StagedBatch):
      # Arena batch from the native staging plane: hand it through
      # whole — the native parser reads records in place (parse_arena),
      # fallback paths materialize bytes themselves. Keyed by the
      # pipeline's OWN single files key, not dataset_keys[0]: specs may
      # declare several keys while this pipeline feeds just one of
      # them, and the Python chain parses under that same key.
      return self._parse_fn.parse_batch(
          {next(iter(self._files)): batch})
    records = {k: [item[k] for item in batch] for k in batch[0]}
    return self._parse_fn.parse_batch(records)

  def _apply_preprocess(self, parsed: specs_lib.SpecStruct
                        ) -> specs_lib.SpecStruct:
    if faultlab_lib.maybe_fire(faultlab_lib.DATA_PREPROCESS) is not None:
      raise faultlab_lib.InjectedPreprocessError(
          "faultlab: injected preprocess failure")
    features = parsed["features"] if "features" in parsed \
        else specs_lib.SpecStruct()
    labels = parsed["labels"] if "labels" in parsed else specs_lib.SpecStruct()
    features = specs_lib.flatten_spec_structure(features)
    labels = specs_lib.flatten_spec_structure(labels)
    if self._preprocess_fn is not None:
      features, labels = self._preprocess_fn(features, labels, self._mode)
    out = specs_lib.SpecStruct()
    out["features"] = features
    if len(labels):
      out["labels"] = labels
    return out

  def _finalize(self, batch: List[Dict[str, bytes]]) -> specs_lib.SpecStruct:
    return self._apply_preprocess(self._parse_only(batch))

  def __iter__(self) -> Iterator[specs_lib.SpecStruct]:
    return self._assemble(self._raw_batches())


class WeightedRecordPipeline:
  """Samples each record from one of several pipelines by weight
  (reference WeightedRecordInputGenerator semantics,
  /root/reference/input_generators/default_input_generator.py:228-314).

  Training mode shuffles each source through its own buffer and refills
  exhausted sources forever. Non-train modes are deterministic and
  terminating: no shuffling, a seeded sampling sequence, and each source
  contributes exactly one pass — when a source exhausts, sampling
  renormalizes over the remainder, and iteration ends once every source
  has been consumed. Batches flow through the same parallel-parse and
  prefetch stages as RecordBatchPipeline.
  """

  def __init__(self,
               file_pattern_groups: Sequence[Union[str, Sequence[str]]],
               weights: Sequence[float],
               parse_fn: parsing.ParseFn,
               batch_size: int,
               mode: str = "train",
               shuffle_buffer_size: int = 512,
               drop_remainder: bool = True,
               repeat: bool = True,
               seed: Optional[int] = None,
               prefetch_size: int = 2,
               num_parallel_parses: int = 2,
               **kwargs):
    if len(file_pattern_groups) != len(weights):
      raise ValueError("One weight per file-pattern group required.")
    if any(w < 0 for w in weights) or sum(weights) <= 0:
      raise ValueError(f"Weights must be non-negative with a positive "
                       f"sum, got {list(weights)}.")
    total = float(sum(weights))
    self._weights = np.asarray([w / total for w in weights], np.float64)
    self._batch_size = batch_size
    self._mode = mode
    self._train = mode == "train"
    self._shuffle_buffer_size = shuffle_buffer_size if self._train else 0
    self._drop_remainder = drop_remainder
    self._repeat = repeat and self._train
    self._seed = seed
    self._prefetch_size = prefetch_size
    self._num_parallel_parses = num_parallel_parses
    self._sources = [
        RecordBatchPipeline(patterns, parse_fn, batch_size=1,
                            mode=mode, drop_remainder=False, seed=seed,
                            **kwargs)
        for patterns in file_pattern_groups]
    self._parse_fn = parse_fn

  def _source_iter(self, idx: int, epoch: int) -> Iterator[Dict[str, bytes]]:
    # The source's _host_seed_offset rides along, mirroring
    # RecordBatchPipeline._epoch_seed: on the shared-files path (fewer
    # files than hosts) co-hosted processes must not read identical
    # record orders, and this path drives the source's _record_tuples
    # directly, bypassing its own _epoch_seed.
    source = self._sources[idx]
    seed = (None if self._seed is None
            else self._seed + 7919 * idx + 104_729 * epoch
            + source._host_seed_offset)
    stream = source._record_tuples(seed)
    if self._shuffle_buffer_size:
      stream = shuffled(stream, self._shuffle_buffer_size, seed)
    return iter(stream)

  def _record_stream(self) -> Iterator[Dict[str, bytes]]:
    rng = np.random.RandomState(self._seed)
    n = len(self._sources)
    iterators = [self._source_iter(i, 0) for i in range(n)]
    epochs = [0] * n
    # Zero-weight sources are never sampled (reference semantics), so
    # they start dead — otherwise non-train termination would divide by
    # a zero probability mass once the weighted sources exhaust.
    alive = self._weights > 0
    while alive.any():
      p = self._weights * alive
      idx = int(rng.choice(n, p=p / p.sum()))
      refilled = False
      while True:
        try:
          yield next(iterators[idx])
          break
        except StopIteration:
          if not self._repeat or refilled:  # one pass, or empty source
            alive[idx] = False
            break
          epochs[idx] += 1
          iterators[idx] = self._source_iter(idx, epochs[idx])
          refilled = True

  def _raw_batches(self) -> Iterator[List[Dict[str, bytes]]]:
    return _batched(self._record_stream(), self._batch_size,
                    self._drop_remainder)

  def __iter__(self) -> Iterator[specs_lib.SpecStruct]:
    # The first source is used as the parse/preprocess TEMPLATE only;
    # this pipeline's parallelism rides along as a parameter so the
    # template's own configuration is never mutated (a second iteration
    # or a caller sharing the source used to see the overwritten value).
    return self._sources[0]._assemble(
        self._raw_batches(), prefetch_size=self._prefetch_size,
        num_parallel_parses=self._num_parallel_parses)
