"""Native batched record staging: the host data plane's fast path.

Python-side seam over the C++ `BatchStager` (`native/batch_stager.cc`):
file interleave, reservoir shuffle and batch assembly run on GIL-released
worker threads, and Python receives ONE contiguous arena (+ offsets/
lengths) per batch instead of paying a Python frame per record through
the `interleave_records -> shuffled -> _batched` generator chain. The
arena feeds `BatchExampleParser.parse_arena` directly, so the whole
records->parsed-batch path costs a handful of ctypes calls per batch.

Semantics are pinned against the pure-Python chain by
tests/test_stager.py: identical interleave order (eval mode is
byte-identical end to end), same shuffle distribution and per-seed
determinism in train mode, `_batched` drop_remainder behavior, and
IOError on corruption — `data/pipeline.py` keeps the Python chain as
the no-toolchain fallback.

graftscope telemetry (flows into runs.jsonl via the standard registry
snapshot, gated by `graftscope diff` like any other metric):
  data/stage_ms            consumer wait per staged batch (high = the
                           C++ plane can't keep up; the inverse of
                           data/prefetch_wait_ms one stage downstream)
  data/arena_bytes         payload bytes per staged batch
  data/stager_queue_depth  staged batches waiting in the C++ queue
                           (0 in steady state = Python is the slower
                           side; == queue_depth = staging is)
  data/staged_batches      batches handed to Python

Reference path shape: /root/reference/utils/tfdata.py:174-210 (parallel
interleave) and :629-689 (shuffle/batch options).
"""

from __future__ import annotations

import time
from typing import Iterator, List, Optional, Sequence

import numpy as np

from tensor2robot_tpu import native
from tensor2robot_tpu.obs import metrics as obs_metrics

__all__ = ["StagedBatch", "stager_available", "stage_batches",
           "iter_staged_records"]

# Record-mode streaming (`iter_staged_records`) chunking: up to
# _RECORD_CHUNK records per staged chunk (amortizes the per-chunk Python
# cost on small records) but never much past _RECORD_CHUNK_BYTES of
# payload — the byte cap also bounds the C++ reader queues, so host RSS
# stays ~O(cycle_length + queue_depth) chunks even on multi-MB episode
# records (a count-only bound buffered GiBs there; the Python chain it
# replaces buffered ~one record per active file).
_RECORD_CHUNK = 256
_RECORD_CHUNK_BYTES = 8 << 20  # 8 MiB


class StagedBatch:
  """One staged batch: contiguous payload arena + per-record offsets.

  `arena` is a uint8 numpy array owned by Python (one memcpy out of the
  native buffer); `offsets`/`lengths` are int64 arrays indexing into
  it. `records()` materializes per-record bytes for consumers that need
  them (the Python parse fallback); the fast path hands the arrays to
  `BatchExampleParser.parse_arena` untouched.
  """

  __slots__ = ("arena", "offsets", "lengths")

  def __init__(self, arena: np.ndarray, offsets: np.ndarray,
               lengths: np.ndarray):
    self.arena = arena
    self.offsets = offsets
    self.lengths = lengths

  def __len__(self) -> int:
    return len(self.offsets)

  def records(self) -> List[bytes]:
    view = memoryview(self.arena)
    return [bytes(view[o:o + n]) for o, n in
            zip(self.offsets.tolist(), self.lengths.tolist())]


def stager_available() -> bool:
  """True when the native staging plane can be used (toolchain built)."""
  return native.available()


def stage_batches(files: Sequence[str],
                  batch_size: int,
                  cycle_length: int = 4,
                  shuffle_buffer: int = 0,
                  seed: Optional[int] = None,
                  drop_remainder: bool = True,
                  verify_crc: bool = False,
                  queue_depth: int = 2,
                  max_chunk_bytes: int = 0,
                  telemetry: bool = True) -> Iterator[StagedBatch]:
  """Streams `StagedBatch`es for ONE pass over `files` (final order —
  per-epoch file shuffling stays in the caller, keeping train-mode file
  order identical to the Python chain's). Raises IOError on corruption.

  `seed` drives the C++ reservoir shuffle (std::mt19937_64): same
  distribution as `pipeline.shuffled` and deterministic per seed, not
  the identical permutation. None seeds from the clock (train-mode
  parity with `shuffled(seed=None)`); shuffle_buffer 0 bypasses the
  shuffle entirely, so eval mode is byte-identical to the Python chain.

  `telemetry=False` skips the `data/*` metrics: the documented unit of
  those gauges is PIPELINE batches, so internal consumers staging
  implementation-detail chunks (`iter_staged_records`) must not feed
  them — mixed units would turn a zip-vs-single-dataset `graftscope
  diff` into phantom regressions.

  `max_chunk_bytes` > 0 byte-bounds staging (reader queues + EARLY batch
  flush at that arena size). Record-mode only: an early flush would
  break exact `batch_size` semantics, so pipeline batch staging must
  leave it 0.
  """
  if seed is None:
    seed = time.time_ns() & (2**63 - 1)
  if telemetry:
    stage_hist = obs_metrics.histogram("data/stage_ms")
    arena_hist = obs_metrics.histogram("data/arena_bytes")
    depth_gauge = obs_metrics.gauge("data/stager_queue_depth")
    batch_counter = obs_metrics.counter("data/staged_batches")
  perf_counter_ns = time.perf_counter_ns
  with native.RecordStager(list(files), batch_size=batch_size,
                           cycle_length=cycle_length,
                           shuffle_buffer=shuffle_buffer, seed=seed,
                           drop_remainder=drop_remainder,
                           verify_crc=verify_crc,
                           queue_depth=queue_depth,
                           max_chunk_bytes=max_chunk_bytes) as stager:
    while True:
      t0 = perf_counter_ns()
      out = stager.next_batch()
      if telemetry:
        stage_hist.record((perf_counter_ns() - t0) * 1e-6)
      if out is None:
        return
      arena, offsets, lengths = out
      if telemetry:
        arena_hist.record(float(arena.nbytes))
        depth_gauge.set(float(stager.queue_depth()))
        batch_counter.inc()
      yield StagedBatch(arena, offsets, lengths)


def iter_staged_records(files: Sequence[str],
                        cycle_length: int = 4,
                        verify_crc: bool = False,
                        chunk_records: int = _RECORD_CHUNK,
                        chunk_bytes: int = _RECORD_CHUNK_BYTES
                        ) -> Iterator[bytes]:
  """Record-mode streaming through the native plane (no shuffle/batch):
  byte-identical to `pipeline.interleave_records` over the same file
  order, but with the file IO, CRC and interleave running GIL-free.
  Used by consumers that must stay per-record (the weighted-mixture
  sampler, multi-dataset zip). Chunk boundaries are an implementation
  detail (`chunk_bytes` caps buffered payload regardless of record
  size); the flattened record stream is invariant to them."""
  for batch in stage_batches(files, batch_size=chunk_records,
                             cycle_length=cycle_length, shuffle_buffer=0,
                             seed=0, drop_remainder=False,
                             verify_crc=verify_crc,
                             max_chunk_bytes=chunk_bytes,
                             telemetry=False):
    yield from batch.records()
