"""Spec-driven batched record parsing.

JAX-native re-design of the reference's TFExample auto-parser
(/root/reference/utils/tfdata.py:273-543): from feature/label spec
structures it generates a parse function mapping a batch of serialized
records to a SpecStruct of batched numpy arrays, handling:

* Example and SequenceExample records (`is_sequence` specs);
* fixed-length and variable-length features (VarLen pad/clip with
  `varlen_default_value`, reference :508-513);
* batched image decode for jpeg/png/bmp/gif specs with the reference's
  empty-string -> zeros fallback (:426-484);
* bfloat16 specs parsed as float32 then cast (TPU infeed dtype policy);
* multi-dataset joins: specs with different `dataset_key`s parse from
  separate record streams zipped together (:515-541);
* `<key>_length` side outputs for sequence specs (:369-383).

The parse runs on host CPU (numpy), keeping decode off-device so it
overlaps with TPU compute (SURVEY.md §7 hard parts).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple, Union

import numpy as np

from tensor2robot_tpu import native
from tensor2robot_tpu import specs as specs_lib
from tensor2robot_tpu.data import codec, example_pb2

__all__ = ["create_parse_fn", "ParseFn"]

# Native-path bytes-value capacity for is_extracted raw planes: planes
# split across more values than this re-parse on the Python path (the
# native parser stores at most `cap` values per feature), with a logged
# warning when mismatches disable the fast path for the stream.
_EXTRACTED_VALUE_CAP = 4

# Consecutive mismatched batches before the native parser is disabled
# for a stream. A single anomalous record only downgrades ITS batch;
# a stream that is legacy-format throughout stops paying for the wasted
# native pass after this many batches in a row fall back.
_NATIVE_DISABLE_STREAK = 3
# Non-consecutive mismatch budget: a shuffle-merge of legacy and
# new-format shards interleaves mismatches with good batches, so the
# streak alone would never trip. Disable once this many batches have
# fallen back overall AND mismatches are at least _NATIVE_DISABLE_RATIO
# of all batches attempted natively — the ratio guard keeps a
# multi-day stream with rare anomalous records (say 1 bad batch per
# 10k) on the fast path for its lifetime, while a genuinely mixed
# stream (a legacy shard merge runs ~50% mismatched) still trips.
_NATIVE_DISABLE_TOTAL = 20
_NATIVE_DISABLE_RATIO = 0.25


class _NativeFormatMismatch(Exception):
  """Wire data the native columnar parser cannot surface (e.g. a raw
  plane stored as float_list by legacy writers): retry on the Python
  path, which parses any wire kind."""


@dataclasses.dataclass
class _LeafPlan:
  out_key: str
  feature_name: str
  spec: specs_lib.TensorSpec
  parse_dtype: np.dtype  # dtype to materialize from the wire


def _plan_for(flat_specs: specs_lib.SpecStruct) -> List[_LeafPlan]:
  plans = []
  for key, spec in flat_specs.items():
    name = spec.name or key.rsplit("/", 1)[-1]
    parse_dtype = spec.dtype
    if parse_dtype == specs_lib._canonical_dtype("bfloat16"):
      parse_dtype = np.dtype(np.float32)
    plans.append(_LeafPlan(key, name, spec, parse_dtype))
  return plans


def _feature_values(feature: "example_pb2.Feature") -> Tuple[str, Sequence]:
  kind = feature.WhichOneof("kind")
  if kind == "float_list":
    return kind, feature.float_list.value
  if kind == "int64_list":
    return kind, feature.int64_list.value
  if kind == "bytes_list":
    return kind, feature.bytes_list.value
  return "missing", ()


def _num_image_channels(spec: specs_lib.TensorSpec) -> Optional[int]:
  if spec.shape and spec.shape[-1] in (1, 3):
    return spec.shape[-1]
  return None


def _shaped(values: Sequence, plan: _LeafPlan,
            shape: Tuple[Optional[int], ...]) -> np.ndarray:
  """Reshapes/pads/clips raw wire values to the spec shape."""
  spec = plan.spec
  array = np.asarray(values, dtype=plan.parse_dtype)
  expected = int(np.prod([d for d in shape if d is not None], dtype=np.int64))
  has_unknown = any(d is None for d in shape)
  if not has_unknown:
    if array.size == expected:
      return array.reshape(shape)
    if spec.varlen_default_value is not None:
      flat = np.full(expected, spec.varlen_default_value,
                     dtype=plan.parse_dtype)
      n = min(array.size, expected)
      flat[:n] = array.ravel()[:n]  # clip or pad (reference :508-513)
      return flat.reshape(shape)
    raise ValueError(
        f"Feature {plan.feature_name!r} has {array.size} values, spec "
        f"{plan.out_key!r} expects {expected} ({spec!r}). Set "
        "varlen_default_value to enable pad/clip.")
  # Unknown leading dim: infer it from the payload.
  known = int(np.prod([d for d in shape if d is not None], dtype=np.int64))
  if known == 0 or array.size % known != 0:
    raise ValueError(
        f"Cannot infer unknown dim for {plan.out_key!r}: {array.size} "
        f"values vs known element count {known}.")
  inferred = array.size // known
  concrete = tuple(inferred if d is None else d for d in shape)
  return array.reshape(concrete)


def _native_jpeg_batch(flat_values: List[bytes], plan: _LeafPlan
                       ) -> Optional[np.ndarray]:
  """GIL-free libjpeg batch decode for fixed-shape uint8 jpeg specs;
  None -> caller uses the PIL path (empty/pad payloads, other formats,
  dynamic shapes, or no libjpeg build). The decode thread pool is what
  actually scales host image throughput — Python-level threading over
  PIL measured ~1x (PERFORMANCE.md)."""
  spec = plan.spec
  if (spec.data_format or "").lower() not in ("jpeg", "jpg"):
    return None
  if plan.parse_dtype != np.uint8:
    return None
  shape = spec.shape[-3:]
  if len(shape) != 3 or any(d is None for d in shape) \
      or shape[-1] not in (1, 3):
    return None
  from tensor2robot_tpu import native

  return native.decode_jpeg_batch(flat_values, *shape)


def _decode_image_feature(values: Sequence[bytes], plan: _LeafPlan
                          ) -> np.ndarray:
  spec = plan.spec
  channels = _num_image_channels(spec)
  if len(values) == 0 or (len(values) == 1 and len(values[0]) == 0):
    # Reference fallback: empty string -> zeros (:426-484).
    concrete = tuple(1 if d is None else d for d in spec.shape)
    return np.zeros(concrete, dtype=plan.parse_dtype)
  if len(values) == 1:
    img = codec.decode_image(values[0], channels=channels)
    return img.astype(plan.parse_dtype)
  imgs = [codec.decode_image(v, channels=channels) for v in values]
  return np.stack(imgs).astype(plan.parse_dtype)


def _plane_from_values(values: Sequence[bytes],
                       plan: _LeafPlan) -> np.ndarray:
  """Raw-bytes tensor payload (e.g. pre-extracted uint8 image planes) —
  shared by the Python and native paths so value-join semantics cannot
  diverge. The common single-element case reads zero-copy from the
  proto bytes; joining would duplicate the whole plane."""
  buffer = values[0] if len(values) == 1 else b"".join(values)
  array = np.frombuffer(buffer, dtype=plan.parse_dtype)
  return _shaped(array, plan, plan.spec.shape)


def _parse_leaf_from_feature(feature, plan: _LeafPlan) -> np.ndarray:
  spec = plan.spec
  kind, values = _feature_values(feature)
  if spec.is_image and not spec.is_extracted:
    if kind not in ("bytes_list", "missing"):
      raise ValueError(
          f"Image spec {plan.out_key!r} expects bytes, got {kind}.")
    return _decode_image_feature(values, plan)
  if kind == "missing":
    if spec.is_optional:
      return None  # type: ignore[return-value]
    if spec.varlen_default_value is not None:
      return _shaped([], plan, spec.shape)
    raise ValueError(
        f"Record is missing required feature {plan.feature_name!r} "
        f"for spec {plan.out_key!r}.")
  if kind == "bytes_list" and plan.parse_dtype.kind in "SUO":
    array = np.asarray(list(values), dtype=object)
    return array if array.size != 1 else array.reshape(spec.shape or (1,))
  if kind == "bytes_list":
    return _plane_from_values(values, plan)
  return _shaped(values, plan, spec.shape)


def _pad_time(arrays: List[np.ndarray], time_dim: Optional[int],
              plan: _LeafPlan) -> np.ndarray:
  """Stacks per-record sequence arrays, padding/clipping the time dim."""
  max_t = time_dim if time_dim is not None else max(a.shape[0] for a in arrays)
  fill = plan.spec.varlen_default_value or 0
  out = []
  for a in arrays:
    if a.shape[0] > max_t:
      a = a[:max_t]
    elif a.shape[0] < max_t:
      pad_shape = (max_t - a.shape[0],) + a.shape[1:]
      a = np.concatenate(
          [a, np.full(pad_shape, fill, dtype=a.dtype)], axis=0)
    out.append(a)
  return np.stack(out)


class ParseFn:
  """Callable parsing batches of serialized records into spec layout."""

  def __init__(self,
               feature_spec: specs_lib.SpecStructLike,
               label_spec: Optional[specs_lib.SpecStructLike] = None):
    self._feature_spec = specs_lib.flatten_spec_structure(feature_spec)
    self._label_spec = (specs_lib.flatten_spec_structure(label_spec)
                        if label_spec is not None else specs_lib.SpecStruct())
    merged = specs_lib.SpecStruct()
    for key, spec in self._feature_spec.items():
      merged["features/" + key] = spec
    for key, spec in self._label_spec.items():
      merged["labels/" + key] = spec
    self._dataset_keys = specs_lib.dataset_keys(merged)
    self._plans: Dict[str, List[_LeafPlan]] = {}
    self._sequence_datasets: Dict[str, bool] = {}
    self._native_parsers: Dict[str, Any] = {}
    self._native_mismatch_streak: Dict[str, int] = {}
    self._native_mismatch_total: Dict[str, int] = {}
    self._native_batches_attempted: Dict[str, int] = {}
    for dkey in self._dataset_keys:
      subset = specs_lib.filter_by_dataset(merged, dkey)
      self._plans[dkey] = _plan_for(subset)
      # Two *incompatible* specs mapping to one wire key would silently
      # read the same feature; surface that at construction time.
      # Compatible duplicates are legal and intentional — e.g. MAML's
      # condition/ and inference/ subtrees both read the base feature.
      names: Dict[str, _LeafPlan] = {}
      for plan in self._plans[dkey]:
        other = names.get(plan.feature_name)
        if other is not None:
          compatible = (other.spec.shape == plan.spec.shape
                        and other.spec.dtype == plan.spec.dtype
                        and other.spec.is_sequence == plan.spec.is_sequence)
          if not compatible:
            raise ValueError(
                f"Specs {other.out_key!r} and {plan.out_key!r} both map to "
                f"wire feature {plan.feature_name!r} in dataset {dkey!r} "
                "with different shapes/dtypes; give them distinct names.")
          continue
        names[plan.feature_name] = plan
      self._sequence_datasets[dkey] = any(
          spec.is_sequence for spec in subset.values())
      self._native_parsers[dkey] = self._maybe_native_parser(
          self._plans[dkey])
      self._native_mismatch_streak[dkey] = 0
      self._native_mismatch_total[dkey] = 0
      self._native_batches_attempted[dkey] = 0

  def _maybe_native_parser(self, plans: List[_LeafPlan]):
    """Builds the C++ columnar parser when every leaf fits its profile:
    fixed-shape float/int features (context or fixed-T sequence),
    bytes/image features with a static value capacity (single images,
    multi-image lists, fixed-T image sequences), fixed-shape
    `is_extracted` raw planes (one contiguous single-copy batch
    buffer). Optionals, varlen, dynamic time dims, sequence/string
    extracted planes and string dtypes take the Python path."""
    if len({p.feature_name for p in plans}) != len(plans):
      # Duplicate wire names (e.g. MAML split subtrees): the native
      # name index is one-to-one, so take the Python path.
      return None
    native_plan = []
    for plan in plans:
      spec = plan.spec
      if spec.is_optional or spec.varlen_default_value is not None:
        return None
      if spec.is_extracted:
        # Pre-extracted raw planes: the wire value is a bytes blob. The
        # declared byte size makes the wrapper return the whole batch as
        # one contiguous buffer (single memmove per record) when every
        # record carries exactly one full-size value; planes split
        # across a few bytes values (cap 4, Python-path value-joining
        # parity) take the per-value path. Sequences, dynamic shapes and
        # non-numeric dtypes keep the Python path (frombuffer cannot
        # read strings/objects).
        if (spec.is_sequence or any(d is None for d in spec.shape)
            or plan.parse_dtype.kind in "SUO"
            or plan.parse_dtype.itemsize == 0):
          return None
        nbytes = (int(np.prod(spec.shape, dtype=np.int64))
                  * plan.parse_dtype.itemsize)
        native_plan.append(
            (plan.feature_name, native.KIND_BYTES, nbytes, False, 0,
             _EXTRACTED_VALUE_CAP))
        continue
      if spec.is_image:
        # Only the dims that size native buffers must be concrete: the
        # time dim for sequences and the leading N of multi-image lists.
        # H/W/C may stay dynamic (decode discovers them).
        if spec.is_sequence:
          if spec.shape[0] is None:
            return None  # dynamic time dim: python path
          cap = seq_len = int(spec.shape[0])
        elif len(spec.shape) >= 4:
          if spec.shape[0] is None:
            return None
          seq_len, cap = 0, int(spec.shape[0])  # [N, H, W, C] list
        else:
          seq_len, cap = 0, 1
        # Context images zero-fill when absent (the reference's
        # empty-string -> zeros fallback, honored by the Python path);
        # missing sequence features are an error on both paths.
        missing_ok = not spec.is_sequence
        native_plan.append(
            (plan.feature_name, native.KIND_BYTES, 0, missing_ok, seq_len,
             cap))
        continue
      if any(d is None for d in spec.shape):
        return None  # dynamic dims (incl. dynamic time): python path
      seq_len = int(spec.shape[0]) if spec.is_sequence else 0
      step_shape = spec.shape[1:] if spec.is_sequence else spec.shape
      size = (int(np.prod(step_shape, dtype=np.int64))
              if step_shape else 1)
      if plan.parse_dtype == np.float32:
        native_plan.append(
            (plan.feature_name, native.KIND_FLOAT, size, False, seq_len, 0))
      elif np.issubdtype(plan.parse_dtype, np.integer):
        native_plan.append(
            (plan.feature_name, native.KIND_INT64, size, False, seq_len, 0))
      else:
        return None
    try:
      if not native.available():
        return None
      return native.BatchExampleParser(native_plan)
    except Exception:
      return None

  def _parse_batch_native(self, dkey: str,
                          serialized_list: Sequence[bytes]
                          ) -> Dict[str, np.ndarray]:
    """Fast path: columnar native parse producing full batch arrays."""
    parser = self._native_parsers[dkey]
    plans = self._plans[dkey]
    if hasattr(serialized_list, "arena"):
      # Staged arena batch (data/stager.py): the parser reads straight
      # out of the contiguous arena — no per-record bytes objects on
      # the whole records->parsed-batch path.
      parsed = parser.parse_arena(serialized_list.arena,
                                  serialized_list.offsets,
                                  serialized_list.lengths)
    else:
      parsed = parser.parse(list(serialized_list))
    batch = len(serialized_list)
    out: Dict[str, np.ndarray] = {}
    for i, plan in enumerate(plans):
      spec = plan.spec
      if spec.is_extracted:
        planes_buf = parsed["bytes_planes"].get(i)
        if planes_buf is not None:
          # Contiguous single-copy path: the wrapper already memmoved
          # each full-size plane into one [batch, nbytes] buffer —
          # viewing/reshaping here costs nothing further.
          out[plan.out_key] = planes_buf.view(plan.parse_dtype).reshape(
              (batch,) + tuple(spec.shape))
          continue
        counts = parsed["bytes_counts"][i]
        if int(counts.max(initial=0)) > _EXTRACTED_VALUE_CAP:
          # The native parser stored only the first CAP values; the
          # Python path joins any number, so re-parse there.
          raise _NativeFormatMismatch(plan.feature_name)
        planes = []
        for values in parsed["bytes"][i]:
          if not values:
            # No bytes_list on the wire: legacy writers stored numeric
            # planes as float_list/int64_list, which the columnar
            # parser cannot surface — re-parse on the Python path.
            raise _NativeFormatMismatch(plan.feature_name)
          # Python-path parity via the shared helper (multiple values
          # concatenate; single values read without a join copy).
          planes.append(_plane_from_values(values, plan))
        out[plan.out_key] = np.stack(planes)
        continue
      if spec.is_image and not spec.is_extracted:
        if spec.is_sequence:
          step_plan = _LeafPlan(plan.out_key, plan.feature_name,
                                spec.replace(shape=spec.shape[1:]),
                                plan.parse_dtype)
          t = spec.shape[0]
          flat = [v for values in parsed["bytes"][i] for v in values]
          decoded = _native_jpeg_batch(flat, step_plan)
          if decoded is not None:
            out[plan.out_key] = decoded.reshape(
                (batch, t) + decoded.shape[1:])
          else:
            out[plan.out_key] = np.stack([
                np.stack([_decode_image_feature([v], step_plan)
                          for v in values])
                for values in parsed["bytes"][i]])
          # Python-path parity: lengths report the full step count, even
          # when the stored data is clipped to the spec's time dim.
          out[plan.out_key + "_length"] = parsed["step_counts"][i]
        elif len(spec.shape) >= 4:
          # The native parser stores at most `cap` values; more values on
          # the wire than the spec's leading dim is a loud error (the
          # Python path would stack them all and fail shape validation).
          counts = parsed["bytes_counts"][i]
          if int(counts.max(initial=0)) > spec.shape[0]:
            raise ValueError(
                f"Feature {plan.feature_name!r} has {int(counts.max())} "
                f"bytes values but spec {plan.out_key!r} expects at most "
                f"{spec.shape[0]}.")
          out[plan.out_key] = np.stack(
              [_decode_image_feature(values, plan)
               for values in parsed["bytes"][i]])
        else:
          counts = parsed["bytes_counts"][i]
          if int(counts.max(initial=0)) > 1:
            raise ValueError(
                f"Feature {plan.feature_name!r} has {int(counts.max())} "
                f"bytes values but spec {plan.out_key!r} is a single "
                "image.")
          flat = [values[0] if values else b""
                  for values in parsed["bytes"][i]]
          decoded = _native_jpeg_batch(flat, plan)
          if decoded is not None:
            out[plan.out_key] = decoded
          else:
            out[plan.out_key] = np.stack(
                [_decode_image_feature(values[:1] or [b""], plan)
                 for values in parsed["bytes"][i]])
        continue
      buf = parsed["float"].get(i)
      if buf is None:
        buf = parsed["int"][i]
      out[plan.out_key] = buf.reshape((batch,) + spec.shape)
      if spec.is_sequence:
        out[plan.out_key + "_length"] = parsed["step_counts"][i]
    return out

  @property
  def dataset_keys(self) -> Tuple[str, ...]:
    return self._dataset_keys

  def parse_single(self, records: Union[bytes, Mapping[str, bytes]]
                   ) -> specs_lib.SpecStruct:
    """Parses one record (or one record per dataset_key)."""
    batch = self.parse_batch(
        {k: [v] for k, v in records.items()}
        if isinstance(records, Mapping) else [records])
    out = specs_lib.SpecStruct()
    for key, value in batch.items():
      out[key] = value[0] if value is not None else None
    return out

  def parse_batch(self,
                  records: Union[Sequence[bytes],
                                 Mapping[str, Sequence[bytes]]]
                  ) -> specs_lib.SpecStruct:
    """Parses a batch; returns `features/...` + `labels/...` SpecStruct.

    `records` (or any mapping value) may be a sequence of serialized
    records OR a `data.stager.StagedBatch` arena — the native columnar
    parser then reads records in place (`parse_arena`); fallback paths
    materialize per-record bytes first.
    """
    if not isinstance(records, Mapping):
      if len(self._dataset_keys) > 1:
        raise ValueError(
            f"Multi-dataset specs {self._dataset_keys} require a mapping of "
            "dataset_key -> records.")
      records = {self._dataset_keys[0]: records}
    columns: Dict[str, List[Any]] = {}
    lengths: Dict[str, List[int]] = {}
    batched: Dict[str, np.ndarray] = {}  # native fast-path outputs
    batch_sizes = {k: len(v) for k, v in records.items()}
    if len(set(batch_sizes.values())) > 1:
      raise ValueError(f"Dataset batch sizes differ: {batch_sizes}")
    for dkey, serialized_list in records.items():
      if self._native_parsers.get(dkey) is not None:
        attempted = self._native_batches_attempted.get(dkey, 0) + 1
        self._native_batches_attempted[dkey] = attempted
        try:
          batched.update(self._parse_batch_native(dkey, serialized_list))
          self._native_mismatch_streak[dkey] = 0
          continue
        except _NativeFormatMismatch as mismatch:
          # Legacy wire kind (e.g. float_list plane) or over-cap value
          # splits: the Python path parses any wire format. Only THIS
          # batch falls back — one anomalous record must not downgrade
          # the whole stream. Two disable triggers bound the wasted
          # native passes: _NATIVE_DISABLE_STREAK mismatches in a row
          # (the stream carries that format throughout) and the
          # _NATIVE_DISABLE_TOTAL + _NATIVE_DISABLE_RATIO pair (legacy
          # shards shuffle-merged with new-format ones, where good
          # batches keep resetting the streak; the ratio guard keeps a
          # long stream with RARE anomalies on the fast path forever).
          # Loud on first fallback and on disable, debug in between:
          # the Python path is orders of magnitude slower, and a silent
          # downgrade would be undiagnosable — but one warning per
          # mismatched batch would spam a multi-hour run.
          streak = self._native_mismatch_streak.get(dkey, 0) + 1
          self._native_mismatch_streak[dkey] = streak
          total = self._native_mismatch_total.get(dkey, 0) + 1
          self._native_mismatch_total[dkey] = total
          detail = (
              f"feature {mismatch} uses a wire format it cannot surface "
              "(legacy float_list/int64_list plane, or a plane split "
              f"across >{_EXTRACTED_VALUE_CAP} bytes values)")
          if (streak >= _NATIVE_DISABLE_STREAK
              or (total >= _NATIVE_DISABLE_TOTAL
                  and total >= _NATIVE_DISABLE_RATIO * attempted)):
            logging.warning(
                "Native columnar parser disabled for dataset %r: %s in "
                "%d consecutive / %d total batches. Falling back to the "
                "Python parser for the rest of this stream — expect much "
                "lower host throughput.", dkey, detail, streak, total)
            self._native_parsers[dkey] = None
          elif total == 1:
            logging.warning(
                "Native columnar parser fell back to the Python path for "
                "one batch of dataset %r: %s. The native path stays "
                "enabled; %d consecutive mismatches, or %d total at "
                ">=%d%% of attempted batches, disable it (further "
                "per-batch fallbacks log at debug).",
                dkey, detail, _NATIVE_DISABLE_STREAK,
                _NATIVE_DISABLE_TOTAL,
                int(_NATIVE_DISABLE_RATIO * 100))
          else:
            logging.debug(
                "Native parser per-batch fallback for dataset %r: %s "
                "(streak %d, total %d).", dkey, detail, streak, total)
      plans = self._plans[dkey]
      is_sequence = self._sequence_datasets[dkey]
      if hasattr(serialized_list, "records"):
        # Python path over a staged arena batch (no native parser for
        # these specs, or a format-mismatch fallback): materialize the
        # per-record bytes the proto walk below needs.
        serialized_list = serialized_list.records()
      for serialized in serialized_list:
        if is_sequence:
          message = example_pb2.SequenceExample.FromString(serialized)
          context_features = message.context.feature
          feature_lists = message.feature_lists.feature_list
        else:
          message = example_pb2.Example.FromString(serialized)
          context_features = message.features.feature
          feature_lists = {}
        for plan in plans:
          if plan.spec.is_sequence:
            if plan.feature_name not in feature_lists:
              if plan.spec.is_optional:
                columns.setdefault(plan.out_key, []).append(None)
                continue
              raise ValueError(
                  f"Record missing sequence feature {plan.feature_name!r}.")
            steps = [
                _parse_leaf_from_feature(f, _LeafPlan(
                    plan.out_key, plan.feature_name,
                    plan.spec.replace(shape=plan.spec.shape[1:]),
                    plan.parse_dtype))
                for f in feature_lists[plan.feature_name].feature
            ]
            seq = np.stack(steps) if steps else np.zeros(
                (0,) + tuple(d or 0 for d in plan.spec.shape[1:]),
                dtype=plan.parse_dtype)
            columns.setdefault(plan.out_key, []).append(seq)
            lengths.setdefault(plan.out_key, []).append(len(steps))
          else:
            if plan.feature_name not in context_features:
              value = _parse_leaf_from_feature(
                  example_pb2.Feature(), plan)  # missing-feature path
            else:
              value = _parse_leaf_from_feature(
                  context_features[plan.feature_name], plan)
            columns.setdefault(plan.out_key, []).append(value)

    out = specs_lib.SpecStruct()
    merged_specs = {**{f"features/{k}": v for k, v in
                       self._feature_spec.items()},
                    **{f"labels/{k}": v for k, v in self._label_spec.items()}}
    for out_key, array in batched.items():
      if out_key.endswith("_length") and out_key not in merged_specs:
        out[out_key] = array  # sequence length side outputs
      else:
        out[out_key] = self._maybe_cast(array, merged_specs[out_key])
    for out_key, values in columns.items():
      spec = merged_specs[out_key]
      if all(v is None for v in values):
        continue  # optional, absent everywhere
      if any(v is None for v in values):
        present = sum(1 for v in values if v is not None)
        raise ValueError(
            f"Optional feature {spec.name or out_key!r} ({out_key!r}) is "
            f"present in only {present}/{len(values)} records of the "
            "batch; optional features must be present batch-wide or "
            "absent batch-wide.")
      if spec.is_sequence:
        time_dim = spec.shape[0] if spec.shape and spec.shape[0] is not None \
            else None
        plan = next(p for p in self._plans[spec.dataset_key]
                    if p.out_key == out_key)
        array = _pad_time(values, time_dim, plan)
        out[out_key] = self._maybe_cast(array, spec)
        out[out_key + "_length"] = np.asarray(
            lengths[out_key], dtype=np.int64)
      else:
        array = np.stack(values)
        out[out_key] = self._maybe_cast(array, spec)
    return out

  def _maybe_cast(self, array: np.ndarray,
                  spec: specs_lib.TensorSpec) -> np.ndarray:
    if array.dtype != spec.dtype and array.dtype.kind not in "SUO":
      return array.astype(spec.dtype)
    return array

  def __call__(self, records):
    return self.parse_batch(records)


def create_parse_fn(feature_spec: specs_lib.SpecStructLike,
                    label_spec: Optional[specs_lib.SpecStructLike] = None
                    ) -> ParseFn:
  """Factory mirroring `create_parse_tf_example_fn`
  (/root/reference/utils/tfdata.py:273-543)."""
  return ParseFn(feature_spec, label_spec)
