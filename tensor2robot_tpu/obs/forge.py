"""graftforge: an ahead-of-time compile farm that warms every executable
a deployment needs BEFORE any process starts.

graftcache (obs/excache.py, PR 7) killed recompiles per process on one
topology; PRs 10-14 multiplied the executable surface — serving bucket
ladders x replica counts x decode-step rungs x slot resets x train/eval
steps — and a cold fleet, loop, or trainer still pays its first-process
compiles serially at startup (measured 5.2 s cold vs 1.8 s warm on the
CPU smoke; 20-40 s PER EXECUTABLE over the axon tunnel). The reference
had the same shape at export time: SavedModel signature generation
enumerated every serving entry point from specs alone
(/root/reference/export_generators/default_export_generator.py:37-115)
— graftforge is that enumeration pointed at compiled XLA executables
(PAPERS.md: "Automatic Full Compilation of Julia Programs and ML Models
to Cloud TPUs" — whole-program offline compilation; "Scalable Training
of Language Models using JAX pjit and TPUv4" — compile cost as a
first-class scaling axis; ROADMAP item 5 verbatim).

Three layers:

* **ENUMERATION** (`plan_from_config`, backend-free): from a parsed
  research config and its specs alone — no devices, no checkpoint, no
  traffic — list the complete executable set the deployment will need:
  every `BucketedEngine` bucket rung (x replica placement), every
  `SessionEngine` decode rung + the slot-reset executable, the train
  step (with `num_virtual_stages` for pipelined trunks), the eval step.
  Targets the toolchain cannot cache (donating-mesh executables under
  the `excache.DONATING_MESH_SAFE_FROM` gate; plain-jit eval steps) are
  enumerated as UNFORGEABLE with the reason attached — the plan is the
  honest coverage statement, and flipping the one excache pin constant
  promotes the gated targets wholesale.
* **THE FARM** (`run_forge`): forgeable targets are partitioned over a
  pool of worker subprocesses (`--jobs`), each of which builds exactly
  the objects the live process would build (predictor + engine for
  serving rungs, TrainState + train step for the trainer) and compiles
  through the SAME `obs.xray.analyze_jit` + graftcache path the live
  warmup takes — so a forged entry is byte-identical in key to what the
  live process computes (pinned by tests/test_forge.py). Fresh
  subprocesses are load-bearing, not a convenience: a process that has
  loaded anything from a warm XLA compilation cache serializes poisoned
  payloads (the excache.store validation), and per-target processes
  both parallelize the farm and keep every stored blob self-contained.
* **THE MANIFEST**: one `forge-manifest-v1` record — per-executable
  key, family, compile_s, sizes, per-target errors, the unforgeable
  remainder — appended to runs.jsonl, so `graftscope diff`/`history`
  see forge coverage next to every other run artifact.

Consumers (the three cold-start seams): `train_eval(executable_cache_dir
="auto")` reads `<model_dir>/excache` — forge with `--model-dir` to
pre-populate it; `ServingFleet.warmup()` deserializes every replica's
ladder (N replicas x ladder = N x the win — replicas sharing a
`cache_namespace` deserialize ONE forged entry set); `GraftLoop`
startup threads its cache dir into both the fleet factory and the
learner rounds, so the loop's first serve starts compile-free. A
traffic-derived ladder change pre-forges its new rungs inside
`ServingFleet.rollout(ladder=...)` before the canary swap
(`engine.reladder`).

CLI: `python -m tensor2robot_tpu.bin.graftscope forge <config.gin>`
(`--plan` dry-run enumeration, `--jobs N`, `--verify` against an
existing cache; exit codes match `graftscope cache`: 0 ok, 1 bad/
missing entries, 2 usage). Backend-free at import like the rest of
`obs/` — workers are where jax lives.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from tensor2robot_tpu.obs import graftrace
from tensor2robot_tpu.utils import config

__all__ = ["FORGE_SCHEMA", "plan_from_config", "run_forge", "verify_plan",
           "forge_config", "format_plan", "graftforge", "build_train_step",
           "build_rung_engine"]

FORGE_SCHEMA = "forge-manifest-v1"
FORGE_SCHEMA_VERSION = 1

# Families the farm knows how to build. "eval" is enumerated (the plan
# is the coverage statement) but never farmed: train_eval's eval step is
# a plain jit that only ever rides the XLA-cache backstop tier.
FAMILIES = ("serve", "session", "train", "eval")


@config.configurable
def graftforge(model=None,
               model_dir: Optional[str] = None,
               export_dir: Optional[str] = None,
               jobs: int = 2):
  """Config-engine surface for forge inputs a research config wants to
  pin (`graftforge.model = @MyModel` names the model whose executables
  a serving-only config deploys; serving configs otherwise carry no
  model binding). Returns the bound values — the CLI merges them under
  its own flags."""
  return {"model": model, "model_dir": model_dir,
          "export_dir": export_dir, "jobs": jobs}


# ---------------------------------------------------------------------------
# Enumeration (backend-free).
# ---------------------------------------------------------------------------


def _ref_name(value) -> Optional[str]:
  """The configurable name behind an (unresolved) @reference binding."""
  name = getattr(value, "name", None)
  if isinstance(name, str):
    return name.rsplit(".", 1)[-1]
  if isinstance(value, str):
    return value.rsplit(".", 1)[-1]
  return None


def _bucket_ladder(max_batch_size: int) -> List[int]:
  # Local twin of serving.engine.bucket_ladder: enumeration must stay
  # importable under a poisoned backend without pulling the serving
  # package's import surface; tests pin the two ladders against each
  # other so they cannot drift.
  ladder, b = [], 1
  while b < max_batch_size:
    ladder.append(b)
    b *= 2
  ladder.append(max_batch_size)
  return ladder


def _gate_reason() -> Optional[str]:
  """The donating-mesh gate reason string, or None once the toolchain
  moves past the `excache.DONATING_MESH_SAFE_FROM` pin (version-keyed:
  flipping that ONE constant promotes every gated train target)."""
  from tensor2robot_tpu.obs import excache as excache_lib

  if excache_lib.donating_mesh_cache_unsafe():
    return ("donating-mesh executable gated on this jax "
            "(excache.DONATING_MESH_SAFE_FROM unset — deserialized "
            "donating NamedSharding executables heap-corrupt on 0.4.37)")
  return None


def _resolve_model_source(model: Optional[str] = None,
                          export_dir: Optional[str] = None
                          ) -> Optional[Dict[str, Any]]:
  """Model-source resolution, most explicit first: caller argument,
  `graftforge.model` binding, the trainer/loop model bindings a full
  config already carries. Serving-only configs (serve_fleet.gin) carry
  no model — callers pass `--model`/`--export-dir` or the plan records
  `model: None` and the farm refuses with exit 2."""
  if export_dir:
    return {"kind": "export", "dir": str(export_dir)}
  if model == "flagship":
    return {"kind": "flagship"}
  if model:
    return {"kind": "configurable", "name": str(model)}
  for dotted in ("graftforge.model", "train_eval_model.model",
                 "run_graftloop.model_ctor"):
    # Raw binding on purpose: `@Name()` references resolve to a BUILT
    # model, and enumeration must not construct one at plan time.
    bound = config.raw_binding(dotted)
    if bound is not None:
      name = _ref_name(bound)
      if name == "flagship":
        return {"kind": "flagship"}
      if name:
        return {"kind": "configurable", "name": name}
  return None


def plan_from_config(config_files: Sequence[str],
                     bindings: Sequence[str] = (),
                     model: Optional[str] = None,
                     export_dir: Optional[str] = None,
                     model_dir: Optional[str] = None) -> Dict[str, Any]:
  """Enumerates the executable set a research config deploys.

  Parses the config (fresh registry) and reads its bindings — nothing
  is built, no backend is touched (the `--plan` path runs under a
  poisoned JAX_PLATFORMS, pinned by test). Returns the plan dict the
  farm, the verifier, and the `--plan` renderer all consume:
  `{"targets": [...], "model": ..., "config_files": [...]}` where each
  target carries family, name (= cache namespace), the rung/replica
  grid, and `forgeable` + `reason` for targets the toolchain gates.
  """
  config.clear_config()
  config.parse_config_files_and_bindings(list(config_files),
                                         list(bindings))
  bound = config.bound_configurables()
  query = config.query_parameter_or
  model_source = _resolve_model_source(model=model, export_dir=export_dir)
  model_dir = model_dir or query("graftforge.model_dir") \
      or query("run_graftloop.model_dir")
  targets: List[Dict[str, Any]] = []

  # -- serving bucket ladders (BucketedEngine behind a fleet or solo) ------
  has_loop = "run_graftloop" in bound
  has_fleet = "ServingFleet" in bound
  has_serve = (has_fleet or has_loop or "BucketedEngine" in bound
               or "MicroBatcher" in bound)
  if has_serve:
    buckets = query("BucketedEngine.buckets")
    if buckets is None:
      max_batch = int(query("BucketedEngine.max_batch_size")
                      or query("ServingFleet.max_batch_size")
                      or query("run_graftloop.max_batch_size") or 8)
      buckets = _bucket_ladder(max_batch)
    else:
      buckets = sorted({int(b) for b in buckets})
    replicas = int(query("ServingFleet.num_replicas")
                   or query("run_graftloop.num_replicas") or 1)
    # Placement: a ServingFleet deployment (run_graftserve --replicas)
    # carves disjoint device groups and pins each replica's state, so
    # rung keys diverge per replica (the sharding key component) — one
    # target per replica. The loop builds its fleet without a device
    # carve (devices=None): every replica computes identical keys, so
    # ONE forged entry set warms all of them (forge once, every replica
    # deserializes) — one target, replicas recorded for the plan table.
    placed = has_fleet and not has_loop and replicas > 1
    namespace = "serve/loop" if has_loop else "serve/engine"
    for index in range(replicas if placed else 1):
      targets.append({
          "family": "serve",
          "name": namespace,
          "buckets": list(buckets),
          "replica_index": index,
          "num_replicas": replicas,
          "placed": placed,
          "executables": len(buckets),
          "forgeable": True,
      })

  # -- session decode ladders ----------------------------------------------
  if "SessionEngine" in bound:
    buckets = query("SessionEngine.buckets")
    if buckets is None:
      buckets = _bucket_ladder(int(query("SessionEngine.max_tick_batch")
                                   or 8))
    else:
      buckets = sorted({int(b) for b in buckets})
    targets.append({
        "family": "session",
        "name": "serve/session",
        "buckets": list(buckets),
        "max_sessions": int(query("SessionEngine.max_sessions") or 64),
        "executables": len(buckets) + 1,  # + the slot-reset executable
        "forgeable": True,
    })

  # -- train / eval steps --------------------------------------------------
  has_trainer = config.raw_binding("train_eval_model.model") is not None
  if has_trainer or has_loop:
    if has_trainer:
      # An unbound mesh_shape is NOT single-device: train_eval builds
      # the default all-devices mesh — record it so the worker compiles
      # (and keys) the executable the trainer actually dispatches.
      # (None is reserved for hand-built one-chip plans, bench.py.)
      mesh_shape = query("train_eval_model.mesh_shape") or "default"
      mode = str(query("train_eval_model.mode") or "train_and_evaluate")
      loop_k = int(query("train_eval_model.iterations_per_loop") or 1)
    else:  # the loop's learner: train rounds on a (1,1,1) mesh
      mesh_shape = (1, 1, 1)
      mode = "train"
      loop_k = 1
    gate = _gate_reason()
    model_name = _ref_name(config.raw_binding("train_eval_model.model")
                           or config.raw_binding(
                               "run_graftloop.model_ctor"))
    virtual_stages = None
    if model_name:
      virtual_stages = config.query_parameter_or(
          f"{model_name}.num_virtual_stages")
    step_specs = [("train_step", 1)]
    if loop_k > 1:
      step_specs.append((f"train_loop_k{loop_k}", loop_k))
    for step_name, k in step_specs:
      target = {
          "family": "train",
          "name": step_name,
          "mesh_shape": (list(mesh_shape)
                         if isinstance(mesh_shape, (list, tuple))
                         else mesh_shape),
          "batch_size": int(
              query("run_graftloop.train_batch_size")
              or query("DefaultRandomInputGenerator.batch_size")
              or query("DefaultRecordInputGenerator.batch_size") or 16),
          "executables": 1,
          # The trainer's step donates its mesh-sharded TrainState —
          # the exact shape the excache gate exists for. Forgeable the
          # moment the one pin constant flips.
          "forgeable": gate is None,
      }
      if k > 1:
        target["loop_k"] = k  # the [K,B] scan loop, not K plain steps
      if gate is not None:
        target["reason"] = gate
      if virtual_stages is not None:
        target["num_virtual_stages"] = int(virtual_stages)
      targets.append(target)
    if "evaluate" in mode or "eval" in mode.replace("evaluate", ""):
      targets.append({
          "family": "eval",
          "name": "eval_step",
          "executables": 1,
          "forgeable": False,
          "reason": ("plain-jit executable (never routed through "
                     "analyze_jit); the XLA compilation-cache backstop "
                     "tier covers it in eval modes"),
      })

  return {
      "schema": FORGE_SCHEMA,
      "schema_version": FORGE_SCHEMA_VERSION,
      "config_files": [str(p) for p in config_files],
      "bindings": [str(b) for b in bindings],
      "model": model_source,
      "model_dir": model_dir,
      "targets": targets,
  }


def format_plan(plan: Dict[str, Any]) -> str:
  """The `--plan` table: one line per target, unforgeable reasons
  spelled out (a rung forge can't enumerate is a rung the farm can't
  warm — the graftlint `warmup-unforgeable` rule polices the code side
  of the same contract)."""
  lines = [f"graftforge plan: {', '.join(plan['config_files'])} "
           f"(model: {json.dumps(plan.get('model'))})"]
  lines.append(f"  {'family':<9}{'name':<18}{'executables':>12}  detail")
  total = forgeable = 0
  for target in plan["targets"]:
    count = int(target.get("executables") or 0)
    total += count
    detail = []
    if target.get("buckets"):
      detail.append(f"rungs {target['buckets']}")
    if target["family"] == "session":
      detail.append("+ slot reset")
      detail.append(f"max_sessions {target.get('max_sessions')}")
    if target.get("placed"):
      detail.append(f"replica {target['replica_index']}"
                    f"/{target['num_replicas']} (placed)")
    elif int(target.get("num_replicas") or 1) > 1:
      detail.append(f"shared by {target['num_replicas']} replicas")
    if target.get("num_virtual_stages") is not None:
      detail.append(f"v={target['num_virtual_stages']} (1F1B)")
    if target.get("loop_k"):
      detail.append(f"K={target['loop_k']} scan loop")
    shape = target.get("mesh_shape")
    if shape:
      detail.append(f"mesh {tuple(shape) if isinstance(shape, list) else shape}")
    if target["forgeable"]:
      forgeable += count
    else:
      detail.append(f"UNFORGEABLE: {target.get('reason')}")
    lines.append(f"  {target['family']:<9}{target['name']:<18}"
                 f"{count:>12}  {'; '.join(detail)}")
  lines.append(f"  total {total} executable(s), {forgeable} forgeable")
  return "\n".join(lines)


# ---------------------------------------------------------------------------
# The farm (parent side).
# ---------------------------------------------------------------------------


def _worker_env(device_count: Optional[int]) -> Dict[str, str]:
  env = dict(os.environ)
  if device_count:
    flags = env.get("XLA_FLAGS", "")
    # Replace any inherited count: the forge must match the DEPLOYED
    # topology, not the parent's (mesh_fingerprint is a key component).
    flags = " ".join(f for f in flags.split()
                     if "xla_force_host_platform_device_count" not in f)
    env["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count="
        f"{int(device_count)}").strip()
  # Cross-process tracing: when the parent armed graftrace, workers
  # export their own trace/metrics shards into the same directory
  # (`graftrace.init_from_env` in `_worker_main`), so `graftscope
  # timeline` merges the farm's compile windows with everything else.
  trace_dir = graftrace.export_dir()
  if trace_dir:
    env["GRAFTRACE_DIR"] = trace_dir
    env.setdefault("GRAFTRACE_ROLE", "forge-worker")
  return env


def _run_workers(plan: Dict[str, Any], cache_dir: str, jobs: int,
                 verify: bool, device_count: Optional[int],
                 timeout_s: float) -> List[Dict[str, Any]]:
  """Partitions forgeable targets round-robin over `jobs` worker
  subprocesses and collects their per-target results. Workers re-parse
  the config themselves (a configurable model ctor needs its bindings)
  and write results to a JSON file each — stdout stays human."""
  forgeable = [t for t in plan["targets"] if t["forgeable"]]
  if not forgeable:
    return []
  jobs = max(1, min(int(jobs), len(forgeable)))
  shards: List[List[Dict[str, Any]]] = [[] for _ in range(jobs)]
  for index, target in enumerate(forgeable):
    shards[index % jobs].append(target)
  env = _worker_env(device_count)
  procs: List[Tuple[subprocess.Popen, str, List[Dict[str, Any]]]] = []
  results: List[Dict[str, Any]] = []
  with tempfile.TemporaryDirectory(prefix="graftforge-") as tmp:
    for shard_index, shard in enumerate(shards):
      spec = {
          "config_files": plan["config_files"],
          "bindings": plan["bindings"],
          "model": plan.get("model"),
          "model_dir": plan.get("model_dir"),
          "cache_dir": cache_dir,
          "verify": bool(verify),
          "targets": shard,
      }
      spec_path = os.path.join(tmp, f"spec-{shard_index}.json")
      result_path = os.path.join(tmp, f"result-{shard_index}.json")
      with open(spec_path, "w") as f:
        json.dump(spec, f)
      procs.append((subprocess.Popen(
          [sys.executable, "-m", "tensor2robot_tpu.obs.forge",
           "--worker", spec_path, result_path], env=env), result_path,
          shard))
    deadline = time.monotonic() + timeout_s
    for proc, result_path, shard in procs:
      remaining = max(deadline - time.monotonic(), 1.0)
      try:
        proc.wait(timeout=remaining)
      except subprocess.TimeoutExpired:
        # NEVER SIGKILL a possibly-mid-TPU-init child (CLAUDE.md); over
        # a CPU farm terminate is safe and the worker's targets are
        # reported as errors, not silently dropped.
        proc.terminate()
        try:
          proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
          # Stuck in a native compile (SIGTERM lands between Python
          # bytecodes only): ABANDON it — never SIGKILL — and report
          # its targets as errors; completed shards still count.
          pass
      if os.path.isfile(result_path):
        try:
          with open(result_path) as f:
            results.extend(json.load(f))
          continue
        except (OSError, ValueError):
          pass
      results.extend({
          "name": t["name"], "family": t["family"], "status": "error",
          "error": f"worker exited {proc.returncode} without a result",
      } for t in shard)
  return results


def run_forge(plan: Dict[str, Any], cache_dir: str, jobs: int = 2,
              device_count: Optional[int] = None,
              timeout_s: float = 1200.0,
              runs_path: Optional[str] = None) -> Dict[str, Any]:
  """Runs the compile farm over a plan and returns (+ optionally
  appends) the `forge-manifest-v1` manifest."""
  start = time.perf_counter()
  results = _run_workers(plan, cache_dir, jobs, verify=False,
                         device_count=device_count, timeout_s=timeout_s)
  executables: List[Dict[str, Any]] = []
  errors: List[Dict[str, Any]] = []
  for result in results:
    if result.get("status") == "ok":
      executables.extend(result.get("executables") or [])
    else:
      errors.append({"name": result.get("name"),
                     "family": result.get("family"),
                     "error": result.get("error")})
  unforgeable = [{"name": t["name"], "family": t["family"],
                  "reason": t.get("reason")}
                 for t in plan["targets"] if not t["forgeable"]]
  manifest = {
      "schema": FORGE_SCHEMA,
      "schema_version": FORGE_SCHEMA_VERSION,
      "config_files": plan["config_files"],
      "bindings": plan["bindings"],
      "cache_dir": str(cache_dir),
      "jobs": int(jobs),
      "wall_s": round(time.perf_counter() - start, 3),
      "executables": executables,
      "errors": errors,
      "unforgeable": unforgeable,
      "counts": {
          "forged": sum(1 for e in executables
                        if e.get("action") == "compiled"),
          "cached": sum(1 for e in executables
                        if e.get("action") == "cached"),
          # AOT-less degrades: the engine ran its plain-jit fallback, so
          # NOTHING was stored — a farm full of fallbacks warmed nothing
          # and must not read as clean coverage (the CLI exits 1 on it).
          "fallback": sum(1 for e in executables
                          if e.get("action") == "fallback"),
          "errors": len(errors),
          "unforgeable": len(unforgeable),
      },
      "total_compile_s": round(sum(float(e.get("compile_s") or 0.0)
                                   for e in executables), 3),
  }
  if runs_path:
    from tensor2robot_tpu.obs import runlog as runlog_lib

    record = runlog_lib.make_record("bench",
                                    extra={"forge": manifest})
    runlog_lib.append_record(runs_path, record)
  return manifest


def verify_plan(plan: Dict[str, Any], cache_dir: str,
                device_count: Optional[int] = None,
                timeout_s: float = 600.0) -> Dict[str, Any]:
  """Checks an existing cache against the plan WITHOUT compiling:
  workers trace each forgeable target's executables for their keys
  (`engine.rung_cache_keys` — the same synthesis warmup compiles
  through), and the parent checks presence + checksum against the
  cache's backend-free sidecar metadata."""
  from tensor2robot_tpu.obs import excache as excache_lib

  results = _run_workers(plan, cache_dir, jobs=1, verify=True,
                         device_count=device_count, timeout_s=timeout_s)
  cache = excache_lib.ExecutableCache(cache_dir)
  ok_keys, bad_keys = cache.verify()
  present, missing, corrupt = [], [], []
  errors: List[Dict[str, Any]] = []
  for result in results:
    if result.get("status") != "ok":
      errors.append({"name": result.get("name"),
                     "error": result.get("error")})
      continue
    for executable in result.get("executables") or []:
      key = executable.get("key")
      entry = dict(executable)
      if key in bad_keys:
        corrupt.append(entry)
      elif key in ok_keys:
        present.append(entry)
      else:
        missing.append(entry)
  return {"present": present, "missing": missing, "corrupt": corrupt,
          "errors": errors}


def forge_config(config_files: Sequence[str],
                 bindings: Sequence[str] = (),
                 cache_dir: str = ".graftcache",
                 jobs: int = 2,
                 model: Optional[str] = None,
                 export_dir: Optional[str] = None,
                 model_dir: Optional[str] = None,
                 device_count: Optional[int] = None,
                 runs_path: Optional[str] = None
                 ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
  """Enumerate + farm one research config; returns (plan, manifest)."""
  plan = plan_from_config(config_files, bindings, model=model,
                          export_dir=export_dir, model_dir=model_dir)
  manifest = run_forge(plan, cache_dir, jobs=jobs,
                       device_count=device_count, runs_path=runs_path)
  return plan, manifest


# ---------------------------------------------------------------------------
# Worker side (fresh subprocess; the only half that touches jax).
# ---------------------------------------------------------------------------


def _build_model(source: Dict[str, Any]):
  if source["kind"] == "flagship":
    import jax

    from tensor2robot_tpu.research.qtopt import flagship

    return flagship.make_flagship_model(jax.devices()[0].platform)
  if source["kind"] == "configurable":
    return config.get_configurable(source["name"])()
  raise ValueError(f"unknown model source {source!r}")


def _build_predictor(spec: Dict[str, Any], target: Dict[str, Any]):
  """Exactly what the live deployment builds: an export-bundle
  predictor when serving exports, else a checkpoint predictor that
  restores when the model_dir already has checkpoints and random-inits
  otherwise (the GraftLoop fresh-start rule; cache keys fingerprint
  shapes/shardings, not values, so both warm the same entries)."""
  from tensor2robot_tpu.predictors import predictors as predictors_lib

  source = spec.get("model")
  if source is None:
    raise ValueError(
        "no model source: pass --model/--export-dir or bind "
        "graftforge.model in the config")
  if source["kind"] == "export":
    predictor = predictors_lib.ExportedModelPredictor(
        export_dir=source["dir"])
    if not predictor.restore():
      raise RuntimeError(f"no valid export bundle under {source['dir']}")
  else:
    predictor = predictors_lib.CheckpointPredictor(
        model=_build_model(source),
        model_dir=spec.get("model_dir") or "/nonexistent")
    if not predictor.restore():
      predictor.init_randomly()
  if target.get("placed"):
    import jax

    from tensor2robot_tpu.parallel import mesh as mesh_lib

    groups = mesh_lib.replica_device_groups(
        int(target["num_replicas"]), jax.devices())
    group = groups[int(target["replica_index"])]
    if group:
      predictor.place_on_device(group[0])
  return predictor


def _engine_result(target: Dict[str, Any], engine,
                   verify: bool) -> List[Dict[str, Any]]:
  if verify:
    return [{"name": f"{target['name']}/{rung}", "family": target["family"],
             "rung": rung if isinstance(rung, str) else int(rung),
             "key": key}
            for rung, key in engine.rung_cache_keys().items()]
  engine.warmup()
  by_name = {str(r.get("name")): r for r in engine.compile_records}
  out = []
  for entry in engine.warmup_provenance:
    rung = entry["rung"]
    rec_name = (f"{target['name']}/reset_slot" if rung == "reset" else
                f"{target['name']}/"
                f"{'decode' if target['family'] == 'session' else 'bucket'}"
                f"{rung}")
    record = by_name.get(rec_name, {})
    cache_block = record.get("cache") or {}
    out.append({
        "name": rec_name,
        "family": target["family"],
        "rung": rung,
        "key": entry.get("key") or cache_block.get("key"),
        "action": ("cached" if entry["source"] == "cache" else
                   "compiled" if entry["source"] == "compile" else
                   "fallback"),
        "compile_s": round(float(record.get("compile_s") or 0.0), 4),
        "ms": round(float(entry.get("ms") or 0.0), 2),
        "stored": bool(cache_block.get("stored", entry["source"]
                                       == "cache")),
    })
  return out


def build_train_step(spec: Dict[str, Any],
                     target: Dict[str, Any]) -> Tuple[Any, Tuple]:
  """Builds the trainer's first-dispatch executable, exactly as
  train_eval / bench pay it, and returns `(step, args)` ready to
  `.trace(*args)` or dispatch: the plain step at [B], or — for
  `loop_k` targets — the `make_train_loop` [K, B] scan program (a
  DIFFERENT jaxpr; forging the plain step under the loop name would
  store an entry the trainer never looks up). `mesh_shape=None` is the
  one-chip deployment shape (SingleDeviceSharding donation —
  serializes safely, the bench plan); "default" is train_eval's
  unbound-mesh_shape case (all devices on the data axis); an explicit
  shape mirrors the config. Shared by the farm worker
  (`_forge_train_target`) and the jaxpr audit worker
  (`analysis.jaxpr_audit`): whatever either traces is the program the
  live trainer dispatches."""
  import jax
  import numpy as np

  from tensor2robot_tpu import modes as modes_lib
  from tensor2robot_tpu import specs as specs_lib
  from tensor2robot_tpu.parallel import mesh as mesh_lib
  from tensor2robot_tpu.parallel import train_step as ts

  model = _build_model(spec["model"])
  batch = int(target.get("batch_size") or 16)
  loop_k = int(target.get("loop_k") or 1)
  feature_spec = model.preprocessor.get_out_feature_specification(
      modes_lib.TRAIN)
  label_spec = model.preprocessor.get_out_label_specification(
      modes_lib.TRAIN)
  features = specs_lib.make_random_numpy(feature_spec, batch_size=batch,
                                         seed=0)
  labels = specs_lib.make_random_numpy(label_spec, batch_size=batch,
                                       seed=100)
  mesh_shape = target.get("mesh_shape")
  if mesh_shape is None:
    if loop_k > 1:
      raise ValueError("loop_k targets need a mesh recipe (the live "
                       "K-step loop only exists on the train_eval path)")
    device = jax.devices()[0]
    features = jax.device_put(features, device)
    labels = jax.device_put(labels, device)
    state, _ = ts.create_train_state(model, jax.random.PRNGKey(0),
                                     features)
    step = ts.make_train_step(model)
    args = (state, features, labels)
  else:
    mesh = mesh_lib.create_mesh(
        mesh_shape=None if mesh_shape == "default"
        else tuple(mesh_shape))
    if hasattr(model, "set_mesh"):
      model.set_mesh(mesh)
    state, shardings = ts.create_train_state(
        model, jax.random.PRNGKey(0), features, mesh=mesh)
    batch_spec = getattr(model, "batch_partition_spec", None)
    if loop_k > 1:
      # The live loop stacks K host batches on a leading scan axis
      # (train_eval._stacked_group) and places under the loop spec.
      stack = lambda tree: jax.tree_util.tree_map(  # noqa: E731
          lambda a: np.stack([a] * loop_k), tree)
      features, labels = stack(features), stack(labels)
      batch_spec = ts.loop_batch_spec(batch_spec)
      step = ts.make_train_loop(model, loop_k, mesh=mesh,
                                shardings=shardings,
                                batch_spec=getattr(
                                    model, "batch_partition_spec", None))
    else:
      step = ts.make_train_step(model, mesh=mesh, shardings=shardings,
                                batch_spec=batch_spec)
    placed_features, placed_labels = mesh_lib.place_batch(
        mesh, {"features": features, "labels": labels},
        batch_spec=batch_spec)
    args = (state, placed_features, placed_labels)
  return step, args


def _forge_train_target(spec: Dict[str, Any], target: Dict[str, Any],
                        verify: bool) -> List[Dict[str, Any]]:
  """Compiles (or --verify key-checks) the train-step executable that
  `build_train_step` assembles, through the SAME analyze_jit +
  graftcache path the live trainer takes."""
  from tensor2robot_tpu.obs import excache as excache_lib
  from tensor2robot_tpu.obs import xray as xray_lib

  step, args = build_train_step(spec, target)
  cache = excache_lib.ExecutableCache(spec["cache_dir"])
  if verify:
    traced = step.trace(*args)
    key = excache_lib.cache_key(
        target["name"],
        **excache_lib.key_components_from_traced(traced, args))
    return [{"name": target["name"], "family": "train", "key": key}]
  _, record = xray_lib.analyze_jit(target["name"], step, *args,
                                   cache=cache)
  cache_block = record.get("cache") or {}
  return [{
      "name": target["name"],
      "family": "train",
      "key": cache_block.get("key"),
      "action": "cached" if cache_block.get("hit") else "compiled",
      "compile_s": round(float(record.get("compile_s") or 0.0), 4),
      "stored": bool(cache_block.get("stored", cache_block.get("hit"))),
  }]


def build_rung_engine(spec: Dict[str, Any], target: Dict[str, Any]):
  """The serving engine a "serve"/"session" target deploys, built
  exactly as the live process builds it (predictor + spec-derived
  ladder). Shared by the farm worker (`_forge_target`) and the jaxpr
  audit worker (`analysis.jaxpr_audit`), so both reason over the SAME
  engine the deployment runs."""
  if target["family"] == "serve":
    from tensor2robot_tpu.serving import engine as engine_lib

    # The farm worker IS the enumeration: target["buckets"] came from
    # plan_from_config's spec walk, so the ladder is spec-derived by
    # construction.
    return engine_lib.BucketedEngine(  # graftlint: disable=warmup-unforgeable
        predictor=_build_predictor(spec, target),
        buckets=target["buckets"],
        name=target["name"],
        cache=spec["cache_dir"],
        cache_namespace=target["name"])
  if target["family"] == "session":
    from tensor2robot_tpu.serving import session as session_lib

    # Spec-derived by construction, same as above.
    return session_lib.SessionEngine(  # graftlint: disable=warmup-unforgeable
        predictor=_build_predictor(spec, target),
        max_sessions=int(target.get("max_sessions") or 64),
        buckets=target["buckets"],
        name=target["name"],
        cache=spec["cache_dir"],
        cache_namespace=target["name"])
  raise ValueError(f"no rung engine for family {target['family']!r}")


def _forge_target(spec: Dict[str, Any],
                  target: Dict[str, Any]) -> Dict[str, Any]:
  verify = bool(spec.get("verify"))
  try:
    if target["family"] in ("serve", "session"):
      engine = build_rung_engine(spec, target)
      executables = _engine_result(target, engine, verify)
    elif target["family"] == "train":
      executables = _forge_train_target(spec, target, verify)
    else:
      raise ValueError(f"cannot forge family {target['family']!r}")
  except Exception as e:  # noqa: BLE001 - one bad target != a dead farm
    return {"name": target["name"], "family": target["family"],
            "status": "error", "error": f"{type(e).__name__}: {e}"}
  return {"name": target["name"], "family": target["family"],
          "status": "ok", "executables": executables}


def _worker_main(spec_path: str, result_path: str) -> int:
  with open(spec_path) as f:
    spec = json.load(f)
  if os.environ.get("GRAFTFORGE_PLATFORM", "cpu") == "cpu":
    # Default-safe on the axon environment: a forge worker must never
    # initialize the TPU tunnel by accident (CLAUDE.md).
    from tensor2robot_tpu.utils import backend

    backend.pin_cpu()
  graftrace.init_from_env()  # arm shard export when the parent did
  config.clear_config()
  config.parse_config_files_and_bindings(list(spec["config_files"]),
                                         list(spec["bindings"]))
  results = [_forge_target(spec, target) for target in spec["targets"]]
  with open(result_path, "w") as f:
    json.dump(results, f)
  graftrace.flush()
  return 0 if all(r["status"] == "ok" for r in results) else 1


if __name__ == "__main__":
  if len(sys.argv) == 4 and sys.argv[1] == "--worker":
    sys.exit(_worker_main(sys.argv[2], sys.argv[3]))
  print("usage: python -m tensor2robot_tpu.obs.forge --worker "
        "<spec.json> <result.json>\n(operators drive the farm through "
        "`python -m tensor2robot_tpu.bin.graftscope forge`)",
        file=sys.stderr)
  sys.exit(2)
