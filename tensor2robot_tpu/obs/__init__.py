"""graftscope: unified tracing, metrics, and step-time telemetry.

The reference's only observability is TF summaries plumbed through TPU
`host_call` (/root/reference/models/abstract_model.py:873-936). This
package is the permanent instrumentation layer replacing the ad-hoc
timing that diagnosed every perf round by hand (PERFORMANCE.md):

* `trace`     — low-overhead span tracer exporting Chrome-trace-event
  JSON (Perfetto-loadable);
* `metrics`   — process-wide counters / gauges / streaming histograms,
  snapshotted into the JSONL event stream (`utils/summaries.py`);
* `stepstats` — per-train-step breakdown (data-wait vs device time via
  `utils/backend.sync` semantics, compile-event detection, throughput,
  live-array gauges);
* `xray`      — below-dispatch introspection: per-executable compile
  timing, jaxpr equation counts, donation byte accounting, XLA
  cost/memory analysis, analytic MFU/roofline, and per-shard
  state/batch/HBM-watermark accounting;
* `runlog`    — schema-versioned append-only run history
  (`runs.jsonl`) with direction-aware regression diffing;
* `excache`   — graftcache: persistent on-disk executable/AOT cache
  (content-addressed `serialize_executable` round-trips of the xray
  AOT executables + the XLA compilation-cache backstop), so trainer
  restarts, serving cold starts, and bench probes deserialize warm
  executables instead of recompiling; read back / maintained with
  `graftscope cache`;
* `sentinel`  — online anomaly detection over the stepstats stream:
  EWMA/MAD step-time spikes, data starvation, non-finite divergence
  (piggybacked on the barrier fetch — zero extra tunnel round trips),
  HBM-watermark drift; emits `graftscope-incident-v1` records;
* `faultlab`  — graftguard's seeded deterministic fault-injection
  plane: named injection points threaded through the data/checkpoint/
  train/serving seams, every injected fault counted and stamped into
  the run record so a chaos run (`bench.py --chaos`) is attributable;
* `flightrec` — crash/hang flight recorder: bounded ring buffers of
  recent steps/incidents dumped as a `graftscope-postmortem-v1` bundle
  on unhandled exception, SIGTERM (tunnel-safe: host-side state only),
  watchdog hang timeout, or a fatal sentinel incident; read back with
  `graftscope postmortem`.

Backend-free by construction: importing this package (and using trace /
metrics / runlog) never touches a JAX backend — the same discipline as
`analysis/` (tests/test_observability.py proves it under a poisoned
JAX_PLATFORMS). Only `stepstats` and the `xray` analysis functions
touch the backend, lazily, from inside a live train loop where the
backend is already up.

Read telemetry back with `python -m tensor2robot_tpu.bin.graftscope
<model_dir>` (or `scripts/obs_report.sh`); compare runs with
`... graftscope diff <runA> <runB>` / `... graftscope history <dir>`.
"""

from tensor2robot_tpu.obs import (excache, faultlab, flightrec, metrics,
                                  runlog, sentinel, stepstats, trace, xray)

__all__ = ["excache", "faultlab", "flightrec", "metrics", "runlog",
           "sentinel", "stepstats", "trace", "xray"]
