"""Process-wide metrics registry: counters, gauges, streaming histograms.

Replaces the reference's host_call scalar plumbing
(/root/reference/models/abstract_model.py:873-936) for everything that is
NOT a per-step training scalar: pipeline wait times, serving latencies,
episode counts, bench probe outcomes. Components record into the global
registry from any thread; `snapshot()` flattens the whole registry into
plain floats for the JSONL event stream (`utils/summaries.py`) or a
bench JSON record.

Naming scheme (docs/ARCHITECTURE.md "Observability"): metric names are
`component/metric_unit` (e.g. `data/prefetch_wait_ms`,
`serve/predict_ms`); snapshot keys are prefixed by kind —
`counter/<name>`, `gauge/<name>`, `hist/<name>/<stat>` with stats
`count, mean, min, max, p50, p90, p99`.

Histograms are streaming: a bounded reservoir (Vitter's algorithm R with
a deterministic per-histogram RNG) keeps an unbiased sample of an
unbounded value stream; percentiles are exact until the reservoir fills
(numpy linear interpolation — pinned against `np.percentile` by
tests/test_observability.py). Backend-free by construction: never
imports jax.
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
import zlib
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np

__all__ = ["Counter", "Gauge", "Histogram", "Registry", "get_registry",
           "counter", "gauge", "histogram", "snapshot", "reset",
           "isolated", "percentiles"]

DEFAULT_RESERVOIR_SIZE = 4096
_PCTS = (50.0, 90.0, 99.0)


def percentiles(values: Sequence[float],
                pcts: Iterable[float] = _PCTS) -> List[float]:
  """Linear-interpolation percentiles (np.percentile semantics)."""
  if not len(values):
    return [float("nan") for _ in pcts]
  return [float(v) for v in np.percentile(np.asarray(values, np.float64),
                                          list(pcts))]


class Counter:
  """Monotonic event count."""

  def __init__(self, name: str):
    self.name = name
    self._lock = threading.Lock()
    self._value = 0

  def inc(self, n: int = 1) -> None:
    with self._lock:
      self._value += n

  @property
  def value(self) -> int:
    return self._value


class Gauge:
  """Last-write-wins instantaneous value."""

  def __init__(self, name: str):
    self.name = name
    self._value = float("nan")

  def set(self, value: float) -> None:
    self._value = float(value)

  @property
  def value(self) -> float:
    return self._value


class _HistTimer:
  """Context manager recording an elapsed-milliseconds observation."""

  __slots__ = ("_hist", "_start_ns")

  def __init__(self, hist: "Histogram"):
    self._hist = hist
    self._start_ns = 0

  def __enter__(self) -> "_HistTimer":
    self._start_ns = time.perf_counter_ns()
    return self

  def __exit__(self, exc_type, exc, tb) -> None:
    self._hist.record((time.perf_counter_ns() - self._start_ns) / 1e6)


class Histogram:
  """Streaming value distribution with reservoir-sampled percentiles."""

  def __init__(self, name: str,
               reservoir_size: int = DEFAULT_RESERVOIR_SIZE):
    self.name = name
    self._lock = threading.Lock()
    self._reservoir_size = reservoir_size
    # Deterministic RNG (seeded off a stable digest of the name — NOT
    # hash(), which PYTHONHASHSEED salts per process) so a re-run of
    # the same workload snapshots the same percentiles — diffable
    # telemetry.
    self._rng = random.Random(zlib.crc32(name.encode("utf-8")))
    self._sample: List[float] = []
    self._count = 0
    self._total = 0.0
    self._min = float("inf")
    self._max = float("-inf")
    # Exemplar: the label (a graftrace trace_id) of the WORST sample
    # seen since the last `clear_exemplar()` — the link from a p99
    # regression in runs.jsonl back to its timeline entry. Kept out of
    # `snapshot()` (whose contract is numeric-only values); read via
    # `exemplar()` / `Registry.exemplars()`.
    self._ex_value = float("-inf")
    self._ex_label: Optional[str] = None

  def record(self, value: float, exemplar: Optional[str] = None) -> None:
    value = float(value)
    with self._lock:
      self._record_locked(value)
      if exemplar is not None and value >= self._ex_value:
        self._ex_value = value
        self._ex_label = str(exemplar)

  def record_many(self, values: Iterable[float]) -> None:
    """Records a batch of observations under ONE lock acquisition.

    The hot-path amortization primitive: per-item `record` costs a lock
    round trip per observation, which the data-pipeline consumer loop
    pays once per batch (`data/pipeline.prefetch`). Callers that can
    buffer a few observations locally flush them here instead —
    statistics (count/mean/min/max/reservoir) are IDENTICAL to the
    equivalent sequence of `record` calls, including the deterministic
    reservoir RNG stream.
    """
    with self._lock:
      for value in values:
        self._record_locked(float(value))

  def _record_locked(self, value: float) -> None:
    self._count += 1
    self._total += value
    self._min = min(self._min, value)
    self._max = max(self._max, value)
    if len(self._sample) < self._reservoir_size:
      self._sample.append(value)
    else:
      # Algorithm R: keep each of the n observations with prob k/n.
      idx = self._rng.randrange(self._count)
      if idx < self._reservoir_size:
        self._sample[idx] = value

  def time_ms(self) -> _HistTimer:
    """`with hist.time_ms(): ...` records the window's milliseconds."""
    return _HistTimer(self)

  @property
  def count(self) -> int:
    return self._count

  @property
  def mean(self) -> float:
    return self._total / self._count if self._count else float("nan")

  def percentile(self, pct: float) -> float:
    with self._lock:
      return percentiles(self._sample, [pct])[0]

  def values(self) -> List[float]:
    """Snapshot of the reservoir sample (an unbiased sample of the full
    observation stream once it exceeds the reservoir). Consumers that
    derive policy from observed traffic — the traffic-derived bucket
    ladder (`serving.engine.traffic_bucket_ladder`) reads the
    `serve/request_rows` reservoir — use this instead of reaching into
    `_sample`."""
    with self._lock:
      return list(self._sample)

  def stats(self) -> Dict[str, float]:
    with self._lock:
      p50, p90, p99 = percentiles(self._sample)
      return {"count": float(self._count), "mean": self.mean,
              "min": self._min if self._count else float("nan"),
              "max": self._max if self._count else float("nan"),
              "p50": p50, "p90": p90, "p99": p99}

  def exemplar(self) -> Optional[Dict[str, object]]:
    """The worst-sample exemplar since the last clear, or None."""
    with self._lock:
      if self._ex_label is None:
        return None
      return {"value": self._ex_value, "trace_id": self._ex_label}

  def clear_exemplar(self) -> None:
    """Starts a fresh exemplar window (called by the shard-snapshot
    writer so each metrics shard carries its own window's worst)."""
    with self._lock:
      self._ex_value = float("-inf")
      self._ex_label = None


class Registry:
  """Get-or-create metric store; one per process (see `get_registry`)."""

  def __init__(self):
    self._lock = threading.Lock()
    self._counters: Dict[str, Counter] = {}
    self._gauges: Dict[str, Gauge] = {}
    self._histograms: Dict[str, Histogram] = {}

  def counter(self, name: str) -> Counter:
    with self._lock:
      if name not in self._counters:
        self._counters[name] = Counter(name)
      return self._counters[name]

  def gauge(self, name: str) -> Gauge:
    with self._lock:
      if name not in self._gauges:
        self._gauges[name] = Gauge(name)
      return self._gauges[name]

  def histogram(self, name: str,
                reservoir_size: int = DEFAULT_RESERVOIR_SIZE) -> Histogram:
    with self._lock:
      if name not in self._histograms:
        self._histograms[name] = Histogram(name, reservoir_size)
      return self._histograms[name]

  def snapshot(self, prefix: Optional[str] = None) -> Dict[str, float]:
    """Flat {kind/name[/stat]: float} view of every metric.

    Suitable for `SummaryWriter.write_scalars` (all values are plain
    floats; empty histograms are omitted rather than emitting NaNs).
    With `prefix`, only metrics whose name starts with it are included.
    """
    with self._lock:
      counters = list(self._counters.values())
      gauges = list(self._gauges.values())
      hists = list(self._histograms.values())
    out: Dict[str, float] = {}
    for c in counters:
      if prefix is None or c.name.startswith(prefix):
        out[f"counter/{c.name}"] = float(c.value)
    for g in gauges:
      if prefix is None or g.name.startswith(prefix):
        out[f"gauge/{g.name}"] = g.value
    for h in hists:
      if (prefix is None or h.name.startswith(prefix)) and h.count:
        for stat, value in h.stats().items():
          out[f"hist/{h.name}/{stat}"] = value
    return out

  def stamped_snapshot(self, prefix: Optional[str] = None
                       ) -> Dict[str, object]:
    """`snapshot()` plus the paired monotonic/epoch clock stamp the
    graftrace shards carry (one back-to-back read): consumers that hold
    snapshots over time — the graftwatch SLO engine, staleness
    reporting in `graftscope watch` — get "when was this true" without
    changing the numeric-only `snapshot()` contract."""
    return {
        "clock": {"perf_ns": time.perf_counter_ns(),
                  "epoch_ns": time.time_ns()},
        "snapshot": self.snapshot(prefix),
    }

  def exemplars(self, prefix: Optional[str] = None,
                clear: bool = False) -> Dict[str, Dict[str, object]]:
    """{name: {"value", "trace_id"}} for every histogram holding an
    exemplar. Separate from `snapshot()` on purpose: snapshot values
    are plain floats consumed by scalar writers; trace ids are not.
    With `clear`, each returned exemplar's window is reset (the
    per-snapshot-window semantics the shard writer wants)."""
    with self._lock:
      hists = list(self._histograms.values())
    out: Dict[str, Dict[str, object]] = {}
    for h in hists:
      if prefix is not None and not h.name.startswith(prefix):
        continue
      ex = h.exemplar()
      if ex is not None:
        out[h.name] = ex
        if clear:
          h.clear_exemplar()
    return out

  def reset(self) -> None:
    """Drops every metric. Called by tests and by `train_eval_model` at
    run start (alongside the trace-buffer clear) so a run's final
    snapshot covers exactly that run, not earlier runs in the same
    process."""
    with self._lock:
      self._counters.clear()
      self._gauges.clear()
      self._histograms.clear()


_GLOBAL = Registry()


def get_registry() -> Registry:
  """The process-wide registry the shipped instrumentation records into."""
  return _GLOBAL


def counter(name: str) -> Counter:
  return _GLOBAL.counter(name)


def gauge(name: str) -> Gauge:
  return _GLOBAL.gauge(name)


def histogram(name: str,
              reservoir_size: int = DEFAULT_RESERVOIR_SIZE) -> Histogram:
  return _GLOBAL.histogram(name, reservoir_size)


def snapshot(prefix: Optional[str] = None) -> Dict[str, float]:
  return _GLOBAL.snapshot(prefix)


def reset() -> None:
  _GLOBAL.reset()


@contextlib.contextmanager
def isolated(registry: Optional[Registry] = None):
  """Swaps the process-global registry for a fresh one within the scope.

  Hermetic-test support: unlike `reset()` — which destroys whatever
  other suites recorded into the shared singleton — this snapshots the
  current global, installs `registry` (default: a fresh `Registry`),
  and restores the original on exit, so tests cannot leak counters into
  each other OR wipe state that outlives them. Components that captured
  the registry object before entry keep writing to the old one; the
  shipped instrumentation resolves `get_registry()` / the module-level
  helpers at call time and lands in the isolated registry.
  """
  global _GLOBAL
  previous = _GLOBAL
  _GLOBAL = registry if registry is not None else Registry()
  try:
    yield _GLOBAL
  finally:
    _GLOBAL = previous
