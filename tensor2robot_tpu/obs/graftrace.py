"""graftrace: end-to-end request/causality tracing + shard export.

The reference has no request-scoped tracing at all — serving telemetry
stops at per-call wall clocks inside the exported-SavedModel predictor
(/root/reference/predictors/exported_savedmodel_predictor.py:212-359).
Every observability layer this repo grew (obs/trace.py spans,
obs/metrics.py histograms, sentinel incidents) is per-process, while
PRs 11-15 made the system a multi-process topology: fleet replicas
behind a router, graftloop actors/learner/publisher, forge worker
subprocesses. graftrace is the layer that makes one request (or one
episode) followable across all of them:

* **Trace contexts** — (trace_id, span_id, parent_id) triples minted at
  admission seams (`ServingFleet.predict`, `MicroBatcher.predict`) and
  propagated on a thread-local (`current()` / `activate()`), so worker
  threads and nested dispatch layers attach the SAME ids without any
  call-signature changes. `obs.trace` auto-injects the active context's
  ids into every span/instant via the context-provider hook, so the
  whole existing span surface becomes causally linkable for free.
* **Stage decomposition** — per-request latency split into named stages
  (`queue_wait`/`batch_form`/`dispatch`/`split` sum to the end-to-end
  `serve/request_ms`; `pad`/`device` are informational sub-stages of
  dispatch) recorded into `serve/stage/<name>_ms` histograms and
  summarized by `stage_breakdown()` for the bench headlines.
* **Causality links** — span args may carry `links` (a list of source
  span_ids); `obs.aggregate` synthesizes Perfetto flow events from
  `parent_id`/`links` at merge time, which is what turns the loop's
  `publish_to_first_action` scalar into a walkable chain
  (episode -> replay shard -> learner round -> publish -> first action).
* **Shard export** — `configure(dir)` arms a per-process exporter;
  `flush()` drains the tracer ring into `trace-<pid>-<gen>.json` (with
  a monotonic<->epoch clock-alignment stamp, ring-bounded to `max_gens`
  generations per pid so an always-on loop never grows the directory
  unboundedly) plus a `metrics-<pid>-<gen>.json` registry snapshot with
  histogram exemplars. Subprocess workers arm themselves from
  `GRAFTRACE_DIR` / `GRAFTRACE_ROLE` (`init_from_env`); the deliberate
  `GRAFTRACE_EPOCH_SKEW_NS` knob exists so tests can emit shards from
  processes with skewed wall clocks.

Backend-free by construction: never imports jax; `flush()` never
raises (telemetry must not take a worker down); a process that never
calls `configure()` pays one dict read per `flush()` call.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, Iterable, Optional

from tensor2robot_tpu.obs import metrics as obs_metrics
from tensor2robot_tpu.obs import trace as obs_trace

__all__ = ["TraceContext", "mint", "current", "activate",
           "request_context", "record_stage", "record_stage_many",
           "stage_breakdown", "configure", "init_from_env",
           "is_configured", "export_dir", "flush", "SUMMED_STAGES",
           "INFO_STAGES", "STAGE_PREFIX"]

STAGE_PREFIX = "serve/stage/"
# The stages whose per-request sum reconciles with the end-to-end
# `serve/request_ms` window (bench acceptance: within 5%). `pad` and
# `device` happen INSIDE the dispatch window (engine-side sub-stages)
# and are reported but excluded from the sum — counting them twice
# would break the reconciliation by construction.
SUMMED_STAGES = ("queue_wait", "batch_form", "dispatch", "split")
INFO_STAGES = ("pad", "device")

# Process-unique id source: pid + a random per-process salt + a counter.
# The salt keeps ids unique across a pid reuse (forge workers churn
# pids) without touching time-of-day.
_ID_SALT = int.from_bytes(os.urandom(4), "big")
_id_lock = threading.Lock()
_id_counter = 0


def _next_id() -> str:
  global _id_counter
  with _id_lock:
    _id_counter += 1
    n = _id_counter
  return f"{os.getpid():x}.{_ID_SALT:08x}.{n:x}"


class TraceContext:
  """One causality node: (trace_id, span_id, parent_id)."""

  __slots__ = ("trace_id", "span_id", "parent_id")

  def __init__(self, trace_id: str, span_id: str,
               parent_id: Optional[str] = None):
    self.trace_id = trace_id
    self.span_id = span_id
    self.parent_id = parent_id

  def child(self) -> "TraceContext":
    """A new span under the same trace, parented on this one."""
    return TraceContext(self.trace_id, _next_id(), self.span_id)

  def args(self) -> Dict[str, str]:
    """The trace-event args the aggregator stitches flows from."""
    out = {"trace_id": self.trace_id, "span_id": self.span_id}
    if self.parent_id is not None:
      out["parent_id"] = self.parent_id
    return out

  def __repr__(self) -> str:  # debugging aid only
    return (f"TraceContext(trace={self.trace_id}, span={self.span_id}, "
            f"parent={self.parent_id})")


def mint() -> TraceContext:
  """A fresh root context (new trace_id, no parent)."""
  return TraceContext(_next_id(), _next_id(), None)


_TLS = threading.local()


def current() -> Optional[TraceContext]:
  """The thread's active context, or None."""
  return getattr(_TLS, "ctx", None)


@contextlib.contextmanager
def activate(ctx: Optional[TraceContext]):
  """Installs `ctx` as the thread's active context for the scope."""
  previous = getattr(_TLS, "ctx", None)
  _TLS.ctx = ctx
  try:
    yield ctx
  finally:
    _TLS.ctx = previous


def request_context() -> TraceContext:
  """The admission-seam helper: a child of the active context when one
  is installed (the router already minted the trace), a fresh root
  otherwise (direct batcher/engine clients)."""
  ctx = current()
  return ctx.child() if ctx is not None else mint()


def _context_args() -> Optional[Dict[str, str]]:
  ctx = current()
  return ctx.args() if ctx is not None else None


# Every obs.trace span/instant recorded while a context is active gets
# the context's ids injected into its args — the whole existing span
# surface (engine predict, session dispatch, fleet spans) becomes
# causally linkable without touching its call sites.
obs_trace.set_context_provider(_context_args)


# -- stage decomposition ------------------------------------------------------


def record_stage(name: str, ms: float,
                 ctx: Optional[TraceContext] = None,
                 start_ns: Optional[int] = None) -> None:
  """Records one per-request stage sample: always into the
  `serve/stage/<name>_ms` histogram; additionally as a trace event
  when the tracer is enabled and the caller took the clock reads."""
  obs_metrics.histogram(STAGE_PREFIX + name + "_ms").record(ms)
  if start_ns is not None:
    obs_trace.add_complete(STAGE_PREFIX + name, start_ns,
                           int(ms * 1e6), cat="stage",
                           args=ctx.args() if ctx is not None else None)


def record_stage_many(name: str, values_ms: Iterable[float]) -> None:
  """Batch-amortized histogram path (one lock round trip per batch —
  the `Histogram.record_many` contract); no trace events."""
  obs_metrics.histogram(STAGE_PREFIX + name + "_ms").record_many(
      values_ms)


def stage_breakdown() -> Optional[Dict[str, Any]]:
  """The bench headline block: per-stage p50/p95/p99 plus the
  reconciliation of the summed stage means against the end-to-end
  `serve/request_ms` mean. Returns None when no stage was recorded in
  the current registry window (e.g. a traffic shape that never touched
  the batcher)."""
  registry = obs_metrics.get_registry()
  stages: Dict[str, Dict[str, float]] = {}
  summed_mean = 0.0
  for name in SUMMED_STAGES + INFO_STAGES:
    hist = registry.histogram(STAGE_PREFIX + name + "_ms")
    if not hist.count:
      continue
    p50, p95, p99 = obs_metrics.percentiles(hist.values(),
                                            (50.0, 95.0, 99.0))
    stages[name] = {"count": float(hist.count),
                    "mean_ms": round(hist.mean, 3),
                    "p50_ms": round(p50, 3), "p95_ms": round(p95, 3),
                    "p99_ms": round(p99, 3)}
    if name in SUMMED_STAGES:
      summed_mean += hist.mean
  if not stages:
    return None
  request = registry.histogram("serve/request_ms")
  request_mean = request.mean if request.count else float("nan")
  ratio = (summed_mean / request_mean
           if request.count and request_mean else None)
  return {
      "stages": stages,
      "summed": [s for s in SUMMED_STAGES if s in stages],
      "stage_sum_mean_ms": round(summed_mean, 3),
      "request_mean_ms": (round(request_mean, 3)
                          if request.count else None),
      # ~1.0 when the decomposition accounts for the whole request
      # window (acceptance band: within 5%); the residual is client
      # wakeup + completion bookkeeping.
      "reconciliation_ratio": (round(ratio, 4)
                               if ratio is not None else None),
  }


# -- cross-process shard export ----------------------------------------------

_export_lock = threading.Lock()
_EXPORT: Dict[str, Any] = {"dir": None, "role": "worker", "gen": 0,
                           "max_gens": 8, "skew_ns": 0}


def configure(directory: str, role: str = "worker", max_gens: int = 8,
              skew_ns: Optional[int] = None, enable: bool = True) -> None:
  """Arms the per-process shard exporter (and, by default, the tracer).

  `skew_ns` defaults to `GRAFTRACE_EPOCH_SKEW_NS` (the deliberate
  clock-skew knob the cross-process merge test injects); `max_gens`
  ring-bounds this pid's shard generations on disk.
  """
  os.makedirs(directory, exist_ok=True)
  if skew_ns is None:
    try:
      skew_ns = int(os.environ.get("GRAFTRACE_EPOCH_SKEW_NS", "0"))
    except ValueError:
      skew_ns = 0
  with _export_lock:
    _EXPORT["dir"] = os.path.abspath(directory)
    _EXPORT["role"] = str(role)
    _EXPORT["gen"] = 0
    _EXPORT["max_gens"] = max(int(max_gens), 1)
    _EXPORT["skew_ns"] = int(skew_ns)
  if enable:
    obs_trace.enable()


def init_from_env() -> bool:
  """Subprocess-worker arming: configures from `GRAFTRACE_DIR` /
  `GRAFTRACE_ROLE` when the parent exported them (forge workers, loop
  subprocesses). Returns whether the exporter was armed."""
  directory = os.environ.get("GRAFTRACE_DIR")
  if not directory:
    return False
  configure(directory, role=os.environ.get("GRAFTRACE_ROLE", "worker"))
  return True


def is_configured() -> bool:
  return _EXPORT["dir"] is not None


def export_dir() -> Optional[str]:
  """The armed shard directory (None when not configured) — parents
  hand it to subprocess workers via `GRAFTRACE_DIR`."""
  return _EXPORT["dir"]


def _prune_ring_locked(directory: str, pid: int, newest_gen: int,
                       max_gens: int) -> None:
  floor = newest_gen - max_gens + 1
  if floor <= 0:
    return
  for prefix in ("trace", "metrics"):
    marker = f"{prefix}-{pid}-"
    try:
      names = os.listdir(directory)
    except OSError:
      return
    for name in names:
      if not (name.startswith(marker) and name.endswith(".json")):
        continue
      try:
        gen = int(name[len(marker):-len(".json")])
      except ValueError:
        continue
      if gen < floor:
        try:
          os.remove(os.path.join(directory, name))
        except OSError:
          pass


def flush() -> Optional[str]:
  """Drains the tracer ring into the next shard generation and writes a
  metrics snapshot beside it. No-op (None) unless `configure`d; NEVER
  raises — this is called from worker teardown paths (batcher/fleet/
  loop close, supervisor abandonment) where telemetry failure must not
  mask the real shutdown."""
  try:
    with _export_lock:
      directory = _EXPORT["dir"]
      if directory is None:
        return None
      gen = _EXPORT["gen"]
      _EXPORT["gen"] = gen + 1
      role = _EXPORT["role"]
      skew_ns = _EXPORT["skew_ns"]
      max_gens = _EXPORT["max_gens"]
    tracer = obs_trace.get_tracer()
    events = tracer.events()
    tracer.clear()  # drain: shard generations are disjoint windows
    pid = os.getpid()
    # The clock-alignment stamp: ONE (monotonic, epoch) pair read
    # back-to-back. Event `ts` values are perf_counter microseconds;
    # the aggregator maps them onto the epoch timeline as
    # ts + (epoch_ns - perf_ns)/1e3.
    perf_ns = time.perf_counter_ns()
    epoch_ns = time.time_ns() + skew_ns
    payload = {"graftrace": "v1", "role": role, "pid": pid, "gen": gen,
               "clock": {"perf_ns": perf_ns, "epoch_ns": epoch_ns},
               "traceEvents": events, "displayTimeUnit": "ms"}
    path = os.path.join(directory, f"trace-{pid}-{gen:06d}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
      json.dump(payload, f)
    os.replace(tmp, path)
    registry = obs_metrics.get_registry()
    # The metrics shard carries the SAME paired clock stamp as the
    # trace shard (one back-to-back read, above): `graftscope watch`
    # computes metric staleness from it (now - epoch_ns) and skips a
    # dead worker's final shard once it ages past the staleness bound.
    metrics_payload = {"graftrace": "v1", "role": role, "pid": pid,
                       "gen": gen, "epoch_ns": epoch_ns,
                       "clock": {"perf_ns": perf_ns,
                                 "epoch_ns": epoch_ns},
                       "snapshot": registry.snapshot(),
                       "exemplars": registry.exemplars(clear=True)}
    mpath = os.path.join(directory, f"metrics-{pid}-{gen:06d}.json")
    mtmp = mpath + ".tmp"
    with open(mtmp, "w") as f:
      json.dump(metrics_payload, f)
    os.replace(mtmp, mpath)
    _prune_ring_locked(directory, pid, gen, max_gens)
    return path
  except Exception:  # noqa: BLE001 - teardown telemetry must not raise
    return None


def _reset_for_tests() -> None:
  """Disarms the exporter (test isolation; not part of the public API)."""
  with _export_lock:
    _EXPORT["dir"] = None
    _EXPORT["role"] = "worker"
    _EXPORT["gen"] = 0
    _EXPORT["max_gens"] = 8
    _EXPORT["skew_ns"] = 0
