"""Run-history records (`runs.jsonl`) and regression diffing.

The reference's only run-over-run comparison is a human reading
TensorBoard (/root/reference/models/abstract_model.py:873-936 host_call
scalars); this project's own perf history (BENCH_r01..r05, the round-5
valley, three blind OOMs) lived in hand-written markdown. This module
makes the trajectory machine-comparable: every train/bench run appends
ONE schema-versioned JSON line — step-stat summary, compile telemetry
(`obs.xray` records), memory watermark, bench numbers — to an
append-only `runs.jsonl`, and `diff_records` compares two records'
canonical metrics against direction-aware regression thresholds
(throughput regresses DOWN, step time / compile time / watermark
regress UP).

Readers are tolerant by contract: a torn tail line from a live run or a
corrupt record is skipped and counted (`runlog/corrupt_lines`), never
raised — same discipline as `bin/graftscope`'s metrics reader.

Backend-free by construction (stdlib + the metrics registry only):
`python -m tensor2robot_tpu.bin.graftscope diff` must be safe on the
tunnel machine while a training job owns the TPU
(tests/test_observability.py proves it under a poisoned JAX_PLATFORMS).
"""

from __future__ import annotations

import json
import os
import sys
import time
import uuid
from typing import Any, Dict, List, Optional, Sequence, Tuple

from tensor2robot_tpu.obs import metrics as metrics_lib

__all__ = ["SCHEMA", "SCHEMA_VERSION", "RUNS_FILENAME", "new_run_id",
           "make_record", "append_record", "read_jsonl", "load_records",
           "step_stats_summary", "overlap_summary", "key_metrics",
           "DEFAULT_THRESHOLDS",
           "diff_records", "format_diff", "trend_records", "format_trend",
           "resolve_run", "history_lines",
           "RunResolveError", "INCIDENT_SCHEMA", "INCIDENTS_FILENAME",
           "make_incident"]

SCHEMA = "graftscope-run-v1"
SCHEMA_VERSION = 1
RUNS_FILENAME = "runs.jsonl"

# Online-anomaly incident records (`obs.sentinel` is the writer; the
# flight recorder and `graftscope postmortem` are the readers). One
# JSON line per incident in `incidents.jsonl`, same tolerant-reader /
# fsynced-append contract as runs.jsonl.
INCIDENT_SCHEMA = "graftscope-incident-v1"
INCIDENT_SCHEMA_VERSION = 1
INCIDENTS_FILENAME = "incidents.jsonl"

# metric name -> (bad direction, default relative threshold). "up" means
# an increase beyond the threshold is a regression; "down" a decrease.
# Compile time gets the loosest band (host-load noise swings it), flops
# the tightest (the executable's flop count is deterministic — ANY
# growth is a real model/step change).
DEFAULT_THRESHOLDS: Dict[str, Tuple[str, float]] = {
    "examples_per_sec": ("down", 0.10),
    "mfu": ("down", 0.10),
    "step_ms": ("up", 0.10),
    "compile_time_s": ("up", 0.50),
    "flops_per_step": ("up", 0.05),
    "bytes_per_step": ("up", 0.10),
    "jaxpr_eqns": ("up", 0.25),
    "hbm_watermark_bytes": ("up", 0.10),
    # Data-plane A/B ratio (bench.py --data): stager vs Python-chain
    # throughput measured as back-to-back pairs, so host-load swings
    # cancel — the load-INVARIANT gate for the staging plane (absolute
    # examples_per_sec on that record flaps with the host; see
    # PERFORMANCE.md "Reading a data bench"). 15%: the per-run median
    # still wobbles 1.85-1.90x on this VM.
    "stager_vs_python_chain": ("down", 0.15),
    # Train-smoke data-path ratio (bench.py --smoke / CPU fallback):
    # record-fed vs synthetic device-resident throughput, paired
    # back-to-back — the load-invariant up-good gate for the REAL train
    # data path (ROADMAP item 5). Tightened 0.20 -> 0.15 when the
    # overlapped host loader moved the pair-median from ~0.65 to ~0.89
    # (PERFORMANCE.md "Reading an overlap bench"): a 15% drop from
    # there (~0.76) still clears the pre-overlap level, so the gate
    # protects the overlap win itself, not just staging parity.
    "data_vs_synthetic": ("down", 0.15),
    # graftcache cold-start gates (bench.py --cache / engine warmup,
    # PERFORMANCE.md "Reading a cache bench"): warmup_ms is wall-clock
    # (host noise — loose band), cold_vs_warm_warmup is the paired
    # cold/warm speedup ratio (>= 1; a drop toward 1 means the cache
    # stopped saving compiles — the load-invariant down-bad gate of the
    # ISSUE 7 acceptance).
    "warmup_ms": ("up", 0.50),
    "cold_vs_warm_warmup": ("down", 0.30),
    # Pipeline-schedule gates (bench.py --pp / scripts/pp_bench.sh,
    # PERFORMANCE.md "Reading a pipeline bench"): onefonb_vs_gpipe is
    # the paired step-time ratio GPipe/1F1B on the virtual 8-device
    # mesh (>= 1 when the interleaved schedule wins; back-to-back pairs
    # make it load-invariant like data_vs_synthetic — 15% band for the
    # same reason). pp_bubble_fraction is the STATIC idle-tick fraction
    # of the 1F1B schedule — deterministic from (S, M, v), so any
    # growth is a real schedule change, not noise (tightest band).
    "onefonb_vs_gpipe": ("down", 0.15),
    "pp_bubble_fraction": ("up", 0.02),
    # Stateful-session gates (bench.py --session / scripts/
    # session_bench.sh, PERFORMANCE.md "Reading a session bench"):
    # session_vs_stateless is the paired per-tick cost ratio
    # stateless-full-prefix / cached-decode at T=32 (back-to-back pairs
    # => load-invariant, like data_vs_synthetic; >= 2.0 is the ISSUE 11
    # acceptance floor, measured well above it — a 15% drop still
    # clears the floor with margin). decode_tick_ms is absolute
    # wall-clock on the 1-core host (loose band for the same reason
    # warmup_ms has one).
    "session_vs_stateless": ("down", 0.15),
    "decode_tick_ms": ("up", 0.50),
    # graftkern A/B (ISSUE 20): paired xla/kernel per-tick ratio at the
    # headline T, kernel arm forced on (Pallas interpreter on CPU, so
    # the absolute value is not a win claim there — the gate is a DRIFT
    # detector over the kernel dispatch path; back-to-back pairs make
    # it load-invariant like the other ratio gates).
    "decode_kernel_vs_xla": ("down", 0.15),
    # Fleet-serving gates (bench.py --fleet / scripts/fleet_bench.sh,
    # PERFORMANCE.md "Reading a fleet bench"): fleet_vs_single_replica
    # is the paired 1-vs-2-replica goodput ratio under open-loop load
    # (back-to-back pairs => load-invariant; >= 1.5 is the ISSUE 12
    # acceptance floor). fleet_rollout_shed is the shed/failed count
    # inside the zero-downtime rollout window — expected 0, so ANY
    # growth is a regression of the "no request fails during a
    # rollout" pin (threshold 0: a 0 -> nonzero move reads as rel=inf
    # and flags).
    "fleet_vs_single_replica": ("down", 0.15),
    "fleet_rollout_shed": ("up", 0.0),
    # Chaos/robustness gates (bench.py --chaos / scripts/chaos_bench.sh,
    # PERFORMANCE.md "Reading a chaos bench"): chaos_goodput_ratio is
    # the paired faulted/clean serving-goodput ratio under the seeded
    # fault storm (back-to-back pairs => load-invariant like
    # data_vs_synthetic; a drop means recovery got more expensive or
    # stopped working). chaos_recovery_ms is the worst per-fault-class
    # recovery wall time (probation readmit / divergence rewind) on the
    # 1-core host — wall-clock, so it gets the loose band warmup_ms has.
    "chaos_goodput_ratio": ("down", 0.15),
    "chaos_recovery_ms": ("up", 0.50),
    # graftloop gates (bench.py --loop / scripts/loop_bench.sh,
    # PERFORMANCE.md "Reading a loop bench"): loop_goodput_ratio is the
    # paired chaos/clean COLLECTION goodput ratio (episodes/s) with the
    # full actor/learner/deploy loop under the seeded storm
    # (back-to-back arms => load-invariant; ISSUE 14 acceptance floor
    # 0.8 — a drop means actor restarts / staleness drains / publish
    # stalls started costing collection). publish_to_serve_ms is the
    # deploy-latency half of the continuous-deployment headline
    # (checkpoint-verified to rollout-complete) — wall-clock on the
    # 1-core host, so the loose warmup_ms band.
    "loop_goodput_ratio": ("down", 0.15),
    "publish_to_serve_ms": ("up", 0.50),
    # graftforge gates (bench.py --forge / scripts/forge_bench.sh,
    # PERFORMANCE.md "Reading a forge bench"): forged_vs_cold is the
    # paired cold/forged cold-start speedup ratio measured in two fresh
    # subprocesses back-to-back (load-invariant like cold_vs_warm_warmup
    # — >= 2.0 is the ISSUE 15 acceptance floor; a drop toward 1 means
    # the farm's entries stopped deserializing). forged_start_ms is the
    # absolute forged start wall on the 1-core host (loose band like
    # warmup_ms), and forge_compile_share is the fraction of the forged
    # start's warmup wall spent COMPILING (satellite: the
    # warmup_load_ms/warmup_compile_ms split) — expected 0, so any
    # growth means specific rungs went cold (read warmup_provenance).
    "forged_vs_cold": ("down", 0.30),
    "forged_start_ms": ("up", 0.50),
    "forge_compile_share": ("up", 0.0),
    # graftlint engine telemetry (`lint --runs`, PERFORMANCE.md
    # "Reading a lint record"): parse and rule wall inside the
    # single-pass engine. Wall-clock on the 1-core host, so both get
    # the loose warmup_ms band — the gate exists to catch the ~10x
    # parse regression the per-checker layout used to pay, not 10%
    # host noise.
    "lint_parse_ms": ("up", 0.50),
    "lint_rules_ms": ("up", 0.50),
    # graftrace gates (bench.py --fleet / PERFORMANCE.md "Reading a
    # timeline"): serve_queue_wait_p99_ms is the p99 of the queue_wait
    # stage in the traced fleet arm — growth means admission is
    # outpacing dispatch (wall-clock on the 1-core host, loose band).
    # trace_overhead_ratio is the PAIRED traced-vs-untraced goodput
    # cost (1 - qps_traced/qps_untraced, back-to-back arms =>
    # load-invariant; clamped at 0): the ISSUE 18 acceptance says <= 3%
    # on the CPU smoke, so any 0 -> above-noise growth flags (absolute
    # floor 0.05 via the rel=inf rule on a 0 baseline, then the 50%
    # band on a nonzero one).
    "serve_queue_wait_p99_ms": ("up", 0.50),
    "trace_overhead_ratio": ("up", 0.50),
    # graftwatch gates (bench.py --fleet / PERFORMANCE.md "Reading a
    # watch/SLO report"): fleet_utilization is the duo arm's busy/wall
    # device-second ratio from the obs.usage ledger — DOWN-bad (idle
    # devices are paid for), wall-clock on the 1-core host so it gets
    # the loose band. slo_budget_burn is the SLO engine's worst
    # fast-window burn over the bench's dedicated evaluation window —
    # UP-bad; a healthy arm measures ~0, so the 100% band plus the
    # rel=inf rule on a 0 baseline means any 0 -> nonzero move flags
    # while nonzero noise under 2x does not.
    "fleet_utilization": ("down", 0.50),
    "slo_budget_burn": ("up", 1.00),
}


class RunResolveError(ValueError):
  """A run reference did not resolve to a record (CLI exits 2 on it)."""


def new_run_id() -> str:
  return (time.strftime("%Y%m%dT%H%M%S")
          + f"-{os.getpid()}-{uuid.uuid4().hex[:6]}")


def make_record(kind: str,
                run_id: Optional[str] = None,
                platform: Optional[str] = None,
                device_kind: Optional[str] = None,
                num_devices: Optional[int] = None,
                step_stats: Optional[Dict[str, float]] = None,
                compile_records: Optional[Sequence[Dict[str, Any]]] = None,
                memory: Optional[Dict[str, float]] = None,
                bench: Optional[Dict[str, Any]] = None,
                extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
  """One schema-versioned run record (JSON-safe plain dict)."""
  if kind not in ("train", "bench"):
    raise ValueError(f"Unknown run-record kind {kind!r}")
  record: Dict[str, Any] = {
      "schema": SCHEMA,
      "schema_version": SCHEMA_VERSION,
      "kind": kind,
      "run_id": run_id or new_run_id(),
      "unix_time": time.time(),
  }
  if platform is not None:
    record["platform"] = platform
  if device_kind is not None:
    record["device_kind"] = device_kind
  if num_devices is not None:
    record["num_devices"] = int(num_devices)
  if step_stats:
    record["step_stats"] = dict(step_stats)
  if compile_records:
    record["compile"] = [dict(r) for r in compile_records]
  if memory:
    record["memory"] = dict(memory)
  if bench:
    record["bench"] = dict(bench)
  if extra:
    record["extra"] = dict(extra)
  return record


def make_incident(kind: str,
                  step: Optional[int] = None,
                  severity: str = "warn",
                  value: Optional[float] = None,
                  threshold: Optional[float] = None,
                  detail: Optional[Dict[str, Any]] = None,
                  unix_time: Optional[float] = None) -> Dict[str, Any]:
  """One schema-versioned `graftscope-incident-v1` record (JSON-safe).

  `severity` is `"warn"` (informational anomaly) or `"fatal"` (the run
  is diverging/dying — the flight recorder dumps a postmortem bundle on
  these). A non-finite `value` — the whole point of a nonfinite-loss
  incident — would violate the strict-JSON append contract
  (allow_nan=False), so it is recorded as `detail["value_repr"]` and
  the numeric field dropped.
  """
  if severity not in ("warn", "fatal"):
    raise ValueError(f"Unknown incident severity {severity!r}")
  record: Dict[str, Any] = {
      "schema": INCIDENT_SCHEMA,
      "schema_version": INCIDENT_SCHEMA_VERSION,
      "kind": str(kind),
      "severity": severity,
      "unix_time": time.time() if unix_time is None else float(unix_time),
  }
  detail = dict(detail or {})
  if step is not None:
    record["step"] = int(step)
  if value is not None:
    value = float(value)
    if value == value and abs(value) != float("inf"):
      record["value"] = value
    else:
      detail["value_repr"] = repr(value)
  if threshold is not None:
    record["threshold"] = float(threshold)
  if detail:
    record["detail"] = detail
  return record


def append_record(path: str, record: Dict[str, Any]) -> str:
  """Appends one strict-JSON line (fsynced — a crash right after a run
  must not lose the record); returns `path`."""
  os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
  line = json.dumps(record, allow_nan=False, sort_keys=True)
  with open(path, "a") as f:
    f.write(line + "\n")
    f.flush()
    os.fsync(f.fileno())
  return path


def read_jsonl(path: str, counter_name: str = "runlog/corrupt_lines",
               registry: Optional[metrics_lib.Registry] = None
               ) -> Tuple[List[Dict[str, Any]], int]:
  """THE tolerant JSONL reader: (dict records, corrupt-line count).

  Corrupt / truncated lines (torn tail of a live run, binary garbage,
  disk hiccups) are skipped with a stderr warning and counted in
  `counter/<counter_name>` — a reader must never raise on a file a
  crashed writer left behind (`errors="replace"` keeps even invalid
  UTF-8 from raising). A missing file is an empty history. The one
  shared implementation behind `load_records` AND `bin/graftscope`'s
  metrics reader, so a tolerance fix lands in both.
  """
  reg = registry or metrics_lib.get_registry()
  records: List[Dict[str, Any]] = []
  if not os.path.isfile(path):
    return records, 0
  skipped = 0
  try:
    with open(path, errors="replace") as f:
      for line in f:
        line = line.strip()
        if not line:
          continue
        try:
          record = json.loads(line)
          if not isinstance(record, dict):
            raise ValueError("record is not an object")
          records.append(record)
        except ValueError:
          skipped += 1
  except OSError as e:
    print(f"runlog: cannot read {path}: {e}", file=sys.stderr)
    skipped += 1
  if skipped:
    reg.counter(counter_name).inc(skipped)
    print(f"runlog: skipped {skipped} corrupt line(s) in {path}",
          file=sys.stderr)
  return records, skipped


def load_records(path: str,
                 registry: Optional[metrics_lib.Registry] = None
                 ) -> List[Dict[str, Any]]:
  """Every parseable record in `path`, oldest first (see `read_jsonl`)."""
  records, _ = read_jsonl(path, registry=registry)
  return records


def step_stats_summary(snapshot: Dict[str, float]) -> Dict[str, float]:
  """Run-record step-stat summary from a metrics-registry snapshot
  (the `stepstats/*` histograms `obs.stepstats` feeds every window)."""
  out: Dict[str, float] = {}
  for hist, dst in (("step_ms", "step_ms"), ("device_ms", "device_ms"),
                    ("data_wait_ms", "data_wait_ms"),
                    ("examples_per_sec", "examples_per_sec")):
    for stat in ("mean", "p50", "p90"):
      value = snapshot.get(f"hist/stepstats/{hist}/{stat}")
      if value is not None:
        out[f"{dst}_{stat}"] = float(value)
  count = snapshot.get("hist/stepstats/step_ms/count")
  if count is not None:
    out["windows"] = float(count)
  compiles = snapshot.get("counter/stepstats/compile_events")
  if compiles is not None:
    out["compile_events"] = float(compiles)
  # Overlapped-host-pipeline attribution, so a data_wait_ms movement in
  # a diff is attributable stage by stage from the same record.
  out.update(overlap_summary(snapshot))
  return out


def overlap_summary(snapshot: Dict[str, float]) -> Dict[str, float]:
  """`data/overlap_*` stage attribution from a registry snapshot —
  per-stage timing means/p90s and queue-depth gauges (fed by
  data/overlap.py + DevicePrefetcher), under ONE canonical key shape
  (`overlap_<stage>_<stat>`). The single munging shared by the train
  run record (`step_stats_summary`) and the bench headline's `overlap`
  block, so one runs.jsonl history can never carry two spellings of
  the same stage metric."""
  out: Dict[str, float] = {}
  for key, value in snapshot.items():
    if key.startswith("hist/data/overlap_") and key.endswith(
        ("/mean", "/p90")):
      out["overlap_"
          + key[len("hist/data/overlap_"):].replace("/", "_")] = (
              float(value))
    elif key.startswith("gauge/data/overlap_"):
      out["overlap_" + key[len("gauge/data/overlap_"):]] = float(value)
  return out


def _primary_compile_record(record: Dict[str, Any]
                            ) -> Optional[Dict[str, Any]]:
  """The PRIMARY compile record — the first train-named one (the main
  loop/step, analyzed on first dispatch), falling back to the first.
  Summing across records would diff the telemetry SHAPE, not the
  compiler: a run that also analyzed a loop tail or an in-process
  predictor must not read as a compile-time regression against one
  that didn't."""
  compiles = record.get("compile") or []
  if not compiles:
    return None
  return next((r for r in compiles
               if "train" in str(r.get("name", ""))), compiles[0])


def _primary_compile_cache_hit(record: Dict[str, Any]) -> Optional[bool]:
  """Whether the primary executable came out of graftcache (None when
  the record carries no compile records or no cache block)."""
  primary = _primary_compile_record(record)
  if primary is None or "cache" not in primary:
    return None
  return bool((primary.get("cache") or {}).get("hit"))


def key_metrics(record: Dict[str, Any]) -> Dict[str, float]:
  """The canonical comparable metrics of one record (diff vocabulary).

  Sourced in priority order: step-stat summary, then bench headline
  fields, then compile records (the `train`-named record is primary —
  XLA prices a scan body once, so loop-mode flops are already
  per-step), then the memory watermark. Missing sources just omit keys.
  """
  out: Dict[str, float] = {}
  step_stats = record.get("step_stats") or {}
  if step_stats.get("examples_per_sec_mean") is not None:
    out["examples_per_sec"] = float(step_stats["examples_per_sec_mean"])
  if step_stats.get("step_ms_mean") is not None:
    out["step_ms"] = float(step_stats["step_ms_mean"])
  bench = record.get("bench") or {}
  if bench.get("value") is not None and "sec" in str(bench.get("unit", "")):
    out.setdefault("examples_per_sec", float(bench["value"]))
  if bench.get("step_sec") is not None:
    out.setdefault("step_ms", float(bench["step_sec"]) * 1e3)
  if bench.get("mfu") is not None:
    out["mfu"] = float(bench["mfu"])
  if bench.get("stager_vs_python_chain") is not None:
    out["stager_vs_python_chain"] = float(bench["stager_vs_python_chain"])
  if bench.get("data_vs_synthetic") is not None:
    out["data_vs_synthetic"] = float(bench["data_vs_synthetic"])
  # graftcache cold-start metrics (bench.py --cache headlines; the
  # serve headline's engine warmup lands here too when present).
  if bench.get("warmup_ms") is not None:
    out["warmup_ms"] = float(bench["warmup_ms"])
  if bench.get("cold_vs_warm_warmup") is not None:
    out["cold_vs_warm_warmup"] = float(bench["cold_vs_warm_warmup"])
  # Pipeline-schedule bench (bench.py --pp): the load-invariant paired
  # step-time ratio and the static 1F1B bubble fraction.
  if bench.get("onefonb_vs_gpipe") is not None:
    out["onefonb_vs_gpipe"] = float(bench["onefonb_vs_gpipe"])
  if bench.get("pp_bubble_fraction") is not None:
    out["pp_bubble_fraction"] = float(bench["pp_bubble_fraction"])
  # Session-serving bench (bench.py --session): the load-invariant
  # paired stateless/decode per-tick cost ratio + the absolute decode
  # tick (both at T=32, the headline config).
  if bench.get("session_vs_stateless") is not None:
    out["session_vs_stateless"] = float(bench["session_vs_stateless"])
  if bench.get("decode_tick_ms") is not None:
    out["decode_tick_ms"] = float(bench["decode_tick_ms"])
  if bench.get("decode_kernel_vs_xla") is not None:
    out["decode_kernel_vs_xla"] = float(bench["decode_kernel_vs_xla"])
  # Fleet-serving bench (bench.py --fleet): the load-invariant paired
  # replica-scaling ratio and the rollout-window shed/failure count.
  if bench.get("fleet_vs_single_replica") is not None:
    out["fleet_vs_single_replica"] = float(bench["fleet_vs_single_replica"])
  # Chaos bench (bench.py --chaos): goodput under the seeded fault
  # storm vs clean, and the worst per-fault-class recovery time.
  if bench.get("chaos_goodput_ratio") is not None:
    out["chaos_goodput_ratio"] = float(bench["chaos_goodput_ratio"])
  if bench.get("chaos_recovery_ms") is not None:
    out["chaos_recovery_ms"] = float(bench["chaos_recovery_ms"])
  rollout = bench.get("rollout") or {}
  if rollout.get("window_shed") is not None:
    out["fleet_rollout_shed"] = float(rollout["window_shed"])
  # graftforge bench (bench.py --forge): the paired cold/forged start
  # ratio, the absolute forged start, and the forged start's compile
  # share (0 when every rung deserialized).
  if bench.get("forged_vs_cold") is not None:
    out["forged_vs_cold"] = float(bench["forged_vs_cold"])
  if bench.get("forged_start_ms") is not None:
    out["forged_start_ms"] = float(bench["forged_start_ms"])
  if bench.get("forge_compile_share") is not None:
    out["forge_compile_share"] = float(bench["forge_compile_share"])
  # graftlint telemetry (lint --runs): single-pass engine parse/rule
  # wall, diff-gated so a rule-engine regression shows up like any
  # other bench family.
  if bench.get("lint_parse_ms") is not None:
    out["lint_parse_ms"] = float(bench["lint_parse_ms"])
  if bench.get("lint_rules_ms") is not None:
    out["lint_rules_ms"] = float(bench["lint_rules_ms"])
  # graftrace telemetry (bench.py --fleet): traced-arm queue-wait p99
  # and the paired tracing-overhead ratio, diff-gated like every other
  # bench family.
  if bench.get("serve_queue_wait_p99_ms") is not None:
    out["serve_queue_wait_p99_ms"] = float(
        bench["serve_queue_wait_p99_ms"])
  if bench.get("trace_overhead_ratio") is not None:
    out["trace_overhead_ratio"] = float(bench["trace_overhead_ratio"])
  # graftwatch telemetry (bench.py --fleet): the ledger's fleet-wide
  # device utilization and the SLO engine's worst fast-window burn.
  if bench.get("fleet_utilization") is not None:
    out["fleet_utilization"] = float(bench["fleet_utilization"])
  if bench.get("slo_budget_burn") is not None:
    out["slo_budget_burn"] = float(bench["slo_budget_burn"])
  compiles = record.get("compile") or []
  if compiles:
    primary = _primary_compile_record(record)
    out["compile_time_s"] = (
        float(primary.get("trace_s") or 0.0)
        + float(primary.get("lower_s") or 0.0)
        + float(primary.get("compile_s") or 0.0))
    for src, dst in (("flops", "flops_per_step"),
                     ("bytes_accessed", "bytes_per_step"),
                     ("jaxpr_eqns", "jaxpr_eqns")):
      if primary.get(src) is not None:
        out[dst] = float(primary[src])
  memory = record.get("memory") or {}
  if memory.get("hbm_watermark_bytes"):
    out["hbm_watermark_bytes"] = float(memory["hbm_watermark_bytes"])
  return out


def _bench_not_comparable(a: Dict[str, Any], b: Dict[str, Any]) -> bool:
  """True when two bench records' headline numbers measure different
  things: different metric names, or the same smoke metric across the
  PR-7 record-fed semantic boundary (`data_vs_synthetic` on one side
  only). `diff_records` lists-but-never-flags across these; the
  matching `comparability_warnings` entries do the shouting."""
  metric_a = (a.get("bench") or {}).get("metric")
  metric_b = (b.get("bench") or {}).get("metric")
  if not metric_a or not metric_b:
    return False
  if metric_a != metric_b:
    return True
  has_dvs_a = (a.get("bench") or {}).get("data_vs_synthetic") is not None
  has_dvs_b = (b.get("bench") or {}).get("data_vs_synthetic") is not None
  return has_dvs_a != has_dvs_b


def diff_records(a: Dict[str, Any], b: Dict[str, Any],
                 thresholds: Optional[Dict[str, Tuple[str, float]]] = None,
                 default_threshold: float = 0.10
                 ) -> List[Dict[str, Any]]:
  """Metric deltas b-vs-a with direction-aware regression flags.

  `thresholds` overrides/extends `DEFAULT_THRESHOLDS` per metric;
  metrics absent from both maps regress on |relative change| >
  `default_threshold`. A metric present in only one record is listed
  (delta None) but never flagged — new telemetry must not read as a
  regression. Two bench records with DIFFERENT bench metric names
  (TPU headline vs CPU fallback, cold-start vs warm-start, serve vs
  data) are likewise listed-not-flagged: `comparability_warnings`
  already shouts that the deltas are not meaningful, and a bogus
  exit-3 across that boundary would train people to ignore the gate.
  """
  merged = dict(DEFAULT_THRESHOLDS)
  merged.update(thresholds or {})
  cross_metric = _bench_not_comparable(a, b)
  hit_a, hit_b = (_primary_compile_cache_hit(a),
                  _primary_compile_cache_hit(b))
  cache_hit_differs = (hit_a is not None and hit_b is not None
                       and hit_a != hit_b)
  metrics_a, metrics_b = key_metrics(a), key_metrics(b)
  deltas: List[Dict[str, Any]] = []
  for name in sorted(set(metrics_a) | set(metrics_b)):
    va, vb = metrics_a.get(name), metrics_b.get(name)
    entry: Dict[str, Any] = {"metric": name, "a": va, "b": vb,
                             "delta": None, "rel": None,
                             "regressed": False}
    if va is not None and vb is not None:
      entry["delta"] = vb - va
      rel = ((vb - va) / abs(va)) if va else (0.0 if vb == va
                                             else float("inf"))
      entry["rel"] = rel
      direction, threshold = merged.get(name, (None, default_threshold))
      entry["threshold"] = threshold
      if direction == "up":
        entry["regressed"] = rel > threshold
      elif direction == "down":
        entry["regressed"] = rel < -threshold
      else:
        entry["regressed"] = abs(rel) > threshold
      if cross_metric:
        entry["regressed"] = False
      if name == "compile_time_s" and cache_hit_differs:
        # A cache HIT rewrites compile_s to ~0 (the compile was paid by
        # an earlier process); hit-vs-miss compile-time deltas price
        # cache economics, not the compiler. Listed + warned, never
        # flagged.
        entry["regressed"] = False
    deltas.append(entry)
  return deltas


def _describe(record: Dict[str, Any]) -> str:
  when = record.get("unix_time")
  stamp = (time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(when))
           if when else "?")
  return (f"{record.get('run_id', '?')} ({record.get('kind', '?')}, "
          f"{record.get('platform', '?')}, {stamp})")


def comparability_warnings(a: Dict[str, Any], b: Dict[str, Any]
                           ) -> List[str]:
  """Reasons the two records' deltas may not be meaningful.

  The recurring case: a tunnel outage makes bench fall back to the CPU
  smoke config (its own metric name, NOT comparable to the TPU number —
  bench.py docstring), yet both records land in the same `runs.jsonl`
  and `key_metrics` folds both onto `examples_per_sec`. Diffing across
  that boundary must shout, not silently flag a bogus regression.
  """
  warnings = []
  for field in ("platform", "kind", "device_kind"):
    va, vb = a.get(field), b.get(field)
    if va and vb and va != vb:
      warnings.append(f"{field} differs: {va} vs {vb}")
  metric_a = (a.get("bench") or {}).get("metric")
  metric_b = (b.get("bench") or {}).get("metric")
  if metric_a and metric_b and metric_a != metric_b:
    warnings.append(f"bench metric differs: {metric_a} vs {metric_b}")
  # PR-7 semantic boundary: qtopt_grasps_per_sec_cpu_smoke switched
  # from a synthetic device-resident feed to the real record pipeline
  # (ISSUE 7 kept the name — ROADMAP item 5 tracks it). A record-fed
  # headline carries data_vs_synthetic; diffing it against a pre-PR-7
  # record is a ~4x apparent drop that is a measurement change, not a
  # regression.
  has_dvs_a = (a.get("bench") or {}).get("data_vs_synthetic") is not None
  has_dvs_b = (b.get("bench") or {}).get("data_vs_synthetic") is not None
  if metric_a and metric_a == metric_b and has_dvs_a != has_dvs_b:
    warnings.append(
        "smoke headline semantics differ: one side is record-fed "
        "(data_vs_synthetic present), the other synthetic (pre-PR-7)")
  hit_a, hit_b = (_primary_compile_cache_hit(a),
                  _primary_compile_cache_hit(b))
  if hit_a is not None and hit_b is not None and hit_a != hit_b:
    warnings.append(
        "graftcache hit/miss differs for the primary executable: "
        "compile_time_s deltas price cache economics, not the compiler")
  return warnings


def format_diff(a: Dict[str, Any], b: Dict[str, Any],
                deltas: Sequence[Dict[str, Any]]) -> str:
  lines = ["graftscope diff",
           f"  A: {_describe(a)}",
           f"  B: {_describe(b)}"]
  for warning in comparability_warnings(a, b):
    lines.append(f"  WARNING: {warning} — deltas may not be comparable")
  lines.append(f"  {'metric':<22}{'A':>16}{'B':>16}{'Δ%':>9}  verdict")
  regressions = 0
  for d in deltas:
    fmt = lambda v: f"{v:>16.6g}" if v is not None else f"{'—':>16}"
    if d["rel"] is None:
      verdict = "(only one run)"
      rel = f"{'—':>9}"
    else:
      rel = f"{100.0 * d['rel']:>+8.1f}%"
      if d["regressed"]:
        regressions += 1
        verdict = f"REGRESSED (>{100.0 * d['threshold']:.0f}%)"
      else:
        verdict = "ok"
    lines.append(f"  {d['metric']:<22}{fmt(d['a'])}{fmt(d['b'])}"
                 f"{rel}  {verdict}")
  lines.append(f"  {regressions} regression(s) beyond threshold"
               if regressions else "  no regressions beyond thresholds")
  return "\n".join(lines) + "\n"


def _median(values: Sequence[float]) -> float:
  ordered = sorted(values)
  mid = len(ordered) // 2
  if len(ordered) % 2:
    return float(ordered[mid])
  return (ordered[mid - 1] + ordered[mid]) / 2.0


def trend_records(records: Sequence[Dict[str, Any]], k: int = 3,
                  thresholds: Optional[Dict[str, Tuple[str, float]]] = None,
                  default_threshold: float = 0.10
                  ) -> List[Dict[str, Any]]:
  """N-record trend evaluation (`graftscope diff --trend`): per key
  metric, the MEDIAN of the last `k` records against the median of the
  `k` before them, judged by the same direction-aware thresholds
  `diff_records` uses.

  Pairwise diffing two wall-clock-noisy records flaps; the
  median-of-K window is the same trick the bench's paired arms use,
  applied along the history axis — a metric must move for several
  consecutive runs before the trend flags. Metrics with fewer than
  `k + 1` observations are skipped (no prior window to difference
  against); the prior window is allowed to be short (down to one
  record) so a freshly added metric starts trending as soon as it has
  any history at all. Records whose `key_metrics` lack a metric simply
  don't contribute to that metric's series (mixed-family histories —
  one runs.jsonl holding train AND fleet records — trend per metric,
  not per record).
  """
  if k < 1:
    raise ValueError(f"k must be >= 1, got {k}")
  series: Dict[str, List[float]] = {}
  for record in records:
    for name, value in key_metrics(record).items():
      series.setdefault(name, []).append(float(value))
  merged = dict(DEFAULT_THRESHOLDS)
  merged.update(thresholds or {})
  out: List[Dict[str, Any]] = []
  for name in sorted(series):
    values = series[name]
    if len(values) < k + 1:
      continue
    recent = values[-k:]
    prior = values[max(len(values) - 2 * k, 0):-k]
    recent_med = _median(recent)
    prior_med = _median(prior)
    delta = recent_med - prior_med
    rel = ((delta / abs(prior_med)) if prior_med
           else (0.0 if recent_med == prior_med else float("inf")))
    direction, threshold = merged.get(name, (None, default_threshold))
    if direction == "up":
      regressed = rel > threshold
    elif direction == "down":
      regressed = rel < -threshold
    else:
      regressed = abs(rel) > threshold
    out.append({
        "metric": name, "n": len(values),
        "prior": prior_med, "recent": recent_med,
        "delta": delta, "rel": rel,
        "threshold": threshold, "regressed": regressed,
    })
  return out


def format_trend(source: str, trends: Sequence[Dict[str, Any]],
                 k: int = 3) -> str:
  lines = [f"graftscope trend: {source} "
           f"(median of last {k} vs prior {k})",
           f"  {'metric':<22}{'prior':>16}{'recent':>16}{'Δ%':>9}"
           "  verdict"]
  regressions = 0
  for t in trends:
    rel = (f"{100.0 * t['rel']:>+8.1f}%" if t["rel"] != float("inf")
           else f"{'+inf':>9}")
    if t["regressed"]:
      regressions += 1
      verdict = f"REGRESSED (>{100.0 * t['threshold']:.0f}%)"
    else:
      verdict = "ok"
    lines.append(f"  {t['metric']:<22}{t['prior']:>16.6g}"
                 f"{t['recent']:>16.6g}{rel}  {verdict}")
  if not trends:
    lines.append("  (no metric has enough history to trend)")
  lines.append(f"  {regressions} trend regression(s) beyond threshold"
               if regressions else "  no trend regressions beyond "
               "thresholds")
  return "\n".join(lines) + "\n"


def resolve_run(ref: str) -> Tuple[Dict[str, Any], str]:
  """Resolves a run reference to (record, description).

  A reference is a model_dir (its `runs.jsonl`), a `runs.jsonl` path,
  or either with a `#selector` suffix — a run_id, or an integer index
  into the file (negative from the end). Without a selector the LATEST
  record wins.
  """
  path, selector = ref, None
  if not os.path.exists(path) and "#" in path:
    path, selector = path.rsplit("#", 1)
  if os.path.isdir(path):
    path = os.path.join(path, RUNS_FILENAME)
  if not os.path.isfile(path):
    raise RunResolveError(
        f"no run history at {ref!r} (no such file: {path})")
  records = load_records(path)
  if not records:
    raise RunResolveError(f"no parseable run records in {path}")
  if selector is None:
    return records[-1], f"{path} (latest of {len(records)})"
  try:
    index = int(selector)
  except ValueError:
    for record in reversed(records):
      if record.get("run_id") == selector:
        return record, f"{path}#{selector}"
    raise RunResolveError(f"run_id {selector!r} not found in {path}")
  try:
    return records[index], f"{path}#{index}"
  except IndexError:
    raise RunResolveError(
        f"index {index} out of range ({len(records)} record(s) in {path})")


def history_lines(records: Sequence[Dict[str, Any]], source: str
                  ) -> List[str]:
  """One line per record for `graftscope history`."""
  lines = [f"run history: {source} ({len(records)} record(s))"]
  for i, record in enumerate(records):
    metrics = key_metrics(record)
    parts = []
    for name in ("examples_per_sec", "step_ms", "compile_time_s",
                 "hbm_watermark_bytes"):
      if name in metrics:
        parts.append(f"{name}={metrics[name]:.6g}")
    lines.append(f"  [{i}] {_describe(record)} " + " ".join(parts))
  return lines
