"""Cross-process trace aggregation: graftrace shards -> one timeline.

The merge half of graftrace (obs/graftrace.py): every worker process —
router, fleet replicas, graftloop actors/learner/publisher, forge
workers — drains its tracer ring into `trace-<pid>-<gen>.json` shards
stamped with a monotonic<->epoch clock pair. This module merges a
directory of those shards into ONE Perfetto/chrome://tracing JSON:

* **Clock alignment** — each shard's event timestamps are
  `perf_counter` microseconds, meaningless across processes; the stamp
  maps them onto the shared epoch timeline
  (`ts + (epoch_ns - perf_ns)/1e3`).
* **Causal skew correction** — wall clocks skew between hosts. A
  single correction pass walks the causal edges (`parent_id`/`links`
  in event args) and shifts any process whose causally-downstream
  events would otherwise start BEFORE their upstream source — the
  distributed-tracing happened-before repair, enough for the bounded
  skews NTP leaves behind (tests inject seconds of deliberate skew).
* **Flow synthesis** — Perfetto flow events ("s"/"f" pairs) are
  synthesized centrally here from the args ids, one per causal edge,
  which is what draws the episode -> replay shard -> learner round ->
  publish -> first-action chain as arrows in the UI.

Tolerant by contract (the runlog reader discipline): a corrupt,
truncated or foreign JSON file is counted and skipped, never raised —
a timeline over a crashed run is exactly when this tool matters.
Backend-free: stdlib only, never imports jax.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, List, Optional, Sequence

__all__ = ["discover_shards", "load_shard", "merge_timeline",
           "write_timeline", "has_causal_chain",
           "discover_metrics_shards", "load_metrics_shard",
           "latest_metrics_shards", "sum_snapshots"]

_NS_PER_US = 1000.0


def discover_shards(root: str) -> List[str]:
  """Every graftrace trace shard under `root`, recursively (a loop run
  scatters shards across model_dir subtrees)."""
  return sorted(glob.glob(os.path.join(root, "**", "trace-*.json"),
                          recursive=True))


def load_shard(path: str) -> Optional[Dict[str, Any]]:
  """One parsed shard, or None for anything that is not a well-formed
  graftrace v1 shard (tolerant-reader contract)."""
  try:
    with open(path, "r") as f:
      payload = json.load(f)
  except (OSError, ValueError):
    return None
  if not isinstance(payload, dict) or payload.get("graftrace") != "v1":
    return None
  clock = payload.get("clock")
  if (not isinstance(clock, dict) or "perf_ns" not in clock
      or "epoch_ns" not in clock):
    return None
  if not isinstance(payload.get("traceEvents"), list):
    return None
  return payload


def discover_metrics_shards(root: str) -> List[str]:
  """Every graftrace METRICS shard under `root`, recursively (the
  snapshot-carrying twin `graftrace.flush` writes beside each trace
  shard — the data plane of `graftscope watch`)."""
  return sorted(glob.glob(os.path.join(root, "**", "metrics-*.json"),
                          recursive=True))


def load_metrics_shard(path: str) -> Optional[Dict[str, Any]]:
  """One parsed metrics shard, or None for anything that is not a
  well-formed graftrace v1 metrics shard (tolerant-reader contract —
  a half-written or foreign file is skipped, never raised; the watch
  over a crashed run is exactly when this matters). The paired clock
  stamp is optional: shards written before the stamp landed still
  render, they just report staleness as unknown."""
  try:
    with open(path, "r") as f:
      payload = json.load(f)
  except (OSError, ValueError):
    return None
  if not isinstance(payload, dict) or payload.get("graftrace") != "v1":
    return None
  if not isinstance(payload.get("snapshot"), dict):
    return None
  return payload


def latest_metrics_shards(root: str) -> Dict[str, Any]:
  """{"shards": [payload...], "skipped": n}: the NEWEST generation per
  worker pid (earlier generations are superseded windows of the same
  registry — summing them would double-count every cumulative
  counter), with unreadable files counted, not hidden."""
  newest: Dict[Any, Dict[str, Any]] = {}
  skipped = 0
  for path in discover_metrics_shards(root):
    shard = load_metrics_shard(path)
    if shard is None:
      skipped += 1
      continue
    pid = shard.get("pid")
    held = newest.get(pid)
    if held is None or shard.get("gen", 0) >= held.get("gen", 0):
      newest[pid] = shard
  shards = sorted(newest.values(),
                  key=lambda s: (str(s.get("role")), s.get("pid") or 0))
  return {"shards": shards, "skipped": skipped}


def sum_snapshots(shards: Sequence[Dict[str, Any]]) -> Dict[str, float]:
  """One fleet-wide flat snapshot from per-worker shards: counters SUM
  across workers (cumulative event counts compose), gauges and
  histogram stats take the per-key MAX (point-in-time levels don't sum;
  max is the conservative read for every shipped gauge/stat — worst
  staleness, worst p99, highest watermark)."""
  out: Dict[str, float] = {}
  for shard in shards:
    for key, value in shard.get("snapshot", {}).items():
      if not isinstance(value, (int, float)):
        continue
      value = float(value)
      if key.startswith("counter/"):
        out[key] = out.get(key, 0.0) + value
      else:
        out[key] = max(out.get(key, value), value)
  return out


def _event_args(event: Dict[str, Any]) -> Dict[str, Any]:
  args = event.get("args")
  return args if isinstance(args, dict) else {}


def _causal_sources(event: Dict[str, Any]) -> List[str]:
  """The span_ids this event causally follows (parent + links)."""
  args = _event_args(event)
  sources: List[str] = []
  parent = args.get("parent_id")
  if isinstance(parent, str):
    sources.append(parent)
  links = args.get("links")
  if isinstance(links, (list, tuple)):
    sources.extend(l for l in links if isinstance(l, str))
  return sources


def _span_index(events: Sequence[Dict[str, Any]]
                ) -> Dict[str, Dict[str, Any]]:
  """span_id -> earliest timed event carrying it (the flow anchor).
  Many events can share one span_id (everything recorded under one
  context activation); the earliest is the span's birth."""
  index: Dict[str, Dict[str, Any]] = {}
  for event in events:
    if event.get("ph") not in ("X", "i"):
      continue
    span_id = _event_args(event).get("span_id")
    if not isinstance(span_id, str):
      continue
    held = index.get(span_id)
    if held is None or event.get("ts", 0.0) < held.get("ts", 0.0):
      index[span_id] = event
  return index


def _correct_skew(events: List[Dict[str, Any]]) -> Dict[int, float]:
  """Single happened-before repair pass: for every causal edge whose
  source and destination live in different processes, the destination
  process is shifted forward just enough that no event starts before
  its cause. Returns {pid: shift_us} for the shifted processes."""
  index = _span_index(events)
  shift_us: Dict[int, float] = {}
  for event in events:
    if event.get("ph") not in ("X", "i"):
      continue
    dst_pid = event.get("pid")
    for source_id in _causal_sources(event):
      source = index.get(source_id)
      if source is None or source.get("pid") == dst_pid:
        continue
      needed = float(source.get("ts", 0.0)) - float(event.get("ts", 0.0))
      if needed > shift_us.get(dst_pid, 0.0):
        shift_us[dst_pid] = needed
  for event in events:
    delta = shift_us.get(event.get("pid"))
    if delta and "ts" in event:
      event["ts"] = float(event["ts"]) + delta
  return shift_us


def _synthesize_flows(events: Sequence[Dict[str, Any]]
                      ) -> List[Dict[str, Any]]:
  """One Perfetto flow ("s" at the source span, "f" at the follower)
  per causal edge recoverable from the args ids."""
  index = _span_index(events)
  flows: List[Dict[str, Any]] = []
  flow_id = 0
  for event in events:
    if event.get("ph") not in ("X", "i"):
      continue
    for source_id in _causal_sources(event):
      source = index.get(source_id)
      if source is None or source is event:
        continue
      flow_id += 1
      src_ts = float(source.get("ts", 0.0)) + float(source.get("dur",
                                                               0.0))
      dst_ts = float(event.get("ts", 0.0))
      flows.append({"name": "graftrace", "cat": "graftrace", "ph": "s",
                    "id": flow_id, "pid": source.get("pid"),
                    "tid": source.get("tid"),
                    "ts": min(src_ts, dst_ts)})
      flows.append({"name": "graftrace", "cat": "graftrace", "ph": "f",
                    "bp": "e", "id": flow_id, "pid": event.get("pid"),
                    "tid": event.get("tid"), "ts": dst_ts})
  return flows


def merge_timeline(root: str) -> Dict[str, Any]:
  """Merges every shard under `root` into one clock-aligned timeline.

  Returns {"payload": <Perfetto JSON object>, "stats": {...}}. The
  stats block reports what was covered AND what was dropped (`skipped`
  counts unreadable shards — silent truncation would read as "covered
  everything" when it didn't).
  """
  paths = discover_shards(root)
  timed: List[Dict[str, Any]] = []
  meta: List[Dict[str, Any]] = []
  roles: Dict[int, str] = {}
  shards_used = 0
  skipped = 0
  for path in paths:
    shard = load_shard(path)
    if shard is None:
      skipped += 1
      continue
    shards_used += 1
    clock = shard["clock"]
    offset_us = (float(clock["epoch_ns"]) - float(clock["perf_ns"])
                 ) / _NS_PER_US
    pid = shard.get("pid")
    if isinstance(pid, int):
      roles.setdefault(pid, str(shard.get("role", "worker")))
    for event in shard["traceEvents"]:
      if not isinstance(event, dict):
        continue
      event = dict(event)
      if event.get("ph") == "M":
        meta.append(event)
        continue
      if "ts" in event:
        event["ts"] = float(event["ts"]) + offset_us
      timed.append(event)
  shift_us = _correct_skew(timed)
  flows = _synthesize_flows(timed)
  timed.sort(key=lambda e: e.get("ts", 0.0))
  process_meta = [{"name": "process_name", "ph": "M", "pid": pid,
                   "args": {"name": f"{role} (pid {pid})"}}
                  for pid, role in sorted(roles.items())]
  payload = {"traceEvents": process_meta + meta + timed + flows,
             "displayTimeUnit": "ms"}
  return {
      "payload": payload,
      "stats": {
          "shards": shards_used,
          "skipped": skipped,
          "events": len(timed),
          "flow_links": len(flows) // 2,
          "processes": len(roles),
          "skew_corrected_pids": {str(pid): round(us / 1e3, 3)
                                  for pid, us in shift_us.items()},
      },
  }


def write_timeline(root: str, out_path: str) -> Dict[str, Any]:
  """merge_timeline + atomic write; returns the stats block."""
  merged = merge_timeline(root)
  os.makedirs(os.path.dirname(os.path.abspath(out_path)), exist_ok=True)
  tmp = out_path + ".tmp"
  with open(tmp, "w") as f:
    json.dump(merged["payload"], f)
  os.replace(tmp, out_path)
  stats = dict(merged["stats"])
  stats["path"] = out_path
  return stats


def has_causal_chain(events: Sequence[Dict[str, Any]],
                     names: Sequence[str]) -> bool:
  """Whether some single chain of causal edges walks events named
  `names[0] -> names[1] -> ... -> names[-1]` (each hop a parent/links
  edge). The loop-bench acceptance check: one episode's collect span
  flow-linked through replay shard, learner round, publish, and first
  served action."""
  if not names:
    return True
  by_source: Dict[str, List[Dict[str, Any]]] = {}
  for event in events:
    for source_id in _causal_sources(event):
      by_source.setdefault(source_id, []).append(event)
  frontier = [e for e in events if e.get("name") == names[0]
              and isinstance(_event_args(e).get("span_id"), str)]
  for name in names[1:]:
    next_frontier: List[Dict[str, Any]] = []
    seen = set()
    for event in frontier:
      span_id = _event_args(event).get("span_id")
      for follower in by_source.get(span_id, ()):
        if follower.get("name") != name:
          continue
        follower_span = _event_args(follower).get("span_id")
        if follower_span in seen:
          continue
        seen.add(follower_span)
        next_frontier.append(follower)
    if not next_frontier:
      return False
    frontier = next_frontier
  # Single-name chains still require at least one matching anchor event
  # (an empty frontier never walked anything).
  return bool(frontier)
