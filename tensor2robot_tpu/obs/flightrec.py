"""Crash/hang flight recorder: bounded ring buffers + postmortem bundles.

The round-5 postmortem gap in one sentence: when the axon tunnel died at
14:10 UTC the only evidence was a CPU-fallback metric name in
BENCH_r05.json — no record of the last healthy steps, the incident
sequence, or when the heartbeat turned (VERDICT r5 weakness #1). The
reference stack is no better: a crashed TPUEstimator job leaves whatever
TensorBoard flushed (/root/reference/models/abstract_model.py:873-936).

The `FlightRecorder` keeps O(1)-memory ring buffers of recent step
records and sentinel incidents, and on a fatal event dumps a
`graftscope-postmortem-v1` bundle — the last N steps, incidents, the
tunnel-heartbeat timeline (`utils.backend.tunnel_health()`), a metrics
registry snapshot, the buffered trace spans, and (for crashes) the
exception traceback — into `<out_dir>/postmortem-<stamp>-<reason>/`.
Dump triggers:

* **unhandled exception** — the train loop wraps its body and calls
  `dump("exception", exc=e)` before re-raising;
* **SIGTERM** — an installed handler that is TUNNEL-SAFE by
  construction: it records and flushes HOST-side state only and never
  touches the device (NOTES_r1/r2: signalling a process mid TPU client
  use is the documented tunnel-wedging trigger — the dump must not add
  a device call to that hazard window), then chains to the previous
  disposition so the process still terminates;
* **watchdog hang timeout** — a daemon thread dumps when the loop has
  not called `touch()` within `hang_timeout_secs` (a wedged tunnel
  stalls a device call forever; the bundle is written while the hang is
  LIVE, from host state only);
* **fatal sentinel incident** — `record_incident` auto-dumps once per
  fatal kind (NaN loss/params).

Everything in a bundle is host-side state that already existed;
`graftscope postmortem <dir>` renders it. Backend-free by construction:
this module never imports jax (tests/test_sentinel.py proves import,
recording, watchdog and the SIGTERM handler under a poisoned
JAX_PLATFORMS).
"""

from __future__ import annotations

import collections
import json
import math
import os
import re
import signal
import sys
import threading
import time
import traceback
from typing import Any, Deque, Dict, List, Mapping, Optional

from tensor2robot_tpu.obs import metrics as metrics_lib
from tensor2robot_tpu.obs import trace as trace_lib

__all__ = ["FlightRecorder", "POSTMORTEM_SCHEMA", "BUNDLE_FILENAME",
           "FLIGHTREC_DIRNAME", "find_bundles"]

POSTMORTEM_SCHEMA = "graftscope-postmortem-v1"
POSTMORTEM_SCHEMA_VERSION = 1
BUNDLE_FILENAME = "postmortem.json"
BUNDLE_PREFIX = "postmortem-"
FLIGHTREC_DIRNAME = "flightrec"
TRACE_FILENAME = "trace.graftscope.json"


def _json_safe(value):
  """Strict-JSON scalar: non-finite floats become repr strings (a NaN
  loss is exactly the datum a postmortem exists to keep)."""
  try:
    value = float(value)
  except (TypeError, ValueError):
    return str(value)
  if math.isfinite(value):
    return value
  return repr(value)


def find_bundles(path: str) -> List[str]:
  """Bundle JSON paths under `path`, oldest first.

  Accepts a bundle dir, a flightrec dir, a model_dir (searched
  recursively for `postmortem-*/postmortem.json`), or a bundle JSON
  file directly.
  """
  if os.path.isfile(path):
    return [path]
  direct = os.path.join(path, BUNDLE_FILENAME)
  if os.path.isfile(direct):
    return [direct]
  found = []
  for dirpath, dirnames, filenames in os.walk(path):
    dirnames[:] = sorted(d for d in dirnames
                         if d not in ("checkpoints", "__pycache__", ".git"))
    if (BUNDLE_FILENAME in filenames
        and os.path.basename(dirpath).startswith(BUNDLE_PREFIX)):
      found.append(os.path.join(dirpath, BUNDLE_FILENAME))
  return sorted(found)


class FlightRecorder:
  """Host-side ring buffers + postmortem dumping for one run."""

  def __init__(self,
               out_dir: str,
               capacity: int = 256,
               hang_timeout_secs: Optional[float] = None,
               registry: Optional[metrics_lib.Registry] = None,
               tracer: Optional[trace_lib.Tracer] = None,
               clock=time.time):
    self._out_dir = out_dir
    self._capacity = int(capacity)
    self._hang_timeout = (float(hang_timeout_secs)
                          if hang_timeout_secs else None)
    self._registry = registry  # None = resolve the global at dump time
    self._tracer = tracer
    self._clock = clock
    # Re-entrant ON PURPOSE: the SIGTERM handler runs on the main
    # thread and may interrupt record_step/record_incident between
    # bytecodes WHILE this thread holds the lock — a plain Lock would
    # deadlock the handler's dump() and leave the process unkillable
    # by SIGTERM (strictly worse than no handler).
    self._lock = threading.RLock()
    self._steps: Deque[Dict[str, Any]] = collections.deque(
        maxlen=self._capacity)
    self._incidents: Deque[Dict[str, Any]] = collections.deque(
        maxlen=self._capacity)
    self._dumps: List[str] = []
    self._dump_seq = 0
    self._fatal_dumped: set = set()
    self._last_touch = time.monotonic()
    self._hang_dumped = False
    self._watchdog: Optional[threading.Thread] = None
    self._watchdog_stop = threading.Event()
    self._prev_sigterm = None
    self._signal_installed = False

  # -- recording (cheap, host-only) -----------------------------------------

  def record_step(self, step: int, record: Mapping[str, Any]) -> None:
    """Appends one step/window record (the recorder-observer
    signature); values are sanitized to strict-JSON scalars."""
    entry = {"step": int(step)}
    for key, value in record.items():
      entry[str(key)] = _json_safe(value)
    with self._lock:
      self._steps.append(entry)

  def record_incident(self, incident: Mapping[str, Any]) -> None:
    """Appends a sentinel incident; auto-dumps once per FATAL kind."""
    incident = dict(incident)
    with self._lock:
      self._incidents.append(incident)
    if incident.get("severity") == "fatal":
      kind = str(incident.get("kind", "?"))
      if kind not in self._fatal_dumped:
        self._fatal_dumped.add(kind)
        self.dump(f"incident:{kind}")

  def touch(self) -> None:
    """Watchdog heartbeat — call once per loop iteration."""
    self._last_touch = time.monotonic()
    self._hang_dumped = False

  # -- lifecycle ------------------------------------------------------------

  def install(self) -> None:
    """Arms the SIGTERM handler (main thread only; silently skipped
    elsewhere) and the hang watchdog (when a timeout is configured)."""
    if self._hang_timeout and self._watchdog is None:
      self._last_touch = time.monotonic()
      self._watchdog_stop.clear()
      self._watchdog = threading.Thread(
          target=self._watchdog_main, daemon=True,
          name="flightrec-watchdog")
      self._watchdog.start()
    try:
      self._prev_sigterm = signal.signal(signal.SIGTERM,
                                         self._handle_sigterm)
      self._signal_installed = True
    except ValueError:
      self._signal_installed = False  # not the main thread

  def close(self) -> None:
    """Disarms watchdog + signal handler (restores the previous one)."""
    if self._watchdog is not None:
      self._watchdog_stop.set()
      self._watchdog.join(timeout=5.0)
      self._watchdog = None
    if self._signal_installed:
      try:
        # _prev_sigterm is None when the pre-existing handler was
        # installed outside Python (signal.signal reports None for it);
        # passing None back raises TypeError, so restore the default.
        signal.signal(signal.SIGTERM,
                      self._prev_sigterm if self._prev_sigterm is not None
                      else signal.SIG_DFL)
      except (TypeError, ValueError):
        pass
      self._signal_installed = False

  def __enter__(self) -> "FlightRecorder":
    self.install()
    return self

  def __exit__(self, exc_type, exc, tb) -> None:
    self.close()

  def _watchdog_main(self) -> None:
    poll = min(max(self._hang_timeout / 10.0, 0.05), 5.0)
    while not self._watchdog_stop.wait(poll):
      stalled = time.monotonic() - self._last_touch
      if stalled > self._hang_timeout and not self._hang_dumped:
        # Dump while the hang is LIVE (host state only — the stalled
        # device call keeps hanging undisturbed); latched until the
        # loop touches again so one hang is one bundle.
        self._hang_dumped = True
        self.dump("hang")

  def _handle_sigterm(self, signum, frame) -> None:
    # TUNNEL-SAFE BY CONSTRUCTION (NOTES_r1/r2): everything below is
    # host memory + file IO. No jax import, no device call, no fetch.
    try:
      self.dump("sigterm")
    finally:
      prev = self._prev_sigterm
      if prev is signal.SIG_IGN:
        return
      if callable(prev):
        prev(signum, frame)
        return
      # Default disposition: restore it and re-deliver so the process
      # still dies with the SIGTERM status the sender expects.
      signal.signal(signum, signal.SIG_DFL)
      os.kill(os.getpid(), signum)

  # -- dumping --------------------------------------------------------------

  def dump(self, reason: str, exc: Optional[BaseException] = None) -> str:
    """Writes one postmortem bundle dir; returns its path.

    Never raises (a failing dump must not mask the original crash) —
    on failure it prints to stderr and returns "".
    """
    try:
      return self._dump(reason, exc)
    except Exception as e:  # noqa: BLE001 - see docstring
      print(f"flightrec: postmortem dump failed "
            f"({type(e).__name__}: {e})", file=sys.stderr)
      return ""

  def _dump(self, reason: str, exc: Optional[BaseException]) -> str:
    with self._lock:
      steps = list(self._steps)
      incidents = list(self._incidents)
      self._dump_seq += 1
      seq = self._dump_seq
    registry = self._registry or metrics_lib.get_registry()
    try:
      snapshot = {k: _json_safe(v) for k, v in registry.snapshot().items()}
    except Exception:  # noqa: BLE001 - telemetry-of-telemetry
      snapshot = {}
    heartbeat = None
    try:
      # utils.backend is jax-free at module level; tunnel_health() reads
      # the host-side monitor only — safe from handlers and watchdogs.
      from tensor2robot_tpu.utils import backend

      heartbeat = backend.tunnel_health()
    except Exception:  # noqa: BLE001 - heartbeat is optional context
      pass
    exception = None
    if exc is not None:
      exception = {
          "type": type(exc).__name__,
          "message": str(exc),
          "traceback": "".join(traceback.format_exception(
              type(exc), exc, exc.__traceback__))[-20_000:],
      }
    bundle = {
        "schema": POSTMORTEM_SCHEMA,
        "schema_version": POSTMORTEM_SCHEMA_VERSION,
        "reason": reason,
        "unix_time": self._clock(),
        "pid": os.getpid(),
        "steps": steps,
        "incidents": incidents,
        "heartbeat": heartbeat,
        "metrics": snapshot,
        "watchdog": {
            "hang_timeout_secs": self._hang_timeout,
            "stalled_secs": time.monotonic() - self._last_touch,
        },
        "exception": exception,
    }
    stamp = time.strftime("%Y%m%dT%H%M%S")
    slug = re.sub(r"[^a-zA-Z0-9_.-]+", "_", reason)[:48]
    bundle_dir = os.path.join(self._out_dir,
                              f"{BUNDLE_PREFIX}{stamp}-{seq:02d}-{slug}")
    os.makedirs(bundle_dir, exist_ok=True)
    path = os.path.join(bundle_dir, BUNDLE_FILENAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
      json.dump(bundle, f, allow_nan=False, sort_keys=True)
      f.flush()
      os.fsync(f.fileno())  # SIGTERM path: the bundle must hit disk NOW
    os.replace(tmp, path)
    tracer = self._tracer or trace_lib.get_tracer()
    try:
      if tracer.events():
        tracer.save(os.path.join(bundle_dir, TRACE_FILENAME))
    except Exception:  # noqa: BLE001 - the JSON bundle is the contract
      pass
    with self._lock:
      self._dumps.append(bundle_dir)
    try:
      registry.counter("flightrec/dumps").inc()
    except Exception:  # noqa: BLE001
      pass
    print(f"flightrec: postmortem bundle ({reason}) -> {bundle_dir}",
          file=sys.stderr)
    return bundle_dir

  def dumps(self) -> List[str]:
    """Bundle dirs written by this recorder, oldest first."""
    with self._lock:
      return list(self._dumps)
