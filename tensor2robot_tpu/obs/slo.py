"""graftwatch SLO engine: declarative objectives, error budgets, and
multi-window burn-rate alerting over the graftscope metrics stream.

The reference stack's only notion of "is serving healthy" is a human
reading Estimator eval scalars after the fact
(/root/reference/utils/train_eval.py:136-151 runs eval as a blocking
phase; /root/reference/models/abstract_model.py:873-936 host_call
scalars are the entire signal surface) — there is no objective, no
error budget, and no machine answer to "should this page someone".
Production serving runs the Google-SRE formulation instead: an SLO is a
target ratio of good events over a compliance period, the ERROR BUDGET
is the allowed bad fraction, and alerting fires on the BURN RATE — how
many times faster than budget-rate the service is consuming its budget
— evaluated over a fast AND a slow window simultaneously (the
fast window catches cliffs in minutes, the slow window gates out
blips; both must exceed the factor to fire). pjit-era fleets treat
this continuous evaluation as a first-class subsystem
(arXiv:2204.06514 §4; the serving economics in arXiv:2605.25645).

This module is that layer for the graftscope registry:

* `SloSpec` — one declarative objective. Two families:
  - RATIO: a bad-events counter over a total-events counter
    (latency-SLO breaches over requests, sheds over requests, …);
    `budget` is the allowed bad/total fraction.
  - VALUE: a snapshot scalar (gauge or histogram stat) against a
    `ceiling` (policy staleness bound, publish-to-serve latency);
    each evaluation is one event, breaching when value > ceiling,
    and `budget` is the allowed breaching-sample fraction.
  Burn windows and the budget are REQUIRED at construction — an SLO
  without an explicit budget is an alert nobody sized (the
  `slo-unbudgeted` graftlint rule pins this repo-wide).
* `SloEngine` — feed it `Registry.snapshot()` dicts (or graftrace
  metrics-shard snapshots, same flat schema) via `observe()`; it keeps
  per-spec cumulative counts and a sample window, computes fast/slow
  burn rates and budget consumption, and emits ONE `SLO_BURN`
  sentinel-kind incident per episode: a rising burn-rate edge (warn,
  re-arms when the fast window clears) and a budget exhaustion latch
  (fatal, once). Incidents are `obs.runlog.make_incident` records
  fanned to sinks exactly like `obs.sentinel.Sentinel._emit` — the
  flight recorder, the fleet eviction sink and the postmortem CLI
  consume them unchanged.
* `evaluate_snapshot` — the windowless point-in-time judgment
  (cumulative bad/total vs budget) `graftscope watch` renders from
  shard files alone.

Deterministic by construction: `observe(snapshot, now=...)` takes the
clock as data, every derived number is pure arithmetic over the sample
deque, and under a seeded `obs.faultlab` storm the incident stream is
identical fault-for-fault (tests pin the exact budget-exhaustion
request count). Backend-free at import: stdlib + obs only, never jax.
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence

from tensor2robot_tpu.obs import metrics as obs_metrics
from tensor2robot_tpu.obs import runlog as runlog_lib
from tensor2robot_tpu.obs import sentinel as sentinel_lib
from tensor2robot_tpu.utils import config

__all__ = ["SloSpec", "SloEngine", "evaluate_snapshot",
           "default_serving_slos", "default_loop_slos"]

RATIO = "ratio"
VALUE = "value"

# Google-SRE multi-window default: a 14.4x burn consumes a 30-day
# budget in ~2 days — the canonical page-severity factor. Specs may
# override per objective; the budget/windows themselves have NO default
# (the slo-unbudgeted rule makes the caller own them).
DEFAULT_BURN_FACTOR = 14.4


class SloSpec:
  """One declarative service-level objective (module docstring).

  RATIO family: `bad_key` / `total_key` name cumulative counters in the
  flat snapshot schema (`counter/<name>`). VALUE family: `value_key`
  names any snapshot scalar (`gauge/<name>`, `hist/<name>/<stat>`) and
  `ceiling` is the bound. `budget`, `fast_window_s` and `slow_window_s`
  are keyword-REQUIRED: the burn math is meaningless without them and
  the `slo-unbudgeted` lint rule flags constructions that omit them.
  """

  def __init__(self, name: str, *,
               budget: float,
               fast_window_s: float,
               slow_window_s: float,
               bad_key: Optional[str] = None,
               total_key: Optional[str] = None,
               value_key: Optional[str] = None,
               ceiling: Optional[float] = None,
               burn_factor: float = DEFAULT_BURN_FACTOR,
               description: str = ""):
    if not name:
      raise ValueError("SloSpec needs a name")
    if not 0.0 < float(budget) <= 1.0:
      raise ValueError(f"budget must be in (0, 1], got {budget}")
    if not 0.0 < float(fast_window_s) < float(slow_window_s):
      raise ValueError(
          "windows must satisfy 0 < fast_window_s < slow_window_s, got "
          f"fast={fast_window_s} slow={slow_window_s}")
    ratio = bad_key is not None or total_key is not None
    value = value_key is not None or ceiling is not None
    if ratio == value:
      raise ValueError(
          "exactly one family: (bad_key, total_key) XOR "
          f"(value_key, ceiling) — got spec {name!r} with "
          f"bad_key={bad_key!r} value_key={value_key!r}")
    if ratio and (bad_key is None or total_key is None):
      raise ValueError(f"ratio spec {name!r} needs both bad_key and "
                       "total_key")
    if value and (value_key is None or ceiling is None):
      raise ValueError(f"value spec {name!r} needs both value_key and "
                       "ceiling")
    if float(burn_factor) <= 1.0:
      raise ValueError(f"burn_factor must be > 1, got {burn_factor}")
    self.name = name
    self.kind = RATIO if ratio else VALUE
    self.budget = float(budget)
    self.fast_window_s = float(fast_window_s)
    self.slow_window_s = float(slow_window_s)
    self.bad_key = bad_key
    self.total_key = total_key
    self.value_key = value_key
    self.ceiling = None if ceiling is None else float(ceiling)
    self.burn_factor = float(burn_factor)
    self.description = description

  def counts(self, snapshot: Mapping[str, float],
             prev_bad: float, prev_total: float) -> tuple:
    """Cumulative (bad, total) event counts after folding `snapshot` in.

    RATIO specs read the counters directly (already cumulative). VALUE
    specs treat each evaluated snapshot as one event: total advances by
    one per observation carrying the key, bad by one when the value
    breaches the ceiling — so the same burn/budget arithmetic covers
    both families.
    """
    if self.kind == RATIO:
      bad = float(snapshot.get(self.bad_key, 0.0) or 0.0)
      total = float(snapshot.get(self.total_key, 0.0) or 0.0)
      return bad, total
    value = snapshot.get(self.value_key)
    if value is None:
      return prev_bad, prev_total  # key absent: not an observation
    breach = float(value) > self.ceiling
    return prev_bad + (1.0 if breach else 0.0), prev_total + 1.0

  def describe(self) -> Dict[str, Any]:
    out: Dict[str, Any] = {
        "name": self.name, "kind": self.kind, "budget": self.budget,
        "fast_window_s": self.fast_window_s,
        "slow_window_s": self.slow_window_s,
        "burn_factor": self.burn_factor,
    }
    if self.kind == RATIO:
      out["bad_key"] = self.bad_key
      out["total_key"] = self.total_key
    else:
      out["value_key"] = self.value_key
      out["ceiling"] = self.ceiling
    return out


class _SpecState:
  """Per-spec accumulator: cumulative counts, the burn-window sample
  deque, and the two alert latches."""

  __slots__ = ("samples", "bad", "total", "genesis", "burning",
               "exhausted", "incidents")

  def __init__(self):
    # (now_s, cum_bad, cum_total); pruned to the slow window + one
    # baseline sample past its edge (the windowed delta needs a sample
    # AT-or-before the window start to difference against).
    self.samples: "collections.deque" = collections.deque()
    self.bad = 0.0
    self.total = 0.0
    self.genesis: Optional[tuple] = None  # first (bad, total) seen
    self.burning = False
    self.exhausted = False
    self.incidents = 0


def _windowed_burn(samples, now: float, window_s: float,
                   budget: float) -> float:
  """Burn rate over the trailing window: (bad_delta / total_delta) /
  budget, differenced against the most recent sample at-or-before the
  window start (the whole history while the window is still filling).
  0.0 with no events — no traffic is not an outage."""
  if not samples:
    return 0.0
  cutoff = now - window_s
  baseline = samples[0]
  for sample in samples:
    if sample[0] <= cutoff:
      baseline = sample
    else:
      break
  latest = samples[-1]
  bad_delta = latest[1] - baseline[1]
  total_delta = latest[2] - baseline[2]
  if total_delta <= 0.0:
    return 0.0
  return (bad_delta / total_delta) / budget


class SloEngine:
  """Continuous SLO evaluation over registry snapshots (module doc).

  `sinks` receive `graftscope-incident-v1` records (the sentinel sink
  contract — wire `Sentinel` sinks, the flight recorder, or
  `ServingFleet.sentinel_sink()` directly). `observe()` is cheap
  (pure arithmetic over the sample deque) and safe to call per request
  or per supervisor tick.
  """

  def __init__(self, specs: Sequence[SloSpec],
               sinks: Sequence[Callable[[Dict[str, Any]], Any]] = (),
               registry: Optional[obs_metrics.Registry] = None):
    if not specs:
      raise ValueError("SloEngine needs at least one SloSpec")
    names = [s.name for s in specs]
    if len(set(names)) != len(names):
      raise ValueError(f"duplicate SloSpec names: {sorted(names)}")
    self._specs = list(specs)
    self._sinks = list(sinks)
    self._registry = registry
    self._state = {spec.name: _SpecState() for spec in self._specs}

  def _reg(self) -> obs_metrics.Registry:
    # Late-bound so an engine built outside a `metrics.isolated()`
    # window still lands its telemetry in the window's registry.
    return self._registry or obs_metrics.get_registry()

  def observe(self, snapshot: Mapping[str, float],
              now: float, step: int = 0) -> List[Dict[str, Any]]:
    """Folds one snapshot sample in; returns incidents emitted NOW.

    `now` is explicit data (monotonic seconds from the caller's clock):
    evaluation is a pure function of the (snapshot, now) stream, which
    is what makes a seeded fault storm reproduce an identical incident
    stream.
    """
    emitted: List[Dict[str, Any]] = []
    for spec in self._specs:
      st = self._state[spec.name]
      st.bad, st.total = spec.counts(snapshot, st.bad, st.total)
      if st.genesis is None:
        st.genesis = (st.bad, st.total)
      st.samples.append((now, st.bad, st.total))
      self._prune(st, now, spec.slow_window_s)
      fast = _windowed_burn(st.samples, now, spec.fast_window_s,
                            spec.budget)
      slow = _windowed_burn(st.samples, now, spec.slow_window_s,
                            spec.budget)
      consumed = self._consumed(spec, st)
      reg = self._reg()
      reg.gauge(f"slo/{spec.name}/fast_burn").set(fast)
      reg.gauge(f"slo/{spec.name}/slow_burn").set(slow)
      reg.gauge(f"slo/{spec.name}/budget_consumed").set(consumed)
      if consumed >= 1.0 and not st.exhausted:
        # Budget exhaustion latches ONCE per engine lifetime: the
        # budget does not refill mid-run, so re-emitting every observe
        # would flood the stream the postmortem has to read.
        st.exhausted = True
        emitted.append(self._emit(spec, st, step, "fatal",
                                  "budget_exhausted", fast, slow,
                                  consumed, now))
      burn_now = (fast >= spec.burn_factor and slow >= spec.burn_factor)
      if burn_now and not st.burning and not st.exhausted:
        # Rising-edge burn alert; re-arms when the fast window clears
        # (one incident per burn episode, the sentinel latch idiom).
        st.burning = True
        emitted.append(self._emit(spec, st, step, "warn", "burn_rate",
                                  fast, slow, consumed, now))
      elif not burn_now and fast < spec.burn_factor:
        st.burning = False
    return emitted

  def _prune(self, st: _SpecState, now: float, slow_window_s: float
             ) -> None:
    cutoff = now - slow_window_s
    # Keep ONE sample at-or-before the window edge as the differencing
    # baseline; everything older is dead weight.
    while (len(st.samples) >= 2 and st.samples[0][0] <= cutoff
           and st.samples[1][0] <= cutoff):
      st.samples.popleft()

  def _consumed(self, spec: SloSpec, st: _SpecState) -> float:
    bad = st.bad - st.genesis[0]
    total = st.total - st.genesis[1]
    if total <= 0.0:
      return 0.0
    return (bad / total) / spec.budget

  def _emit(self, spec: SloSpec, st: _SpecState, step: int,
            severity: str, trigger: str, fast: float, slow: float,
            consumed: float, now: float) -> Dict[str, Any]:
    st.incidents += 1
    record = runlog_lib.make_incident(
        sentinel_lib.SLO_BURN, step=step, severity=severity,
        value=round(consumed, 6), threshold=spec.budget,
        detail={
            "slo": spec.name, "trigger": trigger,
            "fast_burn": round(fast, 4), "slow_burn": round(slow, 4),
            "budget_consumed": round(consumed, 6),
            "bad": st.bad - st.genesis[0],
            "total": st.total - st.genesis[1],
            "observed_s": round(now - st.samples[0][0], 3),
            "spec": spec.describe(),
        })
    reg = self._reg()
    reg.counter("sentinel/incidents").inc()
    reg.counter(f"sentinel/{sentinel_lib.SLO_BURN}").inc()
    for sink in self._sinks:
      try:
        sink(record)
      except Exception:  # noqa: BLE001 - a sink must not break evaluation
        pass
    return record

  def state(self, now: Optional[float] = None) -> Dict[str, Any]:
    """JSON-safe per-spec budget state (the bench/loop `slo` block)."""
    out: Dict[str, Any] = {}
    for spec in self._specs:
      st = self._state[spec.name]
      at = now if now is not None else (st.samples[-1][0]
                                        if st.samples else 0.0)
      bad = st.bad - (st.genesis[0] if st.genesis else 0.0)
      total = st.total - (st.genesis[1] if st.genesis else 0.0)
      out[spec.name] = {
          "kind": spec.kind,
          "budget": spec.budget,
          "bad": bad,
          "total": total,
          "ratio": round(bad / total, 6) if total else 0.0,
          "fast_burn": round(_windowed_burn(
              st.samples, at, spec.fast_window_s, spec.budget), 4),
          "slow_burn": round(_windowed_burn(
              st.samples, at, spec.slow_window_s, spec.budget), 4),
          "budget_consumed": round(self._consumed(spec, st), 6),
          "burning": st.burning,
          "exhausted": st.exhausted,
          "incidents": st.incidents,
      }
    return out

  def worst_burn(self) -> float:
    """Max fast-window burn across specs — the one-number headline
    scalar (`slo_budget_burn`, diff-gated up-bad)."""
    state = self.state()
    return max((entry["fast_burn"] for entry in state.values()),
               default=0.0)

  def healthy(self) -> bool:
    return not any(st.burning or st.exhausted
                   for st in self._state.values())


def evaluate_snapshot(specs: Sequence[SloSpec],
                      snapshot: Mapping[str, float]) -> Dict[str, Any]:
  """Windowless point-in-time judgment of one flat snapshot (summed
  graftrace metrics shards, a registry snapshot): cumulative bad/total
  per spec vs its budget. `ok` is the watch dashboard's health bit —
  cumulative-over-budget means the budget is ALREADY spent, whatever
  the windows would say. VALUE specs judge the current value against
  the ceiling directly (one sample is all a point-in-time read has)."""
  out: Dict[str, Any] = {}
  for spec in specs:
    if spec.kind == RATIO:
      bad = float(snapshot.get(spec.bad_key, 0.0) or 0.0)
      total = float(snapshot.get(spec.total_key, 0.0) or 0.0)
      ratio = bad / total if total else 0.0
      consumed = (ratio / spec.budget) if total else 0.0
      out[spec.name] = {
          "kind": RATIO, "bad": bad, "total": total,
          "ratio": round(ratio, 6), "budget": spec.budget,
          "budget_consumed": round(consumed, 6),
          "ok": consumed < 1.0,
      }
    else:
      value = snapshot.get(spec.value_key)
      breached = value is not None and float(value) > spec.ceiling
      out[spec.name] = {
          "kind": VALUE,
          "value": None if value is None else float(value),
          "ceiling": spec.ceiling, "budget": spec.budget,
          "ok": not breached,
      }
  return out


@config.configurable
def default_serving_slos(latency_budget: float = 0.01,
                         shed_budget: float = 0.02,
                         fast_window_s: float = 60.0,
                         slow_window_s: float = 300.0,
                         burn_factor: float = DEFAULT_BURN_FACTOR
                         ) -> List[SloSpec]:
  """The stock serving objectives (fleet bench, watch default):
  latency-SLO breach ratio and fleet shed ratio over routed requests.
  Budgets/windows are explicit HERE so every construction site stays
  `slo-unbudgeted`-clean — override per deployment via config."""
  return [
      SloSpec(
          "serve_latency", budget=latency_budget,
          fast_window_s=fast_window_s, slow_window_s=slow_window_s,
          bad_key="counter/serve/slo_breaches",
          total_key="counter/serve/fleet/requests",
          burn_factor=burn_factor,
          description="end-to-end predict latency over the fleet's "
                      "latency_slo_ms, as counted by "
                      "obs.sentinel.observe_serving_latency"),
      SloSpec(
          "serve_shed", budget=shed_budget,
          fast_window_s=fast_window_s, slow_window_s=slow_window_s,
          bad_key="counter/serve/fleet/shed",
          total_key="counter/serve/fleet/requests",
          burn_factor=burn_factor,
          description="queue-bound sheds over routed requests "
                      "(admission refusals are budgeted errors)"),
  ]


@config.configurable
def default_loop_slos(staleness_bound: float = 1.0,
                      publish_to_serve_ms: float = 60000.0,
                      sample_budget: float = 0.1,
                      fast_window_s: float = 30.0,
                      slow_window_s: float = 120.0,
                      burn_factor: float = DEFAULT_BURN_FACTOR
                      ) -> List[SloSpec]:
  """The graftloop objectives: policy staleness (served versions behind
  the published head) and publish-to-serve deploy latency, both VALUE
  specs over the loop's existing telemetry."""
  return [
      SloSpec(
          "loop_staleness", budget=sample_budget,
          fast_window_s=fast_window_s, slow_window_s=slow_window_s,
          value_key="gauge/loop/staleness",
          ceiling=staleness_bound, burn_factor=burn_factor,
          description="served-policy staleness in published versions"),
      SloSpec(
          "loop_publish_to_serve", budget=sample_budget,
          fast_window_s=fast_window_s, slow_window_s=slow_window_s,
          value_key="hist/loop/publish_to_serve_ms/max",
          ceiling=publish_to_serve_ms, burn_factor=burn_factor,
          description="worst checkpoint-verified -> rollout-complete "
                      "deploy latency"),
  ]
