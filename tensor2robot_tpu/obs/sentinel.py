"""Online anomaly detection over the graftscope step-stats stream.

The reference stack has no online health monitoring at all — a TPU job
that went NaN or starved its infeed was discovered by a human reading
TensorBoard after the fact (/root/reference/models/abstract_model.py:
873-936 host_call scalars are the only signal). This project's own
history is the sharper motivation: rounds 1-5 each ended with the axon
tunnel degrading mid-window and nothing machine-readable recording when
throughput turned or why (VERDICT r5 weakness #1). Production pjit
training at pod scale stays alive through exactly this kind of cheap
in-process detection (arXiv:2204.06514 §4; the serving comparison in
arXiv:2605.25645 attributes regressions the same way).

The Sentinel consumes telemetry that is ALREADY host-side — stepstats
window records, per-step scalars the loop has already fetched, the
barrier leaf `backend.state_barrier` already copies back — so detection
costs ZERO extra tunnel round trips (eager device ops cost ~1.5 s each
over the tunnel; see `utils.backend.sync`). Detectors:

* **step-time spike** — EWMA center + MAD spread over a rolling window
  of `step_ms`; a window beyond `center + max(k·1.4826·MAD,
  min_rel·center)` is an incident — ONE per episode (latched), and a
  persistent shift is re-admitted into the baseline after
  `spike_adapt_after` windows so a degraded-for-good regime does not
  flood incidents forever. Records flagged `barrier_dominated` (the
  timing is a clamped upper bound, `backend.time_train_steps_halves`)
  are excluded from BOTH detection and the running statistics.
* **data starvation** — `data_wait_ms/step_ms` above a fraction for N
  consecutive windows (latched: one incident per starvation episode).
* **non-finite divergence** — `nonfinite_params` piggybacked on the
  stepstats barrier fetch (fatal), plus any non-finite host-side metric
  scalar (fatal, latched per metric so an unrecovered NaN emits once).
* **HBM-watermark drift** — allocator `device_bytes_in_use` (fallback
  `live_bytes`, both from `backend.device_memory_stats()` via the
  stepstats record) growing past the last watermark by a relative AND
  absolute margin; the baseline ratchets only ON incident, so a
  gradual leak accumulates against it and still fires.

Incidents are schema-versioned `graftscope-incident-v1` records
(`obs.runlog.make_incident`) fanned out to sinks — the run's
`incidents.jsonl` appender and the flight recorder's ring buffer — and
counted in the metrics registry (`sentinel/incidents`,
`sentinel/<kind>`). Backend-free by construction: importing and running
this module never touches jax (tests/test_sentinel.py proves it under a
poisoned JAX_PLATFORMS).
"""

from __future__ import annotations

import collections
import dataclasses
import math
import sys
import time
from typing import Any, Callable, Deque, Dict, List, Mapping, Optional

import numpy as np

from tensor2robot_tpu.obs import metrics as metrics_lib
from tensor2robot_tpu.obs import runlog as runlog_lib

__all__ = ["SentinelConfig", "Sentinel", "observe_serving_latency"]

# MAD -> sigma for normally distributed data; the standard robust-scale
# constant (the spike threshold is expressed in sigma-equivalents).
_MAD_SIGMA = 1.4826

# Incident kinds (the postmortem CLI renders these names verbatim).
STEP_TIME_SPIKE = "step_time_spike"
DATA_STARVATION = "data_starvation"
NONFINITE_PARAMS = "nonfinite_params"
NONFINITE_METRIC = "nonfinite_metric"
HBM_DRIFT = "hbm_drift"
SLO_BREACH = "serving_slo_breach"
# Emitted by the graftserve fleet (`serving/fleet.py`) when a replica is
# evicted from the routing set (dispatch-failure streak, heartbeat
# timeout, or an external fatal incident routed through
# `ServingFleet.sentinel_sink`). detail carries {"replica": index,
# "reason": ...}; the fleet's sinks + the flight recorder both consume
# these through the standard incident fan-out.
REPLICA_UNHEALTHY = "replica_unhealthy"
# Emitted by the graftloop supervisor (`loop/supervisor.py`): a worker
# restart after a crash/hang (warn — the loop self-healed) and a worker
# whose restart budget exhausted (fatal — the loop is degraded until an
# operator intervenes). detail carries {"worker": name, "reason": ...}.
LOOP_WORKER_RESTART = "loop_worker_restart"
LOOP_WORKER_LOST = "loop_worker_lost"
# Emitted by the graftloop publisher (`loop/publish.py`) when a
# just-saved checkpoint FAILS the manifest verification walk and is
# refused publication (warn — actors keep serving the last verified
# version; the learner's own verified-restore walk quarantines it).
LOOP_PUBLISH_REJECTED = "loop_publish_rejected"
# Emitted by the graftwatch SLO engine (`obs/slo.py`) when an objective
# is burning its error budget: a multi-window burn-rate edge (warn —
# fast AND slow windows both past the spec's burn factor) or budget
# exhaustion (fatal, latched once). detail carries {"slo": name,
# "trigger": "burn_rate"|"budget_exhausted", "fast_burn", "slow_burn",
# "budget_consumed", "spec": ...}; sinks/flightrec/postmortem consume
# it through the standard incident fan-out. Sinks must reference THIS
# constant, not the literal — the `slo-unbudgeted` lint rule flags
# re-spelled kind strings outside this module.
SLO_BURN = "serving_slo_burn"


@dataclasses.dataclass(frozen=True)
class SentinelConfig:
  """Detector thresholds (defaults sized for the tunnel's noise floor:
  host-load swings this VM's CPU smoke ±20 %, PERFORMANCE.md round 2,
  so the spike floor sits well above it)."""

  # step-time spike: fire when step_ms > ewma + max(spike_sigma *
  # 1.4826 * MAD, spike_min_rel * ewma), after spike_min_points clean
  # windows of warmup, over a spike_window rolling history.
  spike_sigma: float = 6.0
  spike_min_rel: float = 0.5
  spike_min_points: int = 8
  spike_window: int = 64
  ewma_alpha: float = 0.2
  # One incident per spike EPISODE (latched like starvation); after
  # this many consecutive spiking windows the values are re-admitted
  # into the statistics — a persistent regime shift (the tunnel
  # degrading for good) becomes the new baseline instead of an
  # incident-per-window flood that evicts the pre-shift timeline from
  # every ring buffer.
  spike_adapt_after: int = 3
  # data starvation: data_wait_ms/step_ms > starvation_frac for
  # starvation_consecutive windows in a row.
  starvation_frac: float = 0.6
  starvation_consecutive: int = 3
  # HBM drift: watermark grows by BOTH >drift_rel and >drift_min_bytes.
  drift_rel: float = 0.2
  drift_min_bytes: float = 64 * 2**20
  # Bounded incident memory (sinks see every incident regardless).
  max_incidents: int = 256


class Sentinel:
  """In-process anomaly detector; one per telemetry-enabled train run.

  Wiring (`train_eval.train_eval_model`): `observe_step_record` is
  registered as a `StepStatsRecorder` observer (fires at the stepstats
  barrier cadence), `observe_metrics` is fed host-side scalars by
  `hooks.SentinelHook` and the loop's log-cadence fetch. All inputs
  must already live on the host — `observe_metrics` silently skips
  anything that is not a plain number / numpy value rather than force
  a device fetch (the zero-extra-round-trips contract).
  """

  def __init__(self,
               config: Optional[SentinelConfig] = None,
               sinks: Optional[List[Callable[[Dict[str, Any]], Any]]] = None,
               registry: Optional[metrics_lib.Registry] = None,
               clock: Callable[[], float] = time.time):
    self._config = config or SentinelConfig()
    self._sinks = list(sinks or [])
    self._registry = registry or metrics_lib.get_registry()
    self._clock = clock
    cfg = self._config
    self._incidents: Deque[Dict[str, Any]] = collections.deque(
        maxlen=cfg.max_incidents)
    self._by_kind: Dict[str, int] = {}
    self._step_history: Deque[float] = collections.deque(
        maxlen=cfg.spike_window)
    self._ewma: Optional[float] = None
    self._spike_streak = 0
    self._starvation_streak = 0
    self._hbm_watermark: Optional[float] = None
    self._nonfinite_latched: set = set()
    self._params_latched = False

  def add_sink(self, sink: Callable[[Dict[str, Any]], Any]) -> None:
    self._sinks.append(sink)

  # -- observation entry points ---------------------------------------------

  def observe_step_record(self, step: int, record: Mapping[str, Any]
                          ) -> None:
    """Consumes one stepstats window record (the recorder-observer
    signature). Never raises — telemetry must not kill a train loop."""
    try:
      self._check_nonfinite_params(step, record)
      self._check_starvation(step, record)
      self._check_hbm(step, record)
      self._check_spike(step, record)
    except Exception as e:  # noqa: BLE001 - detector bugs stay telemetry
      print(f"sentinel: detector error at step {step}: "
            f"{type(e).__name__}: {e}", file=sys.stderr)

  def observe_metrics(self, step: int, metrics: Mapping[str, Any]) -> None:
    """Checks HOST-SIDE scalars for non-finites. Values that are not
    already host numbers/numpy (i.e. live device arrays) are skipped —
    fetching them here would add a ~1.5 s eager round trip per scalar
    per step over the tunnel."""
    for key, value in metrics.items():
      if isinstance(value, (int, float, np.floating, np.integer,
                            np.bool_)):
        scalar = float(value)
      elif isinstance(value, np.ndarray) and value.size == 1:
        scalar = float(value.reshape(())[()])
      else:
        continue
      if math.isfinite(scalar):
        self._nonfinite_latched.discard(key)
      elif key not in self._nonfinite_latched:
        self._nonfinite_latched.add(key)
        self._emit(NONFINITE_METRIC, step, severity="fatal", value=scalar,
                   detail={"metric": str(key)})

  def reset_nonfinite_latch(self) -> None:
    """Re-arms the non-finite detectors (metrics + params). The latch
    de-dupes one continuous NaN episode; the divergence-rewind path
    must call this after restoring, because a NaN that recurs on the
    first post-rewind observation — no finite value in between — is a
    NEW divergence that has to re-trigger (and eventually exhaust the
    rewind budget), not ride the old episode's latch to a silent
    'successful' run full of NaNs."""
    self._nonfinite_latched.clear()
    self._params_latched = False

  # -- detectors ------------------------------------------------------------

  def _check_spike(self, step: int, record: Mapping[str, Any]) -> None:
    cfg = self._config
    if record.get("barrier_dominated"):
      return  # a clamped upper bound, not a measurement — ignore fully
    step_ms = record.get("step_ms")
    if step_ms is None or not math.isfinite(float(step_ms)):
      return
    step_ms = float(step_ms)
    history = self._step_history
    if self._ewma is not None and len(history) >= cfg.spike_min_points:
      ordered = sorted(history)
      median = ordered[len(ordered) // 2]
      mad = sorted(abs(v - median) for v in history)[len(history) // 2]
      threshold = self._ewma + max(cfg.spike_sigma * _MAD_SIGMA * mad,
                                   cfg.spike_min_rel * self._ewma)
      if step_ms > threshold:
        self._spike_streak += 1
        if self._spike_streak == 1:
          # Latched per episode: ONE incident when the spike starts,
          # not one per window for the rest of the run.
          self._emit(STEP_TIME_SPIKE, step, value=step_ms,
                     threshold=threshold,
                     detail={"ewma_ms": self._ewma, "mad_ms": mad})
        if self._spike_streak <= cfg.spike_adapt_after:
          # A short spike must not drag the running statistics...
          return
        # ...but this is no longer a spike — it is the new regime
        # (the tunnel degraded for good): fall through and re-admit
        # the value so the baseline adapts and the episode can end.
      else:
        self._spike_streak = 0
    history.append(step_ms)
    self._ewma = (step_ms if self._ewma is None
                  else (1 - cfg.ewma_alpha) * self._ewma
                  + cfg.ewma_alpha * step_ms)

  def _check_starvation(self, step: int, record: Mapping[str, Any]) -> None:
    cfg = self._config
    step_ms = float(record.get("step_ms") or 0.0)
    wait_ms = float(record.get("data_wait_ms") or 0.0)
    if step_ms <= 0.0:
      return
    frac = wait_ms / step_ms
    if frac > cfg.starvation_frac:
      self._starvation_streak += 1
      if self._starvation_streak == cfg.starvation_consecutive:
        # Latched: one incident per starvation episode, at the moment
        # the streak condition is first met.
        self._emit(DATA_STARVATION, step, value=frac,
                   threshold=cfg.starvation_frac,
                   detail={"consecutive_windows": self._starvation_streak,
                           "data_wait_ms": wait_ms, "step_ms": step_ms})
    else:
      self._starvation_streak = 0

  def _check_nonfinite_params(self, step: int,
                              record: Mapping[str, Any]) -> None:
    flag = record.get("nonfinite_params")
    if flag:
      if not self._params_latched:
        self._params_latched = True
        self._emit(NONFINITE_PARAMS, step, severity="fatal", value=1.0,
                   detail={"source": "state_barrier leaf fetch"})
    elif flag is not None:
      self._params_latched = False

  def _check_hbm(self, step: int, record: Mapping[str, Any]) -> None:
    cfg = self._config
    value = record.get("device_bytes_in_use", record.get("live_bytes"))
    if value is None:
      return
    value = float(value)
    if not math.isfinite(value) or value <= 0.0:
      return
    if self._hbm_watermark is None:
      self._hbm_watermark = value
      return
    grew_rel = value > self._hbm_watermark * (1.0 + cfg.drift_rel)
    grew_abs = value - self._hbm_watermark > cfg.drift_min_bytes
    if grew_rel and grew_abs:
      self._emit(HBM_DRIFT, step, value=value,
                 threshold=self._hbm_watermark * (1.0 + cfg.drift_rel),
                 detail={"previous_watermark_bytes": self._hbm_watermark})
      # Ratchet ONLY on incident: the baseline stays put under
      # sub-threshold growth, so a gradual leak accumulates against it
      # and fires once the CUMULATIVE drift crosses the thresholds —
      # advancing on every small increase would let a +10%/window leak
      # run forever without an incident (the blind-OOM case).
      self._hbm_watermark = value

  # -- emission -------------------------------------------------------------

  def _emit(self, kind: str, step: int, severity: str = "warn",
            value: Optional[float] = None,
            threshold: Optional[float] = None,
            detail: Optional[Dict[str, Any]] = None) -> None:
    record = runlog_lib.make_incident(
        kind, step=step, severity=severity, value=value,
        threshold=threshold, detail=detail, unix_time=self._clock())
    self._incidents.append(record)
    self._by_kind[kind] = self._by_kind.get(kind, 0) + 1
    self._registry.counter("sentinel/incidents").inc()
    self._registry.counter(f"sentinel/{kind}").inc()
    for sink in self._sinks:
      try:
        sink(record)
      except Exception as e:  # noqa: BLE001 - a sink must not kill the run
        print(f"sentinel: incident sink failed ({type(e).__name__}: {e})",
              file=sys.stderr)

  def incidents(self) -> List[Dict[str, Any]]:
    """The (bounded) incident records emitted so far, oldest first."""
    return list(self._incidents)

  def summary(self) -> Dict[str, Any]:
    """JSON-safe run-record block: totals per kind + overall."""
    return {"incidents": sum(self._by_kind.values()),
            "by_kind": dict(self._by_kind)}


def observe_serving_latency(elapsed_ms: float,
                            slo_ms: Optional[float],
                            registry: Optional[metrics_lib.Registry] = None
                            ) -> bool:
  """Counts a serving-latency SLO breach; returns True when breached.

  The serving twin of the step-time detector: predictors record every
  predict's end-to-end latency (the `np.asarray` fetch inside their
  timed window IS the tunnel barrier) and, when a latency SLO is
  configured, breaches land in `serve/slo_breaches` (+ the breach-ms
  histogram) so a latency regression is a counter, not a percentile
  archaeology session. `slo_ms` None/0 disables.
  """
  if not slo_ms or elapsed_ms <= slo_ms:
    return False
  reg = registry or metrics_lib.get_registry()
  reg.counter("serve/slo_breaches").inc()
  reg.histogram("serve/slo_breach_ms").record(float(elapsed_ms))
  return True
