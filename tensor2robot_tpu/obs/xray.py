"""graftscope-xray: compile, cost and memory introspection below dispatch.

The reference has nothing under the dispatch boundary — TPUEstimator
hides compilation and HBM inside the session
(/root/reference/models/abstract_model.py:662-834) and every OOM or
compile stall surfaces as an opaque session error. Here the jit/pjit
entry points can be X-rayed: `analyze_jit` AOT-traces/lowers/compiles a
jitted callable with per-phase timing and reads the compiled
executable's own XLA cost analysis (FLOPs, bytes accessed) and memory
analysis (argument/output/temp bytes), plus jaxpr equation counts and
declared-donation byte accounting from `Traced.args_info`. From those it
derives arithmetic intensity, an analytic v5e roofline, and (given a
measured step time) MFU — the accounting that diagnosed the round-5
b80–b128 valley by hand (PERFORMANCE.md: 451 ms/step measured vs a
~28 ms roofline priced from the very same cost-analysis numbers).

`memory_accounting` prices a TrainState + batch in bytes, globally and
PER SHARD (via each leaf's `sharding.shard_shape`; replicated leaves
cost full bytes per device), and `hbm_watermark_estimate` combines it
with the executable's temp bytes into the per-run HBM watermark that
rounds 2–5 OOMed without (b512/b320/b384 all died blind).

Analysis results land in three places at once: the process-wide metrics
registry (`xray/<name>/…` gauges), a module-level record collector
(drained into `obs.runlog` run records), and the caller's hands.

Backend-free at import like the rest of `obs/` — jax is imported only
inside the analysis functions, which are called from live loops where
the backend is already up (tests/test_observability.py proves the
import under a poisoned JAX_PLATFORMS). Telemetry must never take down
a train loop: `XrayedFunction` falls back to the plain jitted callable
on ANY analysis or compiled-call failure.

graftcache (PR 7): `analyze_jit`/`XrayedFunction` take a `cache=` seam
(`obs.excache`) that persists the AOT executables they produce and
short-circuits lower+compile with a deserialize on later processes —
trainer restarts, serving cold starts, and bench probes warm-start in
milliseconds. All cache failure modes degrade to the fresh compile.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from tensor2robot_tpu.obs import metrics as metrics_lib
from tensor2robot_tpu.utils import backend as backend_lib

__all__ = ["analyze_jit", "XrayedFunction", "memory_accounting",
           "hbm_watermark_estimate", "analytic_mfu", "pytree_bytes",
           "pytree_shard_bytes", "records", "clear_records"]

_RECORDS: List[Dict[str, Any]] = []
_LOCK = threading.Lock()


def records() -> List[Dict[str, Any]]:
  """Compile records collected since the last `clear_records()`."""
  with _LOCK:
    return list(_RECORDS)


def clear_records() -> None:
  """Drops collected records (run start, alongside trace/metrics reset)."""
  with _LOCK:
    _RECORDS.clear()


def _collect(record: Dict[str, Any]) -> None:
  with _LOCK:
    _RECORDS.append(record)


# ---------------------------------------------------------------------------
# Byte accounting over pytrees.
# ---------------------------------------------------------------------------


def _leaf_nbytes(leaf) -> int:
  """Logical bytes of one array-like leaf (0 for non-arrays)."""
  nbytes = getattr(leaf, "nbytes", None)
  if nbytes is not None:
    return int(nbytes)
  shape = getattr(leaf, "shape", None)
  dtype = getattr(leaf, "dtype", None)
  if shape is None or dtype is None:
    return 0
  import numpy as np

  size = 1
  for dim in shape:
    size *= int(dim)
  return size * np.dtype(dtype).itemsize


def _leaf_shard_nbytes(leaf) -> int:
  """Per-device bytes of one leaf: the shard slice when the leaf carries
  a sharding, the full array otherwise (replicated arrays DO occupy full
  bytes on every device — that is the honest per-shard cost)."""
  sharding = getattr(leaf, "sharding", None)
  shape = getattr(leaf, "shape", None)
  if sharding is not None and shape is not None:
    try:
      import numpy as np

      shard_shape = sharding.shard_shape(tuple(shape))
      size = 1
      for dim in shard_shape:
        size *= int(dim)
      return size * np.dtype(leaf.dtype).itemsize
    except Exception:  # noqa: BLE001 - fall back to the global bytes
      pass
  return _leaf_nbytes(leaf)


def pytree_bytes(tree) -> int:
  """Total logical bytes over every array leaf of `tree`."""
  import jax

  return sum(_leaf_nbytes(x) for x in jax.tree_util.tree_leaves(tree))


def pytree_shard_bytes(tree) -> int:
  """Per-device bytes over every leaf (see `_leaf_shard_nbytes`)."""
  import jax

  return sum(_leaf_shard_nbytes(x) for x in jax.tree_util.tree_leaves(tree))


# ---------------------------------------------------------------------------
# Compile telemetry.
# ---------------------------------------------------------------------------


def _count_eqns(jaxpr) -> int:
  """Total equation count, nested jaxprs (pjit/scan/custom_vjp bodies)
  included — a cheap structural size proxy that moves when a model edit
  re-traces into something materially different."""
  jaxpr = getattr(jaxpr, "jaxpr", jaxpr)  # ClosedJaxpr -> Jaxpr
  total = 0
  for eqn in getattr(jaxpr, "eqns", ()):
    total += 1
    for value in eqn.params.values():
      values = value if isinstance(value, (list, tuple)) else (value,)
      for item in values:
        if hasattr(item, "eqns") or hasattr(item, "jaxpr"):
          total += _count_eqns(item)
  return total


def _donation_bytes(traced, args) -> Tuple[float, float]:
  """(donated, undonated) argument bytes from the Traced's args_info
  (the declared donation set — what the caller hands over, whether or
  not XLA finds a reusable buffer for each)."""
  import jax

  infos = jax.tree_util.tree_leaves(
      traced.args_info, is_leaf=lambda n: hasattr(n, "donated"))
  if infos and all(hasattr(i, "donated") for i in infos):
    donated = sum(_leaf_nbytes(i) for i in infos if i.donated)
    total = sum(_leaf_nbytes(i) for i in infos)
    return float(donated), float(total - donated)
  total = sum(pytree_bytes(a) for a in args)
  return 0.0, float(total)


def analytic_mfu(flops: float, step_sec: float,
                 peak_flops: float = backend_lib.V5E_PEAK_BF16_FLOPS
                 ) -> float:
  """Model FLOP utilization: executable FLOPs over (time x device peak)."""
  return flops / max(step_sec, 1e-12) / peak_flops


def analyze_jit(name: str, fn, *args,
                registry: Optional[metrics_lib.Registry] = None,
                collect: bool = True,
                cache=None) -> Tuple[Any, Dict[str, Any]]:
  """AOT trace->lower->compile of a jitted `fn` at `args`, instrumented.

  Returns `(compiled, record)` where `compiled` is the executable
  (callable with the same signature and shardings/donation as `fn`) and
  `record` is a JSON-safe dict: per-phase times (`trace_s`, `lower_s`,
  `compile_s`), `jaxpr_eqns`, declared `donated_bytes` /
  `undonated_bytes`, XLA `flops` / `bytes_accessed` (None where the
  backend reports none), memory analysis (`temp_bytes`, `output_bytes`,
  `argument_bytes`, `generated_code_bytes`), and the derived
  `arithmetic_intensity` (FLOPs/byte) + `roofline_ms`.

  `roofline_ms` always prices against the project's one real device
  class (v5e public peaks, `utils.backend`), whatever backend compiled
  the executable — it answers "what SHOULD this step cost on the chip",
  which is exactly the number the round-5 valley violated 16x.

  `cache` (an `obs.excache.ExecutableCache` or a directory path)
  short-circuits lower+compile with a persisted executable when the
  content-addressed key (jaxpr fingerprint, abstract shapes/dtypes/
  shardings, donation layout, static args, device topology, backend
  version) hits: the record then carries the COLD process's cost/memory
  analysis plus a `cache` block (`{hit, key, load_ms, bytes}`) and
  `lower_s == compile_s == 0`. A load failure of any kind — corrupt
  blob, version skew, key trouble — falls back to the fresh compile
  below (cache trouble must never take down the run, the same contract
  as every other telemetry path here); a miss stores the fresh
  executable for the next process.

  Raises on (compile) failure — callers that must not die use
  `XrayedFunction` (or wrap in try/except) and keep the plain jitted fn.
  """
  from tensor2robot_tpu.obs import excache as excache_lib

  reg = registry or metrics_lib.get_registry()
  cache = excache_lib.as_cache(cache)
  t0 = time.perf_counter()
  traced = fn.trace(*args)
  t1 = time.perf_counter()

  cache_key = None
  cache_unsafe = False
  unsafe_guard_error = False
  try:
    # Donating multi-device executables must not be DESERIALIZED on
    # this jax at all — from the serialized-AOT tier (measured heap
    # corruption, excache.aot_cache_unsafe) NOR from the XLA persistent
    # compilation cache: a donating NamedSharding executable served out
    # of a warm XLA cache and fed device_put/orbax-restored arrays
    # SIGSEGVs the same way (measured: the trainer resume path —
    # run 1 fills the cache, run 2 restores a checkpoint and crashes
    # on its first dispatch). Such steps always compile fresh, with
    # the XLA tier bypassed for exactly that compile.
    cache_unsafe = excache_lib.aot_cache_unsafe(traced, args)
  except Exception:  # noqa: BLE001 - guard trouble = no caching
    cache_unsafe = True
    unsafe_guard_error = True
  if cache_unsafe and cache is not None:
    # Distinct counters: a BROKEN guard must not read as "donated-mesh
    # executable skipped" in runs.jsonl — they send a diff reader down
    # entirely different trails.
    reg.counter("cache/unsafe_guard_error" if unsafe_guard_error
                else "cache/skipped_donated_mesh").inc()
  if cache_unsafe:
    cache = None
  if cache is not None:
    try:
      cache_key = excache_lib.cache_key(
          name, **excache_lib.key_components_from_traced(traced, args))
    except Exception as e:  # noqa: BLE001 - key trouble = no caching
      reg.counter("cache/key_failures").inc()
      print(f"graftcache: key computation for {name!r} failed "
            f"({type(e).__name__}: {e}); compiling fresh",
            file=sys.stderr)
    if cache_key is not None:
      entry = cache.load(cache_key)
      if entry is not None:
        donated, undonated = _donation_bytes(traced, args)
        record = dict(entry["record"])
        record.update({
            "name": name,
            "trace_s": t1 - t0,
            "lower_s": 0.0,
            "compile_s": 0.0,
            "jaxpr_eqns": _count_eqns(traced.jaxpr),
            "donated_bytes": donated,
            "undonated_bytes": undonated,
            "cache": {"hit": True, "key": cache_key,
                      "load_ms": entry["load_ms"],
                      "bytes": entry["bytes"]},
        })
        record.setdefault("flops", None)
        record.setdefault("bytes_accessed", None)
        reg.counter("xray/analyses").inc()
        reg.gauge(f"xray/{name}/cache_load_ms").set(entry["load_ms"])
        if collect:
          _collect(record)
        return entry["compiled"], record

  lowered = traced.lower()
  t2 = time.perf_counter()
  if (cache is not None and cache_key is not None) or cache_unsafe:
    # Two reasons to compile WITHOUT the XLA persistent cache: an
    # AOT-tier miss about to be stored (the artifact may come out of
    # that cache non-serializable and the entry could never (re)fill —
    # see excache.xla_cache_bypassed), and a donating-mesh executable
    # (an XLA-cache LOAD of one heap-corrupts this jax — see the
    # cache_unsafe guard above).
    with excache_lib.xla_cache_bypassed():
      compiled = lowered.compile()
  else:
    compiled = lowered.compile()
  t3 = time.perf_counter()

  donated, undonated = _donation_bytes(traced, args)
  record: Dict[str, Any] = {
      "name": name,
      "trace_s": t1 - t0,
      "lower_s": t2 - t1,
      "compile_s": t3 - t2,
      "jaxpr_eqns": _count_eqns(traced.jaxpr),
      "donated_bytes": donated,
      "undonated_bytes": undonated,
  }
  flops = bytes_accessed = None
  try:
    cost = compiled.cost_analysis()
    cost = cost[0] if isinstance(cost, (list, tuple)) else (cost or {})
    if "flops" in cost:
      flops = float(cost["flops"])
    if "bytes accessed" in cost:
      bytes_accessed = float(cost["bytes accessed"])
  except Exception:  # noqa: BLE001 - cost analysis is backend-optional
    pass
  record["flops"] = flops
  record["bytes_accessed"] = bytes_accessed
  # flops == 0.0 is a valid answer (copy/gather-dominated executables):
  # the memory-bound roofline bytes/BW is exactly the health-check
  # number then, so only a missing/zero bytes figure disables it.
  if flops is not None and bytes_accessed:
    record["arithmetic_intensity"] = flops / bytes_accessed
    record["roofline_ms"] = 1e3 * max(
        flops / backend_lib.V5E_PEAK_BF16_FLOPS,
        bytes_accessed / backend_lib.V5E_PEAK_HBM_BW)
    record["peak_flops"] = backend_lib.V5E_PEAK_BF16_FLOPS
    record["peak_hbm_bw"] = backend_lib.V5E_PEAK_HBM_BW
  try:
    mem = compiled.memory_analysis()
    if mem is not None:
      record["temp_bytes"] = float(mem.temp_size_in_bytes)
      record["output_bytes"] = float(mem.output_size_in_bytes)
      record["argument_bytes"] = float(mem.argument_size_in_bytes)
      record["generated_code_bytes"] = float(
          mem.generated_code_size_in_bytes)
  except Exception:  # noqa: BLE001 - memory analysis is backend-optional
    pass

  if cache is not None and cache_key is not None:
    # Persist for the NEXT process (best-effort, counted); the stored
    # sidecar carries this record so a warm start keeps full compile
    # telemetry without paying the compile.
    stored = cache.store(cache_key, compiled, record=record, name=name)
    record["cache"] = {"hit": False, "key": cache_key, "stored": stored}

  reg.counter("xray/analyses").inc()
  reg.gauge(f"xray/{name}/compile_s").set(record["compile_s"])
  reg.gauge(f"xray/{name}/jaxpr_eqns").set(float(record["jaxpr_eqns"]))
  reg.gauge(f"xray/{name}/donated_bytes").set(donated)
  if flops is not None:
    reg.gauge(f"xray/{name}/flops").set(flops)
  if bytes_accessed is not None:
    reg.gauge(f"xray/{name}/bytes_accessed").set(bytes_accessed)
  if collect:
    _collect(record)
  return compiled, record


class XrayedFunction:
  """Lazily X-rays a jitted fn on its first call; never breaks the call.

  The first invocation runs `analyze_jit` at the live arguments and
  keeps the AOT executable for every later call (the same compile the
  plain jit would have paid on first dispatch — no double work, the
  plain path never compiles). Any failure — no AOT support, a backend
  without cost analysis, a later call at different shapes that the
  frozen executable rejects — permanently degrades to the plain jitted
  fn with a counter bump (`xray/analyze_failures` /
  `xray/compiled_call_fallbacks`), because telemetry must never take
  down a train loop or a serving path.
  """

  def __init__(self, name: str, fn,
               registry: Optional[metrics_lib.Registry] = None,
               cache=None):
    self._name = name
    self._fn = fn
    self._registry = registry or metrics_lib.get_registry()
    # graftcache seam: a persisted executable turns the first call's
    # compile into a deserialize (trainer restarts / bench probes warm-
    # start); all cache failure modes already degrade inside analyze_jit.
    self._cache = cache
    self._compiled = None
    self._record: Optional[Dict[str, Any]] = None
    self._failed = False
    self._lock = threading.Lock()

  @property
  def record(self) -> Optional[Dict[str, Any]]:
    return self._record

  def _analyze(self, args) -> None:
    with self._lock:
      if self._compiled is not None or self._failed:
        return
      try:
        self._compiled, self._record = analyze_jit(
            self._name, self._fn, *args, registry=self._registry,
            cache=self._cache)
      except Exception as e:  # noqa: BLE001 - degrade, never break the call
        self._failed = True
        self._registry.counter("xray/analyze_failures").inc()
        from absl import logging

        logging.warning("graftscope-xray: analysis of %r unavailable "
                        "(%s: %s); running the plain jitted fn",
                        self._name, type(e).__name__, e)

  def __call__(self, *args):
    if self._compiled is None and not self._failed:
      self._analyze(args)
    compiled = self._compiled
    if compiled is None:
      return self._fn(*args)
    try:
      return compiled(*args)
    except Exception:  # noqa: BLE001 - e.g. new shapes vs frozen executable
      with self._lock:
        self._compiled = None
        self._failed = True
      # Retry on the plain jit ONLY while the inputs are intact — i.e.
      # the failure was a pre-execution rejection (shape/dtype mismatch
      # against the frozen executable). An execution-phase error on a
      # donating fn (e.g. jax_debug_nans) has already consumed its
      # donated buffers; retrying would mask the real error behind an
      # "Array has been deleted", so re-raise the original instead.
      import jax

      if any(getattr(leaf, "is_deleted", lambda: False)()
             for leaf in jax.tree_util.tree_leaves(args)):
        raise
      self._registry.counter("xray/compiled_call_fallbacks").inc()
      # The plain jit re-traces at the new shapes; a genuine math/user
      # error re-raises from here unchanged.
      return self._fn(*args)


# ---------------------------------------------------------------------------
# Memory accounting.
# ---------------------------------------------------------------------------


def memory_accounting(state=None, batch=None,
                      num_data_shards: Optional[int] = None
                      ) -> Dict[str, float]:
  """Prices a TrainState (+ optional batch) in bytes, global and
  per-shard.

  `state` is duck-typed on the TrainState fields (`params`,
  `opt_state`, `ema_params`, `mutable_state`); any may be absent.
  Per-shard bytes come from each leaf's committed sharding
  (`sharding.shard_shape`); replicated leaves cost full bytes per
  device. A HOST batch (numpy, no shardings) is divided by
  `num_data_shards` when given — the data-parallel placement estimate
  for batches that are not on device yet.
  """
  out: Dict[str, float] = {}
  state_total = state_shard = 0
  for field, key in (("params", "params"), ("opt_state", "opt_state"),
                     ("ema_params", "ema"), ("mutable_state", "mutable")):
    tree = getattr(state, field, None)
    if tree is None:
      continue
    total = pytree_bytes(tree)
    shard = pytree_shard_bytes(tree)
    out[f"{key}_bytes"] = float(total)
    out[f"{key}_bytes_per_shard"] = float(shard)
    state_total += total
    state_shard += shard
  if state is not None:
    out["state_bytes"] = float(state_total)
    out["state_bytes_per_shard"] = float(state_shard)
  if batch is not None:
    total = pytree_bytes(batch)
    shard = pytree_shard_bytes(batch)
    if shard == total and num_data_shards and num_data_shards > 1:
      shard = -(-total // num_data_shards)  # host batch: ceil split
    out["batch_bytes"] = float(total)
    out["batch_bytes_per_shard"] = float(shard)
  return out


def hbm_watermark_estimate(memory: Dict[str, float],
                           compile_records=()) -> float:
  """Per-device HBM watermark estimate in bytes.

  resident state + resident batch + the executable's scratch: XLA's
  `temp_bytes` when a compile record reports it, else the param bytes
  again (the gradient/update buffers a train step materializes — the
  floor for any backward pass). An ESTIMATE, not an allocator readout:
  its job is to say "b512 will not fit in 16 GB" BEFORE the probe OOMs
  blind, the way rounds 2–5 did.
  """
  temp = max((float(r.get("temp_bytes") or 0.0) for r in compile_records),
             default=0.0)
  scratch = max(temp, memory.get("params_bytes_per_shard", 0.0))
  return (memory.get("state_bytes_per_shard", 0.0)
          + memory.get("batch_bytes_per_shard", 0.0) + scratch)
