"""Low-overhead span tracer exporting Chrome trace-event JSON.

The reference has no in-repo tracing (SURVEY.md §5: TF summaries through
TPU `host_call`, /root/reference/models/abstract_model.py:873-936); over
the axon tunnel every perf incident so far was diagnosed with hand-rolled
prints. This tracer makes those windows permanent: context-manager /
decorator spans on monotonic clocks (`time.perf_counter_ns`), one ring
buffer per tracer (bounded memory, oldest events dropped), thread-aware
(per-thread `tid` + thread-name metadata), exported in the Chrome
trace-event format that `chrome://tracing` and https://ui.perfetto.dev
load directly.

Backend-free by construction: this module never imports jax and a
disabled tracer costs a single attribute check per span
(tests/test_observability.py runs it under a poisoned JAX_PLATFORMS).
"""

from __future__ import annotations

import collections
import functools
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ["Tracer", "Span", "get_tracer", "enable", "disable", "span",
           "traced", "instant", "add_complete", "save", "clear",
           "set_context_provider"]

# Optional trace-context hook (obs.graftrace installs it): a zero-arg
# callable returning the active request/causality ids as an args dict
# (or None). Every recorded event gets those ids merged into its args —
# explicit per-event args win on key collision — which is how the whole
# existing span surface becomes causally linkable without changing any
# call site. Module-level (not per-Tracer): the context is a property
# of the running thread, not of the buffer it lands in.
_CONTEXT_PROVIDER = None


def set_context_provider(provider) -> None:
  global _CONTEXT_PROVIDER
  _CONTEXT_PROVIDER = provider

# Chrome trace events use microsecond timestamps; perf_counter_ns is the
# monotonic source (wall clocks can step backwards mid-span).
_NS_PER_US = 1000.0


class Span:
  """One in-flight span; records a complete ('X') event on exit.

  Re-entrant use is wrong (one Span = one window); allocate via
  `Tracer.span`. A span created while the tracer is disabled is the
  shared no-op instance and records nothing.
  """

  __slots__ = ("_tracer", "_name", "_cat", "_args", "_start_ns")

  def __init__(self, tracer: Optional["Tracer"], name: str, cat: str,
               args: Optional[Dict[str, Any]]):
    self._tracer = tracer
    self._name = name
    self._cat = cat
    self._args = args
    self._start_ns = 0

  def __enter__(self) -> "Span":
    if self._tracer is not None:
      self._start_ns = time.perf_counter_ns()
    return self

  def __exit__(self, exc_type, exc, tb) -> None:
    if self._tracer is not None:
      end_ns = time.perf_counter_ns()
      self._tracer._record(self._name, self._cat, self._start_ns,
                           end_ns - self._start_ns, self._args)


_NULL_SPAN = Span(None, "", "", None)


def _event_size(event: Dict[str, Any]) -> int:
  """Cheap per-event byte estimate for the ring's byte bound: fixed
  framing + name/cat + per-arg framing + string payload lengths.
  Deliberately NOT json.dumps or str(args) (either would dominate the
  cost of every append — str(args) alone was ~40% of the traced-arm
  fleet-bench overhead); non-string values count a flat 8, so the
  estimate only needs to be proportional, the bound is approximate."""
  size = 96 + len(event.get("name", "")) + len(event.get("cat", ""))
  args = event.get("args")
  if args:
    size += 16 * len(args)
    for key, value in args.items():
      size += len(key) + (len(value) if type(value) is str else 8)
  return size


class Tracer:
  """Bounded in-memory event buffer with Chrome-trace JSON export.

  Bounded BOTH by event count and by estimated bytes (`max_bytes`):
  a count-only ring lets a few arg-heavy spans (rung traces, fat
  request args) hold megabytes hostage in an always-on worker. Oldest
  events are dropped first; `dropped_events` counts them.
  """

  def __init__(self, max_events: int = 200_000,
               max_bytes: int = 64 << 20):
    self._events: "collections.deque" = collections.deque()
    self._sizes: "collections.deque" = collections.deque()
    self._bytes = 0
    self._max_events = max_events
    self._max_bytes = max_bytes
    self._dropped = 0
    self._lock = threading.Lock()
    self._thread_names: Dict[int, str] = {}
    self._enabled = False
    # Cached: one getpid() syscall per EVENT is measurable on the
    # serving hot path. Refreshed after fork (register_at_fork below).
    self._pid = os.getpid()

  def _refresh_pid(self) -> None:
    self._pid = os.getpid()

  # -- lifecycle ------------------------------------------------------------

  @property
  def enabled(self) -> bool:
    return self._enabled

  @property
  def dropped_events(self) -> int:
    return self._dropped

  @property
  def buffered_bytes(self) -> int:
    return self._bytes

  def enable(self) -> None:
    self._enabled = True

  def disable(self) -> None:
    self._enabled = False

  def clear(self) -> None:
    with self._lock:
      self._events.clear()
      self._sizes.clear()
      self._bytes = 0
      self._dropped = 0
      self._thread_names.clear()

  # -- recording ------------------------------------------------------------

  def span(self, name: str, cat: str = "span", **args: Any) -> Span:
    """Context manager timing a code window as one complete event."""
    if not self._enabled:
      return _NULL_SPAN
    return Span(self, name, cat, args or None)

  def traced(self, name: Optional[str] = None, cat: str = "span"):
    """Decorator form of `span` (one event per call)."""

    def wrap(fn):
      span_name = name or getattr(fn, "__qualname__", fn.__name__)

      @functools.wraps(fn)
      def inner(*a, **kw):
        with self.span(span_name, cat=cat):
          return fn(*a, **kw)

      return inner

    return wrap

  def instant(self, name: str, cat: str = "instant", **args: Any) -> None:
    """Zero-duration marker event."""
    if not self._enabled:
      return
    now = time.perf_counter_ns()
    self._append({"name": name, "cat": cat, "ph": "i",
                  "ts": now / _NS_PER_US, "s": "t",
                  "pid": self._pid, "tid": threading.get_ident(),
                  **({"args": args} if args else {})})

  def add_complete(self, name: str, start_ns: int, dur_ns: int,
                   cat: str = "span",
                   args: Optional[Dict[str, Any]] = None) -> None:
    """Records an externally timed window (clock reads already taken by
    the caller — e.g. stepstats' barrier-bounded step windows)."""
    if not self._enabled:
      return
    self._record(name, cat, start_ns, dur_ns, args)

  def _record(self, name: str, cat: str, start_ns: int, dur_ns: int,
              args: Optional[Dict[str, Any]]) -> None:
    self._append({"name": name, "cat": cat, "ph": "X",
                  "ts": start_ns / _NS_PER_US,
                  "dur": max(dur_ns, 0) / _NS_PER_US,
                  "pid": self._pid, "tid": threading.get_ident(),
                  **({"args": args} if args else {})})

  def _append(self, event: Dict[str, Any]) -> None:
    provider = _CONTEXT_PROVIDER
    if provider is not None:
      try:
        ctx_args = provider()
      except Exception:  # noqa: BLE001 - a hook must not break recording
        ctx_args = None
      if ctx_args:
        merged = dict(ctx_args)
        merged.update(event.get("args") or {})
        event["args"] = merged
    size = _event_size(event)
    tid = event["tid"]
    with self._lock:
      if tid not in self._thread_names:
        self._thread_names[tid] = threading.current_thread().name
      self._events.append(event)
      self._sizes.append(size)
      self._bytes += size
      while self._events and (len(self._events) > self._max_events
                              or self._bytes > self._max_bytes):
        self._events.popleft()
        self._bytes -= self._sizes.popleft()
        self._dropped += 1

  # -- export ---------------------------------------------------------------

  def events(self) -> List[Dict[str, Any]]:
    """Snapshot of buffered events plus thread-name metadata events."""
    with self._lock:
      events = list(self._events)
      names = dict(self._thread_names)
    pid = os.getpid()
    meta = [{"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
             "args": {"name": thread_name}}
            for tid, thread_name in sorted(names.items())]
    return meta + events

  def save(self, path: str) -> str:
    """Writes the Chrome trace-event JSON object format; returns path.

    Open the file in Perfetto (https://ui.perfetto.dev) or
    chrome://tracing — both consume this format unmodified.
    """
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    payload = {"traceEvents": self.events(), "displayTimeUnit": "ms"}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
      json.dump(payload, f)
    os.replace(tmp, path)
    return path


_GLOBAL = Tracer()
# The cached pid must not survive a fork (events would carry the
# parent's pid and the aggregator would fold two processes into one
# timeline row).
os.register_at_fork(after_in_child=lambda: _GLOBAL._refresh_pid())


def get_tracer() -> Tracer:
  """The process-wide tracer the shipped instrumentation records into."""
  return _GLOBAL


def enable() -> None:
  _GLOBAL.enable()


def disable() -> None:
  _GLOBAL.disable()


def span(name: str, cat: str = "span", **args: Any) -> Span:
  return _GLOBAL.span(name, cat=cat, **args)


def traced(name: Optional[str] = None, cat: str = "span"):
  return _GLOBAL.traced(name, cat=cat)


def instant(name: str, cat: str = "instant", **args: Any) -> None:
  _GLOBAL.instant(name, cat=cat, **args)


def add_complete(name: str, start_ns: int, dur_ns: int, cat: str = "span",
                 args: Optional[Dict[str, Any]] = None) -> None:
  _GLOBAL.add_complete(name, start_ns, dur_ns, cat=cat, args=args)


def save(path: str) -> str:
  return _GLOBAL.save(path)


def clear() -> None:
  _GLOBAL.clear()
