"""faultlab: seeded, deterministic fault injection for graftguard.

PAPERS.md's scaling writeups treat hardware failure and restart cost as
a first-class axis ("Scalable Training of Language Models using JAX
pjit and TPUv4") and serving availability under rollout/failure as a
measured quantity (the Gemma-on-TPU serving writeup). This repo could
*detect* nearly everything (sentinel incidents, fleet health eviction,
flight-recorder postmortems) but recovery behavior was asserted, never
measured — because nothing could inject a fault on demand. faultlab is
that missing half: a deterministic fault plane threaded through the
existing seams, so `bench.py --chaos` can run a SEEDED fault storm and
price goodput-under-faults and MTTR per fault class like any other
diff-gated bench family.

Injection points (the seam that checks each one is named in situ):

  data.record_io       record-source I/O error (`data/pipeline.py`
                       record stream, both the native-stager and the
                       pure-Python fallback paths)
  data.corrupt_record  corrupt-record bytes: a record in the batch is
                       bit-flipped BEFORE parse, so the parser fails
                       exactly the way real corruption fails
  data.preprocess      preprocess exception inside the overlapped
                       loader's preprocess stage
  serve.dispatch       per-replica dispatch failure (`ServingFleet`)
  serve.latency        per-replica latency spike (spec.arg = ms)
  loop.actor_crash     actor-process death inside the graftloop episode
                       loop (`loop/actor.py`; key = actor index) — the
                       supervisor's restart path is the seam under test
  loop.actor_hang      actor heartbeat stall (spec.arg = seconds the
                       actor sleeps without beating) — drives the
                       supervisor's hang detection
  ckpt.torn            torn (truncated) checkpoint file right after
                       `CheckpointManager.save`
  ckpt.bitflip         single flipped byte in a checkpoint file after
                       save (the silent-corruption case the manifest
                       checksums exist to catch)
  train.nonfinite      non-finite loss injected into the train loop's
                       host-side metric fetch (drives the sentinel
                       divergence incident -> rewind path)

Determinism: every decision is a pure function of (plan seed, point,
key, arrival index) — a crc32-derived uniform, the same construction
`serving/fleet.py` uses for its hash ring — and arrivals are counted
per (point, key) under a lock, so "the 3rd dispatch on replica 1
fails" means the same event every run regardless of thread
interleaving elsewhere. Every injected fault is counted
(`faultlab/injected`, `faultlab/<point>`) and remembered (bounded), so
a chaos run's runs.jsonl record is attributable fault by fault.

Activation is explicit and process-global (`activate(plan)` /
`plan.activated()` context manager); with no active plan every
`maybe_fire` is None and the seams cost one attribute read. Backend-
free at import like the rest of `obs/` (tests/test_graftguard.py
proves it under a poisoned JAX_PLATFORMS).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import zlib
from typing import Any, Deque, Dict, List, Mapping, Optional, Sequence, Tuple

from tensor2robot_tpu.obs import metrics as metrics_lib

__all__ = ["FaultSpec", "FaultPlan", "activate", "deactivate", "active",
           "maybe_fire", "InjectedIOError", "InjectedDispatchError",
           "InjectedPreprocessError", "InjectedActorCrash",
           "DATA_RECORD_IO", "DATA_CORRUPT_RECORD", "DATA_PREPROCESS",
           "SERVE_DISPATCH", "SERVE_LATENCY", "CKPT_TORN", "CKPT_BITFLIP",
           "TRAIN_NONFINITE", "LOOP_ACTOR_CRASH", "LOOP_ACTOR_HANG"]

DATA_RECORD_IO = "data.record_io"
DATA_CORRUPT_RECORD = "data.corrupt_record"
DATA_PREPROCESS = "data.preprocess"
SERVE_DISPATCH = "serve.dispatch"
SERVE_LATENCY = "serve.latency"
CKPT_TORN = "ckpt.torn"
CKPT_BITFLIP = "ckpt.bitflip"
TRAIN_NONFINITE = "train.nonfinite"
LOOP_ACTOR_CRASH = "loop.actor_crash"
LOOP_ACTOR_HANG = "loop.actor_hang"

KNOWN_POINTS = frozenset({
    DATA_RECORD_IO, DATA_CORRUPT_RECORD, DATA_PREPROCESS,
    SERVE_DISPATCH, SERVE_LATENCY, CKPT_TORN, CKPT_BITFLIP,
    TRAIN_NONFINITE, LOOP_ACTOR_CRASH, LOOP_ACTOR_HANG})

# Remembered fire events per plan (attribution, not accounting — the
# registry counters are unbounded).
_MAX_FIRED = 512


class InjectedIOError(IOError):
  """Injected record-source I/O error (real-IOError subclass on
  purpose: recovery code MUST treat it exactly like real corruption)."""


class InjectedDispatchError(RuntimeError):
  """Injected serving dispatch failure."""


class InjectedPreprocessError(ValueError):
  """Injected preprocess-stage exception."""


class InjectedActorCrash(RuntimeError):
  """Injected graftloop actor death (the supervisor must restart)."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
  """One fault rule: WHERE (`point` + optional `key` targeting) and
  WHEN (exactly one of `at` / `every` / `rate`).

  * `at`    — fire on these 0-based arrival indices at the point;
  * `every` — fire on every Nth arrival (n % every == every - 1);
  * `rate`  — Bernoulli(rate) per arrival from the seeded stream;
  * `count` — cap on TOTAL fires of this spec (0 = unlimited);
  * `key`   — only arrivals carrying this key match (e.g. a replica
    index for `serve.*`); None matches any key;
  * `arg`   — mode argument read by the seam (latency ms, etc.).
  """

  point: str
  at: Tuple[int, ...] = ()
  every: int = 0
  rate: float = 0.0
  count: int = 0
  key: Optional[Any] = None
  arg: Any = None

  def __post_init__(self):
    if self.point not in KNOWN_POINTS:
      raise ValueError(f"Unknown faultlab point {self.point!r} "
                       f"(known: {sorted(KNOWN_POINTS)})")
    modes = sum((bool(self.at), bool(self.every), bool(self.rate)))
    if modes != 1:
      raise ValueError(
          "Exactly one of at/every/rate must be set, got "
          f"at={self.at!r} every={self.every!r} rate={self.rate!r}")
    if self.rate and not 0.0 < self.rate <= 1.0:
      raise ValueError(f"rate must be in (0, 1], got {self.rate}")
    if self.every and self.every < 1:
      # bool(-5) passes the one-mode check above, but no arrival index
      # satisfies `n % -5 == -6` — the spec would silently never fire.
      raise ValueError(f"every must be >= 1, got {self.every}")
    object.__setattr__(self, "at", tuple(int(i) for i in self.at))
    if any(i < 0 for i in self.at):
      raise ValueError(f"at indices must be >= 0, got {self.at}")


def _unit(seed: int, point: str, key: Any, n: int) -> float:
  """Deterministic uniform in [0, 1) for one arrival (crc32-derived —
  stable across processes, the `serving/fleet.py` hash-ring choice)."""
  text = f"{seed}/{point}/{key}/{n}"
  return (zlib.crc32(text.encode("utf-8")) & 0xFFFFFFFF) / 2.0**32


class FaultPlan:
  """A seeded set of `FaultSpec`s plus the per-(point, key) arrival
  accounting that makes firing deterministic (module docstring)."""

  def __init__(self, faults: Sequence[FaultSpec] = (), seed: int = 0,
               registry: Optional[metrics_lib.Registry] = None):
    self.seed = int(seed)
    self._faults: List[FaultSpec] = list(faults)
    self._registry = registry
    self._lock = threading.Lock()
    self._arrivals: Dict[Tuple[str, Any], int] = {}
    self._fires_per_spec: Dict[int, int] = {}
    self._fired: Deque[Dict[str, Any]] = collections.deque(
        maxlen=_MAX_FIRED)
    self._by_point: Dict[str, int] = {}

  @classmethod
  def from_config(cls, config: Mapping[str, Any],
                  registry: Optional[metrics_lib.Registry] = None
                  ) -> "FaultPlan":
    """Builds a plan from a JSON-safe dict:
    `{"seed": 7, "faults": [{"point": "serve.dispatch", "at": [3],
    "key": 1}, ...]}` — the shape `bench.py --chaos` and config files
    carry."""
    faults = [FaultSpec(**dict(f)) for f in config.get("faults", ())]
    return cls(faults, seed=int(config.get("seed", 0)), registry=registry)

  def _reg(self) -> metrics_lib.Registry:
    return self._registry or metrics_lib.get_registry()

  def maybe_fire(self, point: str, key: Optional[Any] = None
                 ) -> Optional[FaultSpec]:
    """One arrival at `point` (with optional targeting `key`): returns
    the firing `FaultSpec` — the seam then enacts the fault — or None.
    Deterministic per (seed, point, key, arrival index)."""
    with self._lock:
      slot = (point, key)
      n = self._arrivals.get(slot, 0)
      self._arrivals[slot] = n + 1
      for index, spec in enumerate(self._faults):
        if spec.point != point:
          continue
        if spec.key is not None and spec.key != key:
          continue
        fires = self._fires_per_spec.get(index, 0)
        if spec.count and fires >= spec.count:
          continue
        if spec.at:
          hit = n in spec.at
        elif spec.every:
          hit = n % spec.every == spec.every - 1
        else:
          hit = _unit(self.seed, point, key, n) < spec.rate
        if not hit:
          continue
        self._fires_per_spec[index] = fires + 1
        self._by_point[point] = self._by_point.get(point, 0) + 1
        self._fired.append({"point": point, "key": key, "arrival": n,
                            "spec": index})
        break
      else:
        return None
    reg = self._reg()
    reg.counter("faultlab/injected").inc()
    reg.counter(f"faultlab/{point}").inc()
    return spec

  # -- attribution -----------------------------------------------------------

  def fired(self) -> List[Dict[str, Any]]:
    """The (bounded) fire events so far, oldest first."""
    with self._lock:
      return list(self._fired)

  def summary(self) -> Dict[str, Any]:
    """JSON-safe block for runs.jsonl stamping: seed, totals per point,
    arrival counts — a chaos record is attributable from this alone."""
    with self._lock:
      return {
          "seed": self.seed,
          "injected": sum(self._by_point.values()),
          "by_point": dict(self._by_point),
          "arrivals": {f"{p}" + (f"[{k}]" if k is not None else ""): n
                       for (p, k), n in sorted(self._arrivals.items(),
                                               key=lambda kv: str(kv[0]))},
      }

  # -- activation ------------------------------------------------------------

  def activated(self):
    """Context manager: activates this plan for the `with` body."""
    plan = self

    class _Activation:
      def __enter__(self):
        activate(plan)
        return plan

      def __exit__(self, *exc):
        deactivate()
        return False

    return _Activation()


_active_lock = threading.Lock()
_active_plan: Optional[FaultPlan] = None


def activate(plan: FaultPlan) -> FaultPlan:
  """Makes `plan` the process-global active plan (returns it)."""
  global _active_plan
  with _active_lock:
    _active_plan = plan
  return plan


def deactivate() -> None:
  global _active_plan
  with _active_lock:
    _active_plan = None


def active() -> Optional[FaultPlan]:
  return _active_plan


def maybe_fire(point: str, key: Optional[Any] = None
               ) -> Optional[FaultSpec]:
  """The seam entry point: one attribute read when no plan is active."""
  plan = _active_plan
  if plan is None:
    return None
  return plan.maybe_fire(point, key=key)


# Config-engine activation (utils/config is stdlib-only, so this keeps
# the backend-free import contract): a research config can arm a chaos
# plan for the run it configures, e.g.
#   activate_fault_plan.seed = 13
#   activate_fault_plan.faults = [{"point": "train.nonfinite", "at": [24]}]
from tensor2robot_tpu.utils import config as _config  # noqa: E402


@_config.configurable
def activate_fault_plan(seed: int = 0,
                        faults: Sequence[Mapping[str, Any]] = ()
                        ) -> FaultPlan:
  """Builds and ACTIVATES a `FaultPlan` from JSON-safe spec dicts (the
  `FaultPlan.from_config` shape); returns the active plan."""
  return activate(FaultPlan.from_config({"seed": seed,
                                         "faults": list(faults)}))
