"""graftwatch device-time ledger: busy-vs-idle accounting per replica
device group, utilization, and cost-per-request.

The reference stack has no notion of what a device-second costs — a
TPUEstimator deployment's utilization was whatever the billing console
said a month later (/root/reference/utils/train_eval.py:136-151 is the
whole execution story; nothing measures occupancy). Production TPU
serving decides fleet size on exactly two numbers: utilization and
cost-per-request (PAPERS.md: the Gemma-on-TPU serving economics —
"serve the peak, don't pay for it at the trough"). This ledger derives
both from dispatch windows the serving path ALREADY times:

* BUSY time per group = the batcher dispatch windows
  (`MicroBatcher._serve_batch` / `SessionBatcher._serve_batch` stamp
  `dispatch_ns -> end_ns` around every backend call and hand the
  ledger each window through the `usage=` hook) plus engine warmup
  (the `warmup_ms` provenance — startup compiles/deserializes occupy
  the device too). A dispatch occupies the replica's WHOLE device
  group (SPMD: every device in the group participates), so
  device-seconds scale by the group's device count.
* IDLE time = wall time x devices - busy. Nothing is instrumented for
  idleness — it is the complement, which is what makes busy+idle
  reconcile with wall-clock by construction (tests pin it on the
  virtual 8-device mesh).
* WINDOWED utilization — a bounded sample ring of (t, cum_busy)
  per group answers "how busy over the last W seconds", which is the
  scale-in gate `ServingFleet.recommended_replicas()` consumes: a
  trough recommendation must be backed by SUSTAINED idle
  device-seconds, not one quiet sample.

Every `record_busy` also mirrors into the active metrics registry
(`serve/fleet/busy_ms/<group>` + `serve/fleet/busy_requests/<group>`
counters), so bench `metrics.isolated()` windows and graftrace metrics
shards carry per-group busy time for `graftscope watch` without
touching the ledger object. `summary()` exports the
`serve/fleet/device_seconds_{busy,idle}` / `serve/fleet/utilization` /
`serve/fleet/cost_per_request_usd` gauges and returns the JSON block
runs.jsonl records.

Backend-free at import; thread-safe (one lock, O(1) per record).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, Optional

from tensor2robot_tpu.obs import metrics as obs_metrics
from tensor2robot_tpu.utils import config

__all__ = ["UsageLedger", "COST_PER_DEVICE_HOUR_USD"]

# On-demand v5e list price class — a PLACEHOLDER economics anchor, not
# a billing integration: cost_per_request only needs to be proportional
# to device-seconds to rank configurations; override per deployment.
COST_PER_DEVICE_HOUR_USD = 1.20


class _Group:
  """One accounted device group (a fleet replica, usually)."""

  __slots__ = ("devices", "opened_s", "closed_s", "busy_s", "requests",
               "samples")

  def __init__(self, devices: int, opened_s: float, sample_cap: int):
    self.devices = max(int(devices), 1)
    self.opened_s = opened_s
    self.closed_s: Optional[float] = None
    self.busy_s = 0.0
    self.requests = 0
    # (t, cum_busy_s) ring for windowed utilization; bounded so a
    # long-lived fleet cannot grow the ledger.
    self.samples: "collections.deque" = collections.deque(
        maxlen=sample_cap)


@config.configurable
class UsageLedger:
  """Per-group busy/idle device-time accounting (module docstring).

  `clock` is injectable (monotonic seconds) so the reconciliation
  arithmetic is testable without sleeping; production callers leave the
  default. `name` prefixes the mirrored registry counters/gauges —
  the fleet passes its own name so two fleets in one process (the
  bench's single + duo arms) stay distinguishable.
  """

  def __init__(self, name: str = "serve/fleet",
               cost_per_device_hour_usd: float = COST_PER_DEVICE_HOUR_USD,
               sample_window_s: float = 60.0,
               sample_interval_s: float = 0.25,
               clock=time.monotonic):
    self._name = name
    self._cost_per_device_hour = float(cost_per_device_hour_usd)
    self._sample_interval_s = max(float(sample_interval_s), 0.0)
    cap = int(sample_window_s / max(sample_interval_s, 1e-3)) + 2
    self._sample_cap = max(cap, 8)
    self._clock = clock
    self._lock = threading.Lock()
    self._groups: Dict[str, _Group] = {}

  # -- recording ------------------------------------------------------------

  def open_group(self, group: str, devices: int = 1) -> None:
    """Starts the wall-clock window for a group (idempotent)."""
    now = self._clock()
    with self._lock:
      if group not in self._groups:
        self._groups[group] = _Group(devices, now, self._sample_cap)

  def close_group(self, group: str) -> None:
    """Freezes a group's wall-clock window (replica closed)."""
    now = self._clock()
    with self._lock:
      entry = self._groups.get(group)
      if entry is not None and entry.closed_s is None:
        entry.closed_s = now

  def record_busy(self, group: str, busy_s: float,
                  requests: int = 0) -> None:
    """One dispatch (or warmup) window: `busy_s` seconds during which
    the group's devices were occupied, serving `requests` requests.
    Auto-opens unknown groups (1 device) so bare batchers can feed a
    ledger without fleet choreography."""
    if busy_s < 0.0:
      raise ValueError(f"busy_s must be >= 0, got {busy_s}")
    now = self._clock()
    with self._lock:
      entry = self._groups.get(group)
      if entry is None:
        entry = _Group(1, now, self._sample_cap)
        self._groups[group] = entry
      entry.busy_s += float(busy_s)
      entry.requests += int(requests)
      if (not entry.samples
          or now - entry.samples[-1][0] >= self._sample_interval_s):
        entry.samples.append((now, entry.busy_s))
    # Registry mirror (counters live in whatever registry is active —
    # bench isolation windows and graftrace shards see per-group busy
    # without holding the ledger).
    obs_metrics.counter(f"{self._name}/busy_ms/{group}").inc(
        float(busy_s) * 1e3)
    if requests:
      obs_metrics.counter(f"{self._name}/busy_requests/{group}").inc(
          int(requests))

  def recorder(self, group: str):
    """A `(busy_s, requests) -> None` bound recorder — the shape the
    batcher `usage=` hook takes."""

    def record(busy_s: float, requests: int = 0) -> None:
      self.record_busy(group, busy_s, requests)

    return record

  # -- reading --------------------------------------------------------------

  def window_utilization(self, window_s: float,
                         now: Optional[float] = None) -> tuple:
    """(utilization, coverage_s) over the trailing window, across open
    groups: busy device-seconds in the window over wall device-seconds
    in it. `coverage_s` is how much of the window the ledger actually
    observed (bounded by the youngest group's age) — the scale-in gate
    treats coverage < window as "not sustained yet"."""
    at = self._clock() if now is None else now
    busy = 0.0
    wall = 0.0
    coverage = float(window_s)
    with self._lock:
      open_groups = [g for g in self._groups.values()
                     if g.closed_s is None]
      if not open_groups:
        return 0.0, 0.0
      for entry in open_groups:
        span = min(float(window_s), max(at - entry.opened_s, 0.0))
        coverage = min(coverage, span)
        wall += span * entry.devices
        cutoff = at - window_s
        baseline = 0.0 if entry.opened_s >= cutoff else None
        for t, cum in entry.samples:
          if t <= cutoff:
            baseline = cum
          else:
            break
        if baseline is None:
          # No sample at-or-before the window edge: the oldest retained
          # sample is the closest honest baseline (underestimates busy,
          # which biases the gate AGAINST scale-in — the safe side).
          baseline = entry.samples[0][1] if entry.samples else 0.0
        busy += (entry.busy_s - baseline) * entry.devices
    if wall <= 0.0:
      return 0.0, coverage
    return min(busy / wall, 1.0), coverage

  def summary(self, now: Optional[float] = None) -> Dict[str, Any]:
    """The JSON utilization block (runs.jsonl / bench headline), and
    the gauge export. busy + idle == wall x devices by construction."""
    at = self._clock() if now is None else now
    groups_out: Dict[str, Any] = {}
    busy_total = 0.0
    wall_total = 0.0
    requests_total = 0
    devices_total = 0
    with self._lock:
      items = sorted(self._groups.items())
    for group, entry in items:
      end = entry.closed_s if entry.closed_s is not None else at
      wall_s = max(end - entry.opened_s, 0.0)
      busy_dev_s = entry.busy_s * entry.devices
      wall_dev_s = wall_s * entry.devices
      idle_dev_s = max(wall_dev_s - busy_dev_s, 0.0)
      groups_out[group] = {
          "devices": entry.devices,
          "wall_s": round(wall_s, 4),
          "device_seconds_busy": round(busy_dev_s, 4),
          "device_seconds_idle": round(idle_dev_s, 4),
          "utilization": round(busy_dev_s / wall_dev_s, 4)
                         if wall_dev_s > 0 else 0.0,
          "requests": entry.requests,
      }
      busy_total += busy_dev_s
      wall_total += wall_dev_s
      requests_total += entry.requests
      devices_total += entry.devices
    idle_total = max(wall_total - busy_total, 0.0)
    utilization = busy_total / wall_total if wall_total > 0 else 0.0
    # Cost prices WALL device-seconds (busy AND idle): idle capacity is
    # paid for — that is the whole point of the trough signal.
    cost_total = wall_total / 3600.0 * self._cost_per_device_hour
    cost_per_request = (cost_total / requests_total
                        if requests_total else None)
    out = {
        "devices": devices_total,
        "device_seconds_busy": round(busy_total, 4),
        "device_seconds_idle": round(idle_total, 4),
        "utilization": round(utilization, 4),
        "requests": requests_total,
        "cost_per_device_hour_usd": self._cost_per_device_hour,
        "cost_usd": round(cost_total, 6),
        "cost_per_request_usd": (round(cost_per_request, 8)
                                 if cost_per_request is not None
                                 else None),
        "groups": groups_out,
    }
    obs_metrics.gauge(f"{self._name}/device_seconds_busy").set(
        round(busy_total, 4))
    obs_metrics.gauge(f"{self._name}/device_seconds_idle").set(
        round(idle_total, 4))
    obs_metrics.gauge(f"{self._name}/utilization").set(
        round(utilization, 4))
    if cost_per_request is not None:
      obs_metrics.gauge(f"{self._name}/cost_per_request_usd").set(
          round(cost_per_request, 8))
    return out
