"""graftcache: persistent on-disk executable/AOT cache (compile once,
serve many — across PROCESSES).

Compile time is the measured tax everywhere in this system: the round-5
compile valley (PERFORMANCE.md), `BucketedEngine.warmup()` compiling
every bucket on every serving cold start (20-40 s per compile over the
axon tunnel), every bench probe re-tracing from scratch in its own
subprocess, and every trainer restart re-paying the train-step compile
it already paid yesterday. The reference never solved this either — TF
sessions re-specialize per feed shape behind an opaque boundary
(/root/reference/predictors/exported_savedmodel_predictor.py:53-359);
its closest artifact is the SavedModel exported once and loaded by many
robots. graftcache is that artifact for compiled XLA executables
(PAPERS.md: "Automatic Full Compilation ... to Cloud TPUs" and
"Compiler-First ... Portable O(1) Autoregressive Caching" both argue the
compile-once/serve-many shape; this module makes it persistent).

Two tiers:

* **Serialized AOT executables** — `jax.experimental.serialize_executable`
  round-trips of the very executables `obs.xray.analyze_jit` already
  produces. Content-addressed on disk under a key that fingerprints
  EVERYTHING that could invalidate an executable: the jaxpr (which bakes
  in static_argnums values), abstract arg shapes/dtypes + pytree
  structure + input shardings, the declared donation layout, the device
  topology, and the jax/jaxlib/backend version. A warm process pays one
  deserialize (~ms) instead of one compile (~20-40 s over the tunnel).
* **The XLA compilation cache** (`jax_compilation_cache_dir`) as the
  backstop for plain-jit paths that never route through `analyze_jit`
  (`enable_xla_cache`): those still re-trace, but XLA's own persistent
  cache absorbs the backend compile.

Layout: one `<key>.json` metadata sidecar (strict JSON: name, key
components, byte sizes, sha256 of the blob, the cold process's xray
record) + one `<key>.bin` pickle blob (serialized executable + in/out
tree defs) per entry. The sidecar is everything the backend-free readers
(`graftscope cache` list/verify/evict, `entries`, `verify`) need — only
`load`/`store` touch jax.

Contracts, same as the rest of `obs/`:

* telemetry/caching must never take down the run — a stale, corrupt, or
  version-skewed entry falls back to a fresh compile with a
  `cache/corrupt_entries` counter bump (the entry is quarantined), and
  `store` failures are counted, never raised;
* backend-free at import AND at key computation: `cache_key` is pure
  stdlib over pre-computed component strings (tests/test_excache.py
  proves import + key-compute under a poisoned JAX_PLATFORMS); jax is
  imported only inside `load`/`store`/fingerprint helpers, which run
  where the backend is already up;
* every hit/miss/load lands in the metrics registry
  (`cache/{hits,misses,load_ms,bytes,...}`) and from there in the
  runs.jsonl record, so `graftscope diff` gates cold-start time like any
  other headline metric.

graftlint enforces the key discipline statically: a `cache_key(...)`
call site that omits the mesh/dtype/backend-version components is a
finding (`analysis/cache_check.py`), so a future caller cannot silently
build an under-keyed cache.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import pickle
import re
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from tensor2robot_tpu.obs import metrics as metrics_lib

__all__ = ["CACHE_VERSION", "cache_key", "key_components_from_traced",
           "jaxpr_fingerprint", "pallas_fingerprint", "mesh_fingerprint",
           "backend_fingerprint",
           "aot_cache_unsafe", "donating_mesh_cache_unsafe",
           "DONATING_MESH_SAFE_FROM", "ExecutableCache", "as_cache",
           "enable_xla_cache", "xla_cache_bypassed", "cache_stats"]

# Bumped whenever the entry format (blob layout, meta schema, key
# recipe) changes — part of every key, so an old-format entry can never
# be deserialized by a new reader; it just misses and gets recompiled.
# v2: the key grew the `pallas` component (ISSUE 20 — kernel-revision
# invalidation for Pallas/Mosaic lowerings).
CACHE_VERSION = 2

# THE toolchain pin for the donating-mesh cache gate (ROADMAP item 5's
# standing note, mechanized). On jax 0.4.37 a deserialized executable —
# from the serialized-AOT tier OR the XLA persistent compilation cache
# — that DONATES mesh-typed (NamedSharding) inputs heap-corrupts on
# dispatch ("corrupted double-linked list" / SIGSEGV; repro conditions
# documented on `aot_cache_unsafe` and pinned in tests/test_excache.py
# + tests/test_forge.py). Until a newer toolchain is re-verified, every
# jax version rides the gate: donating-mesh executables skip BOTH cache
# tiers (train modes additionally disarm the XLA tier, train_eval.py).
#
# UN-GATING (one constant): when the image moves past 0.4.37, re-run
# the repro (tests/test_forge.py::TestDonatingMeshGate documents the
# exact conditions), and on a clean pass set this to that jax version
# string (e.g. "0.4.38"). Every version >= it then caches donating-mesh
# executables on both tiers; the existing per-component key-sensitivity
# tests re-verify the key discipline for the newly admitted entries
# unchanged — nothing else moves. None = no version verified safe yet.
DONATING_MESH_SAFE_FROM: Optional[str] = None


def _version_tuple(version: str) -> Tuple[int, ...]:
  """Lenient numeric version parse ('0.4.37' -> (0, 4, 37); non-numeric
  tails like '0.5.0.dev1' truncate at the first non-int segment)."""
  parts: List[int] = []
  for segment in str(version).split("."):
    digits = re.match(r"\d+", segment)
    if digits is None:
      break
    parts.append(int(digits.group()))
  return tuple(parts)


def donating_mesh_cache_unsafe(jax_version: Optional[str] = None) -> bool:
  """True while the running jax rides the donating-mesh SIGSEGV gate.

  Version-keyed against `DONATING_MESH_SAFE_FROM`: the gate is ACTIVE
  (True) unless a safe-from version is pinned and the running jax is at
  or past it. Both tiers consult this one predicate — the serialized
  tier via `aot_cache_unsafe`, the XLA tier via train_eval's train-mode
  disarm — so flipping the single constant above un-gates them
  together, and the key-sensitivity tests re-verify both."""
  if DONATING_MESH_SAFE_FROM is None:
    return True
  if jax_version is None:
    import jax

    jax_version = getattr(jax, "__version__", "0")
  safe_from = _version_tuple(DONATING_MESH_SAFE_FROM)
  current = _version_tuple(jax_version)
  if not safe_from or not current:
    return True  # unparseable pin/version: stay gated
  return current < safe_from

_META_SUFFIX = ".json"
_BLOB_SUFFIX = ".bin"
_KEY_RE = re.compile(r"^[A-Za-z0-9_.-]+$")


# ---------------------------------------------------------------------------
# Key computation (pure — no jax, no backend).
# ---------------------------------------------------------------------------


def _slug(name: str) -> str:
  """Filesystem-safe readable prefix for a key (`serve/engine/bucket4`
  -> `serve-engine-bucket4`)."""
  return re.sub(r"[^A-Za-z0-9_.]+", "-", str(name)).strip("-") or "fn"


def cache_key(name: str, *,
              jaxpr_fingerprint: str,
              avals: str,
              mesh: str,
              backend_version: str,
              donation: str,
              static_args: str,
              pallas: str) -> str:
  """THE canonical graftcache key. Every keyword is mandatory on purpose.

  A cached executable is only valid for exactly the computation, input
  layout, device topology, and compiler that produced it, so the key
  fingerprints all of them:

  * `jaxpr_fingerprint` — the traced computation (static_argnums values
    are baked into the jaxpr, but see `static_args` below);
  * `avals` — abstract arg shapes/dtypes + pytree structure + committed
    input shardings (a dtype or layout change MUST miss);
  * `mesh` — device topology (`mesh_fingerprint`): count, platform,
    device kinds. An executable compiled for 8 virtual CPU devices must
    never load into a 1-device process;
  * `backend_version` — jax/jaxlib/backend versions
    (`backend_fingerprint`): serialized executables do not survive
    compiler upgrades (round-5 measured fact: the terminal's older
    libtpu refused image-AOT-compiled executables);
  * `donation` — the declared donated-argument layout: donation changes
    buffer aliasing in the compiled artifact, not just the jaxpr;
  * `static_args` — repr of the non-array (static/config) arguments, a
    belt-and-braces over the jaxpr baking (a static value that steers
    compile options without appearing in the jaxpr still invalidates);
  * `pallas` — the Pallas/Mosaic lowering component
    (`pallas_fingerprint`): kernel-body hash + kernel count + the jax
    (== pallas) version for every `pallas_call` in the computation, or
    `"none"`. The kernel BODY rides inside the jaxpr fingerprint too,
    but grid/BlockSpec/alias/compiler-params metadata lives in eqn
    params whose rendering the jaxpr hash covers only incidentally —
    this component pins kernel revisions explicitly, so editing a
    kernel (or upgrading the pallas toolchain that compiles it)
    invalidates cached executables even when the surrounding jaxpr
    text is unchanged.

  Pure stdlib over pre-computed strings: key computation must work on
  the tunnel machine with no backend (poisoned-platform test). Callers
  with a live `Traced` use `key_components_from_traced`.

  graftlint (`cache-key-missing-component`) statically flags any call
  site that omits a component — do not "simplify" one away.
  """
  payload = json.dumps({
      "v": CACHE_VERSION,
      "jaxpr": str(jaxpr_fingerprint),
      "avals": str(avals),
      "mesh": str(mesh),
      "backend": str(backend_version),
      "donation": str(donation),
      "static": str(static_args),
      "pallas": str(pallas),
  }, sort_keys=True)
  digest = hashlib.sha256(payload.encode("utf-8")).hexdigest()[:32]
  return f"{_slug(name)}-{digest}"


def mesh_fingerprint(devices: Optional[Sequence[Any]] = None) -> str:
  """Device-topology component: count, platform, sorted device kinds.

  Imports jax lazily (callers run where the backend is already up);
  pass `devices` explicitly to stay backend-free.
  """
  if devices is None:
    import jax

    devices = jax.devices()
  devices = list(devices)
  kinds = sorted({str(getattr(d, "device_kind", "?")) for d in devices})
  platforms = sorted({str(getattr(d, "platform", "?")) for d in devices})
  return (f"n{len(devices)}:" + ",".join(platforms) + ":"
          + ",".join(kinds))


def backend_fingerprint() -> str:
  """Compiler-version component: jax + jaxlib + backend platform_version."""
  import jax

  parts = [f"jax={getattr(jax, '__version__', '?')}"]
  try:
    import jaxlib

    parts.append(f"jaxlib={getattr(jaxlib, '__version__', '?')}")
  except Exception:  # noqa: BLE001 - jaxlib version is best-effort
    pass
  try:
    client = jax.devices()[0].client
    parts.append(f"pjrt={getattr(client, 'platform_version', '?')}")
  except Exception:  # noqa: BLE001 - platform_version is best-effort
    pass
  return ";".join(parts)


def _leaf_is_array(leaf) -> bool:
  return hasattr(leaf, "shape") and hasattr(leaf, "dtype")


# Process-local object addresses inside repr()s — the jaxpr string
# embeds e.g. `jvp_jaxpr_thunk=<function _memoize.<locals>.memoized at
# 0x7eb802cac5e0>` for custom_jvp params (measured: the ONLY jaxpr
# difference between two processes tracing the identical model). Thunk
# identity is not semantic; the equations are. Stripped before hashing
# or no key would ever match across processes.
_ADDR_RE = re.compile(r"0x[0-9a-fA-F]+")


def jaxpr_fingerprint(jaxpr) -> str:
  """sha256 of the jaxpr's address-normalized string form."""
  return hashlib.sha256(
      _ADDR_RE.sub("0x", str(jaxpr)).encode("utf-8")).hexdigest()


def _param_jaxprs(val):
  """Yields the jaxprs nested inside one eqn param value (ClosedJaxpr,
  bare Jaxpr, or tuples/lists of either — the shapes cond/scan/pjit
  and pallas_call actually use)."""
  vals = val if isinstance(val, (tuple, list)) else (val,)
  for v in vals:
    inner = getattr(v, "jaxpr", v)  # ClosedJaxpr -> Jaxpr
    if hasattr(inner, "eqns"):
      yield inner


def pallas_fingerprint(jaxpr) -> str:
  """The Pallas/Mosaic lowering component of a cache key.

  Walks the (closed) jaxpr recursively — through cond/scan/pjit/remat
  sub-jaxprs — collecting every `pallas_call` equation, and hashes
  their address-normalized string forms (the kernel BODY jaxpr plus
  the grid/BlockSpec/alias/compiler-params metadata all render into
  the eqn text). Returns `"none"` for kernel-free computations — the
  overwhelmingly common key stays byte-stable and visibly
  kernel-free — else `jax=<version>;n=<count>;<sha256[:32]>`: a kernel
  revision OR a pallas toolchain bump (pallas ships inside jax, so the
  jax version IS the pallas version) invalidates cached executables.
  Pure jaxpr-walking — never touches a backend (poisoned-platform
  safe)."""
  found: List[str] = []

  def walk(jx):
    for eqn in getattr(jx, "eqns", ()):
      if eqn.primitive.name == "pallas_call":
        found.append(_ADDR_RE.sub("0x", str(eqn)))
      for param_val in eqn.params.values():
        for sub in _param_jaxprs(param_val):
          walk(sub)

  walk(getattr(jaxpr, "jaxpr", jaxpr))
  if not found:
    return "none"
  import jax

  digest = hashlib.sha256("||".join(found).encode("utf-8")).hexdigest()
  return (f"jax={getattr(jax, '__version__', '?')};n={len(found)};"
          f"{digest[:32]}")


def aot_cache_unsafe(traced, args) -> bool:
  """True when serialize/deserialize round-trips must be SKIPPED for
  this executable: the toolchain rides the donating-mesh gate
  (`donating_mesh_cache_unsafe` — version-keyed against the
  `DONATING_MESH_SAFE_FROM` pin) AND it donates at least one input AND
  its inputs carry mesh-typed (non-SingleDevice) shardings.

  Measured on this host (jax 0.4.37, virtual CPU meshes): a
  `deserialize_and_load`-ed executable that donates NamedSharding
  inputs created by `jax.device_put`/orbax-restore corrupts the heap
  ("corrupted double-linked list" / SIGSEGV) — the exact shape of a
  trainer restart (restored TrainState donated into the warm train
  step), on 8-device AND single-device (1,1,1) meshes alike. The plain
  AOT executable, non-donating deserialized executables (the whole
  serving path), and donating ones over plain SingleDeviceSharding
  (the bench probes, the tunnel's one-chip deployment: hundreds of
  warm calls measured stable) are all fine. Until a re-verified
  toolchain lifts the gate, the donating mesh case rides the XLA
  compilation-cache tier instead — warm restarts still skip the
  backend compile, they just re-pay trace+lower.
  """
  import jax

  if not donating_mesh_cache_unsafe():
    return False  # toolchain re-verified past the pin: cache everything
  infos = jax.tree_util.tree_leaves(
      traced.args_info, is_leaf=lambda n: hasattr(n, "donated"))
  if not any(getattr(i, "donated", False) for i in infos):
    return False
  for arg in args:
    for leaf in jax.tree_util.tree_leaves(arg):
      sharding = getattr(leaf, "sharding", None)
      if sharding is None:
        continue
      if not isinstance(sharding, jax.sharding.SingleDeviceSharding):
        return True
  return False


def key_components_from_traced(traced, args) -> Dict[str, str]:
  """The `cache_key` components for one `fn.trace(*args)` result.

  `avals` folds in the abstract shapes/dtypes, the args_info pytree
  structure, AND the committed input shardings read off the live args
  (two identically-shaped batches sharded differently compile different
  executables). `static_args` reprs every argument with no array leaves
  — conservative (a dynamic scalar config arg adds key sensitivity, an
  extra miss at worst, never a mismatched executable).
  """
  import jax

  infos = jax.tree_util.tree_leaves(
      traced.args_info, is_leaf=lambda n: hasattr(n, "donated"))
  avals = [str(getattr(i, "aval", i)) for i in infos]
  structure = str(jax.tree_util.tree_structure(
      traced.args_info, is_leaf=lambda n: hasattr(n, "donated")))
  shardings = []
  for arg in args:
    for leaf in jax.tree_util.tree_leaves(arg):
      sharding = getattr(leaf, "sharding", None)
      if sharding is not None:
        shardings.append(str(sharding))
  static = [repr(a) for a in args
            if not any(_leaf_is_array(leaf)
                       for leaf in jax.tree_util.tree_leaves(a))]
  return {
      "jaxpr_fingerprint": jaxpr_fingerprint(traced.jaxpr),
      "avals": structure + "|" + ";".join(avals)
               + "|" + ";".join(shardings),
      "mesh": mesh_fingerprint(),
      "backend_version": backend_fingerprint(),
      "donation": ",".join("D" if getattr(i, "donated", False) else "-"
                           for i in infos),
      "static_args": ";".join(static),
      "pallas": pallas_fingerprint(traced.jaxpr),
  }


# ---------------------------------------------------------------------------
# The on-disk cache.
# ---------------------------------------------------------------------------


class ExecutableCache:
  """Content-addressed executable store under one directory.

  `load`/`store` never raise (fallback-to-fresh-compile is the caller's
  contract; failures are counted); `entries`/`verify`/`evict` are
  backend-free (metadata sidecars only).
  """

  def __init__(self, cache_dir: str,
               registry: Optional[metrics_lib.Registry] = None):
    self._dir = str(cache_dir)
    self._registry = registry
    self._lock = threading.Lock()

  @property
  def directory(self) -> str:
    return self._dir

  @property
  def _reg(self) -> metrics_lib.Registry:
    # Late-bound: the process-wide registry may be reset/swapped between
    # construction and use (train_eval resets it per run).
    return self._registry or metrics_lib.get_registry()

  def _paths(self, key: str) -> Tuple[str, str]:
    if not _KEY_RE.match(key or ""):
      raise ValueError(f"invalid cache key {key!r}")
    return (os.path.join(self._dir, key + _META_SUFFIX),
            os.path.join(self._dir, key + _BLOB_SUFFIX))

  # -- write side -----------------------------------------------------------

  def store(self, key: str, compiled, record: Optional[Dict[str, Any]] = None,
            name: Optional[str] = None) -> bool:
    """Serializes + persists one executable; False (counted) on failure.

    The serialized payload is VALIDATED by an in-process deserialize
    before anything touches disk: an executable that itself came out of
    the XLA persistent compilation cache serializes to a payload with
    dangling kernel-symbol references ("Symbols not found" — measured
    on this exact host), and persisting it would cost every later
    process a quarantine + recompile. `analyze_jit` compiles AOT-tier
    misses under `xla_cache_bypassed` so this should not occur on the
    standard path; the validation stays as belt-and-braces for direct
    `store` callers. Rejections are counted (`cache/store_rejected`),
    never raised.

    The blob is written `.tmp` + `os.replace` and the metadata sidecar
    AFTER the blob, so a reader can never observe a sidecar whose blob
    is missing/torn — at worst an orphan blob, which `verify` reports
    and `evict` collects.
    """
    try:
      from jax.experimental import serialize_executable

      meta_path, blob_path = self._paths(key)
      payload = serialize_executable.serialize(compiled)
      try:
        serialize_executable.deserialize_and_load(*payload)
      except Exception as e:  # noqa: BLE001 - unloadable = do not persist
        self._reg.counter("cache/store_rejected").inc()
        print(f"graftcache: NOT persisting {key!r} — its serialized "
              f"form does not load back ({type(e).__name__}); this "
              "process loaded kernels from a warm XLA compilation "
              "cache, which poisons every later serialize",
              file=sys.stderr)
        # Self-heal: reset the co-located XLA tier so the NEXT process
        # compiles self-contained payloads and the entry refills (one
        # extra backend-compile generation, then warm again — without
        # this, a quarantined entry could never re-store while tier 2
        # stayed warm). Plain-jit consumers just re-pay one compile.
        xla_dir = os.path.join(self._dir, "xla")
        if os.path.isdir(xla_dir):
          import shutil

          shutil.rmtree(xla_dir, ignore_errors=True)
          self._reg.counter("cache/xla_tier_reset").inc()
          print(f"graftcache: reset XLA cache tier {xla_dir} so the "
                "next process can persist self-contained executables",
                file=sys.stderr)
        return False
      blob = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
      meta = {
          "cache_version": CACHE_VERSION,
          "key": key,
          "name": str(name or (record or {}).get("name") or key),
          "created_unix": time.time(),
          "blob_bytes": len(blob),
          "blob_sha256": hashlib.sha256(blob).hexdigest(),
          "backend_version": backend_fingerprint(),
      }
      if record:
        # The cold process's xray record (compile_s, flops, roofline,
        # memory analysis): a warm start keeps full compile telemetry
        # without paying the compile. Cache bookkeeping is stripped —
        # hit/miss is a property of THIS process, not of the entry.
        stored = {k: v for k, v in record.items() if k != "cache"}
        meta["record"] = stored
      with self._lock:
        os.makedirs(self._dir, exist_ok=True)
        # Temp names are unique PER WRITER (pid+thread): two processes
        # cold-starting the same key against a shared dir must not
        # scribble into one shared ".tmp" (the in-process lock cannot
        # cover cross-process writers); each rename publishes a
        # complete file, last writer wins.
        suffix = f".tmp.{os.getpid()}.{threading.get_ident()}"
        tmp = blob_path + suffix
        with open(tmp, "wb") as f:
          f.write(blob)
        os.replace(tmp, blob_path)
        tmp = meta_path + suffix
        with open(tmp, "w") as f:
          json.dump(meta, f, sort_keys=True)
        os.replace(tmp, meta_path)
      self._reg.counter("cache/stores").inc()
      self._reg.counter("cache/bytes_stored").inc(len(blob))
      return True
    except Exception as e:  # noqa: BLE001 - caching must never break a run
      self._reg.counter("cache/store_failures").inc()
      print(f"graftcache: store of {key!r} failed "
            f"({type(e).__name__}: {e})", file=sys.stderr)
      return False

  # -- read side ------------------------------------------------------------

  def load(self, key: str) -> Optional[Dict[str, Any]]:
    """Deserializes one entry: {"compiled", "record", "load_ms", "bytes"}
    or None (miss / corrupt / version-skewed — counted, never raised).

    Any load failure past "file absent" quarantines the entry (both
    files unlinked) and bumps `cache/corrupt_entries`: a stale or
    corrupt entry must cost ONE fresh compile, not one per process
    forever — and must never serve a mismatched executable (the key
    already fingerprints everything semantic; the checksum catches
    torn/bit-rotted blobs).
    """
    try:
      meta_path, blob_path = self._paths(key)
    except ValueError:
      self._reg.counter("cache/misses").inc()
      return None
    if not os.path.isfile(meta_path) or not os.path.isfile(blob_path):
      self._reg.counter("cache/misses").inc()
      return None
    start = time.perf_counter()

    def read_verified():
      with open(meta_path) as f:
        meta = json.load(f)
      if int(meta.get("cache_version", -1)) != CACHE_VERSION:
        raise ValueError(
            f"cache_version {meta.get('cache_version')} != {CACHE_VERSION}")
      with open(blob_path, "rb") as f:
        blob = f.read()
      if len(blob) != int(meta.get("blob_bytes", -1)):
        raise ValueError(f"blob is {len(blob)} bytes, sidecar says "
                         f"{meta.get('blob_bytes')}")
      digest = hashlib.sha256(blob).hexdigest()
      if digest != meta.get("blob_sha256"):
        raise ValueError("blob sha256 mismatch")
      return meta, blob

    try:
      try:
        meta, blob = read_verified()
      except Exception:  # noqa: BLE001 - maybe a concurrent re-store
        # Cross-process store/load race: another process's store
        # replaces the blob a moment before its sidecar (store's write
        # order), so a reader can pair an old sidecar with a new blob.
        # One short-delay retry reads the settled pair; only a SECOND
        # failure is genuine corruption worth quarantining — a race
        # must never destroy the valid entry a peer just wrote.
        time.sleep(0.05)
        meta, blob = read_verified()
      from jax.experimental import serialize_executable

      payload, in_tree, out_tree = pickle.loads(blob)
      compiled = serialize_executable.deserialize_and_load(
          payload, in_tree, out_tree)
    except Exception as e:  # noqa: BLE001 - corrupt entry -> fresh compile
      self._quarantine(key, e)
      return None
    load_ms = (time.perf_counter() - start) * 1e3
    self._reg.counter("cache/hits").inc()
    self._reg.counter("cache/bytes").inc(len(blob))
    self._reg.histogram("cache/load_ms").record(load_ms)
    return {"compiled": compiled,
            "record": dict(meta.get("record") or {}),
            "load_ms": load_ms, "bytes": len(blob)}

  def _quarantine(self, key: str, error: Exception) -> None:
    self._reg.counter("cache/corrupt_entries").inc()
    print(f"graftcache: entry {key!r} unusable "
          f"({type(error).__name__}: {error}); quarantined — "
          "falling back to a fresh compile", file=sys.stderr)
    try:
      meta_path, blob_path = self._paths(key)
      for path in (meta_path, blob_path):
        try:
          os.unlink(path)
        except OSError:
          pass
    except ValueError:
      pass

  # -- backend-free maintenance (graftscope cache CLI) ----------------------

  def entries(self) -> List[Dict[str, Any]]:
    """Metadata of every entry (sidecars only — no jax, no unpickle).

    Orphan blobs (store died between blob and sidecar) are listed with
    `"orphan": True` so `evict` can collect them.
    """
    out: List[Dict[str, Any]] = []
    if not os.path.isdir(self._dir):
      return out
    seen_blobs = set()
    for fname in sorted(os.listdir(self._dir)):
      path = os.path.join(self._dir, fname)
      if fname.endswith(_META_SUFFIX):
        key = fname[:-len(_META_SUFFIX)]
        entry: Dict[str, Any] = {"key": key}
        try:
          with open(path) as f:
            entry.update({k: v for k, v in json.load(f).items()
                          if k != "record"})
        except (OSError, ValueError) as e:
          entry["corrupt_sidecar"] = f"{type(e).__name__}: {e}"
        blob = os.path.join(self._dir, key + _BLOB_SUFFIX)
        entry["blob_present"] = os.path.isfile(blob)
        seen_blobs.add(key)
        out.append(entry)
    for fname in sorted(os.listdir(self._dir)):
      if fname.endswith(_BLOB_SUFFIX):
        key = fname[:-len(_BLOB_SUFFIX)]
        if key not in seen_blobs:
          out.append({"key": key, "orphan": True,
                      "blob_bytes": os.path.getsize(
                          os.path.join(self._dir, fname))})
    return out

  def verify(self) -> Tuple[List[str], List[str]]:
    """(ok keys, bad keys) by checksum — backend-free, read-only."""
    ok: List[str] = []
    bad: List[str] = []
    for entry in self.entries():
      key = entry["key"]
      if entry.get("orphan") or entry.get("corrupt_sidecar") \
          or not entry.get("blob_present"):
        bad.append(key)
        continue
      blob_path = os.path.join(self._dir, key + _BLOB_SUFFIX)
      try:
        with open(blob_path, "rb") as f:
          blob = f.read()
        if (len(blob) != int(entry.get("blob_bytes", -1))
            or hashlib.sha256(blob).hexdigest()
            != entry.get("blob_sha256")):
          raise ValueError("checksum mismatch")
        ok.append(key)
      except (OSError, ValueError):
        bad.append(key)
    return ok, bad

  def evict(self, key: Optional[str] = None,
            older_than_secs: Optional[float] = None,
            name_prefix: Optional[str] = None) -> int:
    """Removes entries; returns how many were removed.

    No selector = everything INCLUDING the XLA compilation-cache tier
    under `<dir>/xla` (the two tiers are one unit; partial evicts
    leave the XLA tier alone — AOT-miss compiles bypass it anyway, see
    `xla_cache_bypassed`, so evicted entries refill cleanly). `key`
    evicts one entry; `older_than_secs` evicts entries created longer
    ago than that (sidecar-less orphans always match an age sweep);
    `name_prefix` evicts entries whose recorded name starts with it
    (how the cold-start bench resets ONLY its own namespace instead of
    nuking every probe's entries in a shared cache dir).
    """
    selective = (key is not None or older_than_secs is not None
                 or name_prefix is not None)
    if not selective:
      import shutil

      shutil.rmtree(os.path.join(self._dir, "xla"), ignore_errors=True)
    removed = 0
    now = time.time()
    for entry in self.entries():
      if key is not None and entry["key"] != key:
        continue
      if name_prefix is not None and not str(
          entry.get("name") or "").startswith(name_prefix):
        continue
      if older_than_secs is not None and not entry.get("orphan"):
        created = float(entry.get("created_unix") or 0.0)
        if now - created < older_than_secs:
          continue
      for suffix in (_META_SUFFIX, _BLOB_SUFFIX):
        try:
          os.unlink(os.path.join(self._dir, entry["key"] + suffix))
        except OSError:
          continue
      removed += 1
    if removed:
      self._reg.counter("cache/evictions").inc(removed)
    return removed


def as_cache(cache) -> Optional[ExecutableCache]:
  """Coerces a cache argument: ExecutableCache passes through, a
  directory path wraps, None/'' disables."""
  if cache is None or cache == "":
    return None
  if isinstance(cache, ExecutableCache):
    return cache
  return ExecutableCache(str(cache))


# ---------------------------------------------------------------------------
# Tier 2: the XLA compilation cache backstop.
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def xla_cache_bypassed():
  """Temporarily disables the XLA persistent compilation cache.

  `analyze_jit` wraps the compile of every AOT-tier MISS in this: an
  executable served out of the XLA persistent cache serializes with
  dangling kernel symbols (store() would reject it), so a miss that
  compiled through a warm XLA cache could never refill its AOT entry.
  Bypassing the tier for exactly these compiles keeps the stored blob
  self-contained; plain-jit paths (and donating-mesh executables,
  which never reach the AOT tier) still enjoy the XLA cache untouched.
  NOT sufficient on its own: once a process has LOADED any executable
  from a warm XLA cache (e.g. an earlier plain-jit init compile),
  every later serialize in that process is poisoned regardless of this
  bypass (measured) — store()'s validation catches those, and its
  rejection path resets the tier so the next process heals.
  """
  try:
    import jax

    previous = jax.config.jax_compilation_cache_dir
  except Exception:  # noqa: BLE001 - no config = nothing to bypass
    previous = None
  if previous is None:
    yield
    return
  import jax

  jax.config.update("jax_compilation_cache_dir", None)
  try:
    yield
  finally:
    jax.config.update("jax_compilation_cache_dir", previous)


def enable_xla_cache(cache_dir: str) -> bool:
  """Points jax's persistent compilation cache at `<cache_dir>/xla` —
  the backstop for plain-jit paths that never route through
  `analyze_jit` (they still re-trace, but the backend compile is
  absorbed by XLA's own cache). Best-effort: False when this jax/backend
  does not support it. Min-compile-time gate dropped to 0 so
  smoke-scale executables cache too (the default skips anything under
  1 s, which is every CPU-smoke compile)."""
  try:
    import jax

    xla_dir = os.path.join(str(cache_dir), "xla")
    os.makedirs(xla_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", xla_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    return True
  except Exception as e:  # noqa: BLE001 - a backstop, never a blocker
    print(f"graftcache: XLA compilation cache unavailable "
          f"({type(e).__name__}: {e})", file=sys.stderr)
    return False


def cache_stats(registry: Optional[metrics_lib.Registry] = None
                ) -> Dict[str, float]:
  """The `cache/*` registry slice as a flat dict — the block run records
  and bench headlines embed (ISSUE 7: every hit/miss/load lands in
  runs.jsonl). Counters are pre-created so the headline schema is
  stable even on a zero-traffic run."""
  reg = registry or metrics_lib.get_registry()
  for name in ("cache/hits", "cache/misses", "cache/corrupt_entries",
               "cache/stores", "cache/store_failures",
               "cache/store_rejected"):
    reg.counter(name)
  return reg.snapshot(prefix="cache/")
