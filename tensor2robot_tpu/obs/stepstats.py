"""Per-train-step telemetry: data-wait vs device time, compile events.

The reference's TPUEstimator hid the step economics inside
`iterations_per_loop` host calls (/root/reference/models/
abstract_model.py:662-834); our explicit loop can measure them — but ONLY
with the tunnel barrier discipline: `jax.block_until_ready` is NOT a
barrier over the axon tunnel (returns before the remote computation
finishes, NOTES_r2.md), so device completion is established the one
dependable way, a host fetch through `utils.backend.state_barrier`
(the smallest param leaf depends on the full fwd+bwd+update).

Accounting per measured window (`every_n_steps` dispatches, default 1):

* `data_wait_ms`  — host time staging batches (`data_wait()` windows).
  Under the overlapped host loader (`data/overlap.py` stages feeding a
  `DevicePrefetcher`), the loop's `data_wait()` wraps only the DEQUEUE
  of an already-placed batch, so parse/preprocess/place work running in
  worker threads concurrently with device compute inflates NEITHER
  `data_wait_ms` NOR `device_ms` (pinned by the synthetic
  overlapped-producer test in tests/test_overlap.py): a near-zero
  `data_wait_ms` with healthy throughput means the pipeline keeps up,
  and a growing one means the consumer outran it — read the
  `data/overlap_*` stage timings to see which stage binds;
* `device_ms`     — un-overlapped device wait: dispatch-call time plus
  the closing barrier fetch. Host staging that overlaps device compute
  is deliberately NOT charged to the device — the split answers "what
  is the loop's wall clock spent waiting on";
* `host_ms`       — the remainder (hooks, metric fetch, logging);
* `step_ms`       — full window wall time / steps;
* `examples_per_sec`, `compile` (first dispatch, or a dispatch-time
  spike: re-trace/re-compile), `live_arrays` / `live_bytes` gauges.

The barrier costs a real host fetch per measured window (~0.1 s over
the tunnel): use `every_n_steps=1` only for CPU/debug runs and a
coarser cadence for tunnel training so the fetch amortizes (the
windowed averages stay exact) — `train_eval_model`'s default picks
per-step vs log-cadence by backend. Importing this module never
touches jax (backend access is lazy, from inside a live loop); the
train-loop integration lives in `train_eval.py` +
`hooks.core.StepStatsHook`.
"""

from __future__ import annotations

import sys
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from tensor2robot_tpu.obs import metrics as metrics_lib
from tensor2robot_tpu.obs import trace as trace_lib

__all__ = ["StepStatsRecorder"]

# A window whose post-barrier residual is below this fraction of the
# window is "barrier dominated": its step_ms is an upper bound, not a
# measurement (the same 0.2 clamp rule as backend.time_train_steps_halves)
# — flagged in the record so obs.sentinel's spike detector skips it.
BARRIER_DOMINATED_RESIDUAL = 0.2

# A dispatch call taking longer than BOTH this floor and 10x the running
# median is counted as a compile event (tracing + XLA compile happen
# synchronously inside the dispatch call; execution is async).
COMPILE_FLOOR_MS = 50.0
_COMPILE_SPIKE_FACTOR = 10.0
_DISPATCH_HISTORY = 32


class _WaitTimer:
  """Accumulates one staging window into the recorder (+ trace span)."""

  __slots__ = ("_rec", "_start_ns")

  def __init__(self, rec: "StepStatsRecorder"):
    self._rec = rec
    self._start_ns = 0

  def __enter__(self) -> "_WaitTimer":
    self._start_ns = time.perf_counter_ns()
    return self

  def __exit__(self, exc_type, exc, tb) -> None:
    dur_ns = time.perf_counter_ns() - self._start_ns
    self._rec._data_wait_ns += dur_ns
    self._rec._tracer.add_complete("train/data_wait", self._start_ns,
                                   dur_ns, cat="train")


class _NullTimer:
  __slots__ = ()

  def __enter__(self):
    return self

  def __exit__(self, exc_type, exc, tb):
    return None


_NULL_TIMER = _NullTimer()


def _default_barrier(state):
  from tensor2robot_tpu.utils import backend

  # Return the fetched leaf: it is ALREADY on the host (the barrier is
  # a host fetch by definition), so the non-finite divergence check
  # piggybacks on it for free — zero extra tunnel round trips.
  return backend.state_barrier(state)


class StepStatsRecorder:
  """Train-loop step accountant; all clock reads live in this module.

  Protocol (see `train_eval.py`):

    rec.start()                       # after data/state bring-up
    with rec.data_wait(): batch = next(...)
    rec.before_dispatch(); state, m = step(...); rec.after_dispatch()
    with rec.data_wait(): next_batch = next(...)   # overlapped staging
    rec.end_step(step, state, num_steps=k)         # barrier at cadence
    for step, record in rec.drain(): writer.write_scalars(step, record)

  A disabled recorder (`every_n_steps=0`) keeps the call sites
  unconditional and no-ops at one attribute check per call.
  """

  def __init__(self,
               batch_size: int,
               every_n_steps: int = 1,
               barrier: Optional[Callable[[Any], None]] = None,
               registry: Optional[metrics_lib.Registry] = None,
               tracer: Optional[trace_lib.Tracer] = None,
               device_gauges: bool = True):
    self._enabled = every_n_steps > 0
    self._batch_size = int(batch_size)
    self._every_n = max(int(every_n_steps), 1)
    self._barrier = barrier or _default_barrier
    self._registry = registry or metrics_lib.get_registry()
    self._tracer = tracer or trace_lib.get_tracer()
    self._device_gauges = device_gauges
    self._records: List[Tuple[int, Dict[str, float]]] = []
    self._window_start_ns = 0
    self._data_wait_ns = 0
    self._dispatch_ns = 0
    self._barrier_ns = 0
    self._steps_in_window = 0
    self._dispatches_in_window = 0
    self._last_record_step: Optional[int] = None
    self._dispatch_history_ms: List[float] = []
    self._t_dispatch_ns = 0
    self._compile_in_window = 0
    self._observers: List[Callable[[int, Dict[str, float]], Any]] = []
    self._last_barrier_nonfinite: Optional[float] = None

  @property
  def enabled(self) -> bool:
    return self._enabled

  def add_observer(self,
                   observer: Callable[[int, Dict[str, float]], Any]
                   ) -> None:
    """Registers `observer(step, record)`, called synchronously for
    every emitted window record (drain() is untouched — observers are
    the online path, e.g. `obs.sentinel` / the flight recorder). An
    observer that raises is warned about and dropped — telemetry must
    never take down a train loop."""
    self._observers.append(observer)

  def start(self) -> None:
    """Marks the start of the first measurement window."""
    if self._enabled:
      self._window_start_ns = time.perf_counter_ns()

  def data_wait(self):
    """Context manager charging its window to `data_wait_ms`."""
    return _WaitTimer(self) if self._enabled else _NULL_TIMER

  def before_dispatch(self) -> None:
    if self._enabled:
      self._t_dispatch_ns = time.perf_counter_ns()

  def after_dispatch(self) -> None:
    """Call immediately after the (async) step dispatch returns."""
    if not self._enabled:
      return
    dur_ns = time.perf_counter_ns() - self._t_dispatch_ns
    self._dispatch_ns += dur_ns
    self._dispatches_in_window += 1
    dispatch_ms = dur_ns / 1e6
    history = self._dispatch_history_ms
    median = sorted(history)[len(history) // 2] if history else 0.0
    if not history or dispatch_ms > max(COMPILE_FLOOR_MS,
                                        _COMPILE_SPIKE_FACTOR * median):
      # First dispatch always compiles; later spikes are re-traces.
      self._compile_in_window += 1
      self._registry.counter("stepstats/compile_events").inc()
      self._tracer.add_complete("train/compile_dispatch",
                                self._t_dispatch_ns, dur_ns, cat="train")
    history.append(dispatch_ms)
    if len(history) > _DISPATCH_HISTORY:
      history.pop(0)

  def end_step(self, step: int, state: Any, num_steps: int = 1) -> None:
    """Closes the step; at the cadence, barriers and emits a record."""
    if not self._enabled:
      return
    self._steps_in_window += num_steps
    if self._steps_in_window < self._every_n:
      return
    barrier_start_ns = time.perf_counter_ns()
    try:
      fetched = self._barrier(state)
    except Exception:
      # A FAILING barrier is the strongest tunnel evidence there is:
      # stamp it before the exception unwinds into the flight-recorder
      # dump, so the bundle's heartbeat timeline carries the death time
      # and cause for the in-train path (not just bench's probe path).
      self._record_barrier_failure(
          (time.perf_counter_ns() - barrier_start_ns) / 1e9)
      raise
    now_ns = time.perf_counter_ns()
    self._barrier_ns += now_ns - barrier_start_ns
    self._tracer.add_complete("train/barrier", barrier_start_ns,
                              now_ns - barrier_start_ns, cat="train")
    self._observe_barrier(fetched, (now_ns - barrier_start_ns) / 1e9)
    self._emit(step, now_ns)

  def _stamp_heartbeat(self, ok: bool, barrier_s: float,
                       cause: Optional[str] = None) -> None:
    """The ONE place holding the tunnel-evidence rule for barriers:
    stamp the heartbeat monitor only when the barrier actually crossed
    the tunnel (non-CPU backend) — a CPU-pinned run's barriers say
    nothing about tunnel health and must not overwrite a correctly
    recorded DEAD (platform_pinned_cpu) state. Never raises (and in
    the failure path, never masks the barrier's own error)."""
    try:
      import jax

      if jax.devices()[0].platform != "cpu":
        from tensor2robot_tpu.utils import backend

        backend.record_heartbeat(ok, elapsed_s=barrier_s,
                                 source="state_barrier", cause=cause)
    except Exception:  # noqa: BLE001 - heartbeat is best-effort
      pass

  def _record_barrier_failure(self, barrier_s: float) -> None:
    # A FAILING barrier is the strongest tunnel evidence there is.
    self._stamp_heartbeat(False, barrier_s, cause="barrier_failed")

  def _observe_barrier(self, fetched: Any, barrier_s: float) -> None:
    """Piggybacks on the barrier's host fetch: non-finite divergence
    check on the fetched param leaf (zero extra round trips) + a
    tunnel heartbeat stamp (see `_stamp_heartbeat` for the
    crossed-the-tunnel gate)."""
    self._last_barrier_nonfinite = None
    if fetched is not None:
      try:
        import numpy as np

        self._last_barrier_nonfinite = float(
            not bool(np.all(np.isfinite(np.asarray(fetched)))))
      except Exception:  # noqa: BLE001 - non-float leaves etc.
        self._last_barrier_nonfinite = None
    self._stamp_heartbeat(True, barrier_s)

  def _emit(self, step: int, now_ns: int) -> None:
    n = self._steps_in_window
    window_s = max((now_ns - self._window_start_ns) / 1e9, 1e-9)
    data_wait_ms = self._data_wait_ns / 1e6 / n
    device_ms = (self._dispatch_ns + self._barrier_ns) / 1e6 / n
    step_ms = window_s * 1e3 / n
    record: Dict[str, float] = {
        "step_ms": step_ms,
        "device_ms": device_ms,
        "data_wait_ms": data_wait_ms,
        "host_ms": max(step_ms - device_ms - data_wait_ms, 0.0),
        "dispatch_ms": self._dispatch_ns / 1e6 / n,
        "examples_per_sec": n * self._batch_size / window_s,
        "compile": float(self._compile_in_window > 0),
        "steps_in_window": float(n),
        # The 0.2-residual clamp rule (backend.time_train_steps_halves):
        # a window the barrier fetch swallowed is an upper bound — the
        # sentinel spike detector must skip it.
        "barrier_dominated": float(
            window_s * 1e9 - self._barrier_ns
            < BARRIER_DOMINATED_RESIDUAL * window_s * 1e9),
    }
    if self._last_barrier_nonfinite is not None:
      record["nonfinite_params"] = self._last_barrier_nonfinite
    record.update(self._read_device_gauges())
    self._records.append((int(step), record))
    for observer in list(self._observers):
      try:
        observer(int(step), record)
      except Exception as e:  # noqa: BLE001 - drop a broken observer
        self._observers.remove(observer)
        print(f"stepstats: observer {observer!r} failed and was "
              f"detached ({type(e).__name__}: {e})", file=sys.stderr)
    reg = self._registry
    reg.histogram("stepstats/step_ms").record(step_ms)
    reg.histogram("stepstats/device_ms").record(device_ms)
    reg.histogram("stepstats/data_wait_ms").record(data_wait_ms)
    reg.histogram("stepstats/examples_per_sec").record(
        record["examples_per_sec"])
    reg.gauge("stepstats/examples_per_sec").set(record["examples_per_sec"])
    first_step = int(step) - n + 1
    self._tracer.add_complete(
        "train/step_window", self._window_start_ns,
        now_ns - self._window_start_ns, cat="train",
        args={"first_step": first_step, "last_step": int(step), "steps": n})
    self._window_start_ns = now_ns
    self._data_wait_ns = self._dispatch_ns = self._barrier_ns = 0
    self._steps_in_window = self._dispatches_in_window = 0
    self._compile_in_window = 0
    self._last_record_step = int(step)

  def _read_device_gauges(self) -> Dict[str, float]:
    """Live-array count/bytes (+ allocator bytes when the backend
    reports them). Latches off on first failure — telemetry must never
    take down a train loop."""
    if not self._device_gauges:
      return {}
    try:
      from tensor2robot_tpu.utils import backend

      out = backend.device_memory_stats()
      self._registry.gauge("device/live_arrays").set(out["live_arrays"])
      self._registry.gauge("device/live_bytes").set(out["live_bytes"])
      return out
    except Exception:  # noqa: BLE001 - gauges are best-effort
      self._device_gauges = False
      return {}

  def drain(self) -> List[Tuple[int, Dict[str, float]]]:
    """Pops every completed (step, record) pair, oldest first."""
    records, self._records = self._records, []
    return records
