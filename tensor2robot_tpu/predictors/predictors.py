"""Predictors: load trained artifacts, serve `predict(features) -> dict`.

Reference surface (/root/reference/predictors/):
* `AbstractPredictor` (abstract_predictor.py:26-81) — the robot-side
  contract: predict / get_feature_specification / restore / close;
* `ExportedSavedModelPredictor` (exported_savedmodel_predictor.py:53-359)
  — polls timestamped export dirs, validates them, loads assets, serves;
* `CheckpointPredictor` (checkpoint_predictor.py:37-215) — rebuilds the
  PREDICT graph from the model and restores raw training checkpoints;
* `EnsembleExportedSavedModelPredictor`
  (ensemble_exported_savedmodel_predictor.py:32-180) — random sub-sampled
  mean over several exports.

TPU-native redesign: a predictor holds a jitted predict function plus a
restored variables pytree; "loading an export" = reading the bundle's
assets + orbax params and (when no model object is supplied)
reconstructing the model from the bundle's operative config.
"""

from __future__ import annotations

import abc
import glob
import importlib
import json
import os
import threading
import time
from typing import (Any, Callable, Dict, List, Mapping, NamedTuple, Optional,
                    Sequence)

import jax
import numpy as np
import orbax.checkpoint as ocp

from tensor2robot_tpu import checkpoints as checkpoints_lib
from tensor2robot_tpu import modes as modes_lib
from tensor2robot_tpu import specs as specs_lib
from tensor2robot_tpu.export import export_generator as export_lib
from tensor2robot_tpu.obs import metrics as obs_metrics
from tensor2robot_tpu.obs import sentinel as obs_sentinel
from tensor2robot_tpu.obs import trace as obs_trace
from tensor2robot_tpu.obs import xray as obs_xray
from tensor2robot_tpu.parallel import train_step as ts
from tensor2robot_tpu.utils import config

__all__ = ["AbstractPredictor", "CheckpointPredictor",
           "ExportedModelPredictor", "EnsemblePredictor", "ServingBundle",
           "DecodeBundle"]


class ServingBundle(NamedTuple):
  """What `serving.BucketedEngine` needs from a predictor (see
  `_JaxPredictorBase.serving_bundle`)."""

  jit_predict: Callable      # jitted (state, model_features) -> outputs
  get_state: Callable        # () -> current TrainState (restore-aware)
  preprocess: Callable       # wire features -> model-layout features
  feature_spec: Any          # wire-layout feature spec (warmup synthesis)


class DecodeBundle(NamedTuple):
  """What `serving.session.SessionEngine` needs from a predictor (see
  `_JaxPredictorBase.decode_bundle`): the model's session-decode seam
  plus the restore-aware state getter."""

  decode_fn: Callable          # pure (state, session_state, features)
                               #   -> (new_session_state, outputs)
  init_session_state: Callable  # (batch_size) -> host numpy state rows
  get_state: Callable          # () -> current TrainState (restore-aware)
  observation_spec: Any        # per-TICK feature spec (warmup synthesis)
  max_ticks: Optional[int] = None  # decode horizon (KV capacity); None
                                   #   = unbounded (pure-carry models)
  decode_arena_fn: Optional[Callable] = None  # graftkern fused-arena
                               #   (state, arena, slots, features, mask)
                               #   -> (new_arena, outputs); None = the
                               #   model has no kernel-tier layout


class AbstractPredictor(abc.ABC):
  """The robot-side serving contract."""

  @abc.abstractmethod
  def predict(self, features: Mapping[str, Any]) -> Dict[str, np.ndarray]:
    ...

  @abc.abstractmethod
  def get_feature_specification(self) -> specs_lib.SpecStruct:
    ...

  @abc.abstractmethod
  def restore(self) -> bool:
    """Loads the newest artifact; returns True on success."""

  def init_randomly(self) -> None:
    raise NotImplementedError(
        f"{type(self).__name__} does not support random init.")

  @property
  def model_version(self) -> int:
    return self.global_step

  @property
  def global_step(self) -> int:
    return -1

  def assert_is_loaded(self) -> None:
    if self.global_step < 0:
      raise ValueError(f"{type(self).__name__} has no model loaded; call "
                       "restore() first.")

  def close(self) -> None:
    pass


class _JaxPredictorBase(AbstractPredictor):
  """Common predict plumbing: pack features by spec, run jitted fn.

  `latency_slo_ms` arms the serving SLO breach counter
  (`serve/slo_breaches`, `obs.sentinel.observe_serving_latency`):
  every predict whose END-TO-END latency (the `np.asarray` fetch is the
  tunnel barrier) exceeds it increments the counter — a latency
  regression becomes a counter delta in the graftscope report instead
  of a percentile archaeology session. None disables.

  `executable_cache_dir` arms graftcache (`obs.excache`) on the
  in-process predict path: the `serve/predict` executable persists to
  disk, so a robot-side predictor restart deserializes its warm
  executable instead of recompiling (the cold-start tax the reference's
  SavedModel reload also paid per process). None disables; serving
  never breaks on cache trouble (excache fallback contract). The
  graftserve `BucketedEngine` has its own `cache=` seam for the bucket
  ladder."""

  def __init__(self, latency_slo_ms: Optional[float] = None,
               executable_cache_dir: Optional[str] = None):
    self._model = None
    self._state: Optional[ts.TrainState] = None
    self._predict_fn: Optional[Callable] = None
    self._jit_predict: Optional[Callable] = None
    self._global_step = -1
    self._latency_slo_ms = latency_slo_ms
    self._executable_cache_dir = executable_cache_dir
    self._device = None  # replica pin (place_on_device); None = default

  def _build_predict(self) -> None:
    model = self._model
    # The raw jitted predict fn is kept separately from the xray wrapper:
    # graftserve's BucketedEngine AOT-compiles IT once per shape bucket
    # (`serving_bundle`), while the in-process predict path below wraps
    # it in compile telemetry frozen at the first live shape.
    self._jit_predict = ts.make_predict_fn(model)
    # graftscope-xray compile telemetry: the first predict AOT-compiles
    # through analyze_jit (compile time / jaxpr size / cost analysis
    # into the `serve/predict` record) and later calls reuse that
    # executable; a batch-size change or an analysis failure silently
    # degrades to the plain jitted fn (serving must never break on
    # telemetry).
    predict = obs_xray.XrayedFunction("serve/predict", self._jit_predict,
                                      cache=self._executable_cache_dir)
    preprocessor = model.preprocessor

    def fn(features):
      features, _ = preprocessor.preprocess(
          features, specs_lib.SpecStruct(), modes_lib.PREDICT)
      return predict(self._state, features)

    self._predict_fn = fn
    # Model-layout path for callers that already built post-preprocessor
    # features (e.g. WTL pack_features, whose meta layout is not the
    # preprocessor's wire format).
    self._predict_preprocessed_fn = lambda features: predict(self._state,
                                                             features)

  def serving_bundle(self) -> "ServingBundle":
    """The graftserve seam: the pieces an external serving runtime needs.

    Returns the RAW jitted predict fn (AOT-traceable per shape bucket —
    not the xray wrapper, which freezes at its first live shape), a
    state getter (so a later `restore()` hot-swap is visible to cached
    executables without re-warming: shapes/dtypes are stable across
    restores, only values change), the host-side preprocess fn that
    maps wire-layout features to the model layout, and the wire-layout
    feature spec for synthesizing warmup batches.
    """
    self.assert_is_loaded()
    model = self._model
    preprocessor = model.preprocessor

    def preprocess(features):
      features, _ = preprocessor.preprocess(
          features, specs_lib.SpecStruct(), modes_lib.PREDICT)
      return features

    return ServingBundle(
        jit_predict=self._jit_predict,
        get_state=lambda: self._state,
        preprocess=preprocess,
        feature_spec=self.get_feature_specification())

  def decode_bundle(self) -> "DecodeBundle":
    """The session-serving seam (ISSUE 11): the model's pure decode-step
    fn + session-state initializer, with the SAME restore-aware state
    getter as `serving_bundle` — a checkpoint hot-swap lands on the next
    decode tick without re-warming the session engine. Raises for models
    without the seam (`supports_sessions` is the capability flag)."""
    self.assert_is_loaded()
    model = self._model
    if not getattr(model, "supports_sessions", False):
      raise ValueError(
          f"{type(model).__name__} has no session-decode seam "
          "(supports_sessions is False); serve it through the stateless "
          "BucketedEngine instead.")
    return DecodeBundle(
        decode_fn=model.decode_step_fn(),
        init_session_state=model.init_session_state,
        get_state=lambda: self._state,
        observation_spec=model.decode_observation_spec,
        max_ticks=getattr(model, "decode_max_ticks", None),
        decode_arena_fn=(
            model.decode_arena_step_fn()
            if getattr(model, "supports_decode_kernel", False) else None))

  def get_feature_specification(self) -> specs_lib.SpecStruct:
    self.assert_is_loaded()
    return self._model.preprocessor.get_in_feature_specification(
        modes_lib.PREDICT)

  def get_label_specification(self) -> specs_lib.SpecStruct:
    self.assert_is_loaded()
    return specs_lib.flatten_spec_structure(
        self._model.get_label_specification(modes_lib.PREDICT))

  @property
  def global_step(self) -> int:
    return self._global_step

  def predict(self, features) -> Dict[str, np.ndarray]:
    self.assert_is_loaded()
    # graftscope serving latency: the np.asarray fetch inside the timed
    # window IS the tunnel barrier (block_until_ready is not), so the
    # histogram measures true end-to-end latency, not dispatch.
    start = time.perf_counter()
    with obs_trace.span("serve/predict", cat="serve"):
      outputs = self._predict_fn(features)
      result = {k: np.asarray(v)
                for k, v in dict(outputs.items()).items()}
    self._observe_latency((time.perf_counter() - start) * 1e3)
    return result

  def predict_preprocessed(self, features) -> Dict[str, np.ndarray]:
    """Predict on MODEL-layout (already-preprocessed) features."""
    self.assert_is_loaded()
    start = time.perf_counter()
    with obs_trace.span("serve/predict_preprocessed", cat="serve"):
      outputs = self._predict_preprocessed_fn(features)
      result = {k: np.asarray(v)
                for k, v in dict(outputs.items()).items()}
    self._observe_latency((time.perf_counter() - start) * 1e3)
    return result

  def _observe_latency(self, elapsed_ms: float) -> None:
    obs_metrics.histogram("serve/predict_ms").record(elapsed_ms)
    obs_metrics.counter("serve/predictions").inc()
    obs_sentinel.observe_serving_latency(elapsed_ms, self._latency_slo_ms)

  def place_on_device(self, device) -> None:
    """Commits the predictor's state to `device` — the graftserve fleet's
    replica pinning seam (`serving/fleet.py` + `parallel.mesh.
    replica_device_groups`): dispatches follow committed arguments, so a
    predictor placed on replica N's lead device executes there, and the
    engine's warmup-compiled executables are built for that placement.
    The pin is sticky: both restore() implementations re-place freshly
    restored state onto this device, so a rollout hot-swap never
    migrates a replica off its device group."""
    self.assert_is_loaded()
    self._device = device
    self._state = jax.device_put(self._state, device)


@config.configurable
class CheckpointPredictor(_JaxPredictorBase):
  """Serves directly from training checkpoints (reference
  checkpoint_predictor.py:37-215): rebuilds the predict path from the
  model object and polls model_dir for new steps."""

  def __init__(self, model=None, model_dir: Optional[str] = None,
               timeout_secs: float = 0.0,
               latency_slo_ms: Optional[float] = None,
               executable_cache_dir: Optional[str] = None):
    super().__init__(latency_slo_ms=latency_slo_ms,
                     executable_cache_dir=executable_cache_dir)
    if model is None or model_dir is None:
      raise ValueError("model and model_dir are required.")
    self._model = model
    self._checkpoint_dir = os.path.join(model_dir, "checkpoints") \
        if os.path.isdir(os.path.join(model_dir, "checkpoints")) \
        or not os.path.isdir(model_dir) else model_dir
    self._timeout_secs = timeout_secs

  def init_randomly(self) -> None:
    feature_spec = self._model.preprocessor.get_out_feature_specification(
        modes_lib.PREDICT)
    sample = specs_lib.make_random_numpy(feature_spec, batch_size=1, seed=0)
    self._state, _ = ts.create_train_state(
        self._model, jax.random.PRNGKey(0), sample)
    self._global_step = 0
    self._build_predict()

  def restore(self) -> bool:
    from tensor2robot_tpu.utils import retry as retry_lib

    # Jittered appearance poll: N replica predictors waiting on one
    # model_dir de-synchronize instead of stat-ing in lockstep.
    deadline = time.time() + self._timeout_secs
    step = checkpoints_lib.latest_step(self._checkpoint_dir)
    while step is None and time.time() < deadline:
      time.sleep(retry_lib.jittered_s(1.0, jitter=0.25))
      step = checkpoints_lib.latest_step(self._checkpoint_dir)
    if step is None:
      return False
    if self._state is None:
      self.init_randomly()
    abstract = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), self._state)
    with checkpoints_lib.CheckpointManager(self._checkpoint_dir) as manager:
      # step=None: the graftguard verified-fallback walk — a corrupt
      # newest step (torn write racing the poll, bit rot) is
      # quarantined and the newest VERIFIED step serves instead of the
      # hot-swap raising out of a live rollout().
      self._state = manager.restore(abstract_state=abstract)
      step = manager.last_restored_step
    if self._device is not None:
      # Replica pin survives a hot-swap: the restored tree lands on the
      # default device otherwise, silently migrating this replica's
      # dispatches off its carved-out device group mid-rollout.
      self._state = jax.device_put(self._state, self._device)
    self._global_step = step
    self._build_predict()
    return True


def _valid_export_dirs(export_root: str) -> List[str]:
  """Newest-last list of complete export bundles (reference dir polling +
  validation, exported_savedmodel_predictor.py:314-353)."""
  if not os.path.isdir(export_root):
    return []
  out = []
  for path in sorted(glob.glob(os.path.join(export_root, "*"))):
    name = os.path.basename(path)
    if not name.isdigit():
      continue
    has_assets = (
        os.path.isfile(os.path.join(path, specs_lib.ASSET_FILENAME))
        # Reference-era bundles carry only the text-proto sidecar
        # (load_assets transparently falls back to it).
        or os.path.isfile(os.path.join(path, "assets.extra",
                                       specs_lib.PBTXT_ASSET_FILENAME)))
    if (has_assets
        and os.path.isfile(os.path.join(path, export_lib.SIGNATURE_FILENAME))
        and os.path.isdir(os.path.join(path, export_lib.PARAMS_DIRNAME))):
      out.append(path)
  return out


def _model_from_bundle(path: str):
  """Reconstructs the model object from a bundle's signature + config."""
  with open(os.path.join(path, export_lib.SIGNATURE_FILENAME)) as f:
    signature = json.load(f)
  config_path = os.path.join(path, "operative_config.gin")
  if os.path.isfile(config_path):
    config.parse_config_file(config_path)
  module_name, _, class_name = signature["model_class"].rpartition(".")
  module = importlib.import_module(module_name)
  cls = module
  for part in class_name.split("."):
    cls = getattr(cls, part)
  return cls()


@config.configurable
class ExportedModelPredictor(_JaxPredictorBase):
  """Serves from export bundles (reference
  exported_savedmodel_predictor.py:53-359): polls for the newest valid
  timestamped dir, loads assets + params, optional async restore."""

  def __init__(self, export_dir: Optional[str] = None, model=None,
               timeout_secs: float = 0.0,
               latency_slo_ms: Optional[float] = None,
               executable_cache_dir: Optional[str] = None):
    super().__init__(latency_slo_ms=latency_slo_ms,
                     executable_cache_dir=executable_cache_dir)
    if export_dir is None:
      raise ValueError("export_dir is required.")
    self._export_dir = export_dir
    self._model = model
    self._timeout_secs = timeout_secs
    self._loaded_path: Optional[str] = None
    self._restore_thread: Optional[threading.Thread] = None
    # Lets close() interrupt a restore() polling for exports (the wait
    # can be minutes of timeout_secs) instead of blocking the join.
    self._stop_restore = threading.Event()

  def restore(self) -> bool:
    deadline = time.time() + self._timeout_secs
    dirs = _valid_export_dirs(self._export_dir)
    while (not dirs and time.time() < deadline
           and not self._stop_restore.is_set()):
      self._stop_restore.wait(timeout=1.0)
      dirs = _valid_export_dirs(self._export_dir)
    if not dirs:
      return False
    newest = dirs[-1]
    if newest == self._loaded_path:
      return True
    assets = specs_lib.load_assets(
        os.path.join(newest, specs_lib.ASSET_FILENAME))
    if self._model is None:
      self._model = _model_from_bundle(newest)
    # Restore eval-time variables and wrap them in a TrainState shell.
    with ocp.StandardCheckpointer() as checkpointer:
      variables = checkpointer.restore(
          os.path.join(newest, export_lib.PARAMS_DIRNAME))
    self._state = ts.TrainState(
        step=np.asarray(assets.global_step or 0),
        params=variables["params"], opt_state=None,
        mutable_state=variables.get("mutable") or {},
        ema_params=None, rng=jax.random.PRNGKey(0))
    if self._device is not None:
      # Replica pin survives a bundle swap (the CheckpointPredictor
      # restore rule: restored trees land on the default device
      # otherwise, migrating this replica off its device group).
      self._state = jax.device_put(self._state, self._device)
    self._global_step = int(assets.global_step or 0)
    self._loaded_path = newest
    self._build_predict()
    return True

  def restore_async(self) -> threading.Thread:
    """Background restore (reference async restore thread,
    exported_savedmodel_predictor.py:152-159)."""
    # Backstop exemption: a one-shot restore worker with no loop —
    # it terminates by itself after one bundle load, the handle is
    # returned to the caller, and close() joins it.
    thread = threading.Thread(
        target=self.restore,
        daemon=True)  # graftlint: disable=thread-stage-missing-backstop
    thread.start()
    self._restore_thread = thread
    return thread

  def close(self) -> None:
    """Stops and joins an in-flight `restore_async` worker — the
    export-dir poll wakes on the stop event (so close() never waits
    out `timeout_secs`), and an actual bundle load touches the backend
    (device_put of restored params), so it is joined rather than
    abandoned mid-flight at interpreter shutdown (the graftlint
    `thread-stage-missing-close` discipline)."""
    self._stop_restore.set()
    if self._restore_thread is not None and self._restore_thread.is_alive():
      self._restore_thread.join()
    self._stop_restore.clear()  # a later explicit restore() still works
    super().close()

  @property
  def loaded_path(self) -> Optional[str]:
    return self._loaded_path


@config.configurable
class EnsemblePredictor(AbstractPredictor):
  """Mean aggregation over a random subsample of member predictors
  (reference ensemble_exported_savedmodel_predictor.py:97-122)."""

  def __init__(self, predictors: Optional[Sequence[AbstractPredictor]] = None,
               num_samples: Optional[int] = None, seed: int = 0):
    if not predictors:
      raise ValueError("predictors are required.")
    self._predictors = list(predictors)
    self._num_samples = num_samples or len(self._predictors)
    self._rng = np.random.RandomState(seed)

  def restore(self) -> bool:
    return all(p.restore() for p in self._predictors)

  def get_feature_specification(self) -> specs_lib.SpecStruct:
    return self._predictors[0].get_feature_specification()

  @property
  def global_step(self) -> int:
    return min(p.global_step for p in self._predictors)

  def predict(self, features) -> Dict[str, np.ndarray]:
    chosen = self._rng.choice(len(self._predictors), self._num_samples,
                              replace=False)
    outputs = [self._predictors[i].predict(features) for i in chosen]
    keys = outputs[0].keys()
    return {k: np.mean([o[k] for o in outputs], axis=0) for k in keys}

  def close(self) -> None:
    for p in self._predictors:
      p.close()
