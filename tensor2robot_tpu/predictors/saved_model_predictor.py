"""TF SavedModel predictor: serve jax2tf exports through the TF runtime.

Reference parity: `SavedModelTF2Predictor` / `SavedModelTF1Predictor`
(/root/reference/predictors/saved_model_v2_predictor.py:210-289) — robot
stacks that standardize on TF-Serving keep working: the export bundle's
`saved_model/` dir (written by DefaultExportGenerator with
write_saved_model=True) loads with plain `tf.saved_model.load`, no JAX on
the robot.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Mapping, Optional

import numpy as np

from tensor2robot_tpu import specs as specs_lib
from tensor2robot_tpu.export import export_generator as export_lib
from tensor2robot_tpu.predictors import predictors as predictors_lib
from tensor2robot_tpu.utils import config

__all__ = ["SavedModelPredictor"]


@config.configurable
class SavedModelPredictor(predictors_lib.AbstractPredictor):
  """Loads `<bundle>/saved_model/` and serves via the TF runtime."""

  def __init__(self, export_dir: Optional[str] = None,
               timeout_secs: float = 0.0):
    if export_dir is None:
      raise ValueError("export_dir is required.")
    self._export_dir = export_dir
    self._timeout_secs = timeout_secs
    self._module = None
    self._assets: Optional[specs_lib.Assets] = None
    self._input_keys = None

  def restore(self) -> bool:
    import time

    deadline = time.time() + self._timeout_secs
    while True:
      dirs = [p for p in predictors_lib._valid_export_dirs(self._export_dir)
              if os.path.isdir(os.path.join(
                  p, export_lib.SAVED_MODEL_DIRNAME))]
      if dirs:
        break
      if time.time() >= deadline:
        return False
      time.sleep(1.0)
    newest = dirs[-1]
    import tensorflow as tf

    self._module = tf.saved_model.load(
        os.path.join(newest, export_lib.SAVED_MODEL_DIRNAME))
    self._assets = specs_lib.load_assets(
        os.path.join(newest, specs_lib.ASSET_FILENAME))
    spec = specs_lib.filter_required(self._assets.feature_spec)
    self._input_keys = list(spec.keys())
    return True

  def get_feature_specification(self) -> specs_lib.SpecStruct:
    self.assert_is_loaded()
    return self._assets.feature_spec

  @property
  def global_step(self) -> int:
    if self._assets is None:
      return -1
    return int(self._assets.global_step or 0)

  def predict(self, features: Mapping[str, Any]) -> Dict[str, np.ndarray]:
    self.assert_is_loaded()
    import tensorflow as tf

    flat = specs_lib.flatten_spec_structure(dict(features))
    args = [tf.convert_to_tensor(np.asarray(flat[k]))
            for k in self._input_keys]
    outputs = self._module.fn(*args)
    return {k: np.asarray(v) for k, v in outputs.items()}
