"""TF SavedModel predictor: serve jax2tf exports through the TF runtime.

Reference parity: `SavedModelTF2Predictor` / `SavedModelTF1Predictor`
(/root/reference/predictors/saved_model_v2_predictor.py:210-289) — robot
stacks that standardize on TF-Serving keep working: the export bundle's
`saved_model/` dir (written by DefaultExportGenerator with
write_saved_model=True) loads with plain `tf.saved_model.load`, no JAX on
the robot.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Mapping, Optional

import numpy as np

from tensor2robot_tpu import specs as specs_lib
from tensor2robot_tpu.export import export_generator as export_lib
from tensor2robot_tpu.predictors import predictors as predictors_lib
from tensor2robot_tpu.utils import config

__all__ = ["SavedModelPredictor"]


@config.configurable
class SavedModelPredictor(predictors_lib.AbstractPredictor):
  """Loads `<bundle>/saved_model/` and serves via the TF runtime."""

  def __init__(self, export_dir: Optional[str] = None,
               timeout_secs: float = 0.0):
    if export_dir is None:
      raise ValueError("export_dir is required.")
    self._export_dir = export_dir
    self._timeout_secs = timeout_secs
    self._module = None
    self._assets: Optional[specs_lib.Assets] = None
    self._input_keys = None
    self._signature_feeds: Dict[str, str] = {}

  @staticmethod
  def _saved_model_root(path: str) -> Optional[str]:
    """Where the SavedModel lives for a timestamped dir, if anywhere:
    `<dir>/saved_model/` (native bundle) or `<dir>/` itself holding
    saved_model.pb (a reference-era export,
    /root/reference/predictors/exported_savedmodel_predictor.py:176)."""
    nested = os.path.join(path, export_lib.SAVED_MODEL_DIRNAME)
    if os.path.isdir(nested):
      return nested
    if os.path.isfile(os.path.join(path, "saved_model.pb")):
      return path
    return None

  def restore(self) -> bool:
    import glob
    import time

    deadline = time.time() + self._timeout_secs
    while True:
      # Native bundles pass _valid_export_dirs; reference-era dirs are
      # bare SavedModels with a pbtxt sidecar and no signature.json.
      dirs = [p for p in predictors_lib._valid_export_dirs(self._export_dir)
              if self._saved_model_root(p)]
      if not dirs:
        dirs = [p for p in sorted(glob.glob(
                    os.path.join(self._export_dir, "*")))
                if os.path.basename(p).isdigit()
                and os.path.isfile(os.path.join(p, "saved_model.pb"))]
      if dirs:
        break
      if time.time() >= deadline:
        return False
      time.sleep(1.0)
    newest = dirs[-1]
    import tensorflow as tf

    self._module = tf.saved_model.load(self._saved_model_root(newest))
    self._assets = specs_lib.load_assets(
        os.path.join(newest, specs_lib.ASSET_FILENAME))
    spec = specs_lib.filter_required(self._assets.feature_spec)
    self._input_keys = list(spec.keys())
    if not hasattr(self._module, "fn"):
      self._signature_feeds = self._validated_signature_feeds()
    return True

  def _validated_signature_feeds(self) -> Dict[str, str]:
    """Maps serving-signature kwarg name -> feature key, validated.

    Two specs sharing a wire name would silently overwrite each other in
    the kwarg dict, and a name mismatch vs the signature's declared
    inputs surfaces as an opaque TF shape/arg error far from the cause —
    so both are loud errors here, at restore time (ADVICE r3)."""
    feeds: Dict[str, str] = {}
    for key in self._input_keys:
      spec = self._assets.feature_spec[key]
      name = spec.name or key.rsplit("/", 1)[-1]
      if name in feeds:
        raise ValueError(
            f"Feature specs {feeds[name]!r} and {key!r} both feed serving "
            f"signature input {name!r}; give them distinct spec names.")
      feeds[name] = key
    signature = self._module.signatures["serving_default"]
    _, sig_kwargs = signature.structured_input_signature
    declared = set(sig_kwargs)
    if declared and set(feeds) != declared:
      raise ValueError(
          "Feature spec names do not match the serving_default signature "
          f"inputs. Signature declares {sorted(declared)}; specs feed "
          f"{sorted(feeds)} (missing: {sorted(declared - set(feeds))}, "
          f"unexpected: {sorted(set(feeds) - declared)}).")
    return feeds

  def get_feature_specification(self) -> specs_lib.SpecStruct:
    self.assert_is_loaded()
    return self._assets.feature_spec

  @property
  def global_step(self) -> int:
    if self._assets is None:
      return -1
    return int(self._assets.global_step or 0)

  def predict(self, features: Mapping[str, Any]) -> Dict[str, np.ndarray]:
    self.assert_is_loaded()
    import tensorflow as tf

    flat = specs_lib.flatten_spec_structure(dict(features))
    if hasattr(self._module, "fn"):  # native jax2tf export
      args = [tf.convert_to_tensor(np.asarray(flat[k]))
              for k in self._input_keys]
      outputs = self._module.fn(*args)
    else:
      # Reference-era SavedModel: call the serving signature with
      # keyword tensors named by the feature specs (the reference's
      # receiver feed names, exported_savedmodel_predictor.py:260-282).
      # The name->key map was collision-checked and validated against
      # the signature's declared inputs at restore time.
      signature = self._module.signatures["serving_default"]
      kwargs = {name: tf.convert_to_tensor(np.asarray(flat[key]))
                for name, key in self._signature_feeds.items()}
      outputs = signature(**kwargs)
    return {k: np.asarray(v) for k, v in outputs.items()}
