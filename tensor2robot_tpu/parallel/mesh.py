"""Device mesh construction and host<->device data placement.

The TPU-native replacement for the reference's distribution machinery
(SURVEY.md §2.5): where the reference splits batches across TPU shards via
TPUEstimator + CrossShardOptimizer
(/root/reference/models/tpu_model_wrapper.py:45-49) and aggregates
multi-worker gradients with SyncReplicasOptimizer
(/root/reference/models/abstract_model.py:864-871), this framework lays
out a `jax.sharding.Mesh` over ICI (+ a DCN axis for multislice) and lets
XLA insert the collectives from sharding annotations.

Axes (any may be size 1):
* `data`  — data parallelism (batch dim), the default;
* `fsdp`  — parameter/optimizer-state sharding (ZeRO-style), a new
            capability the reference lacks;
* `model` — tensor parallelism on annotated layers, also new.
"""

from __future__ import annotations

import math
from typing import Any, Mapping, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from tensor2robot_tpu import specs as specs_lib
from tensor2robot_tpu.utils import config

__all__ = ["create_mesh", "data_sharding", "replicated",
           "put_host_batch", "place_batch", "local_batch_size",
           "DevicePrefetcher", "shard_map", "replica_device_groups",
           "initialize_multihost"]

DEFAULT_AXES = ("data", "fsdp", "model")


def shard_map(f, mesh: Mesh, in_specs, out_specs):
  """THE repo's shard_map entry point, jax-version tolerant.

  `jax.shard_map` (with its `check_vma` replication check) only exists on
  newer jax; this toolchain's 0.4.37 ships the same primitive as
  `jax.experimental.shard_map.shard_map` (`check_rep`). Every explicit
  SPMD region in this repo (pipeline schedules, ring/ulysses attention,
  MoE all_to_all dispatch) routes through this one wrapper so the
  version split is handled in exactly one place. Replication checking is
  disabled on both paths — these regions use psum-broadcast outputs the
  checker cannot prove replicated.
  """
  if hasattr(jax, "shard_map"):
    return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)
  from jax.experimental import shard_map as _shard_map_lib

  return _shard_map_lib.shard_map(f, mesh=mesh, in_specs=in_specs,
                                  out_specs=out_specs, check_rep=False)


@config.configurable
def create_mesh(mesh_shape: Optional[Sequence[int]] = None,
                axis_names: Sequence[str] = DEFAULT_AXES,
                devices: Optional[Sequence[jax.Device]] = None,
                dcn_data_parallelism: int = 1) -> Mesh:
  """Builds a Mesh over the available devices.

  With `mesh_shape=None`, all devices go on the first ('data') axis and the
  rest are size 1 — pure DP, the reference's only TPU strategy. For
  multislice pods, `dcn_data_parallelism > 1` builds a hybrid mesh whose
  outermost data axis rides DCN while the inner axes stay on ICI
  (mesh_utils.create_hybrid_device_mesh).
  """
  devices = list(devices if devices is not None else jax.devices())
  n = len(devices)
  if mesh_shape is None:
    mesh_shape = [n] + [1] * (len(axis_names) - 1)
  mesh_shape = list(mesh_shape)
  needed = math.prod(mesh_shape)
  if needed > n:
    raise ValueError(
        f"mesh_shape {mesh_shape} does not cover {n} devices.")
  if needed < n:
    # Explicit smaller meshes use a device prefix (debug / smoke runs).
    devices = devices[:needed]
    n = needed
  if len(mesh_shape) != len(axis_names):
    raise ValueError(
        f"mesh_shape rank {len(mesh_shape)} != axis_names "
        f"{len(axis_names)}.")
  if dcn_data_parallelism > 1:
    ici_shape = list(mesh_shape)
    ici_shape[0] //= dcn_data_parallelism
    dcn_shape = [dcn_data_parallelism] + [1] * (len(axis_names) - 1)
    device_array = mesh_utils.create_hybrid_device_mesh(
        ici_shape, dcn_shape, devices=devices)
  else:
    device_array = mesh_utils.create_device_mesh(mesh_shape,
                                                 devices=devices)
  return Mesh(device_array, tuple(axis_names))


def replica_device_groups(num_replicas: int,
                          devices: Optional[Sequence[jax.Device]] = None
                          ) -> list:
  """Carves the device list into disjoint per-replica groups (the
  graftserve fleet's device carve-out, `serving/fleet.py`).

  Groups are CONTIGUOUS runs of the platform device order, so each
  replica's devices stay within one ICI neighborhood — the same locality
  assumption `create_mesh` makes. Multislice seam: on a DCN-connected
  pod the device order groups by slice first (jax sorts by
  process_index), so `num_replicas == num_slices` puts one replica per
  slice with no cross-DCN dispatch inside a replica; a finer carve-out
  composes with `create_mesh(devices=group)` exactly like the
  single-slice case.

  A remainder (len(devices) % num_replicas) is spread one extra device
  over the FIRST groups rather than left idle — replica capacities may
  then differ by one device, which the fleet's least-outstanding-work
  router absorbs by construction.
  """
  devices = list(devices if devices is not None else jax.devices())
  if num_replicas < 1:
    raise ValueError(f"num_replicas must be >= 1, got {num_replicas}")
  if num_replicas > len(devices):
    raise ValueError(
        f"cannot carve {num_replicas} replica device groups out of "
        f"{len(devices)} devices (>= 1 device per replica required)")
  base, remainder = divmod(len(devices), num_replicas)
  groups = []
  offset = 0
  for index in range(num_replicas):
    size = base + (1 if index < remainder else 0)
    groups.append(devices[offset:offset + size])
    offset += size
  return groups


def data_sharding(mesh: Mesh, batch_axis: str = "data") -> NamedSharding:
  """Sharding for batch leaves: leading dim over the data axis."""
  return NamedSharding(mesh, PartitionSpec(batch_axis))


def replicated(mesh: Mesh) -> NamedSharding:
  return NamedSharding(mesh, PartitionSpec())


def local_batch_size(global_batch_size: int, mesh: Mesh) -> int:
  """Per-host batch size (reference per-host batch override,
  /root/reference/utils/tfdata.py:38-61)."""
  process_count = max(
      1, len({d.process_index for d in mesh.devices.flat}))
  if global_batch_size % process_count:
    raise ValueError(
        f"Global batch {global_batch_size} not divisible by host count "
        f"{process_count}.")
  return global_batch_size // process_count


def put_host_batch(mesh: Mesh, batch, batch_axis: str = "data",
                   spec_structure: Optional[specs_lib.SpecStructLike] = None,
                   batch_spec: Optional[PartitionSpec] = None) -> Any:
  """Forms the global on-device array from each host's local numpy batch.

  Single-host: a plain sharded device_put. Multi-host: every process
  passes its local shard and `jax.make_array_from_process_local_data`
  assembles the global array — the infeed path that replaces
  TPUEstimator's per-host infeed threads.

  `batch_spec` overrides the default batch-dim-only placement for every
  leaf (e.g. PartitionSpec('data', 'sp') for sequence-parallel infeed);
  it must match the step's committed in_shardings.
  """
  flat_partition = None
  if spec_structure is not None:
    flat_partition = specs_lib.partition_specs(spec_structure, batch_axis)

  def _put(path_key, x):
    pspec = batch_spec if batch_spec is not None \
        else PartitionSpec(batch_axis)
    if flat_partition is not None and path_key in flat_partition:
      pspec = flat_partition[path_key]
    sharding = NamedSharding(mesh, pspec)
    if jax.process_count() == 1:
      return jax.device_put(x, sharding)
    return jax.make_array_from_process_local_data(sharding, np.asarray(x))

  if isinstance(batch, specs_lib.SpecStruct):
    out = specs_lib.SpecStruct()
    for key, value in specs_lib.flatten_spec_structure(batch).items():
      out[key] = _put(key, value)
    return out
  return jax.tree_util.tree_map(lambda x: _put(None, x), batch)


def place_batch(mesh: Mesh, batch, batch_spec=None):
  """Places one host batch dict: -> (features, labels) device trees.

  Missing labels become an empty SpecStruct. The single shared
  implementation behind both the train loop's inline path and the
  DevicePrefetcher worker, so the two can never diverge.
  """
  features = put_host_batch(mesh, batch["features"], batch_spec=batch_spec)
  labels = (put_host_batch(mesh, batch["labels"], batch_spec=batch_spec)
            if "labels" in batch else specs_lib.SpecStruct())
  return features, labels


class DevicePrefetcher:
  """Background-thread device infeed: places finished host batches ahead.

  The train loop's async dispatch already overlaps ONE host batch with
  device compute; on a slow host feeding a fast chip that single step of
  lookahead is not enough — the loop thread still serializes
  next(dataset) + put_host_batch between dispatches. This wraps the host
  iterator in a daemon thread that keeps up to `depth` batches already
  resident on device (the JAX-native replacement for TPUEstimator's
  per-host infeed threads, /root/reference/models/tpu_model_wrapper.py
  infeed path). It is also the device-side consumer of the pipelined
  host loader (`data/overlap.py`): upstream stages hand it finished
  numpy batches, it pays only the device transfer.

  Iterating yields (features, labels) pairs already placed with
  `put_host_batch` — or, with a custom `place_fn`, whatever that
  returns (the train loop's stacked-group path places K-step groups
  under the loop spec; the bench data probe device_puts to one device).
  Exceptions in the worker re-raise in the consumer; `close()` (also
  called on exhaustion) stops the worker promptly, and with
  `close_source` also closes a closable `dataset` (e.g. an
  `OverlappedLoader`, joining its stage threads) once the worker is
  down. `close()` is MANDATORY for library users — an abandoned
  prefetcher pins `depth` device-resident batches until its finalizer
  runs. The context-manager protocol closes on exit; a
  `weakref.finalize` backstop stops the worker of a
  collected-but-unclosed instance.

  graftscope telemetry: `data/overlap_place_ms` (device-placement time
  per batch, worker-side) and `data/overlap_device_queue_depth`
  (device-resident batches ready) ride the standard registry into
  runs.jsonl with the host-stage `data/overlap_*` metrics.
  """

  _STOP = object()

  def __init__(self, dataset, mesh: Optional[Mesh] = None, batch_spec=None,
               depth: int = 2, max_batches: Optional[int] = None,
               place_fn=None, close_source: bool = False, source=None,
               overlap_place: bool = True):
    import itertools
    import queue
    import threading
    import time as time_lib
    import weakref

    from tensor2robot_tpu.obs import metrics as obs_metrics

    if depth < 1:
      raise ValueError(f"depth must be >= 1, got {depth}")
    if place_fn is None:
      if mesh is None:
        raise ValueError("DevicePrefetcher needs a mesh (default "
                         "place_batch) or an explicit place_fn.")
      place_fn = lambda batch: place_batch(mesh, batch,  # noqa: E731
                                           batch_spec=batch_spec)
    # What close() closes under close_source: by default the dataset
    # itself; pass `source=` when `dataset` is a derived generator and
    # the closable thing is the loader BEHIND it — a generator that is
    # mid-`next` in the worker thread cannot be closed from another
    # thread (ValueError: generator already executing), while a loader
    # close is thread-safe and unsticks the worker.
    self._source = (source if source is not None else dataset) \
        if close_source else None
    if max_batches is not None:
      # Bound the worker to what the consumer will actually take —
      # otherwise it eagerly parses + device-places `depth` extra batches
      # past the end of a bounded loop, pure waste discarded by close().
      dataset = itertools.islice(dataset, max_batches)
    out_queue = queue.Queue(maxsize=depth)
    stop = threading.Event()
    # Worker phase, readable by close(): "source" while blocked in
    # next(dataset), "transfer" during place_fn (an in-flight TPU
    # op — NEVER safe to abandon over the axon tunnel), "queue"/"done"
    # otherwise. A plain one-slot list: writes are atomic under the GIL.
    phase = ["source"]
    self._queue = out_queue
    self._stop = stop
    self._phase = phase
    self._done = False
    sentinel = self._STOP
    place_hist = obs_metrics.histogram("data/overlap_place_ms")
    depth_gauge = obs_metrics.gauge("data/overlap_device_queue_depth")
    perf_counter_ns = time_lib.perf_counter_ns

    # The workers close over locals only — never `self` — so an
    # abandoned-without-close() prefetcher is actually collectable (a
    # live thread would otherwise keep `self` reachable forever and the
    # finalizer below could never fire).
    def _put_final(item):
      while not stop.is_set():
        try:
          out_queue.put(item, timeout=0.1)
          return
        except queue.Full:
          continue

    def _worker():
      # Serial fallback (overlap_place=False): one thread does
      # next(dataset) then place_fn — the pre-ROADMAP-6 shape, kept for
      # A/Bs and for place_fns that must not overlap their source.
      try:
        for batch in dataset:
          if stop.is_set():
            # Checked between next(dataset) and place_fn so a stop
            # requested while the source was producing skips the device
            # transfer and exits without touching the queue.
            return
          phase[0] = "transfer"
          t0 = perf_counter_ns()
          placed = place_fn(batch)
          place_hist.record((perf_counter_ns() - t0) * 1e-6)
          phase[0] = "queue"
          while not stop.is_set():
            try:
              out_queue.put(placed, timeout=0.1)
              break
            except queue.Full:
              continue
          if stop.is_set():
            return
          depth_gauge.set(float(out_queue.qsize()))
          phase[0] = "source"
        _put_final(sentinel)
      except BaseException as e:  # noqa: BLE001 - surfaced to consumer
        _put_final(e)
      finally:
        phase[0] = "done"

    # Overlapped placement (ROADMAP item 6: "unserialize device_put
    # placement"): the single worker used to SERIALIZE next(dataset)
    # with place_fn, so the device transfer of batch N blocked the
    # host-pipeline dequeue of batch N+1. Split into a feeder (host
    # dequeue) and a placer (device_put) over a bounded host queue —
    # batch N+1's source wait now overlaps batch N's transfer. FIFO
    # hand-off on both sides keeps the stream byte-identical to the
    # serial worker (tests/test_overlap.py pins it).
    host_queue = queue.Queue(maxsize=depth) if overlap_place else None
    host_depth_gauge = obs_metrics.gauge("data/overlap_host_queue_depth")

    def _hq_put(item) -> bool:
      while not stop.is_set():
        try:
          host_queue.put(item, timeout=0.1)
          return True
        except queue.Full:
          continue
      return False

    def _feeder():
      try:
        for batch in dataset:
          if stop.is_set():
            return
          if not _hq_put(batch):
            return
          host_depth_gauge.set(float(host_queue.qsize()))
        _hq_put(sentinel)
      except BaseException as e:  # noqa: BLE001 - forwarded to consumer
        _hq_put(e)

    def _placer():
      try:
        while not stop.is_set():
          try:
            item = host_queue.get(timeout=0.1)
          except queue.Empty:
            continue
          if item is sentinel:
            _put_final(sentinel)
            return
          if isinstance(item, BaseException):
            _put_final(item)
            return
          phase[0] = "transfer"
          t0 = perf_counter_ns()
          placed = place_fn(item)
          place_hist.record((perf_counter_ns() - t0) * 1e-6)
          phase[0] = "queue"
          while not stop.is_set():
            try:
              out_queue.put(placed, timeout=0.1)
              break
            except queue.Full:
              continue
          if stop.is_set():
            return
          depth_gauge.set(float(out_queue.qsize()))
          phase[0] = "host"
      except BaseException as e:  # noqa: BLE001 - surfaced to consumer
        _put_final(e)
      finally:
        phase[0] = "done"

    if overlap_place:
      self._feeder = threading.Thread(target=_feeder, daemon=True,
                                      name="device-prefetch-feed")
      self._thread = threading.Thread(target=_placer, daemon=True,
                                      name="device-prefetch")
      self._feeder.start()
    else:
      self._feeder = None
      self._thread = threading.Thread(target=_worker, daemon=True,
                                      name="device-prefetch")
    self._thread.start()
    # Backstop for abandoned instances: stop (but never join, which is
    # illegal from a GC callback) the workers so they cannot spin at
    # 10 Hz holding device batches forever. close() remains the correct
    # path.
    self._finalizer = weakref.finalize(self, stop.set)

  def __iter__(self):
    return self

  def __next__(self):
    if self._done:
      raise StopIteration
    item = self._queue.get()
    if item is self._STOP:
      self.close()
      raise StopIteration
    if isinstance(item, BaseException):
      self.close()
      raise item
    return item

  def __enter__(self):
    return self

  def __exit__(self, exc_type, exc_value, traceback):
    self.close()
    return False

  def close(self, timeout: float = 60.0):
    """Stops the worker and WAITS for it to finish its in-flight batch.

    The join matters on the axon tunnel: a daemon thread killed at
    interpreter shutdown mid device_put is a killed TPU client — the
    documented tunnel-wedging hazard (CLAUDE.md). The workers check the
    stop event at least every 0.1 s, so the joins are normally bounded
    by one in-flight batch. The `timeout` applies ONLY to a thread
    blocked inside next(dataset) on a stalled data source (the FEEDER
    under the default overlapped placement, the single worker in the
    `overlap_place=False` serial mode — the placer never touches the
    source): close() then returns, logging loudly, rather than hang —
    which matters on the preemption save-and-exit path where a timely
    SystemExit beats a clean thread shutdown. While the placer is mid
    device transfer ("transfer" phase), close() keeps waiting
    regardless of `timeout` — abandoning a thread with an in-flight TPU
    op is the wedging hazard itself.
    """
    self._done = True
    self._stop.set()
    import time

    deadline = None
    while True:
      self._thread.join(timeout=1.0)
      if not self._thread.is_alive():
        break
      if self._phase[0] == "transfer":
        deadline = None  # device op in flight: wait it out, full stop
        continue
      if deadline is None:
        deadline = time.monotonic() + timeout
      elif time.monotonic() >= deadline:
        break
    stalled = self._thread if self._thread.is_alive() else None
    if stalled is None and self._feeder is not None:
      # Placer down; the feeder sees the stop event within 0.1 s unless
      # it is blocked in next(dataset) on a stalled source.
      self._feeder.join(timeout=timeout)
      if self._feeder.is_alive():
        stalled = self._feeder
    if stalled is None:
      self._close_source()
      return
    # Stalled inside next(dataset): closing a closable source (e.g. an
    # OverlappedLoader — its get() watches the loader's own stop event)
    # is exactly what unsticks the thread, so try that before giving up
    # on it (only when this prefetcher actually owns a source).
    if self._close_source():
      stalled.join(timeout=5.0)
      if not stalled.is_alive():
        return
    from absl import logging

    logging.error(
        "DevicePrefetcher.close(): %s still alive after %.0fs in "
        "phase %r — blocked in next(dataset) on a stalled data source; "
        "abandoning the daemon thread.", stalled.name, timeout,
        self._phase[0])

  def _close_source(self) -> bool:
    """Closes a `close_source=True` source exactly once (best-effort:
    teardown must not mask the consumer's own error path). Returns
    True when the close succeeded (so close() knows a stalled worker
    may now be unstuck and a short rejoin is worth it)."""
    source, self._source = self._source, None
    if source is None or not hasattr(source, "close"):
      return False
    try:
      source.close()
      return True
    except ValueError:
      # A plain generator currently executing in the worker thread:
      # not closable from here (and closing it would not unstick
      # anything anyway). Expected on the stalled path when no
      # loader-backed `source=` was provided.
      return False
    except Exception:  # noqa: BLE001
      from absl import logging

      logging.exception("DevicePrefetcher: closing the data source "
                        "failed")
      return False


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None,
                         initialization_timeout_secs: float = 300.0,
                         heartbeat_timeout_secs: Optional[float] = None
                         ) -> None:
  """jax.distributed bring-up for multi-host pods (replaces the
  reference's TF_CONFIG cluster plumbing,
  /root/reference/models/abstract_model.py:440-443). No-op when
  single-process or already initialized.

  Failure detection (SURVEY §5): `initialization_timeout_secs` bounds
  how long a worker waits for the coordinator at bring-up — a dead or
  unreachable coordinator surfaces as a clear RuntimeError instead of
  an opaque multi-minute hang. After bring-up, the coordination
  service's own heartbeats detect peers that die mid-training;
  `heartbeat_timeout_secs` tunes how long a silent peer is tolerated
  before the job errors out (None keeps jax's default).
  """
  if num_processes in (None, 1):
    return
  import time

  deadline = time.monotonic() + initialization_timeout_secs
  if process_id not in (None, 0) and coordinator_address:
    # Pre-probe the coordinator over plain TCP within the SAME deadline
    # budget: jax's distributed client handles its init deadline with a
    # FATAL abort (client.h LOG(FATAL)), which no Python except-clause
    # can turn into a diagnosable error. Retrying the probe also
    # tolerates the normal startup race where workers launch before
    # process 0.
    import socket

    host, sep, port_str = coordinator_address.rpartition(":")
    host = host.strip("[]")  # bracketed IPv6 literals
    if not sep or not port_str.isdigit():
      raise ValueError(
          f"coordinator_address {coordinator_address!r} must be "
          "'<host>:<port>' (e.g. '10.0.0.1:8476').")
    port = int(port_str)
    while True:
      try:
        socket.create_connection((host, port), timeout=5.0).close()
        break
      except OSError as exc:
        if time.monotonic() >= deadline:
          raise RuntimeError(
              f"multi-host bring-up failed for process {process_id}/"
              f"{num_processes}: coordinator {coordinator_address!r} "
              "did not become reachable within "
              f"{initialization_timeout_secs:.0f}s "
              f"({type(exc).__name__}: {exc}). Check that process 0 is "
              "alive and the address/port is reachable from this "
              "host.") from exc
        time.sleep(0.5)
  kwargs = {}
  if heartbeat_timeout_secs is not None:
    kwargs["heartbeat_timeout_seconds"] = int(heartbeat_timeout_secs)
  # Hand jax only the RESIDUAL budget so probe + init together respect
  # the caller's bound (jax's own deadline handling is a process abort,
  # so it is the backstop, not the primary detector).
  jax.distributed.initialize(
      coordinator_address=coordinator_address,
      num_processes=num_processes,
      process_id=process_id,
      initialization_timeout=max(1, int(deadline - time.monotonic())),
      **kwargs)
