"""SPMD train/eval step factory.

This module replaces the reference's entire TPU execution layer —
`model_fn` assembly (/root/reference/models/abstract_model.py:662-834),
`create_train_op`, `TPUT2RModelWrapper` and `CrossShardOptimizer`
(/root/reference/models/tpu_model_wrapper.py:127-322) — with one jitted
function over a device mesh:

* the global batch is sharded over the `data` axis; computing the mean
  loss over it makes XLA insert the gradient all-reduce over ICI that
  CrossShardOptimizer provided by hand;
* parameters/optimizer state are replicated by default, or sharded over
  the `fsdp` axis via partition rules (ZeRO — beyond the reference);
* per-leaf `TensorSpec.sharding` annotations give tensor parallelism on
  the `model` axis;
* bfloat16 compute with float32 params, EMA shadow params, mutable
  batch-stats threading, and per-step PRNG folding are all part of the
  step.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, Mapping, Optional, Sequence, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from tensor2robot_tpu import modes as modes_lib
from tensor2robot_tpu import specs as specs_lib

__all__ = ["TrainState", "create_train_state", "make_train_step",
           "make_train_loop", "loop_batch_spec", "make_eval_step",
           "make_eval_loop", "make_predict_fn", "fsdp_rules",
           "state_shardings"]

PartitionRules = Sequence[Tuple[str, PartitionSpec]]


class TrainState(flax.struct.PyTreeNode):
  """The complete training state — one pytree, checkpointable by orbax."""

  step: jnp.ndarray
  params: Any
  opt_state: Any
  mutable_state: Any  # flax mutable collections (batch_stats, ...)
  ema_params: Any  # None when EMA disabled
  rng: jax.Array

  def eval_params(self, use_ema: bool = True):
    """Params for eval/export: EMA shadow when present (the reference's
    swapping-saver semantics, /root/reference/models/optimizers.py:132-159).
    """
    if use_ema and self.ema_params is not None:
      return self.ema_params
    return self.params


def _split_variables(variables: Mapping) -> Tuple[Any, Dict]:
  params = variables["params"]
  mutable = {k: v for k, v in variables.items() if k != "params"}
  return params, mutable


def _optimizer_for(model):
  """The optimizer the step actually uses: `build_optimizer` (framework
  wrappers, e.g. gradient accumulation) when the model provides it —
  subclasses override `create_optimizer`, so calling that directly here
  would silently drop the wrappers."""
  builder = getattr(model, "build_optimizer", None)
  return builder() if builder is not None else model.create_optimizer()


def fsdp_rules(axis: str = "fsdp") -> PartitionRules:
  """Default FSDP rules: shard the largest dim of every >=2D param over
  the fsdp axis (applied only where divisible)."""
  return ((r".*", ("__largest__", axis)),)


def _leaf_partition(path: str, shape: Tuple[int, ...],
                    rules: Optional[PartitionRules],
                    mesh: Mesh) -> PartitionSpec:
  if rules is None or len(shape) < 1:
    return PartitionSpec()
  for pattern, spec in rules:
    if re.search(pattern, path):
      if spec and spec[0] == "__largest__":
        axis_name = spec[1]
        axis_size = mesh.shape[axis_name]
        if axis_size <= 1 or len(shape) < 2:
          return PartitionSpec()
        largest = max(range(len(shape)), key=lambda i: shape[i])
        if shape[largest] % axis_size:
          return PartitionSpec()
        out = [None] * len(shape)
        out[largest] = axis_name
        return PartitionSpec(*out)
      if len(spec) != len(shape):
        return PartitionSpec()
      return PartitionSpec(*spec)
  return PartitionSpec()


def _path_str(path) -> str:
  parts = []
  for entry in path:
    if hasattr(entry, "key"):
      parts.append(str(entry.key))
    elif hasattr(entry, "name"):
      parts.append(str(entry.name))
    elif hasattr(entry, "idx"):
      parts.append(str(entry.idx))
  return "/".join(parts)


def state_shardings(abstract_state: Any, mesh: Mesh,
                    rules: Optional[PartitionRules] = None) -> Any:
  """NamedSharding tree for a TrainState: params (and the param-shaped
  optimizer moments, whose tree paths embed the same param names) follow
  the partition rules; everything else is replicated."""

  def _shard(path, leaf):
    path = _path_str(path)
    shape = getattr(leaf, "shape", ())
    return NamedSharding(mesh, _leaf_partition(path, tuple(shape), rules,
                                               mesh))

  return jax.tree_util.tree_map_with_path(_shard, abstract_state)


def create_train_state(model,
                       rng: jax.Array,
                       sample_features,
                       mesh: Optional[Mesh] = None,
                       rules: Optional[PartitionRules] = None,
                       mode: str = modes_lib.TRAIN) -> Tuple[TrainState, Any]:
  """Initializes a (sharded) TrainState; returns (state, shardings).

  With a mesh, init runs under jit with out_shardings so large params are
  *born sharded* — never materialized replicated on one device.
  """
  optimizer = _optimizer_for(model)

  def _init(rng, features):
    init_rng, state_rng = jax.random.split(rng)
    variables = model.init_variables(init_rng, features, mode=mode)
    params, mutable = _split_variables(variables)
    opt_state = optimizer.init(params)
    # Fresh buffers for the EMA shadow: aliasing params would make the
    # donated train-step receive the same buffer twice.
    ema = (jax.tree_util.tree_map(jnp.copy, params)
           if model.use_ema else None)
    return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                      opt_state=opt_state, mutable_state=mutable,
                      ema_params=ema, rng=state_rng)

  if mesh is None:
    return _init(rng, sample_features), None
  abstract = jax.eval_shape(_init, rng, sample_features)
  shardings = state_shardings(abstract, mesh, rules)
  init_fn = jax.jit(_init, out_shardings=shardings)
  with jax.transfer_guard_device_to_host("allow"):
    state = init_fn(rng, sample_features)
  return state, shardings


def _batch_shardings(mesh: Mesh, batch, batch_axis: str = "data"):
  def _one(x):
    return NamedSharding(mesh, PartitionSpec(batch_axis))

  return jax.tree_util.tree_map(_one, batch)


def _build_step_fn(model) -> Callable:
  """The un-jitted train-step body shared by `make_train_step` (one step
  per dispatch) and `make_train_loop` (a `lax.scan` of it)."""
  optimizer = _optimizer_for(model)
  accum_steps = int(getattr(model, "gradient_accumulation_steps", 1) or 1)
  ema_decay = model.ema_decay
  # Multi-task gradient surgery (QT-Opt PCGrad,
  # /root/reference/research/qtopt/pcgrad.py): when the model exposes
  # model_task_losses_fn and enables use_pcgrad, per-task gradients are
  # computed via jacrev and combined with conflict projection.
  use_pcgrad = bool(getattr(model, "use_pcgrad", False)) and (
      getattr(model, "model_task_losses_fn", None) is not None)

  def step_fn(state: TrainState, features, labels):
    step_rng = jax.random.fold_in(state.rng, state.step)

    def _forward_impl(params, features):
      variables = {"params": params, **state.mutable_state}
      compute_features = model.cast_features_for_compute(features)
      outputs, new_mutable = model.inference_network_fn(
          variables, compute_features, modes_lib.TRAIN, rng=step_rng,
          train=True)
      outputs = jax.tree_util.tree_map(
          lambda x: x.astype(jnp.float32)
          if hasattr(x, "dtype") and x.dtype == jnp.bfloat16 else x, outputs)
      return outputs, new_mutable

    if getattr(model, "remat", False):
      # Recompute the forward in the backward pass instead of storing
      # activations (jax.checkpoint): HBM for FLOPs, the standard knob
      # for fitting reference-scale batches on one chip.
      _forward_impl = jax.checkpoint(_forward_impl)

    def _forward(params):
      return _forward_impl(params, features)

    if use_pcgrad:
      from tensor2robot_tpu.ops import pcgrad as pcgrad_lib

      def losses_vec(params):
        outputs, new_mutable = _forward(params)
        task_losses = model.model_task_losses_fn(
            features, labels, outputs, modes_lib.TRAIN)
        stacked = jnp.stack([task_losses[k] for k in sorted(task_losses)])
        return stacked, (task_losses, new_mutable)

      task_grads_tree, (task_losses, new_mutable) = jax.jacrev(
          losses_vec, has_aux=True)(state.params)
      n_tasks = len(task_losses)
      task_grads = [
          jax.tree_util.tree_map(lambda g, i=i: g[i], task_grads_tree)
          for i in range(n_tasks)]
      grads = pcgrad_lib.pcgrad_combine(
          task_grads,
          use_flat_projection=getattr(model, "pcgrad_flat_projection",
                                      False),
          allowlist=getattr(model, "pcgrad_allowlist", None),
          denylist=getattr(model, "pcgrad_denylist", None))
      loss = sum(task_losses.values())
      scalars = {f"task_loss/{k}": v for k, v in task_losses.items()}
    else:
      def loss_fn(params):
        outputs, new_mutable = _forward(params)
        loss, scalars = model.model_train_fn(
            features, labels, outputs, modes_lib.TRAIN)
        return loss, (scalars, new_mutable)

      (loss, (scalars, new_mutable)), grads = jax.value_and_grad(
          loss_fn, has_aux=True)(state.params)
    updates, new_opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
    new_params = optax.apply_updates(state.params, updates)
    new_ema = state.ema_params
    if new_ema is not None:
      if accum_steps > 1:
        # Under gradient accumulation the EMA must move once per APPLIED
        # update, not per micro-step — otherwise the effective decay is
        # decay^k and eval/export EMA params diverge from an equivalent
        # large-batch run. MultiSteps resets mini_step to 0 on apply.
        applied = new_opt_state.mini_step == 0
        new_ema = jax.tree_util.tree_map(
            lambda e, p: jnp.where(applied,
                                   e * ema_decay + (1.0 - ema_decay) * p,
                                   e),
            new_ema, new_params)
      else:
        new_ema = jax.tree_util.tree_map(
            lambda e, p: e * ema_decay + (1.0 - ema_decay) * p,
            new_ema, new_params)
    new_state = state.replace(
        step=state.step + 1,
        params=new_params,
        opt_state=new_opt_state,
        mutable_state=new_mutable if new_mutable else state.mutable_state,
        ema_params=new_ema)
    metrics = {"loss": loss,
               "global_gradient_norm": optax.global_norm(grads),
               **scalars}
    return new_state, metrics

  return step_fn


def make_train_step(model,
                    mesh: Optional[Mesh] = None,
                    shardings: Any = None,
                    batch_axis: str = "data",
                    batch_spec: Optional[PartitionSpec] = None,
                    donate: bool = True) -> Callable:
  """Builds the jitted SPMD train step: (state, features, labels) ->
  (state, scalars).

  `batch_spec` overrides the default batch-dim-only sharding for
  features/labels — e.g. PartitionSpec('data', 'sp') commits sequence
  batches [B, T, ...] sharded over BOTH the data and sequence-parallel
  axes at infeed (models expose it via `batch_partition_spec`)."""
  step_fn = _build_step_fn(model)
  if mesh is None:
    return jax.jit(step_fn, donate_argnums=(0,) if donate else ())
  batch_ns = NamedSharding(mesh, batch_spec or PartitionSpec(batch_axis))
  replicated_ns = NamedSharding(mesh, PartitionSpec())
  return jax.jit(
      step_fn,
      in_shardings=(shardings, batch_ns, batch_ns),
      # replicated_ns is a pytree prefix covering the whole metrics dict
      out_shardings=(shardings, replicated_ns),
      donate_argnums=(0,) if donate else ())


def loop_batch_spec(batch_spec: Optional[PartitionSpec] = None,
                    batch_axis: str = "data") -> PartitionSpec:
  """The PartitionSpec for a staged [K, B, ...] loop batch: the per-step
  batch sharding with the scan axis unsharded. The ONE derivation shared
  by `make_train_loop`'s in_shardings and the trainer's `place_batch`
  call, so placement can never silently desync from the jit's committed
  shardings."""
  return PartitionSpec(None, *(batch_spec if batch_spec is not None
                               else PartitionSpec(batch_axis)))


def make_train_loop(model,
                    num_steps: int,
                    mesh: Optional[Mesh] = None,
                    shardings: Any = None,
                    batch_axis: str = "data",
                    batch_spec: Optional[PartitionSpec] = None,
                    donate: bool = True) -> Callable:
  """Builds a jitted K-step train LOOP: (state, features, labels) ->
  (state, stacked scalars), with features/labels carrying a leading
  `num_steps` axis of pre-staged batches and the step body running under
  `lax.scan` entirely on device.

  This is the TPU-idiomatic host-training-loop: the reference amortizes
  host round-trips with TPUEstimator `iterations_per_loop`
  (/root/reference/models/abstract_model.py:662-834 runs under
  TPUEstimatorSpec; the estimator loops on-device between session
  calls). Over a remote-dispatch transport every per-step host round
  trip costs wall-clock that the chip spends idle; scanning K real
  train steps per dispatch divides that overhead by K. Semantics are
  pinned identical to K sequential `make_train_step` calls (metrics are
  returned per-step, stacked on a leading axis)."""
  if num_steps < 1:
    raise ValueError(f"num_steps must be >= 1, got {num_steps}")
  step_fn = _build_step_fn(model)

  def loop_fn(state: TrainState, features, labels):
    def body(carry, batch):
      f, l = batch
      new_state, metrics = step_fn(carry, f, l)
      return new_state, metrics

    state, metrics = jax.lax.scan(body, state, (features, labels),
                                  length=num_steps)
    return state, metrics

  if mesh is None:
    return jax.jit(loop_fn, donate_argnums=(0,) if donate else ())
  loop_ns = NamedSharding(mesh, loop_batch_spec(batch_spec, batch_axis))
  replicated_ns = NamedSharding(mesh, PartitionSpec())
  return jax.jit(
      loop_fn,
      in_shardings=(shardings, loop_ns, loop_ns),
      out_shardings=(shardings, replicated_ns),
      donate_argnums=(0,) if donate else ())


def _build_eval_fn(model, use_ema: bool) -> Callable:
  """The un-jitted eval body shared by `make_eval_step` and
  `make_eval_loop`."""

  def eval_fn(state: TrainState, features, labels):
    params = state.eval_params(use_ema=use_ema)
    variables = {"params": params, **state.mutable_state}
    compute_features = model.cast_features_for_compute(features)
    outputs, _ = model.inference_network_fn(
        variables, compute_features, modes_lib.EVAL, train=False)
    outputs = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.float32)
        if hasattr(x, "dtype") and x.dtype == jnp.bfloat16 else x, outputs)
    return model.model_eval_fn(features, labels, outputs)

  return eval_fn


def make_eval_step(model,
                   mesh: Optional[Mesh] = None,
                   shardings: Any = None,
                   batch_axis: str = "data",
                   batch_spec: Optional[PartitionSpec] = None,
                   use_ema: bool = True) -> Callable:
  """Jitted eval step: (state, features, labels) -> metric scalars."""
  eval_fn = _build_eval_fn(model, use_ema)
  if mesh is None:
    return jax.jit(eval_fn)
  batch_ns = NamedSharding(mesh, batch_spec or PartitionSpec(batch_axis))
  return jax.jit(eval_fn, in_shardings=(shardings, batch_ns, batch_ns))


def make_eval_loop(model,
                   num_steps: int,
                   mesh: Optional[Mesh] = None,
                   shardings: Any = None,
                   batch_axis: str = "data",
                   batch_spec: Optional[PartitionSpec] = None,
                   use_ema: bool = True) -> Callable:
  """Jitted K-batch eval LOOP: (state, features, labels) -> metric
  scalars SUMMED over the K batches (divide by K for the mean), with
  features/labels carrying a leading `num_steps` axis.

  The eval twin of `make_train_loop`: in iterations_per_loop training
  the ~8 ms per-dispatch transport floor (PERFORMANCE.md round 5)
  would otherwise make a 100-batch eval cost more wall-clock than the
  500 train steps between evals. Summing on device keeps the host
  transfer to one scalar dict per K batches."""
  if num_steps < 1:
    raise ValueError(f"num_steps must be >= 1, got {num_steps}")
  eval_fn = _build_eval_fn(model, use_ema)

  def loop_fn(state: TrainState, features, labels):
    def body(carry, batch):
      f, l = batch
      return carry, eval_fn(state, f, l)

    _, metrics = jax.lax.scan(body, None, (features, labels),
                              length=num_steps)
    return jax.tree_util.tree_map(lambda x: jnp.sum(x, axis=0), metrics)

  if mesh is None:
    return jax.jit(loop_fn)
  loop_ns = NamedSharding(mesh, loop_batch_spec(batch_spec, batch_axis))
  replicated_ns = NamedSharding(mesh, PartitionSpec())
  return jax.jit(loop_fn,
                 in_shardings=(shardings, loop_ns, loop_ns),
                 out_shardings=replicated_ns)


def make_predict_fn(model, use_ema: bool = True) -> Callable:
  """Jitted predict: (state, features) -> export outputs (the PREDICT
  branch + create_export_outputs_fn,
  /root/reference/models/abstract_model.py:714-736)."""

  def predict_fn(state: TrainState, features):
    params = state.eval_params(use_ema=use_ema)
    variables = {"params": params, **state.mutable_state}
    compute_features = model.cast_features_for_compute(features)
    outputs, _ = model.inference_network_fn(
        variables, compute_features, modes_lib.PREDICT, train=False)
    outputs = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.float32)
        if hasattr(x, "dtype") and x.dtype == jnp.bfloat16 else x, outputs)
    return model.create_export_outputs_fn(features, outputs)

  return jax.jit(predict_fn)
