"""Pipeline parallelism: GPipe and interleaved-1F1B schedules over a mesh axis.

Beyond the reference (SURVEY.md §2.5: PP absent there). Stage parameters
carry a leading stage dim sharded over the `pp` axis; microbatches flow
through a `lax.scan` of compute+`ppermute` ticks, so activations hop
stage-to-stage over ICI while every stage works on a different
microbatch. Differentiable: the scan/ppermute pair transposes cleanly,
so the same function trains (the backward is the reverse schedule over
the same ring).

Two SCHEDULES share one tick skeleton (`_tick_plan`):

* GPipe fill/drain (`num_virtual_stages == 1`): one stage per rank,
  microbatches stream once around the ring. Bubble fraction
  (S-1)/(M+S-1) — grows with stage count.
* Interleaved 1F1B (`num_virtual_stages == v > 1`): each pp rank holds
  `v` virtual stage CHUNKS (stacked [S*v, ...] params sharded over
  `pp`), and microbatches stream around the ring `v` times in groups of
  S, so while early microbatches are deep in their later chunks the
  ring keeps admitting later microbatches — the interleaved schedule of
  Megatron-LM / "Scaling Deep Learning Training with MPMD Pipeline
  Parallelism" (arXiv:2412.14374). The fill is paid ONCE (S-1 ticks)
  instead of once per loop, cutting bubble fraction to
  (S-1)/(v*ceil(M/S)*S + S - 1) -> (S-1)/(v*M) for S | M, and only S
  microbatches are in flight on the ring at any tick (the O(S) live
  working set; the autodiff transpose replays the same schedule in
  reverse, so its in-flight set mirrors the forward's). `lax.scan`
  still stashes one per-tick residual set for the backward — remat the
  stage fn when that dominates.

`schedule_accounting` prices any (S, M, v) statically — total ticks,
per-rank busy/idle ticks, bubble fraction — and every pipelined apply
registers the result as `pp/*` gauges so the schedule win is observable
in runs.jsonl (bench.py --pp measures the wall-clock side as
`onefonb_vs_gpipe`; PERFORMANCE.md "Reading a pipeline bench").

Two PARAM LAYOUTS feed the same schedules:

* `pipelined_apply` — homogeneous: one shape-preserving stage function,
  stage params stacked with a leading [S*v] dim (transformer/MLP
  blocks).
* `pipelined_apply_heterogeneous` — per-stage DIFFERENT functions,
  param pytrees, and activation shapes (e.g. a conv tower whose spatial
  dims and channel counts change every stage). Each stage's params are
  raveled to a flat vector, zero-padded to the widest stage, and stacked
  into one [S*v, P_max] leaf sharded over `pp`; activations travel as
  zero-padded flat [mb, A_max] buffers so every ppermute hop moves a
  same-shape array. Inside the SPMD program a `lax.switch` on the
  global layer index dispatches each rank to the right stage's
  computation — XLA compiles all S*v branches everywhere (static
  shapes, MXU-friendly: the branch unravels to the TRUE shapes before
  any matmul/conv), each rank executes its own `v` per step.

Interleaved placement: ring traversal must compose layers in depth
order, so loop j's visit to rank r executes layer j*S + r — rank r
holds layers {r, S+r, ..., (v-1)S+r}, NOT a contiguous depth block.
Stacks arrive in natural depth order (`params_layout="layer"`) and are
permuted to the sharded interleaved layout on the fly, or pre-permuted
once via `interleave_stage_stack` (`params_layout="interleaved"`) to
keep the per-step permute gather off the hot path.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from tensor2robot_tpu.obs import metrics as metrics_lib
from tensor2robot_tpu.parallel import mesh as mesh_lib

__all__ = ["pipelined_apply", "stack_stage_params",
           "shard_pipeline_tree", "make_pipelined_train_step",
           "ravel_stage_stack", "pipelined_apply_heterogeneous",
           "sequential_apply_heterogeneous", "schedule_accounting",
           "interleave_order", "interleave_stage_stack"]


# ---------------------------------------------------------------------------
# Static schedule accounting (pure Python — backend-free by construction;
# the poisoned-platform trap in tests/test_moe_pipeline.py runs it with no
# usable jax backend).
# ---------------------------------------------------------------------------


def schedule_accounting(num_stages: int, num_micro: int,
                        num_virtual_stages: int = 1) -> Dict[str, Any]:
  """Prices a pipeline schedule from its static structure.

  Tick model: every tick, every rank runs exactly one stage-chunk
  compute and one ppermute hop (the SPMD lockstep `lax.scan` below), so
  wall time is total_ticks * per-tick cost and the bubble fraction is
  the fraction of compute slots that hold no real microbatch work.

  Returns a JSON-safe dict: `schedule`, `total_ticks`,
  `busy_ticks_per_rank`, `idle_ticks_per_rank`, `bubble_fraction`, and
  `padded_microbatches` (interleaved schedules admit microbatches in
  groups of S; a ragged last group pays idle slots, counted here).
  """
  s, m, v = int(num_stages), int(num_micro), int(num_virtual_stages)
  if s < 1 or m < 1 or v < 1:
    raise ValueError(
        f"schedule_accounting needs num_stages >= 1, num_micro >= 1, "
        f"num_virtual_stages >= 1; got ({s}, {m}, {v})")
  if v == 1:
    total = m + s - 1
    padded = 0
  else:
    groups = -(-m // s)
    total = groups * s * v + s - 1
    padded = groups * s - m
  busy = m * v
  return {
      "schedule": "gpipe" if v == 1 else "interleaved-1f1b",
      "num_stages": s,
      "num_micro": m,
      "num_virtual_stages": v,
      "total_ticks": total,
      "busy_ticks_per_rank": busy,
      "idle_ticks_per_rank": total - busy,
      "bubble_fraction": (total - busy) / total,
      "padded_microbatches": padded,
  }


def interleave_order(num_stages: int, num_virtual_stages: int) -> np.ndarray:
  """Permutation mapping sharded-stack position -> depth-order layer.

  Position r*v + j (rank r's j-th local chunk under contiguous `pp`
  sharding of the leading [S*v] dim) holds layer j*S + r, so loop j's
  ring traversal executes layers jS..jS+S-1 in depth order. Identity
  for v == 1.
  """
  s, v = int(num_stages), int(num_virtual_stages)
  return np.array([(k % v) * s + k // v for k in range(s * v)])


def interleave_stage_stack(stacked: Any, num_stages: int,
                           num_virtual_stages: int) -> Any:
  """Permutes depth-ordered stacked stage params (leading [S*v] dim on
  every leaf) into the interleaved sharded layout (see
  `interleave_order`). Do this ONCE before `shard_pipeline_tree` and
  pass `params_layout="interleaved"` to keep the permute gather out of
  the per-step program."""
  perm = interleave_order(num_stages, num_virtual_stages)
  return jax.tree_util.tree_map(lambda leaf: leaf[perm], stacked)


def _registry():
  return metrics_lib.get_registry()


def _validate_and_account(num_stages: int, num_micro: int,
                          num_virtual_stages: int,
                          batch_axis: Optional[str]) -> Dict[str, Any]:
  """Shared host-side validation + `pp/*` telemetry for both apply paths
  (runs at trace time — Python ints only, never tracers)."""
  if num_micro < 1:
    raise ValueError(f"num_micro must be >= 1, got {num_micro}")
  if num_virtual_stages < 1:
    raise ValueError(
        f"num_virtual_stages must be >= 1, got {num_virtual_stages}")
  if batch_axis is not None and not isinstance(batch_axis, str):
    raise TypeError(f"batch_axis must be a mesh-axis name or None, "
                    f"got {batch_axis!r}")
  accounting = schedule_accounting(num_stages, num_micro,
                                   num_virtual_stages)
  reg = _registry()
  if num_micro < num_stages:
    # Silently degenerate before this warning existed: M < S leaves the
    # ring >50% idle under GPipe ((S-1)/(M+S-1) > (S-1)/(2S-2) >= 1/2).
    reg.counter("pp/degenerate_microbatching").inc()
    from absl import logging

    logging.warning(
        "pipeline schedule is bubble-dominated: num_micro=%d < "
        "num_stages=%d gives bubble fraction %.2f — raise the "
        "microbatch count (or num_virtual_stages) to fill the ring",
        num_micro, num_stages, accounting["bubble_fraction"])
  reg.gauge("pp/bubble_fraction").set(accounting["bubble_fraction"])
  reg.gauge("pp/total_ticks").set(float(accounting["total_ticks"]))
  reg.gauge("pp/num_virtual_stages").set(float(num_virtual_stages))
  return accounting


def _tick_plan(num_stages: int, num_micro: int, num_virtual_stages: int):
  """The static tick schedule both apply paths scan over.

  Returns (total_ticks, out_ticks, plan) where `plan(t, idx)` maps the
  scan tick `t` and pp rank `idx` (both traced int32) to
  `(valid, m, chunk)`:

  * `valid` — this (rank, tick) slot holds a real microbatch (idle
    fill/drain/padding slots compute on zeros and are masked off the
    wire so garbage can never reach a valid item, forward or backward);
  * `m` — the microbatch index (clipped into range when invalid);
  * `chunk` — which of the rank's `v` local chunks runs this tick.

  Schedule: work item u = t - idx enumerates rank 0's injection order.
  GPipe (v == 1): u IS the microbatch — one pass around the ring.
  Interleaved (v > 1): microbatches are admitted in groups of S and
  each group streams around the ring v times back-to-back
  (u = g*S*v + j*S + i -> microbatch g*S + i, chunk j). Group stride
  S*v matches the ring latency S exactly, so loop j+1's item arrives
  back at rank 0 on the tick it is scheduled — no buffering, and the
  fill cost (S-1 ticks) is paid once for the whole run.

  `out_ticks[m]` is the tick whose rank-(S-1) output is microbatch m's
  final-layer result.
  """
  s, m_count, v = num_stages, num_micro, num_virtual_stages
  if v == 1:
    span = m_count
    ms = np.arange(m_count)
    out_ticks = ms + s - 1
  else:
    groups = -(-m_count // s)
    span = groups * s * v
    ms = np.arange(m_count)
    out_ticks = (ms // s) * (s * v) + (v - 1) * s + (ms % s) + s - 1
  total_ticks = span + s - 1

  def plan(t, idx):
    u = t - idx
    valid = (u >= 0) & (u < span)
    u = jnp.clip(u, 0, span - 1)
    if v == 1:
      micro_index = u
      chunk = jnp.zeros_like(u)
    else:
      group = u // (s * v)
      within = u % (s * v)
      chunk = within // s
      micro_index = group * s + within % s
      valid = valid & (micro_index < m_count)
    return valid, jnp.clip(micro_index, 0, m_count - 1), chunk

  return total_ticks, out_ticks, plan


def _io_specs(mesh: Mesh, axis_name: str, batch_axis: Optional[str]):
  """(params spec, activation spec) for the shard_map boundary."""
  params_spec = PartitionSpec(axis_name)
  if batch_axis is not None and mesh.shape.get(batch_axis, 1) > 1:
    replicated_spec = PartitionSpec(None, batch_axis)
  else:
    replicated_spec = PartitionSpec()
  return params_spec, replicated_spec


def stack_stage_params(params_list):
  """Stacks per-stage param pytrees into leading-[S] arrays (the layout
  `pp` sharding expects), in natural depth order. For interleaved
  schedules follow with `interleave_stage_stack` (or pass
  `params_layout="layer"` and let the apply permute per step)."""
  return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params_list)


def pipelined_apply(stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                    stage_params: Any,
                    microbatches: jnp.ndarray,
                    mesh: Mesh,
                    axis_name: str = "pp",
                    batch_axis: Optional[str] = None,
                    num_virtual_stages: int = 1,
                    params_layout: str = "layer") -> jnp.ndarray:
  """Runs microbatches through a pipeline of homogeneous stages.

  Args:
    stage_fn: (one stage chunk's params, activation [mb, ...]) ->
      activation of the same shape.
    stage_params: pytree with leading [num_stages * num_virtual_stages]
      dim on every leaf; sharded over `axis_name`.
    microbatches: [num_microbatches, mb, ...] global input (replicated
      over the pp axis; when `batch_axis` is given, the mb dim stays
      sharded over it so PP composes with data parallelism instead of
      all-gathering the batch).
    mesh: mesh containing `axis_name`; its size S is the pp rank count.
    batch_axis: optional mesh axis the microbatch (second) dim is sharded
      over.
    num_virtual_stages: chunks per rank (v). 1 = GPipe fill/drain;
      >1 = interleaved 1F1B (see module docstring).
    params_layout: "layer" (leading dim in depth order; permuted to the
      interleaved layout inside the program) or "interleaved" (already
      permuted via `interleave_stage_stack` — no per-step gather).

  Returns:
    [num_microbatches, mb, ...] outputs (replicated over the pp axis,
    mb dim sharded over `batch_axis` when given).
  """
  num_stages = mesh.shape[axis_name]
  num_micro = microbatches.shape[0]
  v = int(num_virtual_stages)
  if params_layout not in ("layer", "interleaved"):
    raise ValueError(f"params_layout must be 'layer' or 'interleaved', "
                     f"got {params_layout!r}")
  _validate_and_account(num_stages, num_micro, v, batch_axis)
  leading = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
  if leading != num_stages * v:
    raise ValueError(
        f"stage_params leading dim {leading} != num_stages {num_stages} "
        f"* num_virtual_stages {v}")
  if v > 1 and params_layout == "layer":
    stage_params = interleave_stage_stack(stage_params, num_stages, v)
  total_ticks, out_ticks, plan = _tick_plan(num_stages, num_micro, v)

  params_spec, replicated_spec = _io_specs(mesh, axis_name, batch_axis)
  perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

  def local_fn(local_params, micro):
    # local_params leaves: [v, ...] (this rank's chunks, loop-major).
    idx = jax.lax.axis_index(axis_name)
    my_chunk0 = jax.tree_util.tree_map(lambda p: p[0], local_params)

    def tick(carry, t):
      valid, m, chunk = plan(t, idx)
      # Injection only on VALID chunk-0 slots at rank 0: drain ticks no
      # longer re-run a clipped re-read of the last microbatch through
      # stage 0 — the idle slot computes on the (masked-to-zero) wire
      # value instead, so no stale microbatch data re-enters the ring
      # and the idle compute is a foldable constant-operand op.
      inject = (idx == 0) & valid & (chunk == 0)
      x = jnp.where(inject, micro[m], carry)
      # v == 1 uses the hoisted static slice; v > 1 pays one dynamic
      # chunk gather per tick (cheaper than a lax.switch over chunks,
      # whose VJP materializes cotangents for every branch).
      my_params = (my_chunk0 if v == 1 else jax.tree_util.tree_map(
          lambda p: p[chunk], local_params))
      y = stage_fn(my_params, x)
      y = jnp.where(valid, y, jnp.zeros_like(y))
      shifted = jax.lax.ppermute(y, axis_name, perm)
      return shifted, y

    zeros = jnp.zeros_like(micro[0])
    _, ys = jax.lax.scan(tick, zeros, jnp.arange(total_ticks))
    # The last rank's outputs at the (static) final-chunk ticks are the
    # results for microbatches [0, M). Broadcast to every pp rank.
    outs = ys[jnp.asarray(out_ticks)]
    outs = jnp.where(idx == num_stages - 1, outs, jnp.zeros_like(outs))
    return jax.lax.psum(outs, axis_name)

  return mesh_lib.shard_map(
      local_fn, mesh=mesh,
      in_specs=(params_spec, replicated_spec),
      out_specs=replicated_spec)(stage_params, microbatches)


def make_pipelined_train_step(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    loss_fn: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray],
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    axis_name: str = "pp",
    batch_axis: Optional[str] = None,
    num_virtual_stages: int = 1,
    params_layout: str = "layer",
    donate: bool = True,
    audit_name: Optional[str] = None,
    cache=None) -> Callable:
  """Builds a jitted *training* step over the pipelined schedule.

  The forward runs microbatches through `pipelined_apply`; the backward
  is the autodiff transpose of the same scan+ppermute schedule (reverse
  activation hops over the ICI ring), and microbatch gradients
  accumulate into one optimizer update, i.e. microbatch gradient
  accumulation is the sum inside the mean loss.

  Args:
    stage_fn: (stage chunk params, activation [mb, ...]) -> same-shape
      activation (homogeneous stages; see module docstring for scope).
    loss_fn: (outputs [M, mb, ...], targets [M, mb, ...]) -> scalar mean
      loss over all microbatches.
    optimizer: optax transformation over the stacked stage params.
    mesh: mesh containing `axis_name`.
    batch_axis / num_virtual_stages / params_layout: schedule and
      PP x DP composition knobs, as in `pipelined_apply`.
    donate: donate (params, opt_state) buffers to the step — the
      pp-sharded state updates in place instead of doubling its HBM
      footprint.
    audit_name: when set, the step is wrapped in `obs.xray`'s
      `XrayedFunction` under this name: first dispatch AOT-compiles via
      `analyze_jit`, so the per-stage donation layout (args_info
      donated/undonated bytes), compile cost, and flops land in the
      telemetry registry and runs.jsonl next to the `pp/*` schedule
      gauges. graftlint's `pp-schedule-unaudited` rule flags call sites
      that skip this.
    cache: optional `obs.excache` cache for the audited executable
      (donating-mesh steps skip the unsafe tiers automatically).

  Returns:
    jitted (stage_params, opt_state, microbatches, targets) ->
    (stage_params, opt_state, loss). Place stage params / optimizer
    state with `shard_pipeline_tree` first; jit follows the committed
    input shardings, so params and moments stay pp-sharded throughout.
  """

  def step(stage_params, opt_state, microbatches, targets):
    def total_loss(p):
      outputs = pipelined_apply(stage_fn, p, microbatches, mesh,
                                axis_name=axis_name,
                                batch_axis=batch_axis,
                                num_virtual_stages=num_virtual_stages,
                                params_layout=params_layout)
      return loss_fn(outputs, targets)

    loss, grads = jax.value_and_grad(total_loss)(stage_params)
    updates, new_opt_state = optimizer.update(grads, opt_state,
                                              stage_params)
    new_params = optax.apply_updates(stage_params, updates)
    return new_params, new_opt_state, loss

  jitted = jax.jit(step, donate_argnums=(0, 1) if donate else ())
  if audit_name is None:
    return jitted
  from tensor2robot_tpu.obs import xray as xray_lib

  return xray_lib.XrayedFunction(audit_name, jitted, cache=cache)


def ravel_stage_stack(stage_params_list: Sequence[Any]):
  """Packs heterogeneous per-stage param pytrees into one [S, P_max]
  leaf, in natural depth order.

  Each stage's pytree is raveled (jax.flatten_util) to a flat vector,
  zero-padded to the widest stage, and the vectors stacked. Returns
  (stacked [S, P_max] array, unravel_fns, sizes): `unravel_fns[s]`
  rebuilds stage s's pytree from `stacked[s, :sizes[s]]`.
  """
  flats, unravels = [], []
  for params in stage_params_list:
    flat, unravel = ravel_pytree(params)
    flats.append(flat)
    unravels.append(unravel)
  sizes = [int(f.size) for f in flats]
  p_max = max(sizes)
  stacked = jnp.stack(
      [jnp.pad(f, (0, p_max - f.size)) for f in flats])
  return stacked, unravels, sizes


def pipelined_apply_heterogeneous(
    stage_fns: Sequence[Callable[[Any, jnp.ndarray], jnp.ndarray]],
    unravel_fns: Sequence[Callable[[jnp.ndarray], Any]],
    param_sizes: Sequence[int],
    stacked_params: jnp.ndarray,
    microbatches: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = "pp",
    batch_axis: Optional[str] = None,
    num_virtual_stages: int = 1,
    params_layout: str = "layer") -> jnp.ndarray:
  """Pipelines stages with DIFFERENT functions/params/activation shapes.

  Args:
    stage_fns: per-stage (stage params pytree, flat activation
      [mb, A_max]) -> flat activation [mb, out_size_s] with
      out_size_s <= A_max, in depth order; len == S * v. Each stage
      slices/reshapes what it consumes from the padded buffer and
      returns its (unpadded) flat output; zero-padding back to A_max
      happens here.
    unravel_fns / param_sizes: from `ravel_stage_stack`, depth order.
    stacked_params: [S * v, P_max], sharded over `axis_name`
      (`params_layout` as in `pipelined_apply`).
    microbatches: [num_micro, mb, A_max] — stage 0's inputs, already
      flat-padded to the common buffer width.
    mesh: mesh whose `axis_name` has size S == len(stage_fns) // v.
    batch_axis: optional mesh axis the mb dim stays sharded over (PP x DP
      composition, as in `pipelined_apply`).
    num_virtual_stages: chunks per rank (v); 1 = GPipe, >1 =
      interleaved 1F1B over the same `lax.switch` flat-buffer skeleton.

  Returns:
    [num_micro, mb, A_max] final-stage outputs (zero-padded), replicated
    over the pp axis.
  """
  num_layers = len(stage_fns)
  v = int(num_virtual_stages)
  num_stages = mesh.shape[axis_name]
  if num_stages * v != num_layers:
    raise ValueError(
        f"mesh axis {axis_name!r} has size {mesh.shape[axis_name]} and "
        f"num_virtual_stages={v}, but {num_layers} stage functions were "
        f"given (want num_stages * num_virtual_stages stage functions)")
  if params_layout not in ("layer", "interleaved"):
    raise ValueError(f"params_layout must be 'layer' or 'interleaved', "
                     f"got {params_layout!r}")
  if stacked_params.shape[0] != num_layers:
    # Without this, jax's clamping gather semantics would silently reuse
    # a neighboring chunk's params instead of raising (same guard as the
    # homogeneous path's leading-dim check).
    raise ValueError(
        f"stacked_params leading dim {stacked_params.shape[0]} != "
        f"num_stages {num_stages} * num_virtual_stages {v}")
  num_micro, _, a_max = microbatches.shape
  _validate_and_account(num_stages, num_micro, v, batch_axis)
  if v > 1 and params_layout == "layer":
    stacked_params = interleave_stage_stack(stacked_params, num_stages, v)
  total_ticks, out_ticks, plan = _tick_plan(num_stages, num_micro, v)

  params_spec, replicated_spec = _io_specs(mesh, axis_name, batch_axis)
  perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

  def local_fn(local_params, micro):
    # local_params: [v, P_max] — this rank's chunk vectors, loop-major.
    idx = jax.lax.axis_index(axis_name)

    def branch(layer):
      def run(operands):
        vec, x = operands
        params = unravel_fns[layer](vec[:param_sizes[layer]])
        y = stage_fns[layer](params, x)
        return jnp.pad(y, ((0, 0), (0, a_max - y.shape[-1])))
      return run

    branches = [branch(layer) for layer in range(num_layers)]

    def tick(carry, t):
      valid, m, chunk = plan(t, idx)
      inject = (idx == 0) & valid & (chunk == 0)
      x = jnp.where(inject, micro[m], carry)
      # The global layer this rank runs this tick: loop `chunk`'s visit
      # to rank `idx` is layer chunk*S + idx (see interleave_order).
      layer = chunk * num_stages + idx
      pvec = local_params[chunk]
      y = jax.lax.switch(layer, branches, (pvec, x))
      y = jnp.where(valid, y, jnp.zeros_like(y))
      shifted = jax.lax.ppermute(y, axis_name, perm)
      return shifted, y

    zeros = jnp.zeros_like(micro[0])
    _, ys = jax.lax.scan(tick, zeros, jnp.arange(total_ticks))
    outs = ys[jnp.asarray(out_ticks)]
    outs = jnp.where(idx == num_stages - 1, outs, jnp.zeros_like(outs))
    return jax.lax.psum(outs, axis_name)

  return mesh_lib.shard_map(
      local_fn, mesh=mesh,
      in_specs=(params_spec, replicated_spec),
      out_specs=replicated_spec)(stacked_params, microbatches)


def sequential_apply_heterogeneous(
    stage_fns: Sequence[Callable[[Any, jnp.ndarray], jnp.ndarray]],
    unravel_fns: Sequence[Callable[[jnp.ndarray], Any]],
    param_sizes: Sequence[int],
    stacked_params: jnp.ndarray,
    microbatches: jnp.ndarray) -> jnp.ndarray:
  """The mathematically identical no-mesh schedule: every microbatch
  through every stage in depth order (GPipe and interleaved 1F1B are
  execution schedules, not different functions). Used on a single chip
  and as the equivalence oracle in tests. `stacked_params` is the
  depth-ordered stack from `ravel_stage_stack`."""
  num_micro, _, a_max = microbatches.shape
  outs = []
  for m in range(num_micro):
    x = microbatches[m]
    for s, fn in enumerate(stage_fns):
      y = fn(unravel_fns[s](stacked_params[s, :param_sizes[s]]), x)
      x = jnp.pad(y, ((0, 0), (0, a_max - y.shape[-1])))
    outs.append(x)
  return jnp.stack(outs)


def shard_pipeline_tree(tree: Any, mesh: Mesh,
                        axis_name: str = "pp",
                        num_virtual_stages: int = 1) -> Any:
  """Places a pytree for pipeline training: leaves whose leading dim is
  a positive multiple of the `axis_name` rank count — stage stacks, for
  ANY virtual-chunk factor — are sharded over `axis_name`; everything
  else (optimizer scalars like adam's count) is replicated.

  `num_virtual_stages` is accepted for call-site clarity but no longer
  narrows the match: a v>1 stack placed by a caller with the old 3-arg
  habit used to fall silently into the replicated branch (v× memory on
  every rank + a reshard at each step's shard_map boundary)."""
  del num_virtual_stages  # any rank-count multiple is a stage stack
  num_ranks = mesh.shape[axis_name]
  staged = NamedSharding(mesh, PartitionSpec(axis_name))
  replicated = NamedSharding(mesh, PartitionSpec())

  def _place(x):
    dim0 = x.shape[0] if getattr(x, "ndim", 0) >= 1 else 0
    if dim0 >= num_ranks and dim0 % num_ranks == 0:
      return jax.device_put(x, staged)
    return jax.device_put(x, replicated)

  return jax.tree_util.tree_map(_place, tree)
