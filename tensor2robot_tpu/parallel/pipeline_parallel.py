"""Pipeline parallelism: GPipe-style fill/drain over a mesh axis.

Beyond the reference (SURVEY.md §2.5: PP absent there). Stage parameters
carry a leading [num_stages] dim sharded over the `pp` axis; microbatches
flow through a `lax.scan` of compute+`ppermute` ticks, so activations hop
stage-to-stage over ICI while every stage works on a different
microbatch (the classic bubble is (S-1)/(M+S-1)). Differentiable: the
scan/ppermute pair transposes cleanly, so the same function trains.

The stage function must be shape-preserving stage-to-stage (classic
homogeneous-block pipelining, e.g. transformer/MLP block stacks).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["pipelined_apply", "stack_stage_params",
           "shard_pipeline_tree", "make_pipelined_train_step"]


def stack_stage_params(params_list):
  """Stacks per-stage param pytrees into leading-[S] arrays (the layout
  `pp` sharding expects)."""
  return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params_list)


def pipelined_apply(stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                    stage_params: Any,
                    microbatches: jnp.ndarray,
                    mesh: Mesh,
                    axis_name: str = "pp",
                    batch_axis: str = None) -> jnp.ndarray:
  """Runs microbatches through a pipeline of stages.

  Args:
    stage_fn: (one stage's params, activation [mb, ...]) -> activation of
      the same shape.
    stage_params: pytree with leading [num_stages] dim on every leaf;
      sharded over `axis_name`.
    microbatches: [num_microbatches, mb, ...] global input (replicated
      over the pp axis; when `batch_axis` is given, the mb dim stays
      sharded over it so PP composes with data parallelism instead of
      all-gathering the batch).
    mesh: mesh containing `axis_name` with size == num_stages.
    batch_axis: optional mesh axis the microbatch (second) dim is sharded
      over.

  Returns:
    [num_microbatches, mb, ...] outputs (replicated over the pp axis,
    mb dim sharded over `batch_axis` when given).
  """
  num_stages = mesh.shape[axis_name]
  num_micro = microbatches.shape[0]
  total_ticks = num_micro + num_stages - 1

  params_spec = PartitionSpec(axis_name)
  if batch_axis is not None and mesh.shape.get(batch_axis, 1) > 1:
    replicated = PartitionSpec(None, batch_axis)
  else:
    replicated = PartitionSpec()

  def local_fn(local_params, micro):
    # local_params leaves: [1, ...] (this device's stage); squeeze.
    my_params = jax.tree_util.tree_map(lambda x: x[0], local_params)
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

    def tick(carry, t):
      incoming = carry
      inject = micro[jnp.clip(t, 0, num_micro - 1)]
      x = jnp.where(idx == 0, inject, incoming)
      y = stage_fn(my_params, x)
      shifted = jax.lax.ppermute(y, axis_name, perm)
      return shifted, y

    zeros = jnp.zeros_like(micro[0])
    _, ys = jax.lax.scan(tick, zeros, jnp.arange(total_ticks))
    # The last stage's outputs at ticks [S-1, T) are the results for
    # microbatches [0, M). Broadcast them to every pp rank via psum.
    outs = ys[num_stages - 1:]
    outs = jnp.where(idx == num_stages - 1, outs, jnp.zeros_like(outs))
    return jax.lax.psum(outs, axis_name)

  return jax.shard_map(
      local_fn, mesh=mesh,
      in_specs=(params_spec, replicated),
      out_specs=replicated,
      check_vma=False)(stage_params, microbatches)


def make_pipelined_train_step(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    loss_fn: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray],
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    axis_name: str = "pp") -> Callable:
  """Builds a jitted *training* step over the GPipe pipeline.

  The forward runs microbatches through `pipelined_apply`; the backward
  is the autodiff transpose of the same scan+ppermute schedule (reverse
  activation hops over the ICI ring — GPipe's synchronous backward), and
  microbatch gradients accumulate into one optimizer update, i.e.
  microbatch gradient accumulation is the sum inside the mean loss.

  Args:
    stage_fn: (stage params, activation [mb, ...]) -> same-shape
      activation (homogeneous stages; see module docstring for scope).
    loss_fn: (outputs [M, mb, ...], targets [M, mb, ...]) -> scalar mean
      loss over all microbatches.
    optimizer: optax transformation over the stacked stage params.
    mesh: mesh containing `axis_name`.

  Returns:
    jitted (stage_params, opt_state, microbatches, targets) ->
    (stage_params, opt_state, loss). Place stage params / optimizer
    state with `shard_pipeline_tree` first; jit follows the committed
    input shardings, so params and moments stay pp-sharded throughout.
  """

  def step(stage_params, opt_state, microbatches, targets):
    def total_loss(p):
      outputs = pipelined_apply(stage_fn, p, microbatches, mesh,
                                axis_name=axis_name)
      return loss_fn(outputs, targets)

    loss, grads = jax.value_and_grad(total_loss)(stage_params)
    updates, new_opt_state = optimizer.update(grads, opt_state,
                                              stage_params)
    new_params = optax.apply_updates(stage_params, updates)
    return new_params, new_opt_state, loss

  return jax.jit(step)


def shard_pipeline_tree(tree: Any, mesh: Mesh,
                        axis_name: str = "pp") -> Any:
  """Places a pytree for pipeline training: leaves with a leading
  [num_stages] dim are sharded over `axis_name`, everything else
  (optimizer scalars like adam's count) is replicated."""
  num_stages = mesh.shape[axis_name]
  staged = NamedSharding(mesh, PartitionSpec(axis_name))
  replicated = NamedSharding(mesh, PartitionSpec())

  def _place(x):
    if getattr(x, "ndim", 0) >= 1 and x.shape[0] == num_stages:
      return jax.device_put(x, staged)
    return jax.device_put(x, replicated)

  return jax.tree_util.tree_map(_place, tree)
