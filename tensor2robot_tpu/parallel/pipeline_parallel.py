"""Pipeline parallelism: GPipe-style fill/drain over a mesh axis.

Beyond the reference (SURVEY.md §2.5: PP absent there). Stage parameters
carry a leading [num_stages] dim sharded over the `pp` axis; microbatches
flow through a `lax.scan` of compute+`ppermute` ticks, so activations hop
stage-to-stage over ICI while every stage works on a different
microbatch (the classic bubble is (S-1)/(M+S-1)). Differentiable: the
scan/ppermute pair transposes cleanly, so the same function trains.

Two schedules share that skeleton:

* `pipelined_apply` — homogeneous: one shape-preserving stage function,
  stage params stacked with a leading [S] dim (transformer/MLP blocks).
* `pipelined_apply_heterogeneous` — per-stage DIFFERENT functions,
  param pytrees, and activation shapes (e.g. a conv tower whose spatial
  dims and channel counts change every stage). Each stage's params are
  raveled to a flat vector, zero-padded to the widest stage, and stacked
  into one [S, P_max] leaf sharded over `pp`; activations travel as
  zero-padded flat [mb, A_max] buffers so every ppermute hop moves a
  same-shape array. Inside the SPMD program a `lax.switch` on
  `axis_index` dispatches each rank to its own stage's computation —
  XLA compiles all S branches everywhere (static shapes, MXU-friendly:
  the branch unravels to the TRUE shapes before any matmul/conv), each
  rank executes one.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import optax
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["pipelined_apply", "stack_stage_params",
           "shard_pipeline_tree", "make_pipelined_train_step",
           "ravel_stage_stack", "pipelined_apply_heterogeneous",
           "sequential_apply_heterogeneous"]


def stack_stage_params(params_list):
  """Stacks per-stage param pytrees into leading-[S] arrays (the layout
  `pp` sharding expects)."""
  return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params_list)


def pipelined_apply(stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                    stage_params: Any,
                    microbatches: jnp.ndarray,
                    mesh: Mesh,
                    axis_name: str = "pp",
                    batch_axis: str = None) -> jnp.ndarray:
  """Runs microbatches through a pipeline of stages.

  Args:
    stage_fn: (one stage's params, activation [mb, ...]) -> activation of
      the same shape.
    stage_params: pytree with leading [num_stages] dim on every leaf;
      sharded over `axis_name`.
    microbatches: [num_microbatches, mb, ...] global input (replicated
      over the pp axis; when `batch_axis` is given, the mb dim stays
      sharded over it so PP composes with data parallelism instead of
      all-gathering the batch).
    mesh: mesh containing `axis_name` with size == num_stages.
    batch_axis: optional mesh axis the microbatch (second) dim is sharded
      over.

  Returns:
    [num_microbatches, mb, ...] outputs (replicated over the pp axis,
    mb dim sharded over `batch_axis` when given).
  """
  num_stages = mesh.shape[axis_name]
  num_micro = microbatches.shape[0]
  total_ticks = num_micro + num_stages - 1

  params_spec = PartitionSpec(axis_name)
  if batch_axis is not None and mesh.shape.get(batch_axis, 1) > 1:
    replicated = PartitionSpec(None, batch_axis)
  else:
    replicated = PartitionSpec()

  def local_fn(local_params, micro):
    # local_params leaves: [1, ...] (this device's stage); squeeze.
    my_params = jax.tree_util.tree_map(lambda x: x[0], local_params)
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

    def tick(carry, t):
      incoming = carry
      inject = micro[jnp.clip(t, 0, num_micro - 1)]
      x = jnp.where(idx == 0, inject, incoming)
      y = stage_fn(my_params, x)
      shifted = jax.lax.ppermute(y, axis_name, perm)
      return shifted, y

    zeros = jnp.zeros_like(micro[0])
    _, ys = jax.lax.scan(tick, zeros, jnp.arange(total_ticks))
    # The last stage's outputs at ticks [S-1, T) are the results for
    # microbatches [0, M). Broadcast them to every pp rank via psum.
    outs = ys[num_stages - 1:]
    outs = jnp.where(idx == num_stages - 1, outs, jnp.zeros_like(outs))
    return jax.lax.psum(outs, axis_name)

  return jax.shard_map(
      local_fn, mesh=mesh,
      in_specs=(params_spec, replicated),
      out_specs=replicated,
      check_vma=False)(stage_params, microbatches)


def make_pipelined_train_step(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    loss_fn: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray],
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    axis_name: str = "pp") -> Callable:
  """Builds a jitted *training* step over the GPipe pipeline.

  The forward runs microbatches through `pipelined_apply`; the backward
  is the autodiff transpose of the same scan+ppermute schedule (reverse
  activation hops over the ICI ring — GPipe's synchronous backward), and
  microbatch gradients accumulate into one optimizer update, i.e.
  microbatch gradient accumulation is the sum inside the mean loss.

  Args:
    stage_fn: (stage params, activation [mb, ...]) -> same-shape
      activation (homogeneous stages; see module docstring for scope).
    loss_fn: (outputs [M, mb, ...], targets [M, mb, ...]) -> scalar mean
      loss over all microbatches.
    optimizer: optax transformation over the stacked stage params.
    mesh: mesh containing `axis_name`.

  Returns:
    jitted (stage_params, opt_state, microbatches, targets) ->
    (stage_params, opt_state, loss). Place stage params / optimizer
    state with `shard_pipeline_tree` first; jit follows the committed
    input shardings, so params and moments stay pp-sharded throughout.
  """

  def step(stage_params, opt_state, microbatches, targets):
    def total_loss(p):
      outputs = pipelined_apply(stage_fn, p, microbatches, mesh,
                                axis_name=axis_name)
      return loss_fn(outputs, targets)

    loss, grads = jax.value_and_grad(total_loss)(stage_params)
    updates, new_opt_state = optimizer.update(grads, opt_state,
                                              stage_params)
    new_params = optax.apply_updates(stage_params, updates)
    return new_params, new_opt_state, loss

  return jax.jit(step)


def ravel_stage_stack(stage_params_list: Sequence[Any]):
  """Packs heterogeneous per-stage param pytrees into one [S, P_max] leaf.

  Each stage's pytree is raveled (jax.flatten_util) to a flat vector,
  zero-padded to the widest stage, and the vectors stacked. Returns
  (stacked [S, P_max] array, unravel_fns, sizes): `unravel_fns[s]`
  rebuilds stage s's pytree from `stacked[s, :sizes[s]]`.
  """
  flats, unravels = [], []
  for params in stage_params_list:
    flat, unravel = ravel_pytree(params)
    flats.append(flat)
    unravels.append(unravel)
  sizes = [int(f.size) for f in flats]
  p_max = max(sizes)
  stacked = jnp.stack(
      [jnp.pad(f, (0, p_max - f.size)) for f in flats])
  return stacked, unravels, sizes


def pipelined_apply_heterogeneous(
    stage_fns: Sequence[Callable[[Any, jnp.ndarray], jnp.ndarray]],
    unravel_fns: Sequence[Callable[[jnp.ndarray], Any]],
    param_sizes: Sequence[int],
    stacked_params: jnp.ndarray,
    microbatches: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = "pp",
    batch_axis: str = None) -> jnp.ndarray:
  """GPipe over stages with DIFFERENT functions/params/activation shapes.

  Args:
    stage_fns: per-stage (stage params pytree, flat activation
      [mb, A_max]) -> flat activation [mb, out_size_s] with
      out_size_s <= A_max. Each stage slices/reshapes what it consumes
      from the padded buffer and returns its (unpadded) flat output;
      zero-padding back to A_max happens here.
    unravel_fns / param_sizes: from `ravel_stage_stack`.
    stacked_params: [S, P_max], sharded over `axis_name`.
    microbatches: [num_micro, mb, A_max] — stage 0's inputs, already
      flat-padded to the common buffer width.
    mesh: mesh whose `axis_name` has size == len(stage_fns).
    batch_axis: optional mesh axis the mb dim stays sharded over (PP x DP
      composition, as in `pipelined_apply`).

  Returns:
    [num_micro, mb, A_max] final-stage outputs (zero-padded), replicated
    over the pp axis.
  """
  num_stages = len(stage_fns)
  if mesh.shape[axis_name] != num_stages:
    raise ValueError(
        f"mesh axis {axis_name!r} has size {mesh.shape[axis_name]} but "
        f"{num_stages} stage functions were given")
  num_micro, _, a_max = microbatches.shape
  total_ticks = num_micro + num_stages - 1

  params_spec = PartitionSpec(axis_name)
  if batch_axis is not None and mesh.shape.get(batch_axis, 1) > 1:
    replicated = PartitionSpec(None, batch_axis)
  else:
    replicated = PartitionSpec()

  def local_fn(local_params, micro):
    pvec = local_params[0]  # [P_max]: this device's stage, padded
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

    def branch(s):
      def run(operands):
        vec, x = operands
        params = unravel_fns[s](vec[:param_sizes[s]])
        y = stage_fns[s](params, x)
        return jnp.pad(y, ((0, 0), (0, a_max - y.shape[-1])))
      return run

    branches = [branch(s) for s in range(num_stages)]

    def tick(carry, t):
      incoming = carry
      inject = micro[jnp.clip(t, 0, num_micro - 1)]
      x = jnp.where(idx == 0, inject, incoming)
      y = jax.lax.switch(idx, branches, (pvec, x))
      shifted = jax.lax.ppermute(y, axis_name, perm)
      return shifted, y

    zeros = jnp.zeros_like(micro[0])
    _, ys = jax.lax.scan(tick, zeros, jnp.arange(total_ticks))
    outs = ys[num_stages - 1:]
    outs = jnp.where(idx == num_stages - 1, outs, jnp.zeros_like(outs))
    return jax.lax.psum(outs, axis_name)

  return jax.shard_map(
      local_fn, mesh=mesh,
      in_specs=(params_spec, replicated),
      out_specs=replicated,
      check_vma=False)(stacked_params, microbatches)


def sequential_apply_heterogeneous(
    stage_fns: Sequence[Callable[[Any, jnp.ndarray], jnp.ndarray]],
    unravel_fns: Sequence[Callable[[jnp.ndarray], Any]],
    param_sizes: Sequence[int],
    stacked_params: jnp.ndarray,
    microbatches: jnp.ndarray) -> jnp.ndarray:
  """The mathematically identical no-mesh schedule: every microbatch
  through every stage in order (GPipe is an execution schedule, not a
  different function). Used on a single chip and as the equivalence
  reference in tests."""
  num_micro, _, a_max = microbatches.shape
  outs = []
  for m in range(num_micro):
    x = microbatches[m]
    for s, fn in enumerate(stage_fns):
      y = fn(unravel_fns[s](stacked_params[s, :param_sizes[s]]), x)
      x = jnp.pad(y, ((0, 0), (0, a_max - y.shape[-1])))
    outs.append(x)
  return jnp.stack(outs)


def shard_pipeline_tree(tree: Any, mesh: Mesh,
                        axis_name: str = "pp") -> Any:
  """Places a pytree for pipeline training: leaves with a leading
  [num_stages] dim are sharded over `axis_name`, everything else
  (optimizer scalars like adam's count) is replicated."""
  num_stages = mesh.shape[axis_name]
  staged = NamedSharding(mesh, PartitionSpec(axis_name))
  replicated = NamedSharding(mesh, PartitionSpec())

  def _place(x):
    if getattr(x, "ndim", 0) >= 1 and x.shape[0] == num_stages:
      return jax.device_put(x, staged)
    return jax.device_put(x, replicated)

  return jax.tree_util.tree_map(_place, tree)
