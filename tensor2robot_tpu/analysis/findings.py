"""Structured lint findings + `# graftlint: disable=` suppressions.

Finding format mirrors the `path:line:` prefix ConfigError grew for
runtime errors (utils/config.py), so a static finding and the runtime
failure it predicts read the same way.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, Iterable, List, Optional, Set

__all__ = ["Finding", "Suppressions", "load_suppressions", "filter_findings"]

_DISABLE_RE = re.compile(
    r"#\s*graftlint:\s*disable(?:=(?P<rules>[\w,\- ]+))?")


@dataclasses.dataclass(frozen=True)
class Finding:
  """One rule violation: where, which rule, what's wrong.

  `end_line` is the last physical line of the flagged statement (0 means
  same as `line`) so a `# graftlint: disable=` comment anywhere on a
  multi-line statement suppresses it.
  """

  path: str
  line: int
  rule: str
  message: str
  end_line: int = 0

  def __str__(self) -> str:
    return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class Suppressions:
  """Per-file map of line -> suppressed rule ids (empty set = all rules).

  A trailing `# graftlint: disable=rule-a,rule-b` suppresses those rules
  on its statement (any physical line of it); bare `# graftlint: disable`
  suppresses every rule. Works for .py and .gin alike (both use `#`
  comments).
  """

  def __init__(self, by_line: Optional[Dict[int, Set[str]]] = None):
    self._by_line: Dict[int, Set[str]] = by_line or {}

  def is_suppressed(self, line: int, rule: str,
                    end_line: int = 0) -> bool:
    return self.match(line, rule, end_line) is not None

  def match(self, line: int, rule: str,
            end_line: int = 0) -> Optional[int]:
    """The physical line whose `# graftlint: disable` comment suppresses
    (line, rule), or None — the suppression-provenance seam the engine's
    JSON output reports (`suppressed_by`)."""
    for candidate in range(line, max(end_line, line) + 1):
      if candidate in self._by_line:
        rules = self._by_line[candidate]
        if not rules or rule in rules:
          return candidate
    return None

  def __bool__(self) -> bool:
    return bool(self._by_line)


def load_suppressions(text: str) -> Suppressions:
  by_line: Dict[int, Set[str]] = {}
  for lineno, raw in enumerate(text.splitlines(), start=1):
    m = _DISABLE_RE.search(raw)
    if not m:
      continue
    rules = m.group("rules")
    by_line[lineno] = ({r.strip() for r in rules.split(",") if r.strip()}
                       if rules else set())
  return Suppressions(by_line)


def filter_findings(findings: Iterable[Finding],
                    suppressions: Suppressions) -> List[Finding]:
  return [f for f in findings
          if not suppressions.is_suppressed(f.line, f.rule, f.end_line)]
