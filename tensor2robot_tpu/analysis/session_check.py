"""graftlint: session decode state must stay device-resident and bound.

The whole point of stateful serving sessions (ISSUE 11,
`serving/session.py`) is that per-session decode state NEVER leaves the
device and is ALWAYS re-bound after every tick — the two ways a call
site silently gives the O(1) win back:

* dropping the returned state: the decode seam is a pure
  `(state, session_state, features) -> (new_session_state, outputs)`;
  a call site that discards the first element keeps ticking on the OLD
  cache, which "works" (same shapes, plausible numbers) while every
  tick replays position 0 — the bug class that is invisible in shape
  tests and fatal in episodes;
* fetching session state to host: an `np.asarray`/`jax.device_get`
  over a session-state/arena value pays a full state transfer per tick
  (KV caches are the BIG arrays — at T=32 that dwarfs the decode
  compute, quietly rebuilding the stateless cost profile), and over
  the axon tunnel each eager fetch is ~1.5 s (CLAUDE.md).

Rule `session-state-leak` flags, at decode call sites
(`decode_step`/`decode_fn`/`decode_dispatch` call names):

* a bare-expression call (the returned state tuple is discarded);
* a tuple assignment whose STATE slot (first target) is an underscore
  name (`_`, `_state`, ...) — an explicit drop spelled as binding;

and, anywhere:

* `np.asarray` / `np.array` / `jax.device_get` / `jax.device_put`
  -free fetch helpers applied to a name or attribute whose dotted path
  mentions `session_state` or `arena` — host-fetching the state.

Pure AST analysis, backend-free like every graftlint rule (pattern of
`pp_check.py`). Suppress a deliberate exception with a trailing
`# graftlint: disable=session-state-leak`.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from tensor2robot_tpu.analysis import engine as engine_lib
from tensor2robot_tpu.analysis.findings import (Finding, filter_findings,
                                                load_suppressions)

__all__ = ["check_python_source", "check_python_file"]

_RULE = "session-state-leak"
_DECODE_NAMES = ("decode_step", "decode_fn", "decode_dispatch")
_FETCH_NAMES = ("asarray", "array", "device_get")
_STATE_MARKERS = ("session_state", "arena")


def _call_name(func: ast.AST) -> Optional[str]:
  if isinstance(func, ast.Name):
    return func.id
  if isinstance(func, ast.Attribute):
    return func.attr
  return None


def _dotted(node: ast.AST) -> str:
  """Best-effort dotted path of a Name/Attribute chain ('' otherwise)."""
  parts: List[str] = []
  while isinstance(node, ast.Attribute):
    parts.append(node.attr)
    node = node.value
  if isinstance(node, ast.Name):
    parts.append(node.id)
  return ".".join(reversed(parts))


def _mentions_state(node: ast.AST) -> bool:
  dotted = _dotted(node).lower()
  return any(marker in dotted for marker in _STATE_MARKERS)


def _is_underscore(target: ast.AST) -> bool:
  return isinstance(target, ast.Name) and target.id.startswith("_")


def _finding(path: str, node: ast.AST, message: str) -> Finding:
  return Finding(
      path=path, line=node.lineno, rule=_RULE,
      end_line=getattr(node, "end_lineno", node.lineno) or node.lineno,
      message=message)


def _check_node(path: str, node: ast.AST) -> List[Finding]:
  """Findings for one Expr/Assign/Call node (shared by the standalone
  parse path and the engine's single-walk visitor dispatch)."""
  findings: List[Finding] = []
  # Dropped decode state: `decode_step(...)` as a bare statement.
  if (isinstance(node, ast.Expr) and isinstance(node.value, ast.Call)
      and _call_name(node.value.func) in _DECODE_NAMES):
    findings.append(_finding(
        path, node,
        "decode-step result discarded — the returned session state is "
        "never re-bound, so every later tick replays the stale cache; "
        "bind it (`state, outputs = decode_step(...)`) or suppress a "
        "deliberate throwaway"))
    return findings
  # Dropped decode state spelled as `_ , out = decode_step(...)`.
  if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
      and _call_name(node.value.func) in _DECODE_NAMES:
    for target in node.targets:
      if isinstance(target, (ast.Tuple, ast.List)) and target.elts \
          and _is_underscore(target.elts[0]):
        findings.append(_finding(
            path, node,
            "decode-step state bound to an underscore name — the new "
            "session state is dropped and later ticks replay the "
            "stale cache; re-bind the state or suppress a deliberate "
            "single-tick probe"))
        break
  # Host fetch of session state: np.asarray(...session_state/arena...).
  if isinstance(node, ast.Call) and _call_name(node.func) in _FETCH_NAMES:
    if any(_mentions_state(arg) for arg in node.args[:1]):
      findings.append(_finding(
          path, node,
          "session state fetched to host — per-session decode caches "
          "must stay device-resident between ticks (a KV-cache fetch "
          "per tick re-buys the stateless cost, and each eager fetch "
          "over the axon tunnel is ~1.5 s); fetch OUTPUTS only, or "
          "suppress a deliberate debug dump"))
  return findings


def check_python_source(path: str, source: str) -> List[Finding]:
  try:
    tree = ast.parse(source, filename=path)
  except SyntaxError:
    return []  # the engine reports unparseable files
  findings: List[Finding] = []
  for node in ast.walk(tree):
    findings.extend(_check_node(path, node))
  return findings


def check_python_file(path: str) -> List[Finding]:
  with open(path, encoding="utf-8", errors="replace") as f:
    source = f.read()
  return filter_findings(check_python_source(path, source),
                         load_suppressions(source))


def _visit(ctx, node):
  return _check_node(ctx.path, node)


engine_lib.register(engine_lib.Rule(
    name="session", kind="py", scope=".py", family="session",
    infos=(engine_lib.RuleInfo(
        id=_RULE,
        doc=("a decode-step call site that discards the\n"
             "returned session state (bare expression, or\n"
             "the state slot bound to an underscore name) —\n"
             "later ticks replay the stale cache — or an\n"
             "np.asarray/device_get host fetch of a\n"
             "session_state/arena value, which re-buys the\n"
             "stateless per-tick cost (and ~1.5 s per eager\n"
             "fetch over the tunnel)"),
        meaning=("a decode-step call site drops the returned session "
                 "state (bare expression / state bound to an underscore "
                 "name) so later ticks replay the stale cache, or "
                 "host-fetches a session_state/arena value "
                 "(`np.asarray`/`device_get`), re-buying the stateless "
                 "per-tick cost")),),
    visitors={ast.Expr: _visit, ast.Assign: _visit, ast.Call: _visit}))
