"""graftlint: pipeline train steps must be schedule-audited.

A pipelined train step is the one executable in this repo whose cost is
dominated by its SCHEDULE, not its kernels: the bubble fraction and the
per-stage donation layout decide whether the pp dimension scales, and
both are only observable when the step is built through the
`obs.xray`/`analyze_jit` path (`make_pipelined_train_step(audit_name=
...)` wraps the jitted step in an `XrayedFunction` whose first dispatch
records donated/undonated bytes from `Traced.args_info` next to the
`pp/bubble_fraction` schedule gauges). A call site that builds the step
un-audited trains blind — a schedule regression (or a donation silently
dropped by a refactor) never reaches runs.jsonl and can't be gated by
`graftscope diff`:

* `pp-schedule-unaudited` — a `make_pipelined_train_step(...)` call
  site that passes no `audit_name=` (nor an `**kwargs` splat, which is
  not statically analyzable and is accepted like cache_check does).
  Passing `audit_name=None` explicitly is also flagged — spell a
  deliberate opt-out with a suppression comment instead, so the
  decision is visible at the call site.

Pure AST analysis, backend-free like every graftlint rule. Suppress
with a trailing `# graftlint: disable=pp-schedule-unaudited`.
"""

from __future__ import annotations

import ast
from typing import List

from tensor2robot_tpu.analysis import engine as engine_lib
from tensor2robot_tpu.analysis.findings import (Finding, filter_findings,
                                                load_suppressions)

__all__ = ["check_python_source", "check_python_file"]

_RULE = "pp-schedule-unaudited"
_FACTORY = "make_pipelined_train_step"


def _is_factory_call(func: ast.AST) -> bool:
  if isinstance(func, ast.Name):
    return func.id == _FACTORY
  if isinstance(func, ast.Attribute):
    return func.attr == _FACTORY
  return False


def _check_call(path: str, node: ast.Call) -> List[Finding]:
  """Findings for one Call node (shared by the standalone parse path
  and the engine's single-walk visitor dispatch)."""
  if not _is_factory_call(node.func):
    return []
  if any(kw.arg is None for kw in node.keywords):
    return []  # **splat: audit_name may arrive in the dict
  audit = next((kw for kw in node.keywords if kw.arg == "audit_name"),
               None)
  audited = audit is not None and not (
      isinstance(audit.value, ast.Constant) and audit.value.value is None)
  if audited:
    return []
  return [Finding(
      path=path, line=node.lineno, rule=_RULE,
      end_line=getattr(node, "end_lineno", node.lineno) or node.lineno,
      message=("pipelined train step built without audit_name= — the "
               "step never routes through analyze_jit, so its "
               "per-stage donation bytes and pp/bubble_fraction "
               "schedule telemetry stay out of runs.jsonl and "
               "schedule regressions can't be diff-gated; pass "
               "audit_name='<run>/pp_train_step' (or suppress a "
               "deliberate opt-out)"))]


def check_python_source(path: str, source: str) -> List[Finding]:
  try:
    tree = ast.parse(source, filename=path)
  except SyntaxError:
    return []  # the engine reports unparseable files
  findings: List[Finding] = []
  for node in ast.walk(tree):
    if isinstance(node, ast.Call):
      findings.extend(_check_call(path, node))
  return findings


def check_python_file(path: str) -> List[Finding]:
  with open(path, encoding="utf-8", errors="replace") as f:
    source = f.read()
  return filter_findings(check_python_source(path, source),
                         load_suppressions(source))


engine_lib.register(engine_lib.Rule(
    name="pp", kind="py", scope=".py", family="pipeline",
    infos=(engine_lib.RuleInfo(
        id=_RULE,
        doc=("a `make_pipelined_train_step(...)` call site\n"
             "that passes no `audit_name=` (or an explicit\n"
             "None) — the step skips the analyze_jit path,\n"
             "so per-stage donation bytes and the\n"
             "pp/bubble_fraction schedule telemetry never\n"
             "reach runs.jsonl; a `**splat` call site is\n"
             "accepted"),
        meaning=("a `make_pipelined_train_step(...)` call site passes no "
                 "`audit_name=` (or an explicit None) — the step skips "
                 "analyze_jit, so per-stage donation bytes and "
                 "pp/bubble_fraction telemetry never reach runs.jsonl "
                 "(`**splat` accepted)")),),
    visitors={ast.Call: lambda ctx, node: _check_call(ctx.path, node)}))
