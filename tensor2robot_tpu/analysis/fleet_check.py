"""graftlint: fleet/router owners must close (or drain) their replicas.

A `ServingFleet` (`serving/fleet.py`) owns one `MicroBatcher` /
`SessionBatcher` worker thread PER REPLICA plus every replica's engine;
its `close()` is the only path that JOINS those workers — the
tunnel-safe discipline the batchers themselves follow
(`thread-stage-missing-close` mechanizes it at the class level). A
construction site that builds a fleet and never arranges teardown
leaks N dispatch workers that can outlive every consumer, and a daemon
thread killed at interpreter shutdown mid device-dispatch is the
documented tunnel-wedging hazard (CLAUDE.md).

Rule `fleet-replica-unjoined` flags a `ServingFleet(...)` construction
site (any `ServingFleet` / `serving.ServingFleet` call) unless its
owning scope visibly transfers or ends the fleet's lifetime:

* constructed as a `with` context item (the CM protocol closes it);
* the bound name later receives a `.close(...)` or `.drain(...)` call
  in the same scope;
* the bound name is `return`ed or `yield`ed (ownership moves to the
  caller, which this rule will check at ITS construction site — a
  factory is not a leak);
* the value is stored on `self` (an owning object whose own `close`
  discipline the thread rules already police).

Findings anchor on the construction line; a trailing
`# graftlint: disable=fleet-replica-unjoined` suppresses a deliberate
exception (e.g. a process-lifetime server whose fleet dies with the
process). Pure AST analysis, backend-free like every graftlint rule
(pattern of `thread_check.py` / `pp_check.py`).
"""

from __future__ import annotations

import ast
from typing import List, Optional

from tensor2robot_tpu.analysis import engine as engine_lib
from tensor2robot_tpu.analysis.findings import (Finding, filter_findings,
                                                load_suppressions)

__all__ = ["check_python_source", "check_python_file"]

_RULE = "fleet-replica-unjoined"
_FLEET_NAMES = ("ServingFleet",)
_RELEASE_METHODS = ("close", "drain")


def _call_name(func: ast.AST) -> Optional[str]:
  if isinstance(func, ast.Name):
    return func.id
  if isinstance(func, ast.Attribute):
    return func.attr
  return None


def _is_fleet_ctor(node: ast.AST) -> bool:
  return (isinstance(node, ast.Call)
          and _call_name(node.func) in _FLEET_NAMES)


def _scope_bodies(tree: ast.Module):
  """Yields (scope_body, is_module) for the module and every function —
  the ownership units the rule reasons about. Class bodies are not
  scopes of their own (a fleet built at class-definition level is
  module-ish and lands in the module walk)."""
  yield tree.body, True
  for node in ast.walk(tree):
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
      yield node.body, False


def _walk_scope(node: ast.AST):
  """ast.walk that does NOT descend into nested function definitions —
  each function body is its own ownership scope (yielded separately by
  `_scope_bodies`), so a fleet built inside a nested function must be
  judged against THAT scope's releases, not its encloser's."""
  yield node
  for child in ast.iter_child_nodes(node):
    if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
      continue
    yield from _walk_scope(child)


def _released_names(body) -> set:
  """Names whose fleet lifetime is visibly handled inside `body`:
  closed/drained, returned/yielded, or stored on self."""
  released: set = set()
  for stmt in body:
    for node in _walk_scope(stmt):
      if isinstance(node, ast.Call):
        func = node.func
        if (isinstance(func, ast.Attribute)
            and func.attr in _RELEASE_METHODS
            and isinstance(func.value, ast.Name)):
          released.add(func.value.id)
      elif isinstance(node, (ast.Return, ast.Yield)) and node.value:
        if isinstance(node.value, ast.Name):
          released.add(node.value.id)
        elif isinstance(node.value, (ast.Tuple, ast.List)):
          for element in node.value.elts:
            if isinstance(element, ast.Name):
              released.add(element.id)
      elif isinstance(node, ast.Assign):
        for target in node.targets:
          if (isinstance(target, ast.Attribute)
              and isinstance(target.value, ast.Name)
              and target.value.id == "self"
              and isinstance(node.value, ast.Name)):
            released.add(node.value.id)
  return released


def _with_context_calls(body) -> List[ast.Call]:
  """Fleet constructions appearing as `with ServingFleet(...) [as x]`
  context items anywhere in the scope — the CM closes them."""
  calls: List[ast.Call] = []
  for stmt in body:
    for node in _walk_scope(stmt):
      if isinstance(node, (ast.With, ast.AsyncWith)):
        for item in node.items:
          if _is_fleet_ctor(item.context_expr):
            calls.append(item.context_expr)
  return calls


def check_python_tree(path: str, tree: ast.Module) -> List[Finding]:
  """Raw (unfiltered) findings over an already-parsed module (the
  engine's entry point; `check_python_source` wraps it with a parse)."""
  findings: List[Finding] = []
  seen_ctors: set = set()
  for body, _ in _scope_bodies(tree):
    with_calls = {id(c) for c in _with_context_calls(body)}
    released = _released_names(body)
    # Parent map within this scope (function bodies excluded, so a
    # ctor is judged against exactly one scope).
    parents: dict = {}
    for stmt in body:
      for node in _walk_scope(stmt):
        for child in ast.iter_child_nodes(node):
          parents[id(child)] = node
    for stmt in body:
      for node in _walk_scope(stmt):
        if not _is_fleet_ctor(node) or id(node) in seen_ctors:
          continue
        seen_ctors.add(id(node))
        if id(node) in with_calls:
          continue
        parent = parents.get(id(node))
        handled = False
        bound: Optional[str] = None
        if isinstance(parent, ast.Assign) and parent.value is node:
          target = parent.targets[0]
          if isinstance(target, ast.Name):
            bound = target.id
          elif isinstance(target, ast.Attribute) \
              and isinstance(target.value, ast.Name) \
              and target.value.id == "self":
            handled = True  # stored on self: the owner's close discipline
        elif isinstance(parent, ast.Return):
          handled = True  # factory: ownership moves to the caller
        if handled or (bound is not None and bound in released):
          continue
        findings.append(Finding(
            path=path, line=node.lineno, rule=_RULE,
            end_line=getattr(node, "end_lineno", node.lineno)
            or node.lineno,
            message=("ServingFleet constructed but its owner never "
                     "calls close()/drain(), uses it as a context "
                     "manager, returns it, or stores it on self: the "
                     "fleet's per-replica batcher workers are never "
                     "joined (the tunnel-wedging hazard). Close the "
                     "fleet in a finally/with, or suppress a "
                     "process-lifetime server deliberately.")))
  return findings


def check_python_source(path: str, source: str) -> List[Finding]:
  try:
    tree = ast.parse(source, filename=path)
  except SyntaxError:
    return []  # the engine reports unparseable files
  return check_python_tree(path, tree)


def check_python_file(path: str) -> List[Finding]:
  with open(path, encoding="utf-8", errors="replace") as f:
    source = f.read()
  return filter_findings(check_python_source(path, source),
                         load_suppressions(source))


engine_lib.register(engine_lib.Rule(
    name="fleet", kind="py", scope=".py", family="fleet",
    infos=(engine_lib.RuleInfo(
        id=_RULE,
        doc=("a `ServingFleet(...)` construction site whose\n"
             "owning scope never calls close()/drain() on\n"
             "it, uses it as a context manager, returns it,\n"
             "or stores it on self — the fleet's\n"
             "per-replica batcher workers are never joined\n"
             "(the tunnel-safe join discipline the batchers\n"
             "follow, mechanized for the fleet layer)"),
        meaning=("a `ServingFleet(...)` construction site whose owning "
                 "scope never calls `close()`/`drain()` on it, uses it "
                 "as a context manager, returns it, or stores it on "
                 "`self` — the fleet's per-replica batcher workers are "
                 "never joined (the tunnel-safe join discipline, "
                 "mechanized at the fleet layer)")),),
    check=lambda ctx: check_python_tree(ctx.path, ctx.tree)))
