"""Spec/sharding checker: TensorSpec sharding axes vs declared mesh axes.

`TensorSpec.sharding` names mesh axes positionally over the spec's own
shape (specs.py); the mesh axis vocabulary is declared by configs
(`train_eval_model.mesh_axis_names` / `create_mesh.axis_names`) on top of
`parallel.mesh.DEFAULT_AXES`. A sharding annotation naming an axis no
mesh declares compiles fine on a 1-axis test mesh and then fails (or
silently replicates) on the real topology — exactly the class of bug
that should be caught before any backend is touched.

Two faces:

* static — AST scan of `TensorSpec(...)` call sites with literal
  `sharding=` tuples (the CLI path; no imports, no execution);
* structural — `check_spec_structures(feature_spec, label_spec, ...)`
  over live SpecStructs via `specs.sharding_axes` (used by tests and by
  model authors at build time).

Rules:

* `unknown-mesh-axis`       — sharding names an axis no mesh declares;
* `duplicate-sharding-axis` — the same axis twice in one annotation
                              (rejected by jax.sharding.PartitionSpec);
* `sharding-rank-mismatch`  — more sharding entries than the spec has
                              dims;
* `sharding-conflict`       — the same flat key carries different
                              shardings in feature vs label specs.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tensor2robot_tpu.analysis import engine as engine_lib
from tensor2robot_tpu.analysis.findings import (Finding, filter_findings,
                                                load_suppressions)

__all__ = ["known_mesh_axes", "check_python_source", "check_python_file",
           "check_spec_structures"]


def known_mesh_axes(config_paths: Sequence[str] = ()) -> Set[str]:
  """DEFAULT_AXES plus every axis name declared by the given configs."""
  from tensor2robot_tpu.analysis import config_check
  from tensor2robot_tpu.parallel import mesh

  axes = set(mesh.DEFAULT_AXES)
  axes.update(config_check.collect_mesh_axis_names(config_paths))
  return axes


def _literal(node: ast.AST):
  try:
    return ast.literal_eval(node)
  except (ValueError, SyntaxError):
    return None


def _is_tensorspec_call(node: ast.Call) -> bool:
  func = node.func
  if isinstance(func, ast.Name):
    return func.id == "TensorSpec"
  if isinstance(func, ast.Attribute):
    return func.attr == "TensorSpec"
  return False


def _check_axes(axes: Tuple, rank: Optional[int], mesh_axes: Set[str],
                path: str, line: int, where: str,
                end_line: int = 0) -> List[Finding]:
  findings: List[Finding] = []
  named = [a for a in axes if a is not None]
  for axis in named:
    if not isinstance(axis, str):
      findings.append(Finding(
          path, line, "unknown-mesh-axis",
          f"{where}: sharding entry {axis!r} is not a mesh axis name "
          "(expected str or None)", end_line=end_line))
    elif axis not in mesh_axes:
      findings.append(Finding(
          path, line, "unknown-mesh-axis",
          f"{where}: sharding axis {axis!r} names no declared mesh "
          f"dimension (known axes: {sorted(mesh_axes)})",
          end_line=end_line))
  dupes = {a for a in named if named.count(a) > 1}
  for axis in sorted(str(d) for d in dupes):
    findings.append(Finding(
        path, line, "duplicate-sharding-axis",
        f"{where}: axis {axis!r} appears more than once in one sharding "
        "annotation (PartitionSpec forbids reuse)", end_line=end_line))
  if rank is not None and len(axes) > rank:
    findings.append(Finding(
        path, line, "sharding-rank-mismatch",
        f"{where}: sharding has {len(axes)} entries for a rank-{rank} "
        "spec (sharding is positional over the spec's own shape)",
        end_line=end_line))
  return findings


def _check_tensorspec_call(path: str, node: ast.Call,
                           mesh_axes: Set[str]) -> List[Finding]:
  """Findings for one TensorSpec(...) Call node (shared by the
  standalone parse path and the engine's single-walk dispatch)."""
  if not _is_tensorspec_call(node):
    return []
  sharding_node = shape_node = None
  for kw in node.keywords:
    if kw.arg == "sharding":
      sharding_node = kw.value
    elif kw.arg == "shape":
      shape_node = kw.value
  if shape_node is None and node.args:
    shape_node = node.args[0]
  if sharding_node is None:
    return []
  sharding = _literal(sharding_node)
  if not isinstance(sharding, (list, tuple)):
    return []  # computed sharding: out of static reach
  shape = _literal(shape_node) if shape_node is not None else None
  rank = len(shape) if isinstance(shape, (list, tuple)) else None
  return _check_axes(
      tuple(sharding), rank, mesh_axes, path, node.lineno, "TensorSpec",
      end_line=getattr(node, "end_lineno", 0) or 0)


def check_python_source(text: str, path: str,
                        mesh_axes: Optional[Set[str]] = None
                        ) -> List[Finding]:
  """Statically audits literal `TensorSpec(..., sharding=...)` calls."""
  mesh_axes = mesh_axes if mesh_axes is not None else known_mesh_axes()
  try:
    tree = ast.parse(text, filename=path)
  except SyntaxError:
    return []  # the engine owns the parse-error finding
  findings: List[Finding] = []
  for node in ast.walk(tree):
    if isinstance(node, ast.Call):
      findings.extend(_check_tensorspec_call(path, node, mesh_axes))
  return sorted(filter_findings(findings, load_suppressions(text)),
                key=lambda f: (f.line, f.rule))


def check_python_file(path: str,
                      mesh_axes: Optional[Set[str]] = None
                      ) -> List[Finding]:
  with open(path) as f:
    return check_python_source(f.read(), path, mesh_axes)


def check_spec_structures(feature_spec,
                          label_spec=None,
                          mesh_axes: Optional[Set[str]] = None,
                          origin: str = "<specs>") -> List[Finding]:
  """Audits live spec structures (model feature/label specs).

  Reports unknown/duplicate axes per leaf plus `sharding-conflict`: a
  flat key annotated differently in the feature and label structures —
  the two would commit contradictory layouts for what the data layer
  treats as one logical stream.
  """
  from tensor2robot_tpu import specs as specs_lib

  mesh_axes = mesh_axes if mesh_axes is not None else known_mesh_axes()
  findings: List[Finding] = []
  by_key: Dict[str, Tuple] = {}
  for struct_name, struct in (("feature_spec", feature_spec),
                              ("label_spec", label_spec)):
    if struct is None:
      continue
    axes_map = specs_lib.sharding_axes(struct)
    specs_flat = specs_lib.flatten_spec_structure(struct)
    for key, sharding in axes_map.items():
      rank = len(specs_flat[key].shape)
      findings.extend(_check_axes(sharding, rank, mesh_axes, origin, 0,
                                  f"{struct_name}[{key!r}]"))
      if key in by_key and by_key[key] != sharding:
        findings.append(Finding(
            origin, 0, "sharding-conflict",
            f"key {key!r} is sharded {by_key[key]!r} in feature_spec "
            f"but {sharding!r} in label_spec"))
      by_key.setdefault(key, sharding)
  return findings


engine_lib.register(engine_lib.Rule(
    name="spec", kind="py", scope=".py", family="spec",
    infos=(
        engine_lib.RuleInfo(
            id="unknown-mesh-axis",
            doc="TensorSpec.sharding names an undeclared axis",
            meaning=("`TensorSpec.sharding` names an axis no mesh "
                     "declares")),
        engine_lib.RuleInfo(
            id="duplicate-sharding-axis",
            doc="same axis twice in one annotation",
            meaning=("same axis twice in one annotation (PartitionSpec "
                     "forbids)")),
        engine_lib.RuleInfo(
            id="sharding-rank-mismatch",
            doc="more sharding entries than spec dims",
            meaning="more sharding entries than spec dims"),
        engine_lib.RuleInfo(
            id="sharding-conflict",
            doc=("feature vs label sharding disagreement\n"
                 "(structure-level API only)"),
            meaning=("feature vs label sharding disagreement "
                     "(structure-level API)")),
    ),
    visitors={ast.Call: lambda ctx, node: _check_tensorspec_call(
        ctx.path, node, ctx.mesh_axes)}))
