"""graftlint: retry loops in serving//data/ hot paths must use the
shared RetryPolicy, not constant sleeps with swallowed errors.

graftguard (`utils/retry.py`) exists because every retry in the tree
used to be bespoke: a constant `time.sleep` inside a loop that also
swallows exceptions is the signature of a hand-rolled retry — no
jitter (N clients hammering a dead dependency re-synchronize into
thundering herds), no deadline budget (the loop can spin forever), no
telemetry (`retry/*` counters are how runs.jsonl shows retry
pressure), and a bare `except` that eats the error class information a
retryable-predicate needs.

Rule `bare-retry-rule` flags a `for`/`while` loop in a `serving/` or
`data/` source file (the dispatch and ingest hot paths; other trees
adopt the policy by convention, not lint force) that contains BOTH:

* a `time.sleep(<constant>)` call whose delay is a literal/constant
  expression — `sleep(policy.backoff_s(n))` or any computed delay does
  not match; and
* an exception handler that swallows broadly: a bare `except:` or
  `except Exception/BaseException:` whose body only `pass`es or
  `continue`s.

A bounded poll (`while not done: sleep(0.005)` with no exception
swallowing) and stop-aware queue waits are deliberately NOT flagged —
they pace, they don't retry. Suppress a justified exception with a
trailing `# graftlint: disable=bare-retry-rule`.

Pure AST analysis, backend-free like every graftlint rule (pattern of
`fleet_check.py` / `thread_check.py`).
"""

from __future__ import annotations

import ast
import os
from typing import List

from tensor2robot_tpu.analysis import engine as engine_lib
from tensor2robot_tpu.analysis.findings import (Finding, filter_findings,
                                                load_suppressions)

__all__ = ["check_python_source", "check_python_file"]

_RULE = "bare-retry-rule"
# Path components this rule polices (the issue-13 hot paths).
_HOT_DIRS = frozenset({"serving", "data"})
_BROAD_EXC = frozenset({"Exception", "BaseException"})


def _is_constant_number(node: ast.AST) -> bool:
  if isinstance(node, ast.Constant):
    return isinstance(node.value, (int, float)) and not isinstance(
        node.value, bool)
  if isinstance(node, ast.UnaryOp) and isinstance(node.op,
                                                  (ast.USub, ast.UAdd)):
    return _is_constant_number(node.operand)
  if isinstance(node, ast.BinOp):
    return (_is_constant_number(node.left)
            and _is_constant_number(node.right))
  return False


def _is_constant_sleep(node: ast.AST) -> bool:
  """`time.sleep(<constant>)` (or any `*.sleep` / bare `sleep` — the
  module alias doesn't change what the loop does)."""
  if not isinstance(node, ast.Call) or not node.args:
    return False
  func = node.func
  name = (func.attr if isinstance(func, ast.Attribute)
          else func.id if isinstance(func, ast.Name) else None)
  return name == "sleep" and _is_constant_number(node.args[0])


def _swallows_broadly(handler: ast.ExceptHandler) -> bool:
  """Bare `except:` / `except (Base)Exception:` whose body is only
  pass/continue — the error vanishes and the loop goes around again."""
  exc_type = handler.type
  if exc_type is not None:
    names = []
    nodes = exc_type.elts if isinstance(exc_type, ast.Tuple) else [exc_type]
    for node in nodes:
      if isinstance(node, ast.Name):
        names.append(node.id)
      elif isinstance(node, ast.Attribute):
        names.append(node.attr)
    if not any(name in _BROAD_EXC for name in names):
      return False
  return all(isinstance(stmt, (ast.Pass, ast.Continue))
             for stmt in handler.body)


def _walk_no_nested_defs(node: ast.AST):
  """Walks a loop body without descending into nested function
  definitions — a sleep inside a nested def is not this loop's
  pacing."""
  yield node
  for child in ast.iter_child_nodes(node):
    if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
      continue
    yield from _walk_no_nested_defs(child)


_PACKAGE_DIR = "tensor2robot_tpu"


def _in_hot_path(path: str) -> bool:
  """Hot iff a `serving`/`data` DIRECTORY component lies below the repo
  package when the path contains one. Matching the absolute path would
  tie the rule's scope to the checkout location — a repo cloned under
  e.g. ~/data/ would police every file in the tree."""
  parts = os.path.normpath(path).split(os.sep)[:-1]  # dirs only
  for i in range(len(parts) - 1, -1, -1):
    if parts[i] == _PACKAGE_DIR:
      parts = parts[i + 1:]
      break
  return bool(_HOT_DIRS.intersection(parts))


def _check_loop(path: str, node: ast.AST) -> List[Finding]:
  """Findings for one For/While/AsyncFor node (shared by the standalone
  parse path and the engine's single-walk visitor dispatch; the
  hot-path gate is applied by the caller)."""
  has_sleep = False
  swallow_line = None
  for inner in _walk_no_nested_defs(node):
    if inner is node:
      continue
    if _is_constant_sleep(inner):
      has_sleep = True
    elif isinstance(inner, ast.ExceptHandler) and _swallows_broadly(inner):
      swallow_line = inner.lineno
  if not has_sleep or swallow_line is None:
    return []
  return [Finding(
      path=path, line=node.lineno, rule=_RULE,
      end_line=getattr(node, "end_lineno", node.lineno) or node.lineno,
      message=(
          "retry loop with a constant time.sleep and a broad "
          f"except-swallow (line {swallow_line}) in a serving/data "
          "hot path — use utils.retry.RetryPolicy (jittered "
          "backoff, deadline budget, retry/* telemetry) or "
          "suppress with justification"))]


def check_python_source(path: str, source: str) -> List[Finding]:
  if not _in_hot_path(path):
    return []
  try:
    tree = ast.parse(source, filename=path)
  except SyntaxError:
    return []  # the engine owns parse errors
  findings: List[Finding] = []
  for node in ast.walk(tree):
    if isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
      findings.extend(_check_loop(path, node))
  suppressions = load_suppressions(source)
  return filter_findings(findings, suppressions)


def check_python_file(path: str) -> List[Finding]:
  try:
    with open(path, encoding="utf-8", errors="replace") as f:
      source = f.read()
  except OSError as e:
    return [Finding(path=path, line=0, rule=_RULE,
                    message=f"cannot read file: {e}")]
  return check_python_source(path, source)


def _visit(ctx, node):
  return _check_loop(ctx.path, node)


engine_lib.register(engine_lib.Rule(
    name="retry", kind="py", scope=".py, serving//data/ hot paths only",
    family="retry",
    infos=(engine_lib.RuleInfo(
        id=_RULE,
        doc=("a for/while loop containing BOTH a constant\n"
             "`time.sleep(<literal>)` AND a broad\n"
             "except-swallow (bare `except:` or\n"
             "`except (Base)Exception:` with a pass/continue\n"
             "body) — a hand-rolled retry with no jitter,\n"
             "deadline budget, or telemetry; migrate to\n"
             "`utils.retry.RetryPolicy` or suppress with\n"
             "justification"),
        meaning=("a `for`/`while` loop in a `serving/`/`data/` hot path "
                 "containing BOTH a constant `time.sleep(<literal>)` "
                 "AND a broad except-swallow (bare `except:` / `except "
                 "(Base)Exception:` with a pass/continue body) — a "
                 "hand-rolled retry with no jitter, deadline budget, or "
                 "`retry/*` telemetry; migrate to "
                 "`utils.retry.RetryPolicy` (`analysis/retry_check.py`; "
                 "computed delays like `sleep(policy.backoff_s(n))` and "
                 "pure poll loops are not flagged)")),),
    path_filter=_in_hot_path,
    visitors={ast.For: _visit, ast.While: _visit, ast.AsyncFor: _visit}))
