"""graftlint: static analysis for configs, specs, and tracer hygiene.

The framework's core promise is spec-driven correctness — configs,
TensorSpecs and the pipeline must agree (SURVEY.md §0). Before this
subsystem those contracts were enforced only at runtime (fresh-process
config smoke test, call-time spec validation) and by convention (the
CLAUDE.md axon-tunnel rules). `graftlint` checks them *before any JAX
backend is touched*, which on this machine also means before the fragile
TPU tunnel can be wedged — the compiler-first discipline of arxiv
1810.09868 / 2204.06514 applied to framework plumbing.

Three analyzers, one CLI (`python -m tensor2robot_tpu.analysis.lint`):

* `config_check`  — per-binding static resolution of every `.gin` file
  against the configurable registry (no-execute parse via
  `utils.config.iter_config_statements`);
* `tracer_check`  — AST lint for axon-tunnel and tracing hazards
  (`jax.block_until_ready`, import-time backend touches, host syncs and
  impure calls inside jitted functions);
* `spec_check`    — TensorSpec sharding axes vs mesh axis names declared
  in configs, plus structure-level feature/label conflict checks.

Analysis NEVER initializes a JAX backend (pinned by
tests/test_static_analysis.py, which runs the CLI under a bogus
JAX_PLATFORMS trap). Findings are structured (file, line, rule, message);
`# graftlint: disable=<rule>` on the offending line suppresses.
"""

from tensor2robot_tpu.analysis.findings import Finding  # noqa: F401
