"""graftlint rule engine: one parse per file, one suppression model.

Before this module, `analysis/lint.py:run` called eleven checkers per
Python file and every checker re-ran `ast.parse` on the same source —
~10 parses per file, each checker with its own open/parse/filter tail.
The engine inverts that: checkers REGISTER rules here (a `Rule` carries
its catalog metadata plus either a whole-tree `check` callback or a
node-type `visitors` dispatch table), and the engine walks each file
ONCE — one read, one `ast.parse`, one shared `ast.walk` node list for
every visitor rule, one central suppression pass with provenance (which
`# graftlint: disable` line swallowed which finding).

The registry is the single source of truth for the rule catalog:
`catalog_text()` renders `--list-rules` and `catalog_markdown()` renders
the docs/ARCHITECTURE.md table (a test pins the docs against it), so a
new rule cannot ship undocumented.

Finding parity is a hard contract: for every registered rule the
engine's output is byte-identical to the old per-checker pipeline
(tests/test_static_analysis.py::test_engine_matches_per_checker_pipeline
runs both over the whole repo). The argument: each rule emits raw
findings in its original traversal order (the shared walk list IS
`ast.walk`'s BFS order), `filter_findings` preserves order, Python's
sort is stable, and the final global sort key (path, line, rule) is the
one `lint.run` always applied — so filter-then-concat-then-sort equals
the old concat-of-per-checker-filtered-then-sort, tie for tie.

Also home to the incremental mode: `--cache-file` keys each `.py`
file's findings on a content hash (plus the mesh-axis vocabulary and
the registered rule list, which both change findings without changing
the file), and `--changed-only` reports only files whose hash moved —
the CI fast path behind `scripts/lint.sh --changed`. `.gin` results
additionally depend on the importable module registry, so config files
are only served from cache in `--changed-only` mode (a full cached run
re-checks every config).

Backend-free like every graftlint rule: nothing here imports jax, and
the poisoned-JAX_PLATFORMS test covers the engine path end to end.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import re
import time
from typing import (Any, Callable, Dict, Iterable, List, Mapping, Optional,
                    Sequence, Set, Tuple, Type)

from tensor2robot_tpu.analysis.findings import Finding, load_suppressions

__all__ = [
    "RuleInfo", "Rule", "FileContext", "EngineResult", "register",
    "registered_rules", "rule_infos", "severity_of", "load_builtin_rules",
    "catalog_text", "catalog_markdown", "discover", "run_engine",
    "finding_fingerprint", "load_baseline", "write_baseline",
]

SEVERITIES = ("error", "warning")

# Checker execution order per file — the exact order lint.run has always
# applied (tie-order inside one (path, line, rule) sort key depends on
# it, so it is part of the byte-parity contract, not a style choice).
CHECK_ORDER = ("tracer", "spec", "cache", "pp", "session", "fleet",
               "forge", "retry", "thread", "loop", "native", "tracectx",
               "slo", "pallas")

# Catalog presentation order — the family order `--list-rules` has
# always printed (config first, spec last) with the jaxpr-audit family
# appended after it.
CATALOG_ORDER = ("config", "tracer", "tracectx", "cache", "pp",
                 "session", "retry", "fleet", "forge", "loop", "thread",
                 "native", "pallas", "slo", "spec", "audit")

_SKIP_DIRS = {"__pycache__", ".git", "node_modules", ".ipynb_checkpoints"}

_CATALOG_FOOTER = ("Suppress a finding with a trailing "
                   "`# graftlint: disable=<rule>`.")

# `parse-error` is shared: config_check reports unparseable .gin files
# and the engine itself reports unparseable .py files (the role
# tracer_check's parse owned before the single-parse refactor).
_PARSE_ERROR_RULE = "parse-error"


@dataclasses.dataclass(frozen=True)
class RuleInfo:
  """Catalog metadata for one rule id.

  `doc` is the pre-wrapped plain-text block `--list-rules` prints
  (first line + continuation lines, no indentation — the renderer owns
  layout); `meaning` is the one-line markdown cell of the
  docs/ARCHITECTURE.md rule table. Both live next to the checker that
  owns the rule, so catalog and implementation cannot drift.
  """

  id: str
  doc: str
  meaning: str
  severity: str = "error"

  def __post_init__(self):
    if self.severity not in SEVERITIES:
      raise ValueError(f"Unknown severity {self.severity!r} for rule "
                       f"{self.id!r} (want one of {SEVERITIES})")


# A whole-tree callback: ctx -> raw (unfiltered, emission-order)
# findings. A visitor callback: (ctx, node) -> iterable of findings for
# one matching node of the shared walk.
CheckFn = Callable[["FileContext"], List[Finding]]
VisitFn = Callable[["FileContext", ast.AST], Iterable[Finding]]


@dataclasses.dataclass(frozen=True)
class Rule:
  """One registered checker: catalog entries + how to run it.

  kind:
    "py"     — runs over parsed Python files (check or visitors);
    "gin"    — runs over config files (check; self-filtered);
    "native" — runs over the native wrapper (check; self-filtered);
    "jaxpr"  — catalog/severity only; executed by `graftscope audit`,
               not by the file walk.

  `path_filter` (path -> bool) scopes path-gated rules (retry's hot
  paths, the loop package, the native wrapper) without the rule body
  re-deriving it per node.
  """

  name: str
  kind: str
  scope: str
  family: str
  infos: Tuple[RuleInfo, ...]
  check: Optional[CheckFn] = None
  visitors: Optional[Mapping[Type[ast.AST], VisitFn]] = None
  path_filter: Optional[Callable[[str], bool]] = None

  def applies_to(self, path: str) -> bool:
    return self.path_filter is None or self.path_filter(path)


_REGISTRY: Dict[str, Rule] = {}
_BUILTINS_LOADED = False


def register(rule: Rule) -> Rule:
  """Adds a rule to the registry (idempotent re-registration allowed so
  module reloads in tests don't explode; a DIFFERENT rule under an
  existing name is a programming error)."""
  existing = _REGISTRY.get(rule.name)
  if existing is not None and {i.id for i in existing.infos} != {
      i.id for i in rule.infos}:
    raise ValueError(f"rule {rule.name!r} already registered with "
                     "different rule ids")
  _REGISTRY[rule.name] = rule
  return rule


def load_builtin_rules() -> None:
  """Imports every checker module once; each registers itself at import
  bottom (the engine never imports checkers at module level, so there
  is no import cycle — checkers import `engine` freely)."""
  global _BUILTINS_LOADED
  if _BUILTINS_LOADED:
    return
  # Import order is irrelevant: execution order is CHECK_ORDER and
  # catalog order is CATALOG_ORDER, both keyed by rule name.
  from tensor2robot_tpu.analysis import (cache_check, config_check,  # noqa: F401
                                         fleet_check, forge_check,
                                         jaxpr_audit, loop_check,
                                         native_check, pallas_check,
                                         pp_check, retry_check,
                                         session_check, slo_check,
                                         spec_check, thread_check,
                                         trace_check, tracer_check)
  _BUILTINS_LOADED = True


def registered_rules() -> Dict[str, Rule]:
  load_builtin_rules()
  return dict(_REGISTRY)


def rule_infos() -> List[RuleInfo]:
  """Every RuleInfo in catalog order."""
  rules = registered_rules()
  infos: List[RuleInfo] = []
  for name in CATALOG_ORDER:
    if name in rules:
      infos.extend(rules[name].infos)
  for name in sorted(set(rules) - set(CATALOG_ORDER)):
    infos.extend(rules[name].infos)
  return infos


def severity_of(rule_id: str) -> str:
  for info in rule_infos():
    if info.id == rule_id:
      return info.severity
  return "error"


# --------------------------------------------------------------------
# Catalog rendering — the single source of truth behind --list-rules
# AND the docs/ARCHITECTURE.md table.

_DOC_ID_WIDTH = 21   # two-space indent + 21-char id field + two spaces
_DOC_INDENT = " " * 25


def catalog_text() -> str:
  """The --list-rules catalog (layout byte-compatible with the old
  hand-maintained `_RULE_CATALOG` string)."""
  rules = registered_rules()
  blocks: List[str] = []
  for name in CATALOG_ORDER:
    rule = rules.get(name)
    if rule is None:
      continue
    lines = [f"{rule.family} rules ({rule.scope}):"]
    for info in rule.infos:
      doc_lines = info.doc.splitlines() or [""]
      lines.append(f"  {info.id.ljust(_DOC_ID_WIDTH)}  {doc_lines[0]}")
      lines.extend(f"{_DOC_INDENT}{rest}" for rest in doc_lines[1:])
    blocks.append("\n".join(lines))
  return "\n\n".join(blocks) + f"\n\n{_CATALOG_FOOTER}\n"


def catalog_markdown() -> str:
  """The docs/ARCHITECTURE.md rule table (regenerated, never edited by
  hand — tests pin the docs section against this output)."""
  lines = ["| Rule | Family | Severity | Meaning |", "|---|---|---|---|"]
  rules = registered_rules()
  for name in CATALOG_ORDER:
    rule = rules.get(name)
    if rule is None:
      continue
    for info in rule.infos:
      lines.append(f"| `{info.id}` | {rule.family} | {info.severity} "
                   f"| {info.meaning} |")
  return "\n".join(lines) + "\n"


# --------------------------------------------------------------------
# File discovery (moved here from lint.py; lint re-exports it).

def discover(paths: Sequence[str]) -> Tuple[List[str], List[str]]:
  """(.py files, .gin files) under the given files/directories."""
  py_files: List[str] = []
  gin_files: List[str] = []
  for path in paths:
    if os.path.isfile(path):
      (py_files if path.endswith(".py") else
       gin_files if path.endswith(".gin") else []).append(path)
      continue
    for dirpath, dirnames, filenames in os.walk(path):
      dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
      for name in sorted(filenames):
        if name.endswith(".py"):
          py_files.append(os.path.join(dirpath, name))
        elif name.endswith(".gin"):
          gin_files.append(os.path.join(dirpath, name))
  return py_files, gin_files


# --------------------------------------------------------------------
# Per-file context shared by every rule.

class FileContext:
  """One parsed file, shared across all rules: the source, the tree,
  ONE cached `ast.walk` node list for visitor dispatch, and a per-rule
  memo for derived structures (e.g. forge's module-literal table) so a
  rule computes them once per file, not once per node."""

  def __init__(self, path: str, source: str, tree: Optional[ast.Module],
               mesh_axes: Set[str]):
    self.path = path
    self.source = source
    self.tree = tree
    self.mesh_axes = mesh_axes
    self._nodes: Optional[List[ast.AST]] = None
    self._memo: Dict[str, Any] = {}

  @property
  def nodes(self) -> List[ast.AST]:
    if self._nodes is None:
      self._nodes = list(ast.walk(self.tree)) if self.tree else []
    return self._nodes

  def memo(self, key: str, factory: Callable[[], Any]) -> Any:
    if key not in self._memo:
      self._memo[key] = factory()
    return self._memo[key]


@dataclasses.dataclass
class EngineResult:
  findings: List[Finding]
  # (finding, line of the `# graftlint: disable` comment that ate it) —
  # the provenance the enriched --json output reports. Only rules the
  # engine filters centrally appear here (config/native self-filter).
  suppressed: List[Tuple[Finding, int]]
  stats: Dict[str, Any]


def _run_py_rules(ctx: FileContext,
                  rules: Sequence[Rule]) -> List[Finding]:
  """Raw findings of every applicable py rule, in CHECK_ORDER. Visitor
  rules share ONE pass over the cached walk list; per-rule buckets keep
  each rule's emissions in its own traversal order (== what its
  standalone `ast.walk` produced)."""
  applicable = [r for r in rules if r.applies_to(ctx.path)]
  buckets: Dict[str, List[Finding]] = {r.name: [] for r in applicable}
  visitor_rules = [r for r in applicable if r.visitors is not None]
  if visitor_rules:
    for node in ctx.nodes:
      node_type = type(node)
      for rule in visitor_rules:
        handler = rule.visitors.get(node_type)
        if handler is not None:
          buckets[rule.name].extend(handler(ctx, node))
  raw: List[Finding] = []
  for rule in applicable:
    if rule.check is not None:
      buckets[rule.name].extend(rule.check(ctx))
    raw.extend(buckets[rule.name])
  return raw


# --------------------------------------------------------------------
# Incremental cache.

CACHE_SCHEMA = "graftlint-cache-v1"
# Bump when rule logic changes in a way that invalidates cached
# findings without changing file contents.
ENGINE_CACHE_VERSION = 1

_GIN_INCLUDE_RE = re.compile(r"^\s*include\s+['\"](?P<path>[^'\"]+)['\"]",
                             re.MULTILINE)


def _sha256(text: str) -> str:
  return hashlib.sha256(text.encode("utf-8", "surrogatepass")).hexdigest()


def _gin_digest(path: str, _seen: Optional[Set[str]] = None) -> str:
  """Content hash over a config file AND its include closure (an edit
  to an included base config changes the includer's findings)."""
  seen = _seen if _seen is not None else set()
  real = os.path.realpath(path)
  if real in seen:
    return ""
  seen.add(real)
  try:
    with open(path, encoding="utf-8", errors="replace") as f:
      text = f.read()
  except OSError:
    return "unreadable"
  parts = [_sha256(text)]
  for m in _GIN_INCLUDE_RE.finditer(text):
    inc = m.group("path")
    if not os.path.isabs(inc):
      inc = os.path.join(os.path.dirname(path), inc)
    parts.append(_gin_digest(inc, seen))
  return _sha256("\n".join(parts))


def _finding_to_json(f: Finding) -> Dict[str, Any]:
  return {"path": f.path, "line": f.line, "rule": f.rule,
          "message": f.message, "end_line": f.end_line}


def _finding_from_json(d: Dict[str, Any]) -> Finding:
  return Finding(path=d["path"], line=int(d["line"]), rule=d["rule"],
                 message=d["message"], end_line=int(d.get("end_line", 0)))


class _Cache:
  """Content-hash-keyed findings cache (one JSON file).

  Validity is global over (schema, engine version, registered rule ids,
  mesh-axis vocabulary): any of those changing can change findings with
  no file edit, so a mismatch drops the whole cache rather than serving
  stale results file by file.
  """

  def __init__(self, path: str, rule_ids: Sequence[str],
               vocab_digest: str):
    self.path = path
    self._stamp = {
        "schema": CACHE_SCHEMA,
        "version": ENGINE_CACHE_VERSION,
        "rules": sorted(rule_ids),
        "vocab": vocab_digest,
    }
    self._files: Dict[str, Dict[str, Any]] = {}
    self.hits = 0
    try:
      with open(path, encoding="utf-8") as f:
        data = json.load(f)
      if all(data.get(k) == v for k, v in self._stamp.items()):
        self._files = data.get("files", {})
    except (OSError, ValueError):
      pass

  def lookup(self, path: str, digest: str
             ) -> Optional[Tuple[List[Finding], List[Tuple[Finding, int]]]]:
    entry = self._files.get(path)
    if not entry or entry.get("digest") != digest:
      return None
    self.hits += 1
    findings = [_finding_from_json(d) for d in entry["findings"]]
    suppressed = [(_finding_from_json(d), int(line))
                  for d, line in entry["suppressed"]]
    return findings, suppressed

  def store(self, path: str, digest: str, findings: Sequence[Finding],
            suppressed: Sequence[Tuple[Finding, int]]) -> None:
    self._files[path] = {
        "digest": digest,
        "findings": [_finding_to_json(f) for f in findings],
        "suppressed": [[_finding_to_json(f), line]
                       for f, line in suppressed],
    }

  def save(self) -> None:
    data = dict(self._stamp)
    data["files"] = self._files
    tmp = f"{self.path}.tmp.{os.getpid()}"
    os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
    with open(tmp, "w", encoding="utf-8") as f:
      json.dump(data, f)
    os.replace(tmp, self.path)


# --------------------------------------------------------------------
# Baseline files: accept today's findings, gate only NEW ones.

BASELINE_SCHEMA = "graftlint-baseline-v1"


def finding_fingerprint(f: Finding) -> str:
  """Line-number-independent identity of a finding (path + rule +
  message), so edits above a known finding don't churn the baseline."""
  return _sha256(f"{f.path}\0{f.rule}\0{f.message}")[:16]


def load_baseline(path: str) -> Set[str]:
  with open(path, encoding="utf-8") as f:
    data = json.load(f)
  if data.get("schema") != BASELINE_SCHEMA:
    raise ValueError(f"{path}: not a {BASELINE_SCHEMA} file")
  return set(data.get("fingerprints", []))


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
  data = {"schema": BASELINE_SCHEMA,
          "fingerprints": sorted({finding_fingerprint(f)
                                  for f in findings})}
  with open(path, "w", encoding="utf-8") as f:
    json.dump(data, f, indent=1, sort_keys=True)
    f.write("\n")


# --------------------------------------------------------------------
# The engine proper.

def _known_mesh_axes(gin_files: Sequence[str]) -> Set[str]:
  """Axis vocabulary: DEFAULT_AXES + every discovered config + the
  repo's own shipped configs (linting one .py file must still know the
  axes configs elsewhere declare — lint.run's long-standing rule)."""
  from tensor2robot_tpu.analysis import spec_check
  package_dir = os.path.dirname(os.path.abspath(__file__))
  _, repo_gin = discover([os.path.dirname(package_dir)])
  return spec_check.known_mesh_axes(sorted(set(gin_files) | set(repo_gin)))


def run_engine(paths: Sequence[str],
               cache_path: Optional[str] = None,
               changed_only: bool = False) -> EngineResult:
  """Runs every registered file rule over `paths`.

  `cache_path` enables the incremental mode; `changed_only`
  additionally restricts the report to files whose content hash moved
  (and allows .gin cache reuse — see the module docstring caveat).
  """
  load_builtin_rules()
  wall_start = time.perf_counter()
  py_files, gin_files = discover(list(paths))
  mesh_axes = _known_mesh_axes(gin_files)
  rules = _REGISTRY
  py_rules = [rules[name] for name in CHECK_ORDER
              if name in rules and rules[name].kind in ("py", "native")]
  gin_rules = [r for r in rules.values() if r.kind == "gin"]

  cache: Optional[_Cache] = None
  if cache_path:
    rule_ids = [info.id for info in rule_infos()]
    cache = _Cache(cache_path, rule_ids,
                   vocab_digest=_sha256(",".join(sorted(mesh_axes))))

  findings: List[Finding] = []
  suppressed: List[Tuple[Finding, int]] = []
  changed_files: Set[str] = set()
  parse_ms = 0.0
  rules_ms = 0.0
  parses = 0
  cache_hits = 0

  def _record(path: str, kept: List[Finding],
              supp: List[Tuple[Finding, int]], fresh: bool,
              digest: Optional[str]) -> None:
    nonlocal cache_hits
    if fresh:
      changed_files.add(path)
      if cache is not None and digest is not None:
        cache.store(path, digest, kept, supp)
    else:
      cache_hits += 1
    # Inclusion is decided per CHECKED file (a config finding may point
    # at an included path — it still belongs to the includer's report).
    if fresh or not changed_only:
      findings.extend(kept)
      suppressed.extend(supp)

  for path in gin_files:
    digest = _gin_digest(path) if cache is not None else None
    # Config findings depend on the importable module registry, not
    # just the file — cached .gin results are only trusted on the
    # explicit --changed-only fast path.
    if cache is not None and changed_only and digest is not None:
      hit = cache.lookup(path, digest)
      if hit is not None:
        _record(path, hit[0], hit[1], fresh=False, digest=digest)
        continue
    t0 = time.perf_counter()
    gin_findings: List[Finding] = []
    for rule in gin_rules:
      if rule.applies_to(path):
        gin_findings.extend(rule.check(  # self-filtered by config_check
            FileContext(path, "", None, mesh_axes)))
    rules_ms += (time.perf_counter() - t0) * 1e3
    _record(path, gin_findings, [], fresh=True, digest=digest)

  for path in py_files:
    with open(path) as f:
      source = f.read()
    digest = _sha256(source) if cache is not None else None
    if cache is not None and digest is not None:
      hit = cache.lookup(path, digest)
      if hit is not None:
        _record(path, hit[0], hit[1], fresh=False, digest=digest)
        continue
    t0 = time.perf_counter()
    try:
      tree = ast.parse(source, filename=path)
    except SyntaxError as e:
      parse_ms += (time.perf_counter() - t0) * 1e3
      parses += 1
      # The one finding that is never suppressible: an unparseable file
      # has no trustworthy comment lines (tracer_check's old contract).
      _record(path,
              [Finding(path, e.lineno or 0, _PARSE_ERROR_RULE,
                       f"syntax error: {e.msg}")],
              [], fresh=True, digest=digest)
      continue
    parse_ms += (time.perf_counter() - t0) * 1e3
    parses += 1
    ctx = FileContext(path, source, tree, mesh_axes)
    t0 = time.perf_counter()
    raw = _run_py_rules(ctx, py_rules)
    supps = load_suppressions(source)
    kept: List[Finding] = []
    supp: List[Tuple[Finding, int]] = []
    for f_ in raw:
      at = supps.match(f_.line, f_.rule, f_.end_line)
      if at is None:
        kept.append(f_)
      else:
        supp.append((f_, at))
    rules_ms += (time.perf_counter() - t0) * 1e3
    _record(path, kept, supp, fresh=True, digest=digest)

  if cache is not None:
    cache.save()

  key = lambda f: (f.path, f.line, f.rule)  # noqa: E731
  result = EngineResult(
      findings=sorted(findings, key=key),
      suppressed=sorted(suppressed, key=lambda pair: key(pair[0])),
      stats={
          "files": len(py_files) + len(gin_files),
          "py_files": len(py_files),
          "gin_files": len(gin_files),
          "parses": parses,
          "parse_ms": round(parse_ms, 3),
          "rules_ms": round(rules_ms, 3),
          "wall_ms": round((time.perf_counter() - wall_start) * 1e3, 3),
          "cache_hits": cache_hits,
      })
  return result
