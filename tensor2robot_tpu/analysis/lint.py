"""graftlint CLI: run every static analyzer over configs and sources.

Usage (from the repo root):

  python -m tensor2robot_tpu.analysis.lint tensor2robot_tpu scripts
  python -m tensor2robot_tpu.analysis.lint --json some/file.py
  python -m tensor2robot_tpu.analysis.lint --list-rules
  python -m tensor2robot_tpu.analysis.lint --cache-file .lintcache \
      --changed-only tensor2robot_tpu

Thin shell over `analysis/engine.py`: the rule registry supplies the
checkers, the engine parses each file ONCE and runs every registered
rule over the shared tree (the old layout re-parsed every file per
checker — ~10x the parses), and this module owns argv/exit-code/output
concerns only. Mesh axis names are still collected from ALL discovered
configs before any Python file is checked, so spec annotations are
validated against the full declared vocabulary. Exits non-zero iff
findings remain after `# graftlint: disable=` suppressions.

Output contracts:

* plain text — byte-stable `path:line: [rule] message` lines (existing
  scripts parse this; the engine parity test pins the findings
  themselves byte-identical to the per-checker pipeline);
* `--json` — one JSON object per line with `severity` (from the rule
  registry) and suppression provenance: suppressed findings are
  emitted too, with `"suppressed": true` and `"suppressed_by": <line
  of the disable comment>` (exit code counts only unsuppressed ones);
* `--list-rules` — the catalog, generated from the registry
  (docs/ARCHITECTURE.md renders the same registry; a test pins them);
* `--stats` — `lint/files`, `lint/parse_ms`, `lint/rules_ms` on
  stderr; `--runs PATH` appends the same block to a runs.jsonl so lint
  latency is diff-gated like every other bench family;
* `--baseline` / `--write-baseline` — accept today's findings, gate
  only new ones (fingerprints are line-number-independent);
* `--cache-file` / `--changed-only` — content-hash incremental mode
  (`scripts/lint.sh --changed` is the CI entry point).

No JAX backend is ever initialized (tests/test_static_analysis.py runs
this CLI under a poisoned JAX_PLATFORMS to prove it); `scripts/lint.sh`
additionally pins JAX_PLATFORMS=cpu as belt-and-braces for interactive
use on the tunnel machine.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from tensor2robot_tpu.analysis import engine as engine_lib
from tensor2robot_tpu.analysis.findings import Finding

__all__ = ["run", "main"]

# Back-compat alias: callers (and tests) reached lint._discover.
_discover = engine_lib.discover


def run(paths: List[str]) -> List[Finding]:
  """Runs all analyzers; returns every unsuppressed finding."""
  return engine_lib.run_engine(paths).findings


def _finding_json(finding: Finding, suppressed_by: Optional[int] = None
                  ) -> str:
  record = {"path": finding.path, "line": finding.line,
            "rule": finding.rule,
            "severity": engine_lib.severity_of(finding.rule),
            "message": finding.message,
            "suppressed": suppressed_by is not None}
  if suppressed_by is not None:
    record["suppressed_by"] = suppressed_by
  return json.dumps(record)


def _append_runs_record(runs_path: str, stats: dict,
                        finding_count: int) -> None:
  """One runs.jsonl bench record carrying the lint telemetry block —
  `graftscope diff` gates lint_parse_ms/lint_rules_ms like any other
  wall-clock metric (runlog.DEFAULT_THRESHOLDS)."""
  from tensor2robot_tpu.obs import runlog
  record = runlog.make_record(
      "bench",
      bench={"name": "lint", "unit": "ms",
             "lint_parse_ms": stats["parse_ms"],
             "lint_rules_ms": stats["rules_ms"]},
      extra={"lint": {"files": stats["files"],
                      "py_files": stats["py_files"],
                      "gin_files": stats["gin_files"],
                      "parses": stats["parses"],
                      "parse_ms": stats["parse_ms"],
                      "rules_ms": stats["rules_ms"],
                      "wall_ms": stats["wall_ms"],
                      "cache_hits": stats["cache_hits"],
                      "findings": finding_count}})
  runlog.append_record(runs_path, record)


def main(argv: List[str] = None) -> int:
  parser = argparse.ArgumentParser(
      prog="python -m tensor2robot_tpu.analysis.lint",
      description="graftlint: static analysis for configs, specs, and "
                  "tracer hygiene (no JAX backend use).")
  parser.add_argument("paths", nargs="*",
                      default=["tensor2robot_tpu", "scripts"],
                      help="files or directories to lint "
                           "(default: tensor2robot_tpu scripts)")
  parser.add_argument("--json", action="store_true", dest="as_json",
                      help="emit findings as JSON lines (includes rule "
                           "severity and suppression provenance)")
  parser.add_argument("--list-rules", action="store_true",
                      help="print the rule catalog (generated from the "
                           "rule registry) and exit")
  parser.add_argument("--stats", action="store_true",
                      help="print lint files/parse/rule timing to stderr")
  parser.add_argument("--runs", metavar="PATH",
                      help="append a lint telemetry record to this "
                           "runs.jsonl (diff-gated like bench metrics)")
  parser.add_argument("--baseline", metavar="PATH",
                      help="suppress findings recorded in this baseline "
                           "file (gate only NEW findings)")
  parser.add_argument("--write-baseline", metavar="PATH",
                      help="write current findings to a baseline file "
                           "and exit 0")
  parser.add_argument("--cache-file", metavar="PATH",
                      help="incremental mode: reuse findings of files "
                           "whose content hash is unchanged")
  parser.add_argument("--changed-only", action="store_true",
                      help="with --cache-file: report only files whose "
                           "content hash moved (CI fast path; .gin "
                           "results may be stale vs module edits — run "
                           "a full lint before release)")
  args = parser.parse_args(argv)
  if args.list_rules:
    print(engine_lib.catalog_text(), end="")
    return 0
  if args.changed_only and not args.cache_file:
    print("graftlint: --changed-only requires --cache-file",
          file=sys.stderr)
    return 2
  missing = [p for p in args.paths if not os.path.exists(p)]
  if missing:
    print(f"graftlint: no such path: {', '.join(missing)}",
          file=sys.stderr)
    return 2
  # An explicitly named file the analyzers would silently skip is an
  # operator error, not a clean result.
  unsupported = [p for p in args.paths
                 if os.path.isfile(p) and not p.endswith((".py", ".gin"))]
  if unsupported:
    print("graftlint: unsupported file type (want .py or .gin): "
          f"{', '.join(unsupported)}", file=sys.stderr)
    return 2
  result = engine_lib.run_engine(list(args.paths),
                                 cache_path=args.cache_file,
                                 changed_only=args.changed_only)
  findings = result.findings
  if args.write_baseline:
    engine_lib.write_baseline(args.write_baseline, findings)
    print(f"graftlint: baseline with {len(findings)} finding(s) "
          f"written to {args.write_baseline}", file=sys.stderr)
    return 0
  if args.baseline:
    try:
      known = engine_lib.load_baseline(args.baseline)
    except (OSError, ValueError) as e:
      print(f"graftlint: cannot read baseline: {e}", file=sys.stderr)
      return 2
    findings = [f for f in findings
                if engine_lib.finding_fingerprint(f) not in known]
  for finding in findings:
    if args.as_json:
      print(_finding_json(finding))
    else:
      print(finding)
  if args.as_json:
    # Suppression provenance: what `# graftlint: disable` comments ate,
    # and where — so a JSON consumer can audit the suppressions too.
    for finding, at_line in result.suppressed:
      print(_finding_json(finding, suppressed_by=at_line))
  if args.stats:
    s = result.stats
    print(f"graftlint: lint/files={s['files']} "
          f"lint/parse_ms={s['parse_ms']:.1f} "
          f"lint/rules_ms={s['rules_ms']:.1f} "
          f"(parses={s['parses']}, cache_hits={s['cache_hits']}, "
          f"wall_ms={s['wall_ms']:.1f})", file=sys.stderr)
  if args.runs:
    _append_runs_record(args.runs, result.stats, len(findings))
  if findings:
    print(f"graftlint: {len(findings)} finding(s)", file=sys.stderr)
    return 1
  return 0


if __name__ == "__main__":
  sys.exit(main())
