"""graftlint CLI: run every static analyzer over configs and sources.

Usage (from the repo root):

  python -m tensor2robot_tpu.analysis.lint tensor2robot_tpu scripts
  python -m tensor2robot_tpu.analysis.lint --json some/file.py
  python -m tensor2robot_tpu.analysis.lint --list-rules

Walks the given files/directories: `.gin` files go through the config
checker, `.py` files through the tracer-hygiene and spec/sharding
checkers. Mesh axis names are collected from ALL discovered configs
before any Python file is checked, so spec annotations are validated
against the full declared vocabulary. Exits non-zero iff findings
remain after `# graftlint: disable=` suppressions.

No JAX backend is ever initialized (tests/test_static_analysis.py runs
this CLI under a poisoned JAX_PLATFORMS to prove it); `scripts/lint.sh`
additionally pins JAX_PLATFORMS=cpu as belt-and-braces for interactive
use on the tunnel machine.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Tuple

from tensor2robot_tpu.analysis import (cache_check, config_check,
                                       fleet_check, forge_check,
                                       loop_check, native_check, pp_check,
                                       retry_check, session_check,
                                       spec_check, thread_check,
                                       tracer_check)
from tensor2robot_tpu.analysis.findings import Finding

__all__ = ["run", "main"]

_RULE_CATALOG = """\
config rules (.gin):
  parse-error            file does not parse
  broken-import          an `import a.b.c` line fails to import
  unknown-configurable   Name.param / @Name resolves to no configurable
  missing-import         Name resolves, but only via import pollution —
                         no import line (nor entry binary) covers its
                         defining module in a fresh process
  unknown-parameter      Name has no parameter `param`
  duplicate-binding      same (scope, Name, param) bound twice in one
                         file (include-then-override is idiomatic)
  undefined-macro        %MACRO referenced but never defined
  type-mismatch          literal value contradicts annotation/default

tracer rules (.py):
  block-until-ready      jax.block_until_ready outside utils/backend.py
  import-time-backend    backend-touching call at module import level
  host-sync-in-jit       .item() / float() / np.asarray() on traced
                         values inside a jitted function
  impure-in-jit          time.time / stateful np.random inside a jitted
                         function
  device-timing          time.time/perf_counter window around device
                         dispatch without a host-fetch barrier (measures
                         dispatch, not execution, over the tunnel);
                         obs/ and utils/backend.py are exempt

cache rules (.py):
  cache-key-missing-component  a `cache_key(...)` call site omits one
                         of the mandatory executable-cache key
                         components (jaxpr fingerprint, aval shapes/
                         dtypes, mesh topology, backend version,
                         donation layout, static args) — an under-keyed
                         cache can serve a mismatched executable;
                         a `**splat` call site is accepted

pipeline rules (.py):
  pp-schedule-unaudited  a `make_pipelined_train_step(...)` call site
                         that passes no `audit_name=` (or an explicit
                         None) — the step skips the analyze_jit path,
                         so per-stage donation bytes and the
                         pp/bubble_fraction schedule telemetry never
                         reach runs.jsonl; a `**splat` call site is
                         accepted

session rules (.py):
  session-state-leak     a decode-step call site that discards the
                         returned session state (bare expression, or
                         the state slot bound to an underscore name) —
                         later ticks replay the stale cache — or an
                         np.asarray/device_get host fetch of a
                         session_state/arena value, which re-buys the
                         stateless per-tick cost (and ~1.5 s per eager
                         fetch over the tunnel)

retry rules (.py, serving//data/ hot paths only):
  bare-retry-rule        a for/while loop containing BOTH a constant
                         `time.sleep(<literal>)` AND a broad
                         except-swallow (bare `except:` or
                         `except (Base)Exception:` with a pass/continue
                         body) — a hand-rolled retry with no jitter,
                         deadline budget, or telemetry; migrate to
                         `utils.retry.RetryPolicy` or suppress with
                         justification

fleet rules (.py):
  fleet-replica-unjoined a `ServingFleet(...)` construction site whose
                         owning scope never calls close()/drain() on
                         it, uses it as a context manager, returns it,
                         or stores it on self — the fleet's
                         per-replica batcher workers are never joined
                         (the tunnel-safe join discipline the batchers
                         follow, mechanized for the fleet layer)

forge rules (.py):
  warmup-unforgeable     a BucketedEngine/SessionEngine construction
                         whose `buckets=` is computed at runtime —
                         graftforge cannot enumerate those rungs from
                         the config/specs, so the compile farm cannot
                         warm them and their first live request pays
                         the 20-40 s tunnel compile; literal ladders,
                         bucket_ladder(...), module-level literal
                         constants, and `**splat` sites are accepted
                         (route live ladder changes through
                         ServingFleet.rollout(ladder=...))

loop rules (.py, the loop/ package only):
  unsupervised-loop-worker a bare threading.Thread construction in a
                         loop-package module other than supervisor.py —
                         the worker is outside the supervisor's restart/
                         heartbeat/escalation machinery (dies silently,
                         hangs invisibly); register it with
                         Supervisor.spawn instead

thread rules (.py):
  thread-stage-missing-close     a class starts a threading.Thread but
                         defines no close() — its worker can never be
                         stopped/joined (the tunnel-wedging hazard);
                         loader/stage classes must expose close()
  thread-stage-missing-backstop  such a class has close() but neither
                         __enter__ (context-manager use) nor a
                         weakref.finalize backstop — an abandoned
                         instance leaks its worker until process exit

native rules (native/__init__.py ↔ native/*.cc):
  native-binding-missing a .cc source exports a `t2r_*` symbol the
                         ctypes wrapper never references
  native-binding-unknown the wrapper references a `t2r_*` name no .cc
                         source defines

spec rules (.py):
  unknown-mesh-axis      TensorSpec.sharding names an undeclared axis
  duplicate-sharding-axis  same axis twice in one annotation
  sharding-rank-mismatch more sharding entries than spec dims
  sharding-conflict      feature vs label sharding disagreement
                         (structure-level API only)

Suppress a finding with a trailing `# graftlint: disable=<rule>`.
"""

_SKIP_DIRS = {"__pycache__", ".git", "node_modules", ".ipynb_checkpoints"}


def _discover(paths: List[str]) -> Tuple[List[str], List[str]]:
  """(.py files, .gin files) under the given files/directories."""
  py_files: List[str] = []
  gin_files: List[str] = []
  for path in paths:
    if os.path.isfile(path):
      (py_files if path.endswith(".py") else
       gin_files if path.endswith(".gin") else []).append(path)
      continue
    for dirpath, dirnames, filenames in os.walk(path):
      dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
      for name in sorted(filenames):
        if name.endswith(".py"):
          py_files.append(os.path.join(dirpath, name))
        elif name.endswith(".gin"):
          gin_files.append(os.path.join(dirpath, name))
  return py_files, gin_files


def run(paths: List[str]) -> List[Finding]:
  """Runs all analyzers; returns every unsuppressed finding."""
  py_files, gin_files = _discover(paths)
  findings: List[Finding] = []
  # The axis vocabulary always includes the repo's own shipped configs,
  # not just configs under `paths` — otherwise linting a single .py file
  # would flag axes (e.g. 'sp', 'pp') that a config elsewhere declares.
  package_dir = os.path.dirname(os.path.abspath(__file__))
  _, repo_gin = _discover([os.path.dirname(package_dir)])
  mesh_axes = spec_check.known_mesh_axes(
      sorted(set(gin_files) | set(repo_gin)))
  for path in gin_files:
    findings.extend(config_check.check_config_file(path))
  for path in py_files:
    findings.extend(tracer_check.check_python_file(path))
    findings.extend(spec_check.check_python_file(path, mesh_axes))
    findings.extend(cache_check.check_python_file(path))
    findings.extend(pp_check.check_python_file(path))
    findings.extend(session_check.check_python_file(path))
    findings.extend(fleet_check.check_python_file(path))
    findings.extend(forge_check.check_python_file(path))
    findings.extend(retry_check.check_python_file(path))
    findings.extend(thread_check.check_python_file(path))
    findings.extend(loop_check.check_python_file(path))
    # A native-package wrapper pulls in the export/binding coverage
    # check for its whole directory (.cc sources aren't walked
    # directly — the wrapper is the unit whose drift matters).
    if (os.path.basename(path) == "__init__.py"
        and os.path.basename(os.path.dirname(path)) == "native"):
      findings.extend(native_check.check_native_bindings(
          os.path.dirname(path)))
  return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def main(argv: List[str] = None) -> int:
  parser = argparse.ArgumentParser(
      prog="python -m tensor2robot_tpu.analysis.lint",
      description="graftlint: static analysis for configs, specs, and "
                  "tracer hygiene (no JAX backend use).")
  parser.add_argument("paths", nargs="*",
                      default=["tensor2robot_tpu", "scripts"],
                      help="files or directories to lint "
                           "(default: tensor2robot_tpu scripts)")
  parser.add_argument("--json", action="store_true", dest="as_json",
                      help="emit findings as JSON lines")
  parser.add_argument("--list-rules", action="store_true",
                      help="print the rule catalog and exit")
  args = parser.parse_args(argv)
  if args.list_rules:
    print(_RULE_CATALOG, end="")
    return 0
  missing = [p for p in args.paths if not os.path.exists(p)]
  if missing:
    print(f"graftlint: no such path: {', '.join(missing)}",
          file=sys.stderr)
    return 2
  # An explicitly named file the analyzers would silently skip is an
  # operator error, not a clean result.
  unsupported = [p for p in args.paths
                 if os.path.isfile(p) and not p.endswith((".py", ".gin"))]
  if unsupported:
    print("graftlint: unsupported file type (want .py or .gin): "
          f"{', '.join(unsupported)}", file=sys.stderr)
    return 2
  findings = run(list(args.paths))
  for finding in findings:
    if args.as_json:
      print(json.dumps({"path": finding.path, "line": finding.line,
                        "rule": finding.rule,
                        "message": finding.message}))
    else:
      print(finding)
  if findings:
    print(f"graftlint: {len(findings)} finding(s)", file=sys.stderr)
    return 1
  return 0


if __name__ == "__main__":
  sys.exit(main())
