"""graftlint: a `trace_ctx` parameter accepted then dropped.

graftrace (`obs/graftrace.py`) threads request/causality contexts
across the serving and loop layers two ways: the thread-local
(`activate`/`current`) for same-thread propagation, and an explicit
`trace_ctx` parameter at hand-off seams where the producing thread is
not the consuming one (`ReplayRecordSink.append_episode` is the
canonical carrier). The failure mode this rule mechanizes: a seam grows
a `trace_ctx` parameter, callers dutifully pass their context, and the
body never touches it — every caller's causal edge silently evaporates,
the merged timeline shows orphaned spans, and nothing errors. Exactly
the class of bug (dropped-on-the-floor telemetry plumbing) that is
invisible until someone needs the trace that isn't there.

Rule `trace-context-dropped` flags a function (sync or async) that
declares a parameter named `trace_ctx` whose body never references
`trace_ctx` — not to record it, not to forward it, not to default it
into the thread-local. A nested function closing over the name counts
as a use (forwarding through a worker closure is the normal shape).
Suppress a deliberate sink (e.g. an interface-compat stub) with a
trailing `# graftlint: disable=trace-context-dropped`.

Pure AST analysis, backend-free like every graftlint rule (pattern of
`fleet_check.py` / `thread_check.py`).
"""

from __future__ import annotations

import ast
from typing import List

from tensor2robot_tpu.analysis import engine as engine_lib
from tensor2robot_tpu.analysis.findings import (Finding, filter_findings,
                                                load_suppressions)

__all__ = ["check_python_source", "check_python_file"]

_RULE = "trace-context-dropped"
_PARAM = "trace_ctx"


def _declares_param(node: ast.AST) -> bool:
  args = node.args
  named = list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
  if args.vararg is not None:
    named.append(args.vararg)
  if args.kwarg is not None:
    named.append(args.kwarg)
  return any(a.arg == _PARAM for a in named)


def _body_uses_param(node: ast.AST) -> bool:
  """Whether the function BODY references the name (the walk covers
  nested defs too — a closure forwarding the context is a use; the
  declaring function's own parameter list is not part of its body)."""
  for stmt in node.body:
    for inner in ast.walk(stmt):
      if isinstance(inner, ast.Name) and inner.id == _PARAM:
        return True
      # A nested def RE-DECLARING trace_ctx shadows the outer one; its
      # internal uses belong to the inner scope, but the engine visits
      # every FunctionDef in the shared walk anyway, so the inner
      # function is judged on its own. Over-approximating here (a
      # shadowed use counts for the outer scope too) only costs a
      # missed finding on a pathological shape, never a false positive.
  return False


def _check_function(path: str, node: ast.AST) -> List[Finding]:
  if not _declares_param(node):
    return []
  if _body_uses_param(node):
    return []
  return [Finding(
      path=path, line=node.lineno, rule=_RULE,
      end_line=getattr(node, "end_lineno", node.lineno) or node.lineno,
      message=(f"function {node.name!r} declares a `trace_ctx` "
               "parameter but its body never references it: every "
               "caller's causal edge is silently dropped (the merged "
               "timeline shows orphaned spans). Record it, forward it, "
               "or fall back to `graftrace.current()` — or suppress a "
               "deliberate interface-compat sink."))]


def check_python_tree(path: str, tree: ast.Module) -> List[Finding]:
  """Raw (unfiltered) findings over an already-parsed module (the
  engine's entry point; `check_python_source` wraps it with a parse)."""
  findings: List[Finding] = []
  for node in ast.walk(tree):
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
      findings.extend(_check_function(path, node))
  return findings


def check_python_source(path: str, source: str) -> List[Finding]:
  try:
    tree = ast.parse(source, filename=path)
  except SyntaxError:
    return []  # the engine reports unparseable files
  return check_python_tree(path, tree)


def check_python_file(path: str) -> List[Finding]:
  with open(path, encoding="utf-8", errors="replace") as f:
    source = f.read()
  return filter_findings(check_python_source(path, source),
                         load_suppressions(source))


def _visit_function(ctx: engine_lib.FileContext,
                    node: ast.AST) -> List[Finding]:
  return _check_function(ctx.path, node)


engine_lib.register(engine_lib.Rule(
    name="tracectx", kind="py", scope=".py", family="tracectx",
    infos=(engine_lib.RuleInfo(
        id=_RULE,
        doc=("a function declaring a `trace_ctx` parameter\n"
             "whose body never references it: callers pass\n"
             "their graftrace context and the causal edge is\n"
             "silently dropped — the merged timeline shows\n"
             "orphaned spans with nothing erroring"),
        meaning=("a function declaring a `trace_ctx` parameter whose "
                 "body never references it — callers' graftrace "
                 "causal edges are silently dropped and the merged "
                 "timeline shows orphaned spans")),),
    visitors={ast.FunctionDef: _visit_function,
              ast.AsyncFunctionDef: _visit_function}))
