"""AST lint for axon-tunnel and jit-tracing hazards in Python sources.

Mechanizes the CLAUDE.md tunnel rules so they are enforced, not remembered:

* `block-until-ready`   — `jax.block_until_ready` anywhere outside
  `utils/backend.py`. Over the axon tunnel it is NOT a barrier (returns
  before the remote computation finishes; NOTES_r2.md) — use
  `utils.backend.sync` / `state_barrier`.
* `import-time-backend` — backend-touching calls at module import level
  (`jax.devices`, `jax.default_backend`, `jax.device_put`, any
  `jax.numpy` / `jax.random` / `jax.nn` call, …). Importing such a module
  initializes the backend — on this machine, the TPU tunnel — as a side
  effect of `import`. Module/class-level statements and function default
  arguments count; `if __name__ == "__main__"` blocks do not (script
  mains may touch hardware deliberately).
* `host-sync-in-jit`    — `.item()`, or `float()`/`int()`/`bool()`/
  `np.asarray()`/`np.array()` applied to a traced argument, inside a
  `jax.jit`/`pjit`-traced function: a host sync that fails or silently
  constant-folds under tracing.
* `impure-in-jit`       — `time.time`-family calls or stateful global
  `np.random.*` inside a traced function: traced once, frozen forever.
* `device-timing`       — a `time.time()`/`time.perf_counter()` clock
  pair (``t0 = time.perf_counter()`` … ``time.perf_counter() - t0``)
  whose window contains a device-dispatching call (`jnp.*`,
  `jax.lax.*`, `jax.device_put`, …) but no host-fetch barrier
  (`backend.sync`/`state_barrier`, `np.asarray`, `jax.device_get`,
  `.item()`, `float()`): over the axon tunnel that measures DISPATCH,
  not execution (NOTES_r2.md: a 58 ms step "completed" in 0.9 ms).
  `obs/` and `utils/backend.py` are exempt — they are the two places
  allowed to own clocks around device code (the barrier discipline
  lives there).

A function is "traced" when decorated with `jax.jit`/`pjit` (directly or
via `functools.partial`), or passed by name/lambda to a `jax.jit(...)` /
`pjit(...)` call in an enclosing scope. Nested defs inherit tracedness.

Suppress with a trailing `# graftlint: disable=<rule>` comment.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tensor2robot_tpu.analysis import engine as engine_lib
from tensor2robot_tpu.analysis.findings import (Finding, filter_findings,
                                                load_suppressions)

__all__ = ["check_python_source", "check_python_file"]

_JIT_NAMES = {
    "jax.jit", "jax.pjit", "jax.experimental.pjit.pjit",
    "jax.experimental.pjit",
}
_PARTIAL_NAMES = {"functools.partial", "partial"}

# Calls that initialize / query the backend or create device values.
_BACKEND_CALLS = {
    "jax.devices", "jax.local_devices", "jax.device_count",
    "jax.local_device_count", "jax.default_backend", "jax.process_count",
    "jax.process_index", "jax.device_put", "jax.device_get",
    "jax.live_arrays", "jax.block_until_ready",
}
# Any call through these prefixes executes an op (= backend init when at
# import time).
_BACKEND_PREFIXES = ("jax.numpy.", "jax.random.", "jax.nn.", "jax.lax.")

_TIME_CALLS = {
    "time.time", "time.perf_counter", "time.monotonic",
    "time.process_time", "time.time_ns", "time.perf_counter_ns",
}
# numpy.random entry points that are NOT the stateful global RNG.
_NP_RANDOM_SAFE = {
    "RandomState", "Generator", "default_rng", "SeedSequence", "PCG64",
    "MT19937", "Philox", "SFC64", "BitGenerator",
}
_HOST_CONVERTERS = {"float", "int", "bool"}
_NP_HOST_CONVERTERS = {"numpy.asarray", "numpy.array", "numpy.asanyarray"}

# device-timing rule vocabulary: calls that dispatch device work (async
# over the tunnel) vs calls that establish device completion on the host.
_DISPATCH_CALLS = {"jax.device_put"}
_BARRIER_CALLS = _NP_HOST_CONVERTERS | {"jax.device_get", "float", "int"}
# Method/attribute names that barrier regardless of the object they hang
# off (backend.sync, backend_lib.state_barrier, arr.item(), and the
# backend timing helpers, which barrier internally).
_BARRIER_ATTRS = {"sync", "state_barrier", "block_until_ready", "item",
                  "time_op", "time_train_steps", "time_train_steps_halves"}


def _import_aliases(tree: ast.AST) -> Dict[str, str]:
  """name -> dotted module/attr path, from every import in the file."""
  aliases: Dict[str, str] = {}
  for node in ast.walk(tree):
    if isinstance(node, ast.Import):
      for alias in node.names:
        aliases[alias.asname or alias.name.split(".", 1)[0]] = (
            alias.name if alias.asname else alias.name.split(".", 1)[0])
    elif isinstance(node, ast.ImportFrom) and not node.level:
      for alias in node.names:
        if node.module:
          aliases[alias.asname or alias.name] = (
              f"{node.module}.{alias.name}")
  return aliases


def _qualified(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
  """Dotted name of an expression like `jnp.asarray` -> 'jax.numpy.asarray'."""
  parts: List[str] = []
  while isinstance(node, ast.Attribute):
    parts.append(node.attr)
    node = node.value
  if not isinstance(node, ast.Name):
    return None
  root = aliases.get(node.id, node.id)
  return ".".join([root] + list(reversed(parts)))


def _root_name(node: ast.AST) -> Optional[str]:
  """Base variable of `x`, `x.attr`, `x[i]`, `x.attr[i]` chains."""
  while isinstance(node, (ast.Attribute, ast.Subscript)):
    node = node.value
  return node.id if isinstance(node, ast.Name) else None


def _is_jit_expr(node: ast.AST, aliases: Dict[str, str]) -> bool:
  """True for `jax.jit`, `pjit`, `functools.partial(jax.jit, ...)`."""
  q = _qualified(node, aliases)
  if q in _JIT_NAMES or (q is not None and q.split(".")[-1] == "pjit"):
    return True
  if isinstance(node, ast.Call):
    fq = _qualified(node.func, aliases)
    if fq in _JIT_NAMES or (fq is not None and fq.split(".")[-1] == "pjit"):
      return True  # jax.jit(static_argnums=...) factory style
    if fq in _PARTIAL_NAMES and node.args and _is_jit_expr(node.args[0],
                                                           aliases):
      return True
  return False


class _TracedCollector(ast.NodeVisitor):
  """Finds function nodes whose bodies run under jit tracing."""

  def __init__(self, aliases: Dict[str, str]):
    self.aliases = aliases
    self.traced: List[ast.AST] = []
    # Stack of {local def name -> node} scopes for resolving jax.jit(f).
    self._scopes: List[Dict[str, ast.AST]] = [{}]

  def _handle_def(self, node):
    self._scopes[-1][node.name] = node
    if any(_is_jit_expr(d, self.aliases) for d in node.decorator_list):
      self.traced.append(node)
    self._scopes.append({})
    self.generic_visit(node)
    self._scopes.pop()

  visit_FunctionDef = _handle_def
  visit_AsyncFunctionDef = _handle_def

  def visit_ClassDef(self, node):
    self._scopes.append({})
    self.generic_visit(node)
    self._scopes.pop()

  def visit_Call(self, node):
    if _is_jit_expr(node.func, self.aliases) and node.args:
      target = node.args[0]
      if isinstance(target, ast.Lambda):
        self.traced.append(target)
      elif isinstance(target, ast.Name):
        for scope in reversed(self._scopes):
          if target.id in scope:
            self.traced.append(scope[target.id])
            break
    self.generic_visit(node)


def _walk_traced(node: ast.AST, aliases: Dict[str, str], path: str,
                 findings: List[Finding]) -> None:
  """Applies the in-jit rules over one traced function's subtree."""
  params: Set[str] = set()

  def _add_params(fn_node) -> None:
    if isinstance(fn_node, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
      a = fn_node.args
      for arg in (list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
                  + ([a.vararg] if a.vararg else [])
                  + ([a.kwarg] if a.kwarg else [])):
        params.add(arg.arg)

  _add_params(node)

  def _visit(n: ast.AST) -> None:
    if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
      _add_params(n)  # nested defs trace too; their args are tracers
    if isinstance(n, ast.Call):
      q = _qualified(n.func, aliases)
      if (isinstance(n.func, ast.Attribute) and n.func.attr == "item"
          and not n.args and not n.keywords):
        findings.append(Finding(
            path, n.lineno, "host-sync-in-jit",
            ".item() inside a jit-traced function is a host sync — "
            "return the array and convert outside the jit boundary",
            end_line=getattr(n, "end_lineno", 0) or 0))
      elif (q in _HOST_CONVERTERS or q in _NP_HOST_CONVERTERS) and n.args:
        root = _root_name(n.args[0])
        if root is not None and root in params:
          findings.append(Finding(
              path, n.lineno, "host-sync-in-jit",
              f"{q}() on traced argument {root!r} inside a jit-traced "
              "function forces a host sync (or silently freezes a "
              "tracer) — use jnp ops or move it outside the jit",
              end_line=getattr(n, "end_lineno", 0) or 0))
      elif q in _TIME_CALLS:
        findings.append(Finding(
            path, n.lineno, "impure-in-jit",
            f"{q}() inside a jit-traced function is evaluated once at "
            "trace time and frozen into the compiled program",
            end_line=getattr(n, "end_lineno", 0) or 0))
      elif (q is not None and q.startswith("numpy.random.")
            and q.split(".")[-1] not in _NP_RANDOM_SAFE):
        findings.append(Finding(
            path, n.lineno, "impure-in-jit",
            f"stateful {q}() inside a jit-traced function is drawn once "
            "at trace time and frozen — use jax.random with an explicit "
            "key", end_line=getattr(n, "end_lineno", 0) or 0))
    for child in ast.iter_child_nodes(n):
      _visit(child)

  for child in ast.iter_child_nodes(node):
    _visit(child)


def _check_import_time(tree: ast.Module, aliases: Dict[str, str],
                       path: str, findings: List[Finding]) -> None:
  """Flags backend-touching calls executed as a side effect of import."""

  def _is_main_guard(node: ast.AST) -> bool:
    return (isinstance(node, ast.If)
            and isinstance(node.test, ast.Compare)
            and isinstance(node.test.left, ast.Name)
            and node.test.left.id == "__name__")

  def _flag_calls(n: ast.AST) -> None:
    if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
      # Body runs later — but default arguments AND decorator
      # expressions evaluate at import time.
      defaults = list(n.args.defaults) + [d for d in n.args.kw_defaults
                                          if d is not None]
      if not isinstance(n, ast.Lambda):
        defaults.extend(n.decorator_list)
      for d in defaults:
        _flag_calls_expr(d)
      return
    if _is_main_guard(n):
      return
    if isinstance(n, ast.Call):
      _flag_call(n)
    for child in ast.iter_child_nodes(n):
      _flag_calls(child)

  def _flag_calls_expr(n: ast.AST) -> None:
    for sub in ast.walk(n):
      if isinstance(sub, ast.Call):
        _flag_call(sub)

  def _flag_call(n: ast.Call) -> None:
    q = _qualified(n.func, aliases)
    if q is None:
      return
    if q in _BACKEND_CALLS or q.startswith(_BACKEND_PREFIXES):
      findings.append(Finding(
          path, n.lineno, "import-time-backend",
          f"{q}() at module import level initializes the JAX backend "
          "(the axon TPU tunnel on this machine) as an import side "
          "effect — build the value lazily or use numpy",
          end_line=getattr(n, "end_lineno", 0) or 0))

  for stmt in tree.body:
    _flag_calls(stmt)


def _check_device_timing(tree: ast.Module, aliases: Dict[str, str],
                         path: str, findings: List[Finding]) -> None:
  """Flags host-clock windows around un-barriered device dispatches.

  Pattern: ``t0 = time.perf_counter()`` … ``time.perf_counter() - t0``
  within one scope, with a device-dispatching call between the two clock
  reads and no host-fetch barrier. Each function is its own scope
  (nested defs do not execute inside the enclosing window)."""

  def _is_clock_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and _qualified(node.func, aliases) in _TIME_CALLS)

  def _scope_statements(scope: ast.AST):
    """Yields every node in the scope, skipping nested function bodies."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
      node = stack.pop()
      yield node
      if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)):
        stack.extend(ast.iter_child_nodes(node))

  def _check_scope(scope: ast.AST) -> None:
    clock_assigns: Dict[str, List[int]] = {}
    closes: List[tuple] = []  # (varname, line, end_line)
    calls: List[tuple] = []  # (line, qualified, attr_name)
    for node in _scope_statements(scope):
      if (isinstance(node, ast.Assign) and _is_clock_call(node.value)
          and len(node.targets) == 1
          and isinstance(node.targets[0], ast.Name)):
        clock_assigns.setdefault(node.targets[0].id, []).append(node.lineno)
      elif (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub)
            and isinstance(node.right, ast.Name)
            and (_is_clock_call(node.left)
                 or isinstance(node.left, ast.Name))):
        closes.append((node.right.id, node.lineno,
                       getattr(node, "end_lineno", 0) or node.lineno))
      if isinstance(node, ast.Call):
        attr = (node.func.attr
                if isinstance(node.func, ast.Attribute) else None)
        calls.append((node.lineno, _qualified(node.func, aliases), attr))
    for var, line, end_line in closes:
      starts = [s for s in clock_assigns.get(var, []) if s < line]
      if not starts:
        continue
      start = max(starts)
      window = [(q, attr) for (call_line, q, attr) in calls
                if start < call_line <= end_line]
      dispatches = [q for q, _ in window if q is not None
                    and (q in _DISPATCH_CALLS
                         or q.startswith(_BACKEND_PREFIXES))]
      barriered = any((q in _BARRIER_CALLS if q is not None else False)
                      or attr in _BARRIER_ATTRS for q, attr in window)
      if dispatches and not barriered:
        findings.append(Finding(
            path, line, "device-timing",
            f"host-clock window (since line {start}) times "
            f"{dispatches[0]}() without a host-fetch barrier — over the "
            "axon tunnel this measures dispatch, not execution; use "
            "tensor2robot_tpu.utils.backend.time_op / "
            "time_train_steps (or end the window with backend.sync / "
            "np.asarray)", end_line=end_line))

  _check_scope(tree)
  for node in ast.walk(tree):
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
      _check_scope(node)


def check_python_tree(tree: ast.Module, path: str,
                      allow_block_until_ready: bool = False,
                      allow_device_timing: bool = False
                      ) -> List[Finding]:
  """Raw (unfiltered, unsorted) findings over an already-parsed module
  — the engine's entry point; `check_python_source` wraps it with the
  parse/filter/sort tail the standalone API always had."""
  aliases = _import_aliases(tree)
  findings: List[Finding] = []

  if not allow_device_timing:
    _check_device_timing(tree, aliases, path, findings)

  if not allow_block_until_ready:
    for node in ast.walk(tree):
      if (isinstance(node, ast.Call)
          and isinstance(node.func, (ast.Attribute, ast.Name))):
        q = _qualified(node.func, aliases)
        if (q == "jax.block_until_ready"
            or (isinstance(node.func, ast.Attribute)
                and node.func.attr == "block_until_ready")):
          findings.append(Finding(
              path, node.lineno, "block-until-ready",
              "jax.block_until_ready is NOT a barrier over the axon TPU "
              "tunnel (returns before the remote computation finishes) "
              "— use tensor2robot_tpu.utils.backend.sync / "
              "state_barrier",
              end_line=getattr(node, "end_lineno", 0) or 0))

  _check_import_time(tree, aliases, path, findings)

  collector = _TracedCollector(aliases)
  collector.visit(tree)
  seen_traced: Set[int] = set()
  for node in collector.traced:
    if id(node) in seen_traced:
      continue
    seen_traced.add(id(node))
    _walk_traced(node, aliases, path, findings)

  return findings


def check_python_source(text: str, path: str,
                        allow_block_until_ready: bool = False,
                        allow_device_timing: bool = False
                        ) -> List[Finding]:
  """Lints one Python source; returns (suppression-filtered) findings."""
  try:
    tree = ast.parse(text, filename=path)
  except SyntaxError as e:
    return [Finding(path, e.lineno or 0, "parse-error",
                    f"syntax error: {e.msg}")]
  findings = check_python_tree(
      tree, path, allow_block_until_ready=allow_block_until_ready,
      allow_device_timing=allow_device_timing)
  return sorted(filter_findings(findings, load_suppressions(text)),
                key=lambda f: (f.line, f.rule))


def path_exemptions(path: str) -> Tuple[bool, bool]:
  """(allow_block_until_ready, allow_device_timing) for one path —
  shared by `check_python_file` and the engine registration, so the
  exemption map cannot drift between the two call paths."""
  norm = path.replace("\\", "/")
  allow = norm.endswith("utils/backend.py")
  # obs/ owns the instrumentation clocks (its windows end in barriers by
  # design); backend.py owns the shared timing recipes.
  allow_timing = allow or "/obs/" in norm or norm.startswith("obs/")
  return allow, allow_timing


def check_python_file(path: str) -> List[Finding]:
  allow, allow_timing = path_exemptions(path)
  with open(path) as f:
    return check_python_source(f.read(), path, allow_block_until_ready=allow,
                               allow_device_timing=allow_timing)


def _engine_check(ctx) -> List[Finding]:
  allow, allow_timing = path_exemptions(ctx.path)
  return check_python_tree(ctx.tree, ctx.path,
                           allow_block_until_ready=allow,
                           allow_device_timing=allow_timing)


engine_lib.register(engine_lib.Rule(
    name="tracer", kind="py", scope=".py", family="tracer",
    infos=(
        engine_lib.RuleInfo(
            id="block-until-ready",
            doc="jax.block_until_ready outside utils/backend.py",
            meaning=("`jax.block_until_ready` outside `utils/backend.py` "
                     "— not a tunnel barrier, use `backend.sync`")),
        engine_lib.RuleInfo(
            id="import-time-backend",
            doc="backend-touching call at module import level",
            meaning=("backend-touching call (`jax.devices`, any "
                     "`jnp`/`jax.random`/`jax.nn` call, fn default args) "
                     "at module import level")),
        engine_lib.RuleInfo(
            id="host-sync-in-jit",
            doc=(".item() / float() / np.asarray() on traced\n"
                 "values inside a jitted function"),
            meaning=("`.item()` / `float()` / `np.asarray()` on traced "
                     "values inside a jitted function")),
        engine_lib.RuleInfo(
            id="impure-in-jit",
            doc=("time.time / stateful np.random inside a jitted\n"
                 "function"),
            meaning=("`time.time` family / stateful global `np.random` "
                     "inside a jitted function")),
        engine_lib.RuleInfo(
            id="device-timing",
            doc=("time.time/perf_counter window around device\n"
                 "dispatch without a host-fetch barrier (measures\n"
                 "dispatch, not execution, over the tunnel);\n"
                 "obs/ and utils/backend.py are exempt"),
            meaning=("`time.time`/`perf_counter` window around a device "
                     "dispatch without a host-fetch barrier — measures "
                     "dispatch, not execution, over the tunnel; `obs/` "
                     "and `utils/backend.py` (the clock owners) are "
                     "exempt")),
    ),
    check=_engine_check))
