"""graftlint: forgeable warmup surfaces (graftforge, obs/forge.py).

graftforge warms a deployment's executables from its research config
and specs ALONE — before any process starts, with no traffic to learn
from. That only works when every engine's bucket ladder is derivable
statically: a `BucketedEngine`/`SessionEngine` construction whose
`buckets=` is computed at runtime (a traffic-derived
`traffic_bucket_ladder(...)`, an attribute read, arbitrary arithmetic)
describes rungs the compile farm cannot enumerate — and a rung forge
can't enumerate is a rung the farm can't warm: its first live request
pays the 20-40 s tunnel compile the farm exists to kill.

* `warmup-unforgeable` — an engine construction site whose `buckets=`
  value is not spec-derivable. Accepted as derivable: no `buckets=` at
  all (the default doubling ladder from `max_batch_size`), a literal
  `None`, a literal list/tuple of ints, a module-level constant bound
  to such a literal, a direct `bucket_ladder(...)` call (the canonical
  derivation), and `**splat` call sites (not statically analyzable).
  Everything else is a finding. Runtime-derived ladders are sometimes
  the point (the fleet bench's `traffic_bucket_ladder` A/B) — those
  sites carry a justified suppression and, in production, route ladder
  changes through `ServingFleet.rollout(ladder=...)`, which pre-forges
  the new rungs inside the drained window instead of in front of
  traffic.

Pure AST analysis, backend-free like every graftlint rule. Suppress
with a trailing `# graftlint: disable=warmup-unforgeable`.
"""

from __future__ import annotations

import ast
from typing import Dict, List

from tensor2robot_tpu.analysis import engine as engine_lib
from tensor2robot_tpu.analysis.findings import (Finding, filter_findings,
                                                load_suppressions)

__all__ = ["check_python_source", "check_python_file"]

_RULE = "warmup-unforgeable"
_ENGINE_NAMES = ("BucketedEngine", "SessionEngine")


def _callee_name(func: ast.AST) -> str:
  if isinstance(func, ast.Name):
    return func.id
  if isinstance(func, ast.Attribute):
    return func.attr
  return ""


def _is_int_literal_sequence(node: ast.AST) -> bool:
  if not isinstance(node, (ast.List, ast.Tuple)):
    return False
  return all(isinstance(e, ast.Constant) and isinstance(e.value, int)
             for e in node.elts)


def _module_literal_names(tree: ast.Module) -> Dict[str, bool]:
  """Module-level `NAME = [1, 2, 4]`-style constants (the one
  indirection worth resolving: bench.py's SESSION_BUCKETS pattern)."""
  out: Dict[str, bool] = {}
  for node in tree.body:
    if isinstance(node, ast.Assign) and len(node.targets) == 1 \
        and isinstance(node.targets[0], ast.Name):
      out[node.targets[0].id] = _is_int_literal_sequence(node.value)
  return out


def _buckets_derivable(value: ast.AST,
                       literals: Dict[str, bool]) -> bool:
  if isinstance(value, ast.Constant) and value.value is None:
    return True
  if _is_int_literal_sequence(value):
    return True
  if isinstance(value, ast.Name):
    return literals.get(value.id, False)
  if isinstance(value, ast.Call) \
      and _callee_name(value.func) == "bucket_ladder":
    return True
  return False


def _check_call(path: str, node: ast.Call,
                literals: Dict[str, bool]) -> List[Finding]:
  """Findings for one Call node (shared by the standalone parse path
  and the engine's single-walk visitor dispatch; `literals` is the
  once-per-file module-literal table)."""
  if _callee_name(node.func) not in _ENGINE_NAMES:
    return []
  if any(kw.arg is None for kw in node.keywords):
    return []  # **splat: not statically analyzable, accepted
  findings: List[Finding] = []
  for kw in node.keywords:
    if kw.arg == "buckets" and not _buckets_derivable(kw.value,
                                                      literals):
      findings.append(Finding(
          path=path, line=node.lineno, rule=_RULE,
          end_line=getattr(node, "end_lineno", node.lineno),
          message=(f"{_callee_name(node.func)} built with a runtime-"
                   "derived bucket ladder: graftforge cannot "
                   "enumerate these rungs from specs, so the compile "
                   "farm cannot warm them — pass a literal ladder / "
                   "bucket_ladder(...), or route the ladder change "
                   "through ServingFleet.rollout(ladder=...) and "
                   "suppress with justification")))
  return findings


def check_python_source(path: str, source: str) -> List[Finding]:
  try:
    tree = ast.parse(source, filename=path)
  except SyntaxError:
    return []  # the engine reports unparseable files
  literals = _module_literal_names(tree)
  findings: List[Finding] = []
  for node in ast.walk(tree):
    if isinstance(node, ast.Call):
      findings.extend(_check_call(path, node, literals))
  return findings


def check_python_file(path: str) -> List[Finding]:
  try:
    with open(path, encoding="utf-8") as f:
      source = f.read()
  except (OSError, UnicodeDecodeError):
    return []
  return filter_findings(check_python_source(path, source),
                         load_suppressions(source))


def _visit(ctx, node):
  literals = ctx.memo("forge:literals",
                      lambda: _module_literal_names(ctx.tree))
  return _check_call(ctx.path, node, literals)


engine_lib.register(engine_lib.Rule(
    name="forge", kind="py", scope=".py", family="forge",
    infos=(engine_lib.RuleInfo(
        id=_RULE,
        doc=("a BucketedEngine/SessionEngine construction\n"
             "whose `buckets=` is computed at runtime —\n"
             "graftforge cannot enumerate those rungs from\n"
             "the config/specs, so the compile farm cannot\n"
             "warm them and their first live request pays\n"
             "the 20-40 s tunnel compile; literal ladders,\n"
             "bucket_ladder(...), module-level literal\n"
             "constants, and `**splat` sites are accepted\n"
             "(route live ladder changes through\n"
             "ServingFleet.rollout(ladder=...))"),
        meaning=("a `BucketedEngine`/`SessionEngine` construction whose "
                 "`buckets=` is computed at runtime — graftforge cannot "
                 "enumerate those rungs from the config/specs, so the "
                 "compile farm cannot warm them and their first live "
                 "request pays the 20–40 s tunnel compile (literal "
                 "ladders, `bucket_ladder(...)`, module-level literal "
                 "constants, and `**splat` sites accepted; route live "
                 "ladder changes through `ServingFleet.rollout("
                 "ladder=...)`, which pre-forges inside the drained "
                 "window)")),),
    visitors={ast.Call: _visit}))
