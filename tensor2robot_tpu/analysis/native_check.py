"""graftlint: native export ↔ ctypes binding coverage.

The native layer is a ctypes seam: every `extern "C"` `t2r_*` function a
`.cc` source exports must be referenced by `native/__init__.py` (an
argtypes/restype declaration, an `hasattr` feature probe, or a call
site), and every `t2r_*` name the wrapper mentions must exist in some
source. Before this check the drift was silent in BOTH directions — a
new C++ export without a binding just never ran (the round-6 stager
shipped five accessors at once), and a typoed `lib.t2r_...` attribute
only exploded at call time in whatever process first took that path.

Pure text analysis (regex over the sources): no compile, no ctypes
load, backend-free like every graftlint rule.
"""

from __future__ import annotations

import os
import re
from typing import List, Set, Tuple

from tensor2robot_tpu.analysis import engine as engine_lib
from tensor2robot_tpu.analysis.findings import (Finding, filter_findings,
                                                load_suppressions)

__all__ = ["exported_symbols", "bound_symbols", "check_native_bindings"]

# A C/C++ function DEFINITION or extern declaration at statement start:
# optional `extern "C"`/`const`, a return type (word, optionally
# pointered), then the t2r_ name and its parameter list opener.
# Call sites inside function bodies are fenced out by the keyword guard
# (`return t2r_...(...)`) and by requiring the type token shape
# (`if (t2r_...` has no preceding type word).
_EXPORT_RE = re.compile(
    r'^\s*(?:extern\s+"C"\s+)?(?:const\s+)?'
    r"(?P<type>\w+)(?:\s*\*)*\s+\*?(?P<name>t2r_\w+)\s*\(",
    re.MULTILINE)
_CC_KEYWORDS = {"return", "if", "while", "switch", "case", "else", "do"}
# \b keeps filenames like `libt2r_native.so` from matching mid-word;
# tokens ending in `_` are wildcard prose mentions (`t2r_stager_*`),
# not symbol references.
_TOKEN_RE = re.compile(r"\bt2r_\w*[A-Za-z0-9](?![\w*])")


def exported_symbols(cc_path: str) -> Set[str]:
  """`t2r_*` functions defined (or extern-declared) in one .cc file."""
  with open(cc_path, encoding="utf-8") as f:
    text = f.read()
  return {m.group("name") for m in _EXPORT_RE.finditer(text)
          if m.group("type") not in _CC_KEYWORDS}


def bound_symbols(init_path: str) -> Tuple[Set[str], List[Tuple[int, str]]]:
  """(all t2r_ tokens in the wrapper, [(line, token), ...] occurrences).

  Token-level on purpose: `lib.t2r_x` attribute bindings, `hasattr(lib,
  "t2r_x")` probes and docstring references all count as coverage — the
  check is for symbols NOBODY mentions, not for a particular binding
  style.
  """
  with open(init_path, encoding="utf-8") as f:
    return _bound_symbols_in_text(f.read())


def _bound_symbols_in_text(text: str) -> Tuple[Set[str],
                                               List[Tuple[int, str]]]:
  tokens: Set[str] = set()
  occurrences: List[Tuple[int, str]] = []
  for lineno, line in enumerate(text.splitlines(), start=1):
    for m in _TOKEN_RE.finditer(line):
      tokens.add(m.group(0))
      occurrences.append((lineno, m.group(0)))
  return tokens, occurrences


def check_native_bindings(native_dir: str) -> List[Finding]:
  """Findings for export/binding drift under one native package dir.

  native-binding-missing  a .cc exports `t2r_x` but `__init__.py` never
                          mentions it (the symbol is dead weight at best,
                          an unshipped feature at worst)
  native-binding-unknown  `__init__.py` mentions `t2r_x` but no .cc
                          defines it (typo or a binding for deleted C++)
  """
  init_path = os.path.join(native_dir, "__init__.py")
  if not os.path.isfile(init_path):
    return []
  cc_paths = sorted(
      os.path.join(native_dir, name) for name in os.listdir(native_dir)
      if name.endswith(".cc"))
  exported: Set[str] = set()
  for cc_path in cc_paths:
    exported |= exported_symbols(cc_path)
  if not cc_paths:
    return []
  with open(init_path, encoding="utf-8") as f:
    init_text = f.read()
  bound, occurrences = _bound_symbols_in_text(init_text)
  findings: List[Finding] = []
  for name in sorted(exported - bound):
    findings.append(Finding(
        path=init_path, line=1, rule="native-binding-missing",
        message=f"native sources export {name!r} but the ctypes wrapper "
                "never references it (add a binding or drop the export)"))
  for lineno, token in occurrences:
    if token not in exported:
      findings.append(Finding(
          path=init_path, line=lineno, rule="native-binding-unknown",
          message=f"{token!r} is referenced here but no .cc source "
                  "defines it (typo, or the C++ side was removed)"))
  return filter_findings(findings, load_suppressions(init_text))


def _is_native_wrapper(path: str) -> bool:
  """A native-package wrapper pulls in the export/binding coverage
  check for its whole directory (.cc sources aren't walked directly —
  the wrapper is the unit whose drift matters)."""
  return (os.path.basename(path) == "__init__.py"
          and os.path.basename(os.path.dirname(path)) == "native")


engine_lib.register(engine_lib.Rule(
    name="native", kind="native",
    scope="native/__init__.py ↔ native/*.cc", family="native",
    infos=(
        engine_lib.RuleInfo(
            id="native-binding-missing",
            doc=("a .cc source exports a `t2r_*` symbol the\n"
                 "ctypes wrapper never references"),
            meaning=("a `.cc` source exports a `t2r_*` symbol the "
                     "ctypes wrapper never references")),
        engine_lib.RuleInfo(
            id="native-binding-unknown",
            doc=("the wrapper references a `t2r_*` name no .cc\n"
                 "source defines"),
            meaning=("the wrapper references a `t2r_*` name no `.cc` "
                     "source defines")),
    ),
    path_filter=_is_native_wrapper,
    # Self-filtered against __init__.py's own suppressions (the engine's
    # central pass re-applies the same suppressions — a no-op).
    check=lambda ctx: check_native_bindings(os.path.dirname(ctx.path))))
