"""graftlint: an SLO declared without owning its budget/burn windows.

graftwatch (`obs/slo.py`) makes every objective carry its own error
budget and burn windows — `SloSpec` keyword-REQUIRES `budget`,
`fast_window_s` and `slow_window_s` precisely so no spec inherits an
invisible default that an operator never chose. Two drift modes defeat
that at a distance, and rule `slo-unbudgeted` mechanizes both:

1. A `SloSpec(...)` construction that verifiably omits any of the three
   required budget keywords. The runtime would TypeError too, but only
   on the code path that builds the spec — a config-gated or
   rarely-exercised SLO definition ships broken and fires exactly when
   someone finally needs the objective. A `**kwargs` splat in the call
   is unverifiable statically and is skipped (the runtime check owns
   it).
2. The `SLO_BURN` incident kind re-spelled as a string literal outside
   `obs/sentinel.py`. Incident sinks, eviction plumbing and dashboards
   must reference `sentinel.SLO_BURN` — a re-typed literal keeps
   working until the constant is ever renamed or namespaced, at which
   point that sink silently stops matching burn incidents (the alert
   that doesn't fire is the most expensive kind of broken).

Suppress a deliberate site (e.g. a doc snippet) with a trailing
`# graftlint: disable=slo-unbudgeted`.

Pure AST analysis, backend-free like every graftlint rule (pattern of
`trace_check.py` / `fleet_check.py`).
"""

from __future__ import annotations

import ast
from typing import List

from tensor2robot_tpu.analysis import engine as engine_lib
from tensor2robot_tpu.analysis.findings import (Finding, filter_findings,
                                                load_suppressions)

__all__ = ["check_python_source", "check_python_file"]

_RULE = "slo-unbudgeted"
_REQUIRED = ("budget", "fast_window_s", "slow_window_s")
# Built by concatenation so this module's own source never contains the
# literal it polices.
_SLO_BURN_LITERAL = "serving_" + "slo_burn"
# The defining module (and its tests' fixture strings) legitimately
# spell the kind out; everything else must import the constant.
_DEFINING_SUFFIX = "obs/sentinel.py"


def _is_slospec_call(node: ast.Call) -> bool:
  func = node.func
  if isinstance(func, ast.Name):
    return func.id == "SloSpec"
  if isinstance(func, ast.Attribute):
    return func.attr == "SloSpec"
  return False


def _check_call(path: str, node: ast.Call) -> List[Finding]:
  if not _is_slospec_call(node):
    return []
  keywords = {kw.arg for kw in node.keywords}
  if None in keywords:
    return []  # **kwargs splat: not statically verifiable
  missing = [name for name in _REQUIRED if name not in keywords]
  if not missing:
    return []
  return [Finding(
      path=path, line=node.lineno, rule=_RULE,
      end_line=getattr(node, "end_lineno", node.lineno) or node.lineno,
      message=("SloSpec constructed without explicit "
               f"{', '.join(missing)}: every objective must own its "
               "error budget and burn windows (no inherited defaults) "
               "— this call TypeErrors the first time its code path "
               "runs, which for a config-gated SLO is during the "
               "incident it was meant to catch."))]


def _check_literal(path: str, node: ast.Constant) -> List[Finding]:
  if node.value != _SLO_BURN_LITERAL:
    return []
  if path.replace("\\", "/").endswith(_DEFINING_SUFFIX):
    return []
  return [Finding(
      path=path, line=node.lineno, rule=_RULE,
      end_line=getattr(node, "end_lineno", node.lineno) or node.lineno,
      message=(f"incident kind {_SLO_BURN_LITERAL!r} re-spelled as a "
               "literal: reference `obs.sentinel.SLO_BURN` instead — a "
               "re-typed kind keeps matching only until the constant "
               "changes, and then this sink/filter silently stops "
               "seeing burn incidents."))]


def check_python_tree(path: str, tree: ast.Module) -> List[Finding]:
  """Raw (unfiltered) findings over an already-parsed module (the
  engine's entry point; `check_python_source` wraps it with a parse)."""
  findings: List[Finding] = []
  for node in ast.walk(tree):
    if isinstance(node, ast.Call):
      findings.extend(_check_call(path, node))
    elif isinstance(node, ast.Constant):
      findings.extend(_check_literal(path, node))
  findings.sort(key=lambda f: f.line)
  return findings


def check_python_source(path: str, source: str) -> List[Finding]:
  try:
    tree = ast.parse(source, filename=path)
  except SyntaxError:
    return []  # the engine reports unparseable files
  return check_python_tree(path, tree)


def check_python_file(path: str) -> List[Finding]:
  with open(path, encoding="utf-8", errors="replace") as f:
    source = f.read()
  return filter_findings(check_python_source(path, source),
                         load_suppressions(source))


def _visit_call(ctx: engine_lib.FileContext,
                node: ast.Call) -> List[Finding]:
  return _check_call(ctx.path, node)


def _visit_constant(ctx: engine_lib.FileContext,
                    node: ast.Constant) -> List[Finding]:
  return _check_literal(ctx.path, node)


engine_lib.register(engine_lib.Rule(
    name="slo", kind="py", scope=".py", family="slo",
    infos=(engine_lib.RuleInfo(
        id=_RULE,
        doc=("an SLO that does not own its budget: a\n"
             "SloSpec call verifiably missing budget/\n"
             "fast_window_s/slow_window_s (it TypeErrors the\n"
             "first time that code path runs), or the\n"
             "SLO_BURN incident kind re-spelled as a string\n"
             "literal outside obs/sentinel.py (a sink that\n"
             "silently stops matching if the constant ever\n"
             "changes)"),
        meaning=("a `SloSpec` call verifiably missing its required "
                 "`budget`/`fast_window_s`/`slow_window_s` keywords, or "
                 "the `SLO_BURN` incident kind re-spelled as a literal "
                 "outside `obs/sentinel.py` instead of referencing "
                 "`sentinel.SLO_BURN`")),),
    visitors={ast.Call: _visit_call,
              ast.Constant: _visit_constant}))
