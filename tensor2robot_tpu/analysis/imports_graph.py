"""Static (AST-level) import closure over the repo's own packages.

The config checker must answer "does this .gin file's `import` lines —
plus the trainer/actor entry binaries — make configurable X importable in
a fresh process?" WITHOUT relying on what happens to be in `sys.modules`
of the analyzing process (a previously-analyzed config may have imported
the module, which would mask a missing import line). So the import graph
is computed statically: parse each module's AST for import statements and
take the transitive closure, following only modules that live inside the
repo (jax/numpy/absl terminate the walk).
"""

from __future__ import annotations

import ast
import functools
import os
from typing import Iterable, List, Optional, Set, Tuple

__all__ = ["module_file", "static_import_closure", "module_imports"]


def _repo_root() -> str:
  # analysis/ sits directly under the package; repo root is two up.
  return os.path.dirname(os.path.dirname(os.path.dirname(
      os.path.abspath(__file__))))


def module_file(module: str, repo_root: Optional[str] = None
                ) -> Optional[str]:
  """Path of `module` if it is a repo-local python module/package."""
  root = repo_root or _repo_root()
  rel = module.replace(".", os.sep)
  for candidate in (os.path.join(root, rel + ".py"),
                    os.path.join(root, rel, "__init__.py")):
    if os.path.isfile(candidate):
      return candidate
  return None


def _ancestors(module: str) -> List[str]:
  parts = module.split(".")
  return [".".join(parts[:i]) for i in range(1, len(parts))]


@functools.lru_cache(maxsize=None)
def module_imports(module: str, repo_root: Optional[str] = None
                   ) -> Tuple[str, ...]:
  """Direct imports of `module` (absolute names), from its AST only."""
  path = module_file(module, repo_root)
  if path is None:
    return ()
  try:
    tree = ast.parse(open(path).read(), filename=path)
  except SyntaxError:
    return ()
  package = module if path.endswith("__init__.py") else \
      module.rsplit(".", 1)[0] if "." in module else ""
  out: List[str] = []
  for node in ast.walk(tree):
    if isinstance(node, ast.Import):
      out.extend(alias.name for alias in node.names)
    elif isinstance(node, ast.ImportFrom):
      if node.level:  # relative import
        base_parts = package.split(".") if package else []
        # level=1 is the current package; each extra level pops one.
        base_parts = base_parts[:len(base_parts) - (node.level - 1)]
        base = ".".join(p for p in base_parts if p)
      else:
        base = node.module or ""
      if node.level and node.module:
        base = f"{base}.{node.module}" if base else node.module
      if base:
        out.append(base)
        # `from pkg import sub` may name a submodule: include it when it
        # resolves to a repo file (importing it executes sub's module).
        for alias in node.names:
          child = f"{base}.{alias.name}"
          if module_file(child, repo_root) is not None:
            out.append(child)
  return tuple(out)


def static_import_closure(modules: Iterable[str],
                          repo_root: Optional[str] = None) -> Set[str]:
  """Transitive closure of repo-local modules reachable from `modules`.

  Importing `a.b.c` also executes `a` and `a.b` package __init__s, so
  ancestors enter the closure (and their own imports are followed).
  """
  root = repo_root or _repo_root()
  seen: Set[str] = set()
  stack = list(modules)
  while stack:
    mod = stack.pop()
    if mod in seen:
      continue
    seen.add(mod)
    for anc in _ancestors(mod):
      if anc not in seen and module_file(anc, root) is not None:
        stack.append(anc)
    if module_file(mod, root) is None:
      continue  # external module: keep the name, don't walk into it
    for imp in module_imports(mod, root):
      if imp not in seen:
        stack.append(imp)
  return seen
