"""graftlint: thread-spawning loader/stage classes must be closable.

The repo's data plane runs on background threads (the pipelined host
loader's parse pool + preprocess worker, `DevicePrefetcher`'s infeed
worker, `MicroBatcher`'s dispatch worker), and the hard-won discipline
for them is uniform (NOTES_r1/r2, `parallel/mesh.py`): a stage thread
must be STOPPABLE AND JOINABLE through a `close()` method — a daemon
thread killed at interpreter shutdown mid device-op is a killed TPU
client, the documented tunnel-wedging hazard — and an instance that is
abandoned without close() must still be recoverable, either because
callers hold it in a `with` block (context manager) or because a
`weakref.finalize` backstop stops the worker when the instance is
collected. These rules mechanize that discipline for every NEW
thread-spawning class, the same way `device-timing` mechanized the
barrier rules:

* `thread-stage-missing-close` — a class whose body starts a
  `threading.Thread` but defines no `close()` method: its worker can
  outlive every consumer with no way to stop it.
* `thread-stage-missing-backstop` — such a class has `close()` but
  neither context-manager support (`__enter__`) nor a
  `weakref.finalize` registration: an abandoned instance leaks its
  worker until process exit.

Both findings anchor on the `Thread(...)` construction line, so one
trailing `# graftlint: disable=<rule>` there suppresses a deliberate
exception (e.g. a one-shot worker that terminates by itself and is
joined elsewhere). Plain functions that spawn-and-join inline
(`serving/loadgen.run_load`, `data/pipeline.prefetch`) are exempt by
construction — the rule is about classes, whose instances carry the
thread's lifetime past the spawning call.

Pure AST analysis, backend-free like every graftlint rule.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from tensor2robot_tpu.analysis import engine as engine_lib
from tensor2robot_tpu.analysis.findings import (Finding, filter_findings,
                                                load_suppressions)

__all__ = ["check_python_source", "check_python_file"]

_RULE_CLOSE = "thread-stage-missing-close"
_RULE_BACKSTOP = "thread-stage-missing-backstop"


def _is_thread_ctor(func: ast.AST) -> bool:
  """`threading.Thread(...)` / `Thread(...)` construction."""
  if isinstance(func, ast.Name):
    return func.id == "Thread"
  if isinstance(func, ast.Attribute):
    return func.attr == "Thread"
  return False


def _is_finalize_call(node: ast.Call) -> bool:
  """`weakref.finalize(...)` (or any `.finalize(...)`) registration."""
  func = node.func
  if isinstance(func, ast.Attribute):
    return func.attr == "finalize"
  if isinstance(func, ast.Name):
    return func.id == "finalize"
  return False


def _scan_class(cls: ast.ClassDef):
  """(thread_calls, has_close, has_enter, has_finalize) for one class,
  not descending into nested classes (their threads are their own
  responsibility)."""
  thread_calls: List[ast.Call] = []
  has_finalize = False
  has_close = any(isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
                  and item.name == "close" for item in cls.body)
  has_enter = any(isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
                  and item.name == "__enter__" for item in cls.body)

  def _walk(node: ast.AST) -> None:
    nonlocal has_finalize
    for child in ast.iter_child_nodes(node):
      if isinstance(child, ast.ClassDef):
        continue
      if isinstance(child, ast.Call):
        if _is_thread_ctor(child.func):
          thread_calls.append(child)
        elif _is_finalize_call(child):
          has_finalize = True
      _walk(child)

  for item in cls.body:
    _walk(item)
  return thread_calls, has_close, has_enter, has_finalize


def _check_class(path: str, node: ast.ClassDef) -> List[Finding]:
  """Findings for one ClassDef (shared by the standalone parse path and
  the engine's single-walk visitor dispatch)."""
  thread_calls, has_close, has_enter, has_finalize = _scan_class(node)
  findings: List[Finding] = []
  for call in thread_calls:
    end_line = getattr(call, "end_lineno", call.lineno) or call.lineno
    if not has_close:
      findings.append(Finding(
          path=path, line=call.lineno, rule=_RULE_CLOSE,
          end_line=end_line,
          message=(f"class {node.name} starts a thread but defines no "
                   "close(): the worker cannot be stopped/joined — a "
                   "daemon thread killed at interpreter shutdown mid "
                   "device op is the documented tunnel-wedging hazard. "
                   "Add close() that stops AND joins the worker "
                   "(DevicePrefetcher/OverlappedLoader discipline).")))
    elif not (has_enter or has_finalize):
      findings.append(Finding(
          path=path, line=call.lineno, rule=_RULE_BACKSTOP,
          end_line=end_line,
          message=(f"class {node.name} starts a thread and has close() "
                   "but neither __enter__ (context-manager use) nor a "
                   "weakref.finalize backstop: an instance abandoned "
                   "without close() leaks its worker until process "
                   "exit. Add the CM protocol or register a finalizer "
                   "that sets the stop event.")))
  return findings


def check_python_source(path: str, source: str) -> List[Finding]:
  try:
    tree = ast.parse(source, filename=path)
  except SyntaxError:
    return []  # the engine reports unparseable files
  findings: List[Finding] = []
  for node in ast.walk(tree):
    if isinstance(node, ast.ClassDef):
      findings.extend(_check_class(path, node))
  return findings


def check_python_file(path: str) -> List[Finding]:
  with open(path, encoding="utf-8", errors="replace") as f:
    source = f.read()
  return filter_findings(check_python_source(path, source),
                         load_suppressions(source))


engine_lib.register(engine_lib.Rule(
    name="thread", kind="py", scope=".py", family="thread",
    infos=(
        engine_lib.RuleInfo(
            id=_RULE_CLOSE,
            doc=("a class starts a threading.Thread but\n"
                 "defines no close() — its worker can never be\n"
                 "stopped/joined (the tunnel-wedging hazard);\n"
                 "loader/stage classes must expose close()"),
            meaning=("a class starts a `threading.Thread` but defines "
                     "no `close()` — its worker can never be "
                     "stopped/joined")),
        engine_lib.RuleInfo(
            id=_RULE_BACKSTOP,
            doc=("such a class has close() but neither\n"
                 "__enter__ (context-manager use) nor a\n"
                 "weakref.finalize backstop — an abandoned\n"
                 "instance leaks its worker until process exit"),
            meaning=("such a class has `close()` but neither "
                     "`__enter__` nor a `weakref.finalize` backstop — "
                     "abandoned instances leak their worker")),
    ),
    visitors={ast.ClassDef: lambda ctx, node: _check_class(ctx.path,
                                                           node)}))
