"""Static checker for `.gin` experiment configs.

Promotes the fresh-process config smoke test (tests/test_configs_smoke.py
`test_config_runs_in_fresh_process`) from one end-to-end run to a
per-binding static check: every `Name.param` binding and `@Name` reference
in every config must resolve against the configurable registry *and* be
covered by the config's own `import` lines (plus what the entry binaries
import), so a config can never depend on test-process import pollution.

Rules (rule ids):

* `broken-import`        — an `import a.b.c` line that does not import;
* `unknown-configurable` — a `Name.param` binding or `@Name` reference
                           whose Name is not a registered configurable;
* `missing-import`       — Name resolves, but no import line (nor the
                           entry binaries) pulls in its defining module
                           in a fresh process (static import closure);
* `unknown-parameter`    — Name has no parameter `param`
                           (inspect.signature, honoring **kwargs);
* `duplicate-binding`    — the same (scope, Name, param) bound twice in
                           one config (the later silently shadows);
* `undefined-macro`      — `%MACRO` referenced but never defined;
* `type-mismatch`        — a literal value whose type contradicts the
                           parameter's annotation (or default's type);
* `parse-error`          — the file does not parse at all.

Resolution imports the modules named by the config (registering their
configurables) but NEVER uses a JAX backend — module import-time backend
purity is itself enforced by `tracer_check`.
"""

from __future__ import annotations

import collections.abc
import functools
import importlib
import inspect
import os
import typing
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from tensor2robot_tpu.analysis import imports_graph
from tensor2robot_tpu.analysis.findings import (Finding, filter_findings,
                                                load_suppressions)
from tensor2robot_tpu.utils import config

__all__ = ["check_config_file", "ENTRY_MODULES", "collect_mesh_axis_names"]

# What a fresh trainer/actor process imports before parsing any config:
# these modules' static import closures are always "covered" (the
# fresh-process smoke test launches exactly these binaries).
ENTRY_MODULES = (
    "tensor2robot_tpu.bin.run_t2r_trainer",
    "tensor2robot_tpu.bin.run_collect_eval",
    "tensor2robot_tpu.bin.run_meta_collect_eval",
)

# Runtime twins of ENTRY_MODULES for registry population: the bin modules
# themselves define clashing absl flags, so import what they import.
_ENTRY_RUNTIME_IMPORTS = (
    "tensor2robot_tpu.train_eval",
    "tensor2robot_tpu.envs.run_env",
    "tensor2robot_tpu.envs.run_meta_env",
)


_entry_runtime_imported = False


def _import_entry_runtime() -> None:
  global _entry_runtime_imported
  if _entry_runtime_imported:
    return
  _entry_runtime_imported = True
  for mod in _ENTRY_RUNTIME_IMPORTS:
    try:
      importlib.import_module(mod)
    except ImportError:
      pass  # reported per-config via the registry checks if it matters


@functools.lru_cache(maxsize=None)
def _entry_closure(repo_root: Optional[str]) -> frozenset:
  return frozenset(imports_graph.static_import_closure(
      ENTRY_MODULES, repo_root=repo_root))


def _collect_statements(path: str,
                        seen: Optional[Set[str]] = None,
                        texts: Optional[Dict[str, str]] = None
                        ) -> Tuple[List[config.ConfigStatement],
                                   List[Finding], Dict[str, str]]:
  """All statements of `path` with includes followed (cycle-safe).

  Also returns each visited file's text (path -> source) so callers can
  apply suppressions without re-reading from disk.
  """
  seen = seen if seen is not None else set()
  texts = texts if texts is not None else {}
  real = os.path.realpath(path)
  if real in seen:
    return [], [], texts
  seen.add(real)
  findings: List[Finding] = []
  statements: List[config.ConfigStatement] = []
  try:
    with open(path) as f:
      text = f.read()
  except OSError as e:
    return [], [Finding(path, 0, "parse-error", str(e))], texts
  texts[path] = text
  try:
    parsed = list(config.iter_config_statements(text, path=path))
  except config.ConfigError as e:
    return [], [Finding(path, 0, "parse-error", str(e))], texts
  for st in parsed:
    if st.kind == "include":
      if not os.path.isfile(st.include_target):
        findings.append(Finding(path, st.line, "broken-import",
                                f"include target {st.include_target!r} "
                                "does not exist", end_line=st.end_line))
        continue
      sub_statements, sub_findings, _ = _collect_statements(
          st.include_target, seen, texts)
      statements.extend(sub_statements)
      findings.extend(sub_findings)
    else:
      statements.append(st)
  return statements, findings, texts


def _walk_placeholders(value: Any):
  """Yields every _ConfigurableReference / _MacroReference inside value."""
  if isinstance(value, (config._ConfigurableReference,
                        config._MacroReference)):
    yield value
  elif isinstance(value, (list, tuple)):
    for v in value:
      yield from _walk_placeholders(v)
  elif isinstance(value, dict):
    for k, v in value.items():
      yield from _walk_placeholders(k)
      yield from _walk_placeholders(v)


def _resolve_configurable(name: str):
  """Registry lookup with gin's scope / trailing-path conventions."""
  if "/" in name:
    name = name.rsplit("/", 1)[-1]
  return config.get_configurable(name)


def _defining_module(fn) -> Optional[str]:
  target = fn if inspect.isclass(fn) else getattr(fn, "__wrapped__", fn)
  return getattr(target, "__module__", None)


def _signature_of(fn) -> Optional[inspect.Signature]:
  target = fn.__init__ if inspect.isclass(fn) else fn
  try:
    return inspect.signature(target)
  except (TypeError, ValueError):
    return None


_SIMPLE_TYPES: Dict[Any, Tuple[type, ...]] = {
    bool: (bool,),
    int: (int,),
    float: (int, float),
    str: (str,),
}


def _types_from_annotation(annotation) -> Optional[Tuple[type, ...]]:
  """Acceptable literal types for an annotation; None = don't check."""
  if annotation in _SIMPLE_TYPES:
    return _SIMPLE_TYPES[annotation]
  origin = typing.get_origin(annotation)
  if origin is typing.Union:
    out: Tuple[type, ...] = ()
    for arg in typing.get_args(annotation):
      if arg is type(None):
        out += (type(None),)
        continue
      sub = _types_from_annotation(arg)
      if sub is None:
        return None  # a member we can't check -> don't check the union
      out += sub
    return out
  if origin in (list, tuple, collections.abc.Sequence):
    return (list, tuple)
  if origin in (dict, collections.abc.Mapping,
                collections.abc.MutableMapping):
    return (dict,)
  return None


def _types_from_default(default) -> Optional[Tuple[type, ...]]:
  if default is inspect.Parameter.empty or default is None:
    return None
  if isinstance(default, config._Required):
    return None
  for py_type, accepted in _SIMPLE_TYPES.items():
    if type(default) is py_type:
      return accepted
  if isinstance(default, (list, tuple)):
    return (list, tuple)
  if isinstance(default, dict):
    return (dict,)
  return None


def _type_mismatch(fn, param: str, value: Any) -> Optional[str]:
  """Message if `value`'s literal type contradicts the parameter, else
  None. Conservative: only flags when both sides are confidently known."""
  for _ in _walk_placeholders(value):
    return None  # @refs / %macros resolve to arbitrary types
  sig = _signature_of(fn)
  if sig is None or param not in sig.parameters:
    return None
  parameter = sig.parameters[param]
  expected: Optional[Tuple[type, ...]] = None
  annotation = parameter.annotation
  if annotation is not inspect.Parameter.empty:
    if isinstance(annotation, str):
      # `from __future__ import annotations` modules: resolve lazily.
      target = fn.__init__ if inspect.isclass(fn) else \
          getattr(fn, "__wrapped__", fn)
      try:
        hints = typing.get_type_hints(target)
        annotation = hints.get(param, inspect.Parameter.empty)
      except Exception:
        annotation = inspect.Parameter.empty
    if annotation is not inspect.Parameter.empty:
      expected = _types_from_annotation(annotation)
  if expected is None:
    expected = _types_from_default(parameter.default)
  if expected is None:
    return None
  if value is None:
    # None is conventional "unset" for configs; only annotations that
    # explicitly include NoneType were checked above.
    if type(None) in expected or parameter.default is None:
      return None
    return (f"literal None but parameter expects "
            f"{'/'.join(t.__name__ for t in expected)}")
  if bool not in expected and isinstance(value, bool):
    return (f"literal bool {value!r} but parameter expects "
            f"{'/'.join(t.__name__ for t in expected)}")
  if isinstance(value, expected):
    return None
  return (f"literal {type(value).__name__} {value!r} but parameter "
          f"expects {'/'.join(t.__name__ for t in expected)}")


def check_config_file(path: str,
                      repo_root: Optional[str] = None) -> List[Finding]:
  """Statically checks one config file; returns (suppression-filtered)
  findings."""
  _import_entry_runtime()
  statements, findings, texts = _collect_statements(path)

  import_lines = [st for st in statements if st.kind == "import"]
  for st in import_lines:
    try:
      importlib.import_module(st.module)
    except Exception as e:  # noqa: BLE001 - any import failure is the finding
      findings.append(Finding(st.path or path, st.line, "broken-import",
                              f"cannot import {st.module!r}: "
                              f"{type(e).__name__}: {e}",
                              end_line=st.end_line))

  covered = imports_graph.static_import_closure(
      [st.module for st in import_lines], repo_root=repo_root)
  covered |= _entry_closure(repo_root)
  defined_macros = {st.name for st in statements if st.kind == "macro"}

  def _check_reference(st: config.ConfigStatement, name: str,
                       what: str) -> Optional[Any]:
    """Shared resolve + import-coverage check; returns the configurable."""
    try:
      fn = _resolve_configurable(name)
    except config.ConfigError:
      findings.append(Finding(
          st.path or path, st.line, "unknown-configurable",
          f"{what} {name!r} does not resolve to a registered "
          "configurable (is its module imported by this config?)",
          end_line=st.end_line))
      return None
    module = _defining_module(fn)
    if (module and module not in covered
        and imports_graph.module_file(module, repo_root) is not None):
      findings.append(Finding(
          st.path or path, st.line, "missing-import",
          f"{what} {name!r} is defined in {module} which no `import` "
          "line of this config (nor the entry binaries) pulls in — a "
          "fresh process would fail to resolve it",
          end_line=st.end_line))
    return fn

  def _check_value_placeholders(st: config.ConfigStatement) -> None:
    """@refs / %macros are checked wherever they appear — binding RHS
    AND macro definition values (a bad reference hidden behind a macro
    fails at resolve time all the same)."""
    for placeholder in _walk_placeholders(st.value):
      if isinstance(placeholder, config._MacroReference):
        if placeholder.name not in defined_macros:
          findings.append(Finding(
              st.path or path, st.line, "undefined-macro",
              f"%{placeholder.name} is never defined in this config",
              end_line=st.end_line))
      else:
        _check_reference(st, placeholder.name,
                         f"reference @{placeholder.name}")

  seen_bindings: Dict[Tuple[str, str, str], config.ConfigStatement] = {}
  for st in statements:
    if st.kind == "macro":
      key = ("%", st.name, "")
      if key in seen_bindings and seen_bindings[key].path == st.path:
        first = seen_bindings[key]
        findings.append(Finding(
            st.path or path, st.line, "duplicate-binding",
            f"macro {st.name!r} already defined at "
            f"{first.location} (this one shadows it)",
            end_line=st.end_line))
      seen_bindings[key] = st
      _check_value_placeholders(st)
    if st.kind != "binding":
      continue
    key = (st.scope, st.name, st.param)
    if key in seen_bindings:
      first = seen_bindings[key]
      # Same-file rebinds only: overriding an included file's binding is
      # gin's standard include-then-override idiom (later bind wins by
      # design); rebinding within one file is a genuine mistake.
      if first.path == st.path:
        scope_str = f"{st.scope}/" if st.scope else ""
        findings.append(Finding(
            st.path or path, st.line, "duplicate-binding",
            f"{scope_str}{st.name}.{st.param} already bound at "
            f"{first.location} (this one shadows it)",
            end_line=st.end_line))
    seen_bindings[key] = st

    fn = _check_reference(st, st.name, "binding target")
    if fn is not None:
      sig = _signature_of(fn)
      if sig is not None:
        params = set(sig.parameters) - {"self"}
        has_var_kw = any(p.kind == inspect.Parameter.VAR_KEYWORD
                         for p in sig.parameters.values())
        if not has_var_kw and st.param not in params:
          findings.append(Finding(
              st.path or path, st.line, "unknown-parameter",
              f"{st.name!r} has no parameter {st.param!r} "
              f"(parameters: {sorted(params)})", end_line=st.end_line))
        else:
          mismatch = _type_mismatch(fn, st.param, st.value)
          if mismatch:
            findings.append(Finding(st.path or path, st.line,
                                    "type-mismatch",
                                    f"{st.name}.{st.param}: {mismatch}",
                                    end_line=st.end_line))

    _check_value_placeholders(st)

  # Suppressions are per-file: group findings by path and filter each
  # against that file's own `# graftlint: disable=` comments (using the
  # source text already read by _collect_statements).
  out: List[Finding] = []
  by_path: Dict[str, List[Finding]] = {}
  for f in findings:
    by_path.setdefault(f.path, []).append(f)
  for file_path, file_findings in by_path.items():
    text = texts.get(file_path)
    if text is None:
      out.extend(file_findings)
      continue
    out.extend(filter_findings(file_findings, load_suppressions(text)))
  return sorted(out, key=lambda f: (f.path, f.line, f.rule))


def collect_mesh_axis_names(config_paths: Sequence[str]) -> Set[str]:
  """Mesh axis names declared across configs (`mesh_axis_names` /
  `axis_names` tuple bindings) — the vocabulary the spec checker
  validates TensorSpec.sharding annotations against."""
  axes: Set[str] = set()
  for path in config_paths:
    # Unparseable configs contribute no statements (_collect_statements
    # returns the failure as a parse-error finding, never raises).
    statements, _, _ = _collect_statements(path)
    for st in statements:
      if st.kind != "binding":
        continue
      if st.param not in ("mesh_axis_names", "axis_names"):
        continue
      if isinstance(st.value, (list, tuple)):
        axes.update(v for v in st.value if isinstance(v, str))
  return axes


from tensor2robot_tpu.analysis import engine as engine_lib

engine_lib.register(engine_lib.Rule(
    name="config", kind="gin", scope=".gin", family="config",
    infos=(
        engine_lib.RuleInfo(
            id="parse-error",
            doc="file does not parse",
            meaning="file does not parse"),
        engine_lib.RuleInfo(
            id="broken-import",
            doc="an `import a.b.c` line fails to import",
            meaning="an `import a.b.c` line fails to import"),
        engine_lib.RuleInfo(
            id="unknown-configurable",
            doc="Name.param / @Name resolves to no configurable",
            meaning=("`Name.param` / `@Name` resolves to no "
                     "configurable")),
        engine_lib.RuleInfo(
            id="missing-import",
            doc=("Name resolves, but only via import pollution —\n"
                 "no import line (nor entry binary) covers its\n"
                 "defining module in a fresh process"),
            meaning=("resolves only via import pollution; a fresh "
                     "process would fail")),
        engine_lib.RuleInfo(
            id="unknown-parameter",
            doc="Name has no parameter `param`",
            meaning=("`Name` has no parameter `param` (honors "
                     "`**kwargs`)")),
        engine_lib.RuleInfo(
            id="duplicate-binding",
            doc=("same (scope, Name, param) bound twice in one\n"
                 "file (include-then-override is idiomatic)"),
            meaning=("same (scope, Name, param) bound twice in one "
                     "file; later shadows (include-then-override across "
                     "files is idiomatic and not flagged)")),
        engine_lib.RuleInfo(
            id="undefined-macro",
            doc="%MACRO referenced but never defined",
            meaning="`%MACRO` referenced but never defined"),
        engine_lib.RuleInfo(
            id="type-mismatch",
            doc="literal value contradicts annotation/default",
            meaning=("literal value contradicts the parameter's "
                     "annotation/default")),
    ),
    # Self-filtered (config_check applies each file's own suppressions,
    # including across includes — the engine adds nothing on top).
    check=lambda ctx: check_config_file(ctx.path)))
