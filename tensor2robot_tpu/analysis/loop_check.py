"""graftlint: graftloop worker threads must be supervisor-registered.

The always-on loop's liveness floor is the supervisor (`loop/
supervisor.py`): every loop worker goes through `Supervisor.spawn`, so
crashes restart under the shared retry schedule, hangs are detected by
heartbeat, and escalation budgets stop a dying worker from
restart-looping forever. A worker thread constructed with a bare
`threading.Thread(...)` inside the loop package sidesteps ALL of that —
it dies silently, hangs invisibly, and its failure never reaches the
incident stream. This rule mechanizes the registration seam the
supervisor module documents, the same way `fleet-replica-unjoined`
mechanized the fleet's join discipline:

* `unsupervised-loop-worker` — a `threading.Thread(...)` construction
  in a module of the `loop` package OTHER than `supervisor.py` (whose
  monitor + worker threads ARE the supervision machinery, exempt by
  construction). Register the worker with `Supervisor.spawn(name,
  target)` instead; a deliberate unsupervised helper (e.g. a bounded
  one-shot join-elsewhere thread) suppresses with a trailing
  `# graftlint: disable=unsupervised-loop-worker`.

Scope is PATH-based (a file whose parent directory is named `loop`):
the discipline belongs to the loop subsystem — data-plane loaders and
serving batchers have their own thread rules (`thread-stage-*`), which
still apply here too. Pure AST analysis, backend-free like every
graftlint rule.
"""

from __future__ import annotations

import ast
import os
from typing import List

from tensor2robot_tpu.analysis import engine as engine_lib
from tensor2robot_tpu.analysis.findings import (Finding, filter_findings,
                                                load_suppressions)

__all__ = ["check_python_source", "check_python_file"]

_RULE = "unsupervised-loop-worker"
_EXEMPT_BASENAMES = frozenset({"supervisor.py"})


def _in_loop_package(path: str) -> bool:
  return os.path.basename(os.path.dirname(os.path.abspath(path))) == "loop"


def _is_thread_ctor(func: ast.AST) -> bool:
  """`threading.Thread(...)` / `Thread(...)` construction."""
  if isinstance(func, ast.Name):
    return func.id == "Thread"
  if isinstance(func, ast.Attribute):
    return func.attr == "Thread"
  return False


def _rule_applies(path: str) -> bool:
  return (_in_loop_package(path)
          and os.path.basename(path) not in _EXEMPT_BASENAMES)


def _check_call(path: str, node: ast.Call) -> List[Finding]:
  """Findings for one Call node (shared by the standalone parse path
  and the engine's single-walk visitor dispatch; the path gate is
  applied by the caller)."""
  if not _is_thread_ctor(node.func):
    return []
  end_line = getattr(node, "end_lineno", node.lineno) or node.lineno
  return [Finding(
      path=path, line=node.lineno, rule=_RULE, end_line=end_line,
      message=("bare threading.Thread in the loop package: this "
               "worker is outside the supervisor's restart/heartbeat"
               "/escalation machinery — it dies silently and hangs "
               "invisibly. Register it with Supervisor.spawn(name, "
               "target) (loop/supervisor.py) instead."))]


def check_python_source(path: str, source: str) -> List[Finding]:
  if not _rule_applies(path):
    return []
  try:
    tree = ast.parse(source, filename=path)
  except SyntaxError:
    return []  # the engine reports unparseable files
  findings: List[Finding] = []
  for node in ast.walk(tree):
    if isinstance(node, ast.Call):
      findings.extend(_check_call(path, node))
  return findings


def check_python_file(path: str) -> List[Finding]:
  with open(path, encoding="utf-8", errors="replace") as f:
    source = f.read()
  return filter_findings(check_python_source(path, source),
                         load_suppressions(source))


engine_lib.register(engine_lib.Rule(
    name="loop", kind="py", scope=".py, the loop/ package only",
    family="loop",
    infos=(engine_lib.RuleInfo(
        id=_RULE,
        doc=("a bare threading.Thread construction in a\n"
             "loop-package module other than supervisor.py —\n"
             "the worker is outside the supervisor's restart/\n"
             "heartbeat/escalation machinery (dies silently,\n"
             "hangs invisibly); register it with\n"
             "Supervisor.spawn instead"),
        meaning=("a bare `threading.Thread` construction in a "
                 "loop-package module other than `supervisor.py` — the "
                 "worker is outside the supervisor's restart/heartbeat/"
                 "escalation machinery (dies silently, hangs "
                 "invisibly); register it with `Supervisor.spawn` "
                 "instead")),),
    path_filter=_rule_applies,
    visitors={ast.Call: lambda ctx, node: _check_call(ctx.path, node)}))
