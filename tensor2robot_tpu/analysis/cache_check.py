"""graftlint: executable-cache key completeness.

A persistent executable cache is only as safe as its key: an entry
keyed without the device topology loads an 8-device executable into a
1-device process; without dtypes it serves a bf16 executable to f32
traffic; without the backend version it replays executables across a
compiler upgrade (the round-5 measured fact: the terminal's older
libtpu refused image-compiled executables — version skew is real on
this project's one deployment). `obs.excache.cache_key` therefore takes
every component as a mandatory keyword, and this rule makes omission a
STATIC finding rather than a runtime TypeError in whatever process
first takes the path:

* `cache-key-missing-component` — a `cache_key(...)` /
  `excache.cache_key(...)` call site that does not pass every required
  component keyword (`jaxpr_fingerprint`, `avals`, `mesh`,
  `backend_version`, `donation`, `static_args`, `pallas`). A literal
  `**kwargs` splat at the call site is accepted (not statically
  analyzable); the idiomatic `**key_components_from_traced(...)` splat
  is exactly that.

Pure AST analysis, backend-free like every graftlint rule. Suppress
with a trailing `# graftlint: disable=cache-key-missing-component`.
"""

from __future__ import annotations

import ast
from typing import List

from tensor2robot_tpu.analysis import engine as engine_lib
from tensor2robot_tpu.analysis.findings import (Finding, filter_findings,
                                                load_suppressions)

__all__ = ["REQUIRED_COMPONENTS", "check_python_source",
           "check_python_file"]

# Mirrors the mandatory keywords of obs.excache.cache_key — the
# components without which a persisted executable can be loaded into
# the wrong topology/dtype/compiler (tests/test_excache.py pins the two
# lists against each other so they cannot drift).
REQUIRED_COMPONENTS = ("jaxpr_fingerprint", "avals", "mesh",
                       "backend_version", "donation", "static_args",
                       "pallas")

_RULE = "cache-key-missing-component"


def _is_cache_key_call(func: ast.AST) -> bool:
  if isinstance(func, ast.Name):
    return func.id == "cache_key"
  if isinstance(func, ast.Attribute):
    return func.attr == "cache_key"
  return False


def _check_call(path: str, node: ast.Call) -> List[Finding]:
  """Findings for one Call node (shared by the standalone parse path
  and the engine's single-walk visitor dispatch)."""
  if not _is_cache_key_call(node.func):
    return []
  if any(kw.arg is None for kw in node.keywords):
    return []  # **splat: components arrive as a dict, not analyzable
  passed = {kw.arg for kw in node.keywords}
  missing = [c for c in REQUIRED_COMPONENTS if c not in passed]
  if not missing:
    return []
  return [Finding(
      path=path, line=node.lineno, rule=_RULE,
      end_line=getattr(node, "end_lineno", node.lineno) or node.lineno,
      message=(f"cache_key call omits key component(s) "
               f"{', '.join(missing)} — an under-keyed executable "
               "cache can serve a mismatched executable (wrong "
               "mesh/dtype/compiler); pass every component, e.g. "
               "**excache.key_components_from_traced(traced, args)"))]


def check_python_source(path: str, source: str) -> List[Finding]:
  try:
    tree = ast.parse(source, filename=path)
  except SyntaxError:
    return []  # the engine (née tracer_check) reports unparseable files
  findings: List[Finding] = []
  for node in ast.walk(tree):
    if isinstance(node, ast.Call):
      findings.extend(_check_call(path, node))
  return findings


def check_python_file(path: str) -> List[Finding]:
  with open(path, encoding="utf-8", errors="replace") as f:
    source = f.read()
  return filter_findings(check_python_source(path, source),
                         load_suppressions(source))


engine_lib.register(engine_lib.Rule(
    name="cache", kind="py", scope=".py", family="cache",
    infos=(engine_lib.RuleInfo(
        id=_RULE,
        doc=("a `cache_key(...)` call site omits one\n"
             "of the mandatory executable-cache key\n"
             "components (jaxpr fingerprint, aval shapes/\n"
             "dtypes, mesh topology, backend version,\n"
             "donation layout, static args, pallas kernel\n"
             "lowerings) — an under-keyed cache can serve\n"
             "a mismatched executable;\n"
             "a `**splat` call site is accepted"),
        meaning=("a `cache_key(...)` call site omits a mandatory key "
                 "component (`**splat` accepted)")),),
    visitors={ast.Call: lambda ctx, node: _check_call(ctx.path, node)}))
