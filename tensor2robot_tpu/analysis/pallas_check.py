"""graftlint: Pallas kernel-tier fallback discipline.

Pallas is the one dependency tier this repo treats as OPTIONAL at every
call site: kernels must degrade to their XLA reference composition when
pallas cannot import (CPU-only deployments, toolchain skew) and must be
runnable under `interpret=True` so CPU tier-1/bench exercise the real
kernel body (`ops/attention.py`'s flash tier and `ops/decode_kernels.py`
set the pattern — soft import + `pallas_available()` + an `interpret`
seam). A `pl.pallas_call` added without that discipline turns every
import of its module into a hard pallas dependency and every CPU run
into a lowering error ("Only interpret mode is supported on CPU
backend") instead of a measured fallback:

* `pallas-missing-fallback` — a `pallas_call(...)` /
  `pl.pallas_call(...)` call site in a module that (a) imports pallas
  UNGUARDED (no `try:`-wrapped import, so there is no XLA fallback seam
  to take when the import fails), or (b) does not thread an
  `interpret=` argument through the call (a `**splat` at the call site
  is accepted — not statically analyzable), so CPU smoke cannot run the
  kernel in interpreter mode.

Pure AST analysis, backend-free like every graftlint rule. Suppress
with a trailing `# graftlint: disable=pallas-missing-fallback`.
"""

from __future__ import annotations

import ast
from typing import List

from tensor2robot_tpu.analysis import engine as engine_lib
from tensor2robot_tpu.analysis.findings import (Finding, filter_findings,
                                                load_suppressions)

__all__ = ["check_python_source", "check_python_file"]

_RULE = "pallas-missing-fallback"


def _is_pallas_import(node: ast.AST) -> bool:
  """True for any statement that imports pallas (`import
  jax.experimental.pallas ...`, `from jax.experimental import pallas`,
  `from jax.experimental.pallas import tpu`)."""
  if isinstance(node, ast.Import):
    return any("pallas" in (alias.name or "") for alias in node.names)
  if isinstance(node, ast.ImportFrom):
    module = node.module or ""
    if "pallas" in module:
      return True
    return module.startswith("jax.experimental") and any(
        alias.name == "pallas" for alias in node.names)
  return False


def _has_guarded_pallas_import(tree: ast.Module) -> bool:
  """True when every pallas import in the module sits under a `try:`
  (the soft-import fallback seam); False when any is unguarded or when
  the module never imports pallas at module scope (a function-local
  import still raises at call time — same missing seam)."""
  guarded = False
  for node in ast.walk(tree):
    if isinstance(node, ast.Try):
      for stmt in ast.walk(node):
        if _is_pallas_import(stmt):
          guarded = True
  # Any pallas import NOT inside a Try is unguarded.
  trys = [n for n in ast.walk(tree) if isinstance(n, ast.Try)]
  in_try = set()
  for t in trys:
    for stmt in ast.walk(t):
      in_try.add(id(stmt))
  for node in ast.walk(tree):
    if _is_pallas_import(node) and id(node) not in in_try:
      return False
  return guarded


def _is_pallas_call(func: ast.AST) -> bool:
  if isinstance(func, ast.Name):
    return func.id == "pallas_call"
  if isinstance(func, ast.Attribute):
    return func.attr == "pallas_call"
  return False


def _check_tree(path: str, tree: ast.Module) -> List[Finding]:
  """Findings for one parsed module (shared by the standalone path and
  the engine's whole-tree check)."""
  findings: List[Finding] = []
  guarded = _has_guarded_pallas_import(tree)
  for node in ast.walk(tree):
    if not (isinstance(node, ast.Call) and _is_pallas_call(node.func)):
      continue
    end = getattr(node, "end_lineno", node.lineno) or node.lineno
    if not guarded:
      findings.append(Finding(
          path=path, line=node.lineno, rule=_RULE, end_line=end,
          message=("pallas_call in a module without a try-guarded "
                   "pallas import — there is no XLA fallback seam when "
                   "pallas cannot import; soft-import pallas and gate "
                   "the kernel tier on it (the ops/attention.py / "
                   "ops/decode_kernels.py pattern)")))
    elif not any(kw.arg == "interpret" or kw.arg is None
                 for kw in node.keywords):
      findings.append(Finding(
          path=path, line=node.lineno, rule=_RULE, end_line=end,
          message=("pallas_call without an `interpret=` seam — CPU "
                   "smoke/tier-1 cannot run this kernel in interpreter "
                   "mode and hits 'Only interpret mode is supported on "
                   "CPU backend' instead of exercising the kernel "
                   "body; thread an interpret argument through the "
                   "call (`**splat` accepted)")))
  return findings


def check_python_source(path: str, source: str) -> List[Finding]:
  try:
    tree = ast.parse(source, filename=path)
  except SyntaxError:
    return []  # the engine reports unparseable files
  return _check_tree(path, tree)


def check_python_file(path: str) -> List[Finding]:
  with open(path, encoding="utf-8", errors="replace") as f:
    source = f.read()
  return filter_findings(check_python_source(path, source),
                         load_suppressions(source))


engine_lib.register(engine_lib.Rule(
    name="pallas", kind="py", scope=".py", family="pallas",
    infos=(engine_lib.RuleInfo(
        id=_RULE,
        doc=("a `pallas_call` site lacks the kernel-tier\n"
             "fallback discipline: its module imports pallas\n"
             "unguarded (no XLA fallback seam when the import\n"
             "fails) or the call threads no `interpret=` seam\n"
             "(CPU smoke cannot run the kernel body);\n"
             "a `**splat` call site is accepted"),
        meaning=("a `pallas_call` site has no XLA fallback seam or no "
                 "`interpret=` guard for CPU runs (`**splat` "
                 "accepted)")),),
    check=lambda ctx: _check_tree(ctx.path, ctx.tree)))
