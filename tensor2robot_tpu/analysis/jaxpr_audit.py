"""graftaudit: jaxpr-level semantic auditing of a config's jit entry
points — ahead-of-time, trace-only, never over the tunnel.

The reference stack validated tensors at RUNTIME (tensorspec_utils
assert/validate helpers fired per batch inside the input pipeline); the
graftlint layer moved the spec checks ahead of time but stops at the
AST. This module closes the remaining gap: the expensive mistakes that
are INVISIBLE in source text and only exist in the traced program —

* `audit-baked-constant`       a large array closure-captured into the
                               jitted function becomes a jaxpr constant:
                               it bloats every serialized graftcache
                               entry, dodges donation, and re-uploads
                               with every executable;
* `audit-undonated-state`      a state-sized input whose shape/dtype
                               reappears in the outputs but is not
                               donated — the runtime keeps two copies
                               live across every dispatch (the train
                               state / decode arena mistake);
* `audit-host-callback-in-loop` a host-callback primitive inside a
                               `scan`/`while` body: one host round-trip
                               PER ITERATION (~1.5 s each over the axon
                               tunnel, CLAUDE.md), serialized against
                               the device stream;
* `audit-unhashable-static`    a static arg that is unhashable (jit
                               raises at every call site) or hashes by
                               object identity (every fresh instance is
                               a silent recompile).

Split exactly like `obs/forge.py`, whose enumeration it reuses: the
PARENT (`audit_config`) is backend-free — it enumerates the config's
executable set through `forge.plan_from_config`, then hands every
traceable target to ONE fresh worker subprocess (`--worker`), which
pins the CPU backend (`utils.backend.pin_cpu`; `GRAFTAUDIT_PLATFORM`
overrides, the forge-worker pattern) before any jax import can touch
the axon tunnel. The worker builds exactly the objects the deployment
builds — `forge.build_rung_engine(...)` + `rung_traces()` for serving
ladders, `forge.build_train_step(...)` for the trainer — and audits
each `.trace(*args)` result: `traced.jaxpr` for constants and loop
bodies, `traced.args_info` for donation. Tracing never lowers or
compiles, so even excache-gated (unforgeable) train targets are
auditable.

Findings surface through the graftlint engine: the four rules are
registered in `analysis/engine.py`'s catalog (kind "jaxpr" — catalog/
severity only, the file walk never runs them), anchored on the audited
config file spanning its full length, so one trailing
`# graftlint: disable=<rule>` comment anywhere in the config suppresses
deliberately accepted hits. CLI: `python -m
tensor2robot_tpu.bin.graftscope audit <config.gin>` (exit 0 clean, 1
findings/errors, 2 usage).

`audit_callable(name, fn, args, ...)` is the fixture-test seam: it
audits ONE callable the same way the worker audits a config target
(tests/test_jaxpr_audit.py seeds each violation through it).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

from tensor2robot_tpu.analysis import engine as engine_lib
from tensor2robot_tpu.analysis.findings import Finding, load_suppressions

__all__ = ["audit_config", "audit_callable", "audit_traced",
           "report_findings", "format_report", "AUDIT_CONST_BYTES",
           "AUDIT_STATE_BYTES"]

# A closure-captured constant this large is a deployment bug, not a
# scalar epsilon: 1 MiB is far above any legitimate baked table in this
# repo and far below any real weight array.
AUDIT_CONST_BYTES = 1 << 20
# Inputs at least this large with an output shape twin are "state" for
# the donation rule (param leaves, decode arenas — not batch scalars).
AUDIT_STATE_BYTES = 64 << 10

_LOOP_PRIMITIVES = frozenset({"scan", "while"})
# Host round-trip primitives. Matching also catches dialect variants
# ("callback" substring) so a jax rename degrades to MORE coverage.
_CALLBACK_PRIMITIVES = frozenset({"pure_callback", "io_callback",
                                  "debug_callback", "outside_call"})


def _entry(executable: str, rule: str, message: str) -> Dict[str, str]:
  return {"executable": executable, "rule": rule, "message": message}


def _aval_bytes(aval) -> int:
  import numpy as np

  shape = getattr(aval, "shape", None)
  dtype = getattr(aval, "dtype", None)
  if shape is None or dtype is None:
    return 0
  size = 1
  for dim in shape:
    try:
      size *= int(dim)
    except TypeError:  # symbolic dim: size unknowable, skip
      return 0
  return size * np.dtype(dtype).itemsize


def _sub_jaxprs(params: Mapping[str, Any]):
  """Every sub-jaxpr hiding in one eqn's params (scan/while bodies,
  cond branches, pjit calls) — ClosedJaxpr or raw Jaxpr, single or
  listed."""
  for value in params.values():
    for candidate in (value if isinstance(value, (list, tuple))
                      else (value,)):
      inner = getattr(candidate, "jaxpr", None)
      if inner is not None and hasattr(inner, "eqns"):
        yield inner
      elif hasattr(candidate, "eqns"):
        yield candidate


def _walk_loop_callbacks(jaxpr, enclosing_loop: Optional[str],
                         hits: List[Tuple[str, str]]) -> None:
  for eqn in jaxpr.eqns:
    prim = eqn.primitive.name
    if enclosing_loop and (prim in _CALLBACK_PRIMITIVES
                           or "callback" in prim):
      hits.append((prim, enclosing_loop))
    loop = prim if prim in _LOOP_PRIMITIVES else enclosing_loop
    for sub in _sub_jaxprs(eqn.params):
      _walk_loop_callbacks(sub, loop, hits)


def audit_traced(name: str, traced,
                 const_bytes: int = AUDIT_CONST_BYTES,
                 state_bytes: int = AUDIT_STATE_BYTES
                 ) -> List[Dict[str, str]]:
  """Audits one `jitted.trace(*args)` result (worker side; jax is
  imported by the caller's trace already). Returns raw entry dicts —
  the parent converts them to engine Findings."""
  import jax

  entries: List[Dict[str, str]] = []
  closed = traced.jaxpr  # ClosedJaxpr

  # -- audit-baked-constant ------------------------------------------------
  for var, _val in zip(closed.jaxpr.constvars, closed.consts):
    aval = getattr(var, "aval", None)
    nbytes = _aval_bytes(aval)
    if nbytes >= const_bytes:
      entries.append(_entry(
          name, "audit-baked-constant",
          f"a {tuple(aval.shape)} {aval.dtype} constant "
          f"({nbytes / 2**20:.1f} MiB) is baked into the executable "
          "(closure-captured array: it bloats every serialized cache "
          "entry, dodges donation, and re-uploads with the program — "
          "pass it as an argument instead)"))

  # -- audit-undonated-state -----------------------------------------------
  infos = jax.tree_util.tree_leaves(
      traced.args_info, is_leaf=lambda n: hasattr(n, "donated"))
  out_sigs = {(tuple(a.shape), str(a.dtype)) for a in closed.out_avals
              if hasattr(a, "shape") and hasattr(a, "dtype")}
  undonated = 0
  undonated_bytes = 0
  # args_info leaves and in_avals share one flat order (ArgInfo keeps
  # its aval private, so the donation flag is paired with the public
  # aval list; a length mismatch — statics, future jax — skips the
  # rule rather than mispairing).
  in_avals = list(closed.in_avals)
  for info, aval in (zip(infos, in_avals)
                     if len(infos) == len(in_avals) else ()):
    if getattr(info, "donated", False):
      continue
    nbytes = _aval_bytes(aval)
    if (nbytes >= state_bytes
        and (tuple(aval.shape), str(aval.dtype)) in out_sigs):
      undonated += 1
      undonated_bytes += nbytes
  if undonated:
    entries.append(_entry(
        name, "audit-undonated-state",
        f"{undonated} undonated input leaf(ves) totalling "
        f"{undonated_bytes / 2**20:.1f} MiB whose shape/dtype reappears "
        "in the outputs — state carried through the step without "
        "donate_argnums keeps BOTH copies live across every dispatch"))

  # -- audit-host-callback-in-loop -----------------------------------------
  hits: List[Tuple[str, str]] = []
  _walk_loop_callbacks(closed.jaxpr, None, hits)
  for prim, loop in hits:
    entries.append(_entry(
        name, "audit-host-callback-in-loop",
        f"host-callback primitive {prim!r} inside a {loop!r} body: one "
        "host round-trip PER ITERATION (~1.5 s each over the axon "
        "tunnel), serialized against the device stream — hoist it out "
        "of the loop or batch it"))
  return entries


def _audit_static_args(name: str,
                       static_args: Mapping[str, Any]
                       ) -> List[Dict[str, str]]:
  entries: List[Dict[str, str]] = []
  for arg_name in sorted(static_args):
    value = static_args[arg_name]
    try:
      hash(value)
    except TypeError:
      entries.append(_entry(
          name, "audit-unhashable-static",
          f"static arg {arg_name!r} ({type(value).__name__}) is "
          "unhashable — jit raises at every call site; pin it as a "
          "hashable (tuple / frozenset / frozen dataclass)"))
      continue
    if type(value).__hash__ is object.__hash__ and not callable(value):
      entries.append(_entry(
          name, "audit-unhashable-static",
          f"static arg {arg_name!r} ({type(value).__name__}) hashes by "
          "object identity — every fresh instance is a new jit cache "
          "entry, a silent recompile per construction"))
  return entries


def audit_callable(name: str, fn, args: Sequence[Any],
                   donate_argnums: Sequence[int] = (),
                   static_args: Optional[Mapping[str, Any]] = None
                   ) -> List[Dict[str, str]]:
  """Audits ONE callable exactly as the worker audits a config target
  (the fixture-test seam). `fn` may be a plain callable (jitted here
  with `donate_argnums`) or anything with a `.trace` AOT method;
  `static_args` is a name->value mapping audited for hashability
  WITHOUT entering the trace (an unhashable static would abort it)."""
  import jax

  entries = _audit_static_args(name, dict(static_args or {}))
  jitted = fn if hasattr(fn, "trace") else jax.jit(
      fn, donate_argnums=tuple(donate_argnums))
  entries.extend(audit_traced(name, jitted.trace(*args)))
  return entries


# ---------------------------------------------------------------------------
# Worker side (fresh subprocess; the only half that touches jax —
# the obs/forge.py split).
# ---------------------------------------------------------------------------


def _audit_target(spec: Dict[str, Any],
                  target: Dict[str, Any]) -> Dict[str, Any]:
  from tensor2robot_tpu.obs import forge

  findings: List[Dict[str, str]] = []
  try:
    if target["family"] in ("serve", "session"):
      engine = forge.build_rung_engine(spec, target)
      for rung, traced, _args in engine.rung_traces():
        if target["family"] == "session":
          exe = (f"{target['name']}/reset_slot" if rung == "reset"
                 else f"{target['name']}/decode{rung}")
        else:
          exe = f"{target['name']}/bucket{rung}"
        findings.extend(audit_traced(exe, traced))
    elif target["family"] == "train":
      step, args = forge.build_train_step(spec, target)
      findings.extend(audit_traced(target["name"], step.trace(*args)))
    else:
      return {"name": target["name"], "family": target["family"],
              "status": "skipped",
              "reason": "no trace recipe for this family"}
  except Exception as e:  # noqa: BLE001 - one bad target != a dead audit
    return {"name": target["name"], "family": target["family"],
            "status": "error", "error": f"{type(e).__name__}: {e}"}
  return {"name": target["name"], "family": target["family"],
          "status": "ok", "findings": findings}


def _worker_main(spec_path: str, result_path: str) -> int:
  with open(spec_path) as f:
    spec = json.load(f)
  if os.environ.get("GRAFTAUDIT_PLATFORM", "cpu") == "cpu":
    # Default-safe on the axon environment: the audit worker must never
    # initialize the TPU tunnel by accident (CLAUDE.md; the
    # GRAFTFORGE_PLATFORM pattern).
    from tensor2robot_tpu.utils import backend

    backend.pin_cpu()
  from tensor2robot_tpu.utils import config

  config.clear_config()
  config.parse_config_files_and_bindings(list(spec["config_files"]),
                                         list(spec["bindings"]))
  results = [_audit_target(spec, target) for target in spec["targets"]]
  with open(result_path, "w") as f:
    json.dump(results, f)
  return 0 if all(r["status"] != "error" for r in results) else 1


# ---------------------------------------------------------------------------
# Parent side (backend-free).
# ---------------------------------------------------------------------------


def _run_worker(plan: Dict[str, Any], targets: List[Dict[str, Any]],
                cache_dir: Optional[str], device_count: Optional[int],
                timeout_s: float) -> List[Dict[str, Any]]:
  from tensor2robot_tpu.obs import forge

  if not targets:
    return []
  env = forge._worker_env(device_count)
  with tempfile.TemporaryDirectory(prefix="graftaudit-") as tmp:
    spec = {
        "config_files": plan["config_files"],
        "bindings": plan["bindings"],
        "model": plan.get("model"),
        "model_dir": plan.get("model_dir"),
        # Engines want a cache dir at construction; tracing never
        # touches it, so a throwaway default keeps the audit read-only.
        "cache_dir": cache_dir or os.path.join(tmp, "cache"),
        "targets": targets,
    }
    spec_path = os.path.join(tmp, "spec.json")
    result_path = os.path.join(tmp, "result.json")
    with open(spec_path, "w") as f:
      json.dump(spec, f)
    proc = subprocess.Popen(
        [sys.executable, "-m", "tensor2robot_tpu.analysis.jaxpr_audit",
         "--worker", spec_path, result_path], env=env)
    try:
      proc.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
      # NEVER SIGKILL a possibly-mid-TPU-init child (CLAUDE.md); the
      # worker is CPU-pinned but the discipline is unconditional.
      proc.terminate()
      try:
        proc.wait(timeout=30)
      except subprocess.TimeoutExpired:
        pass  # abandon, never SIGKILL
    if os.path.isfile(result_path):
      try:
        with open(result_path) as f:
          return json.load(f)
      except (OSError, ValueError):
        pass
    return [{"name": t["name"], "family": t["family"], "status": "error",
             "error": f"audit worker exited {proc.returncode} without "
                      "a result"} for t in targets]


def report_findings(plan: Dict[str, Any],
                    results: Sequence[Dict[str, Any]]) -> List[Finding]:
  """Worker entries -> engine-catalogued Findings, anchored on the
  first audited config file and spanning its full length — so a
  trailing `# graftlint: disable=<rule>` comment on ANY line of the
  config suppresses a deliberately accepted hit (file-level
  suppression, the same `findings.Suppressions` model every graftlint
  rule uses)."""
  anchor = (plan.get("config_files") or ["<config>"])[0]
  try:
    with open(anchor, encoding="utf-8", errors="replace") as f:
      text = f.read()
  except OSError:
    text = ""
  end_line = max(1, text.count("\n") + 1)
  raw = [Finding(path=anchor, line=1, rule=entry["rule"],
                 message=f"{entry['executable']}: {entry['message']}",
                 end_line=end_line)
         for result in results
         for entry in (result.get("findings") or [])]
  supps = load_suppressions(text)
  kept = [f for f in raw if supps.match(f.line, f.rule, f.end_line) is None]
  return sorted(kept, key=lambda f: (f.path, f.rule, f.message))


def _default_device_count(plan: Dict[str, Any]) -> int:
  """The smallest worker topology the plan's targets can build on:
  placed fleet replicas need one device each, an explicit mesh shape
  needs its product, and the trainer's unbound "default" mesh mirrors
  the repo's standard virtual 8-device topology (tests/conftest.py)."""
  need = 1
  for target in plan["targets"]:
    if target.get("placed"):
      need = max(need, int(target.get("num_replicas") or 1))
    shape = target.get("mesh_shape")
    if isinstance(shape, (list, tuple)):
      product = 1
      for dim in shape:
        product *= int(dim)
      need = max(need, product)
    elif shape == "default":
      need = max(need, 8)
  return need


def audit_config(config_files: Sequence[str],
                 bindings: Sequence[str] = (),
                 model: Optional[str] = None,
                 export_dir: Optional[str] = None,
                 model_dir: Optional[str] = None,
                 cache_dir: Optional[str] = None,
                 device_count: Optional[int] = None,
                 timeout_s: float = 600.0
                 ) -> Tuple[Dict[str, Any], List[Dict[str, Any]],
                            List[Finding]]:
  """Audits every jit entry point a research config deploys.

  Backend-free in THIS process: enumeration is `forge.plan_from_config`
  and all tracing happens in one CPU-pinned worker subprocess (its
  device count defaults to what the plan's targets need). Returns
  `(plan, per-target results, findings)` — findings already filtered
  through the config's suppression comments. Excache-gated
  (unforgeable) train targets ARE audited: tracing never serializes an
  executable, so the donating-mesh gate does not apply.
  """
  from tensor2robot_tpu.obs import forge

  plan = forge.plan_from_config(config_files, bindings, model=model,
                                export_dir=export_dir,
                                model_dir=model_dir)
  targets = [t for t in plan["targets"]
             if t["family"] in ("serve", "session", "train")]
  results = _run_worker(plan, targets, cache_dir,
                        device_count or _default_device_count(plan),
                        timeout_s)
  return plan, results, report_findings(plan, results)


def format_report(plan: Dict[str, Any],
                  results: Sequence[Dict[str, Any]],
                  findings: Sequence[Finding]) -> str:
  """The `graftscope audit` summary table (format_plan's sibling)."""
  lines = [f"graftaudit: {', '.join(plan['config_files'])} "
           f"(model: {json.dumps(plan.get('model'))})"]
  for result in results:
    status = result["status"]
    detail = (result.get("error") or result.get("reason")
              or f"{len(result.get('findings') or [])} finding(s)")
    lines.append(f"  {result['family']:<9}{result['name']:<18}"
                 f"{status:>8}  {detail}")
  lines.append(f"  {len(findings)} finding(s) after suppressions")
  return "\n".join(lines)


engine_lib.register(engine_lib.Rule(
    name="audit", kind="jaxpr",
    scope="jit entry points, via `graftscope audit <config>`",
    family="audit",
    infos=(
        engine_lib.RuleInfo(
            id="audit-baked-constant", severity="warning",
            doc=("a large array is closure-captured into a jit\n"
                 "entry point (a jaxpr constant: bloats every\n"
                 "cache entry, dodges donation)"),
            meaning=("a large array is closure-captured into a jit "
                     "entry point — a jaxpr constant that bloats every "
                     "serialized cache entry and dodges donation")),
        engine_lib.RuleInfo(
            id="audit-undonated-state", severity="warning",
            doc=("a state-sized input whose shape/dtype reappears\n"
                 "in the outputs is not donated (two live copies\n"
                 "per dispatch)"),
            meaning=("a state-sized input whose shape/dtype reappears "
                     "in the outputs is not donated — two live copies "
                     "per dispatch (the train-state/arena mistake)")),
        engine_lib.RuleInfo(
            id="audit-host-callback-in-loop", severity="warning",
            doc=("a host-callback primitive inside a scan/while\n"
                 "body: one host round-trip PER ITERATION"),
            meaning=("a host-callback primitive inside a `scan`/`while` "
                     "body — one host round-trip per iteration (~1.5 s "
                     "each over the axon tunnel)")),
        engine_lib.RuleInfo(
            id="audit-unhashable-static", severity="warning",
            doc=("a static arg is unhashable (jit raises) or\n"
                 "hashes by identity (silent recompile per\n"
                 "instance)"),
            meaning=("a static arg is unhashable (jit raises at every "
                     "call site) or hashes by object identity (a silent "
                     "recompile per fresh instance)")),
    )))


if __name__ == "__main__":
  if len(sys.argv) == 4 and sys.argv[1] == "--worker":
    sys.exit(_worker_main(sys.argv[2], sys.argv[3]))
  print("usage: python -m tensor2robot_tpu.analysis.jaxpr_audit "
        "--worker <spec.json> <result.json>\n(operators drive the audit "
        "through `python -m tensor2robot_tpu.bin.graftscope audit`)",
        file=sys.stderr)
  sys.exit(2)
