"""Checkpoint management: async orbax save/restore, continuous-eval
iteration, crash-safe backups.

Replaces the reference's TF Saver/scaffold machinery
(/root/reference/models/abstract_model.py:786-804), the async checkpoint
hooks (/root/reference/hooks/checkpoint_hooks.py), `checkpoints_iterator`
continuous eval and the retrying backup-copy logic
(/root/reference/utils/train_eval.py:585-733) with orbax:

* async checkpointing overlaps HBM->disk with the next train steps;
* restore is sharding-aware: params are restored directly into their mesh
  layout (no host-side detour);
* `checkpoints_iterator` polls a model_dir for new steps (continuous
  eval); `backup_checkpoint` hardlink-copies a checkpoint so a concurrent
  GC cannot delete it mid-eval.
"""

from __future__ import annotations

import os
import shutil
import time
from typing import Any, Iterator, Optional, Sequence

import jax
import orbax.checkpoint as ocp

from tensor2robot_tpu.utils import config

__all__ = ["CheckpointManager", "checkpoints_iterator", "backup_checkpoint",
           "latest_step"]


@config.configurable
class CheckpointManager:
  """Thin, spec-aware wrapper over orbax CheckpointManager."""

  def __init__(self,
               directory: str,
               max_to_keep: int = 5,
               save_interval_steps: int = 1,
               async_checkpointing: bool = True,
               keep_period: Optional[int] = None):
    self._directory = os.path.abspath(directory)
    os.makedirs(self._directory, exist_ok=True)
    options = ocp.CheckpointManagerOptions(
        max_to_keep=max_to_keep,
        save_interval_steps=save_interval_steps,
        keep_period=keep_period,
        enable_async_checkpointing=async_checkpointing)
    self._manager = ocp.CheckpointManager(self._directory, options=options)

  @property
  def directory(self) -> str:
    return self._directory

  def save(self, step: int, state: Any, force: bool = False) -> bool:
    return self._manager.save(step, args=ocp.args.StandardSave(state),
                              force=force)

  def restore(self, step: Optional[int] = None,
              abstract_state: Optional[Any] = None) -> Any:
    """Restores `step` (or latest). With `abstract_state` (a
    jax.eval_shape tree, optionally with shardings attached) the restore
    is sharded/in-layout."""
    if step is None:
      step = self.latest_step()
    if step is None:
      raise FileNotFoundError(f"No checkpoint in {self._directory}")
    if abstract_state is not None:
      return self._manager.restore(
          step, args=ocp.args.StandardRestore(abstract_state))
    return self._manager.restore(step)

  def latest_step(self) -> Optional[int]:
    return self._manager.latest_step()

  def all_steps(self):
    return self._manager.all_steps()

  def wait_until_finished(self) -> None:
    self._manager.wait_until_finished()

  def reached_preemption(self, step: int) -> bool:
    """True when the orchestrator signaled preemption (SIGTERM on Borg /
    GCE maintenance events). The train loop saves and exits cleanly so
    the next incarnation resumes losslessly — elastic behavior the
    reference lacks (SURVEY.md §5 'no preemption handling')."""
    try:
      return bool(self._manager.reached_preemption(step))
    except (AttributeError, NotImplementedError):
      return False  # orbax without preemption support on this platform
    except Exception:  # noqa: BLE001 - never lose the save, but say why
      from absl import logging

      logging.exception("reached_preemption check failed; treating as "
                        "preempted so the state is saved.")
      return True

  def close(self) -> None:
    self._manager.close()

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()


def warm_start_params(params, checkpoint_path: str,
                      filter_fn=None,
                      strict: bool = False):
  """Partial restore from a foreign checkpoint into freshly-init params.

  The reference's warm-start machinery: `default_init_from_checkpoint_fn`
  partial restore (/root/reference/models/abstract_model.py:86-126) and
  ResNet-pretrain init (/root/reference/layers/resnet.py:213-232). Leaves
  whose flattened path exists in the checkpoint with a matching shape are
  replaced; everything else keeps its fresh init. `filter_fn(path)` can
  deny-list leaves (e.g. heads). Returns (merged_params, restored_paths).
  """
  import jax
  import numpy as np

  with ocp.StandardCheckpointer() as checkpointer:
    restored = checkpointer.restore(os.path.abspath(checkpoint_path))
  # Accept either a bare params tree, an export-bundle variables dict, or
  # a full TrainState tree.
  if isinstance(restored, dict):
    if "params" in restored:
      restored = restored["params"]
  flat_restored = {
      jax.tree_util.keystr(path): leaf
      for path, leaf in jax.tree_util.tree_leaves_with_path(restored)}

  restored_paths = []

  def _merge(path, leaf):
    key = jax.tree_util.keystr(path)
    if filter_fn is not None and not filter_fn(key):
      return leaf
    candidate = flat_restored.get(key)
    if candidate is None or tuple(np.shape(candidate)) != tuple(
        np.shape(leaf)):
      if strict and candidate is None:
        raise ValueError(f"warm start: {key!r} missing from checkpoint")
      return leaf
    restored_paths.append(key)
    return np.asarray(candidate).astype(leaf.dtype)

  merged = jax.tree_util.tree_map_with_path(_merge, params)
  if not restored_paths:
    raise ValueError(
        f"Warm start from {checkpoint_path} restored nothing; checkpoint "
        f"keys: {sorted(flat_restored)[:10]}...")
  return merged, restored_paths


def latest_step(directory: str) -> Optional[int]:
  """Latest checkpoint step in a directory, without holding a manager."""
  if not os.path.isdir(directory):
    return None
  steps = []
  for name in os.listdir(directory):
    if name.isdigit() and os.path.isdir(os.path.join(directory, name)):
      steps.append(int(name))
  return max(steps) if steps else None


def checkpoints_iterator(directory: str,
                         timeout_secs: float = 10.0,
                         total_timeout_secs: Optional[float] = None,
                         min_interval_secs: float = 0.0
                         ) -> Iterator[int]:
  """Yields new checkpoint steps as they appear (the reference's
  continuous-eval driver, /root/reference/utils/train_eval.py:585-611)."""
  seen = set()
  start = time.time()
  while True:
    step = latest_step(directory)
    if step is not None and step not in seen:
      seen.add(step)
      yield step
      if min_interval_secs:
        time.sleep(min_interval_secs)
      continue
    if (total_timeout_secs is not None
        and time.time() - start > total_timeout_secs):
      return
    time.sleep(timeout_secs)


def backup_checkpoint(directory: str, step: int,
                      backup_root: Optional[str] = None,
                      max_attempts: int = 3) -> Optional[str]:
  """Copies a checkpoint out of GC's reach before a long eval (reference
  create_backup_checkpoint_for_eval + retrying save_copy,
  /root/reference/utils/train_eval.py:616-733). Retries if the writer
  races us; returns the backup path or None."""
  src = os.path.join(directory, str(step))
  backup_root = backup_root or os.path.join(directory, "eval_backup")
  dst = os.path.join(backup_root, str(step))
  for attempt in range(max_attempts):
    try:
      if os.path.isdir(dst):
        shutil.rmtree(dst)
      os.makedirs(backup_root, exist_ok=True)
      shutil.copytree(src, dst, copy_function=_link_or_copy)
      return dst
    except (FileNotFoundError, shutil.Error, OSError):
      if attempt == max_attempts - 1:
        return None
      time.sleep(0.5 * (attempt + 1))
  return None


def _link_or_copy(src: str, dst: str) -> None:
  try:
    os.link(src, dst)
  except OSError:
    shutil.copy2(src, dst)


def average_checkpoints(directory: str,
                        steps: Optional[Sequence[int]] = None,
                        last_n: int = 3):
  """Uniform parameter average over several checkpoints.

  Checkpoint averaging commonly buys robotics eval stability beyond a
  single EMA (a capability the reference lacks). Returns the averaged
  `params` tree from the TrainStates at `steps` (default: last_n
  available steps).
  """
  import numpy as np

  available = []
  for name in sorted(os.listdir(directory)):
    if name.isdigit():
      available.append(int(name))
  available.sort()
  if steps is None:
    steps = available[-last_n:]
  if not steps:
    raise ValueError(f"No checkpoints to average in {directory}")
  missing = [s for s in steps if s not in available]
  if missing:
    raise ValueError(f"Steps {missing} not found; available: {available}")
  total = None
  with ocp.StandardCheckpointer() as checkpointer:
    for step in steps:
      step_dir = os.path.join(directory, str(step))
      # CheckpointManager layout nests the state under an item dir
      # (named 'default' in current orbax); prefer it explicitly and
      # fall back deterministically.
      default_dir = os.path.join(step_dir, "default")
      if os.path.isdir(default_dir):
        target = default_dir
      else:
        item_dirs = sorted(
            os.path.join(step_dir, d) for d in os.listdir(step_dir)
            if os.path.isdir(os.path.join(step_dir, d)))
        target = item_dirs[0] if item_dirs else step_dir
      restored = checkpointer.restore(target)
      params = restored["params"] if "params" in restored else restored
      if total is None:
        total = jax.tree_util.tree_map(
            lambda x: np.asarray(x, np.float64), params)
      else:
        total = jax.tree_util.tree_map(
            lambda acc, x: acc + np.asarray(x, np.float64), total, params)
  n = float(len(steps))
  return jax.tree_util.tree_map(
      lambda x: (x / n).astype(np.float32), total)
