"""Checkpoint management: async orbax save/restore, continuous-eval
iteration, crash-safe backups.

Replaces the reference's TF Saver/scaffold machinery
(/root/reference/models/abstract_model.py:786-804), the async checkpoint
hooks (/root/reference/hooks/checkpoint_hooks.py), `checkpoints_iterator`
continuous eval and the retrying backup-copy logic
(/root/reference/utils/train_eval.py:585-733) with orbax:

* async checkpointing overlaps HBM->disk with the next train steps;
* restore is sharding-aware: params are restored directly into their mesh
  layout (no host-side detour);
* `checkpoints_iterator` polls a model_dir for new steps (continuous
  eval); `backup_checkpoint` hardlink-copies a checkpoint so a concurrent
  GC cannot delete it mid-eval.

graftguard checkpoint integrity (the recovery floor under divergence
rewind and fleet rollout): every completed save gets a checksummed
MANIFEST sidecar (`manifests/<step>.json`: per-file size + crc32,
written from the bytes on disk once the async save commits), restores
VERIFY against it, and a corrupt step — torn/truncated (restore
raises) or silently bit-flipped (checksum mismatch) — is QUARANTINED
(moved to `quarantine/<step>`, counted `ckpt/quarantined`) with
automatic fallback to the newest verified step instead of raising out
of `restore(step=None)`. Polling and backup-copy retries run under the
shared `utils.retry.RetryPolicy` (jittered backoff + telemetry)
instead of the previous bespoke constant-sleep loops. The
`obs.faultlab` points `ckpt.torn` / `ckpt.bitflip` corrupt a
just-saved step AFTER its manifest is written from the good bytes, so
chaos runs exercise exactly the detection the manifest exists for.
"""

from __future__ import annotations

import json
import os
import shutil
import time
import zlib
from typing import Any, Dict, Iterator, List, Optional, Sequence

import jax
import orbax.checkpoint as ocp

from tensor2robot_tpu.obs import faultlab as faultlab_lib
from tensor2robot_tpu.obs import metrics as metrics_lib
from tensor2robot_tpu.utils import config
from tensor2robot_tpu.utils import retry as retry_lib

__all__ = ["CheckpointManager", "CheckpointCorruptionError",
           "checkpoints_iterator", "backup_checkpoint", "latest_step",
           "write_manifest", "verify_step_files", "quarantine_step",
           "MANIFEST_DIRNAME", "QUARANTINE_DIRNAME"]

MANIFEST_DIRNAME = "manifests"
QUARANTINE_DIRNAME = "quarantine"
MANIFEST_SCHEMA = "graftguard-manifest-v1"


class CheckpointCorruptionError(RuntimeError):
  """A checkpoint failed integrity verification (or restore) and no
  intact fallback step exists."""


def _manifest_path(directory: str, step: int) -> str:
  return os.path.join(directory, MANIFEST_DIRNAME, f"{int(step)}.json")


def _step_files(step_dir: str) -> List[str]:
  """Relative paths of every file under a step dir, sorted."""
  out: List[str] = []
  for dirpath, dirnames, filenames in os.walk(step_dir):
    dirnames.sort()
    for name in sorted(filenames):
      out.append(os.path.relpath(os.path.join(dirpath, name), step_dir))
  return out


def _file_crc32(path: str) -> int:
  crc = 0
  with open(path, "rb") as f:
    for chunk in iter(lambda: f.read(1 << 20), b""):
      crc = zlib.crc32(chunk, crc)
  return crc & 0xFFFFFFFF


def write_manifest(directory: str, step: int) -> str:
  """Writes the checksummed manifest sidecar for one COMPLETE step dir
  (atomic tmp+rename; the sidecar lives OUTSIDE the step dir so orbax
  never sees an item it does not own). Returns the manifest path."""
  step_dir = os.path.join(directory, str(int(step)))
  files: Dict[str, Dict[str, int]] = {}
  for rel in _step_files(step_dir):
    path = os.path.join(step_dir, rel)
    files[rel] = {"size": os.path.getsize(path), "crc32": _file_crc32(path)}
  manifest = {"schema": MANIFEST_SCHEMA, "schema_version": 1,
              "step": int(step), "files": files}
  path = _manifest_path(directory, step)
  os.makedirs(os.path.dirname(path), exist_ok=True)
  tmp = path + ".tmp"
  with open(tmp, "w") as f:
    json.dump(manifest, f, sort_keys=True)
    f.flush()
    os.fsync(f.fileno())
  os.replace(tmp, path)
  metrics_lib.counter("ckpt/manifests_written").inc()
  return path


def verify_step_files(directory: str, step: int) -> Optional[bool]:
  """Verifies a step dir against its manifest: True (every listed file
  present with matching size+crc32), False (mismatch/missing — counted
  `ckpt/verify_failures`), or None (no readable manifest: pre-manifest
  checkpoints stay restorable, integrity enforced by the restore
  try/except instead)."""
  path = _manifest_path(directory, step)
  try:
    with open(path) as f:
      manifest = json.load(f)
    listed = manifest["files"]
  except (OSError, ValueError, KeyError, TypeError):
    return None
  step_dir = os.path.join(directory, str(int(step)))
  for rel, meta in listed.items():
    full = os.path.join(step_dir, rel)
    try:
      if os.path.getsize(full) != int(meta["size"]):
        metrics_lib.counter("ckpt/verify_failures").inc()
        return False
      if _file_crc32(full) != int(meta["crc32"]):
        metrics_lib.counter("ckpt/verify_failures").inc()
        return False
    except OSError:
      metrics_lib.counter("ckpt/verify_failures").inc()
      return False
  return True


def quarantine_step(directory: str, step: int, reason: str) -> Optional[str]:
  """Moves a corrupt step (and its manifest) to `quarantine/<step>` so
  no later `latest_step`/restore ever considers it again; counted
  `ckpt/quarantined`. Returns the quarantine path (None on failure —
  quarantining is best-effort, the fallback walk skips the step either
  way)."""
  from absl import logging

  step_dir = os.path.join(directory, str(int(step)))
  qdir = os.path.join(directory, QUARANTINE_DIRNAME)
  dst = os.path.join(qdir, str(int(step)))
  try:
    os.makedirs(qdir, exist_ok=True)
    if os.path.isdir(dst):  # a previous quarantine of the same step
      dst = f"{dst}.{int(time.time())}"
    shutil.move(step_dir, dst)
  except OSError:
    logging.exception("graftguard: quarantining checkpoint step %d failed",
                      step)
    return None
  manifest = _manifest_path(directory, step)
  if os.path.isfile(manifest):
    try:
      shutil.move(manifest, os.path.join(dst, "graftguard.manifest.json"))
    except OSError:
      pass
  metrics_lib.counter("ckpt/quarantined").inc()
  logging.warning("graftguard: checkpoint step %d QUARANTINED (%s) -> %s",
                  step, reason, dst)
  return dst


def _corrupt_step_for_faultlab(directory: str, step: int, mode: str) -> None:
  """Enacts a ckpt.torn / ckpt.bitflip fault on the LARGEST file of a
  completed step dir (deterministic target; called only by `save` after
  the manifest captured the good bytes)."""
  step_dir = os.path.join(directory, str(int(step)))
  candidates = [(os.path.getsize(os.path.join(step_dir, rel)), rel)
                for rel in _step_files(step_dir)]
  candidates = [(size, rel) for size, rel in candidates if size > 1]
  if not candidates:
    return
  _, rel = max(candidates, key=lambda sr: (sr[0], sr[1]))
  path = os.path.join(step_dir, rel)
  size = os.path.getsize(path)
  with open(path, "r+b") as f:
    if mode == "torn":
      f.truncate(size // 2)
    else:  # bitflip: one byte mid-file, the silent-corruption case
      f.seek(size // 2)
      byte = f.read(1)
      f.seek(size // 2)
      f.write(bytes([byte[0] ^ 0xFF]))
    f.flush()
    os.fsync(f.fileno())


def _step_looks_torn(directory: str, step: int) -> bool:
  """Structural verdict for a MANIFEST-LESS step dir whose restore just
  failed: torn bytes (quarantine + fall back) or a caller error on
  intact bytes (re-raise)? A step orbax committed is complete by
  construction (tmp-dir rename), so "intact" is checkable without a
  manifest: the dir exists, its `_CHECKPOINT_METADATA` parses, and no
  file in the tree is empty — a crashed foreign writer's partial dir
  fails one of these. Restore failures on a structurally intact dir
  (topology mismatch, wrong abstract_state, OOM) must NOT quarantine:
  that would displace every good pre-manifest checkpoint."""
  step_dir = os.path.join(directory, str(int(step)))
  if not os.path.isdir(step_dir):
    return True
  try:
    with open(os.path.join(step_dir, "_CHECKPOINT_METADATA")) as f:
      json.load(f)
  except (OSError, ValueError):
    return True
  for root, _, files in os.walk(step_dir):
    for name in files:
      try:
        if os.path.getsize(os.path.join(root, name)) == 0:
          return True
      except OSError:
        return True
  return False


@config.configurable
class CheckpointManager:
  """Thin, spec-aware wrapper over orbax CheckpointManager."""

  def __init__(self,
               directory: str,
               max_to_keep: int = 5,
               save_interval_steps: int = 1,
               async_checkpointing: bool = True,
               keep_period: Optional[int] = None):
    self._directory = os.path.abspath(directory)
    os.makedirs(self._directory, exist_ok=True)
    self._options = ocp.CheckpointManagerOptions(
        max_to_keep=max_to_keep,
        save_interval_steps=save_interval_steps,
        keep_period=keep_period,
        enable_async_checkpointing=async_checkpointing)
    self._manager = ocp.CheckpointManager(self._directory,
                                          options=self._options)
    # The step actually restored by the most recent restore() on this
    # manager (the fallback walk may land below the requested/latest
    # step; serving hot-swap reads its new model_version from this).
    self.last_restored_step: Optional[int] = None
    # Manifests are written ONLY for steps THIS manager saved: the
    # saver is the one party that knows the bytes on disk are good. A
    # manager writing a manifest for a step dir it merely found —
    # e.g. at restore time — would bless whatever is there, including
    # a torn dir, defeating the verification entirely. (Steps from
    # earlier processes without a manifest stay restorable; the
    # restore try/except + quarantine walk guards them instead.)
    self._pending_manifest_steps: set = set()

  @property
  def directory(self) -> str:
    return self._directory

  def save(self, step: int, state: Any, force: bool = False) -> bool:
    saved = self._manager.save(step, args=ocp.args.StandardSave(state),
                               force=force)
    if saved:
      self._pending_manifest_steps.add(int(step))
      fault = (faultlab_lib.maybe_fire(faultlab_lib.CKPT_TORN)
               or faultlab_lib.maybe_fire(faultlab_lib.CKPT_BITFLIP))
      if fault is not None:
        # Chaos path: commit the async save, write the manifest from
        # the GOOD bytes, then corrupt — the injected fault must be
        # exactly the one the manifest checksums exist to catch.
        self._manager.wait_until_finished()
        write_manifest(self._directory, step)
        self._pending_manifest_steps.discard(int(step))
        _corrupt_step_for_faultlab(
            self._directory, step,
            "torn" if fault.point == faultlab_lib.CKPT_TORN else "bitflip")
      else:
        self._write_pending_manifests()
    return saved

  def _fs_steps(self) -> List[int]:
    """Steps present ON DISK (digit-named dirs; quarantined steps are
    gone from here by construction). The filesystem is the truth the
    integrity walk needs — orbax's cached step list survives a
    quarantine move and would happily restore a step that no longer
    exists."""
    steps = []
    if os.path.isdir(self._directory):
      for name in os.listdir(self._directory):
        if name.isdigit() and os.path.isdir(
            os.path.join(self._directory, name)):
          steps.append(int(name))
    return sorted(steps)

  def _write_pending_manifests(self) -> None:
    """Writes manifests for COMMITTED steps this manager saved (save
    tracks them; orbax commits an async step by dir rename, so a
    digit-named dir existing means its bytes are complete — an
    in-flight step still lives under a tmp name and is skipped until
    the next call). Never raises — integrity bookkeeping must not
    kill a save."""
    try:
      on_disk = self._fs_steps()
      newest = on_disk[-1] if on_disk else None
      for step in sorted(self._pending_manifest_steps):
        step_dir = os.path.join(self._directory, str(step))
        if not os.path.isdir(step_dir):
          if newest is not None and step < newest:
            self._pending_manifest_steps.discard(step)  # GC'd (max_to_keep)
          continue  # else: still in flight
        if not os.path.isfile(_manifest_path(self._directory, step)):
          write_manifest(self._directory, step)
        self._pending_manifest_steps.discard(step)
    except Exception:  # noqa: BLE001 - see docstring
      from absl import logging

      logging.exception("graftguard: manifest write failed")

  def verify_step(self, step: int) -> Optional[bool]:
    """Manifest verification for one step (see `verify_step_files`)."""
    return verify_step_files(self._directory, step)

  def latest_verified_step(self) -> Optional[int]:
    """Newest step that does not FAIL manifest verification (steps
    without a manifest pass — restore still guards them). The rewind
    target lookup."""
    for step in reversed(self._fs_steps()):
      if self.verify_step(step) is not False:
        return step
    return None

  def restore(self, step: Optional[int] = None,
              abstract_state: Optional[Any] = None) -> Any:
    """Restores `step` (or the newest VERIFIED step). With
    `abstract_state` (a jax.eval_shape tree, optionally with shardings
    attached) the restore is sharded/in-layout.

    Integrity contract (graftguard): every candidate step is verified
    against its manifest first; a corrupt step (checksum mismatch, or
    a torn dir whose restore raises while its manifest is absent/
    failing) is QUARANTINED and — for `step=None` — the walk falls
    back to the next-newest step instead of raising. A restore failure
    on a step whose manifest VERIFIED clean is not corruption (wrong
    abstract state, topology mismatch) and re-raises unchanged — as
    does one on a manifest-less step that is structurally intact
    (`_step_looks_torn`), so pre-manifest checkpoints are never
    displaced by a caller error. An explicit `step` that turns out
    corrupt raises `CheckpointCorruptionError`; an explicit step not
    on disk raises `FileNotFoundError` — the caller asked for that
    step specifically."""
    self.wait_until_finished()  # commits async saves + writes manifests
    explicit = step is not None
    on_disk = self._fs_steps()
    if explicit and int(step) not in on_disk:
      # A missing explicit step (GC'd by max_to_keep, never saved, or
      # already quarantined) is not-found, not corruption.
      raise FileNotFoundError(
          f"checkpoint step {step} not found in {self._directory}")
    candidates = [int(step)] if explicit else list(reversed(on_disk))
    if not candidates:
      raise FileNotFoundError(f"No checkpoint in {self._directory}")
    last_error: Optional[BaseException] = None
    for candidate in candidates:
      verdict = self.verify_step(candidate)
      if verdict is False:
        quarantine_step(self._directory, candidate, "checksum mismatch")
        self._reload_manager()
        if explicit:
          raise CheckpointCorruptionError(
              f"checkpoint step {candidate} in {self._directory} failed "
              "manifest verification (quarantined)")
        continue
      try:
        # Always pass StandardRestore args: the no-target form keeps a
        # read-only manager (which never registered a save handler)
        # restorable — `self._manager.restore(step)` bare raises
        # KeyError('default') on such managers under orbax 0.7.
        restored = self._manager.restore(
            candidate, args=ocp.args.StandardRestore(abstract_state))
        self.last_restored_step = candidate
        return restored
      except Exception as e:  # noqa: BLE001 - classified below
        if verdict is True:
          # Bytes verified clean: this is a caller/topology error, not
          # corruption — surfacing it is the only honest move.
          raise
        if verdict is None and not _step_looks_torn(self._directory,
                                                    candidate):
          # No manifest to consult (pre-manifest/legacy step), but the
          # dir is structurally intact: a restore failure here is a
          # caller error too — quarantining would displace every good
          # legacy checkpoint on e.g. a changed abstract_state.
          raise
        last_error = e
        quarantine_step(self._directory, candidate,
                        f"restore failed: {type(e).__name__}: {e}")
        self._reload_manager()
        if explicit:
          raise CheckpointCorruptionError(
              f"checkpoint step {candidate} in {self._directory} is torn "
              "(restore failed; quarantined)") from e
        metrics_lib.counter("ckpt/restore_fallbacks").inc()
    raise CheckpointCorruptionError(
        f"no intact checkpoint in {self._directory}: every candidate "
        f"step was quarantined") from last_error

  def _reload_manager(self) -> None:
    """Rebuilds the orbax manager after a quarantine move: its cached
    step list would re-offer the quarantined step, and `reload()`
    leaves the default-item handler registry unusable for later
    no-args restores (observed on orbax 0.7.0) — a fresh manager has
    neither problem."""
    try:
      self._manager.close()
    except Exception:  # noqa: BLE001 - the old manager may be wedged
      pass
    self._manager = ocp.CheckpointManager(self._directory,
                                          options=self._options)

  def latest_step(self) -> Optional[int]:
    steps = self._fs_steps()
    return steps[-1] if steps else None

  def all_steps(self):
    return self._fs_steps()

  def wait_until_finished(self) -> None:
    self._manager.wait_until_finished()
    self._write_pending_manifests()

  def reached_preemption(self, step: int) -> bool:
    """True when the orchestrator signaled preemption (SIGTERM on Borg /
    GCE maintenance events). The train loop saves and exits cleanly so
    the next incarnation resumes losslessly — elastic behavior the
    reference lacks (SURVEY.md §5 'no preemption handling')."""
    try:
      return bool(self._manager.reached_preemption(step))
    except (AttributeError, NotImplementedError):
      return False  # orbax without preemption support on this platform
    except Exception:  # noqa: BLE001 - never lose the save, but say why
      from absl import logging

      logging.exception("reached_preemption check failed; treating as "
                        "preempted so the state is saved.")
      return True

  def close(self) -> None:
    self._manager.close()

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()


def warm_start_params(params, checkpoint_path: str,
                      filter_fn=None,
                      strict: bool = False):
  """Partial restore from a foreign checkpoint into freshly-init params.

  The reference's warm-start machinery: `default_init_from_checkpoint_fn`
  partial restore (/root/reference/models/abstract_model.py:86-126) and
  ResNet-pretrain init (/root/reference/layers/resnet.py:213-232). Leaves
  whose flattened path exists in the checkpoint with a matching shape are
  replaced; everything else keeps its fresh init. `filter_fn(path)` can
  deny-list leaves (e.g. heads). Returns (merged_params, restored_paths).
  """
  import jax
  import numpy as np

  with ocp.StandardCheckpointer() as checkpointer:
    restored = checkpointer.restore(os.path.abspath(checkpoint_path))
  # Accept either a bare params tree, an export-bundle variables dict, or
  # a full TrainState tree.
  if isinstance(restored, dict):
    if "params" in restored:
      restored = restored["params"]
  flat_restored = {
      jax.tree_util.keystr(path): leaf
      for path, leaf in jax.tree_util.tree_leaves_with_path(restored)}

  restored_paths = []

  def _merge(path, leaf):
    key = jax.tree_util.keystr(path)
    if filter_fn is not None and not filter_fn(key):
      return leaf
    candidate = flat_restored.get(key)
    if candidate is None or tuple(np.shape(candidate)) != tuple(
        np.shape(leaf)):
      if strict and candidate is None:
        raise ValueError(f"warm start: {key!r} missing from checkpoint")
      return leaf
    restored_paths.append(key)
    return np.asarray(candidate).astype(leaf.dtype)

  merged = jax.tree_util.tree_map_with_path(_merge, params)
  if not restored_paths:
    raise ValueError(
        f"Warm start from {checkpoint_path} restored nothing; checkpoint "
        f"keys: {sorted(flat_restored)[:10]}...")
  return merged, restored_paths


def latest_step(directory: str) -> Optional[int]:
  """Latest checkpoint step in a directory, without holding a manager."""
  if not os.path.isdir(directory):
    return None
  steps = []
  for name in os.listdir(directory):
    if name.isdigit() and os.path.isdir(os.path.join(directory, name)):
      steps.append(int(name))
  return max(steps) if steps else None


def checkpoints_iterator(directory: str,
                         timeout_secs: float = 10.0,
                         total_timeout_secs: Optional[float] = None,
                         min_interval_secs: float = 0.0
                         ) -> Iterator[int]:
  """Yields new checkpoint steps as they appear (the reference's
  continuous-eval driver, /root/reference/utils/train_eval.py:585-611).

  The poll sleep is jittered around `timeout_secs`
  (`utils.retry.jittered_s`) so N continuous-eval pollers on one
  filesystem de-synchronize instead of stat-ing in lockstep."""
  seen = set()
  start = time.time()
  while True:
    step = latest_step(directory)
    if step is not None and step not in seen:
      seen.add(step)
      yield step
      if min_interval_secs:
        time.sleep(min_interval_secs)
      continue
    if (total_timeout_secs is not None
        and time.time() - start > total_timeout_secs):
      return
    time.sleep(retry_lib.jittered_s(timeout_secs, jitter=0.25))


def backup_checkpoint(directory: str, step: int,
                      backup_root: Optional[str] = None,
                      max_attempts: int = 3) -> Optional[str]:
  """Copies a checkpoint out of GC's reach before a long eval (reference
  create_backup_checkpoint_for_eval + retrying save_copy,
  /root/reference/utils/train_eval.py:616-733). Retries under the
  shared `RetryPolicy` (jittered backoff, `retry/ckpt_backup/*`
  telemetry) if the writer races us; returns the backup path or None."""
  src = os.path.join(directory, str(step))
  backup_root = backup_root or os.path.join(directory, "eval_backup")
  dst = os.path.join(backup_root, str(step))

  def _copy() -> str:
    if os.path.isdir(dst):
      shutil.rmtree(dst)
    os.makedirs(backup_root, exist_ok=True)
    shutil.copytree(src, dst, copy_function=_link_or_copy)
    return dst

  policy = retry_lib.RetryPolicy(
      name="ckpt_backup", max_attempts=max_attempts, base_delay_s=0.5,
      multiplier=1.5, max_delay_s=2.0,
      retryable=lambda e: isinstance(e, (OSError, shutil.Error)))
  try:
    return policy.call(_copy)
  except retry_lib.RetryBudgetExhausted:
    return None


def _link_or_copy(src: str, dst: str) -> None:
  try:
    os.link(src, dst)
  except OSError:
    shutil.copy2(src, dst)


def average_checkpoints(directory: str,
                        steps: Optional[Sequence[int]] = None,
                        last_n: int = 3):
  """Uniform parameter average over several checkpoints.

  Checkpoint averaging commonly buys robotics eval stability beyond a
  single EMA (a capability the reference lacks). Returns the averaged
  `params` tree from the TrainStates at `steps` (default: last_n
  available steps).
  """
  import numpy as np

  available = []
  for name in sorted(os.listdir(directory)):
    if name.isdigit():
      available.append(int(name))
  available.sort()
  if steps is None:
    steps = available[-last_n:]
  if not steps:
    raise ValueError(f"No checkpoints to average in {directory}")
  missing = [s for s in steps if s not in available]
  if missing:
    raise ValueError(f"Steps {missing} not found; available: {available}")
  total = None
  with ocp.StandardCheckpointer() as checkpointer:
    for step in steps:
      step_dir = os.path.join(directory, str(step))
      # CheckpointManager layout nests the state under an item dir
      # (named 'default' in current orbax); prefer it explicitly and
      # fall back deterministically.
      default_dir = os.path.join(step_dir, "default")
      if os.path.isdir(default_dir):
        target = default_dir
      else:
        item_dirs = sorted(
            os.path.join(step_dir, d) for d in os.listdir(step_dir)
            if os.path.isdir(os.path.join(step_dir, d)))
        target = item_dirs[0] if item_dirs else step_dir
      restored = checkpointer.restore(target)
      params = restored["params"] if "params" in restored else restored
      if total is None:
        total = jax.tree_util.tree_map(
            lambda x: np.asarray(x, np.float64), params)
      else:
        total = jax.tree_util.tree_map(
            lambda acc, x: acc + np.asarray(x, np.float64), total, params)
  n = float(len(steps))
  return jax.tree_util.tree_map(
      lambda x: (x / n).astype(np.float32), total)
