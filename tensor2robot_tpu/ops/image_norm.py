"""Device-side image normalization that respects the compute dtype.

The reference casts uint8 images to float inside each network
(/root/reference/research/qtopt/networks.py input conversions and the
bfloat16_scope in tpu_model_wrapper.py:185-191). In flax, a module that
writes ``image.astype(jnp.float32) / 255`` poisons the whole tower: every
layer promotes to the widest input dtype, so one f32 activation silently
turns all bf16-policy convolutions into f32 (measured on the Grasping44
train step: 47/47 f32 convolutions before this fix). Normalizing INTO the
module's compute dtype keeps the tower on the MXU's bf16 path.
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp

__all__ = ["normalize_image"]


def normalize_image(image: jnp.ndarray,
                    dtype: Optional[Any] = None) -> jnp.ndarray:
  """uint8 [0, 255] -> float [0, 1] in ``dtype``; float passes through.

  Args:
    image: integer wire image or an already-normalized float image.
    dtype: the module's compute dtype (e.g. ``jnp.bfloat16`` under the
      bfloat16 policy). ``None`` keeps float32 for integer inputs and
      leaves float inputs' dtype untouched.
  """
  # jnp.asarray first: on a raw numpy input, numpy's promotion rules would
  # turn `bf16_array / 255.0` back into float32; jax weak typing keeps the
  # requested dtype (and is a no-op on tracers inside jit).
  if jnp.issubdtype(image.dtype, jnp.integer):
    image = jnp.asarray(image).astype(dtype or jnp.float32) / 255.0
  elif dtype is not None and image.dtype != dtype:
    image = jnp.asarray(image).astype(dtype)
  return image
