"""Attention ops: fused flash attention + ring attention for sequence
parallelism.

The reference has no attention-scale sequence machinery at all
(SURVEY.md §5 "long-context: none") — its longest-sequence handling is
SequenceExample padding and GRU/SNAIL layers. This module adds the
long-context capability TPU-first:

* `attention` — reference jnp implementation (any backend);
* `flash_attention` — Pallas TPU kernel: block-streamed online softmax
  so the [T, T] score matrix never materializes in HBM (O(T) memory);
* `ring_attention` — context parallelism over a mesh axis: each device
  holds a sequence shard, K/V blocks rotate around the ICI ring via
  `ppermute` inside `shard_map` while the online-softmax accumulator
  absorbs one block per hop. Exact (not approximate) attention over
  sequences `axis_size`x longer than one chip's memory; compute and
  ring transfers overlap under XLA's async collectives.

All functions take [batch, heads, seq, head_dim] ("BHTD") arrays.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

__all__ = ["attention", "flash_attention", "ring_attention"]


def _mask_value(dtype) -> jnp.ndarray:
  return jnp.asarray(jnp.finfo(dtype).min / 2, dtype)


def attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
              causal: bool = False) -> jnp.ndarray:
  """Reference softmax attention, [B, H, T, D]."""
  scale = 1.0 / math.sqrt(q.shape[-1])
  scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
  if causal:
    tq, tk = scores.shape[-2], scores.shape[-1]
    mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
    scores = jnp.where(mask, scores, _mask_value(scores.dtype))
  weights = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
  return jnp.einsum("bhqk,bhkd->bhqd", weights.astype(q.dtype), v)


# -- online-softmax block update (shared by flash + ring) -------------------


def _online_block_update(q, k_blk, v_blk, m_prev, l_prev, o_prev,
                         score_mask=None):
  """Absorbs one K/V block into the running (max, denom, output).

  q: [..., Tq, D]; k_blk/v_blk: [..., Tk, D];
  m_prev/l_prev: [..., Tq]; o_prev: [..., Tq, D] (unnormalized
  numerator). Returns updated (m, l, o).
  """
  scale = 1.0 / math.sqrt(q.shape[-1])
  s = jnp.einsum("...qd,...kd->...qk", q, k_blk).astype(jnp.float32) * scale
  if score_mask is not None:
    s = jnp.where(score_mask, s, _mask_value(s.dtype))
  m_new = jnp.maximum(m_prev, s.max(axis=-1))
  alpha = jnp.exp(m_prev - m_new)
  p = jnp.exp(s - m_new[..., None])
  l_new = l_prev * alpha + p.sum(axis=-1)
  o_new = (o_prev * alpha[..., None]
           + jnp.einsum("...qk,...kd->...qd", p.astype(v_blk.dtype),
                        v_blk).astype(jnp.float32))
  return m_new, l_new, o_new


def _finalize(o, l):
  return o / jnp.maximum(l[..., None], 1e-30)


# -- Pallas flash attention --------------------------------------------------


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int,
                  causal: bool, q_block: int):
  """One (batch*head, q_block) program: stream K/V blocks through VMEM."""
  q = q_ref[:]  # [block_q, D]
  tq_idx = pl.program_id(1)
  seq_len = k_ref.shape[0]
  num_k_blocks = seq_len // block_k
  if causal:
    # Future blocks are fully masked: stop the stream at the diagonal.
    num_k_blocks = jnp.minimum(
        num_k_blocks,
        ((tq_idx + 1) * q_block + block_k - 1) // block_k)

  def body(kb, carry):
    m, l, o = carry
    k_blk = k_ref[pl.ds(kb * block_k, block_k), :]
    v_blk = v_ref[pl.ds(kb * block_k, block_k), :]
    mask = None
    if causal:
      q_pos = tq_idx * q_block + jax.lax.broadcasted_iota(
          jnp.int32, (q_block, block_k), 0)
      k_pos = kb * block_k + jax.lax.broadcasted_iota(
          jnp.int32, (q_block, block_k), 1)
      mask = q_pos >= k_pos
    return _online_block_update(q, k_blk, v_blk, m, l, o, mask)

  m0 = jnp.full((q_block,), -jnp.inf, jnp.float32)
  l0 = jnp.zeros((q_block,), jnp.float32)
  o0 = jnp.zeros((q_block, q.shape[-1]), jnp.float32)
  m, l, o = jax.lax.fori_loop(0, num_k_blocks, body, (m0, l0, o0))
  o_ref[:] = _finalize(o, l).astype(o_ref.dtype)


try:  # Pallas import kept soft so CPU-only deployments still import us.
  from jax.experimental import pallas as pl
  from jax.experimental.pallas import tpu as pltpu

  _HAS_PALLAS = True
except Exception:  # pragma: no cover
  _HAS_PALLAS = False


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    causal: bool = False,
                    block_q: int = 128, block_k: int = 128,
                    interpret: bool = False) -> jnp.ndarray:
  """Pallas flash attention, [B, H, T, D]; falls back to `attention`
  when the sequence doesn't tile or Pallas is unavailable."""
  b, h, t, d = q.shape
  if (not _HAS_PALLAS) or t % block_q or t % block_k:
    return attention(q, k, v, causal=causal)
  q3 = q.reshape(b * h, t, d)
  k3 = k.reshape(b * h, t, d)
  v3 = v.reshape(b * h, t, d)
  kernel = functools.partial(_flash_kernel, block_k=block_k,
                             causal=causal, q_block=block_q)
  out = pl.pallas_call(
      kernel,
      grid=(b * h, t // block_q),
      in_specs=[
          pl.BlockSpec((None, block_q, d), lambda bh, qb: (bh, qb, 0)),
          pl.BlockSpec((None, t, d), lambda bh, qb: (bh, 0, 0)),
          pl.BlockSpec((None, t, d), lambda bh, qb: (bh, 0, 0)),
      ],
      out_specs=pl.BlockSpec((None, block_q, d), lambda bh, qb: (bh, qb, 0)),
      out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
      interpret=interpret,
  )(q3, k3, v3)
  return out.reshape(b, h, t, d)


# -- ring attention (context parallelism) ------------------------------------


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   mesh: Mesh,
                   axis_name: str = "sp",
                   causal: bool = False,
                   batch_axis: Optional[str] = "data") -> jnp.ndarray:
  """Exact attention with the sequence dim sharded over `axis_name`.

  Inputs are global [B, H, T, D] arrays (T divisible by the axis size).
  Each device keeps its Q shard resident and absorbs one rotating K/V
  block per ring hop; `ppermute` rides the ICI ring. Returns the global
  [B, H, T, D] output with the same sharding.
  """
  axis_size = mesh.shape[axis_name]
  io_spec = PartitionSpec(batch_axis, None, axis_name, None)

  def local_fn(q_local, k_local, v_local):
    idx = jax.lax.axis_index(axis_name)
    tq = q_local.shape[2]
    m = jnp.full(q_local.shape[:-1], -jnp.inf, jnp.float32)
    l = jnp.zeros(q_local.shape[:-1], jnp.float32)
    o = jnp.zeros(q_local.shape, jnp.float32)
    k_blk, v_blk = k_local, v_local
    for step in range(axis_size):
      src = (idx - step) % axis_size  # whose shard we currently hold
      mask = None
      if causal:
        q_pos = idx * tq + jnp.arange(tq)
        k_pos = src * tq + jnp.arange(tq)
        mask = q_pos[:, None] >= k_pos[None, :]
        mask = mask[None, None]  # broadcast over [B, H]
      m, l, o = _online_block_update(q_local, k_blk, v_blk, m, l, o, mask)
      if step + 1 < axis_size:
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
    return _finalize(o, l).astype(q_local.dtype)

  sharded = jax.shard_map(
      local_fn, mesh=mesh,
      in_specs=(io_spec, io_spec, io_spec),
      out_specs=io_spec,
      check_vma=False)
  return sharded(q, k, v)
