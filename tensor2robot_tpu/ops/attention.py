"""Attention ops: fused flash attention + ring attention for sequence
parallelism.

The reference has no attention-scale sequence machinery at all
(SURVEY.md §5 "long-context: none") — its longest-sequence handling is
SequenceExample padding and GRU/SNAIL layers. This module adds the
long-context capability TPU-first:

* `attention` — reference jnp implementation (any backend);
* `flash_attention` — Pallas TPU kernel: block-streamed online softmax
  so the [T, T] score matrix never materializes in HBM (O(T) memory);
* `ring_attention` — context parallelism over a mesh axis: each device
  holds a sequence shard, K/V blocks rotate around the ICI ring via
  `ppermute` inside `shard_map` while the online-softmax accumulator
  absorbs one block per hop. Exact (not approximate) attention over
  sequences `axis_size`x longer than one chip's memory; compute and
  ring transfers overlap under XLA's async collectives.

All functions take [batch, heads, seq, head_dim] ("BHTD") arrays.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

from tensor2robot_tpu.parallel import mesh as mesh_lib

__all__ = ["attention", "cached_attention", "flash_attention",
           "ring_attention", "ulysses_attention",
           "note_pallas_unavailable"]


def _mask_value(dtype) -> jnp.ndarray:
  return jnp.asarray(jnp.finfo(dtype).min / 2, dtype)


def attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
              causal: bool = False) -> jnp.ndarray:
  """Reference softmax attention, [B, H, T, D]."""
  scale = 1.0 / math.sqrt(q.shape[-1])
  scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
  if causal:
    tq, tk = scores.shape[-2], scores.shape[-1]
    mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
    scores = jnp.where(mask, scores, _mask_value(scores.dtype))
  weights = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
  return jnp.einsum("bhqk,bhkd->bhqd", weights.astype(q.dtype), v)


def cached_attention(q_t: jnp.ndarray, k_cache: jnp.ndarray,
                     v_cache: jnp.ndarray, index: jnp.ndarray
                     ) -> jnp.ndarray:
  """One decode tick against a per-session KV cache: O(1) attention work
  per step instead of the O(T) full-prefix re-run (ISSUE 11 / PAPERS.md
  "Portable O(1) Autoregressive Caching for Inference").

  q_t: [B, H, D] — this tick's single query per session;
  k_cache/v_cache: [B, T_max, H, D] — T-major so the serving arena's
  per-session append is one advanced-index `.at[rows, index].set`;
  index: [B] int32 — each session's CURRENT tick (sessions in one
  continuous-batching dispatch sit at different episode positions).

  Numerics are pinned to row `index` of `attention(..., causal=True)`:
  positions past a session's index score `_mask_value` — exactly what
  the causal mask assigns them there — so the f32 softmax sees the same
  masked score row and `exp` underflows them to exactly 0.
  """
  scale = 1.0 / math.sqrt(q_t.shape[-1])
  scores = jnp.einsum("bhd,bthd->bht", q_t, k_cache) * scale
  valid = jnp.arange(k_cache.shape[1])[None, :] <= index[:, None]  # [B,T]
  scores = jnp.where(valid[:, None, :], scores, _mask_value(scores.dtype))
  weights = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
  return jnp.einsum("bht,bthd->bhd", weights.astype(q_t.dtype), v_cache)


# -- online-softmax block update (shared by flash + ring) -------------------


def _online_block_update(q, k_blk, v_blk, m_prev, l_prev, o_prev,
                         score_mask=None):
  """Absorbs one K/V block into the running (max, denom, output).

  q: [..., Tq, D]; k_blk/v_blk: [..., Tk, D];
  m_prev/l_prev: [..., Tq]; o_prev: [..., Tq, D] (unnormalized
  numerator). Returns updated (m, l, o).
  """
  scale = 1.0 / math.sqrt(q.shape[-1])
  s = jnp.einsum("...qd,...kd->...qk", q, k_blk,
                 preferred_element_type=jnp.float32) * scale
  if score_mask is not None:
    s = jnp.where(score_mask, s, _mask_value(s.dtype))
  m_new = jnp.maximum(m_prev, s.max(axis=-1))
  alpha = jnp.exp(m_prev - m_new)
  p = jnp.exp(s - m_new[..., None])
  l_new = l_prev * alpha + p.sum(axis=-1)
  o_new = (o_prev * alpha[..., None]
           + jnp.einsum("...qk,...kd->...qd", p.astype(v_blk.dtype),
                        v_blk, preferred_element_type=jnp.float32))
  return m_new, l_new, o_new


def _finalize(o, l):
  return o / jnp.maximum(l[..., None], 1e-30)


# -- Pallas flash attention --------------------------------------------------
#
# Forward: FlashAttention online softmax; also emits the per-row
# logsumexp needed by the backward. Backward: FlashAttention-2 style
# recompute kernels (one producing dQ over the q-block grid, one
# producing dK/dV over the k-block grid) — the [T, T] score matrix never
# materializes in HBM in either direction. Sequences that don't tile are
# PADDED to the block size and masked (never a silent O(T^2) fallback).


def _valid_mask(q_start, k_start, q_block, k_block, causal: bool,
                valid_len: int, padded_len: int):
  """Score-entry validity: causal triangle + key/query padding."""
  if not causal and valid_len == padded_len:
    return None
  q_pos = q_start + jax.lax.broadcasted_iota(
      jnp.int32, (q_block, k_block), 0)
  k_pos = k_start + jax.lax.broadcasted_iota(
      jnp.int32, (q_block, k_block), 1)
  mask = jnp.ones((q_block, k_block), bool)
  if causal:
    mask &= q_pos >= k_pos
  if valid_len != padded_len:
    mask &= (k_pos < valid_len) & (q_pos < valid_len)
  return mask


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                      block_k: int, causal: bool, q_block: int,
                      valid_len: int):
  """One (batch*head, q_block) program: stream K/V blocks through VMEM."""
  q = q_ref[:]  # [block_q, D]
  tq_idx = pl.program_id(1)
  seq_len = k_ref.shape[0]
  num_k_blocks = seq_len // block_k
  if causal:
    # Future blocks are fully masked: stop the stream at the diagonal.
    num_k_blocks = jnp.minimum(
        num_k_blocks,
        ((tq_idx + 1) * q_block + block_k - 1) // block_k)

  def body(kb, carry):
    m, l, o = carry
    k_blk = k_ref[pl.ds(kb * block_k, block_k), :]
    v_blk = v_ref[pl.ds(kb * block_k, block_k), :]
    mask = _valid_mask(tq_idx * q_block, kb * block_k, q_block, block_k,
                       causal, valid_len, seq_len)
    return _online_block_update(q, k_blk, v_blk, m, l, o, mask)

  m0 = jnp.full((q_block,), -jnp.inf, jnp.float32)
  l0 = jnp.zeros((q_block,), jnp.float32)
  o0 = jnp.zeros((q_block, q.shape[-1]), jnp.float32)
  m, l, o = jax.lax.fori_loop(0, num_k_blocks, body, (m0, l0, o0))
  o_ref[:] = _finalize(o, l).astype(o_ref.dtype)
  # logsumexp per query row, stored [T, 1]: the trailing unit lane dim
  # keeps the block shape inside Mosaic's (8, 128)-divisible-or-whole
  # tiling rule for EVERY block_q (a [T]-flat lse blocked at block_q
  # fails TPU lowering whenever 8 <= block_q < 128 — caught by the
  # local Mosaic lowering tests; interpret mode hides it).
  # Fully-masked (padded) rows would otherwise carry
  # lse = mask_value + log(block) ~ -1e38, making the backward recompute
  # exp(s - lse) overflow before its own mask zeroes it; pin those rows
  # to 0 (their p is masked to 0 in the backward anyway). Validity is
  # positional: a row is real iff its query index < valid_len (for
  # causal rows the diagonal entry is always unmasked, so l > 0).
  # broadcasted_iota, not 1D lax.iota: Mosaic rejects 1D iota at compile
  # time (TPU vectors are 2D sublane x lane; interpret mode hides this).
  q_pos = tq_idx * q_block + jax.lax.broadcasted_iota(
      jnp.int32, (q_block, 1), 0)
  row_valid = q_pos < valid_len
  lse = (m + jnp.log(jnp.maximum(l, 1e-30)))[:, None]
  lse_ref[:] = jnp.where(row_valid, lse, 0.0)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, block_k: int, causal: bool,
                         q_block: int, valid_len: int):
  """dQ for one q block: dS = P * (dO.V^T - delta); dQ = scale * dS.K."""
  scale = 1.0 / math.sqrt(q_ref.shape[-1])
  q = q_ref[:]
  do = do_ref[:].astype(jnp.float32)
  lse = lse_ref[:]      # [block_q, 1]
  delta = delta_ref[:]  # [block_q, 1]
  tq_idx = pl.program_id(1)
  seq_len = k_ref.shape[0]
  num_k_blocks = seq_len // block_k
  if causal:
    num_k_blocks = jnp.minimum(
        num_k_blocks,
        ((tq_idx + 1) * q_block + block_k - 1) // block_k)

  def body(kb, dq):
    k_blk = k_ref[pl.ds(kb * block_k, block_k), :]
    v_blk = v_ref[pl.ds(kb * block_k, block_k), :]
    s = jnp.matmul(q, k_blk.T,
                   preferred_element_type=jnp.float32) * scale
    p = jnp.exp(s - lse)
    mask = _valid_mask(tq_idx * q_block, kb * block_k, q_block, block_k,
                       causal, valid_len, seq_len)
    if mask is not None:
      p = jnp.where(mask, p, 0.0)
    dp = jnp.matmul(do, v_blk.T, preferred_element_type=jnp.float32)
    ds = p * (dp - delta) * scale
    return dq + jnp.matmul(ds, k_blk,
                           preferred_element_type=jnp.float32)

  dq0 = jnp.zeros((q_block, q.shape[-1]), jnp.float32)
  dq_ref[:] = jax.lax.fori_loop(0, num_k_blocks, body, dq0).astype(
      dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, block_q: int, causal: bool,
                          k_block: int, valid_len: int):
  """dK/dV for one k block: dV = P^T.dO; dK = scale * dS^T.Q."""
  scale = 1.0 / math.sqrt(q_ref.shape[-1])
  k_blk = k_ref[:]
  v_blk = v_ref[:]
  tk_idx = pl.program_id(1)
  seq_len = q_ref.shape[0]
  num_q_blocks = seq_len // block_q
  start_q = 0
  if causal:
    # Blocks strictly above the diagonal see no unmasked entries.
    start_q = (tk_idx * k_block) // block_q

  def body(qb, carry):
    dk, dv = carry
    q_blk = q_ref[pl.ds(qb * block_q, block_q), :]
    do_blk = do_ref[pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
    lse_blk = lse_ref[pl.ds(qb * block_q, block_q), :]    # [block_q, 1]
    delta_blk = delta_ref[pl.ds(qb * block_q, block_q), :]
    s = jnp.matmul(q_blk, k_blk.T,
                   preferred_element_type=jnp.float32) * scale
    p = jnp.exp(s - lse_blk)
    mask = _valid_mask(qb * block_q, tk_idx * k_block, block_q, k_block,
                       causal, valid_len, seq_len)
    if mask is not None:
      p = jnp.where(mask, p, 0.0)
    dv = dv + jnp.matmul(p.T, do_blk,
                         preferred_element_type=jnp.float32)
    dp = jnp.matmul(do_blk, v_blk.T,
                    preferred_element_type=jnp.float32)
    ds = p * (dp - delta_blk) * scale
    dk = dk + jnp.matmul(ds.T, q_blk,
                         preferred_element_type=jnp.float32)
    return dk, dv

  dk0 = jnp.zeros((k_block, k_blk.shape[-1]), jnp.float32)
  dv0 = jnp.zeros((k_block, v_blk.shape[-1]), jnp.float32)
  dk, dv = jax.lax.fori_loop(start_q, num_q_blocks, body, (dk0, dv0))
  dk_ref[:] = dk.astype(dk_ref.dtype)
  dv_ref[:] = dv.astype(dv_ref.dtype)


try:  # Pallas import kept soft so CPU-only deployments still import us.
  from jax.experimental import pallas as pl
  from jax.experimental.pallas import tpu as pltpu  # noqa: F401

  _HAS_PALLAS = True
  _PALLAS_IMPORT_ERROR: Optional[str] = None
except Exception as _pallas_import_exc:  # pragma: no cover
  _HAS_PALLAS = False
  _PALLAS_IMPORT_ERROR = (f"{type(_pallas_import_exc).__name__}: "
                          f"{_pallas_import_exc}")

# Sites that already emitted their one-time pallas-unavailable warning
# (the use_native_stager discipline: a silent capability degrade is a
# debugging trap — the flash tier used to fall back to the O(T^2)
# reference with no trace of WHY).
_PALLAS_WARNED_SITES = set()


def note_pallas_unavailable(site: str) -> None:
  """Records one pallas-unavailable degrade: bumps the
  `ops/pallas_unavailable` counter every time and WARNs once per call
  site with the captured import error, so a fleet quietly serving the
  reference fallback is visible in metrics and logs instead of only in
  its latency."""
  from tensor2robot_tpu.obs import metrics as obs_metrics

  obs_metrics.counter("ops/pallas_unavailable").inc()
  if site not in _PALLAS_WARNED_SITES:
    _PALLAS_WARNED_SITES.add(site)
    from absl import logging

    logging.warning(
        "%s: pallas kernel tier unavailable (%s); falling back to the "
        "XLA reference implementation.", site,
        _PALLAS_IMPORT_ERROR or "import failed")


def _flash_forward(q3, k3, v3, causal, block_q, block_k, valid_len,
                   interpret):
  bh, t, d = q3.shape
  kernel = functools.partial(
      _flash_fwd_kernel, block_k=block_k, causal=causal, q_block=block_q,
      valid_len=valid_len)
  out, lse = pl.pallas_call(
      kernel,
      grid=(bh, t // block_q),
      in_specs=[
          pl.BlockSpec((None, block_q, d), lambda b, qb: (b, qb, 0)),
          pl.BlockSpec((None, t, d), lambda b, qb: (b, 0, 0)),
          pl.BlockSpec((None, t, d), lambda b, qb: (b, 0, 0)),
      ],
      out_specs=[
          pl.BlockSpec((None, block_q, d), lambda b, qb: (b, qb, 0)),
          pl.BlockSpec((None, block_q, 1), lambda b, qb: (b, qb, 0)),
      ],
      out_shape=[
          jax.ShapeDtypeStruct((bh, t, d), q3.dtype),
          jax.ShapeDtypeStruct((bh, t, 1), jnp.float32),
      ],
      interpret=interpret,
  )(q3, k3, v3)
  return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4))
def _flash(causal: bool, block_q: int, block_k: int, valid_len: int,
           interpret: bool, q3, k3, v3):
  out, _ = _flash_forward(q3, k3, v3, causal, block_q, block_k,
                          valid_len, interpret)
  return out


def _flash_fwd(causal, block_q, block_k, valid_len, interpret, q3, k3, v3):
  out, lse = _flash_forward(q3, k3, v3, causal, block_q, block_k,
                            valid_len, interpret)
  return out, (q3, k3, v3, out, lse)


def _flash_bwd(causal, block_q, block_k, valid_len, interpret, residuals,
               g):
  q3, k3, v3, out, lse = residuals
  bh, t, d = q3.shape
  # delta_i = sum_d dO_id * O_id (FlashAttention-2 backward precompute).
  delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                  axis=-1, keepdims=True)  # [bh, t, 1], lse layout
  dq_kernel = functools.partial(
      _flash_bwd_dq_kernel, block_k=block_k, causal=causal,
      q_block=block_q, valid_len=valid_len)
  dq = pl.pallas_call(
      dq_kernel,
      grid=(bh, t // block_q),
      in_specs=[
          pl.BlockSpec((None, block_q, d), lambda b, qb: (b, qb, 0)),
          pl.BlockSpec((None, t, d), lambda b, qb: (b, 0, 0)),
          pl.BlockSpec((None, t, d), lambda b, qb: (b, 0, 0)),
          pl.BlockSpec((None, block_q, d), lambda b, qb: (b, qb, 0)),
          pl.BlockSpec((None, block_q, 1), lambda b, qb: (b, qb, 0)),
          pl.BlockSpec((None, block_q, 1), lambda b, qb: (b, qb, 0)),
      ],
      out_specs=pl.BlockSpec((None, block_q, d), lambda b, qb: (b, qb, 0)),
      out_shape=jax.ShapeDtypeStruct((bh, t, d), q3.dtype),
      interpret=interpret,
  )(q3, k3, v3, g, lse, delta)
  dkv_kernel = functools.partial(
      _flash_bwd_dkv_kernel, block_q=block_q, causal=causal,
      k_block=block_k, valid_len=valid_len)
  dk, dv = pl.pallas_call(
      dkv_kernel,
      grid=(bh, t // block_k),
      in_specs=[
          pl.BlockSpec((None, t, d), lambda b, kb: (b, 0, 0)),
          pl.BlockSpec((None, block_k, d), lambda b, kb: (b, kb, 0)),
          pl.BlockSpec((None, block_k, d), lambda b, kb: (b, kb, 0)),
          pl.BlockSpec((None, t, d), lambda b, kb: (b, 0, 0)),
          pl.BlockSpec((None, t, 1), lambda b, kb: (b, 0, 0)),
          pl.BlockSpec((None, t, 1), lambda b, kb: (b, 0, 0)),
      ],
      out_specs=[
          pl.BlockSpec((None, block_k, d), lambda b, kb: (b, kb, 0)),
          pl.BlockSpec((None, block_k, d), lambda b, kb: (b, kb, 0)),
      ],
      out_shape=[
          jax.ShapeDtypeStruct((bh, t, d), k3.dtype),
          jax.ShapeDtypeStruct((bh, t, d), v3.dtype),
      ],
      interpret=interpret,
  )(q3, k3, v3, g, lse, delta)
  return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


def _next_pow2(n: int) -> int:
  return 1 << (n - 1).bit_length()


def _pow2_floor(n: int) -> int:
  return 1 << (n.bit_length() - 1)


# Minimum block edge: Mosaic tiles f32 at (8, 128); sub-8 q/k blocks can
# fail to compile on real TPU hardware (CPU tests run the interpreter and
# would not catch it).
_MIN_BLOCK = 8


def _default_blocks(t: int) -> Tuple[int, int]:
  """Measured-winner block sizes (v5e, 2026-07-31 on-chip duel,
  scripts/tpu_flash_tune.py): the original 128x128 default LOSES to
  plain XLA attention in fwd+bwd wall-clock (T=4096: 9.59 vs 7.00 ms;
  T=8192: 40.25 vs 28.20) — tiny matmuls leave the MXU idle and
  VPU-softmax dominates. Tuned blocks flip it decisively:
  T=4096 bq=bk=1024 -> 2.98 ms (2.35x over XLA); T=8192 bq=256 bk=512
  -> 14.28 ms (1.97x). VMEM ceilings bound the blocks: BLOCK_Q >= 512
  at T > 4096 dies in compile (bwd block temporaries exceed the 16 MB
  scoped-VMEM stack; block_k=512 with bq=256 is fine and is the T=8192
  winner), and 1024x1024 at T=4096, which fits standalone, overflows
  by 312 KB inside the full train-step graph — so the T<=4096 default
  stays one notch safer (512x512 = 3.61 ms standalone, still 1.94x
  over XLA)."""
  if t <= 4096:
    return (512, 512)
  return (256, 512)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    causal: bool = False,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
  """Pallas flash attention, [B, H, T, D]. Fully differentiable
  (custom FlashAttention-2 backward kernels).

  Sequences that don't tile the block size are padded to the next block
  multiple and masked — never a silent O(T^2) fallback. `interpret=None`
  auto-selects PER LOWERING PLATFORM: real kernels in TPU-target
  programs, the interpreter elsewhere (CPU tests). Cross-attention
  (Tq != Tk) falls back to the reference implementation (the kernels
  assume self-attention layout). `block_q`/`block_k` default to the
  on-chip measured winners for the sequence length (`_default_blocks`).
  """
  b, h, t, d = q.shape
  if block_q is None or block_k is None:
    auto_bq, auto_bk = _default_blocks(t)
    block_q = auto_bq if block_q is None else block_q
    block_k = auto_bk if block_k is None else block_k
  if not _HAS_PALLAS:
    note_pallas_unavailable("flash_attention")
    return attention(q, k, v, causal=causal)
  if k.shape[2] != t:
    return attention(q, k, v, causal=causal)
  if interpret is None:
    # lax.platform_dependent, NOT jax.default_backend(): the process
    # backend bakes the HOST platform into the trace, so AOT-lowering a
    # TPU-topology program from a CPU host silently compiled (and cost-
    # priced) the interpreter emulation instead of the Mosaic kernel in
    # every path that relied on the auto-select (round-5 review catch;
    # pinned by test_default_interpret_lowers_mosaic_for_tpu). The
    # platform switch folds away in single-platform lowerings. The
    # barriers keep XLA:TPU from staging the cond's operands/results in
    # scoped VMEM at long T (same failure mode as the in-kernel
    # barriers below — 16 MB "stack" allocations at T=8192/h512).
    q, k, v = jax.lax.optimization_barrier((q, k, v))
    return jax.lax.optimization_barrier(jax.lax.platform_dependent(
        q, k, v,
        tpu=functools.partial(flash_attention, causal=causal,
                              block_q=block_q, block_k=block_k,
                              interpret=False),
        default=functools.partial(flash_attention, causal=causal,
                                  block_q=block_q, block_k=block_k,
                                  interpret=True)))
  # Normalize blocks to powers of two in [_MIN_BLOCK, next_pow2(T)]: the
  # padding arithmetic below relies on lcm(bq, bk) == max(bq, bk), which
  # only holds for powers of two.
  eff_bq = max(_MIN_BLOCK, min(_pow2_floor(block_q), _next_pow2(t)))
  eff_bk = max(_MIN_BLOCK, min(_pow2_floor(block_k), _next_pow2(t)))
  tile = max(eff_bq, eff_bk)
  t_pad = ((t + tile - 1) // tile) * tile
  assert t_pad % eff_bq == 0 and t_pad % eff_bk == 0
  q3 = q.reshape(b * h, t, d)
  k3 = k.reshape(b * h, t, d)
  v3 = v.reshape(b * h, t, d)
  if t_pad != t:
    pad = ((0, 0), (0, t_pad - t), (0, 0))
    q3 = jnp.pad(q3, pad)
    k3 = jnp.pad(k3, pad)
    v3 = jnp.pad(v3, pad)
  if not interpret:
    # XLA:TPU fuses surrounding layout ops (the model layer's
    # BTHD->BHTD head-split transposes, the non-tiling-T pads above)
    # into the custom-call's scoped-VMEM region; at long T the fused
    # operands/results exceed VMEM and compilation fails with
    # RESOURCE_EXHAUSTED "allocating on stack" (found at T=8192/h512 by
    # the round-5 seqattn duel — interpret mode hid it, like the
    # round-4 lse blocker). The barrier — placed directly on the kernel
    # operands, AFTER any padding — pins them to plain HBM buffers;
    # since its transpose rule is itself a barrier, the backward
    # kernels get the same protection. Pinned by TestFlashMosaicLowering
    # test_long_context_train_graph_compiles.
    q3, k3, v3 = jax.lax.optimization_barrier((q3, k3, v3))
  out = _flash(causal, eff_bq, eff_bk, t, interpret, q3, k3, v3)
  if not interpret:
    out = jax.lax.optimization_barrier(out)  # see the entry barrier
  if t_pad != t:
    out = out[:, :t]
  return out.reshape(b, h, t, d)


# -- Ulysses attention (all_to_all sequence parallelism) ---------------------


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      mesh: Mesh,
                      axis_name: str = "sp",
                      causal: bool = False,
                      batch_axis: Optional[str] = "data",
                      inner: str = "reference",
                      flash_interpret: Optional[bool] = None) -> jnp.ndarray:
  """Exact attention with the sequence dim sharded via head all_to_all
  (DeepSpeed-Ulysses style).

  Inputs are global [B, H, T, D] arrays with T sharded over `axis_name`
  (size S). all_to_alls re-shard q/k/v to [B, H/S, T, D] — each device
  holds its head group over the FULL sequence — the inner attention runs
  unchanged (including causal masking), and a transpose all_to_all
  restores the output's sequence sharding. Communication is 4
  activation-sized all_to_alls per forward (q, k, v inbound + output; 8
  with the VJP) in a FIXED number of steps, vs the ring's S-1 sequential
  K/V hops — at the cost of H % S == 0. The right trade when heads are
  plentiful and per-hop ring latency would dominate.

  `inner` selects the full-sequence kernel on each device: 'reference'
  (XLA) or 'flash' (the Pallas kernel).
  """
  s = mesh.shape[axis_name]
  b, h, t, d = q.shape
  if h % s:
    raise ValueError(f"num_heads={h} must be divisible by the "
                     f"'{axis_name}' axis size {s} for Ulysses "
                     f"(head-group all_to_all)")
  if k.shape[2] != t:
    raise ValueError("ulysses_attention assumes self-attention layout "
                     f"(Tq={t} != Tk={k.shape[2]})")
  if inner not in ("reference", "flash"):
    raise ValueError(f"Unknown inner kernel {inner!r}")
  io_spec = PartitionSpec(batch_axis, None, axis_name, None)

  def local_fn(q_l, k_l, v_l):
    # Shapes here are LOCAL: [B_l, H, T/S, D]. Both all_to_alls use the
    # symmetric split_axis == concat_axis == 0 form with explicit
    # transposes around them: the form with distinct split/concat axes
    # produced a mis-ordered cotangent under autodiff whenever H/S > 1
    # (dims swapped in the VJP), while the 0,0 form is self-transpose.
    b_l, _, t_l, _ = q_l.shape

    def seq_to_heads(x):
      # [B_l,H,T_l,D] -> [S,B_l,H/S,T_l,D] -(a2a)-> src-major ->
      # [B_l,H/S,T,D]; source order == sequence order, so the merge
      # reassembles the global sequence.
      x = x.reshape(b_l, s, h // s, t_l, d)
      x = jnp.moveaxis(x, 1, 0)
      x = jax.lax.all_to_all(x, axis_name, 0, 0)   # [S(src),B_l,H/S,T_l,D]
      x = x.transpose(1, 2, 0, 3, 4)               # [B_l,H/S,S,T_l,D]
      return x.reshape(b_l, h // s, s * t_l, d)

    def heads_to_seq(x):
      # inverse: [B_l,H/S,T,D] -> [S,B_l,H/S,T_l,D] -(a2a)->
      # head-group-major -> [B_l,H,T_l,D]
      x = x.reshape(b_l, h // s, s, t_l, d)
      x = x.transpose(2, 0, 1, 3, 4)               # [S,B_l,H/S,T_l,D]
      x = jax.lax.all_to_all(x, axis_name, 0, 0)   # [S(grp),B_l,H/S,T_l,D]
      x = jnp.moveaxis(x, 0, 1)                    # [B_l,S,H/S,T_l,D]
      return x.reshape(b_l, h, t_l, d)

    q_g, k_g, v_g = seq_to_heads(q_l), seq_to_heads(k_l), seq_to_heads(v_l)
    if inner == "flash":
      out = flash_attention(q_g, k_g, v_g, causal=causal,
                            interpret=flash_interpret)
    else:
      out = attention(q_g, k_g, v_g, causal=causal)
    return heads_to_seq(out)

  sharded = mesh_lib.shard_map(
      local_fn, mesh=mesh,
      in_specs=(io_spec, io_spec, io_spec),
      out_specs=io_spec)
  return sharded(q, k, v)


# -- ring attention (context parallelism) ------------------------------------


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   mesh: Mesh,
                   axis_name: str = "sp",
                   causal: bool = False,
                   batch_axis: Optional[str] = "data",
                   block_k: Optional[int] = None) -> jnp.ndarray:
  """Exact attention with the sequence dim sharded over `axis_name`.

  Inputs are global [B, H, T, D] arrays (T divisible by the axis size).
  Each device keeps its Q shard resident and absorbs one rotating K/V
  block per ring hop; `ppermute` rides the ICI ring. Returns the global
  [B, H, T, D] output with the same sharding.

  `block_k` additionally chunks each hop's K/V block through the online
  softmax (a lax.scan), bounding per-hop score memory at
  [B, H, Tq_local, block_k] instead of [B, H, Tq_local, Tk_local] —
  flash-style streaming inside the ring, useful when the per-device
  shard is itself long. Must divide the local block length.
  """
  axis_size = mesh.shape[axis_name]
  if block_k is not None and (k.shape[2] // axis_size) % block_k:
    raise ValueError(
        f"block_k={block_k} must divide the per-device K length "
        f"{k.shape[2] // axis_size} (T={k.shape[2]} over "
        f"{axis_size} '{axis_name}' shards)")
  io_spec = PartitionSpec(batch_axis, None, axis_name, None)

  def local_fn(q_local, k_local, v_local):
    idx = jax.lax.axis_index(axis_name)
    tq = q_local.shape[2]
    m = jnp.full(q_local.shape[:-1], -jnp.inf, jnp.float32)
    l = jnp.zeros(q_local.shape[:-1], jnp.float32)
    o = jnp.zeros(q_local.shape, jnp.float32)
    k_blk, v_blk = k_local, v_local

    def absorb(src, m, l, o, k_blk, v_blk):
      q_pos = idx * tq + jnp.arange(tq)
      if block_k is None:
        mask = None
        if causal:
          k_pos = src * tq + jnp.arange(tq)
          mask = (q_pos[:, None] >= k_pos[None, :])[None, None]
        return _online_block_update(q_local, k_blk, v_blk, m, l, o, mask)
      num_chunks = k_blk.shape[2] // block_k  # divisibility checked above
      # [C, B, H, block_k, D] chunk-major for the scan.
      k_chunks = jnp.moveaxis(
          k_blk.reshape(k_blk.shape[:2] + (num_chunks, block_k, -1)),
          2, 0)
      v_chunks = jnp.moveaxis(
          v_blk.reshape(v_blk.shape[:2] + (num_chunks, block_k, -1)),
          2, 0)

      def chunk_step(carry, chunk):
        m, l, o = carry
        c_idx, k_c, v_c = chunk
        mask = None
        if causal:
          k_pos = src * tq + c_idx * block_k + jnp.arange(block_k)
          mask = (q_pos[:, None] >= k_pos[None, :])[None, None]
        return _online_block_update(q_local, k_c, v_c, m, l, o, mask), None

      (m, l, o), _ = jax.lax.scan(
          chunk_step, (m, l, o),
          (jnp.arange(num_chunks), k_chunks, v_chunks))
      return m, l, o

    for step in range(axis_size):
      src = (idx - step) % axis_size  # whose shard we currently hold
      m, l, o = absorb(src, m, l, o, k_blk, v_blk)
      if step + 1 < axis_size:
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
    return _finalize(o, l).astype(q_local.dtype)

  sharded = mesh_lib.shard_map(
      local_fn, mesh=mesh,
      in_specs=(io_spec, io_spec, io_spec),
      out_specs=io_spec)
  return sharded(q, k, v)
