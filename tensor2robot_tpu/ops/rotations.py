"""Rotation representations: quaternion / axis-angle / matrix conversions.

The reference leans on tensorflow_graphics for quaternion math in BC-Z
(/root/reference/research/bcz/model.py:32 imports tensorflow_graphics;
pose components use axis-angle and quaternion forms). That dependency is
unavailable here, so the needed closed forms are implemented directly in
jnp — batched, branch-free where possible, jit/grad-safe.

Conventions: quaternions are [..., 4] in (w, x, y, z) order, normalized;
axis-angle is [..., 3] with angle encoded as the vector norm.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["quaternion_normalize", "quaternion_multiply",
           "quaternion_conjugate", "quaternion_rotate",
           "quaternion_to_axis_angle", "axis_angle_to_quaternion",
           "quaternion_to_rotation_matrix", "geodesic_distance"]

_EPS = 1e-8


def quaternion_normalize(q: jnp.ndarray) -> jnp.ndarray:
  return q / jnp.maximum(jnp.linalg.norm(q, axis=-1, keepdims=True), _EPS)


def quaternion_multiply(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
  aw, ax, ay, az = jnp.split(a, 4, axis=-1)
  bw, bx, by, bz = jnp.split(b, 4, axis=-1)
  return jnp.concatenate([
      aw * bw - ax * bx - ay * by - az * bz,
      aw * bx + ax * bw + ay * bz - az * by,
      aw * by - ax * bz + ay * bw + az * bx,
      aw * bz + ax * by - ay * bx + az * bw,
  ], axis=-1)


def quaternion_conjugate(q: jnp.ndarray) -> jnp.ndarray:
  return q * jnp.asarray([1.0, -1.0, -1.0, -1.0], q.dtype)


def quaternion_rotate(q: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
  """Rotates vectors [..., 3] by quaternions [..., 4]."""
  zeros = jnp.zeros_like(v[..., :1])
  qv = jnp.concatenate([zeros, v], axis=-1)
  return quaternion_multiply(
      quaternion_multiply(q, qv), quaternion_conjugate(q))[..., 1:]


def axis_angle_to_quaternion(axis_angle: jnp.ndarray) -> jnp.ndarray:
  # Safe norm: sqrt of a clamped sum keeps gradients finite at zero
  # (plain jnp.linalg.norm has a NaN gradient at 0).
  sq = (axis_angle ** 2).sum(-1, keepdims=True)
  angle = jnp.sqrt(jnp.maximum(sq, _EPS ** 2))
  half = 0.5 * angle
  small = sq < 1e-12
  # Double-where so the untaken branch contributes no NaN gradients.
  safe_angle = jnp.where(small, 1.0, angle)
  sinc_half = jnp.where(small, 0.5 - sq / 48.0,
                        jnp.sin(0.5 * safe_angle) / safe_angle)
  w = jnp.cos(half)
  xyz = axis_angle * sinc_half
  return jnp.concatenate([w, xyz], axis=-1)


def quaternion_to_axis_angle(q: jnp.ndarray) -> jnp.ndarray:
  q = quaternion_normalize(q)
  # Force w >= 0 so the angle is in [0, pi] (shortest arc).
  q = jnp.where(q[..., :1] < 0, -q, q)
  w = jnp.clip(q[..., :1], -1.0, 1.0)
  xyz = q[..., 1:]
  sin_half = jnp.linalg.norm(xyz, axis=-1, keepdims=True)
  angle = 2.0 * jnp.arctan2(sin_half, w)
  small = sin_half < 1e-6
  scale = jnp.where(small, 2.0, angle / jnp.maximum(sin_half, _EPS))
  return xyz * scale


def quaternion_to_rotation_matrix(q: jnp.ndarray) -> jnp.ndarray:
  q = quaternion_normalize(q)
  w, x, y, z = jnp.split(q, 4, axis=-1)
  row0 = jnp.concatenate([1 - 2 * (y ** 2 + z ** 2),
                          2 * (x * y - w * z),
                          2 * (x * z + w * y)], axis=-1)
  row1 = jnp.concatenate([2 * (x * y + w * z),
                          1 - 2 * (x ** 2 + z ** 2),
                          2 * (y * z - w * x)], axis=-1)
  row2 = jnp.concatenate([2 * (x * z - w * y),
                          2 * (y * z + w * x),
                          1 - 2 * (x ** 2 + y ** 2)], axis=-1)
  return jnp.stack([row0, row1, row2], axis=-2)


def geodesic_distance(q1: jnp.ndarray, q2: jnp.ndarray) -> jnp.ndarray:
  """Angle of the relative rotation — the natural orientation loss."""
  q1 = quaternion_normalize(q1)
  q2 = quaternion_normalize(q2)
  dot = jnp.abs((q1 * q2).sum(-1))
  return 2.0 * jnp.arccos(jnp.clip(dot, 0.0, 1.0))
