from tensor2robot_tpu.ops import attention, cem, pcgrad, rotations
