"""PCGrad gradient surgery for multi-task training.

Reference: /root/reference/research/qtopt/pcgrad.py:29-244 — an optimizer
wrapper that projects each task's gradient onto the normal plane of
conflicting tasks' gradients before summing, with allow/deny-listed
variables and either per-variable or flattened projection.

TPU-native form: a pure function over a list of per-task gradient pytrees
(computed with `jax.grad` per task inside the jitted step — the K backward
passes XLA-fuse with the forward). Composes with any optax chain: surgery
happens before `optimizer.update`.
"""

from __future__ import annotations

import re
from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp

__all__ = ["pcgrad_combine"]


def _tree_dot(a, b) -> jnp.ndarray:
  leaves = jax.tree_util.tree_map(lambda x, y: jnp.vdot(x, y), a, b)
  return sum(jax.tree_util.tree_leaves(leaves))


def _tree_sq_norm(a) -> jnp.ndarray:
  return _tree_dot(a, a)


def _project_out(g_task, g_other, use_flat: bool):
  """g_task minus its conflicting component along g_other."""
  if use_flat:
    dot = _tree_dot(g_task, g_other)
    sq = _tree_sq_norm(g_other) + 1e-12
    coeff = jnp.minimum(dot / sq, 0.0)  # only when conflicting (dot < 0)
    return jax.tree_util.tree_map(lambda gt, go: gt - coeff * go,
                                  g_task, g_other)
  # per-variable projection
  def _per_leaf(gt, go):
    dot = jnp.vdot(gt, go)
    sq = jnp.vdot(go, go) + 1e-12
    coeff = jnp.minimum(dot / sq, 0.0)
    return gt - coeff * go

  return jax.tree_util.tree_map(_per_leaf, g_task, g_other)


def _mask_tree(tree, keep_fn):
  return jax.tree_util.tree_map_with_path(
      lambda path, leaf: leaf if keep_fn(jax.tree_util.keystr(path))
      else jnp.zeros_like(leaf), tree)


def pcgrad_combine(task_grads: Sequence[Any],
                   key: Optional[jax.Array] = None,
                   use_flat_projection: bool = False,
                   allowlist: Optional[Sequence[str]] = None,
                   denylist: Optional[Sequence[str]] = None) -> Any:
  """Combines per-task gradients with PCGrad surgery.

  Args:
    task_grads: one gradient pytree per task.
    key: optional PRNG key to randomize task projection order (the
      reference shuffles tasks); None keeps the given order (deterministic
      and jit-cache friendly).
    use_flat_projection: project in the full flattened gradient space
      instead of per variable.
    allowlist / denylist: regexes over param paths; surgery applies only
      to allowed, non-denied leaves — others get the plain gradient sum.

  Returns:
    A single combined gradient pytree.
  """
  task_grads = list(task_grads)
  n = len(task_grads)
  if n == 1:
    return task_grads[0]

  def _keep(path: str) -> bool:
    if denylist and any(re.search(p, path) for p in denylist):
      return False
    if allowlist:
      return any(re.search(p, path) for p in allowlist)
    return True

  filtered = task_grads
  if allowlist or denylist:
    filtered = [_mask_tree(g, _keep) for g in task_grads]

  order = list(range(n))
  projected = []
  for i in order:
    g = filtered[i]
    if key is not None:
      key, perm_key = jax.random.split(key)
      # jit-safe random projection order: permuted fori_loop with a
      # dynamic gather; the self-projection (j == i) is masked out.
      perm = jax.random.permutation(perm_key, n)

      def body(k, g_acc, i=i, perm=perm):
        j = perm[k]
        g_other = jax.tree_util.tree_map(
            lambda *leaves: jnp.stack(leaves)[j], *filtered)
        g_proj = _project_out(g_acc, g_other, use_flat_projection)
        return jax.tree_util.tree_map(
            lambda acc, proj: jnp.where(j == i, acc, proj), g_acc, g_proj)

      g = jax.lax.fori_loop(0, n, body, g)
      projected.append(g)
      continue
    for j in order:
      if j == i:
        continue
      g = _project_out(g, filtered[j], use_flat_projection)
    projected.append(g)

  combined = jax.tree_util.tree_map(
      lambda *leaves: sum(leaves), *projected)
  if allowlist or denylist:
    # Surgery-exempt leaves: plain sum of raw grads.
    raw_sum = jax.tree_util.tree_map(lambda *leaves: sum(leaves),
                                     *task_grads)
    combined = jax.tree_util.tree_map_with_path(
        lambda path, surg, raw: surg
        if _keep(jax.tree_util.keystr(path)) else raw,
        combined, raw_sum)
  return combined
