"""Cross-entropy method optimizer.

Reference: a generic numpy CEM used by serving policies to maximize the
critic over actions (/root/reference/utils/cross_entropy.py:30-154;
defaults 64 samples x 3 iterations, 10 elites,
/root/reference/policies/policies.py:110-116).

Two implementations:
* `cross_entropy_method` — jittable (`lax.fori_loop`), batched over
  observations, runs entirely on device so CEM serving rides the MXU
  (score all candidates in one batched forward pass);
* `CrossEntropyMethod` — the numpy-callable adapter for host-side
  objective functions (e.g. a remote predictor).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["cross_entropy_method", "CrossEntropyMethod"]


def cross_entropy_method(
    key: jax.Array,
    objective_fn: Callable[[jnp.ndarray], jnp.ndarray],
    mean: jnp.ndarray,
    stddev: jnp.ndarray,
    num_samples: int = 64,
    num_iterations: int = 3,
    num_elites: int = 10,
    low: Optional[jnp.ndarray] = None,
    high: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
  """Maximizes objective_fn over action vectors.

  Args:
    key: PRNG key.
    objective_fn: [num_samples, action_dim] -> [num_samples] scores.
      (Batch over observations by vmapping this whole function.)
    mean / stddev: [action_dim] initial sampling distribution.
    low / high: optional clipping bounds.

  Returns:
    (best_action [action_dim], best_score [], final_mean [action_dim]).
  """
  if num_elites < 2:
    # The Bessel-corrected (ddof=1) stddev update is 0/0 on one elite.
    raise ValueError("num_elites must be >= 2 for the stddev update.")
  action_dim = mean.shape[-1]

  def body(i, carry):
    key, mean, stddev, best_action, best_score = carry
    key, sample_key = jax.random.split(key)
    samples = mean + stddev * jax.random.normal(
        sample_key, (num_samples, action_dim))
    if low is not None:
      samples = jnp.clip(samples, low, high)
    scores = objective_fn(samples)
    elite_idx = jax.lax.top_k(scores, num_elites)[1]
    elites = samples[elite_idx]
    new_mean = elites.mean(0)
    # ddof=1 (Bessel): the reference's normal-CEM update uses the sample
    # stddev of the elites (cross_entropy.py:141-143).
    new_stddev = elites.std(0, ddof=1) + 1e-6
    top_idx = elite_idx[0]
    better = scores[top_idx] > best_score
    best_action = jnp.where(better, samples[top_idx], best_action)
    best_score = jnp.where(better, scores[top_idx], best_score)
    return key, new_mean, new_stddev, best_action, best_score

  init = (key, mean, stddev, jnp.zeros_like(mean),
          jnp.asarray(-jnp.inf, jnp.float32))
  _, final_mean, _, best_action, best_score = jax.lax.fori_loop(
      0, num_iterations, body, init)
  return best_action, best_score, final_mean


class CrossEntropyMethod:
  """Host-side numpy CEM with a pluggable objective (reference API)."""

  def __init__(self,
               num_samples: int = 64,
               num_iterations: int = 3,
               num_elites: int = 10,
               early_termination_stddev: float = 0.0,
               seed: Optional[int] = None):
    if num_elites > num_samples:
      raise ValueError("num_elites must be <= num_samples.")
    if num_elites < 2:
      # The Bessel-corrected (ddof=1) stddev update is 0/0 on one elite
      # (the reference's np.std(..., ddof=1) NaNs there too).
      raise ValueError("num_elites must be >= 2 for the stddev update.")
    self._num_samples = num_samples
    self._num_iterations = num_iterations
    self._num_elites = num_elites
    self._early_stddev = early_termination_stddev
    self._rng = np.random.RandomState(seed)

  def optimize(self,
               objective_fn: Callable[[np.ndarray], np.ndarray],
               mean: np.ndarray,
               stddev: np.ndarray,
               low: Optional[np.ndarray] = None,
               high: Optional[np.ndarray] = None
               ) -> Tuple[np.ndarray, float]:
    """Returns (best_action, best_score)."""
    mean = np.asarray(mean, np.float32)
    stddev = np.asarray(stddev, np.float32)
    best_action, best_score = None, -np.inf
    for _ in range(self._num_iterations):
      samples = mean + stddev * self._rng.randn(
          self._num_samples, mean.shape[-1]).astype(np.float32)
      if low is not None:
        samples = np.clip(samples, low, high)
      scores = np.asarray(objective_fn(samples)).reshape(-1)
      elite_idx = np.argsort(scores)[-self._num_elites:]
      elites = samples[elite_idx]
      mean = elites.mean(0)
      # ddof=1 (Bessel): matches the reference normal-CEM update
      # (cross_entropy.py:141-143) — pinned by the executed-parity test
      # that runs the reference implementation on the same draws.
      stddev = elites.std(0, ddof=1)
      if scores[elite_idx[-1]] > best_score:
        best_score = float(scores[elite_idx[-1]])
        best_action = samples[elite_idx[-1]]
      if self._early_stddev and float(stddev.max()) < self._early_stddev:
        break
    # Final sampling-distribution parameters, for callers (and the
    # executed-parity tests) that track the distribution rather than the
    # argmax — the reference's NormalCrossEntropyMethod return surface.
    self.final_mean_ = mean
    self.final_stddev_ = stddev
    return best_action, best_score
