"""graftkern: Pallas decode-tick kernels — fused cached-attention +
arena gather/append/scatter for O(1) session ticks (ISSUE 20).

The session decode tick is the innermost serving loop: every robot pays
it at control frequency. Up to PR 19 it was XLA-default — the reference
re-ran a SavedModel end to end per control tick
(/root/reference/predictors/exported_savedmodel_predictor.py:53-359,
/root/reference/policies/policies.py:188-218 thread recurrent state
host-side), and this repo's `SessionEngine` replaced that with an O(1)
tick whose attention still materializes a masked softmax over the FULL
[B, Tmax] horizon (`ops.attention.cached_attention`) and whose dispatch
round-trips gather -> decode -> scatter as three HBM passes over the
arena KV leaves (`serving/session.py decode_dispatch`). This module is
the Pallas tier that collapses both (PAPER.md §0 scopes Pallas as the
native-code tier; PAPERS.md arXiv:2603.09555's compiler-first O(1)
autoregressive caching is the blueprint):

* `fused_decode_attention` — ONE `pl.pallas_call` per arena KV leaf
  family: for each lane it streams the session's own K/V blocks out of
  the arena AT THE LANE'S SLOT (scalar-prefetched slot indices steer
  the BlockSpec index_map — the gather never materializes), absorbs
  them into a one-row online softmax (the [B, Tmax] score matrix never
  exists; blocks past the lane's tick index are neither fetched — the
  clamped index_map revisits the previous block, which Pallas skips
  re-DMAing — nor computed, via `pl.when`), absorbs this tick's K/V as
  the final softmax position, and writes the appended row back IN
  PLACE through `input_output_aliases` (the scatter is a one-row
  window, not a full-leaf pass). Pad lanes ride through unchanged:
  their masked write lands the OLD row value on the null slot.
* `reference_decode_attention` — the XLA composition
  (gather -> `.at[rows, index].set` append -> `cached_attention` ->
  masked scatter) the kernel is numerics-pinned against; also the
  fallback when Pallas is unavailable.

Numerics contract: identical unmasked score set as `cached_attention`
over the post-append cache (arena positions strictly below the lane's
index, plus the appended position AT the index), f32 online softmax
with the same `_mask_value` masking — tick-by-tick parity is pinned by
tests/test_decode_kernels.py at every T.

CPU smoke runs the kernel with `interpret=True` (`interpret=None`
resolves from the process backend at trace time — see the note inside
`fused_decode_attention` for why flash_attention's platform_dependent
auto-select cannot be used here); the Mosaic lowering is validated
hardware-free by tests/test_mosaic_lowering.py (explicit
`interpret=False` under a TPU-platform export).
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from tensor2robot_tpu.ops import attention as attention_ops

__all__ = ["pallas_available", "pallas_unavailable_reason",
           "fused_decode_attention", "reference_decode_attention"]

try:  # Soft import: CPU-only deployments must still import this module.
  from jax.experimental import pallas as pl
  from jax.experimental.pallas import tpu as pltpu

  _HAS_PALLAS = True
  _PALLAS_IMPORT_ERROR: Optional[str] = None
except Exception as e:  # pragma: no cover - depends on the installed jax
  _HAS_PALLAS = False
  _PALLAS_IMPORT_ERROR = f"{type(e).__name__}: {e}"


def pallas_available() -> bool:
  """True when the Pallas kernel tier can lower at all (import worked)."""
  return _HAS_PALLAS


def pallas_unavailable_reason() -> Optional[str]:
  """Why `pallas_available()` is False (None when it is True) — the
  engine's auto-gate surfaces this instead of silently degrading."""
  return _PALLAS_IMPORT_ERROR


def reference_decode_attention(q: jnp.ndarray, k_new: jnp.ndarray,
                               v_new: jnp.ndarray, k_arena: jnp.ndarray,
                               v_arena: jnp.ndarray, slots: jnp.ndarray,
                               index: jnp.ndarray, mask: jnp.ndarray
                               ) -> Tuple[jnp.ndarray, jnp.ndarray,
                                          jnp.ndarray]:
  """The XLA composition the fused kernel replaces (and is pinned to).

  Gathers each lane's KV rows from the arena, appends this tick's K/V
  at the lane's index, runs `cached_attention`, and scatters the
  appended rows back masked — three full-leaf HBM passes. Pad lanes
  (mask False) scatter the OLD row value through the null slot, so
  duplicates are write-idempotent.
  """
  b = q.shape[0]
  rows = jnp.arange(b)
  k_cache = k_arena[slots].at[rows, index].set(k_new)
  v_cache = v_arena[slots].at[rows, index].set(v_new)
  out = attention_ops.cached_attention(q, k_cache, v_cache, index)
  lane = mask[:, None, None]
  k_row = jnp.where(lane, k_new, k_arena[slots, index])
  v_row = jnp.where(lane, v_new, v_arena[slots, index])
  return (out, k_arena.at[slots, index].set(k_row),
          v_arena.at[slots, index].set(v_row))


def _decode_tick_kernel(slots_ref, idx_ref, mask_ref, q_ref, knew_ref,
                        vnew_ref, karena_ref, varena_ref, out_ref,
                        kupd_ref, vupd_ref, m_ref, l_ref, o_ref,
                        kold_ref, vold_ref, *, block_k: int):
  """One (lane, k-block) program of the fused decode tick.

  Grid (B, NB), NB innermost: the VMEM scratch (running max / denom /
  numerator + the stashed old row at the append position) persists
  across a lane's sequential k-block iterations. Blocks past the
  lane's append block are neither fetched (the clamped index_map
  revisits the previous block index, whose DMA Pallas skips) nor
  computed (`pl.when`), so per-lane HBM traffic is O(index), not
  O(Tmax).
  """
  b = pl.program_id(0)
  kb = pl.program_id(1)
  nb = pl.num_programs(1)
  idx = idx_ref[b]
  last_in = idx // block_k  # block holding the append position
  d = q_ref.shape[-1]
  scale = 1.0 / math.sqrt(d)
  mask_val = jnp.float32(jnp.finfo(jnp.float32).min / 2)

  @pl.when(kb == 0)
  def _init():
    m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
    l_ref[...] = jnp.zeros_like(l_ref)
    o_ref[...] = jnp.zeros_like(o_ref)

  @pl.when(kb <= last_in)
  def _absorb():
    # Online-softmax absorb of one arena K/V block. Entries at or past
    # the lane's index score `_mask_value` — the same masked row
    # `cached_attention` softmaxes — so a partial block (and a pad
    # lane's fully-masked block 0) contributes exactly 0 after the
    # final rescale.
    q = q_ref[0].astype(jnp.float32)                   # [H, D]
    k_blk = karena_ref[0].astype(jnp.float32)          # [bk, H, D]
    v_blk = varena_ref[0].astype(jnp.float32)
    s = jnp.sum(q[None, :, :] * k_blk, axis=-1) * scale  # [bk, H]
    t_pos = kb * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_k, 1), 0)
    s = jnp.where(t_pos < idx, s, mask_val)
    m_prev = m_ref[0]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=0))    # [H]
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new[None, :])                    # [bk, H]
    l_ref[0] = l_ref[0] * alpha + jnp.sum(p, axis=0)
    o_ref[...] = (o_ref[...] * alpha[:, None]
                  + jnp.sum(p[:, :, None] * v_blk, axis=0))
    m_ref[0] = m_new

  @pl.when(kb == last_in)
  def _stash_old_row():
    # The pre-append value at the lane's index, for masked write-back:
    # a pad lane's "append" must land the OLD row (null-slot immunity).
    row = idx - kb * block_k
    kold_ref[...] = karena_ref[0, pl.ds(row, 1)]
    vold_ref[...] = varena_ref[0, pl.ds(row, 1)]

  @pl.when(kb == nb - 1)
  def _epilogue():
    # The appended position is absorbed directly from k_new/v_new (no
    # read-after-write hazard with the in-place row update): its score
    # is the one `cached_attention` sees at position == index.
    q = q_ref[0].astype(jnp.float32)
    s_new = jnp.sum(q * knew_ref[0].astype(jnp.float32),
                    axis=-1) * scale                   # [H]
    m_prev = m_ref[0]
    m_fin = jnp.maximum(m_prev, s_new)
    alpha = jnp.exp(m_prev - m_fin)
    p_new = jnp.exp(s_new - m_fin)
    l_fin = l_ref[0] * alpha + p_new
    o_fin = (o_ref[...] * alpha[:, None]
             + p_new[:, None] * vnew_ref[0].astype(jnp.float32))
    out_ref[0] = (o_fin
                  / jnp.maximum(l_fin, 1e-30)[:, None]).astype(out_ref.dtype)
    live = mask_ref[b] != 0
    kupd_ref[0, 0] = jnp.where(live, knew_ref[0], kold_ref[0])
    vupd_ref[0, 0] = jnp.where(live, vnew_ref[0], vold_ref[0])


def _effective_block(t: int, block_k: int) -> int:
  """Largest block <= block_k that divides T (every T tiles exactly —
  partial-horizon arithmetic stays in the index clamp, not in padding)."""
  block = max(1, min(int(block_k), t))
  while t % block:
    block -= 1
  return block


@functools.partial(jax.jit, static_argnames=("block_k", "interpret"))
def fused_decode_attention(q: jnp.ndarray, k_new: jnp.ndarray,
                           v_new: jnp.ndarray, k_arena: jnp.ndarray,
                           v_arena: jnp.ndarray, slots: jnp.ndarray,
                           index: jnp.ndarray, mask: jnp.ndarray,
                           block_k: int = 8,
                           interpret: Optional[bool] = None
                           ) -> Tuple[jnp.ndarray, jnp.ndarray,
                                      jnp.ndarray]:
  """Fused gather + append + cached-attention decode tick, in place.

  q / k_new / v_new: [B, H, D] — this tick's per-lane query and K/V;
  k_arena / v_arena: [S, T, H, D] — the WHOLE session arena leaf
  (slot-major; slot 0 is the reserved null slot);
  slots: [B] int32 — each lane's arena slot (live lanes distinct);
  index: [B] int32 — each lane's tick position (append target);
  mask:  [B] bool  — live lanes; pad lanes write their OLD row back.

  Returns (out [B, H, D], k_arena', v_arena') with the arenas updated
  only at each live lane's (slot, index) row — alias-updated in place
  when the caller donates them. Falls back to the XLA reference
  composition when Pallas is unavailable (`pallas_available()`).
  """
  if not _HAS_PALLAS:
    attention_ops.note_pallas_unavailable("fused_decode_attention")
    return reference_decode_attention(q, k_new, v_new, k_arena, v_arena,
                                      slots, index, mask)
  if interpret is None:
    # Resolve from the PROCESS backend at trace time. The serving
    # engine compiles its dispatch for the backend it executes on, so
    # this is correct by construction there; flash_attention's
    # platform_dependent auto-select is NOT usable here because inside
    # jit the switch lowers BOTH branches and the interpret=False
    # branch hard-fails CPU lowering ("Only interpret mode is supported
    # on CPU backend") — the eager-only fold is why the model layers
    # pass flash_interpret statically. Cross-platform AOT exports
    # (TPU-target program lowered from a CPU host) must pass
    # interpret=False explicitly (tests/test_mosaic_lowering.py does).
    interpret = jax.default_backend() != "tpu"
  b, h, d = q.shape
  s_sz, t = k_arena.shape[0], k_arena.shape[1]
  bk = _effective_block(t, block_k)
  nb = t // bk
  slots = slots.astype(jnp.int32)
  index = index.astype(jnp.int32)
  mask_i = mask.astype(jnp.int32)
  if not interpret:
    # Pin kernel operands to plain HBM buffers (the flash_attention
    # barrier discipline: XLA:TPU otherwise fuses surrounding layout
    # ops into the custom call's scoped-VMEM region).
    q, k_new, v_new, k_arena, v_arena = jax.lax.optimization_barrier(
        (q, k_new, v_new, k_arena, v_arena))

  def lane(bi, kbi, slots_ref, idx_ref, mask_ref):
    del kbi, slots_ref, idx_ref, mask_ref
    return (bi, 0, 0)

  def arena_block(bi, kbi, slots_ref, idx_ref, mask_ref):
    del mask_ref
    # Clamp past-the-append blocks to the append block: Pallas skips
    # the DMA of a revisited block index, so a lane only ever fetches
    # blocks 0..index//bk — O(index) HBM traffic per tick.
    return (slots_ref[bi], jnp.minimum(kbi, idx_ref[bi] // bk), 0, 0)

  def append_row(bi, kbi, slots_ref, idx_ref, mask_ref):
    del kbi, mask_ref
    return (slots_ref[bi], idx_ref[bi], 0, 0)

  grid_spec = pltpu.PrefetchScalarGridSpec(
      num_scalar_prefetch=3,
      grid=(b, nb),
      in_specs=[
          pl.BlockSpec((1, h, d), lane),          # q
          pl.BlockSpec((1, h, d), lane),          # k_new
          pl.BlockSpec((1, h, d), lane),          # v_new
          pl.BlockSpec((1, bk, h, d), arena_block),   # k_arena
          pl.BlockSpec((1, bk, h, d), arena_block),   # v_arena
      ],
      out_specs=[
          pl.BlockSpec((1, h, d), lane),              # out
          pl.BlockSpec((1, 1, h, d), append_row),     # k_arena'
          pl.BlockSpec((1, 1, h, d), append_row),     # v_arena'
      ],
      scratch_shapes=[
          pltpu.VMEM((1, h), jnp.float32),        # running max
          pltpu.VMEM((1, h), jnp.float32),        # running denom
          pltpu.VMEM((h, d), jnp.float32),        # unnormalized numerator
          pltpu.VMEM((1, h, d), k_arena.dtype),   # old row at index
          pltpu.VMEM((1, h, d), v_arena.dtype),
      ])
  out, k_upd, v_upd = pl.pallas_call(
      functools.partial(_decode_tick_kernel, block_k=bk),
      grid_spec=grid_spec,
      out_shape=[
          jax.ShapeDtypeStruct((b, h, d), q.dtype),
          jax.ShapeDtypeStruct(k_arena.shape, k_arena.dtype),
          jax.ShapeDtypeStruct(v_arena.shape, v_arena.dtype),
      ],
      input_output_aliases={6: 1, 7: 2},  # arenas update in place
      interpret=interpret,
  )(slots, index, mask_i, q, k_new, v_new, k_arena, v_arena)
  if not interpret:
    out, k_upd, v_upd = jax.lax.optimization_barrier((out, k_upd, v_upd))
  return out, k_upd, v_upd
