"""Grasp2Vec visualization: keypoint heatmap overlays.

Reference: /root/reference/research/grasp2vec/visualization.py:31-260 —
localization heatmaps (goal embedding dot-producted with the scene's
spatial features) rendered over the scene image for summaries. Here the
render is pure numpy/PIL producing PNG bytes, written either to disk or
into the JSONL metrics stream as file references.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

__all__ = ["render_heatmap_overlay", "save_heatmap_summaries"]


def _colormap(values: np.ndarray) -> np.ndarray:
  """[H, W] in [0,1] -> [H, W, 3] uint8 blue->red colormap."""
  v = np.clip(values, 0.0, 1.0)
  r = (255 * v).astype(np.uint8)
  g = (255 * (1.0 - np.abs(v - 0.5) * 2)).astype(np.uint8)
  b = (255 * (1.0 - v)).astype(np.uint8)
  return np.stack([r, g, b], axis=-1)


def render_heatmap_overlay(image: np.ndarray, heatmap: np.ndarray,
                           alpha: float = 0.5) -> np.ndarray:
  """Overlays a (low-res) heatmap on an image; returns [H, W, 3] uint8."""
  from PIL import Image

  image = np.asarray(image)
  if image.dtype != np.uint8:
    image = np.clip(image * 255.0, 0, 255).astype(np.uint8)
  if image.shape[-1] == 1:
    image = np.repeat(image, 3, axis=-1)
  heatmap = np.asarray(heatmap, np.float32)
  lo, hi = heatmap.min(), heatmap.max()
  norm = (heatmap - lo) / (hi - lo + 1e-8)
  colored = _colormap(norm)
  resized = np.asarray(Image.fromarray(colored).resize(
      (image.shape[1], image.shape[0])))
  blended = ((1 - alpha) * image + alpha * resized).astype(np.uint8)
  return blended


def save_heatmap_summaries(output_dir: str,
                           step: int,
                           images: np.ndarray,
                           heatmaps: np.ndarray,
                           max_images: int = 4) -> list:
  """Writes overlay PNGs `heatmap_<step>_<i>.png`; returns paths."""
  from PIL import Image

  os.makedirs(output_dir, exist_ok=True)
  paths = []
  for i in range(min(len(images), max_images)):
    overlay = render_heatmap_overlay(images[i], heatmaps[i])
    path = os.path.join(output_dir, f"heatmap_{step}_{i}.png")
    Image.fromarray(overlay).save(path)
    paths.append(path)
  return paths
