"""Grasp2Vec embedding-arithmetic loss family.

Reference: /root/reference/research/grasp2vec/losses.py:29-304 —
L2/cosine arithmetic losses (masked by grasp success), semihard triplet
and bidirectional n-pairs objectives (plus the multilabel variant for
failed grasps), keypoint quadrant accuracy for the Shapes dataset,
norm-matching and send-to-zero regularizers, and the spatial softmax
response / TY ratio loss over scene feature maps.

All functions are pure jnp with static shapes: the reference's
`tf.dynamic_partition` + `tf.cond` masking is replaced by weighted means
(`sum(x*m)/max(sum(m),1)`), which XLA fuses and which equal the reference
value for every non-empty mask and 0 for the empty one.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu.layers import tec as tec_lib

__all__ = [
    "l2_arithmetic_loss", "cosine_arithmetic_loss", "triplet_loss",
    "npairs_loss_bidirectional", "npairs_loss_multilabel",
    "keypoint_accuracy", "send_to_zero_loss", "match_norms_loss",
    "get_softmax_response", "ty_loss", "heatmap_keypoints",
]


def _masked_mean(values: jnp.ndarray,
                 mask: Optional[jnp.ndarray]) -> jnp.ndarray:
  if mask is None:
    return values.mean()
  mask = mask.reshape(values.shape).astype(values.dtype)
  return jnp.sum(values * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def _l2_normalize(x: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
  return x / jnp.maximum(jnp.linalg.norm(x, axis=axis, keepdims=True), 1e-12)


def l2_arithmetic_loss(pregrasp_embedding, goal_embedding,
                       postgrasp_embedding, mask=None) -> jnp.ndarray:
  """Masked mean of ||pre - goal - post||^2 (reference :29-52)."""
  raw = pregrasp_embedding - goal_embedding - postgrasp_embedding
  distances = jnp.sum(raw ** 2, axis=1)
  return _masked_mean(distances, mask)


def cosine_arithmetic_loss(pregrasp_embedding, goal_embedding,
                           postgrasp_embedding, mask=None) -> jnp.ndarray:
  """Masked mean cosine distance between normalize(pre - post) and
  normalize(goal) (reference :80-107)."""
  pair_a = _l2_normalize(pregrasp_embedding - postgrasp_embedding)
  pair_b = _l2_normalize(goal_embedding)
  distances = 1.0 - jnp.sum(pair_a * pair_b, axis=1)
  return _masked_mean(distances, mask)


def triplet_loss(pregrasp_embedding, goal_embedding, postgrasp_embedding,
                 margin: float = 3.0
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
  """Semihard triplet over {normalize(pre-post), normalize(goal)} pairs
  sharing per-example labels (reference :56-77). Returns
  (loss, pairs, labels) like the reference."""
  pair_a = _l2_normalize(pregrasp_embedding - postgrasp_embedding)
  pair_b = _l2_normalize(goal_embedding)
  n = pregrasp_embedding.shape[0]
  labels = jnp.tile(jnp.arange(n), 2)
  pairs = jnp.concatenate([pair_a, pair_b], axis=0)
  loss = tec_lib.triplet_semihard_loss(
      pairs, labels, margin=margin, distance="euclidean")
  return loss, pairs, labels


def npairs_loss_bidirectional(pregrasp_embedding, goal_embedding,
                              postgrasp_embedding,
                              non_negativity_constraint: bool = False
                              ) -> jnp.ndarray:
  """n-pairs in both anchor orders over (pre - post, goal)
  (reference :159-185)."""
  pair_a = pregrasp_embedding - postgrasp_embedding
  if non_negativity_constraint:
    pair_a = jax.nn.relu(pair_a)
  pair_b = goal_embedding
  loss_1 = tec_lib.npairs_loss(pair_a, pair_b)
  loss_2 = tec_lib.npairs_loss(pair_b, pair_a)
  return loss_1 + loss_2


def npairs_loss_multilabel(pregrasp_embedding, goal_embedding,
                           postgrasp_embedding, grasp_success
                           ) -> jnp.ndarray:
  """n-pairs with failed grasps collapsed onto a shared 'nothing grasped'
  class (reference :188-219): example i gets label i+... only when its
  grasp succeeded, else label 0, and targets spread probability over all
  examples sharing a label."""
  pair_a = pregrasp_embedding - postgrasp_embedding
  pair_b = goal_embedding
  n = pregrasp_embedding.shape[0]
  success = jnp.reshape(grasp_success, (n,)).astype(jnp.int32)
  labels = jnp.arange(n, dtype=jnp.int32) * success

  def one_direction(anchor, positive):
    logits = anchor @ positive.T
    same = (labels[:, None] == labels[None, :]).astype(jnp.float32)
    targets = same / same.sum(-1, keepdims=True)
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    return -(targets * log_probs).sum(-1).mean()

  return one_direction(pair_a, pair_b) + one_direction(pair_b, pair_a)


# Host constant on purpose: a module-level `jnp.array` initializes the JAX
# backend at import time — over the axon tunnel that means ANY import of
# this module touches (and can wedge) TPU hardware. numpy converts to a
# device constant at trace time instead (graftlint: import-time-backend).
_QUADRANT_CENTERS = np.array(
    [[0.5, -0.5], [-0.5, -0.5], [0.5, 0.5], [-0.5, 0.5]], np.float32)


def keypoint_accuracy(keypoints, labels
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
  """Quadrant accuracy + sigmoid CE of spatial-softmax keypoints against
  integer quadrant labels (reference :110-135, Shapes dataset only)."""
  keypoints = jnp.reshape(keypoints, (-1, 2))
  labels = jnp.reshape(labels, (-1,)).astype(jnp.int32)
  logits = keypoints @ _QUADRANT_CENTERS.T
  correct = (jnp.argmax(logits, axis=1) == labels).astype(jnp.float32)
  one_hot = jax.nn.one_hot(labels, 4)
  ce = jnp.maximum(logits, 0) - logits * one_hot + jnp.log1p(
      jnp.exp(-jnp.abs(logits)))
  return correct.mean(), ce.mean()


def send_to_zero_loss(tensor, mask=None) -> jnp.ndarray:
  """Masked mean L2 norm (reference :138-156)."""
  return _masked_mean(jnp.linalg.norm(tensor, axis=1), mask)


def match_norms_loss(anchor_tensors, paired_tensors) -> jnp.ndarray:
  """Pushes paired-tensor norms toward (stop-gradient) anchor norms
  (reference :222-238). Scaling pinned by the executed reference:
  tf.nn.l2_loss is a scalar sum(x^2)/2 over the BATCH (the reference's
  outer reduce_mean is a no-op on that scalar), so this is a batch sum,
  not a mean."""
  anchor_norms = jax.lax.stop_gradient(
      jnp.linalg.norm(anchor_tensors, axis=1))
  paired_norms = jnp.linalg.norm(paired_tensors, axis=1)
  return 0.5 * jnp.sum((anchor_norms - paired_norms) ** 2)


def get_softmax_response(goal_embedding, scene_spatial
                         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
  """(max heatmap response, max softmax mass) of a goal embedding against
  a spatial feature map (reference _GetSoftMaxResponse :241-266)."""
  heatmap = jnp.einsum("bhwd,bd->bhw", scene_spatial, goal_embedding)
  flat = heatmap.reshape(heatmap.shape[0], -1)
  max_heat = flat.max(axis=1)
  max_soft = jax.nn.softmax(flat, axis=1).max(axis=1)
  return max_heat, max_soft


def ty_loss(pregrasp_spatial, postgrasp_spatial,
            goal_embedding) -> jnp.ndarray:
  """Likelihood-ratio localization loss: the goal should respond more in
  the pregrasp scene than the postgrasp scene (reference :269-303)."""
  pre = _l2_normalize(pregrasp_spatial)
  post = _l2_normalize(postgrasp_spatial)
  goal = _l2_normalize(goal_embedding)[:, None, None, :]
  pre_max = jnp.sum(pre * goal, axis=-1).max(axis=(1, 2))
  post_max = jnp.sum(post * goal, axis=-1).max(axis=(1, 2))
  return jnp.mean(post_max - pre_max)


def heatmap_keypoints(heatmap: jnp.ndarray) -> jnp.ndarray:
  """Spatial soft-argmax of a [B, H, W] heatmap -> [B, 2] (x, y) in
  [-1, 1], the keypoint parameterization `keypoint_accuracy` scores."""
  b, h, w = heatmap.shape
  probs = jax.nn.softmax(heatmap.reshape(b, -1), axis=-1).reshape(b, h, w)
  ys = jnp.linspace(-1.0, 1.0, h)
  xs = jnp.linspace(-1.0, 1.0, w)
  y = jnp.sum(probs.sum(axis=2) * ys[None, :], axis=1)
  x = jnp.sum(probs.sum(axis=1) * xs[None, :], axis=1)
  return jnp.stack([x, y], axis=-1)
