"""Grasp2Vec: self-supervised grasping representation via embedding
arithmetic.

Reference: /root/reference/research/grasp2vec/ — scene/goal `Embedding`
towers (networks.py), `Grasp2VecModel` with the
phi(pregrasp) - phi(postgrasp) ~= psi(goal) objective
(grasp2vec_model.py:136-240), the NPairs/Triplet/Arithmetic losses +
keypoint accuracy (losses.py:29-296) and heatmap visualization
(visualization.py:31-260).

The scene tower keeps its spatial map so goal embeddings can be
dot-producted against it for localization heatmaps — all batched matmuls.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tensor2robot_tpu import modes as modes_lib
from tensor2robot_tpu import specs as specs_lib
from tensor2robot_tpu.layers import tec as tec_lib
from tensor2robot_tpu.models import abstract as abstract_model
from tensor2robot_tpu.specs import SpecStruct, TensorSpec
from tensor2robot_tpu.utils import config

__all__ = ["SceneEmbedding", "GoalEmbedding", "Grasp2VecModel",
           "keypoint_heatmap"]


class SceneEmbedding(nn.Module):
  """Conv tower -> (pooled embedding, spatial feature map)."""

  embedding_size: int = 64
  filters: Tuple[int, ...] = (32, 64, 64)

  @nn.compact
  def __call__(self, image: jnp.ndarray, train: bool = False):
    x = image
    for i, f in enumerate(self.filters):
      x = nn.Conv(f, (3, 3), strides=(2, 2), name=f"conv_{i}")(x)
      x = nn.LayerNorm(name=f"norm_{i}")(x)
      x = nn.relu(x)
    spatial = nn.Conv(self.embedding_size, (1, 1), name="proj")(x)
    pooled = spatial.mean(axis=(1, 2))
    return pooled, spatial


class GoalEmbedding(nn.Module):
  embedding_size: int = 64
  filters: Tuple[int, ...] = (32, 64, 64)

  @nn.compact
  def __call__(self, image: jnp.ndarray, train: bool = False):
    x = image
    for i, f in enumerate(self.filters):
      x = nn.Conv(f, (3, 3), strides=(2, 2), name=f"conv_{i}")(x)
      x = nn.LayerNorm(name=f"norm_{i}")(x)
      x = nn.relu(x)
    x = x.mean(axis=(1, 2))
    return nn.Dense(self.embedding_size, name="proj")(x)


def keypoint_heatmap(spatial_features: jnp.ndarray,
                     goal_embedding: jnp.ndarray) -> jnp.ndarray:
  """Dot-product localization heatmap [B, H, W] (reference
  visualization.py heatmaps)."""
  return jnp.einsum("bhwc,bc->bhw", spatial_features, goal_embedding)


class _Grasp2VecNetwork(nn.Module):
  embedding_size: int = 64

  @nn.compact
  def __call__(self, features, mode: str = modes_lib.TRAIN,
               train: bool = False):
    def _norm(img):
      if jnp.issubdtype(img.dtype, jnp.integer):
        return img.astype(jnp.float32) / 255.0
      return img

    scene = SceneEmbedding(self.embedding_size, name="scene")
    goal = GoalEmbedding(self.embedding_size, name="goal")
    pregrasp, pregrasp_spatial = scene(_norm(features["pregrasp_image"]),
                                       train=train)
    postgrasp, _ = scene(_norm(features["postgrasp_image"]), train=train)
    goal_emb = goal(_norm(features["goal_image"]), train=train)
    outputs = specs_lib.SpecStruct()
    outputs["pregrasp_embedding"] = pregrasp
    outputs["postgrasp_embedding"] = postgrasp
    outputs["goal_embedding"] = goal_emb
    outputs["arithmetic_embedding"] = pregrasp - postgrasp
    outputs["heatmap"] = keypoint_heatmap(pregrasp_spatial, goal_emb)
    return outputs


@config.configurable
class Grasp2VecModel(abstract_model.T2RModel):
  """phi(pre) - phi(post) ~= psi(goal) with an n-pairs objective."""

  def __init__(self, image_size: int = 48, embedding_size: int = 64,
               **kwargs):
    super().__init__(**kwargs)
    self._image_size = image_size
    self._embedding_size = embedding_size

  def get_feature_specification(self, mode):
    image = lambda name: TensorSpec(
        shape=(self._image_size, self._image_size, 3), dtype=np.uint8,
        name=name, data_format="jpeg")
    return SpecStruct({
        "pregrasp_image": image("pregrasp/image"),
        "postgrasp_image": image("postgrasp/image"),
        "goal_image": image("goal/image"),
    })

  def get_label_specification(self, mode):
    # Self-supervised: no labels beyond the images themselves.
    return SpecStruct()

  def create_module(self):
    return _Grasp2VecNetwork(embedding_size=self._embedding_size)

  def model_train_fn(self, features, labels, inference_outputs, mode):
    arithmetic = inference_outputs["arithmetic_embedding"]
    goal = inference_outputs["goal_embedding"]
    npairs = tec_lib.npairs_loss(arithmetic, goal)
    # Symmetric direction (reference uses both anchor orders).
    npairs_reverse = tec_lib.npairs_loss(goal, arithmetic)
    loss = 0.5 * (npairs + npairs_reverse)
    return loss, {"npairs": npairs, "npairs_reverse": npairs_reverse}

  def model_eval_fn(self, features, labels, inference_outputs):
    loss, scalars = self.model_train_fn(
        features, labels, inference_outputs, modes_lib.EVAL)
    arithmetic = inference_outputs["arithmetic_embedding"]
    goal = inference_outputs["goal_embedding"]
    # Retrieval accuracy: does each arithmetic embedding rank its own
    # goal first (reference keypoint/retrieval accuracy)?
    sims = arithmetic @ goal.T
    correct = jnp.argmax(sims, axis=-1) == jnp.arange(sims.shape[0])
    return {"loss": loss, "retrieval_accuracy": correct.mean(), **scalars}
